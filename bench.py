"""Driver benchmark — prints ONE JSON line.

Round-1 metric: large-payload echo throughput through the full RPC stack
(framed tpu_std protocol, zero-copy attachments, keep-write socket path)
over loopback — the reference's headline config ("Echo throughput,
pooled/single connections, large payloads", BASELINE.md: 2.3 GB/s pooled
on a 24-core E5-2620). vs_baseline is against that 2.3 GB/s.

Later rounds move this metric onto the device path (ICI transfer via the
mesh transport), per BASELINE.json's north star.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

PAYLOAD = 1 << 20          # 1 MB, the rdma_performance headline size
WARMUP_S = 1.0
MEASURE_S = 4.0
N_THREADS = 4
BASELINE_GBPS = 2.3


def main() -> None:
    from brpc_tpu.butil.iobuf import IOBuf
    from brpc_tpu.client import Channel, Controller
    from brpc_tpu.server import Server, Service

    class Echo(Service):
        def Echo(self, cntl, request):
            # echo the attachment back without copying its bytes
            cntl.response_attachment.append_iobuf(cntl.request_attachment)
            return b"ok"

    srv = Server()
    srv.add_service(Echo(), name="Bench")
    assert srv.start("127.0.0.1:0") == 0
    addr = str(srv.listen_endpoint)

    stop_at = [0.0]
    counters = []
    attachment = bytes(PAYLOAD)

    def worker(idx: int, counter: list) -> None:
        ch = Channel()
        ch.init(addr)
        while time.perf_counter() < stop_at[0]:
            cntl = Controller()
            cntl.timeout_ms = 10_000
            cntl.request_attachment = IOBuf(attachment)
            c = ch.call_method("Bench.Echo", b"", cntl=cntl)
            if not c.failed and len(c.response_attachment) == PAYLOAD:
                counter[0] += 1

    # warmup
    stop_at[0] = time.perf_counter() + WARMUP_S
    w = [0]
    worker(0, w)

    stop_at[0] = time.perf_counter() + MEASURE_S
    threads = []
    for i in range(N_THREADS):
        c = [0]
        counters.append(c)
        t = threading.Thread(target=worker, args=(i, c))
        t.start()
        threads.append(t)
    t0 = time.perf_counter()
    for t in threads:
        t.join()
    elapsed = time.perf_counter() - t0

    total_reqs = sum(c[0] for c in counters)
    # payload moves twice per call (request + response attachment)
    gbps = total_reqs * PAYLOAD * 2 / elapsed / 1e9
    srv.stop()
    print(json.dumps({
        "metric": "echo_1mb_attachment_throughput",
        "value": round(gbps, 3),
        "unit": "GB/s",
        "vs_baseline": round(gbps / BASELINE_GBPS, 3),
    }))


if __name__ == "__main__":
    main()
