"""Driver benchmark — prints ONE JSON line (headline + full metric set).

Headline: 1MB-attachment echo throughput through the full RPC stack —
native C++ IO engine server, pooled connections, client processes (the
reference's "Echo throughput, pooled connections, large payloads"
config; BASELINE.md: 2.3 GB/s on a 24-core E5-2620 — this box has ONE
core).  vs_baseline is against that 2.3 GB/s.

The "extra" dict carries the rest of the BASELINE.md north-star set:
  - echo_1kb_p99_us          sync unary latency on the raw latency lane
                             (@raw_method + call_raw — the framework's
                             intended path for echo-class RPCs; the
                             _cntl variants measure the full Controller
                             path) (target < 50 µs)
  - sweep_*_gbps             64B → 1MB payload sweep (raw latency lane;
                             _cntl variants cover the Controller path)
  - streaming_gbps           windowed stream, 1MB chunks
  - fanout_qps               ParallelChannel over 3 servers
  - ici_1mb_tensor_gbps      device-resident 1MB tensor echo on the
                             real chip (rdma_performance north star) —
                             zero host copies on the data path
  - shm_1mb_gbps             same-host shm descriptor lane, 1MB echo
                             (attachments by (ring,slot,off,len), one
                             staging memcpy — attach_copy_count pins it;
                             zero_copy_vs_copy_gbps is the paired A/B
                             ratio against the byte lane)
"""

from __future__ import annotations

import json
import multiprocessing as mp
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

BASELINE_GBPS = 2.3
HEADLINE_PAYLOAD = 1 << 20
HEADLINE_SECONDS = 4.0
HEADLINE_PROCS = 2
WALL_CAP_S = 20.0      # per-measurement wall cap: failing calls each
                       # burn their timeout; a window must never spiral


def _echo_worker(addr: str, payload: int, seconds: float, q) -> None:
    """Client process: pooled-connection echo loop (own interpreter, own
    GIL — the reference benches with separate client processes too)."""
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from brpc_tpu.butil.iobuf import IOBuf
    from brpc_tpu.client import Channel, ChannelOptions, Controller

    opts = ChannelOptions()
    opts.connection_type = "pooled"
    ch = Channel(opts)
    ch.init(addr)
    att = bytes(payload)
    n = 0
    # warmup (also hides interpreter spawn cost from the measured window)
    for _ in range(5):
        cntl = Controller(); cntl.timeout_ms = 10_000
        cntl.request_attachment = IOBuf(att)
        ch.call_method("Bench.Echo", b"", cntl=cntl)
    t0 = time.perf_counter()
    end = t0 + seconds
    while time.perf_counter() < end:
        cntl = Controller()
        cntl.timeout_ms = 10_000
        cntl.request_attachment = IOBuf(att)
        c = ch.call_method("Bench.Echo", b"", cntl=cntl)
        if not c.failed and len(c.response_attachment) == payload:
            n += 1
    q.put((n, time.perf_counter() - t0))


def _start_server(native: bool = True):
    from brpc_tpu.server import Server, ServerOptions, Service
    from brpc_tpu.server.service import raw_method

    class Echo(Service):
        def Echo(self, cntl, request):
            cntl.response_attachment.append_iobuf(cntl.request_attachment)
            return b"ok"

        @raw_method(native="echo")
        def EchoRaw(self, payload, attachment):
            # the reference's echo handler copies the attachment and
            # nothing else (example/echo_c++) — this is that handler on
            # the latency lane; native="echo" answers it inside the C++
            # engine (zero Python per request), with this fn as the
            # behavioral spec and live fallback
            return payload, attachment

        @raw_method()
        def EchoPyRaw(self, payload, attachment):
            # a REAL Python handler on the raw lane (kind-2 dispatch:
            # the engine batches the burst, calls this under one GIL
            # entry, builds the response natively) — what a user's own
            # service actually pays, measured honestly alongside the
            # all-C++ number
            return payload, attachment

    opts = ServerOptions()
    opts.native = native
    opts.native_loops = 1          # 1-core box: extra loops only add contention
    opts.usercode_inline = True    # echo handlers never block
    srv = Server(opts)
    srv.add_service(Echo(), name="Bench")
    assert srv.start("127.0.0.1:0") == 0
    return srv


def bench_headline_and_sweep(extra: dict) -> float:
    srv = _start_server(native=True)
    addr = str(srv.listen_endpoint)
    try:
        # headline: client processes, pooled connections, 1MB.  Sweep
        # the client count like the reference's thread sweep and keep
        # the best configuration; each worker times its own window
        # (interpreter startup is not part of the echo path).
        ctx = mp.get_context("spawn")
        headline = 0.0
        ncores = os.cpu_count() or 1
        sweep = [n for n in (1, 2, 4, 8) if n <= max(1, ncores - 1)] or [1]
        for nprocs in sweep:
            # best of 3 windows (early exit on a good one): the
            # sandbox's throughput swings ~2x between scheduler
            # phases; report peak capacity, not one unlucky window
            best = 0.0
            for _attempt in range(3):
                q = ctx.Queue()
                procs = [ctx.Process(target=_echo_worker,
                                     args=(addr, HEADLINE_PAYLOAD,
                                           HEADLINE_SECONDS, q))
                         for _ in range(nprocs)]
                for p in procs:
                    p.start()
                results = []
                # ONE shared deadline for the whole window — a wedged
                # run costs at most this, not nprocs x timeout
                qdl = time.perf_counter() + HEADLINE_SECONDS * 5 + 60
                for _ in procs:
                    try:
                        results.append(q.get(
                            timeout=max(0.1, qdl - time.perf_counter())))
                    except Exception:
                        pass
                if len(results) < nprocs:
                    # fewer workers reported than the label claims:
                    # record it rather than silently skewing the sweep
                    extra[f"echo_1mb_{nprocs}proc_missing"] = \
                        nprocs - len(results)
                for p in procs:
                    if p.is_alive():
                        p.terminate()
                    p.join(10)
                gbps = sum(n * HEADLINE_PAYLOAD * 2 / dt / 1e9
                           for n, dt in results)
                best = max(best, gbps)
                if best >= headline * 0.9:
                    break        # good window already; second adds nothing
            extra[f"echo_1mb_{nprocs}proc_gbps"] = round(best, 3)
            if best < headline * 0.9:
                break                    # past the knee; stop burning time
            headline = max(headline, best)

        # sweep on an in-process client (pooled).  Primary keys measure
        # the raw latency lane (@raw_method + call_raw — the framework's
        # intended echo path, mirroring the reference's do-nothing echo
        # handler); _cntl variants keep the full Controller path
        # visible at the ends of the range.
        from brpc_tpu.butil.iobuf import IOBuf
        from brpc_tpu.client import Channel, ChannelOptions, Controller
        opts = ChannelOptions()
        opts.connection_type = "pooled"
        ch = Channel(opts)
        ch.init(addr)

        def _call_raw(att):
            try:
                ch.call_raw("Bench.EchoRaw", b"", att, timeout_ms=10_000)
                return True
            except Exception:
                return False

        def _call_cntl(att):
            cntl = Controller()
            cntl.timeout_ms = 10_000
            cntl.request_attachment = IOBuf(att)
            return not ch.call_method("Bench.Echo", b"",
                                      cntl=cntl).failed

        def measure(size: int, one_call):
            """Echo throughput at one payload size.  Runs at least
            ``reps`` calls AND at least MIN_WINDOW_S of wall time (small
            payloads need the longer window — scheduler-phase swings on
            this box are ~2x), capped at WALL_CAP_S."""
            MIN_WINDOW_S = 1.5
            att = bytes(size)
            reps = max(30, min(2000, (64 << 20) // max(size, 1) // 8))
            for _ in range(3):
                one_call(att)                  # warmup; failures ignored
            t0 = time.perf_counter()
            done = 0
            while True:
                if one_call(att):
                    done += 1
                dt = time.perf_counter() - t0
                if dt > WALL_CAP_S:
                    break
                if done >= reps and dt >= MIN_WINDOW_S:
                    break
            dt = time.perf_counter() - t0
            return done * size * 2 / dt / 1e9, done / dt

        for size, label in ((64, "64b"), (4096, "4kb"),
                            (65536, "64kb"), (1 << 20, "1mb")):
            gbps, qps = measure(size, _call_raw)
            if size == HEADLINE_PAYLOAD:
                # best-of-3 windows for the 1MB raw point, same
                # peak-capacity rationale as the proc sweep above: this
                # is the data-plane acceptance key and one unlucky
                # scheduler phase must not stand in for the lane
                for _ in range(2):
                    if gbps >= headline * 0.9:
                        break
                    g2, q2 = measure(size, _call_raw)
                    if g2 > gbps:
                        gbps, qps = g2, q2
            extra[f"sweep_{label}_gbps"] = round(gbps, 3)
            extra[f"sweep_{label}_qps"] = round(qps, 1)
            if size == HEADLINE_PAYLOAD:
                # the HEADLINE stays the full-Controller-stack number
                # (the baseline's "pooled connections, large payloads"
                # row is brpc's full stack too); the raw-lane 1MB point
                # is reported but never feeds the headline.
                # Retry-when-unlucky applies to the headline candidate.
                cg, _ = measure(size, _call_cntl)
                extra["sweep_1mb_cntl_gbps"] = round(cg, 3)
                if cg < headline * 0.9:
                    g2, _ = measure(size, _call_cntl)
                    cg = max(cg, g2)
                headline = max(headline, cg)
            elif size == 64:
                _, cq = measure(size, _call_cntl)
                extra["sweep_64b_cntl_qps"] = round(cq, 1)

        # pipelined small-message QPS (batch fast lane: one vectored
        # write per 256 calls, responses matched by correlation id —
        # the reference measures QPS with deep async pipelines too).
        # Best-of-3 windows per lane (the PR-6 raw-sweep discipline:
        # one unlucky scheduler phase must not stand in for a lane),
        # measured PAIRED and INTERLEAVED — each round runs both lanes
        # back-to-back on the same connection with the order
        # alternating, so `cntl_vs_raw_gap` (median per-round
        # raw/cntl ratio, the ISSUE-8 acceptance key) is phase-immune
        # on this throttled box even when the absolute numbers swing.
        reqs = [b"x" * 64] * 256

        def batch_window(mth: str, secs: float = 1.5) -> float:
            t0 = time.perf_counter()
            n = 0
            while time.perf_counter() - t0 < secs:
                try:
                    ch.call_batch(mth, reqs)
                    n += len(reqs)
                except Exception:
                    pass                  # window failure ≠ bench death
            return n / (time.perf_counter() - t0)

        for mth in ("Bench.EchoRaw", "Bench.Echo"):
            for _ in range(3):
                try:
                    ch.call_batch(mth, reqs)
                except Exception:
                    pass                    # warmup failure ≠ bench death
        best_raw = best_cntl = 0.0
        gaps = []
        for rnd in range(3):
            order = ("Bench.EchoRaw", "Bench.Echo") if rnd % 2 == 0 \
                else ("Bench.Echo", "Bench.EchoRaw")
            vals = {}
            for mth in order:
                vals[mth] = batch_window(mth)
            best_raw = max(best_raw, vals["Bench.EchoRaw"])
            best_cntl = max(best_cntl, vals["Bench.Echo"])
            if vals["Bench.Echo"] > 0:
                gaps.append(vals["Bench.EchoRaw"] / vals["Bench.Echo"])
        extra["sweep_64b_pipelined_qps"] = round(best_raw, 1)
        extra["sweep_64b_pipelined_cntl_qps"] = round(best_cntl, 1)
        if gaps:
            gaps.sort()
            extra["cntl_vs_raw_gap"] = round(gaps[len(gaps) // 2], 2)

        # 1KB sync latency distribution — best of 3 windows, SAME count
        # for both lanes so the raw-vs-cntl delta stays a fair read
        # (best-of-N p50 decreases stochastically with N).  The box's
        # scheduler phases can inflate a single window's tail 2x; a
        # shared section cap keeps a throttled box from eating the
        # budget the later sections need.  Primary keys measure the raw
        # latency lane; _cntl keys the full Controller path.
        att = bytes(1024)
        sect0 = time.perf_counter()
        LAT_SECTION_CAP_S = 45.0

        def lat_window(one_call):
            best_p50, best_p99 = float("inf"), float("inf")
            for _window in range(5):
                if time.perf_counter() - sect0 > LAT_SECTION_CAP_S:
                    break
                lats = []
                w0 = time.perf_counter()
                for _ in range(1500):
                    t0 = time.perf_counter()
                    if one_call():
                        lats.append((time.perf_counter() - t0) * 1e6)
                    if time.perf_counter() - w0 > WALL_CAP_S:
                        break
                if not lats:
                    continue     # whole window failed: never index empty
                lats.sort()
                p50 = lats[len(lats) // 2]
                if p50 < best_p50:
                    best_p50 = p50
                    best_p99 = lats[int(len(lats) * 0.99)]
            return best_p50, best_p99

        def one_raw():
            try:
                ch.call_raw("Bench.EchoRaw", b"", att, timeout_ms=10_000)
                return True
            except Exception:
                return False

        def one_cntl():
            cntl = Controller()
            cntl.timeout_ms = 10_000
            cntl.request_attachment = IOBuf(att)
            return not ch.call_method("Bench.Echo", b"",
                                      cntl=cntl).failed

        def one_pyraw():
            try:
                ch.call_raw("Bench.EchoPyRaw", b"", att,
                            timeout_ms=10_000)
                return True
            except Exception:
                return False

        p50, p99 = lat_window(one_raw)
        if p50 < float("inf"):
            extra["echo_1kb_p50_us"] = round(p50, 1)
            extra["echo_1kb_p99_us"] = round(p99, 1)
        p50, p99 = lat_window(one_pyraw)
        if p50 < float("inf"):
            extra["echo_1kb_pyhandler_p50_us"] = round(p50, 1)
            extra["echo_1kb_pyhandler_p99_us"] = round(p99, 1)
        p50, p99 = lat_window(one_cntl)
        if p50 < float("inf"):
            extra["echo_1kb_cntl_p50_us"] = round(p50, 1)
            extra["echo_1kb_cntl_p99_us"] = round(p99, 1)
            # ISSUE-8 tracking key: the full-Controller unary tail
            # latency the client lane is accountable for (same value,
            # the name the acceptance/perf-guard tables key on)
            extra["cntl_echo_p99_us"] = round(p99, 1)
        return headline
    finally:
        srv.stop()


def bench_loop_scaling(extra: dict) -> None:
    """Multi-core engine scaling (ISSUE 11): the SO_REUSEPORT-sharded
    per-core loops against the one-loop baseline.

    - sweep_64b_pipelined_qps_4loop  pipelined 64B echo over one conn
                                     per loop on a 4-loop engine (all-
                                     C++ kind-0 dispatch: the engine's
                                     capacity, not the client's)
    - loop_scaling_efficiency        median over PAIRED INTERLEAVED
                                     rounds of qps(2) / (2 * qps(1)) —
                                     the phase-immune acceptance key
                                     (≈1/N is the expected floor when
                                     loops outnumber cores; see PERF
                                     §14 for the 1-core caveat)
    - loop_scaling_efficiency_4loop  same at N=4
    - sweep_64b_pipelined_4loop_p99_us  sync per-call p99 on a probe
                                     conn while every loop serves
                                     pipelined load (full-core tail)
    """
    import socket as pysock
    import struct as _struct
    import threading as _threading

    def _tlv(tag, data):
        return bytes([tag]) + _struct.pack("<I", len(data)) + data

    def _frame(cid, payload):
        meta = (_tlv(1, _struct.pack("<Q", cid)) + _tlv(4, b"Bench")
                + _tlv(5, b"EchoRaw"))
        return (b"TRPC" + _struct.pack(
            "<II", len(meta) + len(payload), len(meta)) + meta + payload)

    BURST = 128
    blast = b"".join(_frame(i + 1, b"x" * 64) for i in range(BURST))

    def _drain(sock, want, buf):
        seen = 0
        while seen < want:
            chunk = sock.recv(262144)
            if not chunk:
                raise ConnectionError("peer closed mid-burst")
            buf += chunk
            seen = 0
            off = 0
            while off + 12 <= len(buf):
                (blen,) = _struct.unpack_from("<I", buf, off + 4)
                if off + 12 + blen > len(buf):
                    break
                off += 12 + blen
                seen += 1
        del buf[:]
        return seen

    def _conn_window(port, secs, out, idx):
        """One pipelined connection: blast/drain bursts for `secs`,
        record completed frames."""
        try:
            s = pysock.create_connection(("127.0.0.1", port), timeout=10)
            s.setsockopt(pysock.IPPROTO_TCP, pysock.TCP_NODELAY, 1)
            buf = bytearray()
            s.sendall(blast)            # warmup burst
            _drain(s, BURST, buf)
            n = 0
            t0 = time.perf_counter()
            while time.perf_counter() - t0 < secs:
                s.sendall(blast)
                _drain(s, BURST, buf)
                n += BURST
            out[idx] = n / (time.perf_counter() - t0)
            s.close()
        except Exception:
            out[idx] = 0.0

    def measure(port, nconns, secs=1.2, probe_lats=None):
        """nconns pipelined conns in parallel threads; optional probe
        thread measuring sync per-call latency on its own conn."""
        out = [0.0] * nconns
        threads = [_threading.Thread(target=_conn_window,
                                     args=(port, secs, out, i))
                   for i in range(nconns)]
        stop = _threading.Event()

        def _probe():
            try:
                s = pysock.create_connection(("127.0.0.1", port),
                                             timeout=10)
                s.setsockopt(pysock.IPPROTO_TCP, pysock.TCP_NODELAY, 1)
                buf = bytearray()
                one = _frame(7, b"p" * 64)
                while not stop.is_set():
                    t0 = time.perf_counter()
                    s.sendall(one)
                    _drain(s, 1, buf)
                    probe_lats.append((time.perf_counter() - t0) * 1e6)
                s.close()
            except Exception:
                pass

        pt = None
        if probe_lats is not None:
            pt = _threading.Thread(target=_probe)
        for t in threads:
            t.start()
        if pt is not None:
            pt.start()
        for t in threads:
            t.join()
        stop.set()
        if pt is not None:
            pt.join(timeout=10)
        return sum(out)

    from brpc_tpu.server import Server, ServerOptions, Service
    from brpc_tpu.server.service import raw_method

    class EchoN(Service):
        @raw_method(native="echo")
        def EchoRaw(self, payload, attachment):
            return payload, attachment

    def _mk(loops):
        opts = ServerOptions()
        opts.native = True
        opts.usercode_inline = True
        opts.native_loops = loops
        srv = Server(opts)
        srv.add_service(EchoN(), name="Bench")
        assert srv.start("127.0.0.1:0") == 0
        return srv

    servers = {}
    try:
        # all three configs live through every round so the paired
        # interleaved A/B runs same-phase (the cntl_vs_raw discipline)
        for n in (1, 2, 4):
            servers[n] = _mk(n)
        ports = {n: servers[n].listen_endpoint.port for n in (1, 2, 4)}
        # warm every config once outside the scored rounds
        for n in (1, 2, 4):
            measure(ports[n], n, secs=0.3)
        eff2, eff4 = [], []
        best = {1: 0.0, 2: 0.0, 4: 0.0}
        for rnd in range(3):
            order = (1, 2, 4) if rnd % 2 == 0 else (4, 2, 1)
            qps = {}
            for n in order:
                qps[n] = measure(ports[n], n)
            for n in (1, 2, 4):
                best[n] = max(best[n], qps[n])
            if qps[1] > 0:
                eff2.append(qps[2] / (2.0 * qps[1]))
                eff4.append(qps[4] / (4.0 * qps[1]))
        extra["sweep_64b_pipelined_qps_1loop"] = round(best[1], 1)
        extra["sweep_64b_pipelined_qps_2loop"] = round(best[2], 1)
        extra["sweep_64b_pipelined_qps_4loop"] = round(best[4], 1)
        if eff2:
            eff2.sort()
            eff4.sort()
            extra["loop_scaling_efficiency"] = \
                round(eff2[len(eff2) // 2], 3)
            extra["loop_scaling_efficiency_4loop"] = \
                round(eff4[len(eff4) // 2], 3)
        # p99 under full-core pipelined load: every loop of the 4-loop
        # engine saturated by a pipelined conn, a probe conn measures
        # sync per-call latency through the same loops
        lats: list = []
        measure(ports[4], 4, secs=1.5, probe_lats=lats)
        if len(lats) >= 20:
            lats.sort()
            extra["sweep_64b_pipelined_4loop_p99_us"] = \
                round(lats[int(len(lats) * 0.99)], 1)
            extra["sweep_64b_pipelined_4loop_p50_us"] = \
                round(lats[len(lats) // 2], 1)
        # scaling diagnostics: windowed busy imbalance of the 4-loop
        # engine right after load (the /native smoking-gun number)
        bridge = servers[4]._native_bridge
        if bridge is not None:
            extra["loop_busy_imbalance_4loop"] = round(
                bridge.telemetry.loop_busy_imbalance(), 4)
    finally:
        for srv in servers.values():
            srv.stop()


def bench_data_plane(extra: dict) -> None:
    """The zero-copy tensor data plane (ISSUE 6):

    - shm_1mb_gbps           1MB raw echo riding the same-host shm ring
                             (attachments pass by descriptor; echo
                             responses re-describe the request's slot)
    - zero_copy_vs_copy_gbps paired interleaved A/B on ONE connection
                             (methodology of native_telemetry_overhead_
                             pct): median per-round shm-lane / byte-lane
                             throughput ratio — box phase drift cancels
    - attach_copy_count      payload copies per eligible 1MB call on the
                             shm lane (engine data_plane_copies ledger +
                             Python copy_audit) — the lane admits exactly
                             its ONE staging memcpy
    """
    from brpc_tpu.transport import shm_ring
    if not shm_ring.shm_supported():
        extra["shm_skipped"] = "no tmpfs/mmap shm support in sandbox"
        return
    from brpc_tpu.butil import copy_audit
    from brpc_tpu.butil.flags import get_flag, set_flag
    from brpc_tpu.client import Channel, ChannelOptions

    flag0 = bool(get_flag("rpc_shm_data_plane"))
    srv = _start_server(native=True)
    try:
        opts = ChannelOptions()
        opts.connection_type = "pooled"
        ch = Channel(opts)
        ch.init(str(srv.listen_endpoint))
        att = bytes(HEADLINE_PAYLOAD)

        def one() -> bool:
            try:
                ch.call_raw("Bench.EchoRaw", b"", att, timeout_ms=10_000)
                return True
            except Exception:
                return False

        for _ in range(5):
            one()                      # warmup + shm ring handshake

        def window(secs: float) -> float:
            n = 0
            t0 = time.perf_counter()
            while True:
                if one():
                    n += 1
                dt = time.perf_counter() - t0
                if dt >= secs or dt > WALL_CAP_S:
                    break
            return n * HEADLINE_PAYLOAD * 2 / dt / 1e9

        # paired interleaved A/B, order alternated per round; arm A =
        # shm lane, arm B = byte lane, same connection, same handler
        a_best, b_best, ratios = 0.0, 0.0, []
        for r in range(5):
            vals = {}
            for shm_on in ((True, False) if r % 2 == 0
                           else (False, True)):
                set_flag("rpc_shm_data_plane", shm_on)
                one()                  # settle lane state pre-window
                vals[shm_on] = window(1.5)
            a_best = max(a_best, vals[True])
            b_best = max(b_best, vals[False])
            if vals[False] > 0:
                ratios.append(vals[True] / vals[False])
        set_flag("rpc_shm_data_plane", True)   # copy-count probe below
        extra["shm_1mb_gbps"] = round(a_best, 3)
        extra["copy_lane_1mb_gbps"] = round(b_best, 3)
        if ratios:
            ratios.sort()
            extra["zero_copy_vs_copy_gbps"] = round(
                ratios[len(ratios) // 2], 2)

        # copies per call, both ledgers (engine C++ + Python audit)
        one()                          # re-engage the shm lane
        eng = srv._native_bridge.engine
        base = dict(eng.telemetry()["data_plane_copies"])
        N = 20
        with copy_audit.audit() as snap:
            done = sum(1 for _ in range(N) if one())
            counts, _nb = snap()
        cur = eng.telemetry()["data_plane_copies"]
        eng_copies = sum(cur[k] - base.get(k, 0) for k in cur)
        if done:
            extra["attach_copy_count"] = round(
                (sum(counts.values()) + eng_copies) / done, 2)
        st = shm_ring.shm_stats()
        extra["shm_staged_gb"] = round(st["staged_bytes"] / 1e9, 2)
        extra["shm_desc_reused"] = st["desc_reused"]
    finally:
        # restore the OPERATOR's setting, not a hard-coded on — later
        # bench phases must run under the configured lane state
        set_flag("rpc_shm_data_plane", flag0)
        srv.stop()


def bench_streaming(extra: dict) -> None:
    import threading

    from brpc_tpu.client import Channel, Controller
    from brpc_tpu.server import Server, Service
    from brpc_tpu.streaming import StreamOptions, stream_accept, stream_create

    received = [0]
    done_evt = threading.Event()
    TOTAL = 256 << 20

    class Sink(Service):
        def Start(self, cntl, request):
            def on_received(stream, msgs):
                received[0] += sum(len(m) for m in msgs)
                if received[0] >= TOTAL:
                    done_evt.set()
            stream_accept(cntl, StreamOptions(on_received=on_received,
                                              max_buf_size=8 << 20))
            return b"ok"

    srv = Server()
    srv.add_service(Sink(), name="S")
    assert srv.start("127.0.0.1:0") == 0
    try:
        ch = Channel()
        ch.init(str(srv.listen_endpoint))
        cntl = Controller()
        cntl.timeout_ms = 10_000
        stream = stream_create(cntl, StreamOptions(max_buf_size=8 << 20))
        c = ch.call_method("S.Start", b"", cntl=cntl)
        assert not c.failed, c.error_text
        chunk = bytes(1 << 20)
        t0 = time.perf_counter()
        sent = 0
        while sent < TOTAL:
            if stream.write(chunk) != 0:
                break
            sent += len(chunk)
        done_evt.wait(30)
        dt = time.perf_counter() - t0
        stream.close()
        extra["streaming_gbps"] = round(received[0] / dt / 1e9, 3)
    finally:
        srv.stop()


def _stream_count_child(addr: str, n: int, q) -> None:
    """Subprocess client for the stream A/B: opens ``n`` sessions,
    counts every received token chunk, and reports (tokens, seconds)
    measured first-chunk → all-streams-closed.  A separate PROCESS so
    the client's Python chunk parsing does not share the server
    pusher's GIL (in-process the two arms compress into each other)."""
    import os
    import time as _t

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from brpc_tpu.client import Channel, Controller
    from brpc_tpu.streaming import StreamOptions, stream_create

    import threading as _th

    got = [0]
    first = []
    lock = _th.Lock()       # deliver callbacks run on several runtime
                            # threads; a bare += would lose increments

    def on_recv(s, msgs):
        with lock:
            if not first:
                first.append(_t.perf_counter())
            got[0] += len(msgs)

    chans = []
    for _ in range(4):
        ch = Channel()
        ch.init(addr)
        chans.append(ch)
    streams = []
    try:
        for i in range(n):
            cntl = Controller()
            cntl.timeout_ms = 30_000
            st = stream_create(cntl,
                               StreamOptions(on_received=on_recv))
            c = chans[i % len(chans)].call_method("PS.Open", b"",
                                                  cntl=cntl)
            if c.failed:
                q.put(("error", c.error_text))
                return
            if not st.wait_established(15):
                q.put(("error", "establish timeout"))
                return
            streams.append(st)
    except Exception as e:
        q.put(("error", f"{type(e).__name__}: {e}"))
        return
    q.put(("ready", None))
    deadline = _t.time() + 90
    while any(not s.closed for s in streams) and _t.time() < deadline:
        _t.sleep(0.02)
    end = _t.perf_counter()
    dt = (end - first[0]) if first else 0.0
    q.put(("done", (got[0], dt)))


def bench_decode_stream(extra: dict) -> None:
    """Kind-5 streaming lane + continuous-batching LLM decode.

    Two halves:

    - ``stream_native_vs_py``: PAIRED interleaved A/B of the stream
      TRANSPORT at c=64 sessions — a server-side pusher emits one
      token-sized chunk per session per step (the decode service's
      write shape: native arm batch-writes the step through
      ``stream_write_many`` → one coalesced writev per conn; Python
      arm pays per-chunk ``Stream.write``).  Arms alternate per round
      on the SAME server via the live lane flag, so the ratio is
      phase-immune.
    - ``stream_tokens_per_s`` / ``stream_ttft_p99_ms`` /
      ``decode_stream_sessions``: the real LMService ``Decode`` path —
      64 concurrent sessions riding the continuous batcher, aggregate
      tokens/s and time-to-first-token p99 measured end-to-end.
    """
    import struct as _struct
    import threading

    from brpc_tpu.butil.flags import set_flag
    from brpc_tpu.client import Channel, Controller
    from brpc_tpu.server import Server, ServerOptions, Service
    from brpc_tpu.streaming import (StreamOptions, stream_accept,
                                    stream_create)

    C = 64                              # concurrent decode sessions

    # ---- transport A/B: synthetic token pusher ------------------------
    class Push(Service):
        def __init__(self):
            self.streams = []

        def Open(self, cntl, request):
            s = stream_accept(cntl, StreamOptions(write_timeout_s=5.0))
            assert s is not None
            self.streams.append(s)
            return b"ok"

    opts = ServerOptions()
    opts.native = True
    opts.usercode_inline = True
    srv = Server(opts)
    svc = Push()
    srv.add_service(svc, name="PS")
    assert srv.start("127.0.0.1:0") == 0
    engine = srv._native_bridge.engine
    tok = _struct.pack("<i", 7)

    def push_window(server_streams, seconds):
        """Emit one token per session per step until the window ends;
        returns steps emitted.  Native streams batch through the
        engine (ONE coalesced call per step); Python ones pay
        per-chunk writes — exactly the two transports under
        measurement."""
        t_end = time.perf_counter() + seconds
        steps = 0
        native = [s for s in server_streams if s._native_tx is not None]
        pys = [s for s in server_streams if s._native_tx is None]
        items = [(s.id, tok) for s in native]
        while time.perf_counter() < t_end:
            if items:
                # batch-bounded credit wait: stalled/dead sessions fail
                # fast instead of eating the window
                engine.stream_write_many(items, 1000)
            if pys:
                # drop a failed session from the loop (its write just
                # burned its timeout) — re-writing it every step would
                # stall the whole py arm and corrupt the gated ratio;
                # dropping ONLY it keeps the rest of the step honest
                pys = [s for s in pys if s.write(tok) == 0]
            steps += 1
        return steps

    def run_arm(native_on, nprocs=4):
        """One arm: C sessions split over ``nprocs`` CLIENT PROCESSES
        (a single client process's chunk parsing caps near the py
        arm's rate and would mask the native lane's headroom), server
        pushes one window, aggregate rate = Σtokens / max(dt)."""
        set_flag("rpc_native_stream_lane", bool(native_on))
        ctx = mp.get_context("spawn")
        per = C // nprocs
        procs = []
        try:
            for _ in range(nprocs):
                q = ctx.Queue()
                p = ctx.Process(target=_stream_count_child,
                                args=(str(srv.listen_endpoint), per, q))
                p.start()
                procs.append((p, q))
            for _p, q in procs:
                tag, info = q.get(timeout=120)
                assert tag == "ready", (tag, info)
            mine = svc.streams[-C:]
            want_native = bool(native_on)
            assert all((s._native_tx is not None) == want_native
                       for s in mine)
            push_window(mine, 0.15)               # warm the pipe
            push_window(mine, 1.0)                # the measured window
            for s in mine:
                s.close()
            toks = 0
            dt = 0.0
            for _p, q in procs:
                tag, (t, d) = q.get(timeout=120)
                assert tag == "done", tag
                toks += t
                dt = max(dt, d)
            return toks / dt if dt > 0 else 0.0
        finally:
            for p, _q in procs:
                p.join(15)
                if p.is_alive():
                    p.kill()
                    p.join(10)

    try:
        ratios = []
        a_best = b_best = 0.0
        for r in range(4):               # interleaved, alternating order
            if r % 2 == 0:
                a = run_arm(True)
                b = run_arm(False)
            else:
                b = run_arm(False)
                a = run_arm(True)
            a_best = max(a_best, a)
            b_best = max(b_best, b)
            ratios.append(a / b if b > 0 else 0.0)
        ratios.sort()
        extra["stream_native_tokens_per_s"] = round(a_best, 1)
        extra["stream_py_tokens_per_s"] = round(b_best, 1)
        extra["stream_native_vs_py"] = round(ratios[len(ratios) // 2], 2)
    finally:
        set_flag("rpc_native_stream_lane", True)
        srv.stop()

    # ---- end-to-end: continuous-batching LM decode at c=64 ------------
    import numpy as np

    from brpc_tpu.models.lm_service import (LMService,
                                            pack_generate_request)
    from brpc_tpu.models.transformer_lm import LMConfig

    cfg = LMConfig(vocab=256, dim=64, heads=4, depth=2, max_seq=96,
                   remat=False)
    opts2 = ServerOptions()
    opts2.native = True
    opts2.usercode_inline = True
    srv2 = Server(opts2)
    lm = LMService(cfg=cfg, decode_slots=C)
    srv2.add_service(lm, name="LM")
    assert srv2.start("127.0.0.1:0") == 0
    MAX_NEW = 24
    prompt = np.arange(8, dtype=np.int32)[None, :] % cfg.vocab
    try:
        chans = []
        for _ in range(4):
            ch = Channel()
            ch.init(str(srv2.listen_endpoint))
            chans.append(ch)

        def warm():
            done = threading.Event()
            cntl = Controller()
            cntl.timeout_ms = 120_000
            st = stream_create(cntl, StreamOptions(
                on_closed=lambda s: done.set()))
            c = chans[0].call_method(
                "LM.Decode", pack_generate_request(prompt, MAX_NEW),
                cntl=cntl)
            assert not c.failed, c.error_text
            assert done.wait(120)

        warm()                           # compile prefill + step once

        ttfts = []
        counts = [0] * C
        closed = [threading.Event() for _ in range(C)]
        lock = threading.Lock()

        def one(i):
            first = []
            t_start = time.perf_counter()

            def on_recv(s, msgs, _i=i, _first=first, _t=t_start):
                if not _first:
                    _first.append(time.perf_counter() - _t)
                counts[_i] += len(msgs)

            cntl = Controller()
            cntl.timeout_ms = 120_000
            st = stream_create(cntl, StreamOptions(
                on_received=on_recv,
                on_closed=lambda s, _i=i: closed[_i].set()))
            c = chans[i % len(chans)].call_method(
                "LM.Decode", pack_generate_request(prompt, MAX_NEW),
                cntl=cntl)
            if c.failed:
                closed[i].set()
                return
            if closed[i].wait(180) and first:
                with lock:
                    ttfts.append(first[0])

        t0 = time.perf_counter()
        threads = [threading.Thread(target=one, args=(i,))
                   for i in range(C)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(240)
        dt = time.perf_counter() - t0
        total = sum(counts)
        extra["decode_stream_sessions"] = int(
            sum(1 for e in closed if e.is_set()))
        if dt > 0 and total:
            extra["stream_tokens_per_s"] = round(total / dt, 1)
        if ttfts:
            ttfts.sort()
            extra["stream_ttft_p99_ms"] = round(
                ttfts[min(len(ttfts) - 1,
                          int(len(ttfts) * 0.99))] * 1e3, 2)
    finally:
        srv2.stop()


def bench_kv_disagg(extra: dict) -> None:
    """§17 disaggregated prefill/decode + the KV transfer plane
    (ISSUE 15):

    - ``kv_transfer_gbps``: the page plane's same-host byte lane —
      2MB pages staged into the shm ring (the lane's ONE memcpy),
      resolved and landed on the import side; GB/s over the full
      stage→resolve→land cycle.
    - ``disagg_handoff_copies``: payload copies (engine ledgers of
      BOTH tiers + Python copy_audit) across one full ici-lane
      handoff session — PINNED at exactly 0 (the "zero payload bytes
      through the message path" acceptance, perf_guard PINNED_ZERO).
    - ``disagg_ttft_p99_ms`` / ``mono_ttft_p99_ms`` /
      ``disagg_vs_mono_ttft``: PAIRED interleaved A/B — the same
      C-session decode workload against the two-tier stack (prefill
      tier hands every session to the decode tier mid-request) and
      against one monolithic server; TTFT p99 per arm, order
      alternated per round, ratio from per-round pairs (phase-immune).
    - ``disagg_sessions_per_box``: sessions completed by the two-tier
      stack with the PAGED decode tier (ISSUE 16) — 128 concurrent
      sessions against a device page pool sized to the 16 contiguous
      slots' bytes of the round-15 arm, overflow spilling to the host
      tier (the "sessions-per-box at fixed p99" lever the ROADMAP
      names, now the paged allocator's headline).
    - ``kv_bytes_per_session``: device-pool peak bytes ÷ sessions
      completed in that round — the KV footprint the box paid per
      served session (contiguous would pay max_seq bytes regardless
      of use; PERF §18).
    - ``prefix_cache_hit_ttft_p99_ms`` / ``prefix_alias_copies``: C
      sessions re-sending a prompt whose context pages sit in the
      cross-session prefix cache — TTFT p99 with prefill skipped, and
      the copy-audit total while the hits alias shared pages (PINNED
      at exactly 0: a hit that copies is a prefix cache in name only).
    """
    import threading

    import numpy as np

    from brpc_tpu.butil import copy_audit
    from brpc_tpu.client import Channel, Controller
    from brpc_tpu.kv import DecodeTierService, KvTransport, \
        PrefillService
    from brpc_tpu.kv import pages as kv_pages
    from brpc_tpu.kv import transport as kv_transport
    from brpc_tpu.models.lm_service import (LMService,
                                            pack_generate_request)
    from brpc_tpu.models.transformer_lm import LMConfig
    from brpc_tpu.server import Server, ServerOptions
    from brpc_tpu.streaming import StreamOptions, stream_create
    from brpc_tpu.transport import shm_ring

    # ---- page-plane transfer throughput (shm byte lane) ---------------
    if shm_ring.shm_supported():
        import jax.numpy as jnp
        PAGE = 2 * 1024 * 1024 - 4096     # fits the default ring slot
        page_host = np.zeros((PAGE,), np.uint8)
        moved = 0
        t0 = time.perf_counter()
        while time.perf_counter() - t0 < 1.0:
            staged = shm_ring.stage_page(page_host, owner=("kv", -1))
            if staged is None:
                break
            desc, lease = staged
            parsed = shm_ring.decode_desc(desc)
            view = shm_ring.resolve(parsed[0], parsed[2], parsed[3])
            landed = jnp.asarray(np.frombuffer(view, np.uint8))
            landed.block_until_ready()
            del view, landed
            shm_ring.client_complete(lease)
            moved += PAGE
        dt = time.perf_counter() - t0
        if moved:
            extra["kv_transfer_gbps"] = round(moved / dt / 1e9, 3)

    # ---- the two-tier stack (shared by the copy pin and the A/B) ------
    C = 16                               # concurrent decode sessions
    MAX_NEW = 16
    cfg = LMConfig(vocab=256, dim=64, heads=4, depth=2, max_seq=96,
                   remat=False)
    prompt = np.arange(8, dtype=np.int32)[None, :] % cfg.vocab

    def native_opts():
        o = ServerOptions()
        o.native = True
        o.usercode_inline = False        # prefill runs nested RPCs
        return o

    kv_pages._reset_for_tests()
    kv_transport._reset_for_tests()
    dec_lm = LMService(cfg=cfg, decode_slots=C)
    dec_srv = Server(native_opts())
    dec_srv.add_service(dec_lm, name="LM")
    dec_srv.add_service(DecodeTierService(dec_lm), name="KV")
    assert dec_srv.start("127.0.0.1:0") == 0
    dch = Channel()
    dch.init(str(dec_srv.listen_endpoint))
    pre_svc = PrefillService(cfg=cfg, params=dec_lm.params,
                             decode_channel=dch,
                             transport=KvTransport(), decode_slots=C)
    pre_srv = Server(native_opts())
    pre_srv.add_service(pre_svc, name="LM")
    assert pre_srv.start("127.0.0.1:0") == 0

    mono_lm = LMService(cfg=cfg, params=dec_lm.params, decode_slots=C)
    mono_srv = Server(native_opts())
    mono_srv.add_service(mono_lm, name="LM")
    assert mono_srv.start("127.0.0.1:0") == 0

    def one_session(srv, chans, i, ttfts, done_counter, lock, p=None):
        first = []
        t_start = time.perf_counter()

        def on_recv(s, msgs, _first=first, _t=t_start):
            if not _first:
                _first.append(time.perf_counter() - _t)

        ok = threading.Event()
        cntl = Controller()
        cntl.timeout_ms = 120_000
        stream_create(cntl, StreamOptions(
            on_received=on_recv, on_closed=lambda s: ok.set()))
        c = chans[i % len(chans)].call_method(
            "LM.Decode",
            pack_generate_request(prompt if p is None else p, MAX_NEW),
            cntl=cntl)
        if c.failed:
            return
        if ok.wait(120) and first:
            with lock:
                ttfts.append(first[0])
                done_counter[0] += 1

    def run_arm(srv, n=C, p=None):
        chans = []
        for _ in range(4):
            ch = Channel()
            ch.init(str(srv.listen_endpoint))
            chans.append(ch)
        ttfts = []
        done = [0]
        lock = threading.Lock()
        threads = [threading.Thread(target=one_session,
                                    args=(srv, chans, i, ttfts, done,
                                          lock, p))
                   for i in range(n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(180)
        ttfts.sort()
        p99 = ttfts[min(len(ttfts) - 1, int(len(ttfts) * 0.99))] * 1e3 \
            if ttfts else None
        return p99, done[0]

    try:
        run_arm(pre_srv)                 # compile both tiers once
        run_arm(mono_srv)

        # ---- the copy pin: one full ici handoff, both ledgers ---------
        engines = [s._native_bridge.engine for s in (pre_srv, dec_srv)]

        def ledgers():
            return sum(sum(e.telemetry()["data_plane_copies"].values())
                       for e in engines)

        base = ledgers()
        with copy_audit.audit() as snap:
            p99_once, done_once = run_arm(pre_srv)
            counts, _nb = snap()
        if done_once:
            extra["disagg_handoff_copies"] = \
                sum(counts.values()) + (ledgers() - base)

        # ---- paired interleaved A/B -----------------------------------
        dis_p, mono_p, ratios = [], [], []
        dis_done = 0
        for r in range(3):
            arms = [("disagg", pre_srv), ("mono", mono_srv)]
            if r % 2:
                arms.reverse()
            vals = {}
            for name, srv in arms:
                p99, done = run_arm(srv)
                vals[name] = p99
                if name == "disagg":
                    dis_done = max(dis_done, done)
            if vals.get("disagg") is not None:
                dis_p.append(vals["disagg"])
            if vals.get("mono") is not None:
                mono_p.append(vals["mono"])
            if vals.get("disagg") and vals.get("mono"):
                ratios.append(vals["disagg"] / vals["mono"])
        if dis_p:
            extra["disagg_ttft_p99_ms"] = round(
                statistics.median(dis_p), 2)
        if mono_p:
            extra["mono_ttft_p99_ms"] = round(
                statistics.median(mono_p), 2)
        if ratios:
            ratios.sort()
            extra["disagg_vs_mono_ttft"] = round(
                ratios[len(ratios) // 2], 2)
        extra["disagg_sessions_per_box"] = dis_done
        st = kv_transport.kv_stats()
        extra["disagg_handoff_sessions"] = st["sessions"]
        extra["disagg_local_fallbacks"] = st["local_fallbacks"]

        # ---- paged decode tier: 8x the sessions on the SAME device
        # KV byte budget (ISSUE 16).  The pool is C*pps pages — byte-
        # identical to the 16 contiguous slots above — while 128
        # concurrent sessions ride it; the overflow parks in the host
        # tier and resumes as pages free.  Sessions completed is the
        # headline (every close is a failed session, so churn cannot
        # fake it).
        PAGE_TOK = 16
        PPS = cfg.max_seq // PAGE_TOK
        C_PAGED = 128
        page_bytes = 2 * cfg.depth * PAGE_TOK * cfg.dim * 4   # k+v, f32
        kv_pages._reset_for_tests()
        kv_transport._reset_for_tests()
        pag_lm = LMService(cfg=cfg, params=dec_lm.params,
                           decode_slots=C_PAGED, paged=True,
                           page=PAGE_TOK, kv_pages=C * PPS + 1,
                           kv_host_slots=2 * C_PAGED + 32)
        pag_srv = Server(native_opts())
        pag_srv.add_service(pag_lm, name="LM")
        pag_srv.add_service(DecodeTierService(pag_lm), name="KV")
        assert pag_srv.start("127.0.0.1:0") == 0
        pch = Channel()
        pch.init(str(pag_srv.listen_endpoint))
        pre2 = PrefillService(cfg=cfg, params=dec_lm.params,
                              decode_channel=pch,
                              transport=KvTransport(),
                              decode_slots=C_PAGED)
        pre2_srv = Server(native_opts())
        pre2_srv.add_service(pre2, name="LM")
        assert pre2_srv.start("127.0.0.1:0") == 0
        try:
            run_arm(pre2_srv, 8)         # compile the paged step once
            _p99, paged_done = run_arm(pre2_srv, C_PAGED)
            if paged_done:
                extra["disagg_sessions_per_box"] = paged_done
                if _p99 is not None:
                    extra["paged_ttft_p99_ms"] = round(_p99, 2)
                kv = pag_lm.batcher().kv_stats()
                extra["kv_bytes_per_session"] = round(
                    page_bytes * kv["alloc"]["peak_in_use"]
                    / paged_done)
                extra["paged_spills"] = kv["spills"]
        finally:
            pre2_srv.stop()
            pag_srv.stop()

        # ---- cross-session prefix cache: TTFT with prefill skipped,
        # and the alias-copy pin (a hit ALIASES the cached context
        # pages — refcounts move, bytes do not)
        kv_pages._reset_for_tests()
        hit_lm = LMService(cfg=cfg, params=dec_lm.params,
                           decode_slots=C, paged=True, page=PAGE_TOK)
        hit_srv = Server(native_opts())
        hit_srv.add_service(hit_lm, name="LM")
        assert hit_srv.start("127.0.0.1:0") == 0
        try:
            # 17-token prompt: the 16-token context is exactly one
            # full page, cached by the seeding session's prefill
            long_p = np.arange(17, dtype=np.int32)[None, :] % cfg.vocab
            run_arm(hit_srv, 1, long_p)          # seed + compile
            pf = hit_lm.batcher().prefills_run
            with copy_audit.audit() as snap:
                hp99, hit_done = run_arm(hit_srv, C, long_p)
                counts, _nb = snap()
            if hit_done and hp99 is not None:
                extra["prefix_cache_hit_ttft_p99_ms"] = round(hp99, 2)
                extra["prefix_alias_copies"] = sum(counts.values())
                pst = kv_pages.prefix_event_counters()
                extra["prefix_cache_hits"] = pst["prefix_hit"] \
                    + pst["prefix_partial_hit"]
                extra["prefix_prefills_skipped"] = \
                    hit_done - (hit_lm.batcher().prefills_run - pf)
        finally:
            hit_srv.stop()
    finally:
        pre_srv.stop()
        mono_srv.stop()
        dec_srv.stop()


def bench_slo_sched(extra: dict) -> None:
    """§19 SLO-tiered batch scheduler (ISSUE 17), direct-batcher
    benches (no RPC: the scheduler itself is the unit under test):

    - ``decode_itl_p99_ms`` / ``decode_itl_p99_ms_chunked_off`` /
      ``decode_itl_idle_p99_ms`` / ``slo_chunked_itl_gain``: a live
      decode session's inter-token latency p99 while long-prompt
      sessions join — PAIRED interleaved A/B, chunked prefill ON
      (budget 16) vs OFF (whole-prompt prefill between steps, the
      head-of-line block); idle p99 from the same session before the
      joins start; the gain ratio is OFF/ON from per-round pairs
      (phase-immune).
    - ``spec_decode_tokens_per_s`` / ``spec_decode_tokens_per_s_plain``
      / ``spec_accept_rate``: paired A/B of the draft+verify batcher
      mode (k=3, self-draft) vs plain decode on the same paged config;
      acceptance from the spec counters.  NOTE (PARITY §19): with
      random init weights the draft and verify programs split argmax
      near-ties, so acceptance — and therefore the speedup — is far
      below a trained model's; the recorded baseline gates collapse,
      it does not claim a win on this box.
    - ``slo_tier_victim_goodput``: an INTERACTIVE session live while a
      batch session coexists and a third join forces a spill — time to
      complete the interactive stream with the tier registry ON
      (batch victim parked) vs OFF (fattest-first parks the
      interactive one); ratio is OFF/ON medians over interleaved
      rounds, mirroring ``overload_fairness_victim_goodput``.
    """
    import jax
    import numpy as np

    from brpc_tpu.models.lm_service import (ContinuousBatcher,
                                            TierRegistry,
                                            _reset_sched_for_tests,
                                            spec_counters)
    from brpc_tpu.models.transformer_lm import LMConfig, init_params
    from brpc_tpu.kv import pages as kv_pages
    from brpc_tpu.streaming import StreamOptions

    class Rec:
        """Batcher-facing stream stub recording per-token arrival."""

        def __init__(self):
            self.closed = False
            self.close_reason = None
            self.stamps = []
            self.id = 0
            self._native_tx = None
            self.options = StreamOptions()

        def write(self, data):
            self.stamps.append(time.perf_counter())
            return 0

        def close(self, reason=None):
            self.closed = True
            self.close_reason = reason

    def wait(pred, timeout=120.0):
        deadline = time.perf_counter() + timeout
        while not pred() and time.perf_counter() < deadline:
            time.sleep(0.001)
        return pred()

    def p99(vals):
        s = sorted(vals)
        return s[min(len(s) - 1, int(len(s) * 0.99))] * 1e3 if s else None

    # ---- (a) chunked-prefill ITL A/B ---------------------------------
    # prefill cost must dominate a decode step for the HOL block to be
    # visible: 192-token context, 4 layers
    cfg = LMConfig(vocab=256, dim=128, heads=4, depth=4, max_seq=256,
                   remat=False)
    params = init_params(jax.random.PRNGKey(0), cfg)
    live_p = np.arange(8, dtype=np.int32) % cfg.vocab
    long_p = (np.arange(193, dtype=np.int32) * 7) % cfg.vocab

    def itl_arm(chunk):
        """Returns (idle_p99_ms, loaded_p99_ms) for one arm."""
        _reset_sched_for_tests()
        bat = ContinuousBatcher(cfg, params, slots=8,
                                prefill_chunk_tokens=chunk)
        live = Rec()
        bat.join(live, live_p, 140)
        if not wait(lambda: len(live.stamps) >= 10):
            return None, None
        idle_from = len(live.stamps)
        # idle and loaded windows get comparable sample counts (p99
        # of a small sample is its max; asymmetry would skew the ratio)
        wait(lambda: len(live.stamps) >= idle_from + 60)
        idle = np.diff(live.stamps[idle_from:]).tolist()
        # long-prompt joins arrive while the live session decodes
        joiners = []
        load_from = len(live.stamps)
        for _ in range(3):
            j = Rec()
            joiners.append(j)
            bat.join(j, long_p, 4)
            time.sleep(0.05)
        wait(lambda: all(j.closed for j in joiners))
        loaded = np.diff(live.stamps[load_from:len(live.stamps)])
        loaded = loaded.tolist()
        wait(lambda: live.closed)
        return p99(idle), p99(loaded)

    on_idle, on_load, off_load, gains = [], [], [], []
    for r in range(3):
        arms = [(16, True), (None, False)]
        if r % 2:
            arms.reverse()
        pair = {}
        for chunk, is_on in arms:
            i, l = itl_arm(chunk)
            if l is None:
                continue
            pair[is_on] = l
            if is_on:
                on_load.append(l)
                if i is not None:
                    on_idle.append(i)
            else:
                off_load.append(l)
        if True in pair and False in pair and pair[True] > 0:
            gains.append(pair[False] / pair[True])
    if on_load:
        extra["decode_itl_p99_ms"] = round(statistics.median(on_load), 2)
    if on_idle:
        extra["decode_itl_idle_p99_ms"] = \
            round(statistics.median(on_idle), 2)
    if off_load:
        extra["decode_itl_p99_ms_chunked_off"] = \
            round(statistics.median(off_load), 2)
    if gains:
        extra["slo_chunked_itl_gain"] = \
            round(statistics.median(gains), 3)

    # ---- (b) speculative decoding A/B --------------------------------
    cfg2 = LMConfig(vocab=256, dim=64, heads=4, depth=2, max_seq=96,
                    remat=False)
    params2 = init_params(jax.random.PRNGKey(1), cfg2)
    sp_prompt = np.arange(8, dtype=np.int32) % cfg2.vocab

    def spec_arm(spec):
        kv_pages._reset_for_tests()
        _reset_sched_for_tests()
        kw = dict(spec_decode_k=3, draft_params=params2) if spec else {}
        bat = ContinuousBatcher(cfg2, params2, slots=4, paged=True,
                                page=16, **kw)
        # warm the programs off the clock
        w = Rec()
        bat.join(w, sp_prompt, 4)
        if not wait(lambda: w.closed):
            return None, None
        sc0 = spec_counters()
        recs = [Rec() for _ in range(4)]
        t0 = time.perf_counter()
        for rec in recs:
            bat.join(rec, sp_prompt, 64)
        if not wait(lambda: all(rec.closed for rec in recs)):
            return None, None
        dt = time.perf_counter() - t0
        sc1 = spec_counters()
        acc = sc1["spec_accept"] - sc0["spec_accept"]
        rej = sc1["spec_reject"] - sc0["spec_reject"]
        rate = acc / (acc + rej) if (acc + rej) else None
        return 4 * 64 / dt, rate

    sp_on, sp_off, rates = [], [], []
    for r in range(2):
        arms = [True, False]
        if r % 2:
            arms.reverse()
        for spec in arms:
            tps, rate = spec_arm(spec)
            if tps is None:
                continue
            (sp_on if spec else sp_off).append(tps)
            if spec and rate is not None:
                rates.append(rate)
    if sp_on:
        extra["spec_decode_tokens_per_s"] = \
            round(statistics.median(sp_on), 1)
    if sp_off:
        extra["spec_decode_tokens_per_s_plain"] = \
            round(statistics.median(sp_off), 1)
    if rates:
        extra["spec_accept_rate"] = round(statistics.median(rates), 3)

    # ---- (c) tier-aware victim choice --------------------------------
    cfg3 = LMConfig(vocab=64, dim=32, heads=4, depth=2, max_seq=32,
                    remat=False)
    params3 = init_params(jax.random.PRNGKey(0), cfg3)
    pi = np.arange(14, dtype=np.int32) % cfg3.vocab    # 6 pages
    pb = np.arange(10, dtype=np.int32) % cfg3.vocab    # 4 pages
    pc = np.arange(6, dtype=np.int32) % cfg3.vocab     # 3 pages

    def victim_arm(tiered):
        """Interactive session's wall time to complete while a spill
        lands; 10 usable pages of 4 — A(6) + B(4) fill the pool, C(3)
        forces one park."""
        kv_pages._reset_for_tests()
        _reset_sched_for_tests()
        reg = None
        if tiered:
            reg = TierRegistry()
            reg.set_tier(b"vic", "interactive")
            reg.set_tier(b"hog", "batch")
        bat = ContinuousBatcher(cfg3, params3, slots=3, paged=True,
                                page=4, pages=11, host_slots=64,
                                prefix=False, tiers=reg)
        a, b, c = Rec(), Rec(), Rec()
        bat.join(a, pi, 11, tenant=b"vic")
        if not wait(lambda: a.stamps):
            return None
        bat.join(b, pb, 7, tenant=b"hog")
        if not wait(lambda: b.stamps):
            return None
        # clock starts at the CONTENDING join (per-batcher compiles
        # landed above): the window is the contested phase only
        t0 = time.perf_counter()
        bat.join(c, pc, 7)
        if not wait(lambda: a.closed and b.closed and c.closed):
            return None
        return (a.stamps[-1] - t0) * 1e3 if a.stamps else None

    vic_on, vic_off = [], []
    for r in range(3):
        arms = [True, False]
        if r % 2:
            arms.reverse()
        for tiered in arms:
            d = victim_arm(tiered)
            if d is not None:
                (vic_on if tiered else vic_off).append(d)
    if vic_on:
        extra["slo_tier_victim_ms"] = \
            round(statistics.median(vic_on), 1)
    if vic_off:
        extra["slo_tier_victim_ms_untiered"] = \
            round(statistics.median(vic_off), 1)
    if vic_on and vic_off and statistics.median(vic_on) > 0:
        extra["slo_tier_victim_goodput"] = round(
            statistics.median(vic_off) / statistics.median(vic_on), 3)


def bench_lm_telemetry(extra: dict) -> None:
    """§20 inference-plane observability (ISSUE 18): the observer
    effect of the serving-plane telemetry on the batcher step loop.

    - ``lm_telemetry_overhead_pct``: decode tokens/s with the
      ``lm_telemetry`` flag ON (per-phase histogram samples, session
      timelines, SLO verdicts) vs OFF (the ``_live[0]`` branch only) on
      ONE paged+chunked batcher — paired interleaved A/B with
      alternating order and the MEDIAN per-round overhead reported,
      methodology of ``native_telemetry_overhead_pct``.
    - ``lm_telemetry_ab_noise_pct``: the CONTROL pair (OFF vs OFF,
      same methodology) — this box's A/B noise floor; the overhead key
      is only meaningful next to it.
    - ``lm_telemetry_within_noise``: the perf_guard gate — 1.0 when
      the measured overhead sits within the control noise (2x margin,
      1pp floor: sub-percent jitter on a quiet box must not fail the
      build), else 0.0.  The design contract is ZERO locks/allocs per
      sample, so the honest claim is "indistinguishable from noise",
      not a hard pct bar.
    """
    import jax
    import numpy as np

    from brpc_tpu.butil.flags import set_flag
    from brpc_tpu.models import lm_telemetry as lmt
    from brpc_tpu.models.lm_service import ContinuousBatcher
    from brpc_tpu.models.transformer_lm import LMConfig, init_params
    from brpc_tpu.kv import pages as kv_pages
    from brpc_tpu.streaming import StreamOptions

    class Rec:
        def __init__(self):
            self.closed = False
            self.close_reason = None
            self.n = 0
            self.id = 0
            self._native_tx = None
            self.options = StreamOptions()

        def write(self, data):
            self.n += 1
            return 0

        def close(self, reason=None):
            self.closed = True
            self.close_reason = reason

    def wait(pred, timeout=120.0):
        deadline = time.perf_counter() + timeout
        while not pred() and time.perf_counter() < deadline:
            time.sleep(0.001)
        return pred()

    # paged + chunked so every phase site is live (prefix lookup, page
    # alloc, chunk slices, decode rounds, stream emits) — the arm with
    # telemetry ON pays the FULL per-sample cost, not a subset
    cfg = LMConfig(vocab=256, dim=64, heads=4, depth=2, max_seq=96,
                   remat=False)
    params = init_params(jax.random.PRNGKey(0), cfg)
    kv_pages._reset_for_tests()
    bat = ContinuousBatcher(cfg, params, slots=8, paged=True, page=8,
                            prefill_chunk_tokens=8)
    prompts = [(np.arange(12, dtype=np.int32) * (3 + i)) % cfg.vocab
               for i in range(6)]
    MAX_NEW = 32

    def phase(tel_on: bool) -> float:
        set_flag("lm_telemetry", tel_on)
        recs = []
        t0 = time.perf_counter()
        for p in prompts:
            r = Rec()
            recs.append(r)
            bat.join(r, p, MAX_NEW)
        if not wait(lambda: all(r.closed for r in recs)):
            raise RuntimeError("telemetry-bench sessions never closed")
        dt = time.perf_counter() - t0
        return sum(r.n for r in recs) / dt

    def paired_ab(a_on: bool, rounds: int = 5) -> float:
        """Median per-round (B - A)/B pct, order alternated; arm B is
        always telemetry-OFF."""
        pcts = []
        for r in range(rounds):
            if r % 2 == 0:
                qa = phase(a_on)
                qb = phase(False)
            else:
                qb = phase(False)
                qa = phase(a_on)
            if qb > 0:
                pcts.append((qb - qa) / qb * 100)
        pcts.sort()
        return round(pcts[len(pcts) // 2], 2) if pcts else 0.0

    try:
        phase(True)                       # warm prefill/step programs
        phase(False)
        pct = paired_ab(True)             # on vs off
        noise = paired_ab(False)          # off vs off: the noise floor
        extra["lm_telemetry_overhead_pct"] = pct
        extra["lm_telemetry_ab_noise_pct"] = noise
        extra["lm_telemetry_within_noise"] = \
            1.0 if pct <= max(2.0 * abs(noise), 1.0) else 0.0
    finally:
        set_flag("lm_telemetry", True)


def bench_fleet_obs(extra: dict) -> None:
    """§21 fleet observability (ISSUE 19): propagation latency of the
    load-report plane and its observer effect on a serving workload.

    - ``fleet_report_p99_ms``: one report push (member → registry RPC)
      until the fresh report is VISIBLE on the registry's /fleet page
      over HTTP — the whole pipeline the 'draining within one interval'
      promise rides, measured end to end (includes the page render and
      one poll round-trip, so this is an upper bound on raw ingest).
    - ``fleet_report_overhead_pct``: echo qps against the member with
      the ``fleet_obs`` flag ON (cadence reporter pushing every 0.25s,
      flight-recorder writes live) vs OFF.  A localhost echo loop
      drifts ±20% across contiguous half-second phases (scheduler +
      allocator weather), so contiguous A/B phases à la
      ``lm_telemetry_overhead_pct`` cannot resolve a sub-percent
      effect here; instead each round interleaves sixteen 100ms
      slices A/B/A/B and aggregates qps per side, which cancels drift
      at the slice scale.  Reported value is the median round pct.
    - ``fleet_obs_ab_noise_pct``: the OFF/OFF control — the same
      slice-interleaved rounds with the flag off on both sides, i.e.
      zero true effect.  Reported value is the ENVELOPE (max |pct|)
      of the control rounds: the magnitude pure noise reaches by
      chance under this exact methodology.
    - ``fleet_obs_within_noise``: the perf_guard gate — 1.0 when the
      measured overhead median sits inside the zero-effect envelope
      (1pp floor).  The honest claim is 'indistinguishable from
      noise', not 'zero': the serving path pays a flag-cache read and
      a deque append, and the cadence push costs ~0.6ms per interval
      off the serving thread.
    """
    import gc
    import http.client

    from brpc_tpu import fleet
    from brpc_tpu.butil.flags import set_flag
    from brpc_tpu.client import Channel
    from brpc_tpu.server import Server, Service

    class E(Service):
        def Echo(self, cntl, request):
            return request

    fleet._reset_for_tests()
    reg_srv = Server()
    reg = fleet.host_registry(reg_srv, ttl_s=5.0)
    if reg_srv.start("127.0.0.1:0") != 0:
        raise RuntimeError("fleet bench: registry start failed")
    mem = Server()
    mem.add_service(E(), name="E")
    if mem.start("127.0.0.1:0") != 0:
        reg_srv.stop()
        raise RuntimeError("fleet bench: member start failed")
    reg_addr = str(reg_srv.listen_endpoint)
    mem_addr = str(mem.listen_endpoint)

    def fleet_page() -> dict:
        host, _, port = reg_addr.rpartition(":")
        conn = http.client.HTTPConnection(host, int(port), timeout=2.0)
        try:
            conn.request("GET", "/fleet?format=json")
            return json.loads(conn.getresponse().read().decode("utf-8"))
        finally:
            conn.close()

    try:
        rep = fleet.attach_reporter(mem, reg_addr, interval_s=0.25)
        # -- propagation: push → visible on /fleet over HTTP ------------
        samples = []
        prev = -1
        for _ in range(12):
            t0 = time.perf_counter()
            rep.push_now(fresh=True)
            deadline = t0 + 5.0
            while time.perf_counter() < deadline:
                row = next((m for m in fleet_page()["members"]
                            if m["instance"] == mem_addr), None)
                seq = (row or {}).get("report", {}).get("seq", -1) \
                    if row and row.get("report") else -1
                if seq > prev:
                    prev = seq
                    break
            samples.append((time.perf_counter() - t0) * 1e3)
        samples.sort()
        extra["fleet_report_p99_ms"] = round(
            samples[min(len(samples) - 1,
                        int(0.99 * len(samples)))], 2)
        extra["fleet_members_ok"] = \
            sum(1 for m in reg.members() if m["state"] == "ok")

        # -- observer effect: echo qps, fleet_obs ON vs OFF -------------
        ch = Channel()
        ch.init(mem_addr)

        def ab_slice(on: bool, dur: float = 0.1):
            set_flag("fleet_obs", on)
            n = 0
            t0 = time.perf_counter()
            while time.perf_counter() - t0 < dur:
                ch.call("E.Echo", b"x" * 64, timeout_ms=2000)
                n += 1
            return n, time.perf_counter() - t0

        def round_pct(a_on: bool, slices: int = 16) -> float:
            na = ta = nb = tb = 0.0
            for i in range(slices):
                if i % 2 == 0:
                    n, t = ab_slice(a_on)
                    na += n
                    ta += t
                else:
                    n, t = ab_slice(False)
                    nb += n
                    tb += t
            qa, qb = na / ta, nb / tb
            return (qb - qa) / qb * 100 if qb > 0 else 0.0

        for _ in range(2):               # warm connection + code paths
            ab_slice(True)
            ab_slice(False)
        gc.collect()
        pcts = sorted(round_pct(True) for _ in range(7))
        ctrl = sorted(round_pct(False) for _ in range(7))
        pct = round(pcts[len(pcts) // 2], 2)
        noise = round(max(abs(p) for p in ctrl), 2)
        extra["fleet_report_overhead_pct"] = pct
        extra["fleet_obs_ab_noise_pct"] = noise
        extra["fleet_obs_within_noise"] = \
            1.0 if pct <= max(noise, 1.0) else 0.0
    finally:
        set_flag("fleet_obs", True)
        mem.stop()
        reg_srv.stop()
        fleet._reset_for_tests()


def bench_fanout(extra: dict) -> None:
    """ParallelChannel over 3 sub-servers.  Primary keys use the
    framework's intended partition-serving shape — raw echo parts on
    native/inline servers (the reference's fan-out benches run against
    its cheapest C++ echo handlers too).  The _cntl key is the FULL
    path both ways: real (cntl, request) methods on the sub-servers
    (slim native dispatch) reached through the full-Controller fan-out
    (pinned-socket native scatter) — retries/backup/rpcz machinery all
    live; `_cntl_pytransport` keeps the pure-Python sub-server number
    visible alongside, like the http/grpc sections do."""
    from brpc_tpu.client import Channel
    from brpc_tpu.client.parallel_channel import ParallelChannel
    from brpc_tpu.server import Server, ServerOptions, Service
    from brpc_tpu.server.service import raw_method

    class Part(Service):
        @raw_method(native="echo")
        def Get(self, payload, attachment):
            return payload, attachment

    class PartCntl(Service):
        def Get(self, cntl, request):
            return request

    def start_servers(native: bool, both: bool):
        servers = []
        for _ in range(3):
            o = ServerOptions()
            if native:
                o.native, o.usercode_inline, o.native_loops = True, True, 1
            s = Server(o)
            s.add_service(PartCntl(), name="PC")
            if both:
                s.add_service(Part(), name="P")
            assert s.start("127.0.0.1:0") == 0
            servers.append(s)
        pc = ParallelChannel()
        for s in servers:
            sub = Channel()
            sub.init(str(s.listen_endpoint))
            pc.add_channel(sub)
        return servers, pc

    def window(pc, mth: str, secs: float = 1.5) -> float:
        t0 = time.perf_counter()
        n = 0
        while time.perf_counter() - t0 < secs:
            c = pc.call_method(mth, b"x")
            if not c.failed:
                n += 1
        return n / (time.perf_counter() - t0)

    # PAIRED INTERLEAVED A/B on ONE server set (both services live on
    # every sub-server): raw fan-out (native-echo parts via pinned
    # scatter) vs the FULL-Controller fan-out (slim kind-3 parts via
    # the same scatter) alternate within each round, best-of-3 windows
    # per lane — `fanout_cntl_vs_raw_gap` (median per-round ratio) is
    # the phase-immune read of the remaining client-bookkeeping gap.
    servers, pc = start_servers(native=True, both=True)
    try:
        for _ in range(5):
            pc.call_method("P.Get", b"x")
            pc.call_method("PC.Get", b"x")
        best_raw = best_cntl = 0.0
        gaps = []
        for rnd in range(3):
            order = ("P.Get", "PC.Get") if rnd % 2 == 0 \
                else ("PC.Get", "P.Get")
            vals = {}
            for mth in order:
                vals[mth] = window(pc, mth)
            best_raw = max(best_raw, vals["P.Get"])
            best_cntl = max(best_cntl, vals["PC.Get"])
            if vals["PC.Get"] > 0:
                gaps.append(vals["P.Get"] / vals["PC.Get"])
    finally:
        for s in servers:
            s.stop()
    extra["fanout_qps"] = round(best_raw, 1)
    extra["fanout_subcalls_qps"] = round(3 * best_raw, 1)
    extra["fanout_cntl_qps"] = round(best_cntl, 1)
    if gaps:
        gaps.sort()
        extra["fanout_cntl_vs_raw_gap"] = round(gaps[len(gaps) // 2], 2)

    servers, pc = start_servers(native=False, both=False)
    try:
        for _ in range(5):
            pc.call_method("PC.Get", b"x")
        extra["fanout_cntl_pytransport_qps"] = round(
            window(pc, "PC.Get", 2.0), 1)
    finally:
        for s in servers:
            s.stop()


def bench_http(extra: dict) -> None:
    """HTTP/1.1 keep-alive 1KB echo (VERDICT r4 #7).  Primary keys
    measure the NATIVE port (the engine cuts complete HTTP messages in
    C++, Python parses + dispatches — the reference's every-protocol-
    through-the-C++-core shape); `_pytransport` keys keep the pure-
    Python lane visible.  stdlib http.client is the peer."""
    import http.client

    from brpc_tpu.server import Server, ServerOptions, Service

    class HttpEcho(Service):
        def Echo(self, cntl, request):
            return request

    def measure(native: bool):
        opts = ServerOptions()
        if native:
            opts.native = True
            opts.native_loops = 1
            opts.usercode_inline = True
        srv = Server(opts)
        srv.add_service(HttpEcho(), name="H")
        assert srv.start("127.0.0.1:0") == 0
        try:
            ep = srv.listen_endpoint
            conn = http.client.HTTPConnection(ep.host, ep.port,
                                              timeout=10)
            body = bytes(1024)

            def one():
                conn.request("POST", "/H/Echo", body=body)
                r = conn.getresponse()
                return len(r.read()) == 1024 and r.status == 200

            for _ in range(20):
                one()
            lats = []
            t0 = time.perf_counter()
            n = 0
            while time.perf_counter() - t0 < 3.0:
                c0 = time.perf_counter()
                if one():
                    n += 1
                    lats.append((time.perf_counter() - c0) * 1e6)
            dt = time.perf_counter() - t0
            conn.close()
            lats.sort()
            return (round(n / dt, 1),
                    round(lats[len(lats) // 2], 1) if lats else None,
                    round(lats[int(len(lats) * 0.99)], 1) if lats
                    else None)
        finally:
            srv.stop()

    def measure_load(nconn: int = 16, seconds: float = 3.0):
        """Multi-connection load variant (VERDICT r5 Weak #4): the
        serial number above is latency in disguise — this one is what
        the lane does with nconn concurrent keep-alive clients
        hammering it (aggregate completed requests / wall time)."""
        import threading

        opts = ServerOptions()
        opts.native = True
        opts.native_loops = 1
        opts.usercode_inline = True
        srv = Server(opts)
        srv.add_service(HttpEcho(), name="H")
        assert srv.start("127.0.0.1:0") == 0
        try:
            ep = srv.listen_endpoint
            body = bytes(1024)
            counts = [0] * nconn
            start = threading.Barrier(nconn + 1)
            stop = [False]

            def worker(i):
                conn = http.client.HTTPConnection(ep.host, ep.port,
                                                  timeout=10)
                try:
                    try:
                        for _ in range(3):
                            conn.request("POST", "/H/Echo", body=body)
                            conn.getresponse().read()
                    finally:
                        start.wait(30)   # NEVER skip the barrier: a
                        #                  failed warmup must not hang
                        #                  the main thread's wait
                    while not stop[0]:
                        conn.request("POST", "/H/Echo", body=body)
                        r = conn.getresponse()
                        if len(r.read()) == 1024 and r.status == 200:
                            counts[i] += 1
                except Exception:
                    pass
                finally:
                    conn.close()

            ts = [threading.Thread(target=worker, args=(i,))
                  for i in range(nconn)]
            for t in ts:
                t.start()
            start.wait(60)
            t0 = time.perf_counter()
            time.sleep(seconds)
            stop[0] = True
            for t in ts:
                t.join(15)
            dt = time.perf_counter() - t0
            return round(sum(counts) / dt, 1)
        finally:
            srv.stop()

    def measure_pipelined(burst: int = 32, seconds: float = 1.5,
                          rounds: int = 3):
        """Keep-alive PIPELINED bursts on a raw socket — the HTTP
        analogue of sweep_64b_pipelined_qps — measured through the
        SLIM HTTP LANE (engine kind 4) and the classic EV_HTTP lane
        INTERLEAVED in the same process on the same connection
        (set_http_slim toggles per phase), so the slim_vs_classic
        ratio stays honest on gVisor-class boxes where absolute
        numbers are meaningless."""
        import socket as psock

        opts = ServerOptions()
        opts.native = True
        opts.native_loops = 1
        opts.usercode_inline = True
        srv = Server(opts)
        srv.add_service(HttpEcho(), name="H")
        assert srv.start("127.0.0.1:0") == 0
        try:
            ep = srv.listen_endpoint
            eng = srv._native_bridge.engine
            body = bytes(1024)
            req = (b"POST /H/Echo HTTP/1.1\r\nHost: b\r\n"
                   b"Content-Length: 1024\r\n"
                   b"Content-Type: application/octet-stream\r\n\r\n"
                   + body)
            conn = psock.create_connection((ep.host, ep.port),
                                           timeout=10)
            conn.setsockopt(psock.IPPROTO_TCP, psock.TCP_NODELAY, 1)
            # learn the exact response size once (both lanes are
            # byte-identical — enforced by tests/test_http_slim.py)
            conn.sendall(req)
            buf = b""
            while b"\r\n\r\n" not in buf:
                buf += conn.recv(65536)
            head, _, rest = buf.partition(b"\r\n\r\n")
            clen = int([l.split(b":")[1] for l in head.split(b"\r\n")
                        if l.lower().startswith(b"content-length")][0])
            resp_len = len(head) + 4 + clen
            while len(buf) < resp_len:
                buf += conn.recv(65536)
            blob = req * burst
            want = resp_len * burst

            def phase(slim_on: bool, secs: float) -> float:
                eng.set_http_slim(slim_on)
                n = 0
                t0 = time.perf_counter()
                while time.perf_counter() - t0 < secs:
                    conn.sendall(blob)
                    got = 0
                    while got < want:
                        got += len(conn.recv(min(65536, want - got)))
                    n += burst
                return n / (time.perf_counter() - t0)

            phase(True, 0.2)                  # warm both lanes
            phase(False, 0.2)
            slim = classic = 0.0
            for _ in range(rounds):           # interleaved A/B rounds
                slim += phase(True, seconds / rounds)
                classic += phase(False, seconds / rounds)
            eng.set_http_slim(True)
            conn.close()
            return round(slim / rounds, 1), round(classic / rounds, 1)
        finally:
            srv.stop()

    def measure_telemetry_overhead(burst: int = 32, rounds: int = 7,
                                   secs: float = 0.5):
        """Cost of the always-on native telemetry's SNAPSHOT path on
        the hottest HTTP lane: pipelined slim bursts with a background
        thread polling engine.telemetry() at 10Hz (a very hot scraper —
        Prometheus scrapes every 15s) vs no polling, paired
        per round with alternating order and the MEDIAN per-round
        overhead reported.  A CONTROL A/B (no polling in either arm,
        same methodology) runs alongside and records this box's A/B
        noise floor — its scheduler phases swing short windows ~2x, so
        the overhead key is only meaningful next to the noise key.
        The capture side (histograms, fallback counters, timestamps)
        is always on in BOTH arms — by design it has no off switch —
        so this pair bounds the marginal cost of reading the table."""
        import socket as psock
        import threading

        opts = ServerOptions()
        opts.native = True
        opts.native_loops = 1
        opts.usercode_inline = True
        srv = Server(opts)
        srv.add_service(HttpEcho(), name="H")
        assert srv.start("127.0.0.1:0") == 0
        try:
            ep = srv.listen_endpoint
            eng = srv._native_bridge.engine
            body = bytes(1024)
            req = (b"POST /H/Echo HTTP/1.1\r\nHost: b\r\n"
                   b"Content-Length: 1024\r\n"
                   b"Content-Type: application/octet-stream\r\n\r\n"
                   + body)
            conn = psock.create_connection((ep.host, ep.port),
                                           timeout=10)
            conn.setsockopt(psock.IPPROTO_TCP, psock.TCP_NODELAY, 1)
            conn.sendall(req)
            buf = b""
            while b"\r\n\r\n" not in buf:
                buf += conn.recv(65536)
            head, _, rest = buf.partition(b"\r\n\r\n")
            clen = int([l.split(b":")[1] for l in head.split(b"\r\n")
                        if l.lower().startswith(b"content-length")][0])
            resp_len = len(head) + 4 + clen
            while len(buf) < resp_len:
                buf += conn.recv(65536)
            blob = req * burst
            want = resp_len * burst
            poll_stop = [False]
            polling = [False]

            def poller():
                while not poll_stop[0]:
                    if polling[0]:
                        eng.telemetry()
                    time.sleep(0.1)           # 10Hz snapshot rate

            pt = threading.Thread(target=poller, daemon=True)
            pt.start()

            def phase(poll_on: bool, ssecs: float) -> float:
                polling[0] = poll_on
                n = 0
                t0 = time.perf_counter()
                while time.perf_counter() - t0 < ssecs:
                    conn.sendall(blob)
                    got = 0
                    while got < want:
                        part = conn.recv(min(65536, want - got))
                        if not part:
                            raise ConnectionError(
                                "server closed mid-phase")
                        got += len(part)
                    n += burst
                return n / (time.perf_counter() - t0)

            def paired_ab(a_polls: bool) -> tuple:
                """Median per-round (B - A)/B pct with order alternated
                per round; arm B never polls."""
                pcts, a_qps, b_qps = [], [], []
                for r in range(rounds):
                    if r % 2 == 0:
                        qa = phase(a_polls, secs)
                        qb = phase(False, secs)
                    else:
                        qb = phase(False, secs)
                        qa = phase(a_polls, secs)
                    a_qps.append(qa)
                    b_qps.append(qb)
                    if qb > 0:
                        pcts.append((qb - qa) / qb * 100)
                pcts.sort()
                med = pcts[len(pcts) // 2] if pcts else 0.0
                return (round(med, 2),
                        round(sum(a_qps) / len(a_qps), 1),
                        round(sum(b_qps) / len(b_qps), 1))

            phase(True, 0.2)                  # warm both phase shapes
            phase(False, 0.2)
            pct, qp, qn = paired_ab(True)     # poll vs no-poll
            noise, _, _ = paired_ab(False)    # no-poll vs no-poll
            poll_stop[0] = True
            pt.join(5)
            conn.close()
            return pct, noise, qp, qn
        finally:
            srv.stop()

    qps, p50, p99 = measure(native=True)
    extra["http_1kb_qps"] = qps
    if p50 is not None:
        extra["http_1kb_p50_us"] = p50
        extra["http_1kb_p99_us"] = p99
    try:
        extra["http_1kb_qps_c16"] = measure_load(16)
    except Exception as e:
        extra["http_c16_error"] = f"{type(e).__name__}: {e}"[:120]
    try:
        slim_qps, classic_qps = measure_pipelined()
        extra["http_1kb_pipelined_qps"] = slim_qps
        extra["http_1kb_pipelined_classic_qps"] = classic_qps
        if classic_qps:
            extra["http_slim_vs_classic"] = round(slim_qps / classic_qps,
                                                  2)
    except Exception as e:
        extra["http_pipelined_error"] = f"{type(e).__name__}: {e}"[:120]
    try:
        pct, noise, qps_poll, qps_nopoll = measure_telemetry_overhead()
        extra["native_telemetry_overhead_pct"] = pct
        extra["native_telemetry_ab_noise_pct"] = noise
        extra["native_telemetry_poll_qps"] = qps_poll
        extra["native_telemetry_nopoll_qps"] = qps_nopoll
    except Exception as e:
        extra["telemetry_overhead_error"] = f"{type(e).__name__}: {e}"[:120]
    qps, p50, p99 = measure(native=False)
    extra["http_1kb_pytransport_qps"] = qps
    if p99 is not None:
        extra["http_1kb_pytransport_p99_us"] = p99


def bench_trace(extra: dict) -> None:
    """trace_propagation_overhead_pct: cost of FORCING a trace on the
    hottest Controller lane (tpu_std slim native dispatch) — forced
    traces ride the same native path as untraced calls since the
    distributed-rpcz PR (trace TLVs in the raw_call tail, context
    through the kind-3 shim, client+server span recording), so this
    pair bounds the whole observer effect: TLV bytes + two Span
    objects + two store inserts per call.  Paired interleaved A/B with
    alternating order and the MEDIAN per-round overhead reported, plus
    the same-methodology no-trace/no-trace control as the noise floor
    (methodology of native_telemetry_overhead_pct)."""
    from brpc_tpu.client import Channel, ChannelOptions, Controller
    from brpc_tpu.rpcz import global_span_store
    from brpc_tpu.server import Server, ServerOptions, Service

    class TraceEcho(Service):
        def Echo(self, cntl, request):
            return request

    rounds, secs = 7, 0.4
    opts = ServerOptions()
    opts.native = True
    opts.native_loops = 1
    opts.usercode_inline = True
    srv = Server(opts)
    srv.add_service(TraceEcho(), name="TR")
    assert srv.start("127.0.0.1:0") == 0
    try:
        co = ChannelOptions()
        co.connection_type = "pooled"
        ch = Channel(co)
        ch.init(str(srv.listen_endpoint))
        payload = bytes(128)
        tid_counter = [1]

        def phase(traced: bool, ssecs: float) -> float:
            n = 0
            t0 = time.perf_counter()
            while time.perf_counter() - t0 < ssecs:
                cntl = Controller()
                cntl.timeout_ms = 10_000
                if traced:
                    tid_counter[0] += 1
                    cntl.trace_id = tid_counter[0]
                c = ch.call_method("TR.Echo", payload, cntl=cntl)
                if c.failed:
                    raise RuntimeError(c.error_text)
                n += 1
            return n / (time.perf_counter() - t0)

        def paired_ab(a_traced: bool) -> tuple:
            pcts, a_qps, b_qps = [], [], []
            for r in range(rounds):
                if r % 2 == 0:
                    qa = phase(a_traced, secs)
                    qb = phase(False, secs)
                else:
                    qb = phase(False, secs)
                    qa = phase(a_traced, secs)
                a_qps.append(qa)
                b_qps.append(qb)
                if qb > 0:
                    pcts.append((qb - qa) / qb * 100)
            pcts.sort()
            med = pcts[len(pcts) // 2] if pcts else 0.0
            return (round(med, 2),
                    round(sum(a_qps) / len(a_qps), 1),
                    round(sum(b_qps) / len(b_qps), 1))

        phase(True, 0.2)                  # warm both shapes
        phase(False, 0.2)
        pct, q_traced, q_plain = paired_ab(True)
        noise, _, _ = paired_ab(False)
        extra["trace_propagation_overhead_pct"] = pct
        extra["trace_propagation_ab_noise_pct"] = noise
        extra["trace_forced_qps"] = q_traced
        extra["trace_untraced_qps"] = q_plain
        global_span_store().clear()       # the bench recorded ~1e4 spans
    finally:
        srv.stop()


def bench_robustness(extra: dict) -> None:
    """§10 deadline plane: (a) goodput_under_overload — paired
    interleaved A/B at ~2x capacity, shedding ON vs OFF, measuring
    completed-WITHIN-DEADLINE QPS (what doomed work costs a saturated
    server); (b) retry_amplification_factor — proxy-free attempt
    accounting against a dead backend, channel retry budget on vs off
    (what hedging storms cost a degraded one)."""
    import socket as pysock

    from brpc_tpu.butil.flags import set_flag
    from brpc_tpu.client import Channel, ChannelOptions, Controller
    from brpc_tpu.deadline import shed_counters
    from brpc_tpu.server import Server, ServerOptions, Service

    import struct

    from brpc_tpu.protocol.meta import (RpcMeta, TLV_CORRELATION,
                                        TLV_TIMEOUT, encode_tlv)

    class Work(Service):
        def __init__(self):
            self.good = 0               # completions with budget left

        def Spin(self, cntl, request):
            time.sleep(0.002)           # 2ms of "handler work"
            rem = cntl.deadline_remaining_ms()
            if rem is not None and rem > 0:
                # the slim lane coalesces a burst's responses into one
                # writev at end-of-batch, so client-side arrival time
                # can't tell in-budget work from doomed work; the
                # handler's own completion-vs-deadline check can
                # (response build after this is ~µs)
                self.good += 1
            return b"done"

    opts = ServerOptions()
    opts.native = True
    opts.native_loops = 1
    opts.usercode_inline = True         # the overload model: one lane,
    srv = Server(opts)                  # queueing is the engine batch
    work = Work()
    srv.add_service(work, name="OV")
    assert srv.start("127.0.0.1:0") == 0
    ep = srv.listen_endpoint
    try:
        mtlv = encode_tlv(4, b"OV") + encode_tlv(5, b"Spin")
        DEADLINE_MS = 25                # ~12 handler slots per budget

        def _burst_frames(cid0: int, k: int) -> bytes:
            out = b""
            for i in range(k):
                mb = (TLV_CORRELATION + struct.pack("<Q", cid0 + i)
                      + mtlv + TLV_TIMEOUT
                      + struct.pack("<I", DEADLINE_MS))
                out += b"TRPC" + struct.pack("<II", len(mb), len(mb)) + mb
            return out

        def overload_window(secs: float) -> float:
            """One pipelined client, bursts of 24 requests with 25ms
            propagated budgets: each burst is ~2x what one budget can
            cover (24 x 2ms handler vs a 25ms deadline), so the tail's
            budgets die in the engine batch queue.  Shedding ON answers
            the doomed tail in microseconds and reaches the next
            burst's FRESH budgets ~20ms sooner; OFF burns 2ms of
            handler time per corpse first.  Returns completed-WITHIN-
            DEADLINE QPS, counted at the handler (see Work.Spin: the
            slim lane coalesces each burst's responses into one writev,
            so client-side arrival times can't see in-budget work)."""
            K = 24
            good0 = work.good
            cid = 1
            stop = time.perf_counter() + secs
            with pysock.create_connection(
                    (str(ep.host), ep.port), timeout=10) as c:
                c.settimeout(10)
                while time.perf_counter() < stop:
                    c.sendall(_burst_frames(cid, K))
                    cid += K
                    buf = b""
                    got = 0
                    while got < K:
                        while True:
                            if len(buf) >= 12:
                                (bl,) = struct.unpack_from("<I", buf, 4)
                                if len(buf) >= 12 + bl:
                                    break
                            buf += c.recv(65536)
                        (bl,) = struct.unpack_from("<I", buf, 4)
                        m = RpcMeta.decode(buf[12:12 + struct.unpack_from(
                            "<I", buf, 8)[0]])
                        assert m is not None
                        buf = buf[12 + bl:]
                        got += 1
            return (work.good - good0) / secs

        overload_window(0.4)            # warm connections + lanes
        shed_qps, noshed_qps = [], []
        sheds0 = sum(shed_counters().values())
        for r in range(4):              # interleaved, alternating order
            arms = [(True, shed_qps), (False, noshed_qps)]
            if r % 2:
                arms.reverse()
            for on, acc in arms:
                set_flag("enable_deadline_shed", on)
                acc.append(overload_window(1.0))
        set_flag("enable_deadline_shed", True)
        shed_q = statistics.median(shed_qps)
        noshed_q = statistics.median(noshed_qps)
        extra["goodput_under_overload_shed_qps"] = round(shed_q, 1)
        extra["goodput_under_overload_noshed_qps"] = round(noshed_q, 1)
        extra["goodput_under_overload"] = \
            round(shed_q / max(noshed_q, 0.1), 3)
        extra["goodput_bench_sheds"] = \
            sum(shed_counters().values()) - sheds0
    finally:
        srv.stop()

    # (b) retry amplification against a dead backend: attempts per call
    probe = pysock.socket()
    probe.bind(("127.0.0.1", 0))
    dead = f"127.0.0.1:{probe.getsockname()[1]}"
    probe.close()

    def amplification(budget_max: float) -> float:
        co = ChannelOptions()
        co.timeout_ms = 1000
        co.max_retry = 3
        co.connection_type = "pooled"
        co.retry_budget_max = budget_max
        ch = Channel(co)
        ch.init(dead)
        calls, attempts = 24, 0
        for _ in range(calls):
            cntl = Controller()
            cntl.timeout_ms = 1000
            c = ch.call_method("OV.Spin", b"", cntl=cntl)
            attempts += 1 + c.retried_count
        return attempts / calls

    extra["retry_amplification_factor"] = round(amplification(8.0), 3)
    extra["retry_amplification_unbudgeted"] = \
        round(amplification(0.0), 3)


def bench_overload_fairness(extra: dict) -> None:
    """§12 overload plane: (a) multi-tenant fairness — paired
    interleaved A/B with the hot tenant offering 10x its fair share,
    fair admission ON vs OFF, measuring the victim tenant's goodput
    and p99 ("one hot tenant cannot starve the rest"); (b)
    auto_limit_converged — AutoLimiter sanity on a synthetic latency
    curve (converges to a finite limit, shrinks under blow-up)."""
    import threading

    from brpc_tpu.butil.flags import set_flag, get_flag
    from brpc_tpu.butil.status import Errno
    from brpc_tpu.client import Channel, ChannelOptions, Controller
    from brpc_tpu.server import Server, ServerOptions, Service

    ELIMIT = int(Errno.ELIMIT)

    class Work(Service):
        def Spin(self, cntl, request):
            time.sleep(0.05)            # 50ms of "handler work": hot
            return b"done"              # calls block server-side, not
    #                                     on this 1-core box's GIL

    opts = ServerOptions()
    # fiber-pool server: real concurrent handlers — the contention the
    # tenant scheduler divides.  Capacity is sized WELL BELOW what one
    # Python client can offer on this 1-core box (~400 calls/s): tenant
    # capacity 2 at 50ms ≈ 40/s, so the hot tenant's ~300/s offered
    # load is ~7-15x its 1-slot (~20/s) fair share.  Fairness OFF:
    # FCFS on the server cap — a freed slot is re-taken by the hot
    # stream within a few ms, and the victim's modest-rate arrivals
    # mostly find it full.  Fairness ON: the hot tenant is held near
    # its weighted share and the victim's guaranteed slot always
    # admits.
    opts.max_concurrency = 3
    opts.tenant_fair_capacity = 2
    # enough fiber workers that ADMISSION is the only queue: an
    # admitted victim must run promptly, not sit behind hot handlers
    # in the worker pool (that queue is what CoDel/limiters manage,
    # not what this A/B measures)
    opts.num_workers = 16
    srv = Server(opts)
    srv.add_service(Work(), name="OV")
    assert srv.start("127.0.0.1:0") == 0
    addr = str(srv.listen_endpoint)
    HOT_WINDOW = 24                     # pipelined in-flight frames
    stop_evt = threading.Event()

    def hot_client():
        """Raw pipelined byte-lane flood with the hot tenant's TLV: a
        window of 24 frames, one fresh frame per response read.  A
        rejected frame bounces back in ~1ms and is immediately
        re-offered, so a freed slot is re-taken within ~1-2ms — real
        oversubscription pressure without 20 Controller threads
        burning this 1-core box's GIL against the victim's client."""
        import socket as pysock
        import struct
        from brpc_tpu.protocol.meta import (TLV_CORRELATION, encode_tlv)

        ep = srv.listen_endpoint
        mtlv = (encode_tlv(4, b"OV") + encode_tlv(5, b"Spin")
                + encode_tlv(22, b"hot"))

        def frame(cid):
            mb = TLV_CORRELATION + struct.pack("<Q", cid) + mtlv
            return b"TRPC" + struct.pack("<II", len(mb), len(mb)) + mb

        while not stop_evt.is_set():
            try:
                with pysock.create_connection(
                        (str(ep.host), ep.port), timeout=5) as c:
                    c.settimeout(5)
                    cid = 1
                    c.sendall(b"".join(frame(cid + i)
                                       for i in range(HOT_WINDOW)))
                    cid += HOT_WINDOW
                    buf = b""
                    while not stop_evt.is_set():
                        while True:
                            if len(buf) >= 12:
                                (bl,) = struct.unpack_from("<I", buf, 4)
                                if len(buf) >= 12 + bl:
                                    break
                            buf += c.recv(65536)
                        (bl,) = struct.unpack_from("<I", buf, 4)
                        buf = buf[12 + bl:]
                        c.sendall(frame(cid))
                        cid += 1
            except OSError:
                if not stop_evt.is_set():
                    time.sleep(0.05)

    def victim_window(secs: float):
        """Serial victim at its own modest pace (~40/s offered — it IS
        the well-behaved tenant; hammering retries would just measure
        a GIL race against the hot client's offer loop): returns
        (goodput_qps, p99_ms of the successful calls).  With fairness
        off its goodput is the probability a FCFS slot happens to be
        free at its arrival instant; with fairness on its guaranteed
        share admits it regardless of the hot tenant's pressure."""
        co = ChannelOptions()
        co.timeout_ms = 2000
        co.max_retry = 0
        co.connection_type = "pooled"
        co.tenant = "victim"
        ch = Channel(co)
        ch.init(addr)
        good, lats = 0, []
        t_end = time.perf_counter() + secs
        while time.perf_counter() < t_end:
            cntl = Controller()
            cntl.timeout_ms = 2000
            t0 = time.perf_counter()
            c = ch.call_method("OV.Spin", b"", cntl=cntl)
            if not c.failed:
                good += 1
                lats.append((time.perf_counter() - t0) * 1e3)
            time.sleep(0.015)
        lats.sort()
        p99 = lats[int(len(lats) * 0.99)] if lats else None
        return good / secs, p99

    prev_fair = get_flag("enable_fair_admission", True)
    hot = threading.Thread(target=hot_client, daemon=True)
    try:
        hot.start()
        time.sleep(0.3)                 # hot load reaches steady state
        on_q, off_q, on_p, off_p = [], [], [], []
        for r in range(6):              # interleaved, alternating order
            arms = [(True, on_q, on_p), (False, off_q, off_p)]
            if r % 2:
                arms.reverse()
            for fair, q_acc, p_acc in arms:
                set_flag("enable_fair_admission", fair)
                time.sleep(0.15)        # in-flight mix turns over
                q, p99 = victim_window(1.2)
                q_acc.append(q)
                if p99 is not None:
                    p_acc.append(p99)
    finally:
        set_flag("enable_fair_admission", prev_fair)
        stop_evt.set()
        hot.join(5)
        srv.stop()
    on_med = statistics.median(on_q)
    off_med = statistics.median(off_q)
    extra["overload_fairness_victim_qps_fair_on"] = round(on_med, 1)
    extra["overload_fairness_victim_qps_fair_off"] = round(off_med, 1)
    extra["overload_fairness_victim_goodput"] = \
        round(on_med / max(off_med, 0.1), 3)
    if on_p:
        extra["overload_fairness_victim_p99_ms"] = \
            round(statistics.median(on_p), 2)
    if off_p:
        extra["overload_fairness_victim_p99_ms_fair_off"] = \
            round(statistics.median(off_p), 2)

    # (b) AutoLimiter convergence sanity: synthetic steady curve then a
    # 20x blow-up — converged finite limit that shrinks under overload
    from brpc_tpu.policy.concurrency_limiter import AutoLimiter
    lim = AutoLimiter(min_limit=2, sample_window_s=0.01,
                      min_sample_count=10)

    def feed(n, lat_us, batches):
        for _ in range(batches):
            for _ in range(n):
                lim.on_responded(0, lat_us)
            time.sleep(0.012)
            lim.on_responded(0, lat_us)

    feed(25, 2_000, 10)
    steady = lim.max_concurrency()
    feed(25, 40_000, 10)
    shrunk = lim.max_concurrency()
    extra["auto_limit_steady"] = steady
    extra["auto_limit_overloaded"] = shrunk
    extra["auto_limit_converged"] = \
        1.0 if (2 <= steady <= 256 and shrunk < steady) else 0.0


def bench_operability(extra: dict) -> None:
    """§15 fleet operability (ISSUE 12): (a) rolling_restart_failed_rpcs
    — a 3-replica fleet under sustained Controller load has every
    replica drained + replaced (lame-duck signal, ELAMEDUCK fail-fast
    retry, file-NS republish); the acceptance pins the failure count at
    EXACTLY 0.  (b) drain_p99_victim_ms — the load's per-call p99
    across the whole roll (victims ride retries while neighbors
    restart).  (c) conns_10k_rss_mb — idle-connection memory probe:
    K idle conns' RSS delta scaled to 10k (both endpoints live in this
    process, so the number covers client+server halves — the honest
    same-box bound for the many-users story)."""
    import socket as pysock
    import threading

    import brpc_tpu.client.naming_service as _ns_mod
    from brpc_tpu.client import Channel, ChannelOptions, Controller
    from brpc_tpu.client.naming_service import global_lame_ducks
    from brpc_tpu.server import Server, ServerOptions, Service

    class Op(Service):
        def Echo(self, cntl, request):
            return b"ok:" + bytes(request)

    def mk(publish_to=None):
        srv = Server(ServerOptions())
        srv.add_service(Op(), name="OP")
        assert srv.start("127.0.0.1:0") == 0
        if publish_to:
            assert srv.publish(publish_to) == 0
        return srv

    import tempfile
    nsdir = tempfile.mkdtemp(prefix="bench_fleet_")
    nsfile = os.path.join(nsdir, "fleet")
    open(nsfile, "w").close()
    old_refresh = _ns_mod.DEFAULT_REFRESH_S
    _ns_mod.DEFAULT_REFRESH_S = 0.2
    replicas = [mk(f"file://{nsfile}") for _ in range(3)]
    try:
        copts = ChannelOptions()
        copts.timeout_ms = 3000
        ch = Channel(copts)
        assert ch.init(f"file://{nsfile}", "rr") == 0

        stop = threading.Event()
        lat_ms: list = []
        counts = [0, 0]                 # sent, failed
        lock = threading.Lock()

        def load():
            i = 0
            while not stop.is_set():
                i += 1
                t0 = time.perf_counter()
                ok = True
                try:
                    r = ch.call("OP.Echo", b"x")
                    ok = (r == b"ok:x")
                except Exception:
                    ok = False
                dt = (time.perf_counter() - t0) * 1e3
                with lock:
                    counts[0] += 1
                    if not ok:
                        counts[1] += 1
                    lat_ms.append(dt)

        workers = [threading.Thread(target=load, daemon=True)
                   for _ in range(3)]
        for t in workers:
            t.start()
        time.sleep(0.4)
        for idx in range(3):            # the roll: successor-first
            old = replicas[idx]
            new = mk(f"file://{nsfile}")
            time.sleep(0.45)            # one naming refresh period
            old.drain(grace_ms=3000)
            old.stop()
            old.join(timeout=3)
            replicas[idx] = new
            time.sleep(0.3)
        stop.set()
        for t in workers:
            t.join(timeout=10)
        extra["rolling_restart_total_rpcs"] = counts[0]
        extra["rolling_restart_failed_rpcs"] = counts[1]
        if lat_ms:
            lat_ms.sort()
            extra["drain_p99_victim_ms"] = round(
                lat_ms[min(len(lat_ms) - 1,
                           int(len(lat_ms) * 0.99))], 3)
    finally:
        _ns_mod.DEFAULT_REFRESH_S = old_refresh
        for s in replicas:
            try:
                s.stop()
            except Exception:
                pass
        global_lame_ducks().reset()

    # ---- idle-connection memory probe, scaled to the box ----
    def _rss_kb() -> int:
        with open("/proc/self/status") as f:
            for ln in f:
                if ln.startswith("VmRSS:"):
                    return int(ln.split()[1])
        return 0

    import resource
    soft_nofile = resource.getrlimit(resource.RLIMIT_NOFILE)[0]
    k = max(100, min(1000, (soft_nofile - 256) // 2))
    srv = Server(ServerOptions())
    srv.add_service(Op(), name="OP")
    assert srv.start("127.0.0.1:0") == 0
    conns = []
    try:
        ep = srv.listen_endpoint
        # settle allocator state before the baseline read
        for _ in range(3):
            c = pysock.create_connection((str(ep.host), ep.port),
                                         timeout=10)
            conns.append(c)
        time.sleep(0.3)
        rss0 = _rss_kb()
        for _ in range(k):
            conns.append(pysock.create_connection(
                (str(ep.host), ep.port), timeout=10))
        deadline = time.time() + 5
        while srv.connection_count() < k and time.time() < deadline:
            time.sleep(0.02)
        time.sleep(0.3)
        rss1 = _rss_kb()
        extra["conns_probe_count"] = k
        extra["conns_10k_rss_mb"] = round(
            max(0, rss1 - rss0) / 1024.0 * (10000.0 / k), 1)
    finally:
        for c in conns:
            try:
                c.close()
            except OSError:
                pass
        srv.stop()


def bench_grpc(extra: dict) -> None:
    """gRPC unary 1KB echo: a real grpcio client against our server ON
    THE NATIVE PORT (h2 rides the engine's passthrough lane — native
    epoll + loop-thread dispatch carry the h2 session), with grpcio-
    client -> grpcio-server loopback on the SAME box as the oracle
    baseline (VERDICT r4 #7: beat grpcio-loopback)."""
    try:
        import grpc
    except Exception:
        extra["grpc_bench_skipped"] = "grpcio not importable"
        return

    from brpc_tpu.server import Server, ServerOptions, Service

    _ident = lambda b: b  # noqa: E731

    class GEcho(Service):
        def Echo(self, cntl, request):
            return request

    def measure(addr: str) -> tuple:
        body = bytes(1024)
        with grpc.insecure_channel(addr) as ch:
            fn = ch.unary_unary("/GEcho/Echo",
                                request_serializer=_ident,
                                response_deserializer=_ident)
            for _ in range(20):
                fn(body, timeout=10)
            lats = []
            t0 = time.perf_counter()
            n = 0
            while time.perf_counter() - t0 < 3.0:
                c0 = time.perf_counter()
                if len(fn(body, timeout=10)) == 1024:
                    n += 1
                    lats.append((time.perf_counter() - c0) * 1e6)
            dt = time.perf_counter() - t0
            lats.sort()
            return (round(n / dt, 1),
                    round(lats[int(len(lats) * 0.99)], 1) if lats
                    else None)

    def measure_load(addr: str, nconn: int = 16,
                     seconds: float = 3.0) -> float:
        """Multi-channel load variant (VERDICT r5 Weak #4): nconn
        independent grpc channels (own h2 connection each) in nconn
        threads — what the lane does under load, not serial latency."""
        import threading

        body = bytes(1024)
        counts = [0] * nconn
        start = threading.Barrier(nconn + 1)
        stop = [False]

        def worker(i):
            with grpc.insecure_channel(addr) as ch:
                fn = ch.unary_unary("/GEcho/Echo",
                                    request_serializer=_ident,
                                    response_deserializer=_ident)
                try:
                    try:
                        for _ in range(3):
                            fn(body, timeout=10)
                    finally:
                        start.wait(30)   # see the http variant: the
                        #                  barrier must always be reached
                    while not stop[0]:
                        if len(fn(body, timeout=10)) == 1024:
                            counts[i] += 1
                except Exception:
                    pass

        ts = [threading.Thread(target=worker, args=(i,))
              for i in range(nconn)]
        for t in ts:
            t.start()
        start.wait(60)
        t0 = time.perf_counter()
        time.sleep(seconds)
        stop[0] = True
        for t in ts:
            t.join(15)
        return round(sum(counts) / (time.perf_counter() - t0), 1)

    gopts = ServerOptions()
    gopts.native = True
    gopts.native_loops = 1
    gopts.usercode_inline = True
    srv = Server(gopts)
    srv.add_service(GEcho(), name="GEcho")
    assert srv.start("127.0.0.1:0") == 0
    try:
        qps, p99 = measure(str(srv.listen_endpoint))
        extra["grpc_unary_qps"] = qps
        if p99 is not None:
            extra["grpc_unary_p99_us"] = p99
        try:
            extra["grpc_unary_qps_c16"] = measure_load(
                str(srv.listen_endpoint), 16)
        except Exception as e:
            extra["grpc_c16_error"] = f"{type(e).__name__}: {e}"[:120]
    finally:
        srv.stop()

    # oracle: grpcio server answering the same shape on the same box
    try:
        from concurrent import futures

        class _Handler(grpc.GenericRpcHandler):
            def service(self, details):
                if details.method == "/GEcho/Echo":
                    return grpc.unary_unary_rpc_method_handler(
                        lambda req, ctx: req,
                        request_deserializer=_ident,
                        response_serializer=_ident)
                return None

        gsrv = grpc.server(futures.ThreadPoolExecutor(max_workers=2))
        gsrv.add_generic_rpc_handlers((_Handler(),))
        port = gsrv.add_insecure_port("127.0.0.1:0")
        gsrv.start()
        try:
            oq, op99 = measure(f"127.0.0.1:{port}")
            extra["grpc_unary_grpcio_oracle_qps"] = oq
            if op99 is not None:
                extra["grpc_unary_grpcio_oracle_p99_us"] = op99
            if oq:
                extra["grpc_vs_grpcio_oracle"] = round(
                    extra["grpc_unary_qps"] / oq, 2)
        finally:
            gsrv.stop(0)
    except Exception as e:
        extra["grpc_oracle_error"] = f"{type(e).__name__}: {e}"[:120]


def bench_device_echo(extra: dict) -> None:
    """The rdma_performance north star: 1MB device tensor echo, payload
    never leaving the device fabric (descriptor send + window/ack)."""
    import jax
    import jax.numpy as jnp

    from brpc_tpu.client import Channel, Controller
    from brpc_tpu.models.ps_service import PSService
    from brpc_tpu.server import Server

    srv = Server()
    srv.add_service(PSService(), name="PS")
    assert srv.start("127.0.0.1:0") == 0
    try:
        from brpc_tpu.client import ChannelOptions
        copts = ChannelOptions()
        copts.connection_type = "pooled"     # descriptor sends ride the
        ch = Channel(copts)                  # sync fast lane
        ch.init(str(srv.listen_endpoint))
        x = jnp.arange((1 << 20) // 4, dtype=jnp.float32)   # 1MB in HBM
        x.block_until_ready()
        def one():
            cntl = Controller()
            cntl.timeout_ms = 120_000
            cntl.request_device_attachment = x
            c = ch.call_method("PS.EchoTensor", b"", cntl=cntl)
            assert not c.failed, c.error_text
            return c.response_device_attachment.tensor()

        # warm + gauge the chip's current speed (the tunneled chip has
        # throttled phases 100x apart); size N to a ~1s window and take
        # the best of 3 windows — the data path is pure host-side
        # descriptor passing, so the bench measures control-plane rps
        # and sandbox scheduling noise dominates single windows
        t0 = time.perf_counter()
        for _ in range(10):
            one()
        per_call = (time.perf_counter() - t0) / 10
        N = max(10, min(4000, int(1.0 / max(per_call, 1e-6))))
        best_rps = 0.0
        frac = 1.0
        window_rps = []
        # 5 windows: this lane swings >2x BETWEEN whole runs on this
        # box (r4's recorded 'regression' 2905->1410 rps re-measured
        # r5 as 1789..3208 across three back-to-back runs of an
        # unchanged lane) — more windows cut the odds a throttled
        # phase owns the whole record; the min/max spread is recorded
        # so the number stays interpretable
        for _ in range(5):
            t0 = time.perf_counter()
            hits = 0
            for _ in range(N):
                if one() is x:       # zero-copy end to end
                    hits += 1
            dt = time.perf_counter() - t0
            # a transient reconnect restarts the domain exchange and
            # host-stages one call; the fabric must still carry ~all
            assert hits >= N * 0.9, (hits, N)
            window_rps.append(N / dt)
            if N / dt > best_rps:
                best_rps = N / dt
                frac = hits / N
        extra["ici_1mb_tensor_rps_min_window"] = round(min(window_rps), 1)
        extra["ici_zero_copy_frac"] = round(frac, 3)
        extra["ici_1mb_tensor_gbps"] = round(
            best_rps * x.nbytes * 2 / 1e9, 3)
        extra["ici_1mb_tensor_rps"] = round(best_rps, 1)
        extra["ici_backend"] = jax.default_backend()
    finally:
        srv.stop()


def _matmul_ceiling_tflops(n: int = 8192, reps: int = 7) -> float:
    """The chip's CURRENT practical matmul throughput (bf16 n^3).  The
    tunnel throttles in phases 2-4x apart lasting minutes — every
    absolute device number in this bench is only meaningful next to the
    ceiling measured in the same window."""
    import time as _t

    import jax
    import jax.numpy as jnp
    a = jnp.ones((n, n), jnp.bfloat16)
    m = jax.jit(lambda a: a @ a)
    for _ in range(reps + 1):
        m(a)
    float(m(a).sum())
    t0 = _t.perf_counter()
    for _ in range(reps - 1):
        m(a)
    float(m(a).sum())
    return 2 * n ** 3 * reps / (_t.perf_counter() - t0) / 1e12


V5E_PEAK_TFLOPS = 197.0     # nominal bf16 peak of the serving chip


def bench_device_compute(extra: dict) -> None:
    """Model-side hot ops on the real chip: the Pallas flash-attention
    kernel vs XLA dense attention (with closed-form TFLOP/s and the
    same-window matmul ceiling), and the int8 serving-decode story."""
    import time as _t

    import jax
    import jax.numpy as jnp

    from brpc_tpu.ops.flash_attention import flash_attention
    from brpc_tpu.parallel.ring_attention import reference_attention

    b, s, h, d = 2, 2048, 8, 128
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q, k, v = (jax.random.normal(kk, (b, s, h, d), jnp.bfloat16) * 0.5
               for kk in ks)

    # n calls queued back-to-back on the device stream, ONE scalar D2H
    # sync on the last (float() — the reliable completion barrier on
    # this tunneled backend; TPU executes queued programs in order, so
    # the last scalar transfers only after all n finish).  Best of two
    # windows: the tunnel has throttled phases.
    def amortized_us(f, n=16):
        float(f(q, k, v))                       # compile + warm
        best = float("inf")
        for _ in range(2):
            t0 = _t.perf_counter()
            for _ in range(n - 1):
                f(q, k, v)
            float(f(q, k, v))
            best = min(best, (_t.perf_counter() - t0) / n * 1e6)
        return best

    flash = jax.jit(
        lambda q, k, v: jnp.sum(flash_attention(q, k, v, True)))
    dense = jax.jit(
        lambda q, k, v: jnp.sum(reference_attention(q, k, v, causal=True)))
    tf = amortized_us(flash)
    td = amortized_us(dense)
    extra["flash_attn_2k_us"] = round(tf, 1)
    extra["flash_vs_xla_dense"] = round(td / tf, 2)

    # long context (16k): where the O(seq) flash schedule + the causal
    # triangular grid matter.  Closed-form causal fwd FLOPs =
    # 2*b*h*s^2*d.  The ceiling probe is INTERLEAVED with the kernel
    # windows — one probe per round, ratio computed per round, median
    # reported — exactly like the int8 lane (VERDICT r5 Weak #3/Next
    # #4: a single up-front probe let a throttle-phase swing masquerade
    # as a kernel regression).  The min-ratio key makes the spread
    # visible in the record.
    try:
        s16 = 16384
        q, k, v = (jax.random.normal(kk, (1, s16, 8, 128),
                                     jnp.bfloat16) * 0.5 for kk in ks)
        float(flash(q, k, v))                  # compile + warm

        def one_window(f, n=8):
            t0 = _t.perf_counter()
            for _ in range(n - 1):
                f(q, k, v)
            float(f(q, k, v))
            return (_t.perf_counter() - t0) / n * 1e6

        fl = 2 * 1 * 8 * s16 * s16 * 128
        dense_ok = True
        try:
            # dense may OOM at 16k (8.6GB of scores) — the flash number
            # is exactly the interesting datum then
            float(dense(q, k, v))
        except Exception as e:
            dense_ok = False
            extra["flash_16k_dense_error"] = f"{type(e).__name__}: {e}"[:120]
        ceils, tfs, ratios, dratios = [], [], [], []
        for _ in range(3):
            ceil = _matmul_ceiling_tflops(reps=5)
            tf16 = one_window(flash)
            ceils.append(ceil)
            tfs.append(tf16)
            ratios.append(fl / (tf16 / 1e6) / 1e12 / max(ceil, 1e-9))
            if dense_ok:
                dratios.append(one_window(dense) / tf16)
        extra["device_matmul_tflops"] = round(max(ceils), 1)
        tf_best = min(tfs)
        extra["flash_attn_16k_us"] = round(tf_best, 1)
        extra["flash_attn_tflops"] = round(fl / (tf_best / 1e6) / 1e12, 1)
        ratios.sort()
        extra["flash_vs_ceiling"] = round(ratios[len(ratios) // 2], 2)
        extra["flash_vs_ceiling_min"] = round(ratios[0], 2)
        if dratios:
            dratios.sort()
            extra["flash_vs_xla_dense_16k"] = round(
                dratios[len(dratios) // 2], 2)
    except Exception as e:
        extra["flash_16k_error"] = f"{type(e).__name__}: {e}"[:120]

    from brpc_tpu.models.transformer_lm import (LMConfig, init_params,
                                                make_train_step)
    cfg = LMConfig(vocab=4096, dim=512, heads=8, depth=4, max_seq=1024,
                   mlp_mult=4)
    params = init_params(jax.random.PRNGKey(0), cfg)
    ids = jax.random.randint(jax.random.PRNGKey(1), (8, 1024), 0,
                             cfg.vocab, jnp.int32)
    labels = jnp.roll(ids, -1, axis=-1)
    step = jax.jit(make_train_step(cfg))
    params, loss = step(params, ids, labels)       # compile + warm
    float(loss)
    N = 6
    best, worst = float("inf"), 0.0
    for _ in range(2):
        t0 = _t.perf_counter()
        for _ in range(N):
            params, loss = step(params, ids, labels)
        float(loss)                 # one scalar sync barriers the chain
        dt = _t.perf_counter() - t0
        best = min(best, dt)
        worst = max(worst, dt)
    extra["lm_train_tokens_per_s"] = round(ids.size * N / best, 0)
    # min-window spread key (VERDICT r5 Weak #7): phase vs regression
    # must be distinguishable from the record alone
    extra["lm_train_tokens_per_s_min_window"] = round(
        ids.size * N / worst, 0)

    # serving decode, batch 32, whole generation burst as ONE compiled
    # lax.scan program (models/transformer_lm.py make_decode_loop): a
    # per-token program pays the tunnel's ~ms dispatch per TOKEN; the
    # scan pays it per burst.  f32 vs weight-only int8 interleaved
    # within each round (phase-robust ratio).  This rig's fixed
    # per-iteration device overheads still dominate a model this size —
    # the closed-form weight-bytes ratio records the HBM story the
    # timer cannot isolate here (PERF.md §3), and compiles of
    # weight-dominated (>=1GB) models exceed this backend's compile
    # budget, so the bytes ratio IS the honest evidence.
    import functools as _ft

    from brpc_tpu.models.transformer_lm import make_decode_loop
    from brpc_tpu.ops.quant import quantize_lm_params
    # max_seq must cover every position the warm + timed rounds write
    # (1 + 5 rounds x 64 steps = 321) or later rounds degenerate into
    # rewriting the final cache slot under a saturated mask
    dcfg = LMConfig(vocab=4096, dim=512, heads=8, depth=4, max_seq=512,
                    mlp_mult=4, remat=False)
    dparams = init_params(jax.random.PRNGKey(2), dcfg)
    qparams = quantize_lm_params(dparams)

    def tree_bytes(t):
        return sum(x.size * x.dtype.itemsize
                   for x in jax.tree_util.tree_leaves(t))

    extra["lm_decode_weight_bytes_f32"] = int(tree_bytes(dparams))
    extra["lm_decode_weight_bytes_int8"] = int(tree_bytes(qparams))
    extra["lm_decode_weight_bytes_ratio"] = round(
        tree_bytes(dparams) / max(tree_bytes(qparams), 1), 2)

    B, NSTEP = 32, 64
    from brpc_tpu.models.transformer_lm import empty_cache
    _, loop = make_decode_loop(dcfg, NSTEP)

    tok = jnp.zeros((B,), jnp.int32)
    setups = []
    for tag, ps in (("f32", dparams), ("int8", qparams)):
        lfn = jax.jit(_ft.partial(loop, ps), donate_argnums=(0,))
        # empty_cache: the model's own layout (running prefill here
        # would pay its pathological compile twice for no measurement
        # value — the loop is what's under test)
        cache, toks = lfn(empty_cache(dcfg, B), tok)  # compile + warm
        jax.block_until_ready(toks)
        setups.append([tag, lfn, cache])
    best = {s[0]: float("inf") for s in setups}
    worst = {s[0]: 0.0 for s in setups}
    ratios = []
    for _ in range(4):
        times = {}
        for srec in setups:
            tag, lfn, cache = srec
            t0 = _t.perf_counter()
            cache, toks = lfn(cache, tok)
            jax.block_until_ready(toks)
            times[tag] = (_t.perf_counter() - t0) / NSTEP
            best[tag] = min(best[tag], times[tag])
            worst[tag] = max(worst[tag], times[tag])
            srec[2] = cache
        ratios.append(times["f32"] / times["int8"])
    for tag, t in best.items():
        extra[f"lm_decode_{tag}_tok_s"] = round(B / t, 1)
        # min-window spread keys (VERDICT r5 Weak #7)
        extra[f"lm_decode_{tag}_tok_s_min_window"] = round(
            B / worst[tag], 1)
    ratios.sort()
    extra["lm_decode_int8_speedup"] = round(ratios[len(ratios) // 2], 2)

    # op-level weight-streaming int8 measurement (VERDICT r4 #4): the
    # decode PROGRAM can't demonstrate the HBM win on this rig, so
    # measure the op the claim is about — stream N DISTINCT stacked
    # weight matrices (256MB bf16 vs 128MB int8, far beyond VMEM)
    # through a matmul chain: lax.scan over the weight axis (XLA
    # prefetches scan inputs) inside one program, weights passed as jit
    # ARGUMENTS (closure constants ride the compile request and blow
    # the remote compiler's size limit), interleaved bf16/int8 windows.
    # Two probes anchor interpretation: raw elementwise HBM bandwidth
    # and the fixed per-program floor — on this tunneled chip the floor
    # is ~70ms and marginal bandwidth ~20GB/s (vs 819GB/s on real v5e
    # HBM), so if the ratio reads ~1.0 the rig, not the quantization,
    # is the limit (PERF.md §3 carries the analysis).
    try:
        D, NW, ROUNDS = 2048, 32, 8     # 256MB bf16 streamed per round
        kw = jax.random.PRNGKey(3)
        Wb = (jax.random.normal(kw, (NW, D, D), jnp.bfloat16) * 0.05)
        scale = jnp.max(jnp.abs(Wb), axis=(1, 2), keepdims=True) \
            .astype(jnp.float32) / 127.0
        Wq = jnp.clip(jnp.round(Wb.astype(jnp.float32) / scale),
                      -127, 127).astype(jnp.int8)
        sc_b = scale.astype(jnp.bfloat16)
        x0 = jax.random.normal(jax.random.PRNGKey(4), (64, D),
                               jnp.bfloat16)

        def chain_bf16(W, x):
            def one_pass(r, acc):
                y, _ = jax.lax.scan(
                    lambda a, w: (jnp.tanh(a @ w), None), acc, W)
                return y
            return jax.lax.fori_loop(0, ROUNDS, one_pass, x)

        def chain_int8(Q, S, x):
            def one_pass(r, acc):
                def body(a, qs):
                    q, s = qs
                    # dequantize fuses into the dot operand read: HBM
                    # traffic is the int8 bytes
                    return jnp.tanh((a @ q.astype(jnp.bfloat16)) * s), \
                        None
                y, _ = jax.lax.scan(body, acc, (Q, S))
                return y
            return jax.lax.fori_loop(0, ROUNDS, one_pass, x)

        fb = jax.jit(lambda W, x: jnp.sum(chain_bf16(W, x)))
        fq = jax.jit(lambda Q, S, x: jnp.sum(chain_int8(Q, S, x)))
        float(fb(Wb, x0)); float(fq(Wq, sc_b, x0))    # compile + warm
        sratios, tb_best = [], float("inf")
        for _ in range(4):
            t0 = _t.perf_counter(); float(fb(Wb, x0))
            tb = _t.perf_counter() - t0
            t0 = _t.perf_counter(); float(fq(Wq, sc_b, x0))
            tq = _t.perf_counter() - t0
            sratios.append(tb / tq)
            tb_best = min(tb_best, tb)
        sratios.sort()
        extra["int8_stream_matmul_speedup"] = round(
            sratios[len(sratios) // 2], 2)
        streamed = NW * ROUNDS * D * D * 2          # bf16 bytes
        extra["int8_stream_bf16_gbs"] = round(
            streamed / tb_best / 1e9, 1)

        # interpretation anchors, same window: elementwise HBM probe at
        # two sizes — equal times = fixed per-program floor, and the
        # marginal rate is the usable bandwidth
        times = {}
        for mb in (256, 1024):
            n = mb * 1024 * 1024 // 2
            xp = jnp.ones((n,), jnp.bfloat16)
            fp = jax.jit(lambda x: x * 1.0001 + 0.5)
            float(fp(xp)[0])
            best = float("inf")
            for _ in range(3):
                t0 = _t.perf_counter()
                float(fp(xp)[0])
                best = min(best, _t.perf_counter() - t0)
            times[mb] = best
        extra["device_program_floor_ms"] = round(times[256] * 1e3, 1)
        marg = (1024 - 256) * 2 / 1024 / max(
            times[1024] - times[256], 1e-9)        # GB/s read+write
        extra["hbm_marginal_gbs"] = round(min(marg, 99999.0), 1)
    except Exception as e:
        extra["int8_stream_error"] = f"{type(e).__name__}: {e}"[:120]


def bench_device_mfu(extra: dict) -> None:
    """The chip-filling train step: dim 2048, depth 8, 0.5M tokens per
    optimizer step via in-jit gradient accumulation (lax.scan over 8
    microbatches of 32x2048 — single-microbatch HBM footprint).  MFU is
    model FLOPs (6*N*T) against the v5e nominal bf16 peak; the
    same-window matmul ceiling is recorded so throttle phases are
    visible (the sustained step regularly EXCEEDS the bursty probe)."""
    import time as _t

    import jax
    import jax.numpy as jnp

    from brpc_tpu.models.transformer_lm import (LMConfig, init_params,
                                                make_train_step)
    cfg = LMConfig(vocab=8192, dim=2048, heads=16, depth=8,
                   max_seq=2048, mlp_mult=4, use_flash=True, remat=True)
    params = init_params(jax.random.PRNGKey(0), cfg)
    nparams = sum(int(x.size)
                  for x in jax.tree_util.tree_leaves(params))
    ACC, B, S = 8, 32, 2048
    ids = jax.random.randint(jax.random.PRNGKey(1), (ACC * B, S), 0,
                             cfg.vocab, jnp.int32)
    labels = jnp.roll(ids, -1, axis=-1)
    step = jax.jit(make_train_step(cfg, accum=ACC), donate_argnums=(0,))
    params, loss = step(params, ids, labels)       # compile + warm
    float(loss)
    ceil = _matmul_ceiling_tflops()
    best = float("inf")
    for _ in range(2):
        t0 = _t.perf_counter()
        params, loss = step(params, ids, labels)
        float(loss)
        best = min(best, _t.perf_counter() - t0)
    tokens = ACC * B * S
    tflops = 6 * nparams * tokens / best / 1e12
    extra["lm_train_big_params_m"] = round(nparams / 1e6, 1)
    extra["lm_train_big_tokens_per_step"] = tokens
    extra["lm_train_big_tokens_per_s"] = round(tokens / best, 0)
    extra["lm_train_big_tflops"] = round(tflops, 1)
    extra["lm_train_mfu"] = round(tflops / V5E_PEAK_TFLOPS, 3)
    extra["lm_train_mfu_ceiling_tflops"] = round(ceil, 1)


def _device_section_worker(which: str, label: str, q) -> None:
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    extra: dict = {}
    try:
        if which == "compute":
            bench_device_compute(extra)
        elif which == "mfu":
            bench_device_mfu(extra)
        else:
            bench_device_echo(extra)
    except Exception as e:
        extra[f"{label}_error"] = f"{type(e).__name__}: {e}"[:160]
    q.put(extra)


def _run_device_section(which: str, label: str, timeout_s: float,
                        extra: dict) -> None:
    """Device-touching sections run in a CHILD process with a hard kill
    timeout: the tunneled chip has been seen to stall for minutes, and a
    wedged device call cannot be preempted in-process — but the bench
    must always print its JSON line."""
    import queue as _queue

    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    p = ctx.Process(target=_device_section_worker, args=(which, label, q))
    p.start()
    deadline = time.time() + timeout_s
    got = False
    while time.time() < deadline:
        try:
            # short poll: a child that DIED without reporting (OOM kill,
            # segfault in the device stack) must not eat the full budget
            extra.update(q.get(timeout=2.0))
            got = True
            break
        except _queue.Empty:
            if not p.is_alive():
                break
    if not got:
        why = ("died without result" if not p.is_alive()
               else f"no result within {timeout_s:.0f}s")
        extra[f"{label}_skipped"] = why
    if p.is_alive():
        p.terminate()
    p.join(10)
    if p.is_alive():
        # SIGTERM-resistant (wedged in a native device call): SIGKILL,
        # or the interpreter's exit joins would hang the whole bench
        p.kill()
        p.join(10)


def main() -> None:
    extra: dict = {}
    # hard internal budget: a throttled window can stretch sections into
    # minutes; the run must ALWAYS print its JSON before any outer
    # timeout, so optional sections are skipped once the budget is spent
    deadline = time.time() + float(os.environ.get("BENCH_BUDGET_S", 560))

    def budget_left(need: float = 0.0) -> bool:
        return time.time() + need < deadline

    # first: device compute wants the host un-throttled (dispatch
    # happens on the single host core; the RPC sections burn its
    # cgroup quota).  Child process + kill timeout: a stalled tunnel
    # must not take the whole bench down with it.
    _run_device_section("compute", "compute",
                        min(200.0, deadline - time.time()), extra)
    # the chip-filling MFU step (compile ~40s + two ~20s steps); its own
    # child so a wedged compile can't take the compute metrics with it
    if budget_left(200.0):
        _run_device_section("mfu", "mfu",
                            min(200.0, deadline - time.time()), extra)
    else:
        extra["mfu_skipped"] = "bench budget spent"
    headline = 0.0
    try:
        headline = bench_headline_and_sweep(extra)  # the metric: always
    except Exception as e:                          # the JSON still prints
        extra["headline_error"] = f"{type(e).__name__}: {e}"[:160]
    for name, fn in (("loop_scaling", bench_loop_scaling),
                     ("data_plane", bench_data_plane),
                     ("streaming", bench_streaming),
                     ("decode_stream", bench_decode_stream),
                     ("kv_disagg", bench_kv_disagg),
                     ("slo_sched", bench_slo_sched),
                     ("lm_telemetry", bench_lm_telemetry),
                     ("fleet_obs", bench_fleet_obs),
                     ("fanout", bench_fanout),
                     ("http", bench_http),
                     ("trace", bench_trace),
                     ("robustness", bench_robustness),
                     ("overload_fairness", bench_overload_fairness),
                     ("operability", bench_operability),
                     ("grpc", bench_grpc)):
        if not budget_left():
            extra[f"{name}_skipped"] = "bench budget spent"
            continue
        try:
            fn(extra)
        except Exception as e:
            extra[f"{name}_error"] = f"{type(e).__name__}: {e}"[:160]
    if budget_left():
        # cap by the remaining budget: overshooting the deadline would
        # defeat the always-print guarantee
        _run_device_section("echo", "ici",
                            min(150.0, deadline - time.time()), extra)
    else:
        extra["ici_skipped"] = "bench budget spent"
    print(json.dumps({
        "metric": "echo_1mb_attachment_throughput",
        "value": round(headline, 3),
        "unit": "GB/s",
        "vs_baseline": round(headline / BASELINE_GBPS, 3),
        "extra": extra,
    }))


if __name__ == "__main__":
    main()
