"""butil unit tests (≈ reference test/resource_pool_unittest.cpp,
test/endpoint_unittest.cpp, test/crc32c_unittest.cc, etc.)."""

import threading

import pytest

from brpc_tpu.butil import (ResourcePool, ObjectPool, DoublyBufferedData,
                            EndPoint, parse_endpoint, device_endpoint,
                            CaseIgnoredFlatMap, MRUCache, BoundedQueue,
                            fast_rand, fast_rand_less_than, fast_rand_double,
                            crc32c, crc32c_extend, Status, Errno,
                            id_slot, id_version)


class TestResourcePool:
    def test_acquire_address_release(self):
        pool = ResourcePool(factory=dict)
        rid, obj = pool.acquire()
        assert pool.address(rid) is obj
        assert pool.release(rid)
        assert pool.address(rid) is None          # stale id resolves to None
        assert not pool.release(rid)               # double release rejected

    def test_version_bump_on_reuse(self):
        pool = ResourcePool(factory=dict)
        rid1, _ = pool.acquire()
        pool.release(rid1)
        rid2, _ = pool.acquire()
        assert id_slot(rid1) == id_slot(rid2)      # slot reused
        assert id_version(rid1) != id_version(rid2)
        assert pool.address(rid1) is None          # old id is dead
        assert pool.address(rid2) is not None

    def test_concurrent_churn(self):
        pool = ResourcePool(factory=object)
        errors = []

        def churn():
            try:
                for _ in range(2000):
                    rid, obj = pool.acquire()
                    assert pool.address(rid) is obj
                    assert pool.release(rid)
            except Exception as e:  # pragma: no cover
                errors.append(e)

        ts = [threading.Thread(target=churn) for _ in range(4)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert not errors
        assert pool.live_count == 0

    def test_object_pool(self):
        resets = []
        pool = ObjectPool(factory=list, reset=lambda x: (x.clear(), resets.append(1)))
        a = pool.get()
        a.append(1)
        pool.put(a)
        b = pool.get()
        assert b is a and b == []
        assert pool.hits == 1


class TestDoublyBuffered:
    def test_read_modify(self):
        d = DoublyBufferedData([1, 2, 3])
        snap = d.read()
        assert snap == [1, 2, 3]
        d.modify(lambda lst: lst.append(4))
        assert d.read() == [1, 2, 3, 4]
        assert snap == [1, 2, 3]  # old snapshot untouched (RCU)

    def test_modify_abort(self):
        d = DoublyBufferedData({"a": 1})
        assert d.modify(lambda m: False) is False
        assert d.read() == {"a": 1}

    def test_reader_during_writes(self):
        d = DoublyBufferedData(list(range(10)))
        stop = threading.Event()
        bad = []

        def reader():
            while not stop.is_set():
                snap = d.read()
                if len(snap) not in (10, 11):
                    bad.append(len(snap))

        t = threading.Thread(target=reader)
        t.start()
        for i in range(200):
            d.modify(lambda lst: (lst.append(i), None)[1] if len(lst) == 10 else lst.pop() and None)
        stop.set()
        t.join()
        assert not bad


class TestEndPoint:
    def test_parse_ipv4(self):
        ep = parse_endpoint("127.0.0.1:8000")
        assert ep.host == "127.0.0.1" and ep.port == 8000
        assert str(ep) == "127.0.0.1:8000"
        assert not ep.is_device

    def test_parse_ipv6(self):
        ep = parse_endpoint("[::1]:80")
        assert ep.host == "::1" and ep.port == 80
        assert str(ep) == "[::1]:80"

    def test_parse_unix(self):
        ep = parse_endpoint("unix:/tmp/sock")
        assert ep.is_unix

    def test_parse_device(self):
        ep = parse_endpoint("ici://pod0/3")
        assert ep.is_device and ep.mesh == "pod0" and ep.device_index == 3
        assert str(ep) == "ici://pod0/3"
        assert ep == device_endpoint("pod0", 3)

    def test_hashable_value_type(self):
        s = {parse_endpoint("a:1"), parse_endpoint("a:1"), parse_endpoint("a:2")}
        assert len(s) == 2

    def test_bad(self):
        with pytest.raises(ValueError):
            parse_endpoint("")


class TestContainers:
    def test_case_ignored_map(self):
        m = CaseIgnoredFlatMap()
        m["Content-Type"] = "application/json"
        assert m["content-type"] == "application/json"
        assert "CONTENT-TYPE" in m
        assert list(m.keys()) == ["Content-Type"]  # original casing kept
        del m["Content-type"]
        assert len(m) == 0

    def test_mru_cache(self):
        c = MRUCache(2)
        c.put(1, "a")
        c.put(2, "b")
        c.get(1)
        c.put(3, "c")  # evicts 2 (least recently used)
        assert c.get(2) is None
        assert c.get(1) == "a" and c.get(3) == "c"

    def test_bounded_queue(self):
        q = BoundedQueue(2)
        assert q.push(1) and q.push(2) and not q.push(3)
        assert q.full
        q.push_force(3)  # evicts 1
        assert q.pop() == 2 and q.pop() == 3 and q.pop() is None


class TestRandAndHash:
    def test_fast_rand_spread(self):
        vals = {fast_rand_less_than(1000) for _ in range(200)}
        assert len(vals) > 50

    def test_fast_rand_double(self):
        for _ in range(100):
            v = fast_rand_double()
            assert 0.0 <= v < 1.0

    def test_crc32c_known_vectors(self):
        # standard CRC32C test vectors
        assert crc32c(b"") == 0
        assert crc32c(b"123456789") == 0xE3069283
        assert crc32c(b"a" * 32) == crc32c_extend(crc32c(b"a" * 16), b"a" * 16)


class TestStatus:
    def test_ok(self):
        st = Status.ok()
        assert st and st.is_ok() and st.error_str() == "OK"

    def test_error(self):
        st = Status(Errno.ERPCTIMEDOUT, "deadline 100ms exceeded")
        assert not st
        assert "ERPCTIMEDOUT" in st.error_str()
        assert st == Errno.ERPCTIMEDOUT
        st.reset()
        assert st
