"""rpcz persistence — sqlite span mirrors with time-range browsing
(≈ the reference's leveldb-backed rpcz, span.cpp:306-319): spans must
survive the process and stay browsable by time window."""

import json
import os
import time
import urllib.request

import pytest

from brpc_tpu.butil.flags import set_flag
from brpc_tpu.client import Channel, Controller
from brpc_tpu.rpcz import Span, browse_persisted, global_span_store
from brpc_tpu.server import Server, Service


@pytest.fixture()
def rpcz_dir(tmp_path):
    d = str(tmp_path / "rpcz")
    set_flag("rpcz_dir", d)
    store = global_span_store()
    store.clear()
    yield d
    store.flush_now()
    set_flag("rpcz_dir", "")
    store.clear()


def test_span_persists_and_browses_by_time(rpcz_dir):
    # explicit trace ids: traced spans are never sampled out, so these
    # records are immune to budget exhaustion by earlier RPC-heavy tests
    t0 = int(time.time() * 1e6)
    early = Span("S.Old", trace_id=0x11)
    early.received_us = t0 - 10_000_000
    early.annotate("ancient")
    early.finish()
    late = Span("S.New", trace_id=0x12)
    late.finish(error_code=7)
    store = global_span_store()
    store.flush_now()

    # whole range
    spans = browse_persisted(limit=10)
    methods = {s["method"] for s in spans}
    assert {"S.Old", "S.New"} <= methods
    # windowed: only the recent span
    recent = browse_persisted(start_us=t0 - 1_000_000, limit=10)
    assert {s["method"] for s in recent} == {"S.New"}
    assert recent[0]["error_code"] == 7
    # windowed: only the old span, annotations intact
    old = browse_persisted(end_us=t0 - 1_000_000, limit=10)
    assert {s["method"] for s in old} == {"S.Old"}
    assert old[0]["annotations"][0]["text"] == "ancient"


def test_spans_survive_process_death(rpcz_dir):
    """The in-memory store dying (≈ process exit) must not lose the
    persisted spans; a different reader browses the file."""
    s = Span("Dead.Rank", trace_id=0x13)
    s.finish()
    store = global_span_store()
    store.flush_now()
    store.clear()                      # "process died"
    assert store.recent() == []
    spans = browse_persisted(limit=5)
    assert any(r["method"] == "Dead.Rank" for r in spans)
    # the file is really on disk under the configured dir
    assert any(f.startswith("rpcz.") and f.endswith(".db")
               for f in os.listdir(rpcz_dir))


def test_rpcz_page_time_range(rpcz_dir):
    """/rpcz?start_us=...&persisted=1 serves the sqlite-backed view."""
    class Svc(Service):
        def Ping(self, cntl, request):
            return b"pong"

    srv = Server()
    srv.add_service(Svc(), name="T")
    assert srv.start("127.0.0.1:0") == 0
    try:
        ch = Channel()
        ch.init(str(srv.listen_endpoint))
        cntl = Controller()
        cntl.timeout_ms = 5_000
        cntl.trace_id = 0xabcd          # traced ⇒ always sampled
        c = ch.call_method("T.Ping", b"", cntl=cntl)
        assert not c.failed
        url = (f"http://{srv.listen_endpoint}/rpcz?persisted=1"
               f"&limit=50")
        with urllib.request.urlopen(url, timeout=10) as r:
            doc = json.loads(r.read())
        assert doc["persisted"] is True
        assert any(s["method"] == "T.Ping" for s in doc["spans"]), doc
    finally:
        srv.stop()


def test_uint64_trace_ids_persist(rpcz_dir):
    """fast_rand() trace ids are uniform uint64 — ~half exceed sqlite's
    signed INTEGER range; they must round-trip (signed-bridge encoding),
    not roll back the whole flush batch (review r4 finding)."""
    big = (1 << 63) + 12345
    s1 = Span("Big.Id", trace_id=big)
    s1.finish()
    s2 = Span("Small.Id", trace_id=0x42)
    s2.finish()
    store = global_span_store()
    store.flush_now()
    spans = browse_persisted(limit=10)
    methods = {r["method"] for r in spans}
    assert {"Big.Id", "Small.Id"} <= methods, methods
    (rec,) = [r for r in spans if r["method"] == "Big.Id"]
    assert int(rec["trace_id"], 16) == big
    # and trace-id filtered browsing finds it
    only = browse_persisted(limit=10, trace_id=big)
    assert [r["method"] for r in only] == ["Big.Id"]
