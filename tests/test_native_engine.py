"""Native C++ IO engine tests — raw-wire adversarial coverage.

Drives the engine (brpc_tpu/native/src/engine.cpp) the way the reference
tests Socket/InputMessenger directly (/root/reference/test/
brpc_socket_unittest.cpp): hand-built frames over raw TCP, byte-at-a-time
delivery, oversized bodies exercising the direct-read path, garbage
protocols, and teardown semantics.
"""

import socket
import struct
import time

import pytest

from brpc_tpu.butil.iobuf import IOBuf
from brpc_tpu.protocol.meta import RpcMeta
from brpc_tpu.server import Server, ServerOptions, Service

from conftest import require_native


@pytest.fixture(scope="module", autouse=True)
def _native_only():
    require_native()


class Echo(Service):
    def Echo(self, cntl, request):
        return request

    def Att(self, cntl, request):
        cntl.response_attachment.append(cntl.request_attachment.to_bytes())
        return b"ok"


@pytest.fixture(scope="module")
def nserver():
    opts = ServerOptions()
    opts.native = True
    srv = Server(opts)
    srv.add_service(Echo(), name="E")
    assert srv.start("127.0.0.1:0") == 0
    assert srv._native_bridge is not None, "engine did not come up"
    yield srv
    srv.stop()


def _connect(srv):
    s = socket.create_connection(("127.0.0.1", srv.listen_endpoint.port),
                                 timeout=10)
    s.settimeout(10)
    return s


def _frame(cid: int, payload: bytes, service="E", method="Echo") -> bytes:
    m = RpcMeta()
    m.correlation_id = cid
    m.service_name = service
    m.method_name = method
    mb = m.encode()
    return (b"TRPC" + struct.pack("<II", len(mb) + len(payload), len(mb))
            + mb + payload)


def _read_exact(s, n: int) -> bytes:
    out = b""
    while len(out) < n:
        chunk = s.recv(n - len(out))
        if not chunk:
            raise ConnectionError("eof")
        out += chunk
    return out


def _read_frame(s):
    head = _read_exact(s, 12)
    assert head[:4] == b"TRPC"
    body, msize = struct.unpack_from("<II", head, 4)
    raw = _read_exact(s, body)
    meta = RpcMeta.decode(raw[:msize])
    return meta, raw[msize:]


def test_roundtrip_raw_wire(nserver):
    s = _connect(nserver)
    try:
        s.sendall(_frame(7, b"hello-native"))
        meta, payload = _read_frame(s)
        assert meta.correlation_id == 7
        assert meta.error_code == 0
        assert payload == b"hello-native"
    finally:
        s.close()


def test_partial_frame_byte_at_a_time(nserver):
    s = _connect(nserver)
    try:
        f = _frame(8, b"trickle")
        for i in range(len(f)):
            s.sendall(f[i:i + 1])
        meta, payload = _read_frame(s)
        assert meta.correlation_id == 8
        assert payload == b"trickle"
    finally:
        s.close()


def test_two_frames_one_segment(nserver):
    s = _connect(nserver)
    try:
        s.sendall(_frame(21, b"first") + _frame(22, b"second"))
        got = {}
        for _ in range(2):
            meta, payload = _read_frame(s)
            got[meta.correlation_id] = payload
        assert got == {21: b"first", 22: b"second"}
    finally:
        s.close()


def test_large_body_direct_read(nserver):
    # > kInbufCap/2 (64KB) triggers the engine's direct-into-buffer path
    big = bytes(range(256)) * 4096          # 1 MB
    s = _connect(nserver)
    try:
        f = _frame(9, big)
        # two sends force the header/body split across reads
        s.sendall(f[:100])
        time.sleep(0.01)
        s.sendall(f[100:])
        meta, payload = _read_frame(s)
        assert meta.correlation_id == 9
        assert payload == big
    finally:
        s.close()


def test_unknown_protocol_closes_conn(nserver):
    # HTTP is a protocol the native port SPEAKS now (EV_HTTP): a GET
    # gets a real response, not a close
    s = _connect(nserver)
    try:
        s.sendall(b"GET / HTTP/1.1\r\nHost: x\r\n\r\n")
        assert s.recv(4096).startswith(b"HTTP/1.1 200")
    finally:
        s.close()
    # genuinely unknown bytes still hand to EV_UNKNOWN and close
    s = _connect(nserver)
    try:
        s.sendall(b"\x7f\x02unframed garbage bytes")
        assert s.recv(4096) == b""
    finally:
        s.close()
    # server still serves new connections afterwards
    s2 = _connect(nserver)
    try:
        s2.sendall(_frame(10, b"alive"))
        _, payload = _read_frame(s2)
        assert payload == b"alive"
    finally:
        s2.close()


def test_malformed_header_closes_conn(nserver):
    s = _connect(nserver)
    try:
        # meta_size > body_size is absolutely wrong per the framing rules
        s.sendall(b"TRPC" + struct.pack("<II", 4, 100) + b"xxxx")
        assert s.recv(4096) == b""
    finally:
        s.close()


def test_truncated_frame_then_close_is_harmless(nserver):
    s = _connect(nserver)
    s.sendall(_frame(11, b"abc")[:7])
    s.close()
    time.sleep(0.05)
    s2 = _connect(nserver)
    try:
        s2.sendall(_frame(12, b"still-up"))
        _, payload = _read_frame(s2)
        assert payload == b"still-up"
    finally:
        s2.close()


def test_tstr_spoofed_dest_dropped_conn_survives(nserver):
    s = _connect(nserver)
    try:
        # stream frame for a stream id never bound to this connection:
        # dispatch must drop it without killing the connection
        spoof = b"TSTR" + struct.pack("<BQI", 0, 0xDEAD_BEEF, 3) + b"boo"
        s.sendall(spoof)
        s.sendall(_frame(13, b"after-spoof"))
        meta, payload = _read_frame(s)
        assert meta.correlation_id == 13
        assert payload == b"after-spoof"
    finally:
        s.close()


def test_attachment_roundtrip_raw_wire(nserver):
    m = RpcMeta()
    m.correlation_id = 14
    m.service_name = "E"
    m.method_name = "Att"
    m.attachment_size = 5
    mb = m.encode()
    body = b"" + b"12345"
    f = b"TRPC" + struct.pack("<II", len(mb) + len(body), len(mb)) + mb + body
    s = _connect(nserver)
    try:
        s.sendall(f)
        meta, payload = _read_frame(s)
        assert meta.error_code == 0
        n = meta.attachment_size
        assert n == 5
        assert payload[-n:] == b"12345"
        assert payload[:-n] == b"ok"
    finally:
        s.close()


def test_unknown_method_error_frame(nserver):
    s = _connect(nserver)
    try:
        s.sendall(_frame(15, b"x", service="E", method="Nope"))
        meta, _ = _read_frame(s)
        assert meta.correlation_id == 15
        assert meta.error_code != 0
    finally:
        s.close()


def test_engine_stats_progress(nserver):
    eng = nserver._native_bridge.engine
    before = eng.stats()
    s = _connect(nserver)
    try:
        s.sendall(_frame(16, b"count-me"))
        _read_frame(s)
    finally:
        s.close()
    # the loop thread bumps bytes_out after writev returns — the client
    # can observe the response bytes first, so poll briefly
    deadline = time.monotonic() + 2.0
    while time.monotonic() < deadline:
        after = eng.stats()
        if (after["messages"] > before["messages"]
                and after["bytes_in"] > before["bytes_in"]
                and after["bytes_out"] > before["bytes_out"]):
            return
        time.sleep(0.005)
    raise AssertionError(f"stats did not progress: {before} -> {after}")


def test_pipelined_burst(nserver):
    # many frames in flight on one connection; all answered
    s = _connect(nserver)
    try:
        n = 64
        blob = b"".join(_frame(100 + i, b"p%03d" % i) for i in range(n))
        s.sendall(blob)
        got = {}
        for _ in range(n):
            meta, payload = _read_frame(s)
            got[meta.correlation_id] = payload
        assert got == {100 + i: b"p%03d" % i for i in range(n)}
    finally:
        s.close()


def test_server_stop_closes_native_conns():
    opts = ServerOptions()
    opts.native = True
    srv = Server(opts)
    srv.add_service(Echo(), name="E")
    assert srv.start("127.0.0.1:0") == 0
    s = _connect(srv)
    try:
        s.sendall(_frame(17, b"pre-stop"))
        _read_frame(s)
        srv.stop()
        assert s.recv(4096) == b""          # engine teardown closed us
    finally:
        s.close()


def test_oversized_body_rejected(nserver):
    s = _connect(nserver)
    try:
        # body_size beyond kMaxBody (512MB) must kill the connection,
        # not allocate
        s.sendall(b"TRPC" + struct.pack("<II", 0xFFFF_FFF0, 16))
        assert s.recv(4096) == b""
    finally:
        s.close()


def test_client_channel_over_native_server(nserver):
    from brpc_tpu.client import Channel
    ch = Channel()
    assert ch.init(str(nserver.listen_endpoint)) == 0
    assert ch.call("E.Echo", b"via-channel") == b"via-channel"
    big = bytes(range(256)) * 2048          # 512KB both directions
    assert ch.call("E.Echo", big) == big


def test_native_stop_closes_listener():
    """After Server.stop() on a native server, new connects must be
    REFUSED — an open listen fd would let the kernel complete
    handshakes into the backlog of a server that never serves them
    (health checks then 'revive' sockets into a black hole and calls
    hang to their deadlines)."""
    import errno
    import socket as _s

    from brpc_tpu.server import Server, ServerOptions, Service

    class E(Service):
        def Echo(self, cntl, request):
            return request

    opts = ServerOptions()
    opts.native = True
    srv = Server(opts)
    srv.add_service(E(), name="E")
    assert srv.start("127.0.0.1:0") == 0
    ep = srv.listen_endpoint
    srv.stop()
    c = _s.socket()
    c.settimeout(1.0)
    try:
        c.connect((str(ep.host), int(ep.port)))
        # a connect that "succeeds" against a closed server means the
        # backlog accepted it — the bug this test pins down
        raise AssertionError("connect succeeded after server stop")
    except (ConnectionRefusedError, _s.timeout, OSError) as e:
        if isinstance(e, OSError) and getattr(e, "errno", None) not in (
                errno.ECONNREFUSED, errno.ETIMEDOUT, None):
            raise
    finally:
        c.close()
