"""Fleet observability plane tests (ISSUE 19): closed FLEET_EVENTS
pins, the flight-recorder ring, the one-build-per-interval report
cache, registry TTL/drain semantics, the /fleet portal +
/metrics?fleet=1 federation, the KV.Probe load-report tail, the fleet
trace index, and the 3-process soak (register / kill -9 → stale /
drain → draining)."""

import json
import http.client
import os
import subprocess
import sys
import threading
import time

import pytest

from brpc_tpu import fleet
from brpc_tpu.server import Server, Service

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _fresh_fleet():
    fleet._reset_for_tests()
    yield
    fleet._reset_for_tests()


# ---------------------------------------------------------------------------
# Flight recorder: closed enum + bounded ring
# ---------------------------------------------------------------------------

# one literal pin per FLEET_EVENTS member (tools/check/enums.py scans
# this file's text for every name — keep them spelled out)
FLEET_EVENT_PINS = (
    "fleet_restart",
    "fleet_drain",
    "fleet_lame_duck",
    "fleet_stop",
    "fleet_register",
    "fleet_deregister",
    "fleet_member_stale",
    "fleet_breaker_trip",
    "fleet_kv_handoff_failed",
    "fleet_kv_evict",
    "fleet_host_spill",
)


def test_fleet_events_closed_and_pinned():
    assert set(FLEET_EVENT_PINS) == set(fleet.FLEET_EVENTS)
    for e in FLEET_EVENT_PINS:
        fleet.record_event(e, "pin")
    counts = fleet.event_counters()
    for e in FLEET_EVENT_PINS:
        assert counts[e] == 1, e
    # closed: an unregistered event fails loudly at the first record
    with pytest.raises(AssertionError):
        fleet.record_event("fleet_" + "unregistered")


def test_flight_recorder_ring_bounded():
    fleet._reset_for_tests(ring=8)
    for i in range(30):
        fleet.record_event("fleet_kv_evict", f"n{i}")
    rows = fleet.recent_events(100)
    assert len(rows) == 8
    assert rows[-1]["detail"] == "n29"          # newest kept
    assert rows[0]["detail"] == "n22"           # oldest evicted
    assert fleet.event_counters()["fleet_kv_evict"] == 30


def test_flight_recorder_flag_gated():
    from brpc_tpu.butil.flags import set_flag
    set_flag("fleet_obs", False)
    try:
        fleet.record_event("fleet_kv_evict", "off")
        assert fleet.event_counters()["fleet_kv_evict"] == 0
        assert fleet.recent_events() == []
    finally:
        set_flag("fleet_obs", True)
    fleet.record_event("fleet_kv_evict", "on")
    assert fleet.event_counters()["fleet_kv_evict"] == 1


# ---------------------------------------------------------------------------
# Load report + snapshot cache
# ---------------------------------------------------------------------------

def test_load_report_shape():
    r = fleet.build_load_report()
    assert r["v"] == fleet.LOAD_REPORT_VERSION
    assert r["drain"] == "serving"
    assert isinstance(r["events"], list)
    assert isinstance(r["trace_roots"], list)
    # seq is per-process monotonic
    assert fleet.build_load_report()["seq"] == r["seq"] + 1


def test_report_cache_one_build_per_interval():
    cache = fleet.report_cache()
    for _ in range(20):
        cache.get()
    assert cache.builds == 1            # the one-build-per-interval pin


def test_probe_response_carries_report_tail():
    from brpc_tpu.kv.transport import (decode_probe_report,
                                       decode_probe_response,
                                       encode_probe_response)
    report = fleet.build_load_report()
    report["instance"] = "10.0.0.1:99"
    data = encode_probe_response(report=report)
    # capability parse is unchanged by the tail
    cap = decode_probe_response(data)
    assert cap is not None and isinstance(cap[2], bool)
    tail = decode_probe_report(data)
    assert tail is not None
    assert tail["instance"] == "10.0.0.1:99"
    assert tail["v"] == fleet.LOAD_REPORT_VERSION
    # a pre-fleet probe (no tail) parses as capabilities-only
    bare = encode_probe_response()
    assert decode_probe_response(bare) is not None
    assert decode_probe_report(bare) is None


# ---------------------------------------------------------------------------
# Registry semantics
# ---------------------------------------------------------------------------

def _mk_report(instance, drain="serving", trace_roots=()):
    r = fleet.build_load_report()
    r["instance"] = instance
    r["drain"] = drain
    r["trace_roots"] = list(trace_roots)
    return r


def test_registry_fresh_stale_draining():
    reg = fleet.FleetRegistry(ttl_s=0.4)
    assert reg.ingest(_mk_report("a:1")) == 0
    assert reg.ingest(_mk_report("b:2")) == 0
    states = {m["instance"]: m["state"] for m in reg.members()}
    assert states == {"a:1": "ok", "b:2": "ok"}
    time.sleep(0.5)
    # TTL'd out: LOUDLY stale, never dropped, one event per transition
    states = {m["instance"]: m["state"] for m in reg.members()}
    assert states == {"a:1": "stale", "b:2": "stale"}
    reg.members()
    assert fleet.event_counters()["fleet_member_stale"] == 2
    # a fresh report revives; an explicit deregister flips to draining
    assert reg.ingest(_mk_report("a:1")) == 0
    assert reg.deregister("b:2") == 0
    states = {m["instance"]: m["state"] for m in reg.members()}
    assert states == {"a:1": "ok", "b:2": "draining"}
    # re-registration after a restart clears the deregister
    assert reg.ingest(_mk_report("b:2")) == 0
    assert {m["instance"]: m["state"]
            for m in reg.members()}["b:2"] == "ok"


def test_registry_rejects_unaddressable():
    reg = fleet.FleetRegistry()
    assert reg.ingest({"v": 1}) == -1           # no instance
    assert reg.ingest({"instance": "a:1"}) == -1  # no version
    assert reg.ingest("junk") == -1


def test_registry_seed_from_file(tmp_path):
    p = tmp_path / "fleet.naming"
    p.write_text("10.0.0.1:80\n# comment\n10.0.0.2:80 extra\n\n")
    reg = fleet.FleetRegistry()
    assert reg.seed_from_url(f"file://{p}") == 2
    states = {m["instance"]: m["state"] for m in reg.members()}
    assert states == {"10.0.0.1:80": "seeded", "10.0.0.2:80": "seeded"}
    # a seeded member's first report promotes it
    assert reg.ingest(_mk_report("10.0.0.1:80")) == 0
    assert {m["instance"]: m["state"]
            for m in reg.members()}["10.0.0.1:80"] == "ok"


def test_registry_trace_index():
    reg = fleet.FleetRegistry()
    reg.ingest(_mk_report("a:1", trace_roots=("dead0", "beef1")))
    reg.ingest(_mk_report("b:2", trace_roots=("beef1",)))
    assert reg.trace_owners("dead0") == ["a:1"]
    assert reg.trace_owners("beef1") == ["a:1", "b:2"]
    assert reg.trace_owners("cafe2") == []
    idx = reg.trace_index()
    assert idx["dead0"] == ["a:1"]


def test_registry_timeline_merges_member_events():
    fleet.record_event("fleet_restart", "registry-local")
    reg = fleet.FleetRegistry()
    rep = _mk_report("a:1")
    rep["events"] = [{"seq": 1, "wall_s": time.time(),
                      "event": "fleet_drain", "detail": "member-side"}]
    reg.ingest(rep)
    rows = reg.timeline()
    insts = {r["instance"] for r in rows}
    assert "a:1" in insts and "(registry)" in insts
    evs = {r["event"] for r in rows}
    assert "fleet_drain" in evs and "fleet_restart" in evs


def test_rollups_and_outliers():
    reg = fleet.FleetRegistry()
    for i, busy in enumerate((0.9, 0.2, 0.5)):
        rep = _mk_report(f"n:{i}")
        rep["busy_ratio"] = busy
        rep["slo"] = {"interactive": {"slo_ok": 8, "slo_ttft_miss": 2}}
        rep["slots"] = {"live": 3, "total": 8}
        reg.ingest(rep)
    roll = reg.rollups()
    assert roll["slo"]["interactive"]["slo_ok"] == 24
    assert roll["slots"] == {"live": 9, "total": 24}
    assert roll["top_busy"][0]["instance"] == "n:0"
    assert roll["top_slo_miss"][0]["miss_ratio"] == pytest.approx(0.2)


def test_federation_injects_instance_label():
    reg = fleet.FleetRegistry()
    reg.ingest(_mk_report("a:1"))
    reg.ingest(_mk_report("b:2"))

    def fake_fetch(instance, timeout_s=1.0):
        return ('# TYPE x_total counter\nx_total 5\n'
                'y{lane="shm"} 2\n')

    body = reg.federate(fetch=fake_fetch)
    assert 'x_total{instance="a:1"} 5' in body
    assert 'y{instance="b:2",lane="shm"} 2' in body
    assert 'fleet_members{state="ok"} 2' in body
    # valid exposition: every sample line is `name{labels} value`
    for line in body.splitlines():
        if not line or line.startswith("#"):
            continue
        series, _, value = line.rpartition(" ")
        assert series and value, line
        float(value)
    # one scrape sweep per interval (cached)
    reg.federate(fetch=fake_fetch)
    assert reg.fed_builds == 1


# ---------------------------------------------------------------------------
# In-process end-to-end: registry server + member server
# ---------------------------------------------------------------------------

class Echo(Service):
    def Echo(self, cntl, request):
        return request


def _http_get(addr, path):
    host, _, port = addr.rpartition(":")
    conn = http.client.HTTPConnection(host, int(port), timeout=5.0)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        return resp.status, resp.read().decode("utf-8", "replace")
    finally:
        conn.close()


def _wait(pred, timeout=10.0, step=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(step)
    return False


def test_fleet_end_to_end_two_servers():
    reg_srv = Server()
    reg_srv.add_service(Echo(), name="E")
    reg = fleet.host_registry(reg_srv, ttl_s=3.0)
    assert reg_srv.start("127.0.0.1:0") == 0
    mem_srv = Server()
    mem_srv.add_service(Echo(), name="E")
    assert mem_srv.start("127.0.0.1:0") == 0
    reg_addr = str(reg_srv.listen_endpoint)
    mem_addr = str(mem_srv.listen_endpoint)
    try:
        fleet.attach_reporter(mem_srv, reg_addr, interval_s=0.2)
        # registration → visible on /fleet
        assert _wait(lambda: any(
            m["instance"] == mem_addr and m["state"] == "ok"
            for m in reg.members()))
        st, body = _http_get(reg_addr, "/fleet?format=json")
        assert st == 200
        doc = json.loads(body)
        assert doc["registry"] is True
        row = next(m for m in doc["members"]
                   if m["instance"] == mem_addr)
        assert row["state"] == "ok"
        assert row["report"]["v"] == fleet.LOAD_REPORT_VERSION
        # pull-on-demand: the member's own /fleet?self=1
        st, body = _http_get(mem_addr, "/fleet?self=1")
        assert st == 200
        assert json.loads(body)["instance"] == mem_addr
        # a plain member hosts no registry
        st, _ = _http_get(mem_addr, "/metrics?fleet=1")
        assert st == 404
        # federation on the registry host: per-instance labels
        st, fed = _http_get(reg_addr, "/metrics?fleet=1")
        assert st == 200
        assert f'instance="{mem_addr}"' in fed
        assert 'fleet_members{state="ok"} 1' in fed
        # drain: the member flips to draining within ~one interval,
        # not the TTL, and the drain events hit the flight recorder
        assert mem_srv.drain(grace_ms=1000) in (0, -1)
        assert _wait(lambda: next(
            m["state"] for m in reg.members()
            if m["instance"] == mem_addr) == "draining", timeout=2.0)
        counts = fleet.event_counters()
        assert counts["fleet_drain"] >= 1
        assert counts["fleet_deregister"] >= 1
    finally:
        mem_srv.stop()
        reg_srv.stop()


def test_fleet_vars_exposed():
    from brpc_tpu.bvar.variable import find_exposed
    fleet.expose_fleet_variables()
    assert find_exposed("fleet_events_total") is not None
    assert find_exposed("fleet_members") is not None
    assert find_exposed("fleet_report_builds") is not None


def test_stitch_seed_remotes():
    from brpc_tpu.rpcz_stitch import collect_trace
    fetched = []

    def fake_fetch(remote, trace_id, timeout_s=2.0, limit=512):
        fetched.append(remote)
        return [{"span_id": 42, "trace_id": f"{trace_id:x}",
                 "parent_span_id": 0, "side": "server",
                 "received_us": 1}]

    out = collect_trace(0xF1EE7, fetch=fake_fetch,
                        seed_remotes=("10.9.9.9:1",))
    assert fetched == ["10.9.9.9:1"]
    assert any(s["span_id"] == 42 for s in out["spans"])
    assert out["remotes"]["10.9.9.9:1"] == "ok"


# ---------------------------------------------------------------------------
# 3-process soak: register / kill -9 → stale / drain → draining,
# trace-index lookup across processes, federation over live members
# ---------------------------------------------------------------------------

_CHILD = r"""
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
sys.path.insert(0, %(repo)r)
from brpc_tpu.server import Server, Service
from brpc_tpu import fleet
from brpc_tpu.client import Channel, Controller

class E(Service):
    def Echo(self, cntl, request):
        return request

srv = Server()
srv.add_service(E(), name="E")
assert srv.start("127.0.0.1:0") == 0
inst = str(srv.listen_endpoint)
# one traced self-call so THIS process holds a trace ROOT the load
# report can index
trace_id = %(trace_id)d
ch = Channel()
ch.init(inst)
cntl = Controller()
cntl.timeout_ms = 5000
cntl.trace_id = trace_id
c = ch.call_method("E.Echo", b"traced", cntl=cntl)
assert not c.failed, c.error_text
fleet.attach_reporter(srv, %(registry)r, interval_s=0.25)
print("PORT=%%d" %% srv.listen_endpoint.port, flush=True)
for line in sys.stdin:
    if line.strip() == "drain":
        srv.drain(grace_ms=1000)
        print("DRAINED", flush=True)
srv.stop()
"""


def _spawn_child(registry_addr, trace_id):
    proc = subprocess.Popen(
        [sys.executable, "-c",
         _CHILD % {"repo": REPO, "registry": registry_addr,
                   "trace_id": trace_id}],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL, text=True)
    port = [None]

    def _read():
        for line in proc.stdout:
            if line.startswith("PORT="):
                port[0] = int(line.strip().split("=", 1)[1])
                return

    reader = threading.Thread(target=_read, daemon=True)
    reader.start()
    reader.join(timeout=180)
    if port[0] is None:
        proc.kill()
        raise RuntimeError("fleet child did not report a port")
    return proc, f"127.0.0.1:{port[0]}"


def test_three_process_fleet_soak():
    reg_srv = Server()
    reg = fleet.host_registry(reg_srv, ttl_s=2.0)
    assert reg_srv.start("127.0.0.1:0") == 0
    reg_addr = str(reg_srv.listen_endpoint)
    t1, t2 = 0xF1EE70001, 0xF1EE70002
    p1 = p2 = None
    try:
        p1, a1 = _spawn_child(reg_addr, t1)
        p2, a2 = _spawn_child(reg_addr, t2)

        def _states():
            return {m["instance"]: m["state"] for m in reg.members()}

        # both register with fresh reports
        assert _wait(lambda: _states().get(a1) == "ok"
                     and _states().get(a2) == "ok", timeout=30.0), \
            _states()
        # fresh report content is visible on /fleet
        st, body = _http_get(reg_addr, "/fleet?format=json")
        assert st == 200
        doc = json.loads(body)
        rows = {m["instance"]: m for m in doc["members"]}
        assert rows[a1]["report"]["drain"] == "serving"
        assert rows[a1]["age_s"] < 2.0

        # trace-index lookup finds the root-holding process
        st, body = _http_get(reg_addr, f"/fleet?trace_id={t1:x}")
        assert st == 200
        assert json.loads(body)["owners"] == [a1]
        from brpc_tpu.rpcz_stitch import locate_trace_root
        assert locate_trace_root(reg_addr, t2) == [a2]

        # federation is valid exposition with per-instance labels
        st, fed = _http_get(reg_addr, "/metrics?fleet=1")
        assert st == 200
        assert f'instance="{a1}"' in fed and f'instance="{a2}"' in fed
        for line in fed.splitlines():
            if not line or line.startswith("#"):
                continue
            series, _, value = line.rpartition(" ")
            float(value)
            assert "{" not in value and series

        # kill -9 one member → stale within TTL (never dropped)
        p1.kill()
        p1.wait(timeout=10)
        assert _wait(lambda: _states().get(a1) == "stale",
                     timeout=8.0), _states()
        assert _states().get(a2) == "ok"

        # drained member → draining within ~one report interval
        p2.stdin.write("drain\n")
        p2.stdin.flush()
        assert _wait(lambda: _states().get(a2) == "draining",
                     timeout=5.0), _states()
    finally:
        for p in (p1, p2):
            if p is not None:
                try:
                    p.stdin.close()
                except Exception:
                    pass
                try:
                    p.wait(timeout=10)
                except Exception:
                    p.kill()
        reg_srv.stop()
