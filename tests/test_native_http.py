"""HTTP/1.x served by the NATIVE engine port.

The engine cuts complete HTTP messages in C++ (request line + headers +
body — Content-Length or chunked; `engine.cpp http_cut`) and hands each
whole message to Python (EV_HTTP), where protocol/http.py parses it and
the normal server dispatch routes it — RPC bridge, restful, builtin
portal.  This is the reference's one-C++-ingestion-loop-for-every-
protocol shape (input_messenger.cpp:329) on the native port; stdlib
http.client is the interop peer."""

import http.client
import json

import pytest

from brpc_tpu.client import Channel
from brpc_tpu.server import Server, ServerOptions, Service
from brpc_tpu.server.service import raw_method


class Calc(Service):
    def Add(self, cntl, request):
        data = json.loads(request or b"{}")
        return {"sum": int(data.get("a", 0)) + int(data.get("b", 0))}

    def Echo(self, cntl, request):
        return request

    @raw_method(native="echo")
    def EchoRaw(self, payload, attachment):
        return payload, attachment


@pytest.fixture(scope="module")
def server():
    opts = ServerOptions()
    opts.native = True
    opts.native_loops = 1
    opts.usercode_inline = True
    srv = Server(opts)
    srv.add_service(Calc(), name="Calc")
    assert srv.start("127.0.0.1:0") == 0
    yield srv
    srv.stop()


def _conn(server):
    ep = server.listen_endpoint
    return http.client.HTTPConnection(ep.host, ep.port, timeout=10)


def test_builtin_portal_on_native_port(server):
    c = _conn(server)
    c.request("GET", "/")
    r = c.getresponse()
    body = r.read()
    assert r.status == 200 and b"/Calc/Add" in body
    c.close()


def test_rpc_bridge_keep_alive(server):
    c = _conn(server)
    c.request("POST", "/Calc/Add", body=json.dumps({"a": 20, "b": 22}),
              headers={"Content-Type": "application/json"})
    r = c.getresponse()
    assert r.status == 200 and json.loads(r.read()) == {"sum": 42}
    # keep-alive: SAME connection serves the next request
    c.request("POST", "/Calc/Echo", body=b"raw-bytes")
    r = c.getresponse()
    assert r.status == 200 and r.read() == b"raw-bytes"
    c.close()


def test_chunked_request_body(server):
    c = _conn(server)
    c.putrequest("POST", "/Calc/Echo")
    c.putheader("Transfer-Encoding", "chunked")
    c.endheaders()
    for chunk in (b"hello ", b"chunked ", b"world"):
        c.send(("%x\r\n" % len(chunk)).encode() + chunk + b"\r\n")
    c.send(b"0\r\n\r\n")
    r = c.getresponse()
    assert r.status == 200 and r.read() == b"hello chunked world"
    c.close()


def test_large_body_direct_read(server):
    # > half the engine inbuf: exercises the direct-into-buffer path
    big = bytes(range(256)) * 1200            # 307200 bytes
    c = _conn(server)
    c.request("POST", "/Calc/Echo", body=big)
    r = c.getresponse()
    assert r.status == 200 and r.read() == big
    # connection still healthy afterwards
    c.request("GET", "/Calc/Add?a=1&b=2")
    r = c.getresponse()
    assert r.status == 200 and json.loads(r.read()) == {"sum": 3}
    c.close()


def test_404_and_get_query(server):
    c = _conn(server)
    c.request("GET", "/no/such/route/here")
    r = c.getresponse()
    assert r.status == 404
    r.read()
    c.request("GET", "/Calc/Add?a=5&b=6")
    r = c.getresponse()
    assert r.status == 200 and json.loads(r.read()) == {"sum": 11}
    c.close()


def test_pipelined_requests_one_write(server):
    """Two requests in one TCP segment: the cut loop must deliver both
    (responses come back in order on the same connection)."""
    import socket as s

    ep = server.listen_endpoint
    sk = s.create_connection((ep.host, ep.port), timeout=10)
    req = (b"POST /Calc/Echo HTTP/1.1\r\nHost: x\r\n"
           b"Content-Length: 3\r\n\r\nabc")
    sk.sendall(req + req)
    data = b""
    while data.count(b"\r\n\r\n") < 2:
        part = sk.recv(65536)
        assert part, f"peer closed early; got {data!r}"
        data = data + part
    assert data.count(b"200") >= 2 and data.count(b"abc") == 2
    sk.close()


def test_tpu_std_and_http_share_the_native_port(server):
    ch = Channel()
    ch.init(str(server.listen_endpoint))
    resp, _ = ch.call_raw("Calc.EchoRaw", b"mixed", timeout_ms=5_000)
    assert bytes(resp) == b"mixed"
    c = _conn(server)
    c.request("POST", "/Calc/Echo", body=b"still http")
    r = c.getresponse()
    assert r.status == 200 and r.read() == b"still http"
    c.close()


def test_pipelined_ordered_on_noninline_server():
    """HTTP has no correlation id: pipelined responses MUST come back
    in request order even on a fiber-pool (non-inline) server — the
    bridge processes EV_HTTP on the loop thread for exactly this."""
    import socket as s

    opts = ServerOptions()
    opts.native = True
    opts.native_loops = 1          # usercode_inline stays False
    srv = Server(opts)
    srv.add_service(Calc(), name="Calc")
    assert srv.start("127.0.0.1:0") == 0
    try:
        ep = srv.listen_endpoint
        sk = s.create_connection((ep.host, ep.port), timeout=10)
        reqs = b"".join(
            b"POST /Calc/Echo HTTP/1.1\r\nHost: x\r\n"
            b"Content-Length: 2\r\n\r\n" + b"%02d" % i
            for i in range(10))
        sk.sendall(reqs)
        data = b""
        while data.count(b"\r\n\r\n") < 10:
            part = sk.recv(65536)
            assert part, f"peer closed early; got {data!r}"
            data += part
        bodies = [data[m.end():m.end() + 2] for m in
                  __import__("re").finditer(rb"\r\n\r\n", data)]
        assert bodies == [b"%02d" % i for i in range(10)], bodies
        sk.close()
    finally:
        srv.stop()


def test_oversized_content_length_rejected_from_headers(server):
    """A Content-Length beyond max_body_size must be refused with 413
    BEFORE the body is buffered (no giant NativeBuf, no wasted read)."""
    import socket as s

    ep = server.listen_endpoint
    sk = s.create_connection((ep.host, ep.port), timeout=10)
    sk.sendall(b"POST /Calc/Echo HTTP/1.1\r\nHost: x\r\n"
               b"Content-Length: 104857600\r\n\r\n")   # 100MB, no body
    sk.settimeout(5)
    data = sk.recv(4096)
    assert data.startswith(b"HTTP/1.1 413"), data
    sk.close()


def test_large_chunked_upload_succeeds(server):
    """Chunked bodies are bounded by http_max_body, NOT the 128KB
    engine inbuf (ADVICE r5 #4): an over-inbuf stream accumulates
    through the incremental chunk FSM and is served whole."""
    import socket as s

    ep = server.listen_endpoint
    sk = s.create_connection((ep.host, ep.port), timeout=15)
    sk.sendall(b"POST /Calc/Echo HTTP/1.1\r\nHost: x\r\n"
               b"Transfer-Encoding: chunked\r\n\r\n")
    blob = bytes(range(256)) * 32              # 8KB
    for _ in range(40):                        # 320KB of chunks
        sk.sendall(b"2000\r\n" + blob + b"\r\n")
    sk.sendall(b"0\r\n\r\n")
    sk.settimeout(15)
    buf = b""
    while b"\r\n\r\n" not in buf:
        buf += sk.recv(65536)
    head, _, rest = buf.partition(b"\r\n\r\n")
    assert head.startswith(b"HTTP/1.1 200"), head
    clen = int([ln.split(b":")[1] for ln in head.split(b"\r\n")
                if ln.lower().startswith(b"content-length")][0])
    while len(rest) < clen:
        rest += sk.recv(65536)
    assert rest == blob * 40
    sk.close()


def test_oversized_chunked_stream_gets_413(server):
    """A chunked stream outgrowing http_max_body gets a clean 413, not
    a TCP reset (the bound is the body limit now, not the inbuf)."""
    import socket as s

    eng = server._native_bridge.engine
    eng.set_http_max_body(64 * 1024)
    try:
        ep = server.listen_endpoint
        sk = s.create_connection((ep.host, ep.port), timeout=10)
        sk.sendall(b"POST /Calc/Echo HTTP/1.1\r\nHost: x\r\n"
                   b"Transfer-Encoding: chunked\r\n\r\n")
        blob = bytes(8192)
        got = b""
        sk.settimeout(10)
        try:
            for _ in range(40):                # ~320KB of chunks
                sk.sendall(b"2000\r\n" + blob + b"\r\n")
        except (BrokenPipeError, ConnectionResetError):
            pass                               # server answered early
        try:
            got = sk.recv(4096)
        except (ConnectionResetError, s.timeout):
            got = b""
        assert got.startswith(b"HTTP/1.1 413"), got
        sk.close()
    finally:
        from brpc_tpu.protocol.base import max_body_size
        eng.set_http_max_body(int(max_body_size()))


def test_transfer_encoding_identity_uses_content_length(server):
    """TE present but NOT chunked: Content-Length framing applies
    (matching protocol/http.py's '\"chunked\" in te' check)."""
    import socket as s

    ep = server.listen_endpoint
    sk = s.create_connection((ep.host, ep.port), timeout=10)
    sk.sendall(b"POST /Calc/Echo HTTP/1.1\r\nHost: x\r\n"
               b"Transfer-Encoding: identity\r\n"
               b"Content-Length: 5\r\n\r\nhello")
    sk.settimeout(5)
    data = sk.recv(65536)
    assert data.startswith(b"HTTP/1.1 200") and data.endswith(b"hello")
    sk.close()


def test_http10_connection_close(server):
    import socket as s

    ep = server.listen_endpoint
    sk = s.create_connection((ep.host, ep.port), timeout=10)
    sk.sendall(b"GET /Calc/Add?a=2&b=3 HTTP/1.0\r\n\r\n")
    sk.settimeout(5)
    data = b""
    while True:
        part = sk.recv(65536)
        if not part:
            break
        data += part
    assert data.startswith(b"HTTP/1.1 200") and b'"sum": 5' in data
    assert b"connection: close" in data.lower()
    sk.close()


def test_internal_port_gates_portal_on_native_port():
    """With an internal port configured, builtin pages on the native
    MAIN port must answer 403 (liveness stays public); the RPC bridge
    keeps working."""
    opts = ServerOptions()
    opts.native = True
    opts.native_loops = 1
    opts.usercode_inline = True
    opts.internal_port = 0          # pick a free one
    srv = Server(opts)
    srv.add_service(Calc(), name="Calc")
    assert srv.start("127.0.0.1:0") == 0
    try:
        ep = srv.listen_endpoint
        c = http.client.HTTPConnection(ep.host, ep.port, timeout=10)
        c.request("GET", "/flags")
        r = c.getresponse()
        assert r.status == 403, r.status
        r.read()
        c.request("POST", "/Calc/Add", body=json.dumps({"a": 1, "b": 1}))
        r = c.getresponse()
        assert r.status == 200 and json.loads(r.read()) == {"sum": 2}
        c.close()
        # the internal port serves the page
        iep = srv.internal_endpoint
        ic = http.client.HTTPConnection(iep.host, iep.port, timeout=10)
        ic.request("GET", "/flags")
        r = ic.getresponse()
        assert r.status == 200
        r.read()
        ic.close()
    finally:
        srv.stop()


def test_garbage_still_closes(server):
    import socket as s

    ep = server.listen_endpoint
    sk = s.create_connection((ep.host, ep.port), timeout=10)
    sk.sendall(b"\x00\x01\x02\x03 utter nonsense\r\n\r\n")
    sk.settimeout(5)
    assert sk.recv(4096) == b""               # engine closed the conn
    sk.close()
