"""Fiber runtime tests (≈ reference test/bthread_unittest.cpp,
bthread_id_unittest.cpp, bthread_execution_queue_unittest.cpp,
bthread_butex_unittest.cpp)."""

import threading
import time

import pytest

from brpc_tpu.fiber import (TaskRuntime, spawn, Butex, CountdownEvent,
                            IdPool, ExecutionQueue, TaskIterator, TimerThread)


class TestRuntime:
    def test_spawn_join_result(self):
        h = spawn(lambda a, b: a + b, 2, 3)
        assert h.result(5) == 5
        assert h.done

    def test_exception_propagates(self):
        def boom():
            raise ValueError("x")
        h = spawn(boom)
        h.join(5)
        with pytest.raises(ValueError):
            h.result(1)

    def test_many_tasks(self):
        rt = TaskRuntime(concurrency=4)
        counter = []
        lock = threading.Lock()

        def inc():
            with lock:
                counter.append(1)

        handles = [rt.spawn(inc) for _ in range(200)]
        for h in handles:
            assert h.join(10)
        assert len(counter) == 200

    def test_blocking_tasks_dont_deadlock_pool(self):
        """More blocked tasks than core workers: pool must grow
        (the usercode_in_pthread deadlock-avoidance property)."""
        rt = TaskRuntime(concurrency=2, max_workers=64)
        gate = threading.Event()
        started = CountdownEvent(8)

        def block():
            started.signal()
            gate.wait(10)

        hs = [rt.spawn(block) for _ in range(8)]
        assert started.wait(5), "pool failed to grow past blocked workers"
        gate.set()
        for h in hs:
            assert h.join(5)

    def test_urgent_goes_first(self):
        rt = TaskRuntime(concurrency=1)
        order = []
        gate = threading.Event()
        rt.spawn(lambda: gate.wait(5))
        rt.spawn(lambda: order.append("bg"))
        rt.spawn(lambda: order.append("urgent"), urgent=True)
        gate.set()
        time.sleep(0.3)
        assert order and order[0] == "urgent"


class TestButex:
    def test_wait_returns_immediately_on_changed_value(self):
        b = Butex(5)
        assert b.wait(expected=4) is True  # value != expected: no block

    def test_wake(self):
        b = Butex(0)
        woken = []

        def waiter():
            b.wait(expected=0, timeout=5)
            woken.append(1)

        t = threading.Thread(target=waiter)
        t.start()
        time.sleep(0.05)
        b.add_and_wake(1)
        t.join(5)
        assert woken

    def test_timeout(self):
        b = Butex(0)
        t0 = time.monotonic()
        assert b.wait(expected=0, timeout=0.1) is False
        assert time.monotonic() - t0 < 2

    def test_countdown(self):
        ev = CountdownEvent(3)
        for _ in range(3):
            spawn(ev.signal)
        assert ev.wait(5)
        assert ev.count <= 0


class TestVersionedId:
    def test_create_lock_unlock_destroy(self):
        pool = IdPool()
        cid = pool.create(data={"x": 1})
        ok, data = pool.lock(cid)
        assert ok and data == {"x": 1}
        pool.unlock(cid)
        assert pool.valid(cid)
        ok, _ = pool.lock(cid)
        assert ok
        assert pool.unlock_and_destroy(cid)
        assert not pool.valid(cid)
        ok, _ = pool.lock(cid)
        assert not ok  # stale id

    def test_error_runs_handler_when_unlocked(self):
        pool = IdPool()
        seen = []

        def on_error(cid, data, code, text):
            seen.append((code, text))
            pool.unlock_and_destroy(cid)

        cid = pool.create(data="d", on_error=on_error)
        assert pool.error(cid, 1008, "timeout")
        assert seen == [(1008, "timeout")]
        assert not pool.valid(cid)

    def test_error_queued_while_locked(self):
        pool = IdPool()
        seen = []

        def on_error(cid, data, code, text):
            seen.append(code)
            pool.unlock_and_destroy(cid)

        cid = pool.create(data="d", on_error=on_error)
        ok, _ = pool.lock(cid)
        assert ok
        assert pool.error(cid, 1009)
        assert seen == []          # queued, not run
        pool.unlock(cid)           # delivery happens here
        assert seen == [1009]
        assert not pool.valid(cid)

    def test_ranged_versions_address_same_call(self):
        """Retry attempt k uses id+k; all address the call, all die
        together on destroy (≈ bthread_id_create_ranged)."""
        pool = IdPool()
        cid = pool.create_ranged("call", None, version_range=4)
        for k in range(4):
            assert pool.valid(cid + k)
        ok, data = pool.lock(cid + 2)
        assert ok and data == "call"
        assert pool.unlock_and_destroy(cid + 2)
        for k in range(4):
            assert not pool.valid(cid + k)

    def test_join_wakes_on_destroy(self):
        pool = IdPool()
        cid = pool.create("c")
        done = []

        def joiner():
            pool.join(cid, timeout=10)
            done.append(1)

        t = threading.Thread(target=joiner)
        t.start()
        time.sleep(0.05)
        ok, _ = pool.lock(cid)
        pool.unlock_and_destroy(cid)
        t.join(5)
        assert done

    def test_lock_contention_serializes(self):
        pool = IdPool()
        cid = pool.create([])
        order = []

        def worker(tag):
            ok, data = pool.lock(cid)
            assert ok
            order.append(tag)
            time.sleep(0.01)
            pool.unlock(cid)

        ts = [threading.Thread(target=worker, args=(i,)) for i in range(5)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert sorted(order) == list(range(5))


class TestExecutionQueue:
    def test_batched_consumption(self):
        got = []
        done = threading.Event()

        def executor(it: TaskIterator):
            for item in it:
                got.append(item)
            if len(got) >= 100:
                done.set()

        q = ExecutionQueue(executor)
        for i in range(100):
            q.execute(i)
        assert done.wait(5)
        assert q.join(5)
        assert got == list(range(100))  # MPSC: single consumer, in order

    def test_high_priority_lane(self):
        got = []
        gate = threading.Event()

        def executor(it: TaskIterator):
            gate.wait(5)
            for item in it:
                got.append(item)

        q = ExecutionQueue(executor)
        q.execute("a")            # consumer starts, blocks on gate
        time.sleep(0.05)
        q.execute("b")
        q.execute("hi", high_priority=True)
        gate.set()
        assert q.join(5)
        assert got.index("hi") < got.index("b")

    def test_stop_rejects(self):
        q = ExecutionQueue(lambda it: [x for x in it])
        q.stop()
        assert q.execute(1) is False

    def test_concurrent_producers(self):
        got = []

        def executor(it):
            for item in it:
                got.append(item)

        q = ExecutionQueue(executor)

        def produce(base):
            for i in range(100):
                q.execute(base + i)

        ts = [threading.Thread(target=produce, args=(k * 1000,)) for k in range(4)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert q.join(5)
        assert len(got) == 400 and len(set(got)) == 400


class TestTimerThread:
    def test_schedule_fires(self):
        tt = TimerThread()
        fired = threading.Event()
        tt.schedule(fired.set, delay_s=0.05)
        assert fired.wait(5)
        assert tt.triggered_count >= 1

    def test_unschedule(self):
        tt = TimerThread()
        fired = []
        tid = tt.schedule(lambda: fired.append(1), delay_s=0.2)
        assert tt.unschedule(tid)
        time.sleep(0.4)
        assert not fired
        assert not tt.unschedule(tid)  # already cancelled

    def test_ordering(self):
        tt = TimerThread()
        order = []
        done = threading.Event()
        tt.schedule(lambda: order.append("b"), delay_s=0.15)
        tt.schedule(lambda: (order.append("a"), None), delay_s=0.05)
        tt.schedule(lambda: (order.append("c"), done.set()), delay_s=0.25)
        assert done.wait(5)
        assert order == ["a", "b", "c"]

    def test_nearer_deadline_preempts_sleep(self):
        tt = TimerThread()
        fired = threading.Event()
        tt.schedule(lambda: None, delay_s=30)   # sleeping until far future
        time.sleep(0.05)
        t0 = time.monotonic()
        tt.schedule(fired.set, delay_s=0.05)    # must wake the thread
        assert fired.wait(5)
        assert time.monotonic() - t0 < 5
