"""TransformerLM model family tests: correctness of the single-device
path, sequence-parallel ring attention equivalence on the virtual mesh,
dp×tp sharded training, remat, and loss descent."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from brpc_tpu.models.transformer_lm import (LMConfig, batch_specs,
                                            init_params, make_forward,
                                            make_train_step, param_specs)


def _data(cfg, batch=4, seq=32, seed=1):
    ki, kl = jax.random.split(jax.random.PRNGKey(seed))
    ids = jax.random.randint(ki, (batch, seq), 0, cfg.vocab, jnp.int32)
    labels = jax.random.randint(kl, (batch, seq), 0, cfg.vocab, jnp.int32)
    return ids, labels


def test_forward_shapes_and_determinism():
    cfg = LMConfig(vocab=64, dim=32, heads=4, depth=2, max_seq=32)
    params = init_params(jax.random.PRNGKey(0), cfg)
    ids, _ = _data(cfg)
    fwd = jax.jit(make_forward(cfg))
    logits = fwd(params, ids)
    assert logits.shape == (4, 32, cfg.vocab)
    assert jnp.isfinite(logits).all()
    np.testing.assert_allclose(np.asarray(fwd(params, ids)),
                               np.asarray(logits), rtol=1e-5)


def test_remat_matches_no_remat():
    cfg_r = LMConfig(vocab=32, dim=16, heads=2, depth=2, remat=True)
    cfg_n = LMConfig(vocab=32, dim=16, heads=2, depth=2, remat=False)
    params = init_params(jax.random.PRNGKey(0), cfg_r)
    ids, labels = _data(cfg_r, seq=16)
    s_r = jax.jit(make_train_step(cfg_r))
    s_n = jax.jit(make_train_step(cfg_n))
    _, loss_r = s_r(params, ids, labels)
    _, loss_n = s_n(params, ids, labels)
    np.testing.assert_allclose(float(loss_r), float(loss_n), rtol=1e-5)


def test_loss_descends():
    cfg = LMConfig(vocab=32, dim=32, heads=4, depth=2, lr=0.5)
    params = init_params(jax.random.PRNGKey(0), cfg)
    ids, labels = _data(cfg, seq=16)
    step = jax.jit(make_train_step(cfg))
    first = None
    for _ in range(10):
        params, loss = step(params, ids, labels)
        first = first if first is not None else float(loss)
    assert float(loss) < first * 0.9, (first, float(loss))


def test_ring_attention_forward_matches_dense():
    """Sequence-parallel forward == single-device forward (long-context
    core guarantee)."""
    n = len(jax.devices())
    if n < 4:
        pytest.skip("needs the virtual multi-device mesh")
    mesh = Mesh(np.array(jax.devices()), ("sp",))
    cfg = LMConfig(vocab=64, dim=32, heads=4, depth=2, causal=True)
    params = init_params(jax.random.PRNGKey(0), cfg)
    seq = 8 * n
    ids, _ = _data(cfg, batch=2, seq=seq)
    dense = jax.jit(make_forward(cfg))(params, ids)
    sharded_fwd = make_forward(cfg, mesh=mesh, sp_axis="sp")
    ids_sp = jax.device_put(ids, NamedSharding(mesh, P(None, "sp")))
    ring = sharded_fwd(params, ids_sp)
    # bf16 matmuls accumulate in different orders across the ring
    np.testing.assert_allclose(np.asarray(ring), np.asarray(dense),
                               rtol=3e-2, atol=8e-3)


def test_scan_layers_matches_unrolled():
    """lax.scan over stacked layer weights == the unrolled stack, for
    identical weights (compile-time-O(1)-in-depth deep-model form)."""
    cfg_u = LMConfig(vocab=32, dim=16, heads=2, depth=3, remat=False)
    cfg_s = LMConfig(vocab=32, dim=16, heads=2, depth=3, remat=False,
                     scan_layers=True)
    pu = init_params(jax.random.PRNGKey(0), cfg_u)
    ps = init_params(jax.random.PRNGKey(0), cfg_s)   # same rng stream
    ids, _ = _data(cfg_u, seq=16)
    want = jax.jit(make_forward(cfg_u))(pu, ids)
    got = jax.jit(make_forward(cfg_s))(ps, ids)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-6)


def test_scan_layers_trains_sharded():
    n = len(jax.devices())
    if n < 4:
        pytest.skip("needs the virtual multi-device mesh")
    tp = 2 if n % 2 == 0 else 1
    dp = n // tp
    mesh = Mesh(np.array(jax.devices()[:dp * tp]).reshape(dp, tp),
                ("dp", "tp"))
    cfg = LMConfig(vocab=64, dim=32, heads=4, depth=3, scan_layers=True)
    params = init_params(jax.random.PRNGKey(0), cfg)
    params = jax.tree_util.tree_map(
        lambda p, s: jax.device_put(p, NamedSharding(mesh, s)),
        params, param_specs(cfg))
    ids, labels = _data(cfg, batch=2 * dp, seq=16)
    ids_spec, lbl_spec = batch_specs()
    ids = jax.device_put(ids, NamedSharding(mesh, ids_spec))
    labels = jax.device_put(labels, NamedSharding(mesh, lbl_spec))
    step = jax.jit(make_train_step(cfg))
    with mesh:
        params, loss = step(params, ids, labels)
        params, loss2 = step(params, ids, labels)
        jax.block_until_ready(loss2)
    assert jnp.isfinite(loss2) and float(loss2) < float(loss)


def test_moe_lm_loss_descends():
    """The MoE variant (sparse FFN, models/moe.py) trains end to end."""
    cfg = LMConfig(vocab=32, dim=32, heads=4, depth=2, lr=0.5,
                   moe_experts=4)
    params = init_params(jax.random.PRNGKey(0), cfg)
    ids, labels = _data(cfg, seq=16)
    step = jax.jit(make_train_step(cfg))
    first = None
    for _ in range(15):
        params, loss = step(params, ids, labels)
        first = first if first is not None else float(loss)
    assert float(loss) < first * 0.9, (first, float(loss))


def test_moe_lm_ep_sharded_step():
    """Experts shard over the tp axis (expert parallelism) and a full
    train step runs on the virtual mesh."""
    n = len(jax.devices())
    if n < 4:
        pytest.skip("needs the virtual multi-device mesh")
    tp = 2 if n % 2 == 0 else 1
    dp = n // tp
    mesh = Mesh(np.array(jax.devices()[:dp * tp]).reshape(dp, tp),
                ("dp", "tp"))
    cfg = LMConfig(vocab=64, dim=32, heads=4, depth=1,
                   moe_experts=2 * tp)
    params = init_params(jax.random.PRNGKey(0), cfg)
    params = jax.tree_util.tree_map(
        lambda p, s: jax.device_put(p, NamedSharding(mesh, s)),
        params, param_specs(cfg))
    ids, labels = _data(cfg, batch=2 * dp, seq=16)
    ids_spec, lbl_spec = batch_specs()
    ids = jax.device_put(ids, NamedSharding(mesh, ids_spec))
    labels = jax.device_put(labels, NamedSharding(mesh, lbl_spec))
    step = jax.jit(make_train_step(cfg))
    with mesh:
        new_params, loss = step(params, ids, labels)
        jax.block_until_ready(loss)
    assert jnp.isfinite(loss)
    assert len(new_params["blk0"]["moe"]["w1"].sharding.device_set) >= tp


def test_all_features_compose():
    """MoE FFN + flash attention + scanned layers + remat in ONE config
    trains and stays finite — the options are orthogonal."""
    cfg = LMConfig(vocab=32, dim=32, heads=4, depth=2, lr=0.1,
                   moe_experts=2, use_flash=True, scan_layers=True,
                   remat=True)
    params = init_params(jax.random.PRNGKey(0), cfg)
    ids, labels = _data(cfg, batch=2, seq=16)
    step = jax.jit(make_train_step(cfg))
    first = None
    for _ in range(8):
        params, loss = step(params, ids, labels)
        first = first if first is not None else float(loss)
    assert jnp.isfinite(loss)
    assert float(loss) < first, (first, float(loss))


def test_dp_tp_sharded_training():
    n = len(jax.devices())
    if n < 4:
        pytest.skip("needs the virtual multi-device mesh")
    tp = 2 if n % 2 == 0 else 1
    dp = n // tp
    mesh = Mesh(np.array(jax.devices()[:dp * tp]).reshape(dp, tp),
                ("dp", "tp"))
    cfg = LMConfig(vocab=64, dim=32, heads=4, depth=2)
    params = init_params(jax.random.PRNGKey(0), cfg)
    specs = param_specs(cfg)

    def put(tree, spec):
        return jax.tree_util.tree_map(
            lambda p, s: jax.device_put(p, NamedSharding(mesh, s)),
            tree, spec)

    params = put(params, specs)
    ids, labels = _data(cfg, batch=2 * dp, seq=16)
    ids_spec, lbl_spec = batch_specs()
    ids = jax.device_put(ids, NamedSharding(mesh, ids_spec))
    labels = jax.device_put(labels, NamedSharding(mesh, lbl_spec))
    step = jax.jit(make_train_step(cfg))
    with mesh:
        new_params, loss = step(params, ids, labels)
        jax.block_until_ready(loss)
    assert jnp.isfinite(loss)
    # tp sharding survived the update
    wqkv = new_params["blk0"]["wqkv"]
    assert len(wqkv.sharding.device_set) >= tp


def test_train_step_grad_accumulation_matches_full_batch():
    """accum=K over K microbatches must produce the same update as one
    full-batch step (same total tokens, mean-of-means loss)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from brpc_tpu.models.transformer_lm import (LMConfig, init_params,
                                                make_train_step)
    cfg = LMConfig(vocab=64, dim=32, heads=2, depth=2, max_seq=16,
                   mlp_mult=2, remat=False, attn_impl="dense")
    params = init_params(jax.random.PRNGKey(0), cfg)
    ids = jax.random.randint(jax.random.PRNGKey(1), (8, 16), 0,
                             cfg.vocab, jnp.int32)
    labels = jnp.roll(ids, -1, axis=-1)
    full = jax.jit(make_train_step(cfg))
    acc = jax.jit(make_train_step(cfg, accum=4))
    p1, l1 = full(params, ids, labels)
    p2, l2 = acc(params, ids, labels)
    np.testing.assert_allclose(float(l1), float(l2), rtol=1e-5)
    for a, b in zip(jax.tree_util.tree_leaves(p1),
                    jax.tree_util.tree_leaves(p2)):
        # f32 summation-order noise only (measured ~2e-5 worst leaf)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-4, rtol=1e-3)


def test_decode_loop_matches_stepwise_greedy():
    """make_decode_loop's one-program scan must generate the same
    greedy tokens as calling decode_step token by token."""
    import functools as ft

    import jax
    import jax.numpy as jnp
    import numpy as np

    from brpc_tpu.models.transformer_lm import (LMConfig, init_params,
                                                make_decode,
                                                make_decode_loop)
    cfg = LMConfig(vocab=64, dim=32, heads=2, depth=2, max_seq=32,
                   mlp_mult=2, remat=False, attn_impl="dense")
    params = init_params(jax.random.PRNGKey(0), cfg)
    prefill, decode_step = make_decode(cfg)
    _, loop = make_decode_loop(cfg, steps=6)
    prompt = jax.random.randint(jax.random.PRNGKey(3), (2, 4), 0,
                                cfg.vocab, jnp.int32)
    cache, logits = jax.jit(ft.partial(prefill, params))(prompt)
    tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    # stepwise reference
    c2, t2, toks_ref = dict(cache), tok, []
    step = jax.jit(ft.partial(decode_step, params))
    for _ in range(6):
        c2, lg = step(c2, t2)
        t2 = jnp.argmax(lg, axis=-1).astype(jnp.int32)
        toks_ref.append(np.asarray(t2))

    _, toks = jax.jit(ft.partial(loop, params))(cache, tok)
    np.testing.assert_array_equal(np.asarray(toks), np.stack(toks_ref))
