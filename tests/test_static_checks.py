"""Static-analysis suite: tier-1 green gate + seeded-drift negatives.

The first half runs all four analyzers over the real tree and demands
ZERO findings — the contract/lane/enum/blocking invariants are tier-1
gates from this round on.  The second half is the linter's own test:
each required drift class is seeded into a COPY of the relevant source
(via the suite's override hook) and the responsible analyzer must
catch it — a linter nobody tests is a linter free to rot.
"""

import subprocess
import sys

import pytest

from brpc_tpu.tools.check import (ANALYZERS, run_all, check_blocking,
                                  check_contracts, check_enums,
                                  check_lanes, Tree)

ENGINE = "brpc_tpu/native/src/engine.cpp"
META = "brpc_tpu/protocol/meta.py"
HTTP_DISPATCH = "brpc_tpu/server/http_dispatch.py"
FAST_CALL = "brpc_tpu/client/fast_call.py"
CLIENT_LANE = "brpc_tpu/transport/client_lane.py"
SLIM = "brpc_tpu/server/slim_dispatch.py"


def _mutate(rel: str, old: str, new: str) -> dict:
    """Override dict with one seeded edit; asserts the anchor exists
    (a moved anchor must fail the negative test loudly, not skip it)."""
    text = Tree().text(rel)
    assert old in text, f"mutation anchor vanished from {rel}: {old!r}"
    return {rel: text.replace(old, new)}


# -- green gate --------------------------------------------------------------

def test_tree_is_clean():
    findings = run_all()
    assert findings == [], "\n".join(repr(f) for f in findings)


@pytest.mark.parametrize("name,fn", ANALYZERS, ids=[n for n, _ in ANALYZERS])
def test_each_analyzer_clean(name, fn):
    findings = fn(Tree())
    assert findings == [], "\n".join(repr(f) for f in findings)


def test_cli_exit_codes():
    r = subprocess.run([sys.executable, "-m", "brpc_tpu.tools.check",
                        "--quiet"], capture_output=True, text=True,
                       timeout=120)
    assert r.returncode == 0, r.stdout + r.stderr
    r = subprocess.run([sys.executable, "-m", "brpc_tpu.tools.check",
                        "-a", "contracts", "--fail-fast"],
                       capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stdout + r.stderr


# -- seeded drifts: the five required classes --------------------------------

def test_drift_enum_member_removed():
    """Deleting a FbReason member breaks BOTH the name-table count and
    every surviving call site that still bumps the counter."""
    ov = _mutate(
        ENGINE,
        "FB_RPC_SHM_LANE,           // frame carries shm data-plane TLVs",
        "// member removed by seeded-drift test")
    findings = check_contracts(Tree(overrides=ov))
    assert any("kFbNames" in f.message for f in findings), findings
    findings = check_enums(Tree(overrides=ov))
    assert any("FB_RPC_SHM_LANE" in f.message for f in findings), findings


def test_drift_tlv_tag_renumbered():
    """Renumbering a meta.py tag leaves the engine scanning the OLD
    number and the pre-encoded prefix carrying the OLD byte."""
    ov = _mutate(META, "_T_TIMEOUT_MS = 13", "_T_TIMEOUT_MS = 23")
    findings = check_contracts(Tree(overrides=ov))
    assert any("tag 13" in f.message for f in findings), findings
    # the pre-encoded TLV_TIMEOUT prefix still says 0x0d
    assert any("TLV_TIMEOUT" in f.message for f in findings), findings


def test_drift_shim_arity_changed():
    """Dropping one arg from the engine's kind-3 call (the 'grew one
    arg in two separate rounds' class, in reverse)."""
    ov = _mutate(ENGINE, "ten ? ten : Py_None, nullptr);", "nullptr);")
    findings = check_contracts(Tree(overrides=ov))
    assert any("kind-3" in f.message and "9 args" in f.message
               for f in findings), findings


def test_drift_shim_arity_changed_python_side():
    """The same class seeded on the Python side: the shim def grows a
    public parameter the engine never passes."""
    ov = _mutate(SLIM, "trace=None, tmo=None, tenant=None,",
                 "trace=None, tmo=None, tenant=None, extra=None,")
    findings = check_contracts(Tree(overrides=ov))
    assert any("kind-3" in f.message and "takes 11" in f.message
               for f in findings), findings


def test_drift_admission_deleted_from_one_lane():
    """Removing the shared admission call from the classic HTTP lane's
    compiled chain (rename → the stage is simply no longer invoked)."""
    ov = _mutate("brpc_tpu/server/interceptors.py",
                 'rej = _admit_stage(_server, _entry, "http", tenant,',
                 'rej = _noadmit_stage(_server, _entry, "http", tenant,')
    findings = check_lanes(Tree(overrides=ov))
    assert any("[http]" in f.message and "admission" in f.message
               for f in findings), findings


def test_drift_unregistered_fallback_reason():
    """(a) a C++ counter bump under a member the enum never declared;
    (b) a Python screening site inventing a reason no test pins."""
    ov = _mutate(ENGINE, "lp->tel.fallbacks[FB_RPC_DISPATCH_OFF]++;",
                 "lp->tel.fallbacks[FB_TOTALLY_NEW_REASON]++;")
    findings = check_enums(Tree(overrides=ov))
    assert any("FB_TOTALLY_NEW_REASON" in f.message
               for f in findings), findings

    # the seeded name is assembled at runtime: a literal here would
    # itself count as a test pin (the checker scans tests/ as text)
    unpinned = "reason_nobody_" + "anchored"
    ov = _mutate(FAST_CALL, '_scatter_fallback("ineligible_cntl")',
                 f'_scatter_fallback("{unpinned}")')
    findings = check_enums(Tree(overrides=ov))
    assert any(unpinned in f.message for f in findings), findings


# -- further drift classes (beyond the required five) ------------------------

def test_drift_stale_reason_name_table():
    """A renamed kFbNames string with the enum untouched: the bridge
    mirror no longer matches (the 'stale telemetry mirror' suspect)."""
    ov = _mutate(ENGINE, '"rpc_dispatch_off",', '"rpc_dispatch_gone",')
    findings = check_contracts(Tree(overrides=ov))
    assert any("FB_REASON_NAMES" in f.message for f in findings), findings


def test_drift_shed_after_user_code():
    """Deadline shed deleted from the grpc lane → doomed work reaches
    the handler."""
    ov = _mutate("brpc_tpu/protocol/h2_rpc.py",
                 'if _maybe_shed(cntl, "grpc", entry.status.full_name):',
                 'if False and _nothing(cntl):')
    findings = check_lanes(Tree(overrides=ov))
    assert any("[grpc]" in f.message and "shed" in f.message
               for f in findings), findings


def test_drift_private_rejection_shape():
    """A lane serializing rejections around the shared helper."""
    ov = _mutate("brpc_tpu/server/interceptors.py",
                 "status_code, body, extra = _reject(rej)",
                 "status_code, body, extra = 503, b'busy', []")
    findings = check_lanes(Tree(overrides=ov))
    assert any("[http]" in f.message and "shared helper" in f.message
               for f in findings), findings


def test_drift_undeclared_flag():
    ov = _mutate(CLIENT_LANE, 'get_flag("rpc_native_client_lane", True)',
                 'get_flag("rpc_native_client_lane_v2", True)')
    findings = check_enums(Tree(overrides=ov))
    assert any("rpc_native_client_lane_v2" in f.message
               for f in findings), findings


def test_drift_blocking_call_on_loop_thread():
    ov = _mutate(CLIENT_LANE, "idp = global_id_pool()",
                 "idp = global_id_pool(); time.sleep(0.01)")
    # the mutated module must still import time for the AST resolver
    ov[CLIENT_LANE] = ov[CLIENT_LANE].replace(
        "import threading", "import threading\nimport time", 1)
    findings = check_blocking(Tree(overrides=ov))
    assert any("sleep" in f.message for f in findings), findings


def test_drift_untimed_wait_on_loop_thread():
    ov = _mutate(
        CLIENT_LANE,
        "sock = Socket.address(sid) if sid is not None else None",
        "sock = Socket.address(sid) if sid is not None else None\n"
        "        self._drained.wait()")
    findings = check_blocking(Tree(overrides=ov))
    assert any(".wait()" in f.message for f in findings), findings


def test_drift_blocking_call_in_handoff_consumer():
    """ISSUE-11 surface: the per-demux-loop burst entry (the cross-loop
    completion handoff delivery callback) is a pinned loop-thread
    entry — a blocking call seeded into it must be flagged."""
    ov = _mutate(CLIENT_LANE, "self._loop_bursts[_idx] += 1",
                 "self._loop_bursts[_idx] += 1; time.sleep(0.001)")
    ov[CLIENT_LANE] = ov[CLIENT_LANE].replace(
        "import threading", "import threading\nimport time", 1)
    findings = check_blocking(Tree(overrides=ov))
    assert any("sleep" in f.message and "_on_loop_burst" in f.message
               for f in findings), findings


def test_drift_blocking_call_in_shm_sweep():
    """ISSUE-11 surface: the per-loop shm sweep (EV_CLOSE -> dead-conn
    slot reclaim) runs on an engine loop — an untimed wait seeded into
    it must be flagged."""
    SHM = "brpc_tpu/transport/shm_ring.py"
    ov = _mutate(SHM, "    if ring is not None:\n        ring.free_owner(owner)",
                 "    if ring is not None:\n        ring.free_owner(owner)\n"
                 "        threading.Event().wait()")
    findings = check_blocking(Tree(overrides=ov))
    assert any(".wait()" in f.message and "on_socket_closed" in f.message
               for f in findings), findings


def test_drift_sleep_in_drain_path():
    """ISSUE-12 surface: Server.drain is deadline-bounded by contract
    and entry-listed in the blocking pass — a time.sleep seeded into
    it must be flagged."""
    SERVER = "brpc_tpu/server/server.py"
    ov = _mutate(SERVER, "        _fleet.on_server_drain(self)\n"
                 "        if self._acceptor is not None:\n"
                 "            self._acceptor.pause_accept()",
                 "        _fleet.on_server_drain(self)\n"
                 "        _time.sleep(0.5)\n"
                 "        if self._acceptor is not None:\n"
                 "            self._acceptor.pause_accept()")
    ov[SERVER] = ov[SERVER].replace("import time as _time",
                                    "import time\nimport time as _time",
                                    1)
    ov[SERVER] = ov[SERVER].replace("_time.sleep", "time.sleep")
    findings = check_blocking(Tree(overrides=ov))
    assert any("sleep" in f.message and "drain" in f.message
               for f in findings), findings


def test_drift_untimed_wait_in_shm_drain_settle():
    """ISSUE-12 surface: the shm settle wait must stay bounded by the
    drain grace — dropping the timeout must be flagged."""
    SHM = "brpc_tpu/transport/shm_ring.py"
    ov = _mutate(SHM, "        ev.wait(0.005)     # timed: the drain "
                 "path stays deadline-bound",
                 "        ev.wait()")
    findings = check_blocking(Tree(overrides=ov))
    assert any(".wait()" in f.message and "drain_settle" in f.message
               for f in findings), findings


def test_drift_lame_duck_reason_renamed():
    """ISSUE-12 surface: the http_lame_duck fallback reason is part of
    the closed engine↔bridge name-table contract — renaming one side
    must be flagged."""
    ov = _mutate(ENGINE, '"http_chunk_stream",  "http_lame_duck",',
                 '"http_chunk_stream",  "http_lameduck2",')
    findings = check_contracts(Tree(overrides=ov))
    assert any("http_lame" in f.message or "kFbNames" in f.message
               for f in findings), findings


# -- ISSUE-13 kind-5 streaming-lane drift classes ----------------------------

def test_drift_stream_shim_arity_changed():
    """Dropping one arg from the engine's kind-5 stream-shim call (the
    same 'grew one arg on one side' class as the kind-3 negative)."""
    ov = _mutate(ENGINE, "sid, swin, nullptr);", "sid, nullptr);")
    findings = check_contracts(Tree(overrides=ov))
    assert any("kind-5" in f.message and "11 args" in f.message
               for f in findings), findings


def test_drift_stream_reason_table_renamed():
    """Renaming a kStreamFbNames string with the enum untouched: the
    stream_slim mirror no longer matches."""
    ov = _mutate(ENGINE, '"stream_chunk_oversize", "stream_drain",',
                 '"stream_chunk_oversize", "stream_drained2",')
    findings = check_contracts(Tree(overrides=ov))
    assert any("STREAM_FB_NAMES" in f.message for f in findings), findings


def test_drift_admission_deleted_from_chain():
    """Deleting the admission stage from the compiled interceptor
    chain breaks EVERY binding lane at once — the linter must see it
    through the chain half of the kind-5 spec."""
    ov = _mutate("brpc_tpu/server/interceptors.py",
                 "rej = _admit_stage(_server, _entry, _lane, tenant,",
                 "rej = _noadmit_stage(_server, _entry, _lane, tenant,")
    findings = check_lanes(Tree(overrides=ov))
    assert any("[stream_slim]" in f.message and "admission" in f.message
               for f in findings), findings


def test_drift_chain_binding_removed_from_lane():
    """The kind-5 lane body no longer calling the compiled chain —
    the binding is gone even though the chain itself is intact."""
    ov = _mutate("brpc_tpu/server/stream_slim.py",
                 "cntl = _enter(sock, cid, len(payload), att, dom, nonce,",
                 "cntl = _no_chain(sock, cid, len(payload), att, dom, nonce,")
    findings = check_lanes(Tree(overrides=ov))
    assert any("[stream_slim]" in f.message
               and ("chain" in f.message or "enter" in f.message)
               for f in findings), findings


def test_drift_blocking_call_in_chunk_delivery():
    """slim_chunks runs inside the engine's batched GIL entry ON a
    loop thread — a sleep seeded into it must be flagged."""
    ov = _mutate("brpc_tpu/server/stream_slim.py",
                 "            s.on_frame(flags, payload)",
                 "            time.sleep(0.001)\n"
                 "            s.on_frame(flags, payload)")
    findings = check_blocking(Tree(overrides=ov))
    assert any("slim_chunks" in f.message and "sleep" in f.message
               for f in findings), findings


def test_drift_untimed_wait_in_stream_drain():
    """Stream drain settle is deadline-bounded by contract — an
    untimed wait_for seeded into drain_close must be flagged."""
    ov = _mutate("brpc_tpu/streaming.py",
                 "                    timeout=cap)",
                 "                    )")
    findings = check_blocking(Tree(overrides=ov))
    assert any("drain_close" in f.message and "wait_for" in f.message
               for f in findings), findings


# -- ISSUE-15 KV transfer plane drift classes --------------------------------

def test_drift_unregistered_kv_reason():
    """A KV fallback reason added to the closed enum without a test
    pin: the enum checker must demand the anchor (the same discipline
    as the engine name tables — an unasserted reason is free to
    drift)."""
    KV = "brpc_tpu/kv/transport.py"
    # assembled at runtime: a literal here would itself count as a pin
    unpinned = "kv_reason_nobody_" + "anchored"
    ov = _mutate(KV, '"kv_peer_remote",',
                 f'"kv_peer_remote", "{unpinned}",')
    findings = check_enums(Tree(overrides=ov))
    assert any(unpinned in f.message for f in findings), findings


def test_drift_unregistered_evict_reason():
    """A paged-KV eviction reason added to the closed enum without a
    test pin: the allocator's close reasons follow the same discipline
    as the transfer plane's fallback/close enums."""
    KV_PAGES = "brpc_tpu/kv/pages.py"
    # assembled at runtime: a literal here would itself count as a pin
    unpinned = "kv_evict_nobody_" + "anchored"
    ov = _mutate(KV_PAGES, '"kv_pool_exhausted",',
                 f'"kv_pool_exhausted", "{unpinned}",')
    findings = check_enums(Tree(overrides=ov))
    assert any(unpinned in f.message for f in findings), findings


def test_drift_blocking_call_in_kv_sweep():
    """The KV page sweep runs from Socket.release on the owning loop —
    a sleep seeded into it must be flagged."""
    KV_PAGES = "brpc_tpu/kv/pages.py"
    ov = _mutate(KV_PAGES, "    if store is not None:\n"
                 "        n = store.release_owner(owner)",
                 "    if store is not None:\n"
                 "        time.sleep(0.01)\n"
                 "        n = store.release_owner(owner)")
    ov[KV_PAGES] = ov[KV_PAGES].replace(
        "import struct", "import struct\nimport time", 1)
    findings = check_blocking(Tree(overrides=ov))
    assert any("sleep" in f.message and "on_socket_closed" in f.message
               for f in findings), findings


def test_drift_untimed_wait_in_kv_drain_settle():
    """The KV drain settle must stay bounded by the drain grace —
    dropping the timeout must be flagged."""
    KV_PAGES = "brpc_tpu/kv/pages.py"
    ov = _mutate(KV_PAGES,
                 "        ev.wait(0.005)     # timed: the drain path "
                 "stays deadline-bound",
                 "        ev.wait()")
    findings = check_blocking(Tree(overrides=ov))
    assert any(".wait()" in f.message and "drain_settle" in f.message
               for f in findings), findings


def test_drift_admission_deleted_from_slim_chain_binding():
    """The kind-3 lane body no longer calling the compiled chain — the
    second binding is gone even though the chain itself is intact
    (mirrors the kind-5 negative)."""
    ov = _mutate("brpc_tpu/server/slim_dispatch.py",
                 "cntl = _enter(sock, cid, len(payload), att, dom, "
                 "nonce,",
                 "cntl = _no_chain(sock, cid, len(payload), att, dom, "
                 "nonce,")
    findings = check_lanes(Tree(overrides=ov))
    assert any("[slim]" in f.message
               and ("chain" in f.message or "enter" in f.message)
               for f in findings), findings


def test_drift_unregistered_sched_event():
    """A member added to the scheduler's closed enum with NO test pin
    (the name is assembled at runtime so this file itself never
    anchors it) must be flagged by the enum analyzer."""
    LM = "brpc_tpu/models/lm_service.py"
    unpinned = "sched_nobody_" + "anchored"
    ov = _mutate(LM, '"sched_chunk_slice",',
                 f'"sched_chunk_slice", "{unpinned}",')
    findings = check_enums(Tree(overrides=ov))
    assert any(unpinned in f.message for f in findings), findings


def test_drift_blocking_call_in_chunk_round():
    """A blocking primitive seeded into the batcher's chunk-prefill
    round (every live session's next token waits on it) must be
    caught by the step-loop entry points."""
    LM = "brpc_tpu/models/lm_service.py"
    ov = _mutate(LM,
                 "filling.sort(key=lambda s: (s.tier_rank, s.slot))",
                 "import time; time.sleep(0.01); "
                 "filling.sort(key=lambda s: (s.tier_rank, s.slot))")
    findings = check_blocking(Tree(overrides=ov))
    assert any("_chunk_round" in f.message and "sleep" in f.message
               for f in findings), findings


def test_drift_http_slim_chain_binding_dropped():
    """The kind-4 shim no longer calling the compiled chain — the
    fourth binding is gone even though the chain itself is intact."""
    ov = _mutate("brpc_tpu/server/http_slim.py",
                 "cntl, early = _enter(",
                 "cntl, early = _no_chain(")
    findings = check_lanes(Tree(overrides=ov))
    assert any("[http_slim]" in f.message
               and ("chain" in f.message or "enter" in f.message)
               for f in findings), findings


def test_drift_unregistered_slo_verdict():
    """A new SLO verdict grown into the closed enum without a test pin
    anywhere under tests/ (the name is assembled at runtime so this
    file itself never anchors it) — the observability surface would
    silently widen past what anything asserts on."""
    LM_TEL = "brpc_tpu/models/lm_telemetry.py"
    unpinned = "slo_nobody_" + "anchored"
    ov = _mutate(LM_TEL, '"slo_untargeted",',
                 f'"slo_untargeted", "{unpinned}",')
    findings = check_enums(Tree(overrides=ov))
    assert any(unpinned in f.message for f in findings), findings


def test_drift_lock_in_step_loop_profiler():
    """A lock acquisition seeded into the per-sample profiler write
    path (record_phase runs inside every batcher decode round) must be
    caught by the step-loop entry points — the ZERO-locks hot-path
    contract is linter-enforced, not reviewed-by-hope."""
    LM_TEL = "brpc_tpu/models/lm_telemetry.py"
    ov = _mutate(LM_TEL, "    _phase_buckets[idx][b] += 1",
                 "    _obs_lock.acquire()\n"
                 "    _phase_buckets[idx][b] += 1")
    ov[LM_TEL] = ov[LM_TEL].replace(
        '_live = [bool(get_flag("lm_telemetry", True))]',
        "_obs_lock = threading.Lock()\n"
        '_live = [bool(get_flag("lm_telemetry", True))]', 1)
    findings = check_blocking(Tree(overrides=ov))
    assert any("record_phase" in f.message and "acquire" in f.message
               for f in findings), findings


def test_drift_unregistered_fleet_event():
    """A new flight-recorder event grown into the closed FLEET_EVENTS
    enum without a test pin anywhere under tests/ (runtime-assembled
    name so this file never anchors it) — the /fleet postmortem
    timeline would widen past what anything asserts on."""
    FLEET = "brpc_tpu/fleet.py"
    unpinned = "fleet_nobody_" + "anchored"
    ov = _mutate(FLEET, '"fleet_host_spill",',
                 f'"fleet_host_spill", "{unpinned}",')
    findings = check_enums(Tree(overrides=ov))
    assert any(unpinned in f.message for f in findings), findings


def test_drift_sleep_in_fleet_report_builder():
    """A time.sleep grown into build_load_report — the entry-listed
    report builder runs inside the KV.Probe handler (engine loop on a
    native server), where a sleep stalls every pinned connection."""
    FLEET = "brpc_tpu/fleet.py"
    ov = _mutate(FLEET, "    report = {",
                 "    time.sleep(0.01)\n    report = {")
    ov[FLEET] = ov[FLEET].replace(
        "import threading", "import threading\nimport time", 1)
    findings = check_blocking(Tree(overrides=ov))
    assert any("build_load_report" in f.message and "sleep" in f.message
               for f in findings), findings


def test_allow_marker_suppresses():
    """The reviewed-exception escape hatch works (and is line-scoped)."""
    ov = _mutate(
        CLIENT_LANE,
        "sock = Socket.address(sid) if sid is not None else None",
        "sock = Socket.address(sid) if sid is not None else None\n"
        "        self._drained.wait()  # static-check: allow")
    findings = check_blocking(Tree(overrides=ov))
    assert findings == [], findings
