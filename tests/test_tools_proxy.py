"""rpc_view browsing proxy + parallel_http mass fetcher.

The proxy bar (VERDICT r3 #3): an operator's browser must be able to
WALK a remote portal through the proxy — pages come back with their
absolute links re-rooted under the proxy's /<target>/ prefix, exactly
what /root/reference/tools/rpc_view/rpc_view.cpp does with its
html rewriting."""

import urllib.error
import urllib.request

import pytest

from brpc_tpu.server import Server, Service
from brpc_tpu.tools.parallel_http import parallel_fetch
from brpc_tpu.tools.rpc_view import ViewProxy, rewrite_links


class Echo(Service):
    def Hi(self, cntl, request):
        return b"hi"


@pytest.fixture()
def portal_server():
    srv = Server()
    srv.add_service(Echo(), name="E")
    assert srv.start("127.0.0.1:0") == 0
    yield srv
    srv.stop()


def _get(url, timeout=10.0):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.status, r.read()


def test_rewrite_links():
    body = (b'<a href="/vars">v</a> <img src="/static/x.png"> '
            b'<form action="/flags?setvalue"> '
            b'<a href="http://elsewhere/abs">keep</a> '
            b'<a href="//proto-relative">keep</a>')
    out = rewrite_links(body, "10.0.0.5:8080")
    assert b'href="/10.0.0.5:8080/vars"' in out
    assert b'src="/10.0.0.5:8080/static/x.png"' in out
    assert b'action="/10.0.0.5:8080/flags?setvalue"' in out
    assert b'href="http://elsewhere/abs"' in out
    assert b'href="//proto-relative"' in out


def test_proxy_serves_and_rewrites(portal_server):
    target = str(portal_server.listen_endpoint)
    proxy = ViewProxy()
    port = proxy.start()
    try:
        status, body = _get(f"http://127.0.0.1:{port}/{target}/status")
        assert status == 200
        assert b"E" in body          # the service shows on /status
        # links on the html page now route back through the proxy
        if b"href=" in body:
            assert f'href="/{target}/'.encode() in body
        # browsing deeper through a rewritten link works
        status, body = _get(f"http://127.0.0.1:{port}/{target}/vars")
        assert status == 200
        # usage page at /
        status, body = _get(f"http://127.0.0.1:{port}/")
        assert status == 200 and b"rpc_view proxy" in body
        # unreachable upstream reports 502, not a hang/crash
        try:
            status, body = _get(
                f"http://127.0.0.1:{port}/127.0.0.1:1/status")
        except urllib.error.HTTPError as e:
            status = e.code
        assert status == 502
    finally:
        proxy.stop()


def test_parallel_fetch(portal_server):
    target = str(portal_server.listen_endpoint)
    servers = [target, "127.0.0.1:1"]           # one up, one down
    results = parallel_fetch(servers, "/status", concurrency=8,
                             timeout=5.0)
    assert results[target].ok and b"Server" in results[target].body \
        or results[target].status == 200
    assert not results["127.0.0.1:1"].ok
    assert results["127.0.0.1:1"].error
