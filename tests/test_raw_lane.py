"""Raw latency lane — @raw_method on the server, call_raw on the client.

The lane's contract (service.py raw_method docstring): bytes-in/
bytes-out handlers with zero-copy payload/attachment views, dispatched
without a ServerController; stats and admission still apply; requests
needing controller-tier features fall back to the full dispatch with
the same handler shape.  ≈ the reference's echo_c++ handler discipline
(/root/reference/docs/cn/benchmark.md:57).
"""

import pytest

from brpc_tpu.client import Channel, ChannelOptions, Controller
from brpc_tpu.client.channel import RpcError
from brpc_tpu.server import Server, Service
from brpc_tpu.server.service import raw_method


class RawEcho(Service):
    @raw_method
    def Echo(self, payload, attachment):
        return bytes(payload) or b"empty", attachment

    @raw_method
    def NoAtt(self, payload, attachment):
        assert attachment is None
        return b"none"

    @raw_method
    def Boom(self, payload, attachment):
        raise ValueError("kaput")

    def Plain(self, cntl, request):
        return b"plain:" + request


@pytest.fixture(params=["py", "native", "native-inline"])
def raw_server_options(request):
    """Three server shapes: Python transport (adapter path), native
    engine (adapter path on fibers), native + usercode_inline (the slim
    raw dispatch — the latency lane proper)."""
    from brpc_tpu.server import ServerOptions
    if request.param.startswith("native"):
        from conftest import require_native
        require_native()
    opts = ServerOptions()
    opts.native = request.param.startswith("native")
    opts.usercode_inline = request.param == "native-inline"
    return opts


@pytest.fixture()
def server(raw_server_options):
    srv = Server(raw_server_options)
    srv.add_service(RawEcho(), name="R")
    assert srv.start("127.0.0.1:0") == 0
    yield srv
    srv.stop()


def _ch(server):
    ch = Channel()
    ch.init(str(server.listen_endpoint))
    return ch


def test_raw_echo_with_attachment(server):
    ch = _ch(server)
    att = bytes(range(256)) * 8
    resp, ratt = ch.call_raw("R.Echo", b"hello", att, timeout_ms=10_000)
    assert bytes(resp) == b"hello"
    assert bytes(ratt) == att


def test_raw_no_attachment(server):
    ch = _ch(server)
    resp, ratt = ch.call_raw("R.NoAtt", b"x", timeout_ms=10_000)
    assert bytes(resp) == b"none"
    assert len(ratt) == 0


def test_raw_handler_exception_maps_to_rpc_error(server):
    ch = _ch(server)
    with pytest.raises(RpcError) as ei:
        ch.call_raw("R.Boom", b"", timeout_ms=10_000)
    assert "kaput" in str(ei.value)


def test_raw_unknown_method(server):
    ch = _ch(server)
    with pytest.raises(RpcError):
        ch.call_raw("R.Nope", b"", timeout_ms=10_000)


def test_raw_method_via_controller_path(server):
    """A @raw_method stays callable through the regular Controller
    client — the full dispatch adapts to the (payload, attachment)
    handler shape."""
    from brpc_tpu.butil.iobuf import IOBuf
    ch = _ch(server)
    cntl = Controller()
    cntl.timeout_ms = 10_000
    cntl.request_attachment = IOBuf(b"tail")
    c = ch.call_method("R.Echo", b"body", cntl=cntl)
    assert not c.failed, c.error_text
    assert c.response == b"body"
    assert c.response_attachment.to_bytes() == b"tail"


def test_traced_request_falls_back_to_full_path(server):
    """A non-zero trace id must record a span — the slim lane rejects
    it and the full path serves the same handler."""
    ch = _ch(server)
    cntl = Controller()
    cntl.timeout_ms = 10_000
    cntl.trace_id = 0xDEAD
    c = ch.call_method("R.Echo", b"traced", cntl=cntl)
    assert not c.failed, c.error_text
    assert c.response == b"traced"


def test_raw_and_plain_methods_coexist(server):
    ch = _ch(server)
    resp, _ = ch.call_raw("R.Echo", b"a", timeout_ms=10_000)
    assert bytes(resp) == b"a"
    c = ch.call_method("R.Plain", b"b")
    assert not c.failed and c.response == b"plain:b"


def test_raw_batch(server):
    """Pipelined batch over a raw method: per-frame slim dispatch."""
    ch = _ch(server)
    out = ch.call_batch("R.Echo", [b"m%d" % i for i in range(32)])
    assert out == [b"m%d" % i for i in range(32)]


def test_raw_stats_recorded(server):
    """Per-method stats and concurrency accounting survive the slim
    path (the lane keeps observability, unlike a bare socket)."""
    ch = _ch(server)
    for _ in range(5):
        ch.call_raw("R.Echo", b"s", timeout_ms=10_000)
    entry = server.find_method("R", "Echo")
    assert entry.status.latency.count() >= 5
    assert entry.status.inflight == 0


class BadReturn(Service):
    @raw_method
    def NoneBack(self, payload, attachment):
        return None          # forgot the return value

    @raw_method
    def BadTuple(self, payload, attachment):
        return (b"a", b"b", b"c")


def test_raw_malformed_return_releases_admission(raw_server_options):
    """A raw handler returning a malformed value must answer the client
    with EINTERNAL and release BOTH admission slots (server inflight +
    method inflight) — not leak them and strand the caller."""
    srv = Server(raw_server_options)
    srv.add_service(BadReturn(), name="B")
    assert srv.start("127.0.0.1:0") == 0
    try:
        ch = Channel()
        ch.init(str(srv.listen_endpoint))
        for mth in ("B.NoneBack", "B.BadTuple"):
            with pytest.raises(RpcError):
                ch.call_raw(mth, b"", timeout_ms=5_000)
        entry = srv.find_method("B", "NoneBack")
        assert entry.status.inflight == 0
        assert srv._inflight == 0
    finally:
        srv.stop()


def test_call_raw_on_ssl_channel_falls_back(raw_server_options):
    """call_raw on a channel whose options the raw lane cannot serve
    (non-tpu_std protocol here; same screen covers TLS) must route
    through call_method, not write raw frames to the socket."""
    srv = Server(raw_server_options)
    srv.add_service(RawEcho(), name="R")
    assert srv.start("127.0.0.1:0") == 0
    try:
        opts = ChannelOptions()
        opts.protocol = "tpu_std"        # control: raw lane works
        ch = Channel(opts)
        ch.init(str(srv.listen_endpoint))
        r, _ = ch.call_raw("R.Echo", b"ok", timeout_ms=5_000)
        assert bytes(r) == b"ok"
    finally:
        srv.stop()


def test_malformed_attachment_size_rejected(server):
    """An attachment-size TLV exceeding the body is a malformed frame:
    the server must answer EREQUEST, not silently fuse the bytes into
    the handler's payload (ADVICE r3: native_bridge silent clamp)."""
    import socket as _socket
    import struct

    from brpc_tpu.butil.status import Errno
    from brpc_tpu.protocol.meta import (RpcMeta, TLV_ATTACHMENT,
                                        TLV_CORRELATION, encode_tlv)

    ep = server.listen_endpoint
    with _socket.create_connection((str(ep.host), ep.port), timeout=5) as c:
        mb = (TLV_CORRELATION + struct.pack("<Q", 7)
              + TLV_ATTACHMENT + struct.pack("<I", 999)   # body is 5 bytes
              + encode_tlv(4, b"R") + encode_tlv(5, b"Echo"))
        body = b"hello"
        c.sendall(b"TRPC" + struct.pack("<II", len(mb) + len(body),
                                        len(mb)) + mb + body)
        c.settimeout(5)
        buf = b""
        while len(buf) < 12:
            buf += c.recv(4096)
        blen, mlen = struct.unpack_from("<II", buf, 4)
        while len(buf) < 12 + blen:
            buf += c.recv(4096)
        meta = RpcMeta.decode(buf[12:12 + mlen])
        assert meta is not None and meta.correlation_id == 7
        assert meta.error_code == int(Errno.EREQUEST)
    # admission slots were released
    entry = server.find_method("R", "Echo")
    assert entry.status.inflight == 0


def test_thread_death_returns_pinned_socket(server):
    """call_raw pins a pooled connection to the calling thread; when the
    thread exits the pin must dissolve back into the pool instead of
    leaking the checked-out socket (ADVICE r3 medium).  The finalizer
    itself only PARKS the sids (running pool code from GC context could
    deadlock on the pool's non-reentrant lock — ADVICE r4); the actual
    return happens on the next raw call or the 5s periodic drain, which
    this test triggers directly."""
    import gc
    import threading

    from brpc_tpu.transport.socket import Socket

    ch = Channel()
    ch.init(str(server.listen_endpoint))
    seen = {}

    def work():
        r, _ = ch.call_raw("R.Echo", b"hi", timeout_ms=5_000)
        assert bytes(r) == b"hi"
        from brpc_tpu.client.fast_call import _tls_raw
        seen.update(_tls_raw.socks)

    t = threading.Thread(target=work)
    t.start()
    t.join()
    assert seen, "worker thread pinned no socket"
    gc.collect()
    from brpc_tpu.client import fast_call
    fast_call._drain_unpinned()      # what the periodic task does
    assert not fast_call._unpin_pending, "drain left sockets parked"
    (sid,) = seen.values()
    s = Socket.address(sid)
    assert s is not None and not s.failed, "pinned socket was dropped"
    pool = s._pooled_home
    assert pool is not None and sid in pool._free, \
        "dead thread's pinned socket never returned to the pool"
