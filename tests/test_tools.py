"""Ops tools tests: rpc_press load generation, rpc_dump capture,
rpc_replay byte-faithful replay, rpc_view portal fetch
(≈ /root/reference/tools/* capabilities)."""

import time

import pytest

from brpc_tpu.butil.flags import set_flag
from brpc_tpu.server import Server, Service
from brpc_tpu.tools.rpc_dump import DumpReader, close_dump
from brpc_tpu.tools.rpc_press import Press, PressOptions
from brpc_tpu.tools.rpc_replay import Replayer, ReplayOptions
from brpc_tpu.tools.rpc_view import fetch


class Echo(Service):
    def __init__(self):
        super().__init__()
        self.seen = []

    def Echo(self, cntl, request):
        return request

    def Record(self, cntl, request):
        self.seen.append((bytes(request),
                          cntl.request_attachment.to_bytes()))
        return b"ok"


@pytest.fixture()
def server():
    svc = Echo()
    srv = Server()
    srv.add_service(svc, name="E")
    assert srv.start("127.0.0.1:0") == 0
    srv.test_svc = svc
    yield srv
    srv.stop()


def test_press_unlimited(server):
    opts = PressOptions()
    opts.server = str(server.listen_endpoint)
    opts.method = "E.Echo"
    opts.duration_s = 1.0
    opts.input = b"press-payload"
    opts.report_interval_s = 10        # quiet during tests
    s = Press(opts).run()
    assert s["errors"] == 0
    assert s["sent"] > 100
    # percentiles ride 1s sampler windows — may still be empty after a
    # 1s press; just check the field is present and sane
    assert s["latency_us_p50"] >= 0


def test_press_target_qps(server):
    opts = PressOptions()
    opts.server = str(server.listen_endpoint)
    opts.method = "E.Echo"
    opts.qps = 200
    opts.duration_s = 2.0
    opts.report_interval_s = 10
    s = Press(opts).run()
    assert s["errors"] == 0
    # pacing should land within a loose band of the target
    assert 100 <= s["qps"] <= 320, s


def test_press_multi_payload_and_errors(server):
    opts = PressOptions()
    opts.server = str(server.listen_endpoint)
    opts.method = "E.Nope"             # unknown method -> all errors
    opts.duration_s = 0.3
    opts.report_interval_s = 10
    s = Press(opts).run()
    assert s["errors"] == s["sent"] > 0


def test_dump_and_replay(server, tmp_path):
    set_flag("rpc_dump_dir", str(tmp_path))
    set_flag("rpc_dump", True)
    try:
        from brpc_tpu.client import Channel, Controller
        ch = Channel()
        ch.init(str(server.listen_endpoint))
        for i in range(10):
            cntl = Controller()
            cntl.timeout_ms = 2000
            cntl.request_attachment.append(b"att%d" % i)
            c = ch.call_method("E.Record", b"body%d" % i, cntl=cntl)
            assert not c.failed, c.error_text
    finally:
        set_flag("rpc_dump", False)
    path = close_dump()
    assert path is not None

    frames = DumpReader(path).frames()
    assert len(frames) == 10
    for i, (meta, payload) in enumerate(frames):
        assert meta.service_name == "E" and meta.method_name == "Record"
        n = meta.attachment_size
        assert payload[:len(payload) - n] == b"body%d" % i
        assert payload[len(payload) - n:] == b"att%d" % i

    # replay into a second server; it must observe identical traffic
    svc2 = Echo()
    srv2 = Server()
    srv2.add_service(svc2, name="E")
    assert srv2.start("127.0.0.1:0") == 0
    try:
        ropts = ReplayOptions()
        ropts.server = str(srv2.listen_endpoint)
        ropts.dump_files = [path]
        summary = Replayer(ropts).run()
        assert summary["errors"] == 0
        assert summary["sent"] == 10
        assert svc2.seen == server.test_svc.seen
    finally:
        srv2.stop()


def test_replay_loop_and_qps(server, tmp_path):
    set_flag("rpc_dump_dir", str(tmp_path))
    set_flag("rpc_dump", True)
    try:
        from brpc_tpu.client import Channel
        ch = Channel()
        ch.init(str(server.listen_endpoint))
        ch.call("E.Echo", b"once", timeout_ms=2000)
    finally:
        set_flag("rpc_dump", False)
    path = close_dump()
    ropts = ReplayOptions()
    ropts.server = str(server.listen_endpoint)
    ropts.dump_files = [path]
    ropts.loop = 5
    ropts.qps = 50
    t0 = time.monotonic()
    summary = Replayer(ropts).run()
    assert summary["sent"] == 5 and summary["errors"] == 0
    assert time.monotonic() - t0 >= 0.05     # pacing actually slept


def test_rpc_view(server):
    body = fetch(str(server.listen_endpoint), "status")
    assert "E.Echo" in body
    body = fetch(str(server.listen_endpoint), "health")
    assert body == "OK\n"
    with pytest.raises(RuntimeError):
        fetch(str(server.listen_endpoint), "no_such_page")


def test_press_cli(server):
    from brpc_tpu.tools.rpc_press import main
    rc = main(["--server", str(server.listen_endpoint),
               "--method", "E.Echo", "--duration", "0.3", "--qps", "100"])
    assert rc == 0


def test_fleet_dump_cli(capsys):
    """fleet_dump against a live registry host: member table + merged
    event timeline render, --json passthrough parses."""
    from brpc_tpu import fleet
    from brpc_tpu.tools.fleet_dump import main
    fleet._reset_for_tests()
    srv = Server()
    srv.add_service(Echo(), name="E")
    reg = fleet.host_registry(srv, ttl_s=5.0)
    assert srv.start("127.0.0.1:0") == 0
    addr = str(srv.listen_endpoint)
    try:
        rep = fleet.build_load_report(srv)
        rep["instance"] = addr
        assert reg.ingest(rep) == 0
        fleet.record_event("fleet_restart", addr)
        assert main([addr]) == 0
        out = capsys.readouterr().out
        assert addr in out and "ok" in out
        assert "timeline" in out and "fleet_restart" in out
        assert main([addr, "--json"]) == 0
        import json as _json
        doc = _json.loads(capsys.readouterr().out)
        assert doc["registry"] is True
        assert main([addr, "--self"]) == 0
        assert main(["127.0.0.1:1", "--timeout", "0.3"]) == 1
    finally:
        srv.stop()
        fleet._reset_for_tests()
