"""Device-layer tests on the virtual 8-device CPU mesh: mesh transport
collectives, pallas/device ops, the flagship EmbeddingPS model, and the
PS service served over real RPC."""

import json

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from brpc_tpu.parallel.mesh_transport import MeshTransport
from brpc_tpu.ops.device_ops import (bytes_to_tensor, checksum_u32,
                                     embedding_bag, tensor_bytes)
from brpc_tpu.models.embedding_ps import (EmbeddingPS, PSConfig,
                                          batch_specs, init_params,
                                          param_specs, sgd_train_step)


@pytest.fixture(scope="module")
def mesh1d():
    devs = np.array(jax.devices())
    return Mesh(devs, ("ici",))


@pytest.fixture(scope="module")
def transport(mesh1d):
    return MeshTransport(mesh=mesh1d, axis="ici")


def test_mesh_scatter_gather(transport):
    x = np.arange(8 * 4, dtype=np.float32).reshape(8, 4)
    xs = transport.scatter(x, axis=0)
    assert len(xs.sharding.device_set) == 8
    np.testing.assert_array_equal(transport.gather(xs), x)


def test_mesh_ring_shift(transport):
    x = jnp.arange(8.0).reshape(8, 1)
    xs = transport.scatter(x, axis=0)
    out = transport.gather(transport.ring_shift(xs, 1))
    np.testing.assert_allclose(out, np.roll(np.arange(8.0), 1).reshape(8, 1))
    out3 = transport.gather(transport.ring_shift(xs, 3))
    np.testing.assert_allclose(out3,
                               np.roll(np.arange(8.0), 3).reshape(8, 1))


def test_mesh_psum_allgather_reduce_scatter(transport):
    x = np.ones((8, 16), np.float32)
    xs = transport.scatter(x, axis=0)
    total = transport.gather(transport.psum(xs))
    np.testing.assert_allclose(total, np.full((1, 16), 8.0))
    ag = transport.gather(transport.all_gather(xs))
    assert ag.shape == (8, 16)
    rs = transport.gather(transport.reduce_scatter(xs))
    np.testing.assert_allclose(rs, np.full((8, 2), 8.0))


def test_mesh_all_to_all(transport):
    x = np.arange(8 * 8, dtype=np.float32).reshape(8, 8)
    xs = transport.scatter(x, axis=0)
    out = transport.gather(transport.all_to_all(xs, split_axis=1,
                                                concat_axis=0))
    # peer d held row d (1,8); afterwards peer d holds column d (8,1):
    # the global result is the transpose, row-blocked by peer
    assert out.shape == (64, 1)
    np.testing.assert_allclose(out.reshape(8, 8), x.T)


def test_checksum_matches_numpy():
    x = jnp.arange(1000, dtype=jnp.float32)
    got = checksum_u32(x)
    want = int(np.uint32(np.sum(
        np.frombuffer(np.arange(1000, dtype=np.float32).tobytes(),
                      dtype=np.uint32), dtype=np.uint64) & 0xFFFFFFFF))
    assert got == want
    # detects corruption
    y = x.at[500].set(123.0)
    assert checksum_u32(y) != got


def test_embedding_bag():
    table = jnp.arange(20.0).reshape(10, 2)
    ids = jnp.array([[0, 1], [2, 2]], jnp.int32)
    out = np.asarray(embedding_bag(table, ids))
    np.testing.assert_allclose(out, [[1.0, 2.0], [4.0, 5.0]])


def test_tensor_bytes_roundtrip():
    x = np.random.default_rng(0).normal(size=(3, 5)).astype(np.float32)
    data, dtype, shape = tensor_bytes(x)
    back = bytes_to_tensor(data, dtype, shape)
    np.testing.assert_array_equal(back, x)


def test_embedding_ps_learns():
    cfg = PSConfig(vocab=64, dim=16, slots=4, hidden=32, classes=4, lr=0.5)
    model = EmbeddingPS(cfg, seed=0)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab, (64, cfg.slots)).astype(np.int32)
    labels = (ids[:, 0] % cfg.classes).astype(np.int32)
    first = model.train_step(ids, labels)
    for _ in range(150):
        last = model.train_step(ids, labels)
    assert last < first * 0.3, (first, last)


def test_embedding_ps_sharded_train_step():
    devs = np.array(jax.devices()).reshape(4, 2)
    mesh = Mesh(devs, ("dp", "tp"))
    cfg = PSConfig(vocab=128, dim=16, slots=4, hidden=32, classes=4,
                   lr=0.1)
    params = init_params(jax.random.PRNGKey(0), cfg)
    shard = {k: NamedSharding(mesh, s) for k, s in param_specs(cfg).items()}
    params = {k: jax.device_put(v, shard[k]) for k, v in params.items()}
    ids = jnp.zeros((8, cfg.slots), jnp.int32)
    labels = jnp.zeros((8,), jnp.int32)
    ids_spec, lbl_spec = batch_specs()
    ids = jax.device_put(ids, NamedSharding(mesh, ids_spec))
    labels = jax.device_put(labels, NamedSharding(mesh, lbl_spec))
    step = jax.jit(sgd_train_step, static_argnames=("lr",))
    with mesh:
        new_params, loss = step(params, ids, labels, lr=cfg.lr)
    assert jnp.isfinite(loss)
    assert len(new_params["emb"].sharding.device_set) == 8


def test_ps_service_over_rpc():
    from brpc_tpu.client import Channel, Controller
    from brpc_tpu.models.ps_service import PSService, pack_ids
    from brpc_tpu.server import Server

    svc = PSService()
    srv = Server()
    srv.add_service(svc, name="PS")
    assert srv.start("127.0.0.1:0") == 0
    try:
        ch = Channel()
        ch.init(str(srv.listen_endpoint))
        cfg = svc.model.cfg
        ids = np.array([[1, 2, 3], [4, 5, 6]], np.int32)

        cntl = Controller()
        cntl.timeout_ms = 30_000     # first call compiles under jit
        c = ch.call_method("PS.Lookup", pack_ids(ids), cntl=cntl)
        assert not c.failed, c.error_text
        info = json.loads(c.response)
        att = c.response_device_attachment
        assert att is not None
        assert (att.dtype, tuple(att.shape)) == \
            (info["dtype"], tuple(info["shape"]))
        pooled = np.asarray(att.tensor())
        assert pooled.shape == (2, cfg.dim)
        want = np.asarray(svc.model.lookup(ids))
        np.testing.assert_allclose(pooled, want, rtol=1e-6)

        # train via RPC moves the loss
        labels = np.array([1, 2], np.int32)
        cntl = Controller()
        cntl.timeout_ms = 30_000
        cntl.request_attachment.append(labels.tobytes())
        c = ch.call_method("PS.Train", pack_ids(ids), cntl=cntl)
        assert not c.failed, c.error_text
        assert "loss" in json.loads(c.response)

        c = ch.call_method("PS.Stat", b"")
        assert json.loads(c.response)["vocab"] == cfg.vocab
    finally:
        srv.stop()
