"""Sequence + pipeline parallelism tests on the 8-device CPU mesh:
ring attention and Ulysses vs a dense oracle (causal + full), pipeline
schedule vs sequential stage application."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from brpc_tpu.parallel.pipeline import make_pipeline, make_pipeline_train
from brpc_tpu.parallel.ring_attention import (make_ring_attention,
                                              make_ulysses_attention,
                                              reference_attention)


@pytest.fixture(scope="module")
def mesh():
    return Mesh(np.array(jax.devices()), ("sp",))


def _qkv(b=2, s=64, h=8, d=16, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    shape = (b, s, h, d)
    return tuple(jax.random.normal(k, shape, jnp.float32) * 0.5
                 for k in ks)


def _shard_seq(mesh, *arrays):
    sh = NamedSharding(mesh, P(None, "sp", None, None))
    return tuple(jax.device_put(a, sh) for a in arrays)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_dense(mesh, causal):
    q, k, v = _qkv()
    want = reference_attention(q, k, v, causal=causal)
    ring = make_ring_attention(mesh, "sp", causal=causal)
    got = ring(*_shard_seq(mesh, q, k, v))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_attention_matches_dense(mesh, causal):
    q, k, v = _qkv(h=8)                   # heads divisible by 8 devices
    want = reference_attention(q, k, v, causal=causal)
    uly = make_ulysses_attention(mesh, "sp", causal=causal)
    got = uly(*_shard_seq(mesh, q, k, v))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_flash_matches_dense(mesh, causal):
    """Ulysses with the Pallas flash kernel as the local attention —
    the O(s)-memory long-context configuration."""
    q, k, v = _qkv(h=8)
    want = reference_attention(q, k, v, causal=causal)
    uly = make_ulysses_attention(mesh, "sp", causal=causal,
                                 use_flash=True)
    got = uly(*_shard_seq(mesh, q, k, v))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-5)


def test_ring_attention_long_sequence(mesh):
    # sequence larger than any single shard would typically hold
    q, k, v = _qkv(b=1, s=512, h=4, d=8, seed=3)
    want = reference_attention(q, k, v, causal=True)
    ring = make_ring_attention(mesh, "sp", causal=True)
    got = ring(*_shard_seq(mesh, q, k, v))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-5)


def test_pipeline_matches_sequential(mesh):
    pp_mesh = Mesh(np.array(jax.devices()), ("pp",))
    n_stages = 8
    width = 16

    def stage_fn(params, x):
        return jnp.tanh(x @ params["w"] + params["b"])

    ks = jax.random.split(jax.random.PRNGKey(0), 2)
    params = {
        "w": jax.random.normal(ks[0], (n_stages, width, width)) * 0.3,
        "b": jax.random.normal(ks[1], (n_stages, width)) * 0.1,
    }
    n_micro, mb = 6, 4
    xs = jax.random.normal(jax.random.PRNGKey(7), (n_micro, mb, width))

    # oracle: apply stages sequentially to each microbatch
    want = xs
    for i in range(n_stages):
        want = jnp.tanh(want @ params["w"][i] + params["b"][i])

    pipe = make_pipeline(pp_mesh, stage_fn, "pp")
    sharded_params = {
        k: jax.device_put(v, NamedSharding(pp_mesh, P("pp")))
        for k, v in params.items()}
    got = pipe(sharded_params, xs)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-6)


def test_pipeline_train_grads_match_unpipelined(mesh):
    """GPipe training step: loss AND parameter gradients from the
    differentiated conveyor must match the single-program unpipelined
    model (microbatch accumulation included)."""
    pp_mesh = Mesh(np.array(jax.devices()), ("pp",))
    n_stages = 8
    width = 16

    def stage_fn(params, x):
        return jnp.tanh(x @ params["w"] + params["b"])

    def loss_fn(outputs, ys):
        return jnp.mean((outputs - ys) ** 2)

    ks = jax.random.split(jax.random.PRNGKey(1), 2)
    params = {
        "w": jax.random.normal(ks[0], (n_stages, width, width)) * 0.3,
        "b": jax.random.normal(ks[1], (n_stages, width)) * 0.1,
    }
    n_micro, mb = 6, 4
    xs = jax.random.normal(jax.random.PRNGKey(8), (n_micro, mb, width))
    ys = jax.random.normal(jax.random.PRNGKey(9), (n_micro, mb, width))

    # oracle: unpipelined forward + grad in one program
    def ref_loss(p, xs, ys):
        h = xs
        for i in range(n_stages):
            h = jnp.tanh(h @ p["w"][i] + p["b"][i])
        return loss_fn(h, ys)

    want_loss, want_grads = jax.value_and_grad(ref_loss)(params, xs, ys)

    step = make_pipeline_train(pp_mesh, stage_fn, loss_fn, "pp")
    sharded_params = {
        k: jax.device_put(v, NamedSharding(pp_mesh, P("pp")))
        for k, v in params.items()}
    got_loss, got_grads = step(sharded_params, xs, ys)

    np.testing.assert_allclose(np.asarray(got_loss),
                               np.asarray(want_loss),
                               rtol=1e-5, atol=1e-6)
    for k in want_grads:
        np.testing.assert_allclose(np.asarray(got_grads[k]),
                                   np.asarray(want_grads[k]),
                                   rtol=1e-4, atol=1e-6,
                                   err_msg=f"grad mismatch for {k}")


def test_pipeline_train_composes_with_data_parallel(mesh):
    """dp×pp in ONE program: each dp group runs the GPipe conveyor on
    its microbatch share, grads pmean across dp — loss and grads match
    the unpipelined full-batch model."""
    devs = np.array(jax.devices()).reshape(2, 4)
    mesh2 = Mesh(devs, ("dp", "pp"))
    n_stages, width = 4, 16

    def stage_fn(params, x):
        return jnp.tanh(x @ params["w"] + params["b"])

    def loss_fn(outputs, ys):
        return jnp.mean((outputs - ys) ** 2)

    ks = jax.random.split(jax.random.PRNGKey(2), 2)
    params = {
        "w": jax.random.normal(ks[0], (n_stages, width, width)) * 0.3,
        "b": jax.random.normal(ks[1], (n_stages, width)) * 0.1,
    }
    n_micro, mb = 4, 8              # mb splits 2 ways over dp
    xs = jax.random.normal(jax.random.PRNGKey(10),
                           (n_micro, mb, width))
    ys = jax.random.normal(jax.random.PRNGKey(11),
                           (n_micro, mb, width))

    def ref_loss(p, xs, ys):
        h = xs
        for i in range(n_stages):
            h = jnp.tanh(h @ p["w"][i] + p["b"][i])
        return loss_fn(h, ys)

    want_loss, want_grads = jax.value_and_grad(ref_loss)(params, xs, ys)

    step = make_pipeline_train(mesh2, stage_fn, loss_fn, "pp",
                               dp_axis="dp")
    sharded_params = {
        k: jax.device_put(v, NamedSharding(mesh2, P("pp")))
        for k, v in params.items()}
    data_sh = NamedSharding(mesh2, P(None, "dp"))
    got_loss, got_grads = step(
        sharded_params, jax.device_put(xs, data_sh),
        jax.device_put(ys, data_sh))

    np.testing.assert_allclose(np.asarray(got_loss),
                               np.asarray(want_loss),
                               rtol=1e-5, atol=1e-6)
    for k in want_grads:
        np.testing.assert_allclose(np.asarray(got_grads[k]),
                                   np.asarray(want_grads[k]),
                                   rtol=1e-4, atol=1e-6,
                                   err_msg=f"dp×pp grad mismatch {k}")
