"""Slim native server-side dispatch (engine kind 3) — adversarial suite.

Contract under test (server/slim_dispatch.py + engine.cpp kind 3): an
eligible unary (cntl, request) method on a native inline server is
dispatched by the C++ engine straight to the shim in one batched GIL
entry and its response frame is built natively — while staying
BYTE-IDENTICAL with the classic Python dispatch, preserving
MethodStatus accounting, concurrency admission, and rpcz sampling, and
falling back to the classic path for everything the slim frame cannot
express.
"""

import socket as pysock
import struct
import threading
import time

import pytest

from brpc_tpu.butil.flags import get_flag, set_flag
from brpc_tpu.butil.status import Errno
from brpc_tpu.client import Channel, ChannelOptions, Controller
from brpc_tpu.protocol.meta import (RpcMeta, TLV_ATTACHMENT,
                                    TLV_CORRELATION, encode_tlv)
from brpc_tpu.server import Server, ServerOptions, Service

from conftest import require_native  # noqa: E402


class SlimSvc(Service):
    def __init__(self):
        self.calls = []        # thread names, to see where dispatch ran

    def Echo(self, cntl, request):
        self.calls.append(threading.current_thread().name)
        cntl.response_attachment.append_iobuf(cntl.request_attachment)
        return b"ok:" + bytes(request)

    def Boom(self, cntl, request):
        raise ValueError("kapow")

    def SetFail(self, cntl, request):
        cntl.set_failed(Errno.EREQUEST, "refused politely")
        return None

    def Later(self, cntl, request):
        cntl.begin_async()
        data = bytes(request)

        def finisher():
            time.sleep(0.05)
            cntl.finish(b"async:" + data)

        threading.Thread(target=finisher, daemon=True).start()
        return None


def _server(native: bool, **opt_kw):
    opts = ServerOptions()
    if native:
        opts.native = True
        opts.usercode_inline = True
        opts.native_loops = 1
    for k, v in opt_kw.items():
        setattr(opts, k, v)
    svc = SlimSvc()
    srv = Server(opts)
    srv.add_service(svc, name="S")
    assert srv.start("127.0.0.1:0") == 0
    return srv, svc


def _channel(srv):
    co = ChannelOptions()
    co.connection_type = "pooled"
    ch = Channel(co)
    ch.init(str(srv.listen_endpoint))
    return ch


def _native_count(srv, name):
    stats = srv._native_bridge.engine.native_stats()
    return stats.get(name, (0, 0))


def _raw_exchange(ep, frame: bytes) -> bytes:
    """Send one crafted frame, read one complete TRPC response frame —
    the raw wire bytes, for byte-identity comparisons."""
    with pysock.create_connection((str(ep.host), ep.port), timeout=10) as c:
        c.sendall(frame)
        c.settimeout(10)
        buf = b""
        while len(buf) < 12:
            buf += c.recv(65536)
        (blen,) = struct.unpack_from("<I", buf, 4)
        while len(buf) < 12 + blen:
            buf += c.recv(65536)
        return buf[:12 + blen]


def _frame(cid: int, svc: bytes, mth: bytes, payload: bytes,
           att: bytes = b"", extra_meta: bytes = b"") -> bytes:
    mb = TLV_CORRELATION + struct.pack("<Q", cid)
    if att:
        mb += TLV_ATTACHMENT + struct.pack("<I", len(att))
    mb += encode_tlv(4, svc) + encode_tlv(5, mth) + extra_meta
    body = mb + payload + att
    return b"TRPC" + struct.pack("<II", len(body), len(mb)) + body


@pytest.fixture()
def rpcz_off():
    """The byte-identity comparisons must exercise the slim FAST path —
    a sampled span escalates to the classic completion (byte-identical
    by construction, so it would vacuously pass)."""
    prev = get_flag("enable_rpcz", True)
    set_flag("enable_rpcz", False)
    yield
    set_flag("enable_rpcz", prev)


@pytest.fixture()
def pair(rpcz_off):
    require_native()
    nsrv, nsvc = _server(native=True)
    psrv, psvc = _server(native=False)
    yield (nsrv, nsvc, psrv, psvc)
    nsrv.stop()
    psrv.stop()


# ---- (a) slim vs classic: byte-identical responses --------------------

def test_byteident_plain(pair):
    nsrv, nsvc, psrv, psvc = pair
    f = _frame(77, b"S", b"Echo", b"hello")
    nat = _raw_exchange(nsrv.listen_endpoint, f)
    cls = _raw_exchange(psrv.listen_endpoint, f)
    assert nat == cls
    assert _native_count(nsrv, "S.Echo")[0] == 1
    assert nsvc.calls and psvc.calls      # the handler ran on both


def test_byteident_attachment(pair):
    nsrv, _, psrv, _ = pair
    f = _frame(78, b"S", b"Echo", b"pay", att=b"A" * 300)
    nat = _raw_exchange(nsrv.listen_endpoint, f)
    cls = _raw_exchange(psrv.listen_endpoint, f)
    assert nat == cls
    # sanity: the response carries the echoed attachment TLV
    meta_len = struct.unpack_from("<I", nat, 8)[0]
    meta = RpcMeta.decode(nat[12:12 + meta_len])
    assert meta.correlation_id == 78 and meta.attachment_size == 300


def test_byteident_handler_exception(pair):
    nsrv, _, psrv, _ = pair
    f = _frame(79, b"S", b"Boom", b"x")
    nat = _raw_exchange(nsrv.listen_endpoint, f)
    cls = _raw_exchange(psrv.listen_endpoint, f)
    assert nat == cls
    meta = RpcMeta.decode(nat[12:12 + struct.unpack_from("<I", nat, 8)[0]])
    assert meta.error_code == int(Errno.EINTERNAL)
    assert "ValueError: kapow" in meta.error_text


def test_byteident_set_failed(pair):
    nsrv, _, psrv, _ = pair
    f = _frame(80, b"S", b"SetFail", b"x")
    nat = _raw_exchange(nsrv.listen_endpoint, f)
    cls = _raw_exchange(psrv.listen_endpoint, f)
    assert nat == cls
    meta = RpcMeta.decode(nat[12:12 + struct.unpack_from("<I", nat, 8)[0]])
    assert meta.error_code == int(Errno.EREQUEST)
    assert meta.error_text == "refused politely"


def test_byteident_malformed_attachment(pair):
    """Attachment-size TLV exceeding the body: the engine answers
    EREQUEST with the same text the classic split_attachment path
    raises — without entering the handler."""
    nsrv, nsvc, psrv, psvc = pair
    mb = (TLV_CORRELATION + struct.pack("<Q", 81)
          + TLV_ATTACHMENT + struct.pack("<I", 999)
          + encode_tlv(4, b"S") + encode_tlv(5, b"Echo"))
    f = b"TRPC" + struct.pack("<II", len(mb) + 4, len(mb)) + mb + b"zzzz"
    nat = _raw_exchange(nsrv.listen_endpoint, f)
    cls = _raw_exchange(psrv.listen_endpoint, f)
    assert nat == cls
    meta = RpcMeta.decode(nat[12:12 + struct.unpack_from("<I", nat, 8)[0]])
    assert meta.error_code == int(Errno.EREQUEST)
    assert not nsvc.calls and not psvc.calls


def test_byteident_admission_reject(pair):
    """ELIMIT from the concurrency gate: the shim's classic error
    builder must produce the same frame as the classic dispatch."""
    nsrv, _, psrv, _ = pair
    for srv in (nsrv, psrv):
        status = srv.find_method("S", "Echo").status
        status.max_concurrency = 1
        status._inflight = 1          # saturate the cap deterministically
    f = _frame(82, b"S", b"Echo", b"x")
    nat = _raw_exchange(nsrv.listen_endpoint, f)
    cls = _raw_exchange(psrv.listen_endpoint, f)
    assert nat == cls
    meta = RpcMeta.decode(nat[12:12 + struct.unpack_from("<I", nat, 8)[0]])
    assert meta.error_code == int(Errno.ELIMIT)


def test_async_method_over_slim_lane(pair):
    """begin_async + finish from another thread: the shim returns None
    (out-of-band) and the classic completion sends the response."""
    nsrv, _, psrv, _ = pair
    f = _frame(83, b"S", b"Later", b"zz")
    nat = _raw_exchange(nsrv.listen_endpoint, f)
    cls = _raw_exchange(psrv.listen_endpoint, f)
    assert nat == cls
    meta_len = struct.unpack_from("<I", nat, 8)[0]
    assert nat[12 + meta_len:] == b"async:zz"


# ---- (b) fallback triggers take the Python path -----------------------

def test_traced_request_rides_slim_lane(pair):
    """Observer-effect-free tracing (distributed-rpcz PR): an explicit
    trace id used to kick the request off the slim lane — tracing
    changed the very path being observed.  The engine now hands the
    trace TLVs through the shim: the request stays native, the forced
    span records with the caller's span id as parent."""
    from brpc_tpu.rpcz import global_span_store

    global_span_store().clear()
    nsrv, nsvc, _, _ = pair
    set_flag("enable_rpcz", True)        # pair runs rpcz_off; tracing
    try:                                 # is exactly what's under test
        ch = _channel(nsrv)
        cntl = Controller()
        cntl.timeout_ms = 5_000
        cntl.trace_id = 4242
        c = ch.call_method("S.Echo", b"traced", cntl=cntl)
        assert not c.failed and bytes(c.response) == b"ok:traced"
        assert _native_count(nsrv, "S.Echo")[0] == 1  # stayed native
        assert len(nsvc.calls) == 1      # the shim ran the handler
        spans = global_span_store().by_trace(4242)
        server_spans = [s for s in spans if s.is_server]
        client_spans = [s for s in spans if not s.is_server]
        assert len(server_spans) == 1 and len(client_spans) == 1
        assert server_spans[0].parent_span_id == client_spans[0].span_id
    finally:
        set_flag("enable_rpcz", False)
        global_span_store().clear()


def test_fallback_large_attachment(pair):
    """Attachments over the slim threshold (16KB) take the classic
    path; under it they ride the slim lane.  Both answer correctly."""
    from brpc_tpu.butil.iobuf import IOBuf

    nsrv, nsvc, _, _ = pair
    ch = _channel(nsrv)
    small, big = bytes(1024), bytes(20 * 1024)
    for att, expect_native in ((small, 1), (big, 1)):
        cntl = Controller()
        cntl.timeout_ms = 10_000
        cntl.request_attachment = IOBuf(att)
        c = ch.call_method("S.Echo", b"p", cntl=cntl)
        assert not c.failed, c.error_text
        assert c.response_attachment.to_bytes() == att
    # exactly ONE of the two rode the slim lane (the small one)
    assert _native_count(nsrv, "S.Echo")[0] == 1
    assert len(nsvc.calls) == 2


def test_fallback_stream_tag(pair):
    """A controller-tier tag (stream window) in the meta bypasses the
    slim lane — the classic dispatch owns anything stream-shaped."""
    nsrv, nsvc, _, _ = pair
    f = _frame(84, b"S", b"Echo", b"sw",
               extra_meta=encode_tlv(14, struct.pack("<I", 4096)))
    nat = _raw_exchange(nsrv.listen_endpoint, f)
    meta_len = struct.unpack_from("<I", nat, 8)[0]
    assert nat[12 + meta_len:] == b"ok:sw"
    assert _native_count(nsrv, "S.Echo")[0] == 0
    assert len(nsvc.calls) == 1


def test_fallback_auth_server_not_registered(rpcz_off):
    """An auth-bearing server registers NOTHING with the engine: every
    request must be observable by the verifier."""
    require_native()

    class Auth:
        def verify(self, auth_data, cntl):
            return True

    srv, svc = _server(native=True, auth=Auth())
    try:
        assert srv._native_bridge.engine.native_stats() == {}
        co = ChannelOptions()
        co.connection_type = "pooled"
        co.auth_data = b"tok"
        ch = Channel(co)
        ch.init(str(srv.listen_endpoint))
        c = ch.call_method("S.Echo", b"a", cntl=Controller())
        assert not c.failed and bytes(c.response) == b"ok:a"
        assert len(svc.calls) == 1
    finally:
        srv.stop()


def test_non_inline_server_keeps_python_path(rpcz_off):
    """usercode_inline=False: user code must stay off the engine loops,
    so the slim lane (and kind 2) must not register."""
    require_native()
    opts = ServerOptions()
    opts.native = True
    opts.usercode_inline = False
    opts.native_loops = 1
    svc = SlimSvc()
    srv = Server(opts)
    srv.add_service(svc, name="S")
    assert srv.start("127.0.0.1:0") == 0
    try:
        assert srv._native_bridge.engine.native_stats() == {}
        ch = _channel(srv)
        c = ch.call_method("S.Echo", b"ni", cntl=Controller())
        assert not c.failed and bytes(c.response) == b"ok:ni"
        # dispatched on a fiber, not an engine loop thread
        assert not any(n.startswith("native-loop") for n in svc.calls)
    finally:
        srv.stop()


# ---- (c) MethodStatus + rpcz survive native dispatch ------------------

def test_method_status_survives_slim_dispatch(rpcz_off):
    require_native()
    srv, svc = _server(native=True)
    try:
        ch = _channel(srv)
        entry = srv.find_method("S", "Echo")
        base = entry.status.latency.count()
        for i in range(7):
            c = ch.call_method("S.Echo", b"m%d" % i, cntl=Controller())
            assert not c.failed
        assert _native_count(srv, "S.Echo")[0] == 7
        assert entry.status.latency.count() == base + 7
        assert entry.status.inflight == 0
        # errors are accounted too (escalated through the classic path)
        c = ch.call_method("S.Boom", b"x", cntl=Controller())
        assert c.failed
        boom = srv.find_method("S", "Boom")
        assert boom.status.errors.get_value() >= 1
        assert boom.status.inflight == 0
    finally:
        srv.stop()


def test_rpcz_sampled_spans_survive_slim_dispatch():
    require_native()
    import brpc_tpu.rpcz as rpcz

    prev = get_flag("enable_rpcz", True)
    set_flag("enable_rpcz", True)
    srv, svc = _server(native=True)
    try:
        ch = _channel(srv)
        before = {id(s) for s in rpcz.global_span_store().recent(2048)}
        for i in range(3):
            c = ch.call_method("S.Echo", b"sp", cntl=Controller())
            assert not c.failed
        spans = [s for s in rpcz.global_span_store().recent(2048)
                 if id(s) not in before and s.full_method == "S.Echo"
                 and s.is_server]
        assert spans, "no sampled server span recorded via the slim lane"
        s = spans[0]
        assert s.request_size > 0 and s.end_us >= s.received_us
    finally:
        srv.stop()
        set_flag("enable_rpcz", prev)


def test_slim_concurrency_limited_method_still_limited(rpcz_off):
    """A per-method cap stays ENFORCED on the slim lane (the shim runs
    admission) — unlike raw kinds, the method still registers."""
    require_native()
    opts = ServerOptions()
    opts.native = True
    opts.usercode_inline = True
    opts.native_loops = 1
    opts.method_max_concurrency = {"S.Echo": 4}
    svc = SlimSvc()
    srv = Server(opts)
    srv.add_service(svc, name="S")
    assert srv.start("127.0.0.1:0") == 0
    try:
        ch = _channel(srv)
        c = ch.call_method("S.Echo", b"lim", cntl=Controller())
        assert not c.failed and bytes(c.response) == b"ok:lim"
        assert _native_count(srv, "S.Echo")[0] == 1   # slim lane active
        status = srv.find_method("S", "Echo").status
        status._inflight = 4          # saturate the cap deterministically
        cntl = Controller()
        cntl.timeout_ms = 5_000
        cntl.max_retry = 0
        c = ch.call_method("S.Echo", b"over", cntl=cntl)
        assert c.failed and c.error_code == int(Errno.ELIMIT)
        status._inflight = 0
    finally:
        srv.stop()


# ---- (d) blocking handlers on a non-inline server ---------------------

def test_blocking_http_handler_does_not_stall_other_conns():
    """ADVICE r5 #1: on a non-inline native server, EV_HTTP dispatch
    rides a per-connection ExecutionQueue — one blocking HTTP handler
    must stall neither tpu_std traffic nor other HTTP connections."""
    require_native()
    import http.client

    release = threading.Event()
    entered = threading.Event()

    class Mixed(Service):
        def Block(self, cntl, request):
            entered.set()
            release.wait(15)
            return b"released"

        def Fast(self, cntl, request):
            return b"fast"

    opts = ServerOptions()
    opts.native = True
    opts.usercode_inline = False
    opts.native_loops = 1
    srv = Server(opts)
    srv.add_service(Mixed(), name="M")
    assert srv.start("127.0.0.1:0") == 0
    try:
        ep = srv.listen_endpoint
        results = {}

        def blocked_http():
            conn = http.client.HTTPConnection(ep.host, ep.port,
                                              timeout=20)
            conn.request("POST", "/M/Block", body=b"")
            results["block"] = conn.getresponse().read()
            conn.close()

        t = threading.Thread(target=blocked_http, daemon=True)
        t.start()
        assert entered.wait(10), "blocking handler never entered"

        # another HTTP connection proceeds while the first one blocks
        t0 = time.monotonic()
        conn2 = http.client.HTTPConnection(ep.host, ep.port, timeout=10)
        conn2.request("POST", "/M/Fast", body=b"")
        assert conn2.getresponse().read() == b"fast"
        conn2.close()
        http_latency = time.monotonic() - t0

        # tpu_std traffic proceeds too
        t0 = time.monotonic()
        ch = _channel(srv)
        c = ch.call_method("M.Fast", b"", cntl=Controller())
        assert not c.failed and bytes(c.response) == b"fast"
        rpc_latency = time.monotonic() - t0

        release.set()
        t.join(10)
        assert results.get("block") == b"released"
        assert http_latency < 5.0 and rpc_latency < 5.0
    finally:
        release.set()
        srv.stop()
