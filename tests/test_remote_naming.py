"""watch:// naming service — long-poll membership from a fleet
controller, index resumption, degrade-to-file.

Mirrors the reference's consul NS test strategy: a local fake HTTP
server plays the registry
(/root/reference/test/brpc_naming_service_unittest.cpp:405-463 fakes
consul the same way), and the acceptance bar is the VERDICT's: a
membership change must propagate to a load balancer mid-traffic
without a single dropped request.
"""

import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

import pytest

from brpc_tpu.butil.flags import set_flag
from brpc_tpu.client import Channel
from brpc_tpu.client.naming_service import create_naming_service
from brpc_tpu.server import Server, Service


class FakeController:
    """Blocking-query membership endpoint (the consul shape)."""

    def __init__(self):
        self.index = 1
        self.members = []          # list of "host:port[ tag]" strings
        self._cond = threading.Condition()
        self.queries = []          # (index, wait) seen, for assertions
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):      # quiet
                pass

            def do_GET(self):
                q = parse_qs(urlparse(self.path).query)
                idx = int(q.get("index", ["0"])[0])
                wait = q.get("wait", ["1s"])[0]
                wait_s = float(wait[:-1]) if wait.endswith("s") else 1.0
                with outer._cond:
                    outer.queries.append((idx, wait_s))
                    # block until membership advances past the caller's
                    # index (a real controller caps the wait)
                    outer._cond.wait_for(
                        lambda: outer.index > idx,
                        timeout=min(wait_s, 5.0))
                    body = ("\n".join(outer.members) + "\n").encode()
                    index = outer.index
                self.send_response(200)
                self.send_header("X-Fleet-Index", str(index))
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.port = self.httpd.server_address[1]
        self._thr = threading.Thread(target=self.httpd.serve_forever,
                                     daemon=True)
        self._thr.start()

    def set_members(self, members):
        with self._cond:
            self.members = list(members)
            self.index += 1
            self._cond.notify_all()

    def stop(self):
        self.httpd.shutdown()
        self.httpd.server_close()


@pytest.fixture()
def controller():
    c = FakeController()
    yield c
    c.stop()


def _wait_until(pred, timeout=10.0, step=0.02):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(step)
    return pred()


def test_watch_pushes_initial_membership(controller):
    controller.set_members(["10.0.0.1:80 a", "10.0.0.2:81 b"])
    ns = create_naming_service(
        f"watch://127.0.0.1:{controller.port}/members")
    assert ns is not None
    try:
        assert _wait_until(lambda: len(ns.current) == 2)
        tags = sorted(n.tag for n in ns.current)
        assert tags == ["a", "b"]
    finally:
        ns.stop()


def test_watch_long_poll_propagates_fast(controller):
    """The change must arrive via the BLOCKING query (sub-second), not a
    polling period."""
    controller.set_members(["10.0.0.1:80"])
    ns = create_naming_service(
        f"watch://127.0.0.1:{controller.port}/members")
    try:
        assert _wait_until(lambda: len(ns.current) == 1)
        t0 = time.time()
        controller.set_members(["10.0.0.1:80", "10.0.0.3:82"])
        assert _wait_until(lambda: len(ns.current) == 2, timeout=5.0)
        assert time.time() - t0 < 2.0, "change rode a poll, not the watch"
        # index resumption: later queries must carry an advanced index
        assert _wait_until(
            lambda: any(q[0] >= 2 for q in controller.queries))
    finally:
        ns.stop()


class Echo(Service):
    def __init__(self, name):
        self.name = name
        self.hits = 0

    def Who(self, cntl, request):
        self.hits += 1
        return self.name.encode()


def test_membership_change_mid_traffic_no_dropped_requests(controller):
    """The VERDICT acceptance: flip membership under live load; every
    request must succeed, and traffic must shift to the new member."""
    servers, svcs = [], []
    for name in ("A", "B", "C"):
        svc = Echo(name)
        s = Server()
        s.add_service(svc, name="E")
        assert s.start("127.0.0.1:0") == 0
        servers.append(s)
        svcs.append(svc)
    try:
        addr = lambda i: str(servers[i].listen_endpoint)  # noqa: E731
        controller.set_members([addr(0), addr(1)])

        ch = Channel()
        assert ch.init(
            f"watch://127.0.0.1:{controller.port}/members", "rr") == 0
        assert _wait_until(
            lambda: len(ch.load_balancer.servers) == 2)

        failures = []
        seen = set()
        stop = threading.Event()

        def hammer():
            from brpc_tpu.client import Controller
            while not stop.is_set():
                cntl = Controller()
                cntl.timeout_ms = 5_000
                c = ch.call_method("E.Who", b"", cntl=cntl)
                if c.failed:
                    failures.append(c.error_text)
                    return
                seen.add(bytes(c.response))

        t = threading.Thread(target=hammer)
        t.start()
        try:
            assert _wait_until(lambda: {b"A", b"B"} <= seen)
            # flip: A out, C in — while the hammer runs
            controller.set_members([addr(1), addr(2)])
            assert _wait_until(lambda: b"C" in seen, timeout=10.0)
        finally:
            stop.set()
            t.join(15)
        assert not failures, failures
        # propagation settled: A no longer selected
        from brpc_tpu.client import Controller
        a_hits = svcs[0].hits
        for _ in range(20):
            cntl = Controller()
            cntl.timeout_ms = 5_000
            c = ch.call_method("E.Who", b"", cntl=cntl)
            assert not c.failed, c.error_text
        assert svcs[0].hits == a_hits, "removed server still selected"
        assert svcs[2].hits > 0
    finally:
        for s in servers:
            s.stop()


def test_degrade_to_file(controller, tmp_path):
    """Controller down at startup ⇒ membership seeds from the mirrored
    backup of the last successful fetch."""
    set_flag("remote_ns_backup_dir", str(tmp_path))
    try:
        controller.set_members(["10.0.0.9:99 backup-me"])
        url = f"watch://127.0.0.1:{controller.port}/members"
        ns = create_naming_service(url)
        assert _wait_until(lambda: len(ns.current) == 1)
        ns.stop()
        controller.stop()        # registry goes dark

        ns2 = create_naming_service(url)
        try:
            assert _wait_until(lambda: len(ns2.current) == 1, timeout=15.0)
            assert ns2.current[0].tag == "backup-me"
        finally:
            ns2.stop()
    finally:
        set_flag("remote_ns_backup_dir", "")
