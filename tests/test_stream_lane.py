"""Kind-5 native streaming lane — native-vs-forced-Python observable
identity, the cross-cutting plane on stream open (trace / deadline /
tenant / admission, via the compiled interceptor chain), every NAMED
fallback reason, credit backpressure, and drain-mid-stream (the
test_deadline_plane lane-matrix shape applied to streams)."""

import os
import signal
import struct
import socket as pysock
import threading
import time

import pytest

from brpc_tpu.butil.flags import get_flag, set_flag
from brpc_tpu.butil.status import Errno
from brpc_tpu.client import Channel, Controller
from brpc_tpu.protocol.meta import RpcMeta
from brpc_tpu.server import Server, ServerOptions, Service
from brpc_tpu.streaming import (StreamOptions, stream_accept,
                                stream_create)

from conftest import require_native, wire_tlv  # noqa: E402


class StreamSvc(Service):
    """Echo-upper streaming service + a plain unary method."""

    def __init__(self):
        self.server_streams = []

    def Start(self, cntl, request):
        def on_received(stream, msgs):
            for m in msgs:
                stream.write(bytes(m).upper())

        s = stream_accept(cntl, StreamOptions(on_received=on_received))
        assert s is not None
        self.server_streams.append(s)
        return b"accepted:" + bytes(request)

    def StartShortFuse(self, cntl, request):
        """Accepts with a short write timeout: backpressure surfaces
        to the producer as EOVERCROWDED instead of a long block."""
        s = stream_accept(cntl, StreamOptions(write_timeout_s=0.25))
        assert s is not None
        self.server_streams.append(s)
        return b"ok"

    def Plain(self, cntl, request):
        return b"plain:" + bytes(request)


def _server(**opt_kw):
    require_native()
    opts = ServerOptions()
    opts.native = True
    opts.usercode_inline = True
    opts.native_loops = 1
    for k, v in opt_kw.items():
        setattr(opts, k, v)
    svc = StreamSvc()
    srv = Server(opts)
    srv.add_service(svc, name="SL")
    assert srv.start("127.0.0.1:0") == 0
    return srv, svc


def _tele(srv) -> dict:
    return srv._native_bridge.engine.telemetry()


@pytest.fixture()
def pair():
    srv, svc = _server()
    yield srv, svc
    srv.stop()


def _open_session(srv, received=None, closed=None, method="SL.Start",
                  payload=b"hi", window=None, cntl=None):
    ch = Channel()
    ch.init(str(srv.listen_endpoint))
    received = received if received is not None else []
    cntl = cntl or Controller()
    opts = StreamOptions(
        on_received=lambda st, msgs: received.extend(msgs),
        on_closed=(lambda st: closed.append(st.close_reason))
        if closed is not None else None)
    if window:
        opts.max_buf_size = window
    stream = stream_create(cntl, opts)
    c = ch.call_method(method, payload, cntl=cntl)
    return c, stream, received


def _echo_roundtrip(srv, n=12):
    received = []
    c, stream, _ = _open_session(srv, received)
    assert not c.failed, (c.error_code, c.error_text)
    assert bytes(c.response) == b"accepted:hi"
    assert stream.wait_established(5.0)
    for i in range(n):
        assert stream.write(f"msg{i}".encode()) == 0
    deadline = time.time() + 10
    while len(received) < n and time.time() < deadline:
        time.sleep(0.01)
    assert received == [f"MSG{i}".encode() for i in range(n)]
    stream.close()
    return stream


# ---------------------------------------------------------------------------
# native-vs-forced-Python observable identity
# ---------------------------------------------------------------------------

def test_native_vs_python_identity_matrix(pair):
    """The SAME workload over both lanes (live flag flip): responses,
    grant negotiation, echo payloads and close behavior identical; the
    native arm rides the stream lane (handled grows, zero fallbacks),
    the Python arm falls back under the NAMED no-capability reason."""
    srv, svc = pair
    t0 = _tele(srv)
    s_native = _echo_roundtrip(srv)
    t1 = _tele(srv)
    assert t1["lanes"]["stream"]["handled"] \
        == t0["lanes"]["stream"]["handled"] + 1
    assert t1["streams"]["chunks_in"] > t0["streams"]["chunks_in"]
    assert t1["streams"]["chunks_out"] >= t0["streams"]["chunks_out"] + 12
    for r, v in t1["streams"]["fallbacks"].items():
        assert v == t0["streams"]["fallbacks"][r], r

    set_flag("rpc_native_stream_lane", False)
    try:
        s_py = _echo_roundtrip(srv)
        t2 = _tele(srv)
        # python arm: open fell back NAMED; no new native opens
        assert t2["lanes"]["stream"]["handled"] \
            == t1["lanes"]["stream"]["handled"]
        assert t2["streams"]["fallbacks"]["stream_no_shim"] \
            > t1["streams"]["fallbacks"]["stream_no_shim"]
    finally:
        set_flag("rpc_native_stream_lane", True)
    # both arms negotiated the same window shape
    assert s_native._write_window == s_py._write_window


def test_plain_unary_still_rides_slim_lane(pair):
    """Kind-3 regression pin: a streamless call on the same service
    keeps its lane (the stream shim only takes stream-TLV requests)."""
    srv, _ = pair
    ch = Channel()
    ch.init(str(srv.listen_endpoint))
    t0 = _tele(srv)
    assert ch.call("SL.Plain", b"x") == b"plain:x"
    t1 = _tele(srv)
    assert t1["lanes"]["slim"]["handled"] \
        == t0["lanes"]["slim"]["handled"] + 1
    assert t1["lanes"]["stream"]["handled"] \
        == t0["lanes"]["stream"]["handled"]


def test_stream_lane_hist_identity(pair):
    """Per the telemetry invariant: every stream-open item lands in
    all three stage hists exactly once (resid count == opens+errors)."""
    srv, _ = pair
    for _ in range(3):
        _echo_roundtrip(srv, n=2)
    t = _tele(srv)
    d = t["lanes"]["stream"]
    total = d["handled"] + d["errors"]
    assert total >= 3
    for st in ("queue", "shim", "resid"):
        assert d[f"{st}_us_count"] == total, (st, d)


# ---------------------------------------------------------------------------
# the cross-cutting plane on stream open (interceptor-chain binding)
# ---------------------------------------------------------------------------

def test_traced_open_stays_on_lane(pair):
    """An explicitly traced stream open RIDES the kind-5 lane (the
    chain's trace extract records the forced span) instead of falling
    back — tracing must not change the path being observed."""
    from brpc_tpu.rpcz import global_span_store
    srv, _ = pair
    t0 = _tele(srv)
    received = []
    cntl = Controller()
    cntl.trace_id = 53535
    c, stream, _ = _open_session(srv, received, cntl=cntl)
    assert not c.failed, c.error_text
    assert stream.wait_established(5.0)
    t1 = _tele(srv)
    assert t1["lanes"]["stream"]["handled"] \
        == t0["lanes"]["stream"]["handled"] + 1
    for r, v in t1["streams"]["fallbacks"].items():
        assert v == t0["streams"]["fallbacks"][r], r
    spans = global_span_store().by_trace(53535)
    assert any(s.is_server for s in spans), spans
    stream.close()


def test_expired_deadline_sheds_open_before_user_code(pair):
    """A stream open carrying an expired on-wire budget (TLV 13 = 0)
    is shed ERPCTIMEDOUT by the chain BEFORE the service method runs —
    no stream is accepted, no grant leaves."""
    srv, svc = pair
    before = len(svc.server_streams)
    meta = (wire_tlv(1, struct.pack("<Q", 77))
            + wire_tlv(4, b"SL") + wire_tlv(5, b"Start")
            + wire_tlv(12, struct.pack("<Q", 999999))
            + wire_tlv(14, struct.pack("<I", 65536))
            + wire_tlv(13, struct.pack("<I", 0)))
    frame = b"TRPC" + struct.pack("<II", len(meta), len(meta)) + meta
    ep = srv.listen_endpoint
    with pysock.create_connection((str(ep.host), ep.port),
                                  timeout=10) as c:
        c.sendall(frame)
        c.settimeout(10)
        buf = b""
        while len(buf) < 12:
            buf += c.recv(65536)
        (blen,) = struct.unpack_from("<I", buf, 4)
        while len(buf) < 12 + blen:
            buf += c.recv(65536)
        (mlen,) = struct.unpack_from("<I", buf, 8)
        resp = RpcMeta.decode(buf[12:12 + mlen])
    assert resp is not None
    assert resp.error_code == int(Errno.ERPCTIMEDOUT), resp.error_code
    assert resp.stream_id == 0          # no grant
    assert len(svc.server_streams) == before


def test_tenant_stamped_open_feeds_admission(pair):
    """A tenant-stamped open runs the shared admission stage with the
    tenant key (per-tenant fair-admission accounting grows)."""
    from brpc_tpu.client import ChannelOptions
    from brpc_tpu.server.admission import admission_counters
    srv, _ = pair
    before = admission_counters().get(("tt-stream", "admitted"), 0)
    co = ChannelOptions()
    co.tenant = "tt-stream"
    ch = Channel(co)
    ch.init(str(srv.listen_endpoint))
    received = []
    cntl = Controller()
    stream = stream_create(
        cntl, StreamOptions(
            on_received=lambda st, msgs: received.extend(msgs)))
    c = ch.call_method("SL.Start", b"t", cntl=cntl)
    assert not c.failed, c.error_text
    assert stream.wait_established(5.0)
    after = admission_counters().get(("tt-stream", "admitted"), 0)
    assert after == before + 1
    stream.close()


def test_draining_server_rejects_open_elameduck(pair):
    """Admission on a draining server: new stream opens bounce with
    ELAMEDUCK (engine declines them under the NAMED stream_drain
    reason; the classic lane serializes the rejection)."""
    srv, _ = pair
    ch = Channel()
    ch.init(str(srv.listen_endpoint))
    assert ch.call("SL.Plain", b"warm") == b"plain:warm"  # conn up
    t0 = _tele(srv)
    assert srv.drain(grace_ms=300) == 0
    cntl = Controller()
    cntl.timeout_ms = 3000
    stream = stream_create(cntl, StreamOptions())
    c = ch.call_method("SL.Start", b"", cntl=cntl)
    assert c.failed
    assert c.error_code == int(Errno.ELAMEDUCK), \
        (c.error_code, c.error_text)
    assert stream.closed                  # never bound
    t1 = _tele(srv)
    assert t1["streams"]["fallbacks"]["stream_drain"] \
        > t0["streams"]["fallbacks"]["stream_drain"]


# ---------------------------------------------------------------------------
# named fallback pins — every kind-5 ineligible shape, byte-identical
# over the Python lane
# ---------------------------------------------------------------------------

def test_fallback_no_shim_lane_off():
    """Lane flag off at listen: no capability — opens fall back under
    stream_no_shim and the whole workload runs on the Python lane
    unchanged."""
    require_native()
    prev = get_flag("rpc_native_stream_lane", True)
    set_flag("rpc_native_stream_lane", False)
    try:
        srv, svc = _server()
        try:
            _echo_roundtrip(srv, n=4)
            t = _tele(srv)
            assert t["streams"]["fallbacks"]["stream_no_shim"] >= 1
            assert t["lanes"]["stream"]["handled"] == 0
            assert svc.server_streams[-1]._native_tx is None
        finally:
            srv.stop()
    finally:
        set_flag("rpc_native_stream_lane", prev)


def test_fallback_non_inline_named():
    """usercode_inline off: the server cannot run the open on the
    loop, and the decline is NAMED stream_non_inline (not a generic
    bucket).  A kind-0 echo method keeps native dispatch on so the
    screening actually runs."""
    require_native()
    from brpc_tpu.server.service import raw_method

    class Mixed(Service):
        @raw_method(native="echo")
        def Echo(self, payload, att):
            return bytes(payload)

        def Start(self, cntl, request):
            s = stream_accept(cntl, StreamOptions())
            assert s is not None
            return b"ok"

    opts = ServerOptions()
    opts.native = True
    opts.usercode_inline = False
    opts.native_loops = 1
    srv = Server(opts)
    srv.add_service(Mixed(), name="M")
    assert srv.start("127.0.0.1:0") == 0
    try:
        received = []
        ch = Channel()
        ch.init(str(srv.listen_endpoint))
        cntl = Controller()
        stream = stream_create(cntl, StreamOptions(
            on_received=lambda st, msgs: received.extend(msgs)))
        c = ch.call_method("M.Start", b"", cntl=cntl)
        assert not c.failed, c.error_text
        assert stream.wait_established(5.0)   # python lane still works
        t = _tele(srv)
        assert t["streams"]["fallbacks"]["stream_non_inline"] >= 1
        assert t["lanes"]["stream"]["handled"] == 0
        stream.close()
    finally:
        srv.stop()


def test_fallback_compressed_open_named(pair):
    """A gzip-compressed open declines under stream_compressed and the
    Python lane serves it byte-identically (stream still binds)."""
    from brpc_tpu.protocol.meta import CompressType
    srv, _ = pair
    t0 = _tele(srv)
    received = []
    cntl = Controller()
    cntl.request_compress_type = CompressType.GZIP
    c, stream, _ = _open_session(srv, received, cntl=cntl)
    assert not c.failed, c.error_text
    assert bytes(c.response) == b"accepted:hi"
    assert stream.wait_established(5.0)
    assert stream.write(b"zz") == 0
    deadline = time.time() + 10
    while not received and time.time() < deadline:
        time.sleep(0.01)
    assert received == [b"ZZ"]
    t1 = _tele(srv)
    assert t1["streams"]["fallbacks"]["stream_compressed"] \
        > t0["streams"]["fallbacks"]["stream_compressed"]
    assert t1["lanes"]["stream"]["handled"] \
        == t0["lanes"]["stream"]["handled"]
    stream.close()


def test_fallback_oversize_chunk_named(pair):
    """A chunk too large for the burst batch rides the direct-read
    Python path under stream_chunk_oversize — and still arrives
    intact (byte-identical delivery through the same Stream)."""
    srv, svc = pair
    received = []
    c, stream, _ = _open_session(srv, received)
    assert not c.failed
    assert stream.wait_established(5.0)
    t0 = _tele(srv)
    big = bytes(bytearray(range(256)) * 400)      # 100KB > inbuf/2
    assert stream.write(big) == 0
    deadline = time.time() + 15
    while not received and time.time() < deadline:
        time.sleep(0.01)
    assert len(received) == 1
    assert bytes(received[0]) == big.upper()
    t1 = _tele(srv)
    assert t1["streams"]["fallbacks"]["stream_chunk_oversize"] \
        > t0["streams"]["fallbacks"]["stream_chunk_oversize"]
    stream.close()


def test_fallback_unregistered_named(pair):
    """Frames for a stream the engine no longer owns (closed server
    side) fall back NAMED and are dropped by the Python guard — never
    crash, never an unknown bucket."""
    srv, svc = pair
    c, stream, _ = _open_session(srv)
    assert not c.failed
    assert stream.wait_established(5.0)
    peer = svc.server_streams[-1]
    peer.close()                       # server side unregisters
    deadline = time.time() + 5
    while not stream.closed and time.time() < deadline:
        time.sleep(0.01)
    t0 = _tele(srv)
    # forge one more DATA frame at the dead sid over a fresh conn
    from brpc_tpu.protocol.streaming import MAGIC
    frame = MAGIC + struct.pack("<BQI", 0, peer.id, 3) + b"xyz"
    ep = srv.listen_endpoint
    with pysock.create_connection((str(ep.host), ep.port),
                                  timeout=5) as s:
        s.sendall(frame)
        time.sleep(0.3)
    t1 = _tele(srv)
    assert t1["streams"]["fallbacks"]["stream_unregistered"] \
        > t0["streams"]["fallbacks"]["stream_unregistered"]


# ---------------------------------------------------------------------------
# credit backpressure + drain-mid-stream
# ---------------------------------------------------------------------------

def test_credit_backpressure_surfaces_and_resumes(pair):
    """Server-side writes against a tiny client window: the producer
    sees EOVERCROWDED at credit exhaustion (counted as a stall), then
    resumes once the consumer's feedback frees credit — and every
    chunk arrives exactly once, in order."""
    srv, svc = pair
    received = []
    hold = threading.Event()

    def slow_consumer(st, msgs):
        hold.wait(2.0)                 # stall the first delivery
        received.extend(msgs)

    ch = Channel()
    ch.init(str(srv.listen_endpoint))
    cntl = Controller()
    stream = stream_create(cntl, StreamOptions(
        on_received=slow_consumer, max_buf_size=4096))
    c = ch.call_method("SL.StartShortFuse", b"", cntl=cntl)
    assert not c.failed, c.error_text
    assert stream.wait_established(5.0)
    peer = svc.server_streams[-1]
    assert peer._native_tx is not None
    assert peer._write_window == 4096   # negotiated client window
    t0 = _tele(srv)
    payload = b"x" * 1024
    sent = 0
    saw_backpressure = False
    deadline = time.time() + 20
    while sent < 12 and time.time() < deadline:
        rc = peer.write(payload)
        if rc == 0:
            sent += 1
            continue
        assert rc == int(Errno.EOVERCROWDED), rc
        saw_backpressure = True
        hold.set()                      # release the consumer
    assert sent == 12
    assert saw_backpressure
    t1 = _tele(srv)
    assert t1["streams"]["credit_stalls"] \
        > t0["streams"]["credit_stalls"]
    deadline = time.time() + 10
    while len(received) < 12 and time.time() < deadline:
        time.sleep(0.01)
    assert [bytes(m) for m in received] == [payload] * 12
    stream.close()


def test_drain_closes_streams_with_named_reason(pair):
    """Drain-mid-stream: lame duck ends in-flight streams AFTER the
    current chunk window with the NAMED close reason — the client's
    on_closed sees 'lame_duck', and drain still settles clean."""
    srv, svc = pair
    received, closed = [], []
    ch = Channel()
    ch.init(str(srv.listen_endpoint))
    cntl = Controller()
    stream = stream_create(cntl, StreamOptions(
        on_received=lambda st, msgs: received.extend(msgs),
        on_closed=lambda st: closed.append(st.close_reason)))
    c = ch.call_method("SL.Start", b"", cntl=cntl)
    assert not c.failed
    assert stream.wait_established(5.0)
    assert stream.write(b"pre-drain") == 0
    deadline = time.time() + 10
    while not received and time.time() < deadline:
        time.sleep(0.01)
    assert received == [b"PRE-DRAIN"]   # window flushed before close
    assert srv.drain(grace_ms=2000) == 0
    deadline = time.time() + 5
    while not closed and time.time() < deadline:
        time.sleep(0.01)
    assert closed == ["lame_duck"], closed
    assert stream.closed


def test_sigterm_drives_drain():
    """graceful_quit_on_sigterm: SIGTERM → drain (streams closed with
    the named reason, in-flight settled) → stop, without killing the
    process."""
    require_native()
    prev_flag = get_flag("graceful_quit_on_sigterm", False)
    prev_handler = signal.getsignal(signal.SIGTERM)
    set_flag("graceful_quit_on_sigterm", True)
    try:
        srv, svc = _server()
        closed = []
        ch = Channel()
        ch.init(str(srv.listen_endpoint))
        cntl = Controller()
        stream = stream_create(cntl, StreamOptions(
            on_closed=lambda st: closed.append(st.close_reason)))
        c = ch.call_method("SL.Start", b"", cntl=cntl)
        assert not c.failed
        assert stream.wait_established(5.0)
        os.kill(os.getpid(), signal.SIGTERM)
        deadline = time.time() + 10
        while (srv._started or not closed) and time.time() < deadline:
            time.sleep(0.02)
        assert not srv._started
        assert closed == ["lame_duck"], closed
    finally:
        set_flag("graceful_quit_on_sigterm", prev_flag)
        signal.signal(signal.SIGTERM, prev_handler)
        import brpc_tpu.server.server as _srv_mod
        _srv_mod._sigterm_installed = False


def test_native_portal_streaming_section(pair):
    """/native carries the streaming block: streams open, chunk flow,
    chunks-per-burst histogram, credit stalls, per-reason fallbacks."""
    import json
    srv, _ = pair
    _echo_roundtrip(srv, n=6)
    ep = srv.listen_endpoint
    req = (b"GET /native HTTP/1.1\r\nHost: x\r\n"
           b"Accept: application/json\r\nConnection: close\r\n\r\n")
    with pysock.create_connection((str(ep.host), ep.port),
                                  timeout=10) as s:
        s.sendall(req)
        buf = b""
        s.settimeout(10)
        while True:
            try:
                chunk = s.recv(65536)
            except OSError:
                break
            if not chunk:
                break
            buf += chunk
    body = buf.split(b"\r\n\r\n", 1)[1]
    page = json.loads(body)
    st = page["streaming"]
    assert st["chunks_in"] >= 6
    assert st["chunks_out"] >= 6
    assert st["chunks_per_burst"]["count"] >= 1
    assert "stream_no_shim" not in st["fallbacks"] \
        or st["fallbacks"]["stream_no_shim"] >= 0
    assert "stream" in page["lanes"]
