"""HTTP protocol + builtin portal tests: stdlib http.client as the interop
peer (a real HTTP implementation we didn't write), RPC bridge, JSON
responses, flags live-set, multi-protocol port sharing
(≈ /root/reference/test/brpc_http_rpc_protocol_unittest.cpp shapes)."""

import http.client
import json

import pytest

from brpc_tpu.butil import flags as flags_mod
from brpc_tpu.client import Channel, ChannelOptions, Controller
from brpc_tpu.server import Server, Service


class Calc(Service):
    def Add(self, cntl, request):
        data = json.loads(request or b"{}")
        return {"sum": int(data.get("a", 0)) + int(data.get("b", 0))}

    def Echo(self, cntl, request):
        return request

    def Fail(self, cntl, request):
        cntl.set_failed(1003, "bad calc")
        return None


@pytest.fixture(scope="module")
def server():
    srv = Server()
    srv.add_service(Calc())
    assert srv.start("127.0.0.1:0") == 0
    yield srv
    srv.stop()


def _conn(server):
    ep = server.listen_endpoint
    return http.client.HTTPConnection(ep.host, ep.port, timeout=5)


def _get(server, path):
    c = _conn(server)
    c.request("GET", path)
    r = c.getresponse()
    body = r.read()
    c.close()
    return r.status, body


def test_index_and_health(server):
    status, body = _get(server, "/")
    assert status == 200
    assert b"/Calc/Add" in body
    status, body = _get(server, "/health")
    assert status == 200 and body == b"OK\n"


def test_status_json(server):
    status, body = _get(server, "/status")
    assert status == 200
    data = json.loads(body)
    assert "Calc.Add" in data["services"]


def test_vars_and_metrics(server):
    from brpc_tpu.bvar.reducer import Adder

    probe = Adder("http_test_probe_var")
    probe << 7
    status, body = _get(server, "/vars")
    assert status == 200
    assert b"http_test_probe_var" in body
    status, body = _get(server, "/vars/http_test_probe_var")
    assert status == 200 and b"7" in body
    status, body = _get(server, "/brpc_metrics")
    assert status == 200
    probe.hide()


def test_flags_get_and_live_set(server):
    status, body = _get(server, "/flags")
    assert status == 200 and b"max_body_size" in body
    # reloadable flag set through the portal
    status, body = _get(server, "/flags/health_check_interval_s?setvalue=7.5")
    assert status == 200, body
    assert flags_mod.get_flag("health_check_interval_s") == 7.5
    # invalid value rejected by validator
    status, body = _get(server, "/flags/health_check_interval_s?setvalue=-1")
    assert status == 403
    flags_mod.set_flag("health_check_interval_s", 3.0)


def test_max_body_size_flag_is_effective():
    import struct

    from brpc_tpu.butil.iobuf import IOBuf
    from brpc_tpu.protocol.base import ParseError
    from brpc_tpu.protocol.tpu_std import MAGIC, parse

    assert flags_mod.set_flag("max_body_size", 16)
    try:
        buf = IOBuf(MAGIC + struct.pack("<II", 100, 0) + b"x" * 100)
        r = parse(buf, None, False, None)
        assert r.error == ParseError.TOO_BIG_DATA
    finally:
        flags_mod.set_flag("max_body_size", 64 * 1024 * 1024)


def test_http_attachment_roundtrip(server):
    opts = ChannelOptions()
    opts.protocol = "http"
    ch = Channel(opts)
    assert ch.init(str(server.listen_endpoint)) == 0
    cntl = Controller()
    cntl.request_attachment.append(b"ATTACH" * 10)
    c = ch.call_method("Calc.Echo", b"body-only", cntl=cntl)
    assert not c.failed, c.error_text
    # server saw payload and attachment separately
    assert c.response == b"body-only"


def test_404(server):
    status, body = _get(server, "/nope")
    assert status == 404


def test_rpc_bridge_post_json(server):
    c = _conn(server)
    c.request("POST", "/Calc/Add", body=json.dumps({"a": 20, "b": 22}),
              headers={"Content-Type": "application/json"})
    r = c.getresponse()
    assert r.status == 200
    assert json.loads(r.read()) == {"sum": 42}
    # keep-alive: same connection again
    c.request("POST", "/Calc/Echo", body=b"raw-bytes")
    r = c.getresponse()
    assert r.status == 200
    assert r.read() == b"raw-bytes"
    c.close()


def test_rpc_bridge_get_query(server):
    status, body = _get(server, "/Calc/Add?a=1&b=2")
    assert status == 200
    assert json.loads(body) == {"sum": 3}


def test_rpc_bridge_error_mapping(server):
    c = _conn(server)
    c.request("POST", "/Calc/Fail", body=b"")
    r = c.getresponse()
    assert r.status == 400
    assert r.getheader("x-rpc-error-code") == "1003"
    assert b"bad calc" in r.read()
    c.close()


def test_http_client_channel(server):
    opts = ChannelOptions()
    opts.protocol = "http"
    ch = Channel(opts)
    assert ch.init(str(server.listen_endpoint)) == 0
    c = ch.call_method("Calc.Echo", b"over-http")
    assert not c.failed, c.error_text
    assert c.response == b"over-http"
    # error propagation carries the rpc code through the http header
    c = ch.call_method("Calc.Fail", b"")
    assert c.failed
    assert c.error_code == 1003


def test_same_port_serves_both_protocols(server):
    # tpu_std client and HTTP client hit the SAME port
    ch = Channel()
    assert ch.init(str(server.listen_endpoint)) == 0
    assert ch.call("Calc.Echo", b"native") == b"native"
    status, body = _get(server, "/health")
    assert status == 200


def test_internal_port_gates_builtin_pages():
    """With internal_port set, operator pages 403 on the public port and
    serve on the internal one; /health stays public (≈ the reference's
    internal-port-only builtin services, server.cpp:1079-1086)."""
    from brpc_tpu.server import ServerOptions

    opts = ServerOptions()
    opts.internal_port = 0          # ephemeral internal port
    srv = Server(opts)
    srv.add_service(Calc())
    assert srv.start("127.0.0.1:0") == 0
    try:
        assert srv.internal_endpoint is not None
        assert srv.internal_endpoint.port != srv.listen_endpoint.port
        status, _ = _get(srv, "/flags")
        assert status == 403
        status, body = _get(srv, "/health")
        assert status == 200 and body == b"OK\n"
        # RPC bridge still works on the public port
        c = _conn(srv)
        c.request("POST", "/Calc/Echo", body=b"ping")
        r = c.getresponse()
        assert r.status == 200 and r.read() == b"ping"
        c.close()
        # internal port serves everything
        iep = srv.internal_endpoint
        ic = http.client.HTTPConnection(iep.host, iep.port, timeout=5)
        ic.request("GET", "/flags")
        r = ic.getresponse()
        assert r.status == 200
        r.read()
        ic.close()
    finally:
        srv.stop()


def test_portal_back_half_pages(server):
    """New portal pages: /sockets, /threads, /protobufs, /vlog, /dir."""
    status, body = _get(server, "/sockets")
    assert status == 200 and b"live sockets" in body
    status, body = _get(server, "/threads")
    assert status == 200 and b"MainThread" in body
    status, body = _get(server, "/protobufs")
    assert status == 200
    import json as _json
    schema = _json.loads(body)
    assert "Calc.Add" in schema
    status, body = _get(server, "/vlog")
    assert status == 200 and b"level=" in body
    status, body = _get(server, "/vlog?setlevel=INFO")
    assert status == 200 and b"INFO" in body
    status, body = _get(server, "/dir")
    assert status == 200
    status, body = _get(server, "/dir/../../etc")
    assert status in (403, 404)


def test_json2pb_bridge():
    """JSON body ⇄ protobuf message conversion on the HTTP bridge
    (≈ /root/reference/src/json2pb/): request JSON parses into the
    method's pb request_type, a pb response renders as JSON."""
    from google.protobuf import struct_pb2

    from brpc_tpu.server import Server, method

    class PbSvc(Service):
        @method(request_type=struct_pb2.Struct)
        def Sum(self, cntl, request):
            out = struct_pb2.Struct()
            out["total"] = request["a"] + request["b"]
            out["who"] = request["who"]
            return out

    srv = Server()
    srv.add_service(PbSvc(), name="PB")
    assert srv.start("127.0.0.1:0") == 0
    try:
        ep = srv.listen_endpoint
        c = http.client.HTTPConnection(ep.host, ep.port, timeout=10)
        c.request("POST", "/PB/Sum",
                  body=json.dumps({"a": 2, "b": 40, "who": "json2pb"}),
                  headers={"content-type": "application/json"})
        r = c.getresponse()
        assert r.status == 200, r.read()
        assert "json" in r.getheader("content-type", "")
        reply = json.loads(r.read())
        c.close()
        assert reply["total"] == 42 and reply["who"] == "json2pb"
        # binary pb still round-trips on the framed path
        from brpc_tpu.client import Channel
        ch = Channel()
        ch.init(str(ep))
        req = struct_pb2.Struct()
        req["a"] = 1; req["b"] = 2; req["who"] = "binary"
        out = ch.call("PB.Sum", req.SerializeToString(),
                      response_type=struct_pb2.Struct)
        assert out["total"] == 3 and out["who"] == "binary"
    finally:
        srv.stop()
