"""Cross-process ICI fabric tests.

Two layers (≈ the reference's RdmaEndpoint TCP-handshake-then-QP shape,
/root/reference/src/brpc/rdma/rdma_endpoint.cpp):

1. REAL subprocess: a tensor-echo server in another interpreter.  The
   domain tokens differ, so the in-process fabric must refuse; with no
   transfer runtime the HOST-STAGED fallback must carry the tensor both
   ways (the ``use_rdma=false`` analogue asserted end to end).
2. Transfer-descriptor wire path: a stand-in transfer fabric (the PJRT
   runtime here lacks the transfer hooks — JaxTransferFabric.supported()
   is probed False) installed on both ends proves the KIND_TRANSFER
   flow: descriptor posted in A, pulled by B via the advertised address,
   TICI ack returns the credit.
"""

import os
import subprocess
import sys
import threading
import time

import jax.numpy as jnp
import numpy as np
import pytest

from brpc_tpu.client import Channel, Controller
from brpc_tpu.ici import fabric as fabric_mod
from brpc_tpu.ici.attachment import KIND_INLINE, KIND_TRANSFER
from brpc_tpu.server import Server, Service

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_CHILD = r"""
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
sys.path.insert(0, %(repo)r)
import jax
jax.config.update("jax_platforms", "cpu")
from brpc_tpu.server import Server, Service

class TensorEcho(Service):
    def Echo(self, cntl, request):
        att = cntl.request_device_attachment
        if att is None:
            return b"no-tensor"
        t = att.tensor()
        cntl.response_device_attachment = t * 2
        return b"doubled"

srv = Server()
srv.add_service(TensorEcho(), name="TE")
assert srv.start("127.0.0.1:0") == 0
print("PORT=%%d" %% srv.listen_endpoint.port, flush=True)
sys.stdin.readline()        # parent closes stdin to stop us
srv.stop()
"""


@pytest.fixture(scope="module")
def child_server():
    proc = subprocess.Popen(
        [sys.executable, "-c", _CHILD % {"repo": REPO}],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL, text=True)
    # the child imports jax (slow under contention) and a bare
    # readline() would block past any deadline; read on a thread so the
    # wait is genuinely bounded, and kill the child if startup fails —
    # an assert before the yield skips the fixture's normal teardown
    got = {"port": None}

    def _read_port():
        for line in proc.stdout:
            if line.startswith("PORT="):
                got["port"] = int(line.strip().split("=")[1])
                return

    reader = threading.Thread(target=_read_port, daemon=True)
    reader.start()
    reader.join(timeout=180)
    if got["port"] is None:
        proc.kill()
        proc.wait(timeout=10)
        raise AssertionError(
            f"child server did not come up (rc={proc.poll()})")
    port = got["port"]
    yield f"127.0.0.1:{port}"
    try:
        proc.stdin.close()
        proc.wait(timeout=10)
    except Exception:
        proc.kill()


def test_cross_process_host_staged_fallback(child_server):
    """Different processes, no transfer runtime: device attachments must
    arrive via the inline fallback and still round-trip correctly."""
    ch = Channel()
    assert ch.init(child_server) == 0
    x = jnp.arange(256, dtype=jnp.float32)
    for i in range(2):                   # first exchanges domains, second
        cntl = Controller()              # knows the peer is foreign
        cntl.timeout_ms = 30_000
        cntl.request_device_attachment = x
        c = ch.call_method("TE.Echo", b"", cntl=cntl)
        assert not c.failed, c.error_text
        assert c.response == b"doubled"
        att = c.response_device_attachment
        assert att is not None
        assert att.kind == KIND_INLINE          # foreign domain ⇒ fallback
        assert not att.device_resident
        np.testing.assert_allclose(np.asarray(att.tensor()),
                                   np.asarray(x) * 2)


# -- KIND_TRANSFER wire path with a stand-in fabric -------------------------

class StandInXfer:
    """In-memory transfer engine with the JaxTransferFabric surface —
    moves arrays by uuid the way the PJRT transfer server would."""

    def __init__(self, addr: bytes):
        self.address = addr
        self._posted = {}
        self._lock = threading.Lock()
        self.pulls = 0
        self._next = 1000

    def post(self, array, nbytes, on_release=None, socket_id=0,
             conn_key=None):
        with self._lock:
            # monotonic like the real fabrics (fabric.py _next_id): a
            # len()-based id COLLIDES when a release lands between two
            # posts — the overwritten entry's on_release never fires and
            # its window credit leaks into every later ici test (this
            # was the round-3/4 order-dependent suite flake)
            uuid = self._next
            self._next += 1
            self._posted[uuid] = (array, nbytes, on_release, socket_id)
        return uuid

    def redeem(self, peer_addr, uuid, specs):
        self.pulls += 1
        with self._lock:
            entry = self._posted.get(uuid)
        assert entry is not None, f"uuid {uuid} not posted"
        return [entry[0]]

    def release(self, uuid, only_socket=None):
        with self._lock:
            entry = self._posted.get(uuid)
            if entry is None:
                return False
            if only_socket is not None and entry[3] != only_socket:
                return False
            del self._posted[uuid]
        if entry[2] is not None:
            entry[2](entry[1])
        return True

    @property
    def live_descriptors(self):
        return len(self._posted)


class XferEcho(Service):
    def Echo(self, cntl, request):
        att = cntl.request_device_attachment
        assert att is not None
        cntl.response_device_attachment = att.tensor() + 1
        return b"plus-one"


@pytest.fixture()
def standin_fabric(monkeypatch):
    fab = StandInXfer(b"standin-addr:7777")
    fabric_mod.set_transfer_fabric(fab)
    # force the "different process" decision: the in-process fast path
    # requires a loopback peer; refusing it here pushes prepare_send to
    # the transfer branch exactly as a foreign-domain peer would
    from brpc_tpu.ici import endpoint as ep_mod
    monkeypatch.setattr(ep_mod, "_is_local_peer", lambda sock: False)
    yield fab
    fabric_mod.set_transfer_fabric(None)
    fabric_mod._xfer_tried = False


def test_transfer_descriptor_path(standin_fabric):
    srv = Server()
    srv.add_service(XferEcho(), name="X")
    assert srv.start("127.0.0.1:0") == 0
    try:
        ch = Channel()
        ch.init(str(srv.listen_endpoint))
        x = jnp.arange(64, dtype=jnp.float32)
        got_kind = None
        for _ in range(2):               # round 1 exchanges domains
            cntl = Controller()
            cntl.timeout_ms = 30_000
            cntl.request_device_attachment = x
            c = ch.call_method("X.Echo", b"", cntl=cntl)
            assert not c.failed, c.error_text
            att = c.response_device_attachment
            assert att is not None
            got_kind = att.kind
            np.testing.assert_allclose(np.asarray(att.tensor()),
                                       np.asarray(x) + 1)
        # once domains are known, payloads ride the transfer fabric
        assert got_kind == KIND_TRANSFER
        assert standin_fabric.pulls >= 2     # request + response legs
        # acks returned every descriptor's credit
        deadline = time.time() + 5
        while standin_fabric.live_descriptors and time.time() < deadline:
            time.sleep(0.01)
        assert standin_fabric.live_descriptors == 0
    finally:
        srv.stop()


def test_transfer_domain_advertised(standin_fabric):
    d = fabric_mod.local_domain_id()
    assert d.endswith(b"@standin-addr:7777")
    assert fabric_mod.peer_transfer_addr(d) == b"standin-addr:7777"
    assert fabric_mod.peer_transfer_addr(b"plain-token") is None
    # foreign token with an address: unreachable in-process, pullable
    assert not fabric_mod.in_process_fabric().can_reach(
        b"other-token@addr:1")
