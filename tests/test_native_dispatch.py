"""GIL-free native dispatch — @raw_method(native=...) answered by the
C++ engine (engine.cpp native_try_handle), the tpu-native analogue of
the reference's built-in C++ services.

Contract under test (service.py raw_method docstring): the Python
handler is the behavioral spec; the native answer must be
indistinguishable from the Python answer, and every fallback condition
(rpc_dump capture, controller-tier request features, concurrency
limits) must land the request back on the Python handler.
"""

import threading

import pytest

from brpc_tpu.client import Channel, ChannelOptions, Controller
from brpc_tpu.client.channel import RpcError
from brpc_tpu.server import Server, ServerOptions, Service
from brpc_tpu.server.service import raw_method

pytestmark = []

from conftest import require_native  # noqa: E402


class NativeEcho(Service):
    def __init__(self):
        self.python_hits = 0

    @raw_method(native="echo")
    def Echo(self, payload, attachment):
        self.python_hits += 1
        return payload, attachment

    @raw_method(native="const")
    def Ping(self, payload, attachment):
        self.python_hits += 1
        return b"pong"

    @raw_method
    def PyOnly(self, payload, attachment):
        return bytes(payload)[::-1]


@pytest.fixture()
def native_server():
    require_native()
    opts = ServerOptions()
    opts.native = True
    opts.usercode_inline = True
    svc = NativeEcho()
    srv = Server(opts)
    srv.add_service(svc, name="N")
    assert srv.start("127.0.0.1:0") == 0
    svc.python_hits = 0       # const-capture at registration ran Ping once
    yield srv, svc
    srv.stop()


def _ch(srv):
    ch = Channel()
    ch.init(str(srv.listen_endpoint))
    return ch


def _native_count(srv, name):
    stats = srv._native_bridge.engine.native_stats()
    return stats.get(name, (0, 0))


def test_native_echo_answers_without_python(native_server):
    srv, svc = native_server
    ch = _ch(srv)
    att = bytes(range(256)) * 4
    for i in range(5):
        resp, ratt = ch.call_raw("N.Echo", b"hello%d" % i, att,
                                 timeout_ms=5_000)
        assert bytes(resp) == b"hello%d" % i
        assert bytes(ratt) == att
    assert svc.python_hits == 0, "native-dispatched calls entered Python"
    count, errors = _native_count(srv, "N.Echo")
    assert count == 5 and errors == 0


def test_native_echo_no_attachment(native_server):
    srv, svc = native_server
    ch = _ch(srv)
    resp, ratt = ch.call_raw("N.Echo", b"solo", timeout_ms=5_000)
    assert bytes(resp) == b"solo" and len(ratt) == 0
    assert svc.python_hits == 0


def test_native_const(native_server):
    srv, svc = native_server
    ch = _ch(srv)
    resp, ratt = ch.call_raw("N.Ping", b"ignored", timeout_ms=5_000)
    assert bytes(resp) == b"pong" and len(ratt) == 0
    assert svc.python_hits == 0
    assert _native_count(srv, "N.Ping")[0] == 1


def test_plain_raw_method_rides_engine_kind2(native_server):
    """A plain @raw_method (no native= tag) is registered as kind 2:
    the engine calls the Python handler from the loop thread (burst-
    batched GIL entry) and builds the response frame natively.  The
    handler still runs — and the call is counted on the native lane."""
    srv, svc = native_server
    ch = _ch(srv)
    resp, _ = ch.call_raw("N.PyOnly", b"abc", timeout_ms=5_000)
    assert bytes(resp) == b"cba"
    assert _native_count(srv, "N.PyOnly") == (1, 0)


def test_native_large_attachment_zero_copy_path(native_server):
    """A 1MB attachment exercises the engine's direct-read completion
    (the zero-copy response path referencing the request buffer).
    The shm data plane is gated off: this test pins the BYTE lane's
    all-C++ path (an eligible shm attachment would ride a descriptor
    through the Python dispatch instead — tests/test_data_plane.py
    owns that lane)."""
    from brpc_tpu.butil.flags import get_flag, set_flag
    from brpc_tpu.transport import shm_ring  # noqa: F401 — defines the
    #                          flag; set_flag on an undefined flag no-ops
    saved = get_flag("rpc_shm_data_plane")
    assert saved is not None
    set_flag("rpc_shm_data_plane", False)
    try:
        _run_large_attachment_check(native_server)
    finally:
        set_flag("rpc_shm_data_plane", saved)


def _run_large_attachment_check(native_server):
    srv, svc = native_server
    ch = _ch(srv)
    att = bytes(1 << 20)
    resp, ratt = ch.call_raw("N.Echo", b"big", att, timeout_ms=20_000)
    assert bytes(resp) == b"big"
    assert len(ratt) == len(att) and bytes(ratt[:64]) == att[:64]
    assert svc.python_hits == 0


def test_native_malformed_attachment_rejected(native_server):
    import socket as pysock
    import struct

    from brpc_tpu.butil.status import Errno
    from brpc_tpu.protocol.meta import (RpcMeta, TLV_ATTACHMENT,
                                        TLV_CORRELATION, encode_tlv)

    srv, svc = native_server
    ep = srv.listen_endpoint
    with pysock.create_connection((str(ep.host), ep.port), timeout=5) as c:
        mb = (TLV_CORRELATION + struct.pack("<Q", 3)
              + TLV_ATTACHMENT + struct.pack("<I", 999)
              + encode_tlv(4, b"N") + encode_tlv(5, b"Echo"))
        c.sendall(b"TRPC" + struct.pack("<II", len(mb) + 4, len(mb))
                  + mb + b"zzzz")
        c.settimeout(5)
        buf = b""
        while len(buf) < 12:
            buf += c.recv(4096)
        blen, mlen = struct.unpack_from("<II", buf, 4)
        while len(buf) < 12 + blen:
            buf += c.recv(4096)
        meta = RpcMeta.decode(buf[12:12 + mlen])
        assert meta.correlation_id == 3
        assert meta.error_code == int(Errno.EREQUEST)
    assert _native_count(srv, "N.Echo")[1] == 1    # errors counter
    assert svc.python_hits == 0


def test_traced_request_falls_back_to_python(native_server):
    """A controller-tier tag (trace id) in the meta must bypass native
    dispatch AND the Python raw lane's slim path contract still holds."""
    srv, svc = native_server
    ch = _ch(srv)
    cntl = Controller()
    cntl.timeout_ms = 5_000
    cntl.trace_id = 77
    c = ch.call_method("N.Echo", b"traced", cntl=cntl)
    assert not c.failed and bytes(c.response) == b"traced"
    assert svc.python_hits == 1
    assert _native_count(srv, "N.Echo")[0] == 0


def test_rpc_dump_toggle_disables_native_dispatch(native_server, tmp_path):
    """Live traffic capture must see every request: flipping the
    rpc_dump flag routes natively-registered methods back to Python,
    and flipping it off restores the native lane."""
    from brpc_tpu.butil.flags import set_flag
    from brpc_tpu.tools.rpc_dump import close_dump

    srv, svc = native_server
    ch = _ch(srv)
    ch.call_raw("N.Echo", b"a", timeout_ms=5_000)
    assert svc.python_hits == 0
    set_flag("rpc_dump_dir", str(tmp_path))
    assert set_flag("rpc_dump", True)
    try:
        # dump capture observes the RpcMessage on the full path — the
        # request must reach Python now
        resp, _ = ch.call_raw("N.Echo", b"b", timeout_ms=5_000)
        assert bytes(resp) == b"b"
        assert svc.python_hits == 1
    finally:
        assert set_flag("rpc_dump", False)
        close_dump()      # the shared dump file must not leak frames
                          # into later tests' captures
    ch.call_raw("N.Echo", b"c", timeout_ms=5_000)
    assert svc.python_hits == 1          # back to native


def test_native_batch_pipelined(native_server):
    """call_batch through the fully-native lane: frames built, written,
    read and cid-matched in C++; mixed with a Python-dispatched method
    to prove cid matching survives out-of-order-capable serving."""
    srv, svc = native_server
    ch = _ch(srv)
    reqs = [b"m%04d" % i for i in range(300)]
    out = ch.call_batch("N.Echo", reqs, timeout_ms=10_000)
    assert len(out) == 300
    assert all(bytes(o) == r for o, r in zip(out, reqs))
    assert svc.python_hits == 0
    assert _native_count(srv, "N.Echo")[0] == 300
    # python-path batch on the same connection still works after
    out2 = ch.call_batch("N.PyOnly", [b"ab", b"cd"], timeout_ms=10_000)
    assert [bytes(o) for o in out2] == [b"ba", b"dc"]


def test_native_batch_error_item(native_server):
    """A batch whose method hits the Python error path must still raise
    RpcError (the native lane returns the full frame for decode)."""
    srv, svc = native_server
    ch = _ch(srv)
    with pytest.raises(RpcError):
        ch.call_batch("N.Nope", [b"x"], timeout_ms=5_000)


def test_concurrency_limited_method_not_registered():
    """A per-method concurrency limit keeps admission in Python: the
    method must NOT be handed to the native engine."""
    require_native()
    opts = ServerOptions()
    opts.native = True
    opts.usercode_inline = True
    opts.method_max_concurrency = {"N.Echo": 4}
    svc = NativeEcho()
    srv = Server(opts)
    srv.add_service(svc, name="N")
    assert srv.start("127.0.0.1:0") == 0
    svc.python_hits = 0       # const-capture at registration ran Ping once
    try:
        ch = _ch(srv)
        resp, _ = ch.call_raw("N.Echo", b"x", timeout_ms=5_000)
        assert bytes(resp) == b"x"
        assert svc.python_hits == 1      # served by Python, limit intact
    finally:
        srv.stop()


def test_native_dispatch_concurrent_callers(native_server):
    """Several threads hammering the native lane on their own pinned
    connections — exercises the coalesced native_flush under load."""
    srv, svc = native_server
    errors = []

    def work(tid):
        try:
            ch = _ch(srv)
            att = bytes(100) * (tid + 1)
            for i in range(50):
                resp, ratt = ch.call_raw("N.Echo", b"t%d" % tid, att,
                                         timeout_ms=10_000)
                assert bytes(resp) == b"t%d" % tid
                assert len(ratt) == len(att)
        except Exception as e:      # pragma: no cover
            errors.append(e)

    ts = [threading.Thread(target=work, args=(t,)) for t in range(4)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert not errors
    assert svc.python_hits == 0
    assert _native_count(srv, "N.Echo")[0] == 200


def test_slim_and_raw_coexist_one_server():
    """A service mixing raw methods and plain (cntl, request) methods:
    raw rides kinds 0/2, plain rides the slim lane (kind 3) — on the
    same connection, interleaved."""
    require_native()

    class Mixed(Service):
        @raw_method
        def Raw(self, payload, attachment):
            return bytes(payload) + b"!"

        def Full(self, cntl, request):
            return b"full:" + bytes(request)

    opts = ServerOptions()
    opts.native = True
    opts.usercode_inline = True
    srv = Server(opts)
    srv.add_service(Mixed(), name="X")
    assert srv.start("127.0.0.1:0") == 0
    try:
        ch = _ch(srv)
        for i in range(3):
            r, _ = ch.call_raw("X.Raw", b"r%d" % i, timeout_ms=5_000)
            assert bytes(r) == b"r%d!" % i
            c = ch.call_method("X.Full", b"f%d" % i, cntl=Controller())
            assert not c.failed and bytes(c.response) == b"full:f%d" % i
        assert _native_count(srv, "X.Raw")[0] == 3
        assert _native_count(srv, "X.Full")[0] == 3
    finally:
        srv.stop()


def test_slim_pipelined_batch():
    """call_batch against a plain (cntl, request) method: the whole
    burst is parsed by the engine and dispatched through the slim shim
    in batched GIL entries, responses cid-matched."""
    require_native()

    class Plain(Service):
        def Ident(self, cntl, request):
            return bytes(request)

    opts = ServerOptions()
    opts.native = True
    opts.usercode_inline = True
    srv = Server(opts)
    srv.add_service(Plain(), name="B")
    assert srv.start("127.0.0.1:0") == 0
    try:
        ch = _ch(srv)
        reqs = [b"b%04d" % i for i in range(300)]
        out = ch.call_batch("B.Ident", reqs, timeout_ms=10_000)
        assert len(out) == 300
        assert all(bytes(o) == r for o, r in zip(out, reqs))
        assert _native_count(srv, "B.Ident")[0] >= 1   # slim lane used
    finally:
        srv.stop()


def test_malformed_meta_never_crashes_engine(native_server):
    """Fuzz-shaped metas against the native scanner: truncated TLV
    lengths, zero-length names, lengths past the body — the engine must
    answer something sane or drop the conn, never wedge the server."""
    import socket as pysock
    import struct

    srv, svc = native_server
    ep = srv.listen_endpoint

    def frame(meta, payload=b"x"):
        return (b"TRPC" + struct.pack("<II", len(meta) + len(payload),
                                      len(meta)) + meta + payload)

    evil_metas = [
        b"\x01\xff\xff\xff\xff",              # TLV len 4GB, no data
        b"\x01\x08\x00\x00\x00" + b"\x01",    # cid TLV truncated
        b"\x04\x00\x00\x00\x00\x05\x00\x00\x00\x00",  # empty svc+mth
        b"\x63\x04\x00\x00\x00abcd",          # unknown tag 0x63
        b"",                                   # empty meta
    ]
    for meta in evil_metas:
        with pysock.create_connection((str(ep.host), ep.port),
                                      timeout=5) as c:
            c.sendall(frame(meta))
            c.settimeout(2)
            try:
                c.recv(4096)       # error frame or EOF — both fine
            except (TimeoutError, ConnectionError, OSError):
                pass
    # the server is still fully alive for well-formed traffic
    ch = _ch(srv)
    resp, _ = ch.call_raw("N.Echo", b"alive", timeout_ms=5_000)
    assert bytes(resp) == b"alive"
