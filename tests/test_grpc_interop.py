"""HTTP/2 + gRPC interop — the real ``grpcio`` package as the oracle.

Both directions (≈ /root/reference/test/brpc_grpc_protocol_unittest.cpp
intent): a grpcio client calls a brpc_tpu server, and the brpc_tpu h2
client calls a grpcio server.  Raw-bytes (identity) serializers keep
protobuf codegen out of the way — the wire mechanics (h2 framing,
HPACK, grpc message framing, trailers) are what is under test.
"""

import threading
import time

import grpc
import pytest

from brpc_tpu.client import Channel, ChannelOptions, Controller
from brpc_tpu.server import Server, Service

_ident = lambda b: b  # noqa: E731


class EchoSvc(Service):
    def Echo(self, cntl, request):
        return request

    def Upper(self, cntl, request):
        return request.upper()

    def Fail(self, cntl, request):
        cntl.set_failed(1003, "bad arg here")
        return None


@pytest.fixture(scope="module")
def server():
    srv = Server()
    srv.add_service(EchoSvc(), name="EchoSvc")
    assert srv.start("127.0.0.1:0") == 0
    yield srv
    srv.stop()


# -- direction 1: grpcio client -> brpc_tpu server -------------------------

def _grpcio_call(server, method: str, payload: bytes, timeout=10):
    ep = server.listen_endpoint
    with grpc.insecure_channel(f"{ep.host}:{ep.port}") as ch:
        fn = ch.unary_unary(method,
                            request_serializer=_ident,
                            response_deserializer=_ident)
        return fn(payload, timeout=timeout)


def test_grpcio_client_unary_echo(server):
    got = _grpcio_call(server, "/EchoSvc/Echo", b"hello-over-grpc")
    assert got == b"hello-over-grpc"


def test_grpcio_client_large_payload(server):
    """Bigger than one h2 frame AND the 64KB initial stream window —
    exercises CONTINUATION-free chunked DATA + flow control."""
    payload = bytes(range(256)) * 4096          # 1MB
    got = _grpcio_call(server, "/EchoSvc/Echo", payload, timeout=30)
    assert got == payload


def test_grpcio_client_package_qualified_path(server):
    got = _grpcio_call(server, "/some.pkg.EchoSvc/Upper", b"abc")
    assert got == b"ABC"


def test_grpcio_client_unknown_method(server):
    with pytest.raises(grpc.RpcError) as ei:
        _grpcio_call(server, "/EchoSvc/Nope", b"x")
    assert ei.value.code() == grpc.StatusCode.UNIMPLEMENTED


def test_grpcio_client_application_error_maps_status(server):
    with pytest.raises(grpc.RpcError) as ei:
        _grpcio_call(server, "/EchoSvc/Fail", b"x")
    assert ei.value.code() == grpc.StatusCode.INVALID_ARGUMENT
    assert "bad arg" in (ei.value.details() or "")


def test_grpcio_client_many_sequential_calls(server):
    """Dynamic HPACK table reuse + stream id growth on one connection."""
    ep = server.listen_endpoint
    with grpc.insecure_channel(f"{ep.host}:{ep.port}") as ch:
        fn = ch.unary_unary("/EchoSvc/Echo", request_serializer=_ident,
                            response_deserializer=_ident)
        for i in range(50):
            assert fn(b"m%d" % i, timeout=10) == b"m%d" % i


def test_grpcio_client_concurrent_streams(server):
    ep = server.listen_endpoint
    errors = []
    with grpc.insecure_channel(f"{ep.host}:{ep.port}") as ch:
        fn = ch.unary_unary("/EchoSvc/Echo", request_serializer=_ident,
                            response_deserializer=_ident)

        def worker(i):
            try:
                body = bytes([i]) * 10000
                assert fn(body, timeout=20) == body
            except Exception as e:
                errors.append(e)

        ts = [threading.Thread(target=worker, args=(i,)) for i in range(8)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
    assert not errors, errors


# -- direction 2: brpc_tpu h2 client -> grpcio server ----------------------

class _GrpcioEcho(grpc.GenericRpcHandler):
    def service(self, handler_call_details):
        method = handler_call_details.method
        if method == "/oracle.Echo/Echo":
            return grpc.unary_unary_rpc_method_handler(
                lambda req, ctx: req,
                request_deserializer=_ident, response_serializer=_ident)
        if method == "/oracle.Echo/Fail":
            def fail(req, ctx):
                ctx.abort(grpc.StatusCode.RESOURCE_EXHAUSTED, "nope")
            return grpc.unary_unary_rpc_method_handler(
                fail, request_deserializer=_ident,
                response_serializer=_ident)
        return None


@pytest.fixture(scope="module")
def grpcio_server():
    from concurrent import futures
    srv = grpc.server(futures.ThreadPoolExecutor(max_workers=4))
    srv.add_generic_rpc_handlers((_GrpcioEcho(),))
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    yield port
    srv.stop(0)


def test_our_client_against_grpcio_server(grpcio_server):
    from brpc_tpu.butil.endpoint import parse_endpoint
    from brpc_tpu.client.grpc_client import GrpcConnection

    conn = GrpcConnection(parse_endpoint(f"127.0.0.1:{grpcio_server}"))
    try:
        status, msg, body = conn.unary_call("/oracle.Echo/Echo",
                                            b"ping-from-tpu", 10.0)
        assert status == 0, (status, msg)
        assert body == b"ping-from-tpu"
        # large payload through the oracle server
        big = bytes(200000)
        status, msg, body = conn.unary_call("/oracle.Echo/Echo", big, 30.0)
        assert status == 0, (status, msg)
        assert body == big
        # error mapping
        status, msg, body = conn.unary_call("/oracle.Echo/Fail", b"x", 10.0)
        assert status == 8, (status, msg)
        assert "nope" in msg
    finally:
        conn.close()


def test_many_connections_share_one_reader_thread(grpcio_server):
    """N concurrent GrpcConnections must not spawn N reader threads:
    the shared selector loop serves them all (pod-scale peer sets)."""
    from brpc_tpu.butil.endpoint import parse_endpoint
    from brpc_tpu.client.grpc_client import GrpcConnection

    before = {t.name for t in threading.enumerate()}
    conns = [GrpcConnection(parse_endpoint(f"127.0.0.1:{grpcio_server}"))
             for _ in range(8)]
    try:
        for i, conn in enumerate(conns):
            status, msg, body = conn.unary_call(
                "/oracle.Echo/Echo", f"c{i}".encode(), 10.0)
            assert status == 0, (status, msg)
            assert body == f"c{i}".encode()
        after = [t.name for t in threading.enumerate()
                 if t.name not in before]
        readers = [n for n in after if "reader" in n]
        assert readers in ([], ["grpc_shared_reader"]), readers
    finally:
        for conn in conns:
            conn.close()


def test_channel_protocol_grpc_end_to_end(grpcio_server):
    opts = ChannelOptions()
    opts.protocol = "grpc"
    ch = Channel(opts)
    assert ch.init(f"127.0.0.1:{grpcio_server}") == 0
    c = ch.call_method("oracle.Echo.Echo", b"via-channel")
    assert not c.failed, c.error_text
    assert c.response == b"via-channel"
    c = ch.call_method("oracle.Echo.Fail", b"x")
    assert c.failed and "grpc-status 8" in c.error_text


def test_channel_grpc_against_our_server(server):
    """Full circle: our Channel speaking gRPC to our own h2 server."""
    opts = ChannelOptions()
    opts.protocol = "grpc"
    ch = Channel(opts)
    ep = server.listen_endpoint
    assert ch.init(f"{ep.host}:{ep.port}") == 0
    c = ch.call_method("EchoSvc.Echo", b"self-grpc")
    assert not c.failed, c.error_text
    assert c.response == b"self-grpc"


# -- streaming: grpcio client -> brpc_tpu server ----------------------------

from brpc_tpu.server import grpc_streaming  # noqa: E402


class StreamSvc(Service):
    @grpc_streaming
    def Countdown(self, cntl, msgs):
        # server-streaming: one request message, N pushed responses
        first = msgs.read()
        for i in range(int(first or b"0"), 0, -1):
            cntl.grpc_stream.write(b"%d" % i)
        return None

    @grpc_streaming
    def Sum(self, cntl, msgs):
        # client-streaming: consume all, single response via return
        return b"%d" % sum(int(m) for m in msgs)

    @grpc_streaming
    def Chat(self, cntl, msgs):
        # bidi: answer each message as it arrives
        for m in msgs:
            cntl.grpc_stream.write(m.upper())
        return None

    @grpc_streaming
    def FailMid(self, cntl, msgs):
        cntl.grpc_stream.write(b"one")
        cntl.set_failed(1003, "stream failed midway")
        return None


@pytest.fixture(scope="module")
def stream_server():
    srv = Server()
    srv.add_service(StreamSvc(), name="S")
    assert srv.start("127.0.0.1:0") == 0
    yield srv
    srv.stop()


def _grpc_channel(server):
    ep = server.listen_endpoint
    return grpc.insecure_channel(f"{ep.host}:{ep.port}")


def test_grpcio_server_streaming(stream_server):
    with _grpc_channel(stream_server) as ch:
        fn = ch.unary_stream("/S/Countdown", request_serializer=_ident,
                             response_deserializer=_ident)
        got = list(fn(b"4", timeout=10))
    assert got == [b"4", b"3", b"2", b"1"]


def test_grpcio_client_streaming(stream_server):
    with _grpc_channel(stream_server) as ch:
        fn = ch.stream_unary("/S/Sum", request_serializer=_ident,
                             response_deserializer=_ident)
        got = fn(iter([b"1", b"2", b"3", b"4"]), timeout=10)
    assert got == b"10"


def test_grpcio_bidi_streaming(stream_server):
    with _grpc_channel(stream_server) as ch:
        fn = ch.stream_stream("/S/Chat", request_serializer=_ident,
                              response_deserializer=_ident)
        got = list(fn(iter([b"alpha", b"beta", b"gamma"]), timeout=10))
    assert got == [b"ALPHA", b"BETA", b"GAMMA"]


def test_grpcio_streaming_error_propagates(stream_server):
    with _grpc_channel(stream_server) as ch:
        fn = ch.unary_stream("/S/FailMid", request_serializer=_ident,
                             response_deserializer=_ident)
        it = fn(b"", timeout=10)
        assert next(it) == b"one"
        with pytest.raises(grpc.RpcError) as ei:
            list(it)
        assert ei.value.code() == grpc.StatusCode.INVALID_ARGUMENT


def test_grpcio_large_server_stream(stream_server):
    """Many pushed messages > initial window: flow control on streams."""
    with _grpc_channel(stream_server) as ch:
        fn = ch.stream_stream("/S/Chat", request_serializer=_ident,
                              response_deserializer=_ident)
        reqs = [bytes([65 + (i % 26)]) * 8000 for i in range(40)]  # ~320KB
        got = list(fn(iter(reqs), timeout=30))
    assert got == [r.upper() for r in reqs]


# -- streaming: brpc_tpu client -> grpcio server ----------------------------

class _GrpcioStreams(grpc.GenericRpcHandler):
    def service(self, handler_call_details):
        m = handler_call_details.method
        if m == "/oracle.S/Count":
            def count(req, ctx):
                for i in range(int(req or b"0")):
                    yield b"tick%d" % i
            return grpc.unary_stream_rpc_method_handler(
                count, request_deserializer=_ident,
                response_serializer=_ident)
        if m == "/oracle.S/Join":
            def join(req_iter, ctx):
                return b",".join(req_iter)
            return grpc.stream_unary_rpc_method_handler(
                join, request_deserializer=_ident,
                response_serializer=_ident)
        if m == "/oracle.S/Rev":
            def rev(req_iter, ctx):
                for r in req_iter:
                    yield r[::-1]
            return grpc.stream_stream_rpc_method_handler(
                rev, request_deserializer=_ident,
                response_serializer=_ident)
        return None


@pytest.fixture(scope="module")
def grpcio_stream_server():
    from concurrent import futures
    srv = grpc.server(futures.ThreadPoolExecutor(max_workers=4))
    srv.add_generic_rpc_handlers((_GrpcioStreams(),))
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    yield port
    srv.stop(0)


def _our_conn(port):
    from brpc_tpu.butil.endpoint import parse_endpoint
    from brpc_tpu.client.grpc_client import GrpcConnection
    return GrpcConnection(parse_endpoint(f"127.0.0.1:{port}"))


def test_our_client_server_streaming(grpcio_stream_server):
    conn = _our_conn(grpcio_stream_server)
    try:
        call = conn.streaming_call("/oracle.S/Count", 10.0)
        call.write(b"3")
        call.done_writing()
        assert list(call) == [b"tick0", b"tick1", b"tick2"]
        assert call.status() == 0, call.message()
    finally:
        conn.close()


def test_our_client_client_streaming(grpcio_stream_server):
    conn = _our_conn(grpcio_stream_server)
    try:
        call = conn.streaming_call("/oracle.S/Join", 10.0)
        for part in (b"a", b"b", b"c"):
            call.write(part)
        call.done_writing()
        assert list(call) == [b"a,b,c"]
        assert call.status() == 0, call.message()
    finally:
        conn.close()


def test_our_client_bidi(grpcio_stream_server):
    conn = _our_conn(grpcio_stream_server)
    try:
        call = conn.streaming_call("/oracle.S/Rev", 10.0)
        call.write(b"abc")
        assert call.read() == b"cba"
        call.write(b"hello")
        assert call.read() == b"olleh"
        call.done_writing()
        assert call.read() is None
        assert call.status() == 0, call.message()
    finally:
        conn.close()


def test_our_client_streaming_against_our_server(stream_server):
    """Full circle: our streaming client against our streaming server."""
    from brpc_tpu.client.grpc_client import GrpcConnection
    from brpc_tpu.butil.endpoint import parse_endpoint
    ep = stream_server.listen_endpoint
    conn = GrpcConnection(parse_endpoint(f"{ep.host}:{ep.port}"))
    try:
        call = conn.streaming_call("/S/Chat", 10.0)
        call.write(b"xyz")
        assert call.read() == b"XYZ"
        call.write(b"q")
        assert call.read() == b"Q"
        call.done_writing()
        assert call.read() is None
        assert call.status() == 0, call.message()
        # client-streaming shape through Channel sugar
        opts = ChannelOptions()
        opts.protocol = "grpc"
        ch2 = Channel(opts)
        assert ch2.init(f"{ep.host}:{ep.port}") == 0
        call = ch2.grpc_stream("S.Sum")
        for i in (b"5", b"6"):
            call.write(i)
        call.done_writing()
        assert list(call) == [b"11"]
    finally:
        conn.close()
