"""HTTP/2 + gRPC interop — the real ``grpcio`` package as the oracle.

Both directions (≈ /root/reference/test/brpc_grpc_protocol_unittest.cpp
intent): a grpcio client calls a brpc_tpu server, and the brpc_tpu h2
client calls a grpcio server.  Raw-bytes (identity) serializers keep
protobuf codegen out of the way — the wire mechanics (h2 framing,
HPACK, grpc message framing, trailers) are what is under test.
"""

import threading
import time

import grpc
import pytest

from brpc_tpu.client import Channel, ChannelOptions, Controller
from brpc_tpu.server import Server, Service

_ident = lambda b: b  # noqa: E731


class EchoSvc(Service):
    def Echo(self, cntl, request):
        return request

    def Upper(self, cntl, request):
        return request.upper()

    def Fail(self, cntl, request):
        cntl.set_failed(1003, "bad arg here")
        return None


@pytest.fixture(scope="module")
def server():
    srv = Server()
    srv.add_service(EchoSvc(), name="EchoSvc")
    assert srv.start("127.0.0.1:0") == 0
    yield srv
    srv.stop()


# -- direction 1: grpcio client -> brpc_tpu server -------------------------

def _grpcio_call(server, method: str, payload: bytes, timeout=10):
    ep = server.listen_endpoint
    with grpc.insecure_channel(f"{ep.host}:{ep.port}") as ch:
        fn = ch.unary_unary(method,
                            request_serializer=_ident,
                            response_deserializer=_ident)
        return fn(payload, timeout=timeout)


def test_grpcio_client_unary_echo(server):
    got = _grpcio_call(server, "/EchoSvc/Echo", b"hello-over-grpc")
    assert got == b"hello-over-grpc"


def test_grpcio_client_large_payload(server):
    """Bigger than one h2 frame AND the 64KB initial stream window —
    exercises CONTINUATION-free chunked DATA + flow control."""
    payload = bytes(range(256)) * 4096          # 1MB
    got = _grpcio_call(server, "/EchoSvc/Echo", payload, timeout=30)
    assert got == payload


def test_grpcio_client_package_qualified_path(server):
    got = _grpcio_call(server, "/some.pkg.EchoSvc/Upper", b"abc")
    assert got == b"ABC"


def test_grpcio_client_unknown_method(server):
    with pytest.raises(grpc.RpcError) as ei:
        _grpcio_call(server, "/EchoSvc/Nope", b"x")
    assert ei.value.code() == grpc.StatusCode.UNIMPLEMENTED


def test_grpcio_client_application_error_maps_status(server):
    with pytest.raises(grpc.RpcError) as ei:
        _grpcio_call(server, "/EchoSvc/Fail", b"x")
    assert ei.value.code() == grpc.StatusCode.INVALID_ARGUMENT
    assert "bad arg" in (ei.value.details() or "")


def test_grpcio_client_many_sequential_calls(server):
    """Dynamic HPACK table reuse + stream id growth on one connection."""
    ep = server.listen_endpoint
    with grpc.insecure_channel(f"{ep.host}:{ep.port}") as ch:
        fn = ch.unary_unary("/EchoSvc/Echo", request_serializer=_ident,
                            response_deserializer=_ident)
        for i in range(50):
            assert fn(b"m%d" % i, timeout=10) == b"m%d" % i


def test_grpcio_client_concurrent_streams(server):
    ep = server.listen_endpoint
    errors = []
    with grpc.insecure_channel(f"{ep.host}:{ep.port}") as ch:
        fn = ch.unary_unary("/EchoSvc/Echo", request_serializer=_ident,
                            response_deserializer=_ident)

        def worker(i):
            try:
                body = bytes([i]) * 10000
                assert fn(body, timeout=20) == body
            except Exception as e:
                errors.append(e)

        ts = [threading.Thread(target=worker, args=(i,)) for i in range(8)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
    assert not errors, errors


# -- direction 2: brpc_tpu h2 client -> grpcio server ----------------------

class _GrpcioEcho(grpc.GenericRpcHandler):
    def service(self, handler_call_details):
        method = handler_call_details.method
        if method == "/oracle.Echo/Echo":
            return grpc.unary_unary_rpc_method_handler(
                lambda req, ctx: req,
                request_deserializer=_ident, response_serializer=_ident)
        if method == "/oracle.Echo/Fail":
            def fail(req, ctx):
                ctx.abort(grpc.StatusCode.RESOURCE_EXHAUSTED, "nope")
            return grpc.unary_unary_rpc_method_handler(
                fail, request_deserializer=_ident,
                response_serializer=_ident)
        return None


@pytest.fixture(scope="module")
def grpcio_server():
    from concurrent import futures
    srv = grpc.server(futures.ThreadPoolExecutor(max_workers=4))
    srv.add_generic_rpc_handlers((_GrpcioEcho(),))
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    yield port
    srv.stop(0)


def test_our_client_against_grpcio_server(grpcio_server):
    from brpc_tpu.butil.endpoint import parse_endpoint
    from brpc_tpu.client.grpc_client import GrpcConnection

    conn = GrpcConnection(parse_endpoint(f"127.0.0.1:{grpcio_server}"))
    try:
        status, msg, body = conn.unary_call("/oracle.Echo/Echo",
                                            b"ping-from-tpu", 10.0)
        assert status == 0, (status, msg)
        assert body == b"ping-from-tpu"
        # large payload through the oracle server
        big = bytes(200000)
        status, msg, body = conn.unary_call("/oracle.Echo/Echo", big, 30.0)
        assert status == 0, (status, msg)
        assert body == big
        # error mapping
        status, msg, body = conn.unary_call("/oracle.Echo/Fail", b"x", 10.0)
        assert status == 8, (status, msg)
        assert "nope" in msg
    finally:
        conn.close()


def test_channel_protocol_grpc_end_to_end(grpcio_server):
    opts = ChannelOptions()
    opts.protocol = "grpc"
    ch = Channel(opts)
    assert ch.init(f"127.0.0.1:{grpcio_server}") == 0
    c = ch.call_method("oracle.Echo.Echo", b"via-channel")
    assert not c.failed, c.error_text
    assert c.response == b"via-channel"
    c = ch.call_method("oracle.Echo.Fail", b"x")
    assert c.failed and "grpc-status 8" in c.error_text


def test_channel_grpc_against_our_server(server):
    """Full circle: our Channel speaking gRPC to our own h2 server."""
    opts = ChannelOptions()
    opts.protocol = "grpc"
    ch = Channel(opts)
    ep = server.listen_endpoint
    assert ch.init(f"{ep.host}:{ep.port}") == 0
    c = ch.call_method("EchoSvc.Echo", b"self-grpc")
    assert not c.failed, c.error_text
    assert c.response == b"self-grpc"
