"""Overload plane — the shared admission stage on all five dispatch
paths (ISSUE 7 acceptance matrix).

Mirrors test_deadline_plane's shape: each of the four rejection causes
— server cap, adaptive/static method cap, CoDel queue discipline, and
per-tenant fair-admission quota — is observed on every server dispatch
path (classic tpu_std, the slim kind-3 native lane, classic HTTP/1.1,
the kind-4 slim HTTP lane, gRPC over h2) with the correct error
(ELIMIT frame / 503 + Retry-After / grpc-status 8), rejected BEFORE
user code runs, and counted in ``overload_admission_total`` under a
closed verdict enum (no "unknown" bucket possible).  Tenant-stamped
traffic must keep riding the native lanes with zero new fallbacks.
"""

import socket as pysock
import struct
import threading
import time

import pytest

from brpc_tpu.butil.flags import get_flag, set_flag
from brpc_tpu.butil.status import Errno
from brpc_tpu.client import Channel, ChannelOptions, Controller
from brpc_tpu.protocol.meta import (RpcMeta, TLV_CORRELATION, encode_tlv)
from brpc_tpu.server import Server, ServerOptions, Service
from brpc_tpu.server.admission import (ADMITTED, CODEL, METHOD_CAP,
                                       SERVER_CAP, TENANT_QUOTA, VERDICTS,
                                       admission_counters,
                                       normalize_tenant)

from conftest import require_native  # noqa: E402

ELIMIT = int(Errno.ELIMIT)


class OvSvc(Service):
    def __init__(self):
        self.echo_calls = []
        self.parked = []
        self._plock = threading.Lock()

    def Echo(self, cntl, request):
        self.echo_calls.append(bytes(request))
        return b"ok:" + bytes(request)

    def Park(self, cntl, request):
        """Async occupancy: holds one admission slot until released —
        works on single-loop inline native servers, where a blocking
        handler would stall the probe itself."""
        cntl.begin_async()
        with self._plock:
            self.parked.append(cntl)
        return None

    def release_parked(self):
        with self._plock:
            parked, self.parked = self.parked, []
        for c in parked:
            c.finish(b"released")


def _server(native: bool, **opt_kv):
    opts = ServerOptions()
    if native:
        opts.native = True
        opts.usercode_inline = True
        opts.native_loops = 1
    for k, v in opt_kv.items():
        setattr(opts, k, v)
    svc = OvSvc()
    srv = Server(opts)
    srv.add_service(svc, name="OV")
    assert srv.start("127.0.0.1:0") == 0
    return srv, svc


def _frame(cid: int, mth: bytes, payload: bytes = b"",
           tenant: bytes = b"") -> bytes:
    mb = TLV_CORRELATION + struct.pack("<Q", cid)
    mb += encode_tlv(4, b"OV") + encode_tlv(5, mth)
    if tenant:
        mb += encode_tlv(22, tenant)
    body = mb + payload
    return b"TRPC" + struct.pack("<II", len(body), len(mb)) + body


def _read_frames(c: pysock.socket, n: int, timeout=10.0):
    c.settimeout(timeout)
    buf = b""
    out = {}
    while len(out) < n:
        while True:
            if len(buf) >= 12:
                (blen,) = struct.unpack_from("<I", buf, 4)
                if len(buf) >= 12 + blen:
                    break
            buf += c.recv(65536)
        (blen,) = struct.unpack_from("<I", buf, 4)
        (mlen,) = struct.unpack_from("<I", buf, 8)
        meta = RpcMeta.decode(buf[12:12 + mlen])
        assert meta is not None
        out[meta.correlation_id] = meta
        buf = buf[12 + blen:]
    return out


def _park(srv, ep, n: int = 1, tenant: bytes = b""):
    """Occupy n admission slots via async Park requests on one
    dedicated connection; returns the open socket (keep it alive)."""
    c = pysock.create_connection((str(ep.host), ep.port), timeout=10)
    base = srv.inflight
    for i in range(n):
        c.sendall(_frame(900 + i, b"Park", tenant=tenant))
    deadline = time.time() + 5
    while srv.inflight < base + n and time.time() < deadline:
        time.sleep(0.005)
    assert srv.inflight >= base + n, "Park requests not admitted in time"
    return c


def _http_exchange(ep, request: bytes):
    with pysock.create_connection((str(ep.host), ep.port), timeout=10) as c:
        c.sendall(request)
        c.settimeout(10)
        buf = b""
        while b"\r\n\r\n" not in buf:
            buf += c.recv(65536)
        head, _, rest = buf.partition(b"\r\n\r\n")
        lines = head.decode("latin1").split("\r\n")
        status = int(lines[0].split()[1])
        headers = {}
        for ln in lines[1:]:
            k, _, v = ln.partition(":")
            headers[k.strip().lower()] = v.strip()
        clen = int(headers.get("content-length", "0"))
        while len(rest) < clen:
            rest += c.recv(65536)
        return status, headers, rest[:clen]


def _http_req(path: bytes, body: bytes, tenant: str = "",
              close=False) -> bytes:
    h = [b"POST " + path + b" HTTP/1.1", b"Host: x",
         b"Content-Length: " + str(len(body)).encode()]
    if tenant:
        h.append(b"x-tenant: " + tenant.encode())
    if close:
        h.append(b"Connection: close")
    return b"\r\n".join(h) + b"\r\n\r\n" + body


def _grpc_call(ep, payload: bytes = b"x", tenant: str = ""):
    """One gRPC unary Echo over raw h2; returns grpc-status str."""
    from brpc_tpu.protocol.h2_rpc import pack_grpc_message
    from brpc_tpu.protocol.h2_session import H2Session

    sess = H2Session(is_server=False)
    sess.start()
    sid = sess.next_stream_id()
    hdrs = [(":method", "POST"), (":path", "/OV/Echo"),
            (":scheme", "http"), (":authority", "t"),
            ("content-type", "application/grpc"), ("te", "trailers")]
    if tenant:
        hdrs.append(("x-tenant", tenant))
    sess.send_headers(sid, hdrs)
    sess.send_data(sid, pack_grpc_message(payload), end_stream=True)
    grpc_status = None
    with pysock.create_connection((str(ep.host), ep.port),
                                  timeout=10) as c:
        c.sendall(sess.take_output())
        c.settimeout(10)
        deadline = time.time() + 10
        while grpc_status is None and time.time() < deadline:
            data = c.recv(65536)
            if not data:
                break
            for ev in sess.feed(data):
                if ev[0] == "headers":
                    for k, v in ev[2]:
                        if k == "grpc-status":
                            grpc_status = v
            out = sess.take_output()
            if out:
                c.sendall(out)
    return grpc_status


def _delta(before, tenant, verdict):
    after = admission_counters()
    return after.get((tenant, verdict), 0) \
        - before.get((tenant, verdict), 0)


def _saturate_method(srv, mth="Echo"):
    status = srv.find_method("OV", mth).status
    status.max_concurrency = 1
    status._inflight = 1
    return status


def _unsaturate_method(status):
    status.max_concurrency = 0
    status._inflight = 0


# ---------------------------------------------------------------------------
# server-cap x five lanes (async Park occupies the only slot)
# ---------------------------------------------------------------------------

def _probe_tpu_std(srv, svc, ep, expect_reject: bool, cid=50,
                   tenant: bytes = b""):
    with pysock.create_connection((str(ep.host), ep.port),
                                  timeout=10) as c:
        c.sendall(_frame(cid, b"Echo", b"probe", tenant=tenant))
        metas = _read_frames(c, 1)
    if expect_reject:
        assert metas[cid].error_code == ELIMIT, metas[cid].error_code
        assert b"probe" not in [x for x in svc.echo_calls]
    else:
        assert metas[cid].error_code == 0


def test_server_cap_classic_tpu_std():
    srv, svc = _server(native=False, max_concurrency=1)
    try:
        before = admission_counters()
        sock = _park(srv, srv.listen_endpoint)
        _probe_tpu_std(srv, svc, srv.listen_endpoint, True)
        assert _delta(before, "-", SERVER_CAP) == 1
        svc.release_parked()
        _read_frames(sock, 1)          # the parked response
        sock.close()
        _probe_tpu_std(srv, svc, srv.listen_endpoint, False, cid=51)
    finally:
        srv.stop()


def test_server_cap_slim_kind3():
    require_native()
    srv, svc = _server(native=True, max_concurrency=1)
    try:
        before = admission_counters()
        sock = _park(srv, srv.listen_endpoint)
        _probe_tpu_std(srv, svc, srv.listen_endpoint, True)
        assert _delta(before, "-", SERVER_CAP) == 1
        svc.release_parked()
        _read_frames(sock, 1)
        sock.close()
    finally:
        srv.stop()


def test_server_cap_http_classic_and_retry_after():
    srv, svc = _server(native=False, max_concurrency=1)
    try:
        sock = _park(srv, srv.listen_endpoint)
        status, headers, body = _http_exchange(
            srv.listen_endpoint, _http_req(b"/OV/Echo", b"p", close=True))
        assert status == 503
        # satellite: 503s carry Retry-After and a reason telling
        # server-cap apart from method-cap/CoDel/tenant-quota
        assert headers.get("retry-after")
        assert headers.get("x-overload-reason") == SERVER_CAP
        assert b"server max_concurrency" in body
        assert svc.echo_calls == []
        svc.release_parked()
        sock.close()
    finally:
        srv.stop()


def test_server_cap_http_slim_kind4():
    require_native()
    srv, svc = _server(native=True, max_concurrency=1)
    try:
        sock = _park(srv, srv.listen_endpoint)
        status, headers, body = _http_exchange(
            srv.listen_endpoint, _http_req(b"/OV/Echo", b"p"))
        assert status == 503
        assert headers.get("retry-after")
        assert headers.get("x-overload-reason") == SERVER_CAP
        assert svc.echo_calls == []
        svc.release_parked()
        sock.close()
    finally:
        srv.stop()


def test_server_cap_grpc_h2():
    srv, svc = _server(native=False, max_concurrency=1)
    try:
        sock = _park(srv, srv.listen_endpoint)
        assert _grpc_call(srv.listen_endpoint) == "8"  # RESOURCE_EXHAUSTED
        assert svc.echo_calls == []
        svc.release_parked()
        sock.close()
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# method-cap x five lanes (saturated MethodStatus, the deterministic
# idiom test_slim_dispatch/test_http_slim already pin byte-identity on)
# ---------------------------------------------------------------------------

def test_method_cap_all_lanes():
    for native, lanes in ((False, ("tpu_std", "http", "grpc")),
                          (True, ("slim", "http_slim"))):
        if native:
            require_native()
        srv, svc = _server(native=native)
        try:
            status = _saturate_method(srv)
            before = admission_counters()
            for lane in lanes:
                if lane in ("tpu_std", "slim"):
                    _probe_tpu_std(srv, svc, srv.listen_endpoint, True)
                elif lane in ("http", "http_slim"):
                    st, headers, body = _http_exchange(
                        srv.listen_endpoint,
                        _http_req(b"/OV/Echo", b"p", close=not native))
                    assert st == 503
                    assert headers.get("x-overload-reason") == METHOD_CAP
                    assert headers.get("retry-after")
                    assert b"method max_concurrency" in body
                else:
                    assert _grpc_call(srv.listen_endpoint) == "8"
            assert svc.echo_calls == []
            assert _delta(before, "-", METHOD_CAP) == len(lanes)
            _unsaturate_method(status)
            # the lane recovers once the cap clears
            _probe_tpu_std(srv, svc, srv.listen_endpoint, False, cid=60)
        finally:
            srv.stop()


# ---------------------------------------------------------------------------
# CoDel x five lanes (degenerate target/interval = 0: the first
# above-target request arms the interval, the second head-drops)
# ---------------------------------------------------------------------------

class _codel_flags:
    def __enter__(self):
        self.prev = (get_flag("enable_codel_shed", False),
                     get_flag("overload_codel_target_ms", 5.0),
                     get_flag("overload_codel_interval_ms", 100.0))
        set_flag("enable_codel_shed", True)
        set_flag("overload_codel_target_ms", 0)
        set_flag("overload_codel_interval_ms", 0)
        return self

    def __exit__(self, *exc):
        set_flag("enable_codel_shed", self.prev[0])
        set_flag("overload_codel_target_ms", self.prev[1])
        set_flag("overload_codel_interval_ms", self.prev[2])
        return False


def test_codel_classic_tpu_std():
    srv, svc = _server(native=False)
    try:
        with _codel_flags():
            before = admission_counters()
            with pysock.create_connection(
                    (str(srv.listen_endpoint.host),
                     srv.listen_endpoint.port), timeout=10) as c:
                c.sendall(_frame(70, b"Echo", b"one"))
                _read_frames(c, 1)
                c.sendall(_frame(71, b"Echo", b"two"))
                metas = _read_frames(c, 1)
            assert metas[71].error_code == ELIMIT
            assert b"two" not in svc.echo_calls
            assert _delta(before, "-", CODEL) >= 1
        # with the flag back off the lane admits again
        _probe_tpu_std(srv, svc, srv.listen_endpoint, False, cid=72)
    finally:
        srv.stop()


def test_codel_slim_kind3():
    require_native()
    srv, svc = _server(native=True)
    try:
        with _codel_flags():
            before = admission_counters()
            with pysock.create_connection(
                    (str(srv.listen_endpoint.host),
                     srv.listen_endpoint.port), timeout=10) as c:
                c.sendall(_frame(73, b"Echo", b"one"))
                _read_frames(c, 1)
                c.sendall(_frame(74, b"Echo", b"two"))
                metas = _read_frames(c, 1)
            assert metas[74].error_code == ELIMIT
            assert b"two" not in svc.echo_calls
            assert _delta(before, "-", CODEL) >= 1
    finally:
        srv.stop()


def test_codel_http_both_lanes():
    for native in (False, True):
        if native:
            require_native()
        srv, svc = _server(native=native)
        try:
            with _codel_flags():
                st1, _, _ = _http_exchange(
                    srv.listen_endpoint,
                    _http_req(b"/OV/Echo", b"one", close=not native))
                assert st1 == 200
                st2, headers, body = _http_exchange(
                    srv.listen_endpoint,
                    _http_req(b"/OV/Echo", b"two", close=not native))
                assert st2 == 503
                assert headers.get("x-overload-reason") == CODEL
                assert headers.get("retry-after")
                assert b"codel" in body
                assert b"two" not in svc.echo_calls
        finally:
            srv.stop()


def test_codel_grpc_h2():
    srv, svc = _server(native=False)
    try:
        with _codel_flags():
            assert _grpc_call(srv.listen_endpoint, b"one") == "0"
            assert _grpc_call(srv.listen_endpoint, b"two") == "8"
            assert b"two" not in svc.echo_calls
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# tenant quota x five lanes: the hot tenant saturates capacity and is
# rejected; the victim's guaranteed share still admits
# ---------------------------------------------------------------------------

def _tenant_servers(native):
    return _server(native=native, tenant_fair_capacity=2)


def test_tenant_quota_classic_tpu_std():
    srv, svc = _server(native=False, tenant_fair_capacity=2)
    try:
        before = admission_counters()
        sock = _park(srv, srv.listen_endpoint, n=2, tenant=b"hot")
        # hot is at its whole-capacity guarantee (sole active tenant)
        # AND the pool is contended: reject
        _probe_tpu_std(srv, svc, srv.listen_endpoint, True, cid=80,
                       tenant=b"hot")
        assert _delta(before, "hot", TENANT_QUOTA) == 1
        # the victim's guaranteed share (cap * 1/2 = 1) still admits
        _probe_tpu_std(srv, svc, srv.listen_endpoint, False, cid=81,
                       tenant=b"victim")
        assert _delta(before, "victim", ADMITTED) == 1
        svc.release_parked()
        _read_frames(sock, 2)
        sock.close()
    finally:
        srv.stop()


def test_tenant_quota_slim_kind3():
    require_native()
    srv, svc = _server(native=True, tenant_fair_capacity=2)
    try:
        before = admission_counters()
        sock = _park(srv, srv.listen_endpoint, n=2, tenant=b"hot")
        _probe_tpu_std(srv, svc, srv.listen_endpoint, True, cid=82,
                       tenant=b"hot")
        assert _delta(before, "hot", TENANT_QUOTA) == 1
        _probe_tpu_std(srv, svc, srv.listen_endpoint, False, cid=83,
                       tenant=b"victim")
        svc.release_parked()
        _read_frames(sock, 2)
        sock.close()
    finally:
        srv.stop()


def test_tenant_quota_http_both_lanes():
    for native in (False, True):
        if native:
            require_native()
        srv, svc = _server(native=native, tenant_fair_capacity=2)
        try:
            sock = _park(srv, srv.listen_endpoint, n=2, tenant=b"hot")
            st, headers, body = _http_exchange(
                srv.listen_endpoint,
                _http_req(b"/OV/Echo", b"hp", tenant="hot",
                          close=not native))
            assert st == 503
            assert headers.get("x-overload-reason") == TENANT_QUOTA
            assert headers.get("retry-after")
            assert b"tenant hot quota" in body
            st2, _, b2 = _http_exchange(
                srv.listen_endpoint,
                _http_req(b"/OV/Echo", b"vp", tenant="victim",
                          close=not native))
            assert st2 == 200 and b2 == b"ok:vp"
            svc.release_parked()
            sock.close()
        finally:
            srv.stop()


def test_tenant_quota_grpc_h2():
    srv, svc = _server(native=False, tenant_fair_capacity=2)
    try:
        sock = _park(srv, srv.listen_endpoint, n=2, tenant=b"hot")
        assert _grpc_call(srv.listen_endpoint, b"hp", tenant="hot") == "8"
        assert _grpc_call(srv.listen_endpoint, b"vp",
                          tenant="victim") == "0"
        svc.release_parked()
        sock.close()
    finally:
        srv.stop()


def test_tenant_quota_respects_fair_admission_flag():
    """enable_fair_admission=False (the bench A/B switch) lets the hot
    tenant through its quota."""
    srv, svc = _server(native=False, tenant_fair_capacity=2)
    try:
        prev = get_flag("enable_fair_admission", True)
        set_flag("enable_fair_admission", False)
        try:
            sock = _park(srv, srv.listen_endpoint, n=2, tenant=b"hot")
            _probe_tpu_std(srv, svc, srv.listen_endpoint, False, cid=85,
                           tenant=b"hot")
            svc.release_parked()
            sock.close()
        finally:
            set_flag("enable_fair_admission", prev)
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# accounting invariants
# ---------------------------------------------------------------------------

def test_counters_closed_enum_and_inflight_drain():
    """No 'unknown' bucket is POSSIBLE (closed verdict set) and every
    admitted request settles its tenant slot."""
    from brpc_tpu.server.admission import tenant_inflight_snapshot
    srv, svc = _server(native=False, tenant_fair_capacity=2)
    try:
        sock = _park(srv, srv.listen_endpoint, n=2, tenant=b"hot")
        _probe_tpu_std(srv, svc, srv.listen_endpoint, True, cid=86,
                       tenant=b"hot")
        assert tenant_inflight_snapshot().get("hot") == 2
        svc.release_parked()
        _read_frames(sock, 2)
        sock.close()
        deadline = time.time() + 5
        while tenant_inflight_snapshot().get("hot") and \
                time.time() < deadline:
            time.sleep(0.01)
        assert not tenant_inflight_snapshot().get("hot")
        assert srv.inflight == 0
    finally:
        srv.stop()
    for (tenant, verdict) in admission_counters():
        assert verdict in VERDICTS, f"unknown verdict bucket {verdict!r}"


def test_tenant_cardinality_bounded():
    """A client stamping a fresh random tenant per request must not
    grow the per-tenant tables (or the label family) without bound:
    past the cap, new names pool into the overflow bucket."""
    from brpc_tpu.server.admission import _MAX_TENANTS, TENANT_OVERFLOW
    srv, svc = _server(native=False)
    try:
        ctl = srv.admission
        entry = srv.find_method("OV", "Echo")
        for i in range(_MAX_TENANTS + 64):
            t = f"rnd-{i}"
            assert ctl.admit(entry, "tpu_std", t, None) is None
            srv.on_request_out(tenant=t)
            entry.status.on_responded(0, 1)
        assert len(ctl._tenant_inflight) <= _MAX_TENANTS + 1
        assert TENANT_OVERFLOW in ctl._tenant_inflight
        # every overflow acquire found its matching release
        assert ctl._tenant_inflight[TENANT_OVERFLOW] == 0
        assert srv.inflight == 0
        # the REJECTION path must hit the same bound: a server-cap
        # flood of fresh random tenant names (the overload case the
        # bound exists for) must not grow the admission counters —
        # rejected tenants never reach the inflight table, so the
        # registry has to count observations, not admissions
        before_rows = len(admission_counters())
        srv.options.max_concurrency = 1
        entry.status._inflight = 0
        with srv._inflight_lock:
            srv._inflight = 1           # saturate the server cap
        try:
            for i in range(128):
                rej = ctl.admit(entry, "tpu_std", f"flood-{i}", None)
                assert rej is not None and rej.reason == SERVER_CAP
        finally:
            with srv._inflight_lock:
                srv._inflight = 0
            srv.options.max_concurrency = 0
        grown = len(admission_counters()) - before_rows
        # one (~other, server_cap) row at most — not 128 tenant rows
        assert grown <= 1, grown
    finally:
        srv.stop()


def test_normalize_tenant():
    assert normalize_tenant(None) == "-"
    assert normalize_tenant(b"") == "-"
    assert normalize_tenant("  ") == "-"
    assert normalize_tenant(b"team-a") == "team-a"
    assert normalize_tenant("team-a") == "team-a"
    assert normalize_tenant(memoryview(b"k")) == "k"


def test_server_wide_adaptive_limiter_spec():
    """ServerOptions.max_concurrency accepts a make_limiter spec: the
    server-wide cap then adapts (and /status-level accounting holds)."""
    srv, svc = _server(native=False, max_concurrency="timeout:50")
    try:
        lim = srv.server_limiter()
        assert lim is not None and lim.kind == "timeout"
        ch = Channel()
        ch.init(str(srv.listen_endpoint))
        for i in range(30):
            assert ch.call("OV.Echo", b"x") == b"ok:x"
        # 30 fast echoes: the timeout limiter converged to a sane
        # non-zero limit fed by real latencies
        assert lim.max_concurrency() >= 1
    finally:
        srv.stop()


def test_default_method_spec_star():
    """method_max_concurrency['*'] installs a limiter on every method
    without its own entry."""
    srv, svc = _server(native=False,
                       method_max_concurrency={"*": "auto",
                                               "OV.Park": 7})
    try:
        assert srv.find_method("OV", "Echo").status.limiter_kind() \
            == "auto"
        park = srv.find_method("OV", "Park").status
        assert park.limiter_kind() == "constant"
        assert park.live_max_concurrency() == 7
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# pinned: tenant-stamped traffic stays on the native lanes — the
# admission stage introduces ZERO new fallback reasons
# ---------------------------------------------------------------------------

def test_no_new_fallbacks_with_tenant_and_rejections():
    require_native()
    srv, svc = _server(native=True, tenant_fair_capacity=8)
    try:
        eng = srv._native_bridge.engine
        t0 = eng.telemetry()
        ep = srv.listen_endpoint
        # tenant-stamped tpu_std rides the slim kind-3 lane
        with pysock.create_connection((str(ep.host), ep.port),
                                      timeout=10) as c:
            c.sendall(_frame(90, b"Echo", b"t1", tenant=b"team-a"))
            metas = _read_frames(c, 1)
            assert metas[90].error_code == 0
            # an ELIMIT rejection must ALSO stay on the lane
            status = _saturate_method(srv)
            c.sendall(_frame(91, b"Echo", b"t2", tenant=b"team-a"))
            metas = _read_frames(c, 1)
            assert metas[91].error_code == ELIMIT
            _unsaturate_method(status)
        # tenant-stamped HTTP rides the slim kind-4 lane
        st, _, body = _http_exchange(
            ep, _http_req(b"/OV/Echo", b"h", tenant="team-a"))
        assert st == 200 and body == b"ok:h"
        t1 = eng.telemetry()
        assert sum(t1["fallbacks"].values()) == \
            sum(t0["fallbacks"].values()), t1["fallbacks"]
        assert t1["lanes"]["slim"]["handled"] \
            >= t0["lanes"]["slim"]["handled"] + 2
        assert t1["lanes"]["http"]["handled"] \
            >= t0["lanes"]["http"]["handled"] + 1
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# client side: ELIMIT fail-fast failover
# ---------------------------------------------------------------------------

def test_client_elimit_fails_over_immediately():
    """An ELIMIT bounce from a saturated replica retries IMMEDIATELY
    (no backoff) on the other replica of an LB channel and succeeds."""
    busy_srv, busy_svc = _server(native=False, max_concurrency=1)
    free_srv, free_svc = _server(native=False)
    sock = _park(busy_srv, busy_srv.listen_endpoint)
    try:
        co = ChannelOptions()
        co.timeout_ms = 3000
        co.max_retry = 2
        co.retry_backoff_ms = 2000      # would blow the elapsed assert
        co.connection_type = "pooled"   # if ELIMIT ever backed off
        ch = Channel(co)
        assert ch.init(
            f"list://{busy_srv.listen_endpoint},"
            f"{free_srv.listen_endpoint}", "rr") == 0
        ok = retried = 0
        t0 = time.monotonic()
        for i in range(6):
            cntl = Controller()
            cntl.timeout_ms = 3000
            c = ch.call_method("OV.Echo", b"x", cntl=cntl)
            if not c.failed:
                ok += 1
            retried += c.retried_count
        elapsed = time.monotonic() - t0
        assert ok == 6, "fail-fast failover must reach the free replica"
        assert retried >= 1          # at least one call bounced off busy
        assert elapsed < 1.5, f"ELIMIT retries must skip backoff " \
                              f"({elapsed:.2f}s)"
    finally:
        busy_svc.release_parked()
        sock.close()
        busy_srv.stop()
        free_srv.stop()


def test_run_raw_keeps_tenant_in_tlv_cache():
    """The per-channel method-TLV cache is shared by every client lane:
    a call_raw that populates it first must include the tenant TLV, or
    later call_method traffic silently loses its fair-admission key."""
    srv, svc = _server(native=False)
    try:
        co = ChannelOptions()
        co.tenant = "acme"
        co.connection_type = "pooled"
        ch = Channel(co)
        ch.init(str(srv.listen_endpoint))
        try:
            ch.call_raw("OV.Echo", b"x")
        except Exception:
            pass                      # reply shape irrelevant here
        tlv = ch._method_tlvs.get("OV.Echo")
        assert tlv is not None
        assert encode_tlv(22, b"acme") in tlv
        # and the round trip through call_method is attributed to acme
        before = admission_counters()
        assert ch.call("OV.Echo", b"y") == b"ok:y"
        assert _delta(before, "acme", ADMITTED) == 1
    finally:
        srv.stop()


def test_breaker_feeds_elimit_at_reduced_weight():
    from brpc_tpu.client.circuit_breaker import CircuitBreakerMap
    from brpc_tpu.butil.endpoint import EndPoint
    m = CircuitBreakerMap()
    ep = EndPoint(host="10.0.0.9", port=1)
    # 20 straight ELIMIT bounces: short EMA converges to 0.3 < 0.6 trip
    for _ in range(20):
        m.on_call(ep, ELIMIT, 100)
    assert not m.isolated(ep)
    # 20 straight REAL errors trip isolation
    for _ in range(20):
        m.on_call(ep, 2001, 100)
    assert m.isolated(ep)


# ---------------------------------------------------------------------------
# slow soak: sustained mixed-tenant overload leaks nothing
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_overload_soak_no_leaks():
    from brpc_tpu.server.admission import tenant_inflight_snapshot
    srv, svc = _server(native=False, tenant_fair_capacity=4,
                       max_concurrency=8)
    try:
        stop = time.time() + 6.0
        errs = []

        def client(tenant):
            co = ChannelOptions()
            co.timeout_ms = 2000
            co.max_retry = 0
            co.connection_type = "pooled"
            co.tenant = tenant
            ch = Channel(co)
            ch.init(str(srv.listen_endpoint))
            while time.time() < stop:
                cntl = Controller()
                cntl.timeout_ms = 2000
                c = ch.call_method("OV.Echo", b"s", cntl=cntl)
                if c.failed and c.error_code != ELIMIT:
                    errs.append(c.error_code)

        threads = [threading.Thread(target=client,
                                    args=(f"t{i % 3}",))
                   for i in range(9)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(30)
        assert not errs, f"non-ELIMIT failures under overload: {errs[:5]}"
        deadline = time.time() + 5
        while (srv.inflight or any(tenant_inflight_snapshot().values())) \
                and time.time() < deadline:
            time.sleep(0.02)
        assert srv.inflight == 0
        snap = tenant_inflight_snapshot()
        assert not any(snap.values()), snap
    finally:
        srv.stop()
