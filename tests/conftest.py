"""Test harness config.

Forces JAX onto a virtual 8-device CPU mesh so multi-chip sharding paths are
exercised without TPU hardware (mirrors how the reference tests distributed
behavior in-process on loopback — /root/reference/test/brpc_server_unittest.cpp:185).

MUST run before any `import jax` anywhere in the test session.
"""
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The axon sitecustomize registers the TPU PJRT plugin at interpreter boot
# and overrides JAX_PLATFORMS from the env; the config update below wins
# as long as no backend has initialized yet (true at conftest time).
try:
    import jax

    jax.config.update("jax_platforms", "cpu")
except ImportError:
    pass

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
