"""Test harness config.

Forces JAX onto a virtual 8-device CPU mesh so multi-chip sharding paths are
exercised without TPU hardware (mirrors how the reference tests distributed
behavior in-process on loopback — /root/reference/test/brpc_server_unittest.cpp:185).

MUST run before any `import jax` anywhere in the test session.
"""
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The axon sitecustomize registers the TPU PJRT plugin at interpreter boot
# and overrides JAX_PLATFORMS from the env; the config update below wins
# as long as no backend has initialized yet (true at conftest time).
try:
    import jax

    jax.config.update("jax_platforms", "cpu")
except ImportError:
    pass

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pytest  # noqa: E402


def require_native():
    """Skip the calling test when the native engine can't build."""
    from brpc_tpu.native import available
    if not available():
        pytest.skip("native engine unavailable (no toolchain)")


@pytest.fixture(scope="session", params=[False, True], ids=["py", "native"])
def native_mode(request):
    """Run server-backed suites over both transports: the pure-Python
    path and the native C++ IO engine (built on demand; the reference
    tests Socket/InputMessenger directly — brpc_socket_unittest.cpp)."""
    if request.param:
        require_native()
    return request.param


@pytest.fixture()
def server_options(native_mode):
    """ServerOptions pre-configured for the current transport param."""
    from brpc_tpu.server import ServerOptions
    opts = ServerOptions()
    opts.native = native_mode
    return opts


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "soak: long mixed-workload soak (duration via SOAK_SECONDS env)")
    config.addinivalue_line(
        "markers",
        "slow: long-running stress/soak tests excluded from tier-1 "
        "(-m 'not slow')")


# -- shared wire-format helpers for the native adversarial suites --------
# (one home for TRPC/TLV byte building: a framing change must not be
# mirrorable into only one of the raw/batch test files)

def wire_tlv(tag: int, data: bytes) -> bytes:
    import struct
    return bytes([tag]) + struct.pack("<I", len(data)) + data


def wire_resp_frame(cid: int, payload: bytes = b"ok",
                    extra_meta: bytes = b"") -> bytes:
    import struct
    meta = wire_tlv(1, struct.pack("<Q", cid)) + extra_meta
    return (b"TRPC" + struct.pack("<II", len(meta) + len(payload),
                                  len(meta)) + meta + payload)


WIRE_TAIL = wire_tlv(4, b"S") + wire_tlv(5, b"M")   # service/method TLVs


def load_native_or_skip(attr: str):
    """The loaded native module, skipping unless ``attr`` exists."""
    require_native()
    from brpc_tpu.native import load
    nat = load()
    if nat is None or not hasattr(nat, attr):
        pytest.skip(f"native {attr} unavailable")
    return nat
