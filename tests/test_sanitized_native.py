"""Sanitizer-hardened native build (``make asan``) + stress test.

brpc keeps its C++ core honest with ASAN/UBSAN CI; the engine gets the
same discipline: ``_native_asan.so`` is the identical translation unit
under ``-fsanitize=address,undefined -fno-omit-frame-pointer``, loaded
into a subprocess (libasan LD_PRELOADed) that drives burst dispatch,
the HTTP slim lane, client demux, scatter and the shm slot lifecycle
(tests/asan_driver.py).  The test fails on ANY sanitizer report.

slow-marked: the instrumented build + run costs ~1-2 minutes, so it
rides the stress tier, not tier-1.
"""

import os
import subprocess
import sys

import pytest

_DIR = os.path.dirname(os.path.abspath(__file__))
_NATIVE = os.path.join(_DIR, "..", "brpc_tpu", "native")

pytestmark = pytest.mark.slow


def _lib(name: str) -> str:
    out = subprocess.run(["g++", f"-print-file-name={name}"],
                         capture_output=True, text=True, timeout=30)
    path = out.stdout.strip()
    return path if os.path.isabs(path) else ""


def test_asan_build_and_stress():
    asan = _lib("libasan.so")
    if not asan:
        pytest.skip("no libasan in this toolchain")
    build = subprocess.run(["make", "-C", _NATIVE, "asan"],
                           capture_output=True, text=True, timeout=300)
    assert build.returncode == 0, build.stdout + build.stderr

    env = dict(os.environ)
    env["BRPC_TPU_NATIVE_ASAN"] = "1"
    # libasan must initialize before CPython; leak detection off (the
    # interpreter's arena behavior floods it with false positives) —
    # use-after-free / overflow / UB detection is the point here
    preload = asan
    ubsan = _lib("libubsan.so")
    if ubsan:
        preload += ":" + ubsan
    env["LD_PRELOAD"] = preload
    env["ASAN_OPTIONS"] = ("detect_leaks=0:abort_on_error=1:"
                           "disable_coredump=1")
    env["UBSAN_OPTIONS"] = "print_stacktrace=1:halt_on_error=1"
    env["PYTHONPATH"] = os.path.abspath(os.path.join(_DIR, "..")) \
        + os.pathsep + env.get("PYTHONPATH", "")

    r = subprocess.run(
        [sys.executable, os.path.join(_DIR, "asan_driver.py")],
        capture_output=True, text=True, timeout=420, env=env)
    out = r.stdout + r.stderr
    assert "AddressSanitizer" not in out, out[-8000:]
    assert "runtime error:" not in out, out[-8000:]
    assert r.returncode == 0, out[-8000:]
    assert "ASAN_DRIVER_OK" in r.stdout, out[-8000:]
