"""FaultyTransport — an in-process TCP proxy with injectable faults.

The adversarial test harness SURVEY §4 calls for (fixture shape
≈ /root/reference/test/brpc_channel_unittest.cpp:166-230's mocked
failure paths): client → proxy → server, with live-togglable

- ``delay_s``            added latency on every forwarded segment
- ``partition``          blackhole: accept + read, forward nothing
- ``drop_after_bytes``   cut the connection after N forwarded bytes
- ``corrupt_byte_at``    flip one byte at stream offset N
- ``reorder_window``     hold segments and flush them out of order

Faults apply to NEW data after the toggle; heal() restores clean
forwarding for subsequent connections.
"""

from __future__ import annotations

import socket
import threading
import time
from typing import List, Optional, Tuple


class FaultyTransport:
    def __init__(self, upstream_host: str, upstream_port: int):
        self._up = (upstream_host, upstream_port)
        self._lst = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._lst.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._lst.bind(("127.0.0.1", 0))
        self._lst.listen(64)
        self.port = self._lst.getsockname()[1]
        self._stop = False
        self.delay_s = 0.0
        self.partition = False
        self.drop_after_bytes = -1
        self.corrupt_byte_at = -1
        self.reorder_window = 0
        self.forwarded_bytes = 0
        self.connections = 0
        # client→server TRPC frame starts forwarded — the attempt
        # counter retry-storm tests pin amplification against
        self.request_frames = 0
        self._lock = threading.Lock()
        self._conns: List[socket.socket] = []
        self._thr = threading.Thread(target=self._accept_loop, daemon=True)
        self._thr.start()

    @property
    def address(self) -> str:
        return f"127.0.0.1:{self.port}"

    def heal(self) -> None:
        self.delay_s = 0.0
        self.partition = False
        self.drop_after_bytes = -1
        self.corrupt_byte_at = -1
        self.reorder_window = 0

    def kill_connections(self) -> None:
        with self._lock:
            conns, self._conns = self._conns, []
        for c in conns:
            try:
                c.close()
            except OSError:
                pass

    def close(self) -> None:
        self._stop = True
        try:
            self._lst.close()
        except OSError:
            pass
        self.kill_connections()

    # -- internals ----------------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._stop:
            try:
                cli, _ = self._lst.accept()
            except OSError:
                return
            self.connections += 1
            try:
                srv = socket.create_connection(self._up, timeout=5)
            except OSError:
                cli.close()
                continue
            with self._lock:
                self._conns += [cli, srv]
            state = {"fwd": 0}
            threading.Thread(target=self._pump,
                             args=(cli, srv, state, True),
                             daemon=True).start()
            threading.Thread(target=self._pump, args=(srv, cli, state),
                             daemon=True).start()

    def _pump(self, src: socket.socket, dst: socket.socket, state,
              inbound: bool = False) -> None:
        held: List[bytes] = []
        try:
            while not self._stop:
                try:
                    data = src.recv(65536)
                except OSError:
                    break
                if not data:
                    break
                if inbound:
                    # count request-frame starts even when the fault
                    # then eats them: an attempt is an attempt
                    self.request_frames += data.count(b"TRPC")
                if self.partition:
                    continue                      # blackhole
                if self.delay_s > 0:
                    time.sleep(self.delay_s)
                off = self.corrupt_byte_at
                if 0 <= off - state["fwd"] < len(data):
                    i = off - state["fwd"]
                    data = data[:i] + bytes([data[i] ^ 0xFF]) + data[i + 1:]
                    self.corrupt_byte_at = -1
                cut = self.drop_after_bytes
                if cut >= 0 and state["fwd"] + len(data) >= cut:
                    take = max(0, cut - state["fwd"])
                    if take:
                        dst.sendall(data[:take])
                        state["fwd"] += take
                    break                         # cut the connection
                if self.reorder_window > 0:
                    held.append(data)
                    if len(held) >= self.reorder_window:
                        for chunk in reversed(held):
                            dst.sendall(chunk)
                            state["fwd"] += len(chunk)
                        held.clear()
                    continue
                dst.sendall(data)
                state["fwd"] += len(data)
                self.forwarded_bytes += len(data)
        except OSError:
            pass
        finally:
            for chunk in held:
                try:
                    dst.sendall(chunk)
                except OSError:
                    break
            for s in (src, dst):
                try:
                    s.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                try:
                    s.close()
                except OSError:
                    pass
