"""End-to-end RPC tests: real Server + Channel over loopback TCP —
the reference's own integration pattern
(/root/reference/test/brpc_server_unittest.cpp:185)."""

import threading
import time

import pytest

from brpc_tpu.butil.iobuf import IOBuf
from brpc_tpu.butil.status import Errno
from brpc_tpu.client import Channel, ChannelOptions, Controller, start_cancel
from brpc_tpu.client.channel import RpcError
from brpc_tpu.fiber.timer_thread import global_timer_thread
from brpc_tpu.protocol.meta import CompressType
from brpc_tpu.server import Server, ServerOptions, Service


class EchoService(Service):
    def Echo(self, cntl, request):
        return request

    def Upper(self, cntl, request):
        return request.upper()

    def WithAttachment(self, cntl, request):
        cntl.response_attachment.append(cntl.request_attachment.to_bytes())
        cntl.response_attachment.append(b"|tail")
        return b"ok"

    def Fail(self, cntl, request):
        cntl.set_failed(Errno.EREQUEST, "deliberate failure")
        return None

    def Boom(self, cntl, request):
        raise RuntimeError("kaboom")

    def Slow(self, cntl, request):
        time.sleep(0.4)
        return b"slow done"

    def AsyncEcho(self, cntl, request):
        cntl.begin_async()
        global_timer_thread().schedule(cntl.finish, 0.05, None,
                                       b"async:" + request)
        return None


@pytest.fixture(scope="module")
def server(native_mode):
    # module-scoped: cannot use the function-scoped server_options fixture
    opts = ServerOptions()
    opts.native = native_mode
    srv = Server(opts)
    assert srv.add_service(EchoService()) == 0
    assert srv.start("127.0.0.1:0") == 0
    yield srv
    srv.stop()


@pytest.fixture()
def channel(server):
    ch = Channel()
    assert ch.init(str(server.listen_endpoint)) == 0
    return ch


def test_sync_echo(channel):
    assert channel.call("EchoService.Echo", b"hello") == b"hello"
    assert channel.call("EchoService.Upper", b"abc") == b"ABC"


def test_large_payload(channel):
    opts = ChannelOptions()
    opts.timeout_ms = 10_000
    big = bytes(range(256)) * 16 * 1024        # 4 MB
    ch = channel
    cntl = Controller()
    cntl.timeout_ms = 10_000
    c = ch.call_method("EchoService.Echo", big, cntl=cntl)
    assert not c.failed, c.error_text
    assert c.response == big


def test_async_call(channel):
    done_evt = threading.Event()
    result = {}

    def on_done(cntl):
        result["failed"] = cntl.failed
        result["resp"] = cntl.response
        done_evt.set()

    channel.call_method("EchoService.Echo", b"async-req", done=on_done)
    assert done_evt.wait(5.0)
    assert not result["failed"]
    assert result["resp"] == b"async-req"


def test_server_async_method(channel):
    c = channel.call_method("EchoService.AsyncEcho", b"ping")
    assert not c.failed, c.error_text
    assert c.response == b"async:ping"


def test_error_propagation(channel):
    c = channel.call_method("EchoService.Fail", b"x")
    assert c.failed
    assert c.error_code == int(Errno.EREQUEST)
    assert "deliberate" in c.error_text


def test_exception_becomes_einternal(channel):
    c = channel.call_method("EchoService.Boom", b"x")
    assert c.failed
    assert c.error_code == int(Errno.EINTERNAL)
    assert "kaboom" in c.error_text


def test_unknown_service_and_method(channel):
    c = channel.call_method("Nope.Echo", b"x")
    assert c.error_code == int(Errno.ENOSERVICE)
    c = channel.call_method("EchoService.Nope", b"x")
    assert c.error_code == int(Errno.ENOMETHOD)


def test_timeout(channel):
    cntl = Controller()
    cntl.timeout_ms = 100
    c = channel.call_method("EchoService.Slow", b"x", cntl=cntl)
    assert c.failed
    assert c.error_code == int(Errno.ERPCTIMEDOUT)
    assert c.latency_us < 2_000_000


def test_attachment_roundtrip(channel):
    cntl = Controller()
    cntl.request_attachment.append(b"BULKDATA" * 100)
    c = channel.call_method("EchoService.WithAttachment", b"body",
                            cntl=cntl)
    assert not c.failed, c.error_text
    assert c.response == b"ok"
    att = c.response_attachment.to_bytes()
    assert att == b"BULKDATA" * 100 + b"|tail"


def test_compression(channel):
    cntl = Controller()
    cntl.request_compress_type = CompressType.GZIP
    payload = b"compress me " * 1000
    c = channel.call_method("EchoService.Echo", payload, cntl=cntl)
    assert not c.failed, c.error_text
    assert c.response == payload


def test_concurrent_calls(channel):
    n = 32
    results = []
    lock = threading.Lock()
    threads = []

    def one(i):
        c = channel.call_method("EchoService.Echo", f"msg{i}".encode())
        with lock:
            results.append((i, c.failed, c.response))

    for i in range(n):
        t = threading.Thread(target=one, args=(i,))
        t.start()
        threads.append(t)
    for t in threads:
        t.join(10.0)
    assert len(results) == n
    for i, failed, resp in results:
        assert not failed
        assert resp == f"msg{i}".encode()


def test_connect_failure_exhausts_retries():
    ch = Channel()
    # nothing listens on this port
    assert ch.init("127.0.0.1:1") == 0
    cntl = Controller()
    cntl.timeout_ms = 3000
    c = ch.call_method("EchoService.Echo", b"x", cntl=cntl)
    assert c.failed
    assert c.error_code in (int(Errno.EFAILEDSOCKET),
                            int(Errno.ERPCTIMEDOUT))
    assert c.retried_count == c.max_retry


def test_cancel(channel):
    cntl = Controller()
    cntl.timeout_ms = 5000
    done_evt = threading.Event()

    def on_done(c):
        done_evt.set()

    channel.call_method("EchoService.Slow", b"x", done=on_done, cntl=cntl)
    start_cancel(cntl.call_id)
    assert done_evt.wait(2.0)
    assert cntl.failed
    assert cntl.error_code == int(Errno.ECANCELLED)


def test_server_concurrency_limit():
    opts = ServerOptions()
    opts.max_concurrency = 2
    srv = Server(opts)
    srv.add_service(EchoService(), name="Echo2")
    assert srv.start("127.0.0.1:0") == 0
    try:
        ch = Channel()
        ch.init(str(srv.listen_endpoint))
        hits = {"limit": 0, "ok": 0}
        lock = threading.Lock()

        def one():
            cntl = Controller()
            cntl.timeout_ms = 5000
            c = ch.call_method("Echo2.Slow", b"x", cntl=cntl)
            with lock:
                if c.error_code == int(Errno.ELIMIT):
                    hits["limit"] += 1
                elif not c.failed:
                    hits["ok"] += 1

        threads = [threading.Thread(target=one) for _ in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(10.0)
        assert hits["ok"] >= 2
        assert hits["limit"] >= 1
    finally:
        srv.stop()


def test_client_survives_server_restart():
    from brpc_tpu.transport.socket_map import global_socket_map
    global_socket_map()._hc = 0.05       # fast health check for the test
    srv = Server()
    srv.add_service(EchoService(), name="Restartable")
    assert srv.start("127.0.0.1:0") == 0
    port = srv.listen_endpoint.port
    ch = Channel()
    ch.init(f"127.0.0.1:{port}")
    assert ch.call("Restartable.Echo", b"one") == b"one"
    srv.stop()
    # connection is dead: calls fail until the server returns
    c = ch.call_method("Restartable.Echo", b"two")
    assert c.failed
    srv2 = Server()
    srv2.add_service(EchoService(), name="Restartable")
    assert srv2.start(f"127.0.0.1:{port}") == 0
    try:
        deadline = time.time() + 5.0
        ok = False
        while time.time() < deadline:
            c = ch.call_method("Restartable.Echo", b"three")
            if not c.failed:
                ok = True
                break
            time.sleep(0.05)
        assert ok, f"never recovered: {c.error_text}"
        assert c.response == b"three"
    finally:
        srv2.stop()
        global_socket_map()._hc = 3.0


def test_method_stats_recorded(server, channel):
    entry = server.find_method("EchoService", "Echo")
    before = entry.status.latency.count()
    channel.call("EchoService.Echo", b"statcheck")
    assert entry.status.latency.count() > before
