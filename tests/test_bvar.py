"""bvar tests (≈ reference test/bvar_reducer_unittest.cpp,
bvar_percentile_unittest.cpp, bvar_sampler_unittest.cpp,
bvar_multi_dimension_unittest.cpp): merge semantics and window math,
using deterministic sampler ticks instead of sleeping."""

import threading

import pytest

from brpc_tpu.bvar import (Adder, Maxer, Miner, IntRecorder, Window, PerSecond,
                           Percentile, LatencyRecorder, PassiveStatus, StatusVar,
                           MultiDimension, tick_once_for_tests, find_exposed,
                           list_exposed, dump_exposed, render_prometheus,
                           Collector, Collected, clear_registry_for_tests)


@pytest.fixture(autouse=True)
def _clean_registry():
    clear_registry_for_tests()
    yield
    clear_registry_for_tests()


class TestReducers:
    def test_adder(self):
        a = Adder()
        a << 1 << 2 << 3
        assert a.get_value() == 6
        a.update(-10)
        assert a.get_value() == -4

    def test_maxer_miner(self):
        m = Maxer()
        m << 5 << 3 << 9
        assert m.get_value() == 9
        n = Miner()
        n << 5 << 3 << 9
        assert n.get_value() == 3

    def test_int_recorder(self):
        r = IntRecorder()
        for v in (10, 20, 30):
            r << v
        assert r.average() == 20
        assert r.sum == 60 and r.num == 3

    def test_multithreaded_merge(self):
        """Write-side is per-thread; read must merge all agents."""
        a = Adder()

        def w():
            for _ in range(10000):
                a << 1

        ts = [threading.Thread(target=w) for _ in range(8)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert a.get_value() == 80000

    def test_dead_thread_value_folds_into_residual(self):
        a = Adder()
        t = threading.Thread(target=lambda: a.update(42))
        t.start()
        t.join()
        assert a.get_value() == 42  # dead thread's agent folded, not lost
        assert a.get_value() == 42  # stable across repeated reads

    def test_cumulative_survives_sampling(self):
        a = Adder()
        Window(a, window_size=2)      # attaches a delta sampler
        a << 7
        tick_once_for_tests()
        tick_once_for_tests()
        tick_once_for_tests()
        assert a.get_value() == 7     # sampling never resets the reducer


class TestWindows:
    def test_window_sums_recent_seconds(self):
        a = Adder()
        w = Window(a, window_size=3)
        for v in (10, 20, 30, 40):
            a << v
            tick_once_for_tests()     # one "second" boundary
        # only last 3 seconds count: 20+30+40
        assert w.get_value() == 90

    def test_per_second(self):
        a = Adder()
        q = PerSecond(a, window_size=5)
        for _ in range(5):
            a << 100
            tick_once_for_tests()
        assert q.get_value() == 100

    def test_two_windows_share_one_sampler(self):
        m = Maxer()
        w1 = Window(m, 10)
        w2 = Window(m, 10)
        m << 5
        tick_once_for_tests()
        assert w1.get_value() == 5
        assert w2.get_value() == 5   # shared ring: no double epoch close

    def test_default_variables_survive_registry_reset(self):
        from brpc_tpu.bvar import expose_default_variables
        expose_default_variables()
        assert find_exposed("process_pid") is not None
        clear_registry_for_tests()
        expose_default_variables()   # must re-expose after reset
        assert find_exposed("process_pid") is not None

    def test_window_of_maxer_is_truly_windowed(self):
        m = Maxer()
        w = Window(m, window_size=2)
        m << 1000
        tick_once_for_tests()
        m << 5
        tick_once_for_tests()
        m << 7
        tick_once_for_tests()
        # the 1000 spike aged out of the 2-second window...
        assert w.get_value() == 7
        # ...but the all-time max is still visible on the reducer itself
        assert m.get_value() == 1000


class TestPercentile:
    def test_quantiles(self):
        p = Percentile()
        for i in range(1, 1001):
            p << i
        tick_once_for_tests()
        assert 400 <= p.get_number(0.5) <= 600
        assert p.get_number(0.99) >= 900
        assert p.get_number(0.0) >= 1

    def test_multithreaded_updates(self):
        p = Percentile()

        def w(base):
            for i in range(1000):
                p << base + i

        ts = [threading.Thread(target=w, args=(k * 1000,)) for k in range(4)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        tick_once_for_tests()
        assert p.get_number(0.99) > p.get_number(0.01)


class TestLatencyRecorder:
    def test_composite(self):
        lr = LatencyRecorder(window_size=5)
        for v in (100, 200, 300, 400, 500):
            lr << v
        tick_once_for_tests()
        assert lr.count() == 5
        assert lr.latency() == 300
        assert lr.max_latency() == 500
        assert lr.qps() > 0
        assert lr.p99() >= lr.p50() >= 100

    def test_expose_subvars(self):
        lr = LatencyRecorder(window_size=5)
        lr.expose("echo_service")
        names = list_exposed()
        assert "echo_service" in names
        assert "echo_service_qps" in names
        assert "echo_service_latency" in names

    def test_dead_thread_window_data_survives(self):
        """A worker dying between sampler ticks must not lose its
        un-drained windowed max / percentile reservoir: the dead-agent
        fold keeps them for the next drain."""
        import threading

        lr = LatencyRecorder(window_size=5)

        def worker():
            for _ in range(1000):
                lr << 5.0
            lr << 9999.0

        t = threading.Thread(target=worker)
        t.start()
        t.join()
        # a cumulative read first: triggers the dead-agent fold BEFORE
        # any sampler drain (the regression path)
        assert lr.count() == 1001
        tick_once_for_tests()
        assert lr.max_latency() == 9999.0
        assert lr.latency_percentile(0.5) == 5.0
        assert lr.count() == 1001


class TestRegistry:
    def test_expose_find_hide(self):
        a = Adder()
        assert a.expose("my counter!")       # sanitized
        assert find_exposed("my_counter_") is a
        a << 3
        assert dump_exposed()["my_counter_"] == "3"
        assert a.hide()
        assert find_exposed("my_counter_") is None

    def test_duplicate_expose_rejected(self):
        a, b = Adder(), Adder()
        assert a.expose("dup")
        assert not b.expose("dup")

    def test_passive_and_status(self):
        x = [1]
        p = PassiveStatus(lambda: x[0], "passive_x")
        s = StatusVar("hello", "status_s")
        assert p.get_value() == 1
        x[0] = 5
        assert p.get_value() == 5
        assert s.get_value() == "hello"
        s.set_value("world")
        assert find_exposed("status_s").get_value() == "world"


class TestMultiDimension:
    def test_labeled_stats(self):
        md = MultiDimension(["method", "code"], Adder, "rpc_errors")
        md.get_stats(["echo", "0"]).update(3)
        md.get_stats(["echo", "1008"]).update(1)
        md.get_stats(["echo", "0"]).update(2)
        assert md.count_stats() == 2
        assert md.get_value()[("echo", "0")] == 5
        with pytest.raises(ValueError):
            md.get_stats(["only-one"])


class TestPrometheus:
    def test_render(self):
        a = Adder()
        a.expose("requests_total")
        a << 17
        md = MultiDimension(["method"], Adder, "per_method")
        md.get_stats(["echo"]).update(4)
        text = render_prometheus()
        assert "requests_total 17" in text
        assert 'per_method{method="echo"} 4' in text


class TestCollector:
    def test_rate_limit_and_drain(self):
        sunk = []
        c = Collector(sink=sunk.extend, max_per_second=10)

        class S(Collected):
            pass

        ok = sum(1 for _ in range(50) if c.submit(S()))
        assert ok == 10 and c.dropped == 40
        drained = c.drain()
        assert len(drained) == 10 and len(sunk) == 10
