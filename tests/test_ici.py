"""ICI data plane tests — device-resident attachments, window+ack flow
control, fallback staging, landing-pool recycling, multi-device redeem.

Shapes mirror the reference's RDMA coverage
(/root/reference/src/brpc/rdma/ + example/rdma_performance/): zero-copy
of the payload end to end, window accounting, fallback when the fabric
is unreachable.
"""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from brpc_tpu.butil.flags import get_flag, set_flag
from brpc_tpu.client import Channel, Controller
from brpc_tpu.ici import DeviceBlockPool, IciEndpoint, local_domain_id
from brpc_tpu.ici.attachment import (KIND_INLINE, KIND_INPROC,
                                     decode_descriptor, encode_descriptor)
from brpc_tpu.ici.fabric import InProcessFabric, in_process_fabric
from brpc_tpu.server import Server, Service


class TensorEcho(Service):
    def Echo(self, cntl, request):
        att = cntl.request_device_attachment
        if att is None:
            return b"no-tensor"
        cntl.response_device_attachment = att.tensor()
        return b"ok"

    def Make(self, cntl, request):
        n = int(request or b"16")
        cntl.response_device_attachment = jnp.arange(n, dtype=jnp.float32)
        return b"made"


@pytest.fixture()
def server(server_options):
    srv = Server(server_options)
    srv.add_service(TensorEcho(), name="TE")
    assert srv.start("127.0.0.1:0") == 0
    yield srv
    srv.stop()


def _channel(server):
    ch = Channel()
    ch.init(str(server.listen_endpoint))
    return ch


def test_descriptor_codec_roundtrip():
    d = encode_descriptor(KIND_INPROC, 12345, 4096, "float32",
                          (32, 32), b"xtra")
    assert decode_descriptor(d) == (KIND_INPROC, 12345, 4096, "float32",
                                    (32, 32), b"xtra")
    d = encode_descriptor(KIND_INLINE, 0, 8, "int8", (), b"")
    assert decode_descriptor(d) == (KIND_INLINE, 0, 8, "int8", (), b"")


def test_in_process_fabric_post_redeem_release():
    f = InProcessFabric()
    x = jnp.ones((128,), jnp.float32)
    did = f.post(x, 512)
    assert f.posted_bytes == 512
    got = f.redeem(did)
    assert got is x                      # same object: zero copies
    assert f.release(did)
    assert f.posted_bytes == 0
    assert not f.release(did)            # double release is a no-op
    assert f.redeem(did) is None         # gone


def test_fabric_ttl_sweep():
    f = InProcessFabric()
    f.post(jnp.zeros((4,)), 16)
    time.sleep(0.05)
    assert f.sweep_expired(0.01) == 1
    assert f.posted_bytes == 0


def test_device_echo_rpc_same_process_zero_copy(server):
    """The headline path: a device tensor rides request AND response as
    descriptors; the redeemed response is the SAME device buffer the
    service produced (no copies anywhere)."""
    ch = _channel(server)
    cntl = Controller()
    cntl.timeout_ms = 30_000
    x0 = jnp.arange(1024, dtype=jnp.float32)
    cntl.request_device_attachment = x0
    c = ch.call_method("TE.Echo", b"", cntl=cntl)
    assert not c.failed, c.error_text
    # first call had no learned domain yet -> inline fallback, still works
    out0 = c.response_device_attachment.tensor()
    np.testing.assert_array_equal(np.asarray(out0), np.asarray(x0))

    # second call: domains learned, request goes device-resident
    cntl = Controller()
    cntl.timeout_ms = 30_000
    x = jnp.arange(262144, dtype=jnp.float32)     # 1MB
    cntl.request_device_attachment = x
    c = ch.call_method("TE.Echo", b"", cntl=cntl)
    assert not c.failed, c.error_text
    att = c.response_device_attachment
    assert att is not None and att.device_resident
    out = att.tensor()
    np.testing.assert_array_equal(np.asarray(out), np.asarray(x))
    # zero-copy proof: the service echoed our posted array; same-process
    # redemption hands back the very same buffer
    assert out.unsafe_buffer_pointer() == x.unsafe_buffer_pointer()


def test_device_response_only(server):
    ch = _channel(server)
    cntl = Controller()
    cntl.timeout_ms = 30_000
    c = ch.call_method("TE.Make", b"64", cntl=cntl)
    assert not c.failed, c.error_text
    att = c.response_device_attachment
    assert att is not None
    # the very FIRST response can already be device-resident: the server
    # learned our domain from the request meta
    assert att.device_resident
    out = att.tensor()
    np.testing.assert_array_equal(np.asarray(out),
                                  np.arange(64, dtype=np.float32))
    assert c.response == b"made"


def test_window_ack_credit_cycle(server):
    """Posted bytes count against the window until the peer's redemption
    ack returns credit (≈ RdmaEndpoint's sliding window)."""
    ch = _channel(server)
    warm = Controller(); warm.timeout_ms = 30_000
    ch.call_method("TE.Make", b"8", cntl=warm)       # learn domains

    from brpc_tpu.ici.endpoint import live_endpoints
    before = {id(ep) for ep in live_endpoints()}
    cntl = Controller()
    cntl.timeout_ms = 30_000
    cntl.request_device_attachment = jnp.ones((4096,), jnp.float32)
    c = ch.call_method("TE.Echo", b"", cntl=cntl)
    assert not c.failed
    c.response_device_attachment.tensor()            # redeem → acks flow
    eps = [ep for ep in live_endpoints() if id(ep) not in before]
    assert eps, "no ICI endpoints created by this call"
    deadline = time.time() + 5
    while time.time() < deadline:
        if all(ep.outstanding_bytes == 0 for ep in eps):
            break
        time.sleep(0.02)
    assert all(ep.outstanding_bytes == 0 for ep in eps), \
        [(ep.posted_count, ep.acked_count, ep.outstanding_bytes)
         for ep in eps]
    assert any(ep.acked_count for ep in eps)


def test_window_blocks_when_full():
    """post() blocks once outstanding ≥ window and resumes on ack."""
    old = get_flag("ici_window_bytes")
    assert set_flag("ici_window_bytes", 1024)
    try:
        ep = IciEndpoint(0)
        f = in_process_fabric()
        d1 = ep.post(jnp.zeros((128,), jnp.float32), 512)   # 512/1024
        d2 = ep.post(jnp.zeros((128,), jnp.float32), 512)   # 1024/1024
        assert d1 and d2
        results = []

        def poster():
            results.append(ep.post(jnp.zeros((1,)), 512, timeout_s=5.0))

        t = threading.Thread(target=poster)
        t.start()
        time.sleep(0.1)
        assert not results                   # blocked on the full window
        f.release(d1)                        # ack → credit back
        t.join(timeout=5)
        assert results and results[0] is not None
        f.release(d2)
        f.release(results[0])
    finally:
        set_flag("ici_window_bytes", old)


def test_window_full_times_out():
    old = get_flag("ici_window_bytes")
    assert set_flag("ici_window_bytes", 64)
    try:
        ep = IciEndpoint(0)
        d1 = ep.post(jnp.zeros((16,), jnp.float32), 64)
        assert d1 is not None
        assert ep.post(jnp.zeros((16,), jnp.float32), 64,
                       timeout_s=0.1) is None
        in_process_fabric().release(d1)
    finally:
        set_flag("ici_window_bytes", old)


def test_oversized_payload_admitted_alone():
    """A payload larger than the whole window must not deadlock: it is
    admitted when it is the only one in flight."""
    old = get_flag("ici_window_bytes")
    assert set_flag("ici_window_bytes", 100)
    try:
        ep = IciEndpoint(0)
        did = ep.post(jnp.zeros((1000,), jnp.float32), 4000,
                      timeout_s=2.0)
        assert did is not None
        in_process_fabric().release(did)
    finally:
        set_flag("ici_window_bytes", old)


def test_fallback_when_fabric_unreachable(server):
    """Peer domains that no fabric bridges ⇒ host-staged bytes (the
    use_rdma=false analogue) — still correct, still transparent."""
    ch = _channel(server)
    warm = Controller(); warm.timeout_ms = 30_000
    ch.call_method("TE.Make", b"8", cntl=warm)

    # poison the learned domain so can_reach() fails
    from brpc_tpu.transport.socket import Socket
    for s in range(1, 128):
        sock = Socket.address(s)
        if sock is not None and sock.ici_peer_domain is not None:
            sock.ici_peer_domain = b"\x00" * 16
    cntl = Controller()
    cntl.timeout_ms = 30_000
    x = jnp.arange(512, dtype=jnp.float32)
    cntl.request_device_attachment = x
    c = ch.call_method("TE.Echo", b"", cntl=cntl)
    assert not c.failed, c.error_text
    out = c.response_device_attachment.tensor()
    np.testing.assert_array_equal(np.asarray(out), np.asarray(x))


def test_user_attachment_coexists_with_device_attachment(server):
    """Byte attachment and device attachment ride the same frame without
    clobbering each other."""
    class Both(Service):
        def M(self, cntl, request):
            assert cntl.request_attachment.to_bytes() == b"user-bytes"
            cntl.response_attachment.append(b"resp-bytes")
            cntl.response_device_attachment = \
                cntl.request_device_attachment.tensor() * 2
            return b"ok"

    srv = Server()
    srv.add_service(Both(), name="B")
    assert srv.start("127.0.0.1:0") == 0
    try:
        ch = _channel(srv)
        for _ in range(2):                   # fallback then device path
            cntl = Controller()
            cntl.timeout_ms = 30_000
            cntl.request_attachment.append(b"user-bytes")
            cntl.request_device_attachment = jnp.ones((32,), jnp.float32)
            c = ch.call_method("B.M", b"", cntl=cntl)
            assert not c.failed, c.error_text
            assert c.response_attachment.to_bytes() == b"resp-bytes"
            out = np.asarray(c.response_device_attachment.tensor())
            np.testing.assert_array_equal(out, np.full((32,), 2.0,
                                                       np.float32))
    finally:
        srv.stop()


def test_multi_device_redeem_lands_on_target():
    """Redeeming onto another mesh device moves the buffer (the ICI hop)
    — runs on the 8-device CPU mesh."""
    devs = jax.devices()
    if len(devs) < 2:
        pytest.skip("needs multi-device mesh")
    f = InProcessFabric()
    x = jax.device_put(jnp.arange(1024, dtype=jnp.float32), devs[0])
    did = f.post(x, 4096)
    y = f.redeem(did, device=devs[3])
    assert list(y.devices()) == [devs[3]]
    np.testing.assert_array_equal(np.asarray(y), np.asarray(x))
    f.release(did)


def test_device_block_pool_recycles_hbm():
    """Same-size landings reuse the same HBM pages (donation recycling —
    the registered-memory reuse of rdma/block_pool)."""
    pool = DeviceBlockPool(max_bytes=1 << 20)
    payload = np.arange(8192, dtype=np.uint8).tobytes()
    a1 = pool.land(payload)
    ptr1 = a1.unsafe_buffer_pointer()
    np.testing.assert_array_equal(np.asarray(a1),
                                  np.frombuffer(payload, np.uint8))
    pool.recycle(a1)
    del a1
    a2 = pool.land(b"\xff" * 8192)
    assert pool.recycled == 1
    assert np.asarray(a2)[0] == 0xFF
    assert a2.unsafe_buffer_pointer() == ptr1      # same pages
    assert pool.pooled_bytes == 0


def test_device_block_pool_respects_cap():
    pool = DeviceBlockPool(max_bytes=100)
    a = pool.land(b"x" * 4096)
    pool.recycle(a)                     # over cap: dropped, not pooled
    assert pool.pooled_bytes == 0


def test_device_block_iobuf_interface():
    """DeviceBlock plugs into IOBuf (interface parity with HostBlockPool)
    and byte access stages D2H only on demand."""
    from brpc_tpu.butil.iobuf import IOBuf
    pool = DeviceBlockPool()
    blk = pool.allocate(64)
    assert blk.capacity == 64
    buf = IOBuf()
    buf._append_ref(blk, 0, 64)
    buf._size = 64
    assert bytes(buf) == b"\x00" * 64   # explicit lazy materialization


def test_expired_descriptor_raises_clean_error(server):
    ch = _channel(server)
    warm = Controller(); warm.timeout_ms = 30_000
    ch.call_method("TE.Make", b"8", cntl=warm)
    cntl = Controller()
    cntl.timeout_ms = 30_000
    c = ch.call_method("TE.Make", b"32", cntl=cntl)
    att = c.response_device_attachment
    assert att is not None and att.device_resident
    # simulate TTL reclaim before redemption
    in_process_fabric().release(att.desc_id)
    with pytest.raises(RuntimeError, match="expired"):
        att.tensor()


def test_forged_ack_from_other_connection_rejected():
    """Acks are bound to the posting connection (descriptor ownership —
    same spoof class the stream layer guards)."""
    from brpc_tpu.ici.endpoint import _process_ack

    f = in_process_fabric()
    ep = IciEndpoint(777)
    did = ep.post(jnp.zeros((8,), jnp.float32), 32)

    class FakeSock:
        def __init__(self, sid):
            self.id = sid

    _process_ack((did,), FakeSock(999))          # wrong connection
    assert f.redeem(did) is not None             # still posted
    assert ep.outstanding_bytes == 32
    _process_ack((did,), FakeSock(777))          # rightful owner
    assert f.redeem(did) is None
    assert ep.outstanding_bytes == 0


def test_socket_death_reclaims_posted_descriptors():
    f = in_process_fabric()
    ep = IciEndpoint(31337)
    did = ep.post(jnp.zeros((8,), jnp.float32), 32)
    assert f.release_socket(31337) == 1
    assert ep.outstanding_bytes == 0
    assert f.redeem(did) is None


def test_dropped_attachment_acks_on_gc(server):
    """A DeviceAttachment discarded without .tensor() returns the
    poster's window credit via a GC-time ack."""
    import gc
    from brpc_tpu.ici.endpoint import live_endpoints

    ch = _channel(server)
    warm = Controller(); warm.timeout_ms = 30_000
    ch.call_method("TE.Make", b"8", cntl=warm)
    if warm.response_device_attachment is not None:
        warm.response_device_attachment.tensor()     # redeem+ack the warmup
    cntl = Controller()
    cntl.timeout_ms = 30_000
    c = ch.call_method("TE.Make", b"256", cntl=cntl)
    assert not c.failed and c.response_device_attachment.device_resident
    eps = [ep for ep in live_endpoints() if ep.posted_count]
    assert eps, "server posted no descriptors"
    c.response_device_attachment = None          # drop unredeemed
    del c, cntl
    gc.collect()
    deadline = time.time() + 5
    while time.time() < deadline:
        if all(ep.outstanding_bytes == 0 for ep in eps):
            break
        time.sleep(0.02)
    assert all(ep.outstanding_bytes == 0 for ep in eps), \
        [(ep.posted_count, ep.acked_count, ep.outstanding_bytes)
         for ep in eps]


def test_ici_disabled_flag_still_delivers_tensor(server):
    """-ici_enabled=false must degrade to host staging, never drop the
    attachment."""
    assert set_flag("ici_enabled", False)
    try:
        ch = _channel(server)
        cntl = Controller()
        cntl.timeout_ms = 30_000
        x = jnp.arange(128, dtype=jnp.float32)
        cntl.request_device_attachment = x
        c = ch.call_method("TE.Echo", b"", cntl=cntl)
        assert not c.failed, c.error_text
        att = c.response_device_attachment
        assert att is not None and not att.device_resident
        np.testing.assert_array_equal(np.asarray(att.tensor()),
                                      np.asarray(x))
    finally:
        assert set_flag("ici_enabled", True)


def test_malformed_descriptor_dropped_cleanly():
    from brpc_tpu.butil.iobuf import IOBuf
    from brpc_tpu.ici.endpoint import split_device_attachment
    from brpc_tpu.protocol.meta import RpcMeta

    meta = RpcMeta()
    meta.ici_desc = b"\x01"                      # truncated
    att = IOBuf(b"payload")
    out, dev = split_device_attachment(meta, att, 1)
    assert dev is None
    assert out.to_bytes() == b"payload"


def test_redeem_bound_to_connection_pair():
    """A descriptor posted for one connection cannot be redeemed through
    another (cross-connection tensor disclosure guard)."""
    f = InProcessFabric()
    x = jnp.ones((16,), jnp.float32)
    key = (("127.0.0.1", 1111), ("127.0.0.1", 2222))
    did = f.post(x, 64, conn_key=key)
    assert f.redeem(did, conn_key=(("127.0.0.1", 1111),
                                   ("127.0.0.1", 3333))) is None
    assert f.redeem(did, conn_key=None) is None
    assert f.redeem(did, conn_key=key) is x
    f.release(did)


def test_oversized_attachment_fails_cleanly(server):
    """>4GiB attachments are refused with an RPC error before any window
    credit or staging is spent (descriptor nbytes is u32)."""
    class Fake:
        dtype = np.dtype("float32")
        shape = (1 << 31,)
        size = 1 << 31
    from brpc_tpu.ici.endpoint import prepare_send

    class SockStub:
        id = 1
        ici_peer_domain = None
        remote_side = None
        local_side = None
        fd = None
        ici_endpoint = None

    import jax as _jax
    real = _jax.Array
    try:
        _jax.Array = (Fake,)  # make isinstance pass for the stub
    except TypeError:
        pytest.skip("cannot stub jax.Array")
    try:
        from brpc_tpu.protocol.meta import RpcMeta
        with pytest.raises(RuntimeError, match="4GiB"):
            prepare_send(SockStub(), RpcMeta(), Fake())
    finally:
        _jax.Array = real


def test_device_attachment_on_fast_lane(server):
    """Device descriptors ride the sync fast lane (pooled connections):
    request AND response stay device-resident, the server's in-handler
    ack piggybacks in front of the response (consumed by sync_call),
    and window credit drains back to zero without a dispatcher."""
    from brpc_tpu.client import ChannelOptions
    from brpc_tpu.ici.endpoint import live_endpoints

    opts = ChannelOptions()
    opts.connection_type = "pooled"
    ch = Channel(opts)
    ch.init(str(server.listen_endpoint))

    x = jnp.arange(65536, dtype=jnp.float32)          # 256KB
    out = None
    for i in range(3):        # first call learns the domain (fallback)
        cntl = Controller()
        cntl.timeout_ms = 30_000
        cntl.request_device_attachment = x
        c = ch.call_method("TE.Echo", b"", cntl=cntl)
        assert not c.failed, (i, c.error_text)
        att = c.response_device_attachment
        assert att is not None
        out = att.tensor()
        np.testing.assert_array_equal(np.asarray(out), np.asarray(x))
    # descriptor path engaged: same-process redemption is the same buffer
    assert out.unsafe_buffer_pointer() == x.unsafe_buffer_pointer()
    # acks flowed back through sync_call: no credit left outstanding
    deadline = time.time() + 5.0
    while time.time() < deadline:
        if all(ep.outstanding_bytes == 0 for ep in live_endpoints()):
            break
        time.sleep(0.01)
    assert all(ep.outstanding_bytes == 0 for ep in live_endpoints()), \
        [(ep.posted_count, ep.acked_count, ep.outstanding_bytes)
         for ep in live_endpoints()]


def test_fast_lane_batch_with_descriptors(server):
    """Pipelined sibling: several descriptor-carrying calls in flight on
    one pooled connection; every response redeems to the posted buffer
    and every ack (interleaved TICI frames in the batch read) lands."""
    from brpc_tpu.client import ChannelOptions
    from brpc_tpu.ici.endpoint import live_endpoints

    opts = ChannelOptions()
    opts.connection_type = "pooled"
    ch = Channel(opts)
    ch.init(str(server.listen_endpoint))
    x = jnp.arange(16384, dtype=jnp.float32)
    for _ in range(2):                     # learn domain
        cntl = Controller()
        cntl.timeout_ms = 30_000
        cntl.request_device_attachment = x
        c = ch.call_method("TE.Echo", b"", cntl=cntl)
        assert not c.failed, c.error_text
        c.response_device_attachment.tensor()
    for _ in range(8):
        cntl = Controller()
        cntl.timeout_ms = 30_000
        cntl.request_device_attachment = x
        c = ch.call_method("TE.Echo", b"", cntl=cntl)
        assert not c.failed, c.error_text
        got = c.response_device_attachment.tensor()
        assert got.unsafe_buffer_pointer() == x.unsafe_buffer_pointer()
    deadline = time.time() + 5.0
    while time.time() < deadline:
        if all(ep.outstanding_bytes == 0 for ep in live_endpoints()):
            break
        time.sleep(0.01)
    assert all(ep.outstanding_bytes == 0 for ep in live_endpoints())


def test_ignored_request_attachment_settles_before_response(server):
    """A handler that never redeems the request descriptor: the server
    settles it when the response is sent, so the credit-return still
    PRECEDES the response on the wire (the fast lane's read loop
    depends on that) and the window drains without the TTL sweep."""
    from brpc_tpu.client import ChannelOptions
    from brpc_tpu.ici.endpoint import live_endpoints

    opts = ChannelOptions()
    opts.connection_type = "pooled"
    ch = Channel(opts)
    ch.init(str(server.listen_endpoint))
    x = jnp.arange(8192, dtype=jnp.float32)
    for i in range(4):
        cntl = Controller()
        cntl.timeout_ms = 30_000
        cntl.request_device_attachment = x
        # TE.Make ignores the request attachment entirely
        c = ch.call_method("TE.Make", b"8", cntl=cntl)
        assert not c.failed, (i, c.error_text)
        assert c.response == b"made"
        c.response_device_attachment.tensor()
    deadline = time.time() + 5.0
    while time.time() < deadline:
        if all(ep.outstanding_bytes == 0 for ep in live_endpoints()):
            break
        time.sleep(0.01)
    assert all(ep.outstanding_bytes == 0 for ep in live_endpoints()), \
        [(ep.posted_count, ep.acked_count, ep.outstanding_bytes)
         for ep in live_endpoints()]
