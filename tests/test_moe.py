"""MoE expert-parallel layer tests: routing correctness, capacity
drops, dense equivalence with one expert, ep-sharded equivalence on the
virtual mesh, and training descent."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from brpc_tpu.models.moe import (MoEConfig, forward, forward_grouped,
                                 init_params, make_train_step, param_specs)


def _data(cfg, tokens=32, seed=0):
    return jax.random.normal(jax.random.PRNGKey(seed), (tokens, cfg.dim),
                             jnp.float32)


def test_shapes_and_finite():
    cfg = MoEConfig(dim=16, hidden=32, num_experts=4)
    params = init_params(jax.random.PRNGKey(0), cfg)
    x = _data(cfg)
    out, aux = jax.jit(lambda p, x: forward(p, x, cfg))(params, x)
    assert out.shape == x.shape
    assert jnp.isfinite(out).all() and jnp.isfinite(aux)
    assert float(aux) > 0


def test_single_expert_equals_dense_ffn():
    """E=1 with ample capacity routes every token through the one
    expert with gate prob 1.0 — identical to a plain FFN."""
    cfg = MoEConfig(dim=16, hidden=32, num_experts=1, capacity_factor=1.0)
    params = init_params(jax.random.PRNGKey(0), cfg)
    x = _data(cfg, tokens=16)
    out, _ = forward(params, x, cfg)
    h = jax.nn.gelu((x.astype(jnp.bfloat16)
                     @ params["w1"][0].astype(jnp.bfloat16)
                     ).astype(jnp.float32)).astype(jnp.bfloat16)
    dense = (h @ params["w2"][0].astype(jnp.bfloat16)).astype(jnp.float32)
    np.testing.assert_allclose(np.asarray(out), np.asarray(dense),
                               rtol=2e-2, atol=2e-2)


def test_capacity_overflow_drops_tokens():
    """With capacity far below demand, some tokens contribute zero
    output; with ample capacity none do."""
    cfg_tight = MoEConfig(dim=8, hidden=16, num_experts=2,
                          capacity_factor=0.25)
    cfg_ample = MoEConfig(dim=8, hidden=16, num_experts=2,
                          capacity_factor=4.0)
    params = init_params(jax.random.PRNGKey(1), cfg_tight)
    x = _data(cfg_tight, tokens=64, seed=3)
    out_t, _ = forward(params, x, cfg_tight)
    out_a, _ = forward(params, x, cfg_ample)
    zero_rows_t = int(jnp.sum(jnp.all(out_t == 0, axis=-1)))
    zero_rows_a = int(jnp.sum(jnp.all(out_a == 0, axis=-1)))
    assert zero_rows_t > 0          # overflow dropped
    assert zero_rows_a == 0         # nothing dropped


def test_grouped_equals_per_group_forward():
    """forward_grouped == stacking forward over each group (linear-
    memory GShard grouping changes nothing numerically)."""
    cfg = MoEConfig(dim=8, hidden=16, num_experts=2, capacity_factor=2.0)
    params = init_params(jax.random.PRNGKey(2), cfg)
    x = jax.random.normal(jax.random.PRNGKey(3), (4, 16, cfg.dim))
    got, aux = forward_grouped(params, x, cfg)
    per = [forward(params, x[g], cfg) for g in range(4)]
    want = jnp.stack([o for o, _ in per])
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(
        float(aux), float(np.mean([float(a) for _, a in per])), rtol=1e-5)


def test_ep_sharded_matches_single_device():
    n = len(jax.devices())
    if n < 4:
        pytest.skip("needs the virtual multi-device mesh")
    cfg = MoEConfig(dim=16, hidden=32, num_experts=n, capacity_factor=2.0)
    params = init_params(jax.random.PRNGKey(0), cfg)
    x = _data(cfg, tokens=8 * n)
    want, _ = jax.jit(lambda p, x: forward(p, x, cfg))(params, x)

    mesh = Mesh(np.array(jax.devices()), ("ep",))
    specs = param_specs(cfg)
    sharded = jax.tree_util.tree_map(
        lambda p, s: jax.device_put(p, NamedSharding(mesh, s)),
        params, specs)
    x_sh = jax.device_put(x, NamedSharding(mesh, P(None, None)))
    with mesh:
        got, _ = jax.jit(lambda p, x: forward(p, x, cfg))(sharded, x_sh)
        jax.block_until_ready(got)
    assert len(sharded["w1"].sharding.device_set) == n  # really ep-sharded
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-2, atol=2e-2)


def test_top2_matches_dense_mixture():
    """top_k=2 with ample capacity == dense renormalized mixture of the
    two best experts, computed by brute force."""
    cfg = MoEConfig(dim=8, hidden=16, num_experts=4, capacity_factor=4.0,
                    top_k=2)
    params = init_params(jax.random.PRNGKey(4), cfg)
    x = _data(cfg, tokens=24, seed=8)
    got, _ = forward(params, x, cfg)

    probs = jax.nn.softmax(x @ params["wg"], axis=-1)
    topv, tope = jax.lax.top_k(probs, 2)
    topv = topv / topv.sum(axis=-1, keepdims=True)

    def ffn(e, xi):
        h = jax.nn.gelu((xi.astype(jnp.bfloat16)
                         @ params["w1"][e].astype(jnp.bfloat16)
                         ).astype(jnp.float32)).astype(jnp.bfloat16)
        return (h @ params["w2"][e].astype(jnp.bfloat16)
                ).astype(jnp.float32)

    want = jnp.stack([
        sum(float(topv[t, j]) * ffn(int(tope[t, j]), x[t])
            for j in range(2))
        for t in range(x.shape[0])])
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=3e-2, atol=3e-2)


def test_top2_trains_and_ep_shards():
    n = len(jax.devices())
    if n < 4:
        pytest.skip("needs the virtual multi-device mesh")
    cfg = MoEConfig(dim=16, hidden=32, num_experts=n, capacity_factor=2.0,
                    top_k=2)
    params = init_params(jax.random.PRNGKey(0), cfg)
    x = _data(cfg, tokens=8 * n)
    want, _ = jax.jit(lambda p, x: forward(p, x, cfg))(params, x)
    mesh = Mesh(np.array(jax.devices()), ("ep",))
    sharded = jax.tree_util.tree_map(
        lambda p, s: jax.device_put(p, NamedSharding(mesh, s)),
        params, param_specs(cfg))
    with mesh:
        got, _ = jax.jit(lambda p, x: forward(p, x, cfg))(sharded, x)
        jax.block_until_ready(got)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-2, atol=2e-2)

    # gradients flow through the K>1 path: a few train steps descend
    target = jnp.tanh(x @ jax.random.normal(jax.random.PRNGKey(7),
                                            (cfg.dim, cfg.dim)) * 0.5)
    step = jax.jit(make_train_step(cfg, lr=0.2))
    first = None
    for _ in range(25):
        params, loss = step(params, x, target)
        first = first if first is not None else float(loss)
    assert jnp.isfinite(loss)
    assert float(loss) < first * 0.9, (first, float(loss))


def test_training_descends_and_uses_multiple_experts():
    cfg = MoEConfig(dim=16, hidden=32, num_experts=4, capacity_factor=2.0)
    params = init_params(jax.random.PRNGKey(0), cfg)
    x = _data(cfg, tokens=64, seed=5)
    target = jnp.tanh(x @ jax.random.normal(jax.random.PRNGKey(6),
                                            (cfg.dim, cfg.dim)) * 0.5)
    step = jax.jit(make_train_step(cfg, lr=0.2))
    first = None
    for _ in range(60):
        params, loss = step(params, x, target)
        first = first if first is not None else float(loss)
    assert float(loss) < first * 0.75, (first, float(loss))
    # routing actually spreads load after training
    probs = jax.nn.softmax(x @ params["wg"], axis=-1)
    used = int(jnp.sum(jnp.bincount(jnp.argmax(probs, axis=-1),
                                    length=cfg.num_experts) > 0))
    assert used >= 2
