"""IOBuf tests — modeled on the reference's test strategy
(/root/reference/test/iobuf_unittest.cpp): build/cut/share semantics,
zero-copy invariants, socket integration."""

import os
import socket
import threading

import pytest

from brpc_tpu.butil.iobuf import (IOBuf, IOPortal, IOBufAppender, IOBufReader,
                                  HostBlockPool, DEFAULT_BLOCK_SIZE)


def test_empty():
    b = IOBuf()
    assert len(b) == 0
    assert b.empty()
    assert b.to_bytes() == b""
    assert b.fetch1() is None


def test_append_and_materialize():
    b = IOBuf()
    b.append(b"hello ")
    b.append("world")
    assert len(b) == 11
    assert bytes(b) == b"hello world"
    assert b == b"hello world"


def test_append_large_bytes_attaches_zero_copy():
    """Large immutable ``bytes`` attach as ONE user block (zero-copy fast
    path) instead of being chopped into pool blocks."""
    b = IOBuf()
    payload = os.urandom(3 * DEFAULT_BLOCK_SIZE + 123)
    b.append(payload)
    assert len(b) == len(payload)
    assert bytes(b) == payload
    assert b.backing_block_count == 1
    # zero-copy: the block's storage IS the payload object (views now
    # export via the Block so recycling can't outrun them — their .obj
    # is the block wrapper, not the storage)
    assert b._refs[0][0].data is payload


def test_append_spanning_blocks():
    """Mutable buffers must be copied into pool blocks (they can change
    under us), so a large bytearray spans multiple blocks."""
    b = IOBuf()
    payload = bytearray(os.urandom(3 * DEFAULT_BLOCK_SIZE + 123))
    b.append(payload)
    assert len(b) == len(payload)
    assert bytes(b) == bytes(payload)
    assert b.backing_block_count >= 3
    payload[:] = b"\x00" * len(payload)   # mutation must not leak through
    assert bytes(b) != bytes(payload)


def test_small_appends_pack_into_shared_block():
    b = IOBuf()
    for i in range(100):
        b.append(b"x" * 10)
    # 1000 bytes should live in very few blocks thanks to the TLS open block
    assert b.backing_block_count <= 2
    assert len(b) == 1000


def test_append_iobuf_shares_blocks():
    a = IOBuf(b"A" * 1000)
    b = IOBuf()
    b.append_iobuf(a)
    b.append_iobuf(a)
    assert len(b) == 2000
    assert bytes(b) == b"A" * 2000
    # sharing: no new blocks created beyond a's
    assert b.backing_block_count <= a.backing_block_count * 2


def test_append_user_data_zero_copy():
    payload = bytearray(b"Z" * 100000)
    b = IOBuf()
    b.append_user_data(memoryview(payload))
    assert len(b) == 100000
    assert b.backing_block_count == 1
    # zero-copy: mutating the user buffer is visible through the view
    payload[0:1] = b"A"
    assert bytes(b.backing_views()[0][:1]) == b"A"


def test_cutn():
    b = IOBuf(b"0123456789")
    head = b.cutn(4)
    assert bytes(head) == b"0123"
    assert bytes(b) == b"456789"
    assert len(b) == 6
    # cut more than available
    rest = b.cutn(100)
    assert bytes(rest) == b"456789"
    assert b.empty()


def test_cutn_zero_copy_shares_storage():
    payload = os.urandom(2 * DEFAULT_BLOCK_SIZE)
    b = IOBuf(payload)
    head = b.cutn(DEFAULT_BLOCK_SIZE + 10)
    assert bytes(head) + bytes(b) == payload


def test_pop_front_back():
    b = IOBuf(b"abcdefgh")
    assert b.pop_front(2) == 2
    assert b.pop_back(2) == 2
    assert bytes(b) == b"cdef"
    assert b.pop_front(100) == 4
    assert b.empty()


def test_fetch_and_copy_to():
    b = IOBuf(b"hello world")
    assert b.fetch(5) == b"hello"
    assert len(b) == 11  # peek doesn't consume
    assert b.copy_to(5, pos=6) == b"world"
    assert b.fetch1() == ord("h")


def test_push_back():
    b = IOBuf()
    for c in b"abc":
        b.push_back(c)
    assert bytes(b) == b"abc"


def test_appender():
    app = IOBufAppender()
    for i in range(1000):
        app.append(f"{i},")
    buf = app.flush()
    assert bytes(buf) == "".join(f"{i}," for i in range(1000)).encode()


def test_reader():
    b = IOBuf(b"0123456789")
    r = IOBufReader(b)
    assert r.read(3) == b"012"
    assert r.read(3) == b"345"
    assert r.remaining() == 4
    assert len(b) == 10  # non-consuming


def test_socket_roundtrip():
    """cut_into_socket / append_from_socket over a socketpair (the loopback
    pattern from the reference tests)."""
    a, b = socket.socketpair()
    try:
        src = IOBuf(os.urandom(100000))
        want = bytes(src)
        received = IOPortal()

        def reader():
            while len(received) < len(want):
                if received.append_from_socket(b) == 0:
                    break

        t = threading.Thread(target=reader)
        t.start()
        while not src.empty():
            src.cut_into_socket(a)
        t.join(timeout=10)
        assert bytes(received) == want
    finally:
        a.close()
        b.close()


def test_block_pool_gc_recycling():
    """Storage returns to the pool only when the last reference dies —
    recycled slabs can never alias live zero-copy views."""
    import gc
    import sys
    if sys.version_info < (3, 12):
        pytest.skip("recycling requires PEP-688 Block.__buffer__ "
                    "(disabled pre-3.12 to keep the no-aliasing "
                    "invariant — see HostBlockPool.allocate)")
    pool = HostBlockPool(block_size=1024)
    blk = pool.allocate()
    assert blk.capacity == 1024
    data_id = id(blk.data)
    del blk
    gc.collect()
    blk2 = pool.allocate()
    assert pool.reused == 1
    assert id(blk2.data) == data_id


def test_block_not_recycled_while_iobuf_alive():
    import gc
    pool = HostBlockPool(block_size=1024)
    blk = pool.allocate()
    blk.data[0:5] = b"hello"
    blk.size = 5
    buf = IOBuf()
    buf._append_ref(blk, 0, 5)
    buf._size = 5
    del blk
    gc.collect()
    blk2 = pool.allocate()   # must NOT hand back the referenced storage
    blk2.data[0:5] = b"WORLD"
    assert bytes(buf) == b"hello"


def test_instance_pool_injection():
    """A custom pool (the DMA/HBM hook) can be injected per-IOBuf."""
    pool = HostBlockPool(block_size=256)
    b = IOBuf(pool=pool)
    b.append(b"x" * 1000)
    assert bytes(b) == b"x" * 1000
    assert pool.allocated >= 4  # all storage came from the injected pool


def test_reader_linear_chunked():
    payload = os.urandom(5 * DEFAULT_BLOCK_SIZE)
    b = IOBuf(payload)
    r = IOBufReader(b)
    got = bytearray()
    while r.remaining():
        got += r.read(1000)
    assert bytes(got) == payload


def test_doubly_buffered_nested_isolation():
    from brpc_tpu.butil import DoublyBufferedData
    d = DoublyBufferedData({"servers": ["a", "b"]})
    snap = d.read()
    d.modify(lambda m: m["servers"].append("c"))
    assert snap["servers"] == ["a", "b"]      # old snapshot isolated (RCU)
    assert d.read()["servers"] == ["a", "b", "c"]


def test_multithreaded_append_isolation():
    """Each thread packs into its own TLS block; buffers must not corrupt."""
    results = {}

    def worker(tid):
        b = IOBuf()
        for i in range(500):
            b.append(bytes([tid]) * 7)
        results[tid] = bytes(b)

    threads = [threading.Thread(target=worker, args=(t,)) for t in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for tid, data in results.items():
        assert data == bytes([tid]) * 3500


def test_views_pin_blocks_against_recycling():
    """Regression: zero-copy views must keep the BLOCK alive — pool
    recycling must never hand a live view's storage to a new IOBuf
    (this corrupted deferred native writes: all pipelined responses
    became the last frame)."""
    import gc
    b = IOBuf(b"A" * 1000)
    views = b.backing_views()
    del b
    gc.collect()
    # churn the pool hard: any recycled storage would be overwritten
    for i in range(64):
        IOBuf(bytes([i]) * 1000)
    # the append may have split across blocks (depends on how full the
    # thread's open block was) — the pinning guarantee covers the
    # concatenation
    assert b"".join(bytes(v) for v in views) == b"A" * 1000
