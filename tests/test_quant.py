"""Weight-only int8 quantization (ops/quant.py) — the serving-memory
half of the LM family: per-channel symmetric quantization, fused
dequant matmul, quantized KV-cache decode, and the RPC service flag."""

import jax
import jax.numpy as jnp
import numpy as np

from brpc_tpu.ops.quant import (QuantTensor, dequantize, qmatmul,
                                quantize_int8, quantize_lm_params,
                                quantized_nbytes)


def test_quantize_roundtrip_error():
    w = jax.random.normal(jax.random.PRNGKey(0), (128, 256), jnp.float32)
    qw = quantize_int8(w)
    assert qw.q.dtype == jnp.int8
    assert qw.s.shape == (256,)
    err = np.abs(np.asarray(dequantize(qw)) - np.asarray(w))
    # symmetric int8: max error is half a quantization step per channel
    step = np.asarray(qw.s)
    assert (err <= step[None, :] * 0.51).all()


def test_qmatmul_close_to_float():
    k1, k2 = jax.random.split(jax.random.PRNGKey(1))
    x = jax.random.normal(k1, (4, 128), jnp.float32)
    w = jax.random.normal(k2, (128, 64), jnp.float32)
    want = x @ w
    got = qmatmul(x, quantize_int8(w))
    # relative error budget: int8 weight noise + bf16 accumulation
    rel = np.abs(np.asarray(got - want)) / (np.abs(np.asarray(want)) + 1)
    assert rel.mean() < 0.02, rel.mean()


def test_qmatmul_passthrough_plain_weight():
    x = jnp.ones((2, 8), jnp.float32)
    w = jnp.ones((8, 4), jnp.float32)
    np.testing.assert_allclose(np.asarray(qmatmul(x, w)),
                               np.asarray(jnp.full((2, 4), 8.0)),
                               rtol=1e-2)


def test_quantized_params_shrink_4x():
    from brpc_tpu.models.transformer_lm import LMConfig, init_params
    cfg = LMConfig(vocab=128, dim=64, heads=4, depth=2, max_seq=64)
    params = init_params(jax.random.PRNGKey(0), cfg)
    qparams = quantize_lm_params(params)
    full = quantized_nbytes(params)
    quant = quantized_nbytes(qparams)
    # matmul weights dominate this config; overall shrink must be >2x
    # (embeddings stay f32), matmul weights themselves 4x
    assert quant < full / 2, (full, quant)
    blk = qparams["blk0"]
    assert isinstance(blk["wqkv"], QuantTensor)
    assert isinstance(qparams["unembed"], QuantTensor)
    assert not isinstance(qparams["embed"], QuantTensor)


def test_quantized_decode_matches_float_greedy():
    """Greedy generation from quantized weights should track the float
    model closely on a short horizon (same argmax most steps)."""
    from brpc_tpu.models.transformer_lm import (LMConfig, init_params,
                                                make_generator)
    cfg = LMConfig(vocab=64, dim=64, heads=4, depth=2, max_seq=48,
                   remat=False)
    params = init_params(jax.random.PRNGKey(0), cfg)
    gen_f = make_generator(cfg, params)
    gen_q = make_generator(cfg, quantize_lm_params(params))
    prompt = np.arange(8, dtype=np.int32)[None, :] % cfg.vocab
    out_f = np.asarray(gen_f(prompt, 12))
    out_q = np.asarray(gen_q(prompt, 12))
    assert out_f.shape == out_q.shape
    agree = (out_f == out_q).mean()
    assert agree >= 0.75, (agree, out_f, out_q)


def test_quantized_lm_service_over_rpc(server_options):
    from brpc_tpu.client import Channel
    from brpc_tpu.models.lm_service import (LMService,
                                            pack_generate_request,
                                            unpack_generated)
    from brpc_tpu.models.transformer_lm import LMConfig
    from brpc_tpu.server import Server

    cfg = LMConfig(vocab=64, dim=32, heads=4, depth=1, max_seq=64,
                   remat=False)
    srv = Server(server_options)
    srv.add_service(LMService(cfg=cfg, quantize=True), name="LM")
    assert srv.start("127.0.0.1:0") == 0
    try:
        from brpc_tpu.client import Controller
        ch = Channel()
        ch.init(str(srv.listen_endpoint))
        prompt = np.array([[1, 2, 3]], dtype=np.int32)
        cntl = Controller()
        cntl.timeout_ms = 120_000       # first call compiles the jits
        c = ch.call_method("LM.Generate",
                           pack_generate_request(prompt, 4), cntl=cntl)
        assert not c.failed, c.error_text
        out = unpack_generated(c.response)
        assert out.shape == (1, 4)      # the new tokens
        assert ((out >= 0) & (out < cfg.vocab)).all()
        import json
        info = json.loads(ch.call("LM.Info", b""))
        assert info["quantized"] is True
        assert info["param_bytes"] > 0
    finally:
        srv.stop()


def test_quantize_scan_layers_tree():
    """Stacked trees quantize with per-(layer, out-channel) scales —
    round-4 upgrade from the old reject-with-ValueError behavior (the
    scanned decode consumes these, test_lm_decode)."""
    from brpc_tpu.models.transformer_lm import LMConfig, init_params
    from brpc_tpu.ops.quant import QuantTensor
    cfg = LMConfig(vocab=64, dim=32, heads=4, depth=2, max_seq=32,
                   scan_layers=True)
    params = init_params(jax.random.PRNGKey(0), cfg)
    qp = quantize_lm_params(params)
    w = qp["blocks"]["wqkv"]
    assert isinstance(w, QuantTensor)
    assert w.q.shape == (2, 32, 3 * 32) and w.q.dtype.name == "int8"
    assert w.s.shape == (2, 3 * 32)
    # layernorm gains stay full precision
    assert not isinstance(qp["blocks"]["ln1"], QuantTensor)


def test_quantize_is_idempotent():
    from brpc_tpu.models.transformer_lm import LMConfig, init_params
    cfg = LMConfig(vocab=64, dim=32, heads=4, depth=1, max_seq=32)
    params = init_params(jax.random.PRNGKey(0), cfg)
    q1 = quantize_lm_params(params)
    q2 = quantize_lm_params(q1)          # no crash, same tensors
    assert q2["blk0"]["wqkv"].q is q1["blk0"]["wqkv"].q
