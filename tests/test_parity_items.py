"""Small parity items: restful mapping, progressive attachment,
SimpleDataPool, PeriodicTask, WorkStealingQueue
(≈ /root/reference/src/brpc/restful.cpp, progressive_attachment.h,
simple_data_pool.h, periodic_task.h, bthread/work_stealing_queue.h)."""

import http.client
import threading
import time

import pytest

from brpc_tpu.butil.periodic_task import PeriodicTask
from brpc_tpu.butil.simple_data_pool import SimpleDataPool
from brpc_tpu.butil.work_stealing_queue import WorkStealingQueue
from brpc_tpu.server import Server, ServerOptions, Service


# -- restful ----------------------------------------------------------------

class Files(Service):
    def Get(self, cntl, request):
        return b"file:" + cntl.http_unresolved_path.encode()

    def Echo(self, cntl, request):
        return b"restful:" + request


@pytest.fixture(scope="module")
def restful_server():
    opts = ServerOptions()
    opts.restful_mappings = \
        "/v1/echo => F.Echo, /files/* => F.Get"
    srv = Server(opts)
    srv.add_service(Files(), name="F")
    assert srv.start("127.0.0.1:0") == 0
    yield srv
    srv.stop()


def _http(server, method, path, body=b""):
    ep = server.listen_endpoint
    c = http.client.HTTPConnection(ep.host, ep.port, timeout=10)
    c.request(method, path, body=body or None)
    r = c.getresponse()
    data = r.read()
    c.close()
    return r.status, data


def test_restful_exact_mapping(restful_server):
    status, body = _http(restful_server, "POST", "/v1/echo", b"hi")
    assert status == 200 and body == b"restful:hi"


def test_restful_wildcard_captures_rest(restful_server):
    status, body = _http(restful_server, "GET", "/files/a/b/c.txt")
    assert status == 200 and body == b"file:a/b/c.txt"
    status, body = _http(restful_server, "GET", "/files")
    assert status == 200 and body == b"file:"


def test_restful_direct_path_still_works(restful_server):
    status, body = _http(restful_server, "POST", "/F/Echo", b"direct")
    assert status == 200 and body == b"restful:direct"


# -- progressive attachment -------------------------------------------------

def test_progressive_attachment_chunked():
    done = threading.Event()

    class Prog(Service):
        def Download(self, cntl, request):
            pa = cntl.create_progressive_attachment()

            def feed():
                for i in range(3):
                    pa.write(b"part%d|" % i)
                pa.close()
                done.set()
            threading.Thread(target=feed, daemon=True).start()
            return b"head|"

    srv = Server()
    srv.add_service(Prog(), name="P")
    assert srv.start("127.0.0.1:0") == 0
    try:
        ep = srv.listen_endpoint
        c = http.client.HTTPConnection(ep.host, ep.port, timeout=10)
        c.request("GET", "/P/Download")
        r = c.getresponse()
        assert r.getheader("transfer-encoding") == "chunked"
        data = r.read()          # http.client de-chunks
        c.close()
        assert done.wait(5)
        assert data == b"head|part0|part1|part2|"
    finally:
        srv.stop()


# -- SimpleDataPool ---------------------------------------------------------

def test_simple_data_pool_recycles():
    made = []

    def factory():
        obj = {"n": len(made)}
        made.append(obj)
        return obj

    pool = SimpleDataPool(factory, max_cached=2)
    a = pool.borrow()
    b = pool.borrow()
    assert pool.created == 2
    pool.give_back(a)
    c = pool.borrow()
    assert c is a                    # recycled, not re-created
    assert pool.created == 2
    pool.give_back(b)
    pool.give_back(c)
    assert pool.free_count == 2


def test_session_local_data_end_to_end():
    from brpc_tpu.client import Channel

    class Svc(Service):
        def Use(self, cntl, request):
            d = cntl.session_local_data()
            d["hits"] = d.get("hits", 0) + 1
            return b"%d" % d["hits"]

    opts = ServerOptions()
    opts.session_local_data_factory = dict
    srv = Server(opts)
    srv.add_service(Svc(), name="S")
    assert srv.start("127.0.0.1:0") == 0
    try:
        ch = Channel()
        ch.init(str(srv.listen_endpoint))
        for _ in range(5):
            n = int(ch.call("S.Use", b""))
            assert n >= 1            # data object is reused across calls
        assert srv._session_pool.created <= 2   # pooled, not per-request
    finally:
        srv.stop()


# -- PeriodicTask -----------------------------------------------------------

def test_periodic_task_runs_and_stops():
    runs = []
    t = PeriodicTask(0.05, lambda: runs.append(time.monotonic()))
    time.sleep(0.4)
    t.stop()
    n = len(runs)
    assert 2 <= n <= 10, n
    time.sleep(0.2)
    assert len(runs) == n            # stopped means stopped


def test_periodic_task_return_false_stops():
    runs = []

    def once():
        runs.append(1)
        return False

    t = PeriodicTask(0.05, once)
    time.sleep(0.3)
    assert len(runs) == 1
    t.stop()


def test_periodic_task_retargets_interval():
    stamps = []

    def fn():
        stamps.append(time.monotonic())
        return 0.2                    # slow down after the first run

    t = PeriodicTask(0.02, fn)
    time.sleep(0.5)
    t.stop()
    assert len(stamps) >= 2
    assert stamps[1] - stamps[0] >= 0.15   # retargeted gap


# -- WorkStealingQueue ------------------------------------------------------

def test_wsq_lifo_pop_fifo_steal():
    q = WorkStealingQueue()
    for i in range(5):
        assert q.push(i)
    ok, item = q.pop()
    assert ok and item == 4          # owner pops newest
    ok, item = q.steal()
    assert ok and item == 0          # thief steals oldest
    assert len(q) == 3


def test_wsq_concurrent_steal_exactly_once():
    q = WorkStealingQueue(capacity=100000)
    N = 20000
    for i in range(N):
        q.push(i)
    got = []
    lock = threading.Lock()

    def thief():
        local = []
        while True:
            ok, item = q.steal()
            if not ok:
                break
            local.append(item)
        with lock:
            got.extend(local)

    owner_got = []

    def owner():
        while True:
            ok, item = q.pop()
            if not ok:
                break
            owner_got.append(item)

    ts = [threading.Thread(target=thief) for _ in range(4)] \
        + [threading.Thread(target=owner)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    allv = got + owner_got
    assert len(allv) == N
    assert sorted(allv) == list(range(N))     # exactly once each


def test_runtime_local_queue_spawn_chain():
    """A task spawned from a worker rides the local queue; chains still
    complete and stealing drains them."""
    from brpc_tpu.fiber import runtime as fr

    results = []
    done = threading.Event()

    def leaf(i):
        results.append(i)
        if len(results) >= 20:
            done.set()

    def root():
        for i in range(20):
            fr.spawn(leaf, i)

    fr.spawn(root)
    assert done.wait(10)
    assert sorted(results) == list(range(20))


# -- trackme ----------------------------------------------------------------

def test_trackme_roundtrip():
    import json

    from brpc_tpu import __version__, trackme
    from brpc_tpu.butil.flags import set_flag
    from brpc_tpu.tools.rpc_view import fetch

    srv = Server()

    class Dummy(Service):
        def Ping(self, cntl, request):
            return b"pong"

    srv.add_service(Dummy(), name="D")
    assert srv.start("127.0.0.1:0") == 0
    try:
        addr = str(srv.listen_endpoint)
        reply = json.loads(fetch(addr, f"trackme?ver={__version__}"))
        assert reply["severity"] == trackme.SEV_OK
        set_flag("trackme_min_version", "99.0.0")
        try:
            reply = json.loads(fetch(addr, "trackme?ver=0.0.1"))
            assert reply["severity"] == trackme.SEV_WARN
            set_flag("trackme_fatal_version", "98.0.0")
            reply = json.loads(fetch(addr, "trackme?ver=0.0.1"))
            assert reply["severity"] == trackme.SEV_FATAL
        finally:
            set_flag("trackme_min_version", "")
            set_flag("trackme_fatal_version", "")
        # client ping task fires and parses without raising
        assert trackme.start_trackme(addr, interval_s=60)
        trackme.stop_trackme()
    finally:
        srv.stop()


# -- /vars live trend graphs ------------------------------------------------

def test_vars_expand_sparkline():
    import http.client

    from brpc_tpu.bvar.reducer import Adder
    from brpc_tpu.bvar.sampler import tick_once_for_tests

    counter = Adder("trend_test_counter")
    srv = Server()

    class Dummy(Service):
        def Ping(self, cntl, request):
            return b"pong"

    srv.add_service(Dummy(), name="D")
    assert srv.start("127.0.0.1:0") == 0
    try:
        ep = srv.listen_endpoint

        def get(path):
            c = http.client.HTTPConnection(ep.host, ep.port, timeout=10)
            c.request("GET", path)
            r = c.getresponse()
            body = r.read()
            c.close()
            return r.status, body

        status, body = get("/vars?expand=trend_test_counter")
        assert status == 200 and b"collecting" in body
        for i in range(4):
            counter << (i + 1)
            tick_once_for_tests()
        status, body = get("/vars?expand=trend_test_counter")
        assert status == 200 and b"polyline" in body   # curve rendered
        status, body = get("/vars?expand=no_such_var")
        assert status == 404
    finally:
        srv.stop()


# -- dynpart LB -------------------------------------------------------------

def test_dynpart_lb_weights_by_tag():
    from brpc_tpu.butil.endpoint import EndPoint
    from brpc_tpu.client.load_balancer import create_load_balancer
    from brpc_tpu.client.naming_service import ServerNode
    from brpc_tpu.policy import load_balancers  # noqa: F401

    lb = create_load_balancer("dynpart")
    nodes = [
        ServerNode(endpoint=EndPoint(host="10.0.0.1", port=1), tag="w=1"),
        ServerNode(endpoint=EndPoint(host="10.0.0.1", port=2), tag="w=9"),
    ]
    lb.reset_servers(nodes)

    class C:
        excluded_servers = set()
        remote_side = None

    picks = [lb.select_server(C()).port for _ in range(1000)]
    heavy = picks.count(2)
    assert 800 <= heavy <= 980, heavy    # ~90% to the w=9 node
