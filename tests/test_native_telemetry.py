"""Native-lane telemetry (engine.telemetry()) — the observability PR's
contract suite.

Covers: counter monotonicity across snapshots, histogram-count /
handled-count consistency per lane, the reason-coded fallback counters
(every ineligible shape from the kind-3/kind-4 adversarial suites must
increment its NAMED reason — the enum has no "unknown" bucket, and
these tests pin each shape to its reason), the scatter_call screening
counters, the engine-loop busy-ratio PassiveStatus, and the /native +
/metrics portal smoke (native_engine_* families must parse as valid
Prometheus exposition text).
"""

import http.client
import json
import re
import socket as pysock
import struct
import threading

import pytest

from brpc_tpu.butil.flags import get_flag, set_flag
from brpc_tpu.client import Channel, ChannelOptions, Controller
from brpc_tpu.protocol.meta import (TLV_ATTACHMENT, TLV_CORRELATION,
                                    encode_tlv)
from brpc_tpu.server import Server, ServerOptions, Service

from conftest import require_native  # noqa: E402
from test_http_slim import FALLBACK_REQUESTS, _exchange, _post  # noqa: E402

LANES = ("raw", "slim", "http", "stream")
STAGES = ("queue", "shim", "resid")


class TeleSvc(Service):
    def Echo(self, cntl, request):
        cntl.response_attachment.append_iobuf(cntl.request_attachment)
        return b"ok:" + bytes(request)

    def Boom(self, cntl, request):
        raise ValueError("kapow")


def _server(**opt_kw):
    opts = ServerOptions()
    opts.native = True
    opts.usercode_inline = True
    opts.native_loops = 1
    for k, v in opt_kw.items():
        setattr(opts, k, v)
    svc = TeleSvc()
    srv = Server(opts)
    srv.add_service(svc, name="S")
    assert srv.start("127.0.0.1:0") == 0
    return srv, svc


def _tele(srv) -> dict:
    return srv._native_bridge.engine.telemetry()


def _channel(srv):
    co = ChannelOptions()
    co.connection_type = "pooled"
    ch = Channel(co)
    ch.init(str(srv.listen_endpoint))
    return ch


def _frame(cid, svc, mth, payload, att=b"", extra_meta=b""):
    mb = TLV_CORRELATION + struct.pack("<Q", cid)
    if att:
        mb += TLV_ATTACHMENT + struct.pack("<I", len(att))
    mb += encode_tlv(4, svc) + encode_tlv(5, mth) + extra_meta
    body = mb + payload + att
    return b"TRPC" + struct.pack("<II", len(body), len(mb)) + body


def _rpc_exchange(ep, frame):
    with pysock.create_connection((str(ep.host), ep.port),
                                  timeout=10) as c:
        c.sendall(frame)
        c.settimeout(10)
        buf = b""
        while len(buf) < 12:
            buf += c.recv(65536)
        (blen,) = struct.unpack_from("<I", buf, 4)
        while len(buf) < 12 + blen:
            buf += c.recv(65536)
        return buf[:12 + blen]


@pytest.fixture()
def rpcz_off():
    prev = get_flag("enable_rpcz", True)
    set_flag("enable_rpcz", False)
    yield
    set_flag("enable_rpcz", prev)


@pytest.fixture()
def server(rpcz_off):
    require_native()
    srv, svc = _server()
    yield srv, svc
    srv.stop()


# ---- (a) snapshot shape, monotonicity, histogram consistency ----------

def test_counters_monotonic_and_hists_sum(server):
    srv, _ = server
    ep = srv.listen_endpoint
    ch = _channel(srv)
    prev = _tele(srv)
    for rnd in range(3):
        for i in range(4):
            c = ch.call_method("S.Echo", b"m%d" % i, cntl=Controller())
            assert not c.failed
            got = _exchange(ep, _post(b"/S/Echo", b"h%d" % i))
            assert got.endswith(b"ok:h%d" % i)
        cur = _tele(srv)
        # monotonic: every lane's handled and stage counts only grow
        for ln in LANES:
            assert cur["lanes"][ln]["handled"] >= \
                prev["lanes"][ln]["handled"]
            for st in STAGES:
                assert cur["lanes"][ln][f"{st}_us_count"] >= \
                    prev["lanes"][ln][f"{st}_us_count"]
        for r, n in cur["fallbacks"].items():
            assert n >= prev["fallbacks"][r], r
        assert cur["burst_count"] >= prev["burst_count"]
        assert cur["writev_iov_count"] >= prev["writev_iov_count"]
        prev = cur
    # the 12 slim + 12 http requests all flowed through the lanes
    assert prev["lanes"]["slim"]["handled"] >= 12
    assert prev["lanes"]["http"]["handled"] >= 12


def test_histogram_counts_match_handled(rpcz_off):
    """Per lane: every batched item lands in all three stage
    histograms exactly once, so resid_count == handled + errors (the
    error answers are built in the same batch walk)."""
    require_native()
    srv, _ = _server()
    try:
        ep = srv.listen_endpoint
        ch = _channel(srv)
        for i in range(6):
            assert not ch.call_method("S.Echo", b"x",
                                      cntl=Controller()).failed
            got = _exchange(ep, _post(b"/S/Echo", b"y"))
            assert got.endswith(b"ok:y")
        got = _exchange(ep, _post(b"/S/Boom", b"z"))
        assert got.startswith(b"HTTP/1.1 500")
        t = _tele(srv)
        for ln in ("slim", "http"):
            d = t["lanes"][ln]
            total = d["handled"] + d["errors"]
            assert total > 0
            for st in STAGES:
                assert d[f"{st}_us_count"] == total, (ln, st, d)
                assert sum(d[f"{st}_us"]) == d[f"{st}_us_count"]
        # Boom escalated through cntl.finish (classic completion), so
        # it still counts as handled on the http lane; the hist/count
        # identity above is the real assertion
        assert sum(t["burst"]) == t["burst_count"] > 0
        assert sum(t["writev_iov"]) == t["writev_iov_count"] > 0
        assert t["inbuf_hwm"] > 0 and t["wq_hwm"] > 0
    finally:
        srv.stop()


# ---- (b) reason-coded fallbacks: every adversarial shape is named -----

# expected engine fallback reason for every kind-4 ineligible shape in
# tests/test_http_slim.py's adversarial suite — no shape may fall back
# with an unnamed ("unknown") reason
HTTP_SHAPE_REASONS = {
    "http10": "http_version",
    "conn_close": "http_connection",
    "chunked": "http_transfer_encoding",
    "expect": "http_expect",
    "upgrade": "http_upgrade",
    "trailing_slash": "http_no_route",
    "dotted_form": "http_no_route",
}


@pytest.mark.parametrize("name,raw", FALLBACK_REQUESTS,
                         ids=[n for n, _ in FALLBACK_REQUESTS])
def test_http_fallback_reasons_named(server, name, raw):
    srv, _ = server
    assert name in HTTP_SHAPE_REASONS, \
        f"adversarial shape {name!r} has no expected fallback reason"
    reason = HTTP_SHAPE_REASONS[name]
    before = _tele(srv)["fallbacks"]
    got = _exchange(srv.listen_endpoint, raw)
    assert got.startswith(b"HTTP/1.1 200")      # served classically
    after = _tele(srv)["fallbacks"]
    assert after[reason] > before[reason], \
        f"{name} did not increment {reason}: {after}"


def test_http_route_level_fallback_attribution(server):
    """Header-scan rejects are attributed to the RESOLVED route too —
    the /native page's per-route top-fallbacks source."""
    srv, _ = server
    raw = _post(b"/S/Echo", b"xy", headers=((b"Expect",
                                             b"100-continue"),))
    _exchange(srv.listen_endpoint, raw)
    routes = _tele(srv)["routes"]
    assert routes["POST /S/Echo"]["fb_http_expect"] >= 1


def test_http_large_and_chunk_stream_reasons(server):
    srv, _ = server
    ep = srv.listen_endpoint
    before = _tele(srv)["fallbacks"]
    # over-inbuf Content-Length body -> direct-read fallback
    big = bytes(80 * 1024)
    got = _exchange(ep, _post(b"/S/Echo", big))
    assert got.endswith(b"ok:" + big)
    # over-inbuf chunked body -> incremental chunk-stream fallback
    blob = bytes(8192)
    chunks = b"".join(b"2000\r\n" + blob + b"\r\n" for _ in range(16))
    raw = (b"POST /S/Echo HTTP/1.1\r\nHost: x\r\n"
           b"Transfer-Encoding: chunked\r\n\r\n" + chunks
           + b"0\r\n\r\n")
    got = _exchange(ep, raw)
    assert got.endswith(b"ok:" + blob * 16)
    after = _tele(srv)["fallbacks"]
    assert after["http_large_body"] > before["http_large_body"]
    assert after["http_chunk_stream"] > before["http_chunk_stream"]


def test_rpc_fallback_reasons_named(server):
    srv, _ = server
    ep = srv.listen_endpoint
    before = _tele(srv)["fallbacks"]
    # trace tags are NO LONGER a fallback on the slim lane (the
    # distributed-rpcz PR hands them through the shim): a traced call
    # must leave every rpc_* fallback counter untouched
    ch = _channel(srv)
    cntl = Controller()
    cntl.timeout_ms = 5_000
    cntl.trace_id = 777
    c = ch.call_method("S.Echo", b"tr", cntl=cntl)
    assert not c.failed and bytes(c.response) == b"ok:tr"
    mid = _tele(srv)["fallbacks"]
    assert mid["rpc_meta_tag"] == before["rpc_meta_tag"]
    assert mid["rpc_trace_raw_lane"] == before["rpc_trace_raw_lane"]
    # stream-window tag (14) -> rpc_meta_tag still
    f = _frame(91, b"S", b"Echo", b"sw",
               extra_meta=encode_tlv(14, struct.pack("<I", 4096)))
    _rpc_exchange(ep, f)
    after = _tele(srv)["fallbacks"]
    assert after["rpc_meta_tag"] > mid["rpc_meta_tag"]
    # unregistered method -> rpc_no_method
    f = _frame(92, b"S", b"Nope", b"x")
    _rpc_exchange(ep, f)
    t = _tele(srv)["fallbacks"]
    assert t["rpc_no_method"] > after["rpc_no_method"]


def test_rpc_att_over_cap_reason_and_method_attribution(server):
    from brpc_tpu.butil.iobuf import IOBuf

    srv, _ = server
    ch = _channel(srv)
    before = _tele(srv)
    cntl = Controller()
    cntl.timeout_ms = 10_000
    cntl.request_attachment = IOBuf(bytes(20 * 1024))   # over 16KB cap
    c = ch.call_method("S.Echo", b"big", cntl=cntl)
    assert not c.failed
    after = _tele(srv)
    assert after["fallbacks"]["rpc_att_over_cap"] \
        > before["fallbacks"]["rpc_att_over_cap"]
    assert after["methods"]["S.Echo"]["fb_rpc_att_over_cap"] >= 1


def test_scatter_fallback_reason_named(rpcz_off):
    """Two ParallelChannel branches to the SAME server: the pinned
    native scatter screens out the repeated remote with a NAMED
    counter and the classic per-branch scatter still serves the
    call."""
    require_native()
    from brpc_tpu.client import fast_call
    from brpc_tpu.client.parallel_channel import ParallelChannel

    srv, _ = _server()
    try:
        before = fast_call.scatter_fallback_counters() \
            .get("repeated_remote", 0)
        pc = ParallelChannel()
        for _ in range(2):
            sub = Channel()
            sub.init(str(srv.listen_endpoint))
            pc.add_channel(sub)
        c = pc.call_method("S.Echo", b"x")
        assert not c.failed
        after = fast_call.scatter_fallback_counters() \
            .get("repeated_remote", 0)
        assert after > before
    finally:
        srv.stop()


# ---- (c) busy ratio + portal/metrics smoke (tier-1) -------------------

def test_busy_ratio_passive_status(server):
    from brpc_tpu.bvar.variable import find_exposed

    srv, _ = server
    v = find_exposed("native_engine_loop_busy_ratio")
    assert v is not None
    ch = _channel(srv)
    for _ in range(8):
        assert not ch.call_method("S.Echo", b"x",
                                  cntl=Controller()).failed
    val = v.get_value()
    assert 0.0 <= val <= 1.0
    # the per-loop split is in the snapshot too
    loops = _tele(srv)["loops"]
    assert loops and all(l["busy_ns"] > 0 for l in loops)


# one sample or TYPE line of Prometheus text exposition format
_PROM_TYPE = re.compile(r"^# TYPE [a-zA-Z_:][a-zA-Z0-9_:]* "
                        r"(gauge|counter|histogram|summary)$")
_PROM_SAMPLE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*"
    r'(\{[a-zA-Z_][a-zA-Z0-9_]*="[^"\\]*"'
    r'(,[a-zA-Z_][a-zA-Z0-9_]*="[^"\\]*")*\})? '
    r"[-+]?[0-9.]+([eE][-+]?[0-9]+)?$")


def test_native_portal_and_metrics_over_native_port(server):
    srv, _ = server
    ep = srv.listen_endpoint
    ch = _channel(srv)
    for i in range(4):
        assert not ch.call_method("S.Echo", b"p%d" % i,
                                  cntl=Controller()).failed
        _exchange(ep, _post(b"/S/Echo", b"q%d" % i))
    conn = http.client.HTTPConnection(ep.host, ep.port, timeout=10)
    conn.request("GET", "/native")
    r = conn.getresponse()
    assert r.status == 200
    page = json.loads(r.read())
    assert set(page["lanes"]) == set(LANES)
    assert page["lanes"]["slim"]["handled"] >= 4
    assert page["lanes"]["http"]["handled"] >= 4
    assert page["lanes"]["http"]["resid_us"]["count"] >= 4
    assert "fallbacks" in page and "routes" in page \
        and "methods" in page and "loops" in page
    assert "scatter_fallbacks" in page
    # /metrics: the new native_engine_* families must be valid
    # Prometheus exposition text
    conn.request("GET", "/metrics")
    r = conn.getresponse()
    assert r.status == 200
    body = r.read().decode()
    native_lines = [l for l in body.splitlines()
                    if "native_engine_" in l]
    assert native_lines, "no native_engine_* families in /metrics"
    for line in native_lines:
        assert _PROM_TYPE.match(line) or _PROM_SAMPLE.match(line), \
            f"invalid exposition line: {line!r}"
    families = {l.split("{")[0].split(" ")[0] for l in native_lines
                if not l.startswith("#")}
    for want in ("native_engine_latency_us",
                 "native_engine_fallback_total",
                 "native_engine_lane_requests",
                 "native_engine_burst_size",
                 "native_engine_loop_busy_ratio"):
        assert want in families, (want, sorted(families))
    # the labeled histogram rows carry lane/stage/le labels
    assert any(l.startswith('native_engine_latency_us{')
               and 'stage="resid"' in l for l in native_lines)
    conn.close()


def test_vars_page_shows_native_engine_families(server):
    srv, _ = server
    ep = srv.listen_endpoint
    conn = http.client.HTTPConnection(ep.host, ep.port, timeout=10)
    conn.request("GET", "/vars?filter=native_engine")
    r = conn.getresponse()
    assert r.status == 200
    body = r.read().decode()
    assert "native_engine_loop_busy_ratio" in body
    assert "native_engine_fallback_total" in body
    conn.close()


def test_one_snapshot_serves_all_vars_per_interval(server):
    """The satellite-1 fix: a full /vars render (every native_engine_*
    and per-method/per-route var) costs at most a couple of
    engine.telemetry() calls per TTL window, not one per var."""
    srv, _ = server
    bridge = srv._native_bridge
    eng = bridge.engine
    calls = [0]
    real = eng.telemetry

    class _Counting:
        def telemetry(self):
            calls[0] += 1
            return real()

        def __getattr__(self, k):
            return getattr(eng, k)

    bridge.telemetry._engine = _Counting()
    try:
        bridge.telemetry._snap = None          # force one refresh
        from brpc_tpu.bvar.variable import dump_exposed
        dump_exposed("native_engine")
        dump_exposed("rpc_server_s_echo")
        assert calls[0] <= 2, calls[0]
    finally:
        bridge.telemetry._engine = eng
