"""TLS transport tests (≈ /root/reference/src/brpc/details/ssl_helper.cpp
capability: encrypted client/server channels on the DCN path).
Self-signed certs are generated per-session with the openssl CLI."""

import subprocess
import time

import pytest

from brpc_tpu.butil.iobuf import IOBuf
from brpc_tpu.client import Channel, ChannelOptions, Controller
from brpc_tpu.server import Server, ServerOptions, Service


@pytest.fixture(scope="module")
def certs(tmp_path_factory):
    d = tmp_path_factory.mktemp("certs")
    cert, key = str(d / "cert.pem"), str(d / "key.pem")
    subprocess.run(
        ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
         "-keyout", key, "-out", cert, "-days", "1",
         "-subj", "/CN=localhost",
         "-addext", "subjectAltName=IP:127.0.0.1,DNS:localhost"],
        check=True, capture_output=True, timeout=60)
    return cert, key


class Echo(Service):
    def Echo(self, cntl, request):
        return request

    def Att(self, cntl, request):
        cntl.response_attachment.append_iobuf(cntl.request_attachment)
        return b"ok"


@pytest.fixture(scope="module")
def tls_server(certs):
    cert, key = certs
    opts = ServerOptions()
    opts.ssl_cert = cert
    opts.ssl_key = key
    srv = Server(opts)
    srv.add_service(Echo(), name="E")
    assert srv.start("127.0.0.1:0") == 0
    yield srv
    srv.stop()


def _tls_channel(server, ctype="single", **kw):
    co = ChannelOptions()
    co.ssl = True
    co.connection_type = ctype
    co.timeout_ms = 5000
    for k, v in kw.items():
        setattr(co, k, v)
    ch = Channel(co)
    assert ch.init(str(server.listen_endpoint)) == 0
    return ch


def test_tls_echo_single(tls_server):
    ch = _tls_channel(tls_server)
    assert ch.call("E.Echo", b"secret-hello") == b"secret-hello"
    for i in range(20):
        assert ch.call("E.Echo", b"m%d" % i) == b"m%d" % i


def test_tls_echo_pooled_and_short(tls_server):
    for ctype in ("pooled", "short"):
        ch = _tls_channel(tls_server, ctype=ctype)
        assert ch.call("E.Echo", b"via-" + ctype.encode()) \
            == b"via-" + ctype.encode()


def test_tls_large_payload_and_attachment(tls_server):
    ch = _tls_channel(tls_server)
    big = bytes(range(256)) * 2048          # 512KB
    cntl = Controller()
    cntl.timeout_ms = 20_000
    cntl.request_attachment = IOBuf(big)
    c = ch.call_method("E.Att", b"", cntl=cntl)
    assert not c.failed, c.error_text
    assert c.response_attachment.to_bytes() == big


def test_tls_verified_against_pinned_ca(tls_server, certs):
    cert, _ = certs
    ch = _tls_channel(tls_server, ssl_ca=cert, ssl_verify=True)
    assert ch.call("E.Echo", b"verified") == b"verified"


def test_plaintext_client_rejected_by_tls_server(tls_server):
    co = ChannelOptions()
    co.timeout_ms = 2000
    co.max_retry = 0
    ch = Channel(co)
    assert ch.init(str(tls_server.listen_endpoint)) == 0
    cntl = Controller()
    ch.call_method("E.Echo", b"plaintext", cntl=cntl)
    assert cntl.failed
    # and the server still serves TLS clients afterwards
    ch2 = _tls_channel(tls_server)
    assert ch2.call("E.Echo", b"still-works") == b"still-works"


def test_tls_client_against_plaintext_server_fails_cleanly():
    srv = Server()
    srv.add_service(Echo(), name="E")
    assert srv.start("127.0.0.1:0") == 0
    try:
        co = ChannelOptions()
        co.ssl = True
        co.timeout_ms = 2000
        co.max_retry = 0
        ch = Channel(co)
        assert ch.init(str(srv.listen_endpoint)) == 0
        cntl = Controller()
        ch.call_method("E.Echo", b"x", cntl=cntl)
        assert cntl.failed
    finally:
        srv.stop()


def test_tls_grpc_interop_skipped_note():
    """gRPC-over-TLS rides the same ssl.SSLContext plumbing via the h2
    client; covered implicitly once GrpcConnection gains TLS (tracked
    in SURVEY §7) — this placeholder documents the boundary."""
    assert True
