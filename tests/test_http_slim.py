"""Slim native HTTP dispatch (engine kind 4) — adversarial suite.

Contract under test (server/http_slim.py + engine.cpp kind 4): an
eligible HTTP/1.1 request to a registered /Service/Method route on a
native inline server is parsed (request line + headers) by the C++
engine, burst-batched into ONE GIL entry, dispatched to the per-route
shim, and its response is serialized natively into the burst's
coalesced writev — while staying BYTE-IDENTICAL with the classic
EV_HTTP path (and the pure-Python transport), preserving MethodStatus
accounting, concurrency admission and rpcz sampling, and falling back
to the classic path for everything the slim serializer cannot express.

Also regression-tests the two round-6 ADVICE fixes that ride along:
the http_sniff prefix-collision hang (#5) and the chunked-body
kInbufCap parity gap (#4).
"""

import socket as pysock
import threading
import time

import pytest

from brpc_tpu.butil.flags import get_flag, set_flag
from brpc_tpu.butil.status import Errno
from brpc_tpu.server import Server, ServerOptions, Service

from conftest import require_native  # noqa: E402


class HttpSvc(Service):
    def __init__(self):
        self.calls = []

    def Echo(self, cntl, request):
        self.calls.append(threading.current_thread().name)
        cntl.response_attachment.append_iobuf(cntl.request_attachment)
        return b"ok:" + bytes(request)

    def Dict(self, cntl, request):
        return {"got": len(request)}

    def Boom(self, cntl, request):
        raise ValueError("kapow")

    def SetFail(self, cntl, request):
        cntl.set_failed(Errno.EREQUEST, "refused politely")
        return None

    def Later(self, cntl, request):
        cntl.begin_async()
        data = bytes(request)

        def finisher():
            time.sleep(0.05)
            cntl.finish(b"async:" + data)

        threading.Thread(target=finisher, daemon=True).start()
        return None

    def Stream(self, cntl, request):
        pa = cntl.create_progressive_attachment()

        def writer():
            time.sleep(0.02)
            pa.write(b"part1-")
            pa.write(b"part2")
            pa.close()

        threading.Thread(target=writer, daemon=True).start()
        return b"head:"


def _server(native: bool, **opt_kw):
    opts = ServerOptions()
    if native:
        opts.native = True
        opts.usercode_inline = True
        opts.native_loops = 1
    for k, v in opt_kw.items():
        setattr(opts, k, v)
    svc = HttpSvc()
    srv = Server(opts)
    srv.add_service(svc, name="S")
    assert srv.start("127.0.0.1:0") == 0
    return srv, svc


def _slim_count(srv, mth, http_method="POST"):
    return srv._native_bridge.engine.http_slim_stats(
        http_method, f"/S/{mth}")[0]


def _exchange(ep, raw: bytes, chunked: bool = False) -> bytes:
    """Send raw request bytes, read one complete HTTP response
    (Content-Length or chunked framing) — the raw wire bytes, for
    byte-identity comparisons."""
    with pysock.create_connection((str(ep.host), ep.port),
                                  timeout=15) as c:
        c.sendall(raw)
        c.settimeout(15)
        buf = b""
        while b"\r\n\r\n" not in buf:
            part = c.recv(65536)
            if not part:
                return buf
            buf += part
        head, _, rest = buf.partition(b"\r\n\r\n")
        if chunked:
            while not rest.endswith(b"0\r\n\r\n"):
                part = c.recv(65536)
                if not part:
                    break
                rest += part
            return head + b"\r\n\r\n" + rest
        clen = 0
        for line in head.split(b"\r\n"):
            if line.lower().startswith(b"content-length:"):
                clen = int(line.split(b":")[1])
        while len(rest) < clen:
            part = c.recv(65536)
            if not part:
                break
            rest += part
        return head + b"\r\n\r\n" + rest[:clen]


def _post(path, body=b"", headers=()):
    h = b""
    for k, v in headers:
        h += k + b": " + v + b"\r\n"
    return (b"POST " + path + b" HTTP/1.1\r\nHost: x\r\n"
            + b"Content-Length: " + str(len(body)).encode() + b"\r\n"
            + h + b"\r\n" + body)


def _tri_exchange(nsrv, psrv, raw, chunked=False):
    """The same raw request through all three lanes: slim (native),
    classic EV_HTTP (same native server, lane gated off), and the
    pure-Python transport.  Returns (slim, classic, pytransport)."""
    eng = nsrv._native_bridge.engine
    slim = _exchange(nsrv.listen_endpoint, raw, chunked)
    eng.set_http_slim(False)
    try:
        classic = _exchange(nsrv.listen_endpoint, raw, chunked)
    finally:
        eng.set_http_slim(True)
    pyt = _exchange(psrv.listen_endpoint, raw, chunked)
    return slim, classic, pyt


@pytest.fixture()
def rpcz_off():
    """Determinism for the byte-identity comparisons (spans never alter
    bytes on this lane, but keep the fast path uniform)."""
    prev = get_flag("enable_rpcz", True)
    set_flag("enable_rpcz", False)
    yield
    set_flag("enable_rpcz", prev)


@pytest.fixture()
def pair(rpcz_off):
    require_native()
    nsrv, nsvc = _server(native=True)
    psrv, psvc = _server(native=False)
    yield (nsrv, nsvc, psrv, psvc)
    nsrv.stop()
    psrv.stop()


# ---- (a) slim vs classic vs pytransport: byte-identical ---------------

def test_byteident_plain_post(pair):
    nsrv, nsvc, psrv, psvc = pair
    raw = _post(b"/S/Echo", b"hello")
    slim, classic, pyt = _tri_exchange(nsrv, psrv, raw)
    assert slim == classic == pyt
    assert slim.startswith(b"HTTP/1.1 200 OK\r\n")
    assert slim.endswith(b"ok:hello")
    assert _slim_count(nsrv, "Echo") == 1      # exactly the first one
    assert len(nsvc.calls) == 2 and len(psvc.calls) == 1


def test_byteident_json_and_get_query(pair):
    nsrv, _, psrv, _ = pair
    raw = _post(b"/S/Dict", b"abcdef")
    slim, classic, pyt = _tri_exchange(nsrv, psrv, raw)
    assert slim == classic == pyt
    assert b"application/json" in slim and b'{"got": 6}' in slim
    raw = b"GET /S/Echo?a=1&b=two%20words HTTP/1.1\r\nHost: x\r\n\r\n"
    slim, classic, pyt = _tri_exchange(nsrv, psrv, raw)
    assert slim == classic == pyt
    assert b'"b": "two words"' in slim
    assert _slim_count(nsrv, "Echo", "GET") == 1


def test_byteident_attachment_roundtrip(pair):
    nsrv, _, psrv, _ = pair
    body = b"payload" + b"A" * 64
    raw = _post(b"/S/Echo", body,
                headers=((b"x-rpc-attachment-size", b"64"),))
    slim, classic, pyt = _tri_exchange(nsrv, psrv, raw)
    assert slim == classic == pyt
    assert b"x-rpc-attachment-size: 64" in slim
    assert slim.endswith(b"ok:payload" + b"A" * 64)


def test_byteident_handler_exception(pair):
    nsrv, _, psrv, _ = pair
    raw = _post(b"/S/Boom", b"x")
    slim, classic, pyt = _tri_exchange(nsrv, psrv, raw)
    assert slim == classic == pyt
    assert slim.startswith(b"HTTP/1.1 500 ")
    assert b"ValueError: kapow" in slim
    assert b"x-rpc-error-code" in slim


def test_byteident_set_failed(pair):
    nsrv, _, psrv, _ = pair
    raw = _post(b"/S/SetFail", b"x")
    slim, classic, pyt = _tri_exchange(nsrv, psrv, raw)
    assert slim == classic == pyt
    assert slim.startswith(b"HTTP/1.1 400 ")
    assert b"refused politely" in slim


def test_byteident_admission_reject(pair):
    nsrv, _, psrv, _ = pair
    for srv in (nsrv, psrv):
        status = srv.find_method("S", "Echo").status
        status.max_concurrency = 1
        status._inflight = 1        # saturate the cap deterministically
    try:
        raw = _post(b"/S/Echo", b"x")
        slim, classic, pyt = _tri_exchange(nsrv, psrv, raw)
        assert slim == classic == pyt
        assert slim.startswith(b"HTTP/1.1 503 ")
        assert b"method max_concurrency" in slim
        # the reject itself rode the slim lane (admission runs IN it)
        assert _slim_count(nsrv, "Echo") >= 1
    finally:
        for srv in (nsrv, psrv):
            status = srv.find_method("S", "Echo").status
            status.max_concurrency = 0
            status._inflight = 0


def test_byteident_async_method(pair):
    """begin_async + finish from another thread: the shim returns None
    (out-of-band) and the classic build_response write completes it."""
    nsrv, _, psrv, _ = pair
    raw = _post(b"/S/Later", b"zz")
    slim, classic, pyt = _tri_exchange(nsrv, psrv, raw)
    assert slim == classic == pyt
    assert slim.endswith(b"async:zz")
    assert _slim_count(nsrv, "Later") == 1     # counted as slim-handled


def test_byteident_progressive_attachment(pair):
    nsrv, _, psrv, _ = pair
    raw = _post(b"/S/Stream", b"")
    slim, classic, pyt = _tri_exchange(nsrv, psrv, raw, chunked=True)
    assert slim == classic == pyt
    assert b"transfer-encoding: chunked" in slim
    assert b"head:" in slim and b"part1-" in slim and b"part2" in slim


def test_pipelined_burst_in_order(pair):
    """A pipelined burst of keep-alive requests in ONE write: every
    response returns IN REQUEST ORDER (HTTP/1.1 has no correlation id),
    all through the slim lane, and the concatenated bytes equal the
    classic native lane's.  (The pure-Python transport spawns a fiber
    per pipelined message and does not guarantee response order — the
    native lanes do, so the oracle here is the classic EV_HTTP lane.)"""
    nsrv, _, _, _ = pair
    burst = b"".join(_post(b"/S/Echo", b"req%d" % i) for i in range(8))
    before = _slim_count(nsrv, "Echo")

    def read_n(ep, n):
        with pysock.create_connection((str(ep.host), ep.port),
                                      timeout=15) as c:
            c.sendall(burst)
            c.settimeout(15)
            buf = b""
            while buf.count(b"HTTP/1.1 200") < n:
                part = c.recv(65536)
                if not part:
                    break
                buf += part
            return buf

    slim = read_n(nsrv.listen_endpoint, 8)
    eng = nsrv._native_bridge.engine
    eng.set_http_slim(False)
    try:
        classic = read_n(nsrv.listen_endpoint, 8)
    finally:
        eng.set_http_slim(True)
    assert slim == classic
    positions = [slim.index(b"ok:req%d" % i) for i in range(8)]
    assert positions == sorted(positions)      # strict request order
    assert _slim_count(nsrv, "Echo") == before + 8


# ---- (b) fallback triggers take the classic path ----------------------

FALLBACK_REQUESTS = [
    ("http10", b"GET /S/Echo HTTP/1.0\r\nHost: x\r\n\r\n"),
    ("conn_close", b"POST /S/Echo HTTP/1.1\r\nHost: x\r\n"
     b"Connection: close\r\nContent-Length: 2\r\n\r\nxy"),
    ("chunked", b"POST /S/Echo HTTP/1.1\r\nHost: x\r\n"
     b"Transfer-Encoding: chunked\r\n\r\n2\r\nxy\r\n0\r\n\r\n"),
    ("expect", b"POST /S/Echo HTTP/1.1\r\nHost: x\r\n"
     b"Expect: 100-continue\r\nContent-Length: 2\r\n\r\nxy"),
    ("upgrade", b"POST /S/Echo HTTP/1.1\r\nHost: x\r\n"
     b"Upgrade: h2c\r\nConnection: keep-alive\r\n"
     b"Content-Length: 2\r\n\r\nxy"),
    ("trailing_slash", _post(b"/S/Echo/", b"xy")),
    ("dotted_form", _post(b"/S.Echo", b"xy")),
]


@pytest.mark.parametrize("name,raw",
                         FALLBACK_REQUESTS,
                         ids=[n for n, _ in FALLBACK_REQUESTS])
def test_fallback_shapes_served_classically(pair, name, raw):
    nsrv, _, psrv, _ = pair
    before = sum(
        v[0] for v in nsrv._native_bridge.engine.http_slim_stats()
        .values())
    nat = _exchange(nsrv.listen_endpoint, raw)
    pyt = _exchange(psrv.listen_endpoint, raw)
    assert nat == pyt
    assert nat.startswith(b"HTTP/1.1 200")
    after = sum(
        v[0] for v in nsrv._native_bridge.engine.http_slim_stats()
        .values())
    assert after == before, f"{name} must not ride the slim lane"


def test_fallback_builtin_portal_and_404(pair):
    nsrv, _, psrv, _ = pair
    for raw in (b"GET /health HTTP/1.1\r\nHost: x\r\n\r\n",
                b"GET /no/such/route HTTP/1.1\r\nHost: x\r\n\r\n"):
        nat = _exchange(nsrv.listen_endpoint, raw)
        pyt = _exchange(psrv.listen_endpoint, raw)
        assert nat == pyt
    stats = nsrv._native_bridge.engine.http_slim_stats()
    assert sum(v[0] for v in stats.values()) == 0


def test_non_inline_server_registers_nothing(rpcz_off):
    """usercode_inline=False: user code must stay off the engine loops,
    so no HTTP route registers; requests serve via the classic path on
    the per-connection ExecutionQueue."""
    require_native()
    opts = ServerOptions()
    opts.native = True
    opts.usercode_inline = False
    opts.native_loops = 1
    svc = HttpSvc()
    srv = Server(opts)
    srv.add_service(svc, name="S")
    assert srv.start("127.0.0.1:0") == 0
    try:
        assert srv._native_bridge.engine.http_slim_stats() == {}
        got = _exchange(srv.listen_endpoint, _post(b"/S/Echo", b"ni"))
        assert got.endswith(b"ok:ni")
        assert not any(n.startswith("native-loop") for n in svc.calls)
    finally:
        srv.stop()


def test_auth_server_registers_nothing(rpcz_off):
    require_native()

    class Auth:
        def verify(self, auth_data, cntl):
            return True

    srv, _ = _server(native=True, auth=Auth())
    try:
        assert srv._native_bridge.engine.http_slim_stats() == {}
        got = _exchange(srv.listen_endpoint, _post(b"/S/Echo", b"a"))
        assert got.endswith(b"ok:a")
    finally:
        srv.stop()


# ---- (c) MethodStatus + rpcz survive the slim lane --------------------

def test_method_status_survives_slim_http(rpcz_off):
    require_native()
    srv, svc = _server(native=True)
    try:
        ep = srv.listen_endpoint
        entry = srv.find_method("S", "Echo")
        base = entry.status.latency.count()
        for i in range(5):
            got = _exchange(ep, _post(b"/S/Echo", b"m%d" % i))
            assert got.endswith(b"ok:m%d" % i)
        assert _slim_count(srv, "Echo") == 5
        assert entry.status.latency.count() == base + 5
        assert entry.status.inflight == 0
        got = _exchange(ep, _post(b"/S/Boom", b"x"))
        assert got.startswith(b"HTTP/1.1 500")
        boom = srv.find_method("S", "Boom")
        assert boom.status.errors.get_value() >= 1
        assert boom.status.inflight == 0
    finally:
        srv.stop()


def test_rpcz_sampled_spans_survive_slim_http():
    require_native()
    import brpc_tpu.rpcz as rpcz

    prev = get_flag("enable_rpcz", True)
    set_flag("enable_rpcz", True)
    srv, _ = _server(native=True)
    try:
        ep = srv.listen_endpoint
        before = {id(s) for s in rpcz.global_span_store().recent(2048)}
        for _ in range(3):
            got = _exchange(ep, _post(b"/S/Echo", b"sp"))
            assert got.endswith(b"ok:sp")
        assert _slim_count(srv, "Echo") == 3   # sampled calls stay slim
        spans = [s for s in rpcz.global_span_store().recent(2048)
                 if id(s) not in before and s.full_method == "S.Echo"
                 and s.is_server]
        assert spans, "no sampled server span recorded via the slim lane"
        s = spans[0]
        assert s.request_size > 0 and s.response_size > 0
    finally:
        srv.stop()
        set_flag("enable_rpcz", prev)


def test_concurrency_cap_still_enforced_on_slim_lane(rpcz_off):
    require_native()
    opts = ServerOptions()
    opts.native = True
    opts.usercode_inline = True
    opts.native_loops = 1
    opts.method_max_concurrency = {"S.Echo": 4}
    svc = HttpSvc()
    srv = Server(opts)
    srv.add_service(svc, name="S")
    assert srv.start("127.0.0.1:0") == 0
    try:
        ep = srv.listen_endpoint
        got = _exchange(ep, _post(b"/S/Echo", b"lim"))
        assert got.endswith(b"ok:lim")
        assert _slim_count(srv, "Echo") == 1   # the lane is active
        status = srv.find_method("S", "Echo").status
        status._inflight = 4        # saturate the cap deterministically
        got = _exchange(ep, _post(b"/S/Echo", b"over"))
        assert got.startswith(b"HTTP/1.1 503")
        status._inflight = 0
    finally:
        srv.stop()


# ---- (d) ADVICE r5 #5: sniff prefix-collision no longer hangs ---------

def test_sniff_collision_does_not_hang(pair):
    """First 4 bytes collide with an HTTP method token but the request
    line never carries ' HTTP/1.': the conn must be arbitrated (served
    or closed) promptly, not held against a CRLFCRLF hunt forever."""
    nsrv, _, _, _ = pair
    ep = nsrv.listen_endpoint
    with pysock.create_connection((str(ep.host), ep.port),
                                  timeout=10) as c:
        c.sendall(b"POST like a redis inline command\r\nkey value\r\n")
        c.settimeout(8)
        t0 = time.monotonic()
        try:
            data = c.recv(4096)
        except pysock.timeout:
            pytest.fail("colliding prefix hung the connection")
        assert data == b""                     # cleanly closed
        assert time.monotonic() - t0 < 5.0


def test_slow_request_line_still_served_after_budget(pair):
    """A legit HTTP client dribbling its request line slower than the
    sniff budget falls to the passthrough registry — and is still
    SERVED there (the registry speaks HTTP too)."""
    nsrv, _, _, _ = pair
    ep = nsrv.listen_endpoint
    with pysock.create_connection((str(ep.host), ep.port),
                                  timeout=15) as c:
        c.sendall(b"POST /S/Echo HT")
        time.sleep(2.6)                        # past the 2s budget
        c.sendall(b"TP/1.1\r\nHost: x\r\nContent-Length: 4\r\n\r\nslow")
        c.settimeout(10)
        buf = b""
        while b"ok:slow" not in buf:
            part = c.recv(65536)
            if not part:
                break
            buf += part
        assert b"ok:slow" in buf


# ---- (e) ADVICE r5 #4: chunked bodies bounded by http_max_body --------

def test_large_chunked_upload_on_native_port(pair):
    """A >64KB chunked upload (over the old inbuf bound) succeeds."""
    nsrv, _, psrv, _ = pair
    blob = bytes(range(256)) * 32              # 8KB
    chunks = b"".join(b"2000\r\n" + blob + b"\r\n" for _ in range(12))
    raw = (b"POST /S/Echo HTTP/1.1\r\nHost: x\r\n"
           b"Transfer-Encoding: chunked\r\n\r\n" + chunks + b"0\r\n\r\n")
    nat = _exchange(nsrv.listen_endpoint, raw)     # 96KB body
    pyt = _exchange(psrv.listen_endpoint, raw)
    assert nat == pyt
    assert nat.endswith(b"ok:" + blob * 12)


def test_pipelined_slim_then_large_chunked_stays_ordered(pair):
    """One burst carrying [slim-eligible POST][chunked POST that
    overflows the inbuf]: the slim response accumulated in native_out
    must reach the wire BEFORE Python answers the chunked message —
    HTTP responses carry no correlation id."""
    nsrv, _, _, _ = pair
    blob = bytes(8192)
    chunks = b"".join(b"2000\r\n" + blob + b"\r\n" for _ in range(16))
    raw = _post(b"/S/Echo", b"pipe") + (
        b"POST /S/Echo HTTP/1.1\r\nHost: x\r\n"
        b"Transfer-Encoding: chunked\r\n\r\n" + chunks + b"0\r\n\r\n")
    ep = nsrv.listen_endpoint
    with pysock.create_connection((str(ep.host), ep.port),
                                  timeout=15) as c:
        c.sendall(raw)
        c.settimeout(15)
        buf = b""
        # first response on the wire must be the slim one, complete
        while buf.count(b"\r\n\r\n") < 1 or b"ok:pipe" not in buf:
            part = c.recv(65536)
            assert part, f"connection closed early: {buf[:120]!r}"
            buf += part
        assert buf.index(b"ok:pipe") < len(buf)
        first_body = buf.index(b"ok:pipe")
        assert b"ok:" + blob[:1] not in buf[:first_body]
        # then the 128KB chunked echo follows whole
        want = b"ok:" + blob * 16
        while want not in buf:
            part = c.recv(65536)
            assert part, "chunked response never arrived"
            buf += part
        assert buf.index(b"ok:pipe") < buf.index(want)
    assert _slim_count(nsrv, "Echo") >= 1


def test_batch_response_delivered_before_error_close(pair):
    """A burst of [valid slim request][malformed HTTP that kills the
    conn]: the valid request ran (side effects committed), so its
    response must be delivered best-effort before the close — not
    silently discarded with the dying connection."""
    nsrv, _, _, _ = pair
    raw = (_post(b"/S/Echo", b"last")
           + b"GET /bad HTTP/1.1\r\n" + b"A" * (70 * 1024))
    ep = nsrv.listen_endpoint
    with pysock.create_connection((str(ep.host), ep.port),
                                  timeout=15) as c:
        c.sendall(raw)
        c.settimeout(10)
        buf = b""
        while True:
            try:
                part = c.recv(65536)
            except pysock.timeout:
                break
            if not part:
                break
            buf += part
        assert b"ok:last" in buf, buf[:200]


def test_large_chunked_upload_with_long_extensions(pair):
    """Chunk-size lines carrying long extensions (>33 bytes) must parse
    identically in the buffered walker and the incremental FSM — the
    same message accepted small must not be hard-closed large."""
    nsrv, _, psrv, _ = pair
    blob = bytes(range(256)) * 32              # 8KB
    ext = b";sig=" + b"0123456789abcdef" * 4   # 69-byte extension tail
    chunks = b"".join(b"2000" + ext + b"\r\n" + blob + b"\r\n"
                      for _ in range(12))
    raw = (b"POST /S/Echo HTTP/1.1\r\nHost: x\r\n"
           b"Transfer-Encoding: chunked\r\n\r\n" + chunks + b"0\r\n\r\n")
    nat = _exchange(nsrv.listen_endpoint, raw)     # 96KB body
    pyt = _exchange(psrv.listen_endpoint, raw)
    assert nat == pyt
    assert nat.endswith(b"ok:" + blob * 12)
