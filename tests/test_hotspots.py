"""/hotspots profiler portal tests — the reference's hotspots_service
capability (CPU/contention/growth/heap) plus device trace capture,
exercised over live HTTP (≈ test strategy of
/root/reference/test/brpc_builtin_service_unittest.cpp)."""

import http.client
import threading
import time

import pytest

from brpc_tpu import profiling
from brpc_tpu.server import Server, Service


class Busy(Service):
    def Spin(self, cntl, request):
        t0 = time.monotonic()
        x = 0
        while time.monotonic() - t0 < 0.3:
            x += sum(range(200))
        return b"%d" % x


@pytest.fixture(scope="module")
def server():
    srv = Server()
    srv.add_service(Busy(), name="B")
    assert srv.start("127.0.0.1:0") == 0
    yield srv
    srv.stop()


def _get(server, path, timeout=30):
    ep = server.listen_endpoint
    c = http.client.HTTPConnection(ep.host, ep.port, timeout=timeout)
    c.request("GET", path)
    r = c.getresponse()
    body = r.read()
    headers = dict(r.getheaders())
    c.close()
    return r.status, body, headers


def test_cpu_profile_names_hot_function(server):
    # drive load from a thread while the profile window is open
    from brpc_tpu.client import Channel
    ch = Channel()
    ch.init(str(server.listen_endpoint))
    stop = [False]

    def load():
        while not stop[0]:
            ch.call("B.Spin", b"", timeout_ms=10_000)

    t = threading.Thread(target=load, daemon=True)
    t.start()
    try:
        status, body, _ = _get(server, "/hotspots/cpu?seconds=1&view=flat")
        assert status == 200
        assert b"Spin" in body or b"test_hotspots" in body, body[:800]
        status, body, _ = _get(server,
                               "/hotspots/cpu?seconds=0.5&view=folded")
        assert status == 200
        assert b";" in body           # folded stacks present
        status, body, _ = _get(server, "/hotspots/cpu?seconds=0.5")
        assert status == 200 and body.startswith(b"<!doctype html>")
        assert b'class="f"' in body   # flame boxes rendered
    finally:
        stop[0] = True
        t.join(timeout=10)


def test_contention_reports_wait_sites(server):
    from brpc_tpu.fiber.butex import Butex
    bx = Butex(0)

    def waiter():
        bx.wait(0, timeout=1.0)

    threads = [threading.Thread(target=waiter) for _ in range(2)]

    def kick():
        time.sleep(0.05)
        for t in threads:
            t.start()
        time.sleep(0.4)
        bx.add_and_wake(1)

    k = threading.Thread(target=kick)
    k.start()
    status, body, _ = _get(server, "/hotspots/contention?seconds=1")
    k.join()
    for t in threads:
        t.join()
    assert status == 200
    assert b"butex" in body, body[:800]
    assert b"test_hotspots" in body   # the wait site is named


def test_growth_names_allocation_site(server):
    hoard = []

    def alloc():
        time.sleep(0.2)
        for _ in range(200):
            hoard.append(bytearray(10_000))

    t = threading.Thread(target=alloc)
    t.start()
    status, body, _ = _get(server, "/hotspots/growth?seconds=1")
    t.join()
    assert status == 200
    assert b"test_hotspots" in body, body[:800]
    hoard.clear()


def test_heap_endpoint(server):
    status, body, _ = _get(server, "/hotspots/heap")
    assert status == 200     # either a report or the "not tracing" hint
    assert b"allocation site" in body or b"tracemalloc" in body


def test_device_trace_tarball(server):
    status, body, headers = _get(server, "/hotspots/device?seconds=0.3",
                                 timeout=60)
    assert status == 200, body[:300]
    assert body[:2] == b"\x1f\x8b"          # gzip magic
    assert "attachment" in headers.get("content-disposition", "")


def test_hotspots_index(server):
    status, body, _ = _get(server, "/hotspots/nope")
    assert status == 404
    assert b"/hotspots/cpu" in body


def test_sampler_direct():
    stop = [False]

    def busy():
        while not stop[0]:
            sum(range(500))

    t = threading.Thread(target=busy, daemon=True)
    t.start()
    prof = profiling.sample_cpu(seconds=0.4, hz=200)
    stop[0] = True
    t.join()
    assert prof.samples > 10
    flat = profiling.render_flat(prof.folded)
    assert "busy" in flat or "test_hotspots" in flat
