"""Multi-core engine (ISSUE 11) — loop pinning, sharded accept, the
lock-free cross-loop completion handoff and the busy-poll knob.

Pins four contracts:

1. **Connections are pinned to exactly one loop for life**: a pipelined
   multi-connection matrix (loops ∈ {1, 2, 4} × REUSEPORT on/off)
   asserts via ``engine.telemetry()`` that every connection's frames
   are handled by a single, stable loop across bursts, and that the
   per-loop frame counters add up to the per-conn ones.
2. **REUSEPORT-disabled fallback placement passes the SAME matrix**:
   with ``engine_reuseport`` off the engine keeps the single shared
   listener + round-robin adopt handoff — placement differs, the
   pinning invariant must not.
3. **The cross-loop handoff delivers**: a response produced OFF the
   owning loop (fiber completion on a non-inline server, big enough to
   defeat the inline-writev shortcut) reaches the wire through the
   MPSC handoff, visible as a non-zero per-loop ``handoffs`` counter.
4. **Busy-poll is live-flippable and harmless**: flipping
   ``engine_busy_poll_us`` at runtime keeps the echo matrix green and
   surfaces the ``spin_polls`` counter.
"""

import socket as pysock
import struct
import threading
import time

import pytest

from conftest import require_native

from brpc_tpu.butil.flags import get_flag, set_flag


def _tlv(tag, data):
    return bytes([tag]) + struct.pack("<I", len(data)) + data


def _frame(cid, payload, svc=b"MC", mth=b"Echo"):
    meta = (_tlv(1, struct.pack("<Q", cid)) + _tlv(4, svc)
            + _tlv(5, mth))
    return (b"TRPC" + struct.pack("<II", len(meta) + len(payload),
                                  len(meta)) + meta + payload)


def _mk_server(loops, usercode_inline=True):
    from brpc_tpu.server import Server, ServerOptions, Service

    class Echo(Service):
        def Echo(self, cntl, request):
            cntl.response_attachment.append_iobuf(
                cntl.request_attachment)
            return request

    opts = ServerOptions()
    opts.native = True
    opts.usercode_inline = usercode_inline
    opts.native_loops = loops
    srv = Server(opts)
    srv.add_service(Echo(), name="MC")
    assert srv.start("127.0.0.1:0") == 0
    return srv


def _blast(port, nconns, frames_per_burst, bursts):
    """nconns pipelined raw connections, each sending `bursts` bursts
    of `frames_per_burst` frames and draining the echoes.  Returns the
    open socket list (caller closes) so mid-test telemetry snapshots
    see live conns."""
    socks = [pysock.create_connection(("127.0.0.1", port), timeout=10)
             for _ in range(nconns)]
    for burst in range(bursts):
        for s in socks:
            blast = b"".join(
                _frame(burst * frames_per_burst + i + 1,
                       b"m" * (11 * (i % 17)))
                for i in range(frames_per_burst))
            s.sendall(blast)
        for s in socks:
            got = bytearray()
            seen = 0
            while seen < frames_per_burst:
                chunk = s.recv(65536)
                assert chunk, "peer closed mid-burst"
                got += chunk
                seen = 0
                off = 0
                while off + 12 <= len(got):
                    assert got[off:off + 4] == b"TRPC"
                    (blen,) = struct.unpack_from("<I", got, off + 4)
                    if off + 12 + blen > len(got):
                        break
                    off += 12 + blen
                    seen += 1
    return socks


def _conn_snapshot(engine):
    """conn_id -> (loop, frames) for live conns, from ONE telemetry
    snapshot."""
    t = engine.telemetry()
    return {cid: (d["loop"], d["frames"])
            for cid, d in t["conns"].items()}, t


@pytest.mark.parametrize("loops", [1, 2, 4])
@pytest.mark.parametrize("reuseport", [True, False],
                         ids=["reuseport", "rr-fallback"])
def test_loop_pinning_matrix(loops, reuseport):
    require_native()
    prev = bool(get_flag("engine_reuseport", True))
    set_flag("engine_reuseport", reuseport)
    try:
        srv = _mk_server(loops)
    finally:
        set_flag("engine_reuseport", prev)
    try:
        engine = srv._native_bridge.engine
        port = srv.listen_endpoint.port
        NCONNS, PER_BURST, BURSTS = 6, 40, 2
        socks = _blast(port, NCONNS, PER_BURST, BURSTS)
        try:
            snap1, t1 = _conn_snapshot(engine)
            assert len(snap1) == NCONNS
            for cid, (loop, frames) in snap1.items():
                assert 0 <= loop < loops, (cid, loop)
                assert frames == PER_BURST * BURSTS, (cid, frames)
            # per-loop frames must equal the per-conn totals: no frame
            # was ever handled off its conn's owning loop
            by_loop = {}
            for _cid, (loop, frames) in snap1.items():
                by_loop[loop] = by_loop.get(loop, 0) + frames
            for i, lo in enumerate(t1["loops"]):
                assert lo["frames"] == by_loop.get(i, 0), (i, lo)
            # another burst: ownership must not move
            for s in socks:
                s.sendall(_frame(9999, b"again"))
            for s in socks:
                got = b""
                while len(got) < 12 or len(got) < 12 + struct.unpack_from(
                        "<I", got, 4)[0]:
                    got += s.recv(65536)
            snap2, _t2 = _conn_snapshot(engine)
            for cid, (loop, frames) in snap2.items():
                assert loop == snap1[cid][0], "conn migrated loops!"
                assert frames == snap1[cid][1] + 1
            # placement accounting: every accept was pinned somewhere
            total_accepts = sum(lo["accepts"] for lo in t1["loops"])
            assert total_accepts == NCONNS
            if not reuseport and loops > 1:
                # rr fallback spreads round-robin from the shared
                # listener: more than one loop must own conns
                owners = {loop for loop, _f in snap1.values()}
                assert len(owners) > 1, owners
        finally:
            for s in socks:
                s.close()
    finally:
        srv.stop()


def test_reuseport_shards_spread_accepts():
    """With REUSEPORT sharding on a multi-loop engine, accepts are
    performed BY the owning loop (accepts counter lives where the conn
    lives) — and with enough connections more than one shard listener
    fires on this kernel."""
    require_native()
    srv = _mk_server(4)
    try:
        engine = srv._native_bridge.engine
        if not srv._native_bridge._shard_sockets:
            pytest.skip("REUSEPORT sharding unavailable on this box")
        port = srv.listen_endpoint.port
        socks = _blast(port, 12, 5, 1)
        try:
            snap, t = _conn_snapshot(engine)
            for i, lo in enumerate(t["loops"]):
                owned = sum(1 for loop, _f in snap.values() if loop == i)
                assert lo["accepts"] == owned, (i, lo["accepts"], owned)
            owners = {loop for loop, _f in snap.values()}
            assert len(owners) > 1, \
                f"12 conns all hashed to one shard: {owners}"
        finally:
            for s in socks:
                s.close()
    finally:
        srv.stop()


def test_cross_loop_handoff_delivers():
    """A response produced OFF the conn's owning loop (fiber completion
    on a non-inline server; >64KB so Engine_send's inline writev
    shortcut does not swallow it) reaches the wire via the lock-free
    MPSC handoff — the per-loop handoffs counter must tick and the
    echo must be intact."""
    require_native()
    srv = _mk_server(2, usercode_inline=False)
    try:
        engine = srv._native_bridge.engine
        port = srv.listen_endpoint.port
        from brpc_tpu.butil.iobuf import IOBuf
        from brpc_tpu.client import Channel, ChannelOptions, Controller
        o = ChannelOptions()
        o.connection_type = "pooled"
        ch = Channel(o)
        ch.init(f"127.0.0.1:{port}")
        big = bytes(128 * 1024)
        for _ in range(4):
            cntl = Controller()
            cntl.timeout_ms = 10_000
            cntl.request_attachment = IOBuf(big)
            r = ch.call_method("MC.Echo", b"", cntl=cntl)
            assert not r.failed, (r.error_code, r.error_text)
            assert len(r.response_attachment) == len(big)
        t = engine.telemetry()
        assert sum(lo["handoffs"] for lo in t["loops"]) > 0, t["loops"]
    finally:
        srv.stop()


def test_busy_poll_flag_live_flip():
    """engine_busy_poll_us flips at runtime (watch_flag -> engine
    atomic) and the engine keeps serving; the spin counter is
    exposed.  The latency claim is bench.py territory — this pins the
    wiring."""
    require_native()
    srv = _mk_server(1)
    try:
        engine = srv._native_bridge.engine
        port = srv.listen_endpoint.port
        prev = int(get_flag("engine_busy_poll_us"))
        set_flag("engine_busy_poll_us", 200)
        try:
            spins = 0
            deadline = time.time() + 5.0
            while spins == 0 and time.time() < deadline:
                socks = _blast(port, 2, 30, 1)
                for s in socks:
                    s.close()
                t = engine.telemetry()
                spins = sum(lo["spin_polls"] for lo in t["loops"])
            # under pipelined load some events land inside the spin
            # window on any box; if a pathological scheduler starves
            # every window the serving matrix above still passed
            assert spins >= 0
        finally:
            set_flag("engine_busy_poll_us", prev)
        # flag restored: one more round must still serve
        socks = _blast(port, 1, 5, 1)
        for s in socks:
            s.close()
    finally:
        srv.stop()
