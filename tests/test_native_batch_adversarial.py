"""Adversarial wire tests for the native call_batch lane.

The C++ batch reader (engine.cpp call_batch) parses frames, matches
correlation ids, and drains TICI interleaves with the GIL released —
exactly the code a malicious or desynced peer talks to.  These tests
drive it over a socketpair with handcrafted bytes, mirroring the
reference's raw-wire protocol unittests (SURVEY §4)."""

import socket
import struct
import threading

import pytest

from conftest import (WIRE_TAIL, load_native_or_skip, wire_resp_frame,
                      wire_tlv)


def _native():
    return load_native_or_skip("call_batch")


_tlv = wire_tlv


_resp_frame = wire_resp_frame
TAIL = WIRE_TAIL


def _complete_frames(data: bytes, want: int) -> bool:
    """True when ``data`` holds ``want`` whole TRPC frames."""
    off = count = 0
    while count < want:
        if len(data) - off < 12 or data[off:off + 4] != b"TRPC":
            return False
        (body,) = struct.unpack_from("<I", data, off + 4)
        if len(data) - off < 12 + body:
            return False
        off += 12 + body
        count += 1
    return True


def _run(nat, responder, n=2, timeout=5.0, base=1000):
    """call_batch over a socketpair; ``responder(data) -> bytes`` maps
    the request bytes to the peer's scripted reply.  The peer reads
    until all n request FRAMES are in hand (parsing headers, not an
    idle heuristic — a descheduled writer must not race the script)."""
    a, b = socket.socketpair()
    a.setblocking(False)

    def peer():
        b.settimeout(10)
        buf = b""
        try:
            while not _complete_frames(buf, n):
                c = b.recv(65536)
                if not c:
                    break
                buf += c
        except socket.timeout:
            pass
        reply = responder(buf)
        if reply:
            b.sendall(reply)

    t = threading.Thread(target=peer)
    t.start()
    try:
        payloads = [b"p%d" % i for i in range(n)]
        return nat.call_batch(a.fileno(), TAIL, payloads, timeout, base,
                              b"", b"")
    finally:
        t.join(15)
        a.close()
        b.close()


def test_happy_path_out_of_order():
    """Responses arriving in reverse order must still land by cid."""
    nat = _native()
    results, acks = _run(
        nat, lambda req: _resp_frame(1001, b"second")
        + _resp_frame(1000, b"first"))
    assert bytes(results[0]) == b"first"
    assert bytes(results[1]) == b"second"
    assert acks == []


def test_duplicate_cid_rejected():
    nat = _native()
    with pytest.raises(ValueError, match="cid"):
        _run(nat, lambda req: _resp_frame(1000) + _resp_frame(1000))


def test_cid_out_of_range_rejected():
    nat = _native()
    with pytest.raises(ValueError, match="cid"):
        _run(nat, lambda req: _resp_frame(9999) + _resp_frame(1000))


def test_bad_magic_rejected():
    nat = _native()
    with pytest.raises(ValueError, match="magic"):
        _run(nat, lambda req: b"JUNKJUNKJUNKJUNK" * 4)


def test_truncated_stream_times_out():
    """A peer that answers one of two responses then goes silent must
    produce a timeout, not a hang."""
    nat = _native()
    with pytest.raises(TimeoutError):
        _run(nat, lambda req: _resp_frame(1000), timeout=0.5)


def test_tici_interleave_collected():
    """TICI credit-return frames between responses come back as acks."""
    nat = _native()
    tici = b"TICI" + struct.pack("<I", 2) + struct.pack("<QQ", 7, 8)
    results, acks = _run(
        nat, lambda req: _resp_frame(1000) + tici + _resp_frame(1001))
    assert bytes(results[0]) == b"ok"
    assert sorted(acks) == [7, 8]


def test_oversized_ack_count_rejected():
    nat = _native()
    evil = b"TICI" + struct.pack("<I", 1 << 20)
    with pytest.raises(ValueError, match="ack"):
        _run(nat, lambda req: evil + _resp_frame(1000) + _resp_frame(1001))


def test_error_response_returned_whole_for_python_decode():
    """A response with controller-tier tags (error code) must come back
    as (frame_body, meta_size) for RpcMeta decoding, not a bare buf."""
    nat = _native()
    err_meta = _tlv(6, struct.pack("<i", 1003)) + _tlv(7, b"nope")
    results, acks = _run(
        nat, lambda req: _resp_frame(1000, b"", extra_meta=err_meta)
        + _resp_frame(1001))
    assert type(results[0]) is tuple
    body, msize = results[0]
    from brpc_tpu.protocol.meta import RpcMeta
    meta = RpcMeta.decode(bytes(memoryview(body)[:msize]))
    assert meta.error_code == 1003 and meta.error_text == "nope"
    assert type(results[1]) is not tuple


def test_attachment_response_returned_whole():
    """attachment-size TLV makes the item non-plain: full frame back."""
    nat = _native()
    att_meta = _tlv(3, struct.pack("<I", 2))
    results, _ = _run(
        nat, lambda req: _resp_frame(1000, b"bodyAT", extra_meta=att_meta)
        + _resp_frame(1001))
    assert type(results[0]) is tuple


def test_request_frames_well_formed():
    """What the lane WRITES must parse as the server's cut loop would:
    header sizes consistent, cids consecutive from the base."""
    nat = _native()
    seen = {}

    def capture(req):
        seen["req"] = req
        return _resp_frame(1000) + _resp_frame(1001)

    _run(nat, capture)
    req = seen["req"]
    cids = []
    off = 0
    while off < len(req):
        assert req[off:off + 4] == b"TRPC"
        body, msize = struct.unpack_from("<II", req, off + 4)
        assert msize <= body
        meta = req[off + 12:off + 12 + msize]
        # first TLV is the cid
        assert meta[0] == 1
        (cid,) = struct.unpack_from("<Q", meta, 5)
        cids.append(cid)
        off += 12 + body
    assert cids == [1000, 1001]
