"""Every registered protocol on the NATIVE port.

tpu_std and HTTP/1.x are cut in C++; anything else (h2/gRPC, redis,
thrift) flips the connection to PASSTHROUGH — the engine delivers raw
gulps and the server's InputMessenger registry (the same table the
Python transport uses) cuts and dispatches.  ≈ the reference's single
C++ ingestion loop carrying all ~20 protocols
(input_messenger.cpp:329); real grpcio / RESP / thrift clients are the
interop peers."""

import threading

import pytest

from brpc_tpu.client import Channel
from brpc_tpu.client.redis_client import RedisClient
from brpc_tpu.server import Server, ServerOptions, Service
from brpc_tpu.server.service import raw_method


class MiniRedis:
    def __init__(self):
        self.store = {}
        self.lock = threading.Lock()

    def on_command(self, args):
        cmd = args[0].upper()
        with self.lock:
            if cmd == b"PING":
                return "PONG"
            if cmd == b"SET":
                self.store[args[1]] = args[2]
                return "OK"
            if cmd == b"GET":
                return self.store.get(args[1])
        from brpc_tpu.protocol.resp import RedisError
        raise RedisError(f"unknown command {cmd.decode()}")


class EchoSvc(Service):
    def Echo(self, cntl, request):
        return request

    @raw_method(native="echo")
    def EchoRaw(self, payload, attachment):
        return payload, attachment


@pytest.fixture(scope="module")
def server():
    opts = ServerOptions()
    opts.native = True
    opts.native_loops = 1
    opts.usercode_inline = True
    srv = Server(opts)
    srv.add_service(EchoSvc(), name="EchoSvc")
    srv.add_service(MiniRedis(), name="redis")
    assert srv.start("127.0.0.1:0") == 0
    yield srv
    srv.stop()


def test_grpcio_client_against_native_port(server):
    grpc = pytest.importorskip("grpc")
    ep = server.listen_endpoint
    ident = lambda b: b  # noqa: E731
    with grpc.insecure_channel(f"{ep.host}:{ep.port}") as ch:
        fn = ch.unary_unary("/EchoSvc/Echo", request_serializer=ident,
                            response_deserializer=ident)
        for i in range(5):
            assert fn(b"over-h2-%d" % i, timeout=10) == b"over-h2-%d" % i


def test_redis_client_against_native_port(server):
    r = RedisClient(str(server.listen_endpoint))
    try:
        assert r.ping() == "PONG"
        assert r.set("k", b"v") == "OK"
        assert r.get("k") == b"v"
    finally:
        r.close()


def test_thrift_client_against_native_port():
    """Thrift framed-binary against a native-port server (own fixture:
    the thrift service shape differs from the shared one)."""
    from brpc_tpu.protocol.thrift_proto import ThriftClient

    class EchoThrift:
        def handle(self, method, body):
            if method == "echo":
                return body
            raise KeyError(method)

    opts = ServerOptions()
    opts.native = True
    opts.native_loops = 1
    opts.usercode_inline = True
    srv = Server(opts)
    srv.add_service(EchoThrift(), name="thrift")
    assert srv.start("127.0.0.1:0") == 0
    try:
        tc = ThriftClient(str(srv.listen_endpoint))
        try:
            assert tc.call("echo", b"\x0b\x00\x01payload\x00") \
                == b"\x0b\x00\x01payload\x00"
        finally:
            tc.close()
    finally:
        srv.stop()


def test_passthrough_off_loop_on_noninline_server():
    """usercode_inline=False: passthrough handlers run on the fiber
    pool (per-connection ExecutionQueue), so a handler that blocks must
    not stall the engine loop — natively-dispatched tpu_std traffic
    keeps flowing while a gRPC handler sleeps."""
    import time as _time

    grpc = pytest.importorskip("grpc")
    opts = ServerOptions()
    opts.native = True
    opts.native_loops = 1          # usercode_inline stays False
    srv = Server(opts)

    class Slow(Service):
        def Echo(self, cntl, request):
            _time.sleep(0.5)       # blocking handler
            return request

        @raw_method(native="echo")
        def EchoRaw(self, payload, attachment):
            return payload, attachment

    srv.add_service(Slow(), name="Slow")
    assert srv.start("127.0.0.1:0") == 0
    try:
        ep = srv.listen_endpoint
        ident = lambda b: b  # noqa: E731
        gch = grpc.insecure_channel(f"{ep.host}:{ep.port}")
        fn = gch.unary_unary("/Slow/Echo", request_serializer=ident,
                             response_deserializer=ident)
        fut = fn.future(b"slow-one", timeout=30)
        _time.sleep(0.1)           # the handler is now sleeping
        # the loop must still answer native traffic promptly.  One
        # bounded retry on a CONNECTION-level error: under full-suite
        # load a transient conn failure was observed once (~1/6 runs,
        # order-dependent); the property under test is the TIMING of a
        # successful call — a genuinely blocked loop fails the dt
        # assert on every attempt, never with a socket error.
        from brpc_tpu.client.channel import RpcError

        ch = Channel()
        ch.init(str(ep))
        for attempt in range(2):
            t0 = _time.perf_counter()
            try:
                resp, _ = ch.call_raw("Slow.EchoRaw", b"fast",
                                      timeout_ms=5_000)
                break
            except RpcError as e:
                if attempt:
                    raise AssertionError(
                        f"raw lane failed twice: [{e.code}] {e}") \
                        from e
        dt = _time.perf_counter() - t0
        assert bytes(resp) == b"fast"
        assert dt < 0.4, f"native lane stalled {dt:.2f}s behind a " \
                         "blocking passthrough handler"
        assert fut.result(timeout=30) == b"slow-one"
        gch.close()
    finally:
        srv.stop()


def test_all_protocols_one_native_port(server):
    """tpu_std (native cut) + HTTP (native cut) + gRPC (passthrough) +
    redis (passthrough), interleaved against one listener."""
    import http.client

    grpc = pytest.importorskip("grpc")
    ep = server.listen_endpoint
    # tpu_std
    ch = Channel()
    ch.init(str(ep))
    resp, _ = ch.call_raw("EchoSvc.EchoRaw", b"std", timeout_ms=5_000)
    assert bytes(resp) == b"std"
    # http
    hc = http.client.HTTPConnection(ep.host, ep.port, timeout=10)
    hc.request("POST", "/EchoSvc/Echo", body=b"via-http")
    r = hc.getresponse()
    assert r.status == 200 and r.read() == b"via-http"
    hc.close()
    # grpc
    ident = lambda b: b  # noqa: E731
    with grpc.insecure_channel(f"{ep.host}:{ep.port}") as gch:
        fn = gch.unary_unary("/EchoSvc/Echo", request_serializer=ident,
                             response_deserializer=ident)
        assert fn(b"via-grpc", timeout=10) == b"via-grpc"
    # redis
    rc = RedisClient(str(ep))
    try:
        assert rc.ping() == "PONG"
    finally:
        rc.close()
    # tpu_std again (the earlier channels unaffected)
    resp, _ = ch.call_raw("EchoSvc.EchoRaw", b"still", timeout_ms=5_000)
    assert bytes(resp) == b"still"
