"""Ecosystem protocol tests: redis (RESP) client+server on the shared
port, memcached text client, thrift framed-binary client+server
(≈ /root/reference/src/brpc/redis.h, memcache.h,
policy/thrift_protocol.cpp capabilities)."""

import socketserver
import threading

import pytest

from brpc_tpu.client.memcache_client import MemcacheClient
from brpc_tpu.client.redis_client import RedisClient
from brpc_tpu.protocol.resp import (NIL, RedisError, decode_one,
                                    encode_command, encode_reply)
from brpc_tpu.protocol.thrift_proto import (TBinary, ThriftApplicationError,
                                            ThriftClient)
from brpc_tpu.server import Server, Service


# -- RESP codec -------------------------------------------------------------

def test_resp_encode_known_bytes():
    assert encode_reply("OK") == b"+OK\r\n"
    assert encode_reply(42) == b":42\r\n"
    assert encode_reply(b"hi") == b"$2\r\nhi\r\n"
    assert encode_reply(None) == b"$-1\r\n"
    assert encode_reply([b"a", 1]) == b"*2\r\n$1\r\na\r\n:1\r\n"
    assert encode_reply(RedisError("boom")) == b"-ERR boom\r\n"
    assert encode_command("GET", "k") == b"*2\r\n$3\r\nGET\r\n$1\r\nk\r\n"


def test_resp_decode_roundtrip_and_partials():
    v, pos = decode_one(b"+PONG\r\n")
    assert v == "PONG" and pos == 7
    v, pos = decode_one(b"$3\r\nabc\r\n")
    assert v == b"abc"
    v, pos = decode_one(b"*2\r\n:1\r\n:2\r\n")
    assert v == [1, 2]
    v, pos = decode_one(b"$-1\r\n")
    assert v is NIL
    # partial: no progress
    v, pos = decode_one(b"$10\r\nabc")
    assert pos == 0 and v is None


# -- redis on the shared RPC port -------------------------------------------

class MiniRedis:
    """In-memory command handler registered as the 'redis' service."""

    def __init__(self):
        self.store = {}
        self.lock = threading.Lock()

    def on_command(self, args):
        cmd = args[0].upper()
        with self.lock:
            if cmd == b"PING":
                return "PONG"
            if cmd == b"SET":
                self.store[args[1]] = args[2]
                return "OK"
            if cmd == b"GET":
                return self.store.get(args[1])
            if cmd == b"DEL":
                n = 0
                for k in args[1:]:
                    n += 1 if self.store.pop(k, None) is not None else 0
                return n
            if cmd == b"INCR":
                v = int(self.store.get(args[1], b"0")) + 1
                self.store[args[1]] = str(v).encode()
                return v
            if cmd == b"KEYS":
                return sorted(self.store)
            raise RedisError(f"unknown command {cmd.decode()}")


@pytest.fixture(scope="module")
def redis_server():
    srv = Server()
    srv.add_service(MiniRedis(), name="redis")

    class Echo(Service):
        def Echo(self, cntl, request):
            return request

    srv.add_service(Echo(), name="E")
    assert srv.start("127.0.0.1:0") == 0
    yield srv
    srv.stop()


def test_redis_client_against_shared_port(redis_server):
    r = RedisClient(str(redis_server.listen_endpoint))
    try:
        assert r.ping() == "PONG"
        assert r.set("k1", b"v1") == "OK"
        assert r.get("k1") == b"v1"
        assert r.get("missing") is None
        assert r.incr("ctr") == 1
        assert r.incr("ctr") == 2
        assert r.delete("k1") == 1
        with pytest.raises(RedisError):
            r.command("NOPE")
    finally:
        r.close()


def test_redis_pipeline(redis_server):
    r = RedisClient(str(redis_server.listen_endpoint))
    try:
        replies = r.pipeline([("SET", "p%d" % i, "x%d" % i)
                              for i in range(10)]
                             + [("GET", "p7")])
        assert replies[:10] == ["OK"] * 10
        assert replies[10] == b"x7"
    finally:
        r.close()


def test_redis_and_rpc_share_the_port(redis_server):
    """RESP and tpu_std coexist on one port (multi-protocol detection)."""
    from brpc_tpu.client import Channel
    ch = Channel()
    ch.init(str(redis_server.listen_endpoint))
    assert ch.call("E.Echo", b"rpc-here") == b"rpc-here"
    r = RedisClient(str(redis_server.listen_endpoint))
    try:
        assert r.ping() == "PONG"
    finally:
        r.close()


# -- memcache client --------------------------------------------------------

class _MiniMemcached(socketserver.ThreadingTCPServer):
    """Tiny text-protocol memcached for client testing."""
    allow_reuse_address = True
    daemon_threads = True

    def __init__(self):
        self.store = {}
        self.cas_counter = [0]
        super().__init__(("127.0.0.1", 0), _McHandler)


class _McHandler(socketserver.StreamRequestHandler):
    def handle(self):
        srv = self.server
        while True:
            line = self.rfile.readline()
            if not line:
                return
            parts = line.strip().split()
            if not parts:
                continue
            verb = parts[0]
            if verb in (b"set", b"add", b"replace", b"cas"):
                key, flags, exp, n = (parts[1].decode(), int(parts[2]),
                                      int(parts[3]), int(parts[4]))
                data = self.rfile.read(n + 2)[:n]
                exists = key in srv.store
                if (verb == b"add" and exists) or \
                        (verb == b"replace" and not exists):
                    self.wfile.write(b"NOT_STORED\r\n")
                    continue
                if verb == b"cas":
                    want = int(parts[5])
                    cur = srv.store.get(key)
                    if cur is None:
                        self.wfile.write(b"NOT_FOUND\r\n")
                        continue
                    if cur[2] != want:
                        self.wfile.write(b"EXISTS\r\n")
                        continue
                srv.cas_counter[0] += 1
                srv.store[key] = (data, flags, srv.cas_counter[0])
                self.wfile.write(b"STORED\r\n")
            elif verb == b"gets" or verb == b"get":
                for k in parts[1:]:
                    ent = srv.store.get(k.decode())
                    if ent is not None:
                        data, flags, cas = ent
                        self.wfile.write(
                            b"VALUE %s %d %d %d\r\n%s\r\n"
                            % (k, flags, len(data), cas, data))
                self.wfile.write(b"END\r\n")
            elif verb == b"delete":
                ok = srv.store.pop(parts[1].decode(), None)
                self.wfile.write(b"DELETED\r\n" if ok else b"NOT_FOUND\r\n")
            elif verb in (b"incr", b"decr"):
                k = parts[1].decode()
                ent = srv.store.get(k)
                if ent is None:
                    self.wfile.write(b"NOT_FOUND\r\n")
                    continue
                v = int(ent[0]) + (int(parts[2]) if verb == b"incr"
                                   else -int(parts[2]))
                srv.store[k] = (str(v).encode(), ent[1], ent[2])
                self.wfile.write(b"%d\r\n" % v)
            elif verb == b"version":
                self.wfile.write(b"VERSION mini-1.0\r\n")
            else:
                self.wfile.write(b"ERROR\r\n")


@pytest.fixture(scope="module")
def memcached():
    srv = _MiniMemcached()
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield f"127.0.0.1:{srv.server_address[1]}"
    srv.shutdown()


def test_memcache_client(memcached):
    mc = MemcacheClient(memcached)
    try:
        assert mc.version().startswith("VERSION")
        assert mc.set("a", b"hello", flags=7)
        got = mc.gets("a")
        assert got is not None
        value, flags, cas = got
        assert value == b"hello" and flags == 7 and cas is not None
        assert mc.get("missing") is None
        assert mc.add("a", b"nope") is False          # exists
        assert mc.replace("a", b"world") is True
        assert mc.get("a") == b"world"
        assert mc.set("n", b"10")
        assert mc.incr("n", 5) == 15
        assert mc.decr("n", 3) == 12
        assert mc.incr("missing") is None
        assert mc.delete("a") is True
        assert mc.delete("a") is False
        # cas: stale id fails, fresh id succeeds
        mc.set("c", b"1")
        _, _, cas = mc.gets("c")
        assert mc.cas("c", b"2", cas) is True
        assert mc.cas("c", b"3", cas) is False
    finally:
        mc.close()


# -- thrift -----------------------------------------------------------------

class CalcThrift:
    """Thrift service: methods handle (method, body) -> body."""

    def handle(self, method, body):
        if method == "echo":
            return body
        if method == "greet":
            name, _ = TBinary.read_string(body, 0)
            return TBinary.write_string(b"hello " + name)
        if method == "boom":
            raise RuntimeError("kaboom")
        raise KeyError(method)


@pytest.fixture(scope="module")
def thrift_server():
    srv = Server()
    srv.add_service(CalcThrift(), name="thrift")
    assert srv.start("127.0.0.1:0") == 0
    yield srv
    srv.stop()


def test_thrift_call_roundtrip(thrift_server):
    tc = ThriftClient(str(thrift_server.listen_endpoint))
    try:
        assert tc.call("echo", b"\x0b\x00\x01payload\x00") \
            == b"\x0b\x00\x01payload\x00"
        out = tc.call("greet", TBinary.write_string(b"tpu"))
        name, _ = TBinary.read_string(out, 0)
        assert name == b"hello tpu"
    finally:
        tc.close()


def test_thrift_unknown_method_and_exception(thrift_server):
    tc = ThriftClient(str(thrift_server.listen_endpoint))
    try:
        with pytest.raises(ThriftApplicationError) as ei:
            tc.call("nope")
        assert ei.value.code == 1                    # UNKNOWN_METHOD
        with pytest.raises(ThriftApplicationError) as ei:
            tc.call("boom")
        assert ei.value.code == 6                    # INTERNAL_ERROR
        assert "kaboom" in ei.value.message
        # connection still alive after exceptions
        assert tc.call("echo", b"\x00") == b"\x00"
    finally:
        tc.close()


def test_thrift_wire_format_constants():
    from brpc_tpu.protocol.thrift_proto import (M_CALL, VERSION_1,
                                                pack_message,
                                                unpack_message)
    frame = pack_message(M_CALL, "m", 7, b"\x00")
    # [len][0x80 01 00 01][i32 len "m"]["m"][i32 7][body]
    assert frame[4:8] == b"\x80\x01\x00\x01"
    assert frame[8:12] == b"\x00\x00\x00\x01"
    assert frame[12:13] == b"m"
    mtype, name, seqid, body = unpack_message(frame[4:])
    assert (mtype, name, seqid, body) == (M_CALL, "m", 7, b"\x00")
    assert VERSION_1 == 0x80010000
