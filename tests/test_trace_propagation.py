"""Distributed rpcz — cross-protocol trace propagation + stitching.

The contract under test (the distributed-rpcz PR):

- an explicitly traced call records BOTH halves — a client span in the
  caller and a server span parented to it — on EVERY wire protocol
  ({tpu_std, HTTP/1.1, gRPC-h2}) against BOTH server transports
  ({pytransport, native slim lanes});
- tracing is observer-effect-free on the native lanes: the engine
  hands the trace context through the kind-3/kind-4 shims instead of
  falling back, so the trace-caused fallback counters stay at zero;
- a ParallelChannel fan-out under one forced trace yields one stitched
  tree (root + N parented branch spans, each with its server child)
  from /rpcz?trace_id=X, and the Chrome trace export is well-formed.
"""

import json
import urllib.request

import pytest

from brpc_tpu.client import Channel, ChannelOptions, Controller
from brpc_tpu.client.parallel_channel import ParallelChannel
from brpc_tpu.rpcz import (format_traceparent, global_span_store,
                           parse_traceparent)
from brpc_tpu.server import Server, ServerOptions, Service

from conftest import require_native  # noqa: E402


class TSvc(Service):
    def Echo(self, cntl, request):
        cntl.annotate("handled")
        return b"ok:" + bytes(request)


def _server(native: bool):
    opts = ServerOptions()
    if native:
        require_native()
        opts.native = True
        opts.usercode_inline = True
        opts.native_loops = 1
    srv = Server(opts)
    srv.add_service(TSvc(), name="T")
    assert srv.start("127.0.0.1:0") == 0
    return srv


def _channel(srv, protocol: str) -> Channel:
    co = ChannelOptions()
    co.protocol = protocol
    if protocol != "grpc":
        co.connection_type = "pooled"
    ch = Channel(co)
    ch.init(str(srv.listen_endpoint))
    return ch


def _assert_linked(trace_id: int, remote: str):
    """One client + one server span under ``trace_id``, parented."""
    spans = global_span_store().by_trace(trace_id)
    server_spans = [s for s in spans if s.is_server]
    client_spans = [s for s in spans if not s.is_server]
    assert len(server_spans) == 1, [s.describe() for s in spans]
    assert len(client_spans) == 1, [s.describe() for s in spans]
    srv_s, cli_s = server_spans[0], client_spans[0]
    assert srv_s.trace_id == trace_id and cli_s.trace_id == trace_id
    assert srv_s.parent_span_id == cli_s.span_id
    assert cli_s.parent_span_id == 0
    assert cli_s.remote_side == remote
    return cli_s, srv_s


# ---- propagation matrix: protocol x server transport ------------------

MATRIX = [("tpu_std", False), ("tpu_std", True),
          ("http", False), ("http", True),
          ("grpc", False), ("grpc", True)]


@pytest.mark.parametrize("protocol,native", MATRIX,
                         ids=[f"{p}-{'native' if n else 'py'}"
                              for p, n in MATRIX])
def test_propagation_matrix(protocol, native):
    global_span_store().clear()
    srv = _server(native)
    trace_id = 0x7A0000 + len(protocol) + (1 if native else 0)
    try:
        ch = _channel(srv, protocol)
        cntl = Controller()
        cntl.timeout_ms = 10_000
        cntl.trace_id = trace_id
        c = ch.call_method("T.Echo", b"m", cntl=cntl)
        assert not c.failed, c.error_text
        assert bytes(c.response) == b"ok:m"
        _assert_linked(trace_id, str(srv.listen_endpoint))
    finally:
        srv.stop()
        global_span_store().clear()


def test_traced_slim_lane_no_fallbacks_and_native_count():
    """Observer effect retired: a traced tpu_std call against the slim
    native lane stays ON the lane (native counter moves) and neither
    trace-related fallback reason moves."""
    global_span_store().clear()
    srv = _server(native=True)
    try:
        before = srv._native_bridge.engine.telemetry()["fallbacks"]
        ch = _channel(srv, "tpu_std")
        cntl = Controller()
        cntl.timeout_ms = 10_000
        cntl.trace_id = 0x51EEF
        c = ch.call_method("T.Echo", b"zz", cntl=cntl)
        assert not c.failed, c.error_text
        tele = srv._native_bridge.engine.telemetry()
        after = tele["fallbacks"]
        assert after["rpc_meta_tag"] == before["rpc_meta_tag"]
        assert after["rpc_trace_raw_lane"] == before["rpc_trace_raw_lane"]
        assert tele["methods"]["T.Echo"]["handled"] >= 1
        # the span covers engine queueing (backdated receive)
        srv_span = [s for s in global_span_store().by_trace(0x51EEF)
                    if s.is_server][0]
        assert srv_span.received_us <= srv_span.start_us
    finally:
        srv.stop()
        global_span_store().clear()


def test_traced_raw_lane_falls_back_with_named_reason():
    """kind-0/1/2 methods have no span machinery: an explicit trace
    routes them to the Python path under the NAMED rpc_trace_raw_lane
    reason (never the catch-all rpc_meta_tag)."""
    require_native()
    from brpc_tpu.server.service import raw_method

    class RawSvc(Service):
        @raw_method(native="echo")
        def Raw(self, payload, attachment):
            return payload, attachment

    opts = ServerOptions()
    opts.native = True
    opts.usercode_inline = True
    opts.native_loops = 1
    srv = Server(opts)
    srv.add_service(RawSvc(), name="R")
    assert srv.start("127.0.0.1:0") == 0
    global_span_store().clear()
    try:
        before = srv._native_bridge.engine.telemetry()["fallbacks"]
        ch = _channel(srv, "tpu_std")
        cntl = Controller()
        cntl.timeout_ms = 10_000
        cntl.trace_id = 0xBAD5EED
        c = ch.call_method("R.Raw", b"tr", cntl=cntl)
        assert not c.failed, c.error_text
        tele = srv._native_bridge.engine.telemetry()
        after = tele["fallbacks"]
        assert after["rpc_trace_raw_lane"] > before["rpc_trace_raw_lane"]
        assert after["rpc_meta_tag"] == before["rpc_meta_tag"]
        assert tele["methods"]["R.Raw"]["fb_rpc_trace_raw_lane"] >= 1
    finally:
        srv.stop()
        global_span_store().clear()


# ---- the acceptance scenario: traced fan-out, stitched tree -----------

@pytest.fixture()
def fanout():
    require_native()
    global_span_store().clear()
    subs = []
    for _ in range(2):
        subs.append(_server(native=True))
    pch = ParallelChannel()
    for s in subs:
        sub = Channel()
        sub.init(str(s.listen_endpoint))
        pch.add_channel(sub)
    yield subs, pch
    for s in subs:
        s.stop()
    global_span_store().clear()


def _fetch(ep, query: str):
    with urllib.request.urlopen(f"http://{ep}/rpcz?{query}",
                                timeout=10) as r:
        return r.read()


def test_parallel_fanout_stitched_tree(fanout):
    subs, pch = fanout
    before = [s._native_bridge.engine.telemetry()["fallbacks"]
              for s in subs]
    cntl = Controller()
    cntl.timeout_ms = 10_000
    cntl.trace_id = 0xFA27
    c = pch.call_method("T.Echo", b"fan", cntl=cntl)
    assert not c.failed, c.error_text
    assert c.response == [b"ok:fan", b"ok:fan"]

    # zero trace-caused fallbacks on every sub-server for the traced run
    for i, s in enumerate(subs):
        after = s._native_bridge.engine.telemetry()["fallbacks"]
        assert after["rpc_meta_tag"] == before[i]["rpc_meta_tag"]
        assert after["rpc_trace_raw_lane"] == \
            before[i]["rpc_trace_raw_lane"]

    # one stitched tree from /rpcz?trace_id=...&format=json: root + 2
    # parented branch client spans, each with its server child
    doc = json.loads(_fetch(subs[0].listen_endpoint,
                            "trace_id=fa27&stitch=1&format=json"))
    assert doc["stitched"] is True
    spans = doc["spans"]
    assert len(spans) == 5, spans
    by_id = {s["span_id"]: s for s in spans}
    roots = doc["tree"]
    assert len(roots) == 1
    root = by_id[roots[0]["span_id"]]
    assert root["side"] == "client"
    assert "ParallelChannel" in root["method"]
    branches = roots[0]["children"]
    assert len(branches) == 2
    sub_eps = {str(s.listen_endpoint) for s in subs}
    for b in branches:
        bs = by_id[b["span_id"]]
        assert bs["side"] == "client"
        assert bs["remote"] in sub_eps
        assert len(b["children"]) == 1
        leaf = by_id[b["children"][0]["span_id"]]
        assert leaf["side"] == "server"
        assert leaf["method"] == "T.Echo"

    # Chrome trace export is well-formed and Perfetto-loadable in shape
    chrome = json.loads(_fetch(subs[0].listen_endpoint,
                               "trace_id=fa27&stitch=1&format=chrome"))
    xev = [e for e in chrome["traceEvents"] if e.get("ph") == "X"]
    assert len(xev) == 5
    for e in xev:
        assert {"name", "ts", "dur", "pid", "tid", "args"} <= set(e)
        assert e["dur"] >= 1
    # the text tree renders every span
    txt = _fetch(subs[0].listen_endpoint,
                 "trace_id=fa27&stitch=1&format=tree").decode()
    assert txt.count("T.Echo") >= 4 and "ParallelChannel" in txt


def test_trace_dump_cli(fanout):
    import contextlib
    import io

    subs, pch = fanout
    cntl = Controller()
    cntl.timeout_ms = 10_000
    cntl.trace_id = 0xFA28
    c = pch.call_method("T.Echo", b"x", cntl=cntl)
    assert not c.failed, c.error_text
    from brpc_tpu.tools.trace_dump import main as td_main
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = td_main([str(subs[0].listen_endpoint), "fa28"])
    assert rc == 0
    doc = json.loads(buf.getvalue())
    assert sum(1 for e in doc["traceEvents"] if e.get("ph") == "X") == 5


# ---- /rpcz paging + traceparent unit coverage -------------------------

def test_rpcz_json_limit_paging():
    """The stitcher's per-hop fetch is always bounded: &limit caps the
    span list even when a trace has more spans than the page."""
    global_span_store().clear()
    srv = _server(native=False)
    try:
        ch = _channel(srv, "tpu_std")
        for i in range(6):
            cntl = Controller()
            cntl.timeout_ms = 10_000
            cntl.trace_id = 0xCAB1E
            assert not ch.call_method("T.Echo", b"x", cntl=cntl).failed
        doc = json.loads(_fetch(srv.listen_endpoint,
                                "trace_id=cab1e&format=json&limit=3"))
        assert len(doc["spans"]) == 3
        doc = json.loads(_fetch(srv.listen_endpoint,
                                "trace_id=cab1e&format=json&limit=100"))
        assert len(doc["spans"]) == 12        # 6 client + 6 server
    finally:
        srv.stop()
        global_span_store().clear()


def test_traceparent_roundtrip():
    v = format_traceparent(0xDEADBEEF, 0x1234)
    assert v == ("00-000000000000000000000000deadbeef-"
                 "0000000000001234-01")
    assert parse_traceparent(v) == (0xDEADBEEF, 0x1234)
    assert parse_traceparent(v.encode()) == (0xDEADBEEF, 0x1234)
    # 128-bit foreign ids truncate to the low 64 bits, consistently
    big = "00-" + "ab" * 16 + "-" + "cd" * 8 + "-00"
    t, p = parse_traceparent(big)
    assert t == int("ab" * 8, 16)
    # malformed shapes reject cleanly
    for bad in ("", "00-zz-11-00", "00-" + "0" * 32 + "-" + "0" * 16
                + "-00", "garbage", None):
        assert parse_traceparent(bad) is None


def test_stitch_wall_clock_budget():
    """Dead peers must not hold the stitch (and the portal handler
    serving it) for max_hops * timeout_s: the walk shares one
    wall-clock budget and truncates when it runs out."""
    import time as _time

    from brpc_tpu.rpcz import global_span_store, start_client_span
    from brpc_tpu.rpcz_stitch import collect_trace

    global_span_store().clear()
    try:
        # 4 client spans, each pointing at a distinct unreachable peer
        for i in range(4):
            s = start_client_span("T.Echo", 0xB0D6E7)
            assert s is not None
            s.remote_side = f"10.255.0.{i}:1"
            s.finish()
        slept = []

        def dead_fetch(remote, trace_id, timeout_s, limit):
            # per-fetch timeout is clamped to the remaining budget
            assert timeout_s <= 0.25 + 1e-6
            slept.append(timeout_s)
            _time.sleep(timeout_s)
            raise ConnectionError("blackholed")

        t0 = _time.monotonic()
        out = collect_trace(0xB0D6E7, timeout_s=2.0, budget_s=0.25,
                            fetch=dead_fetch)
        elapsed = _time.monotonic() - t0
        assert out["truncated"] is True
        assert elapsed < 1.0, elapsed          # nowhere near 4 * 2s
        assert 1 <= len(slept) < 4             # budget cut the walk short
        assert len(out["spans"]) == 4          # local seed still returned
    finally:
        global_span_store().clear()


def test_clock_skew_annotation():
    from brpc_tpu.rpcz_stitch import annotate_skew, build_tree
    spans = [
        {"span_id": 1, "parent_span_id": 0, "received_us": 1000,
         "side": "client"},
        {"span_id": 2, "parent_span_id": 1, "received_us": 400,
         "side": "server"},      # 600us in the parent's past: skewed
        {"span_id": 3, "parent_span_id": 1, "received_us": 1500,
         "side": "server"},
    ]
    annotate_skew(spans)
    assert spans[1]["clock_skew_us"] == 600
    assert "clock_skew_us" not in spans[2]
    roots = build_tree(spans)
    assert len(roots) == 1 and len(roots[0]["children"]) == 2
