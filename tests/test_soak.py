"""Short mixed-load soak: four client lanes (sync unary, pipelined
batch, streaming, device attachments) hammer one process concurrently
for a few seconds.  Catches cross-lane interference — shared reader
stalls, fabric window leaks, correlation-id mixups — that single-lane
tests cannot."""

import threading
import time

import jax.numpy as jnp

from brpc_tpu.butil.iobuf import IOBuf
from brpc_tpu.client import Channel, ChannelOptions, Controller
from brpc_tpu.models.ps_service import PSService
from brpc_tpu.server import Server, ServerOptions, Service
from brpc_tpu.streaming import StreamOptions, stream_accept, stream_create

SOAK_S = 5.0


class _Echo(Service):
    def Echo(self, cntl, request):
        cntl.response_attachment.append_iobuf(cntl.request_attachment)
        return request


class _Sink(Service):
    def Start(self, cntl, request):
        stream_accept(cntl, StreamOptions(on_received=lambda s, m: None,
                                          max_buf_size=1 << 20))
        return b"ok"


def test_mixed_load_soak():
    opts = ServerOptions()
    opts.native = True
    opts.usercode_inline = True
    srv = Server(opts)
    srv.add_service(_Echo(), name="E")
    srv.add_service(PSService(), name="PS")
    psrv = Server()                      # python transport for streams
    psrv.add_service(_Sink(), name="S")
    assert srv.start("127.0.0.1:0") == 0
    assert psrv.start("127.0.0.1:0") == 0
    addr, paddr = str(srv.listen_endpoint), str(psrv.listen_endpoint)

    stop = time.time() + SOAK_S
    errors = []
    counts = {}

    def lane(name, fn):
        def run():
            n = 0
            try:
                while time.time() < stop:
                    fn()
                    n += 1
            except Exception as e:       # noqa: BLE001 - recorded
                errors.append((name, repr(e)))
            counts[name] = n
        return threading.Thread(target=run, name=f"soak_{name}")

    co = ChannelOptions(); co.connection_type = "pooled"
    uch = Channel(co); uch.init(addr)
    def unary():
        cntl = Controller()
        cntl.request_attachment = IOBuf(b"u" * 512)
        c = uch.call_method("E.Echo", b"ping", cntl=cntl)
        assert not c.failed, c.error_text
        assert len(c.response_attachment) == 512

    bo = ChannelOptions(); bo.connection_type = "pooled"
    bch = Channel(bo); bch.init(addr)
    reqs = [b"b" * 64] * 32
    def batch():
        out = bch.call_batch("E.Echo", reqs)
        assert len(out) == 32 and all(o == b"b" * 64 for o in out)

    sch = Channel(); sch.init(paddr)
    def stream():
        cntl = Controller(); cntl.timeout_ms = 10_000
        s = stream_create(cntl, StreamOptions(max_buf_size=1 << 20))
        c = sch.call_method("S.Start", b"", cntl=cntl)
        assert not c.failed, c.error_text
        for _ in range(8):
            if s.write(b"x" * 4096) != 0:
                break
        s.close()

    dch = Channel(); dch.init(addr)
    x = jnp.arange(2048, dtype=jnp.float32)
    def device():
        cntl = Controller(); cntl.timeout_ms = 30_000
        cntl.request_device_attachment = x
        c = dch.call_method("PS.EchoTensor", b"", cntl=cntl)
        assert not c.failed, c.error_text
        c.response_device_attachment.tensor()

    threads = [lane("unary", unary), lane("batch", batch),
               lane("stream", stream), lane("device", device)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(SOAK_S + 30)
    srv.stop()
    psrv.stop()

    assert not errors, errors[:3]
    # every lane made real progress under contention
    for name in ("unary", "batch", "stream", "device"):
        assert counts.get(name, 0) > 5, counts


@__import__("pytest").mark.soak
def test_full_mixed_soak():
    """The VERDICT-r3 soak: pooled + short connections, pipelined
    batches, streaming, device attachments, live flag flips, and a
    fault-proxy partition mid-run — sustained for SOAK_SECONDS (default
    12 for CI; run SOAK_SECONDS=75 for the full 60-90s window).

    Pass bar: zero failures on the healthy lanes, recovery on the
    partitioned lane, zero leaked ICI window credit, zero stuck-fiber
    watchdog hits, and a stable raw-lane p99 (second half no worse than
    5x the first half)."""
    import os

    import pytest

    from brpc_tpu.butil.flags import get_flag, set_flag
    from brpc_tpu.butil.sanitizers import check_stalls
    from brpc_tpu.ici.endpoint import live_endpoints
    from brpc_tpu.server.service import raw_method
    from conftest import require_native
    from fault_proxy import FaultyTransport

    require_native()
    soak_s = float(os.environ.get("SOAK_SECONDS", "12"))

    class RawEcho(Service):
        @raw_method(native="echo")
        def Echo(self, payload, attachment):
            return payload, attachment

        @raw_method()
        def EchoPy(self, payload, attachment):
            # kind-2 lane: the engine calls this Python handler from
            # the loop thread (burst-batched GIL entry)
            return payload, attachment

    opts = ServerOptions()
    opts.native = True
    opts.usercode_inline = True
    srv = Server(opts)
    srv.add_service(_Echo(), name="E")
    srv.add_service(RawEcho(), name="R")
    srv.add_service(PSService(), name="PS")
    psrv = Server()                      # python transport for streams
    psrv.add_service(_Sink(), name="S")
    assert srv.start("127.0.0.1:0") == 0
    assert psrv.start("127.0.0.1:0") == 0
    addr, paddr = str(srv.listen_endpoint), str(psrv.listen_endpoint)
    ep = srv.listen_endpoint
    proxy = FaultyTransport(str(ep.host), ep.port)

    set_flag("stall_watchdog_s", 8.0)
    # earlier suites may deliberately strand descriptor credit (timeout
    # tests rely on the 120s TTL sweep); the soak asserts ITS OWN
    # workload's hygiene: per-endpoint baselines with STRONG refs (an
    # id()-keyed set could alias a GC'd endpoint to a new allocation
    # and mask a genuine soak leak)
    baseline = {e: e.outstanding_bytes for e in live_endpoints()}
    stop_at = time.time() + soak_s
    errors = []
    counts = {}
    lat: list = []                       # (t, us) raw-lane samples

    def lane(name, fn, tolerate=False):
        def run():
            n = 0
            while time.time() < stop_at:
                try:
                    fn()
                    n += 1
                except Exception as e:   # noqa: BLE001
                    if not tolerate:
                        errors.append((name, repr(e)))
                        break
                    time.sleep(0.05)
            counts[name] = n
        return threading.Thread(target=run, name=f"soak_{name}")

    co = ChannelOptions(); co.connection_type = "pooled"
    uch = Channel(co); uch.init(addr)
    def unary_pooled():
        cntl = Controller()
        cntl.request_attachment = IOBuf(b"u" * 512)
        c = uch.call_method("E.Echo", b"ping", cntl=cntl)
        assert not c.failed, c.error_text

    so = ChannelOptions(); so.connection_type = "short"
    sch_short = Channel(so); sch_short.init(addr)
    def unary_short():
        cntl = Controller(); cntl.timeout_ms = 10_000
        c = sch_short.call_method("E.Echo", b"s", cntl=cntl)
        assert not c.failed, c.error_text

    rch = Channel(co); rch.init(addr)
    def raw_lane():
        t0 = time.perf_counter()
        r, _ = rch.call_raw("R.Echo", b"", b"r" * 1024,
                            timeout_ms=10_000)
        lat.append((time.time(), (time.perf_counter() - t0) * 1e6))

    prch = Channel(co); prch.init(addr)
    def pyraw_lane():
        r, _ = prch.call_raw("R.EchoPy", b"k2", b"p" * 256,
                             timeout_ms=10_000)
        assert bytes(r) == b"k2"

    import http.client as _hc
    hconn = [None]
    def native_http():
        if hconn[0] is None:
            hconn[0] = _hc.HTTPConnection(ep.host, ep.port, timeout=10)
        try:
            hconn[0].request("POST", "/E/Echo", body=b"h" * 256)
            resp = hconn[0].getresponse()
            assert resp.status == 200 and len(resp.read()) == 256
        except Exception:
            try:
                hconn[0].close()
            finally:
                hconn[0] = None
            raise

    bch = Channel(co); bch.init(addr)
    reqs = [b"b" * 64] * 64
    def batch():
        out = bch.call_batch("E.Echo", reqs)
        assert len(out) == 64

    stch = Channel(); stch.init(paddr)
    def stream():
        cntl = Controller(); cntl.timeout_ms = 10_000
        s = stream_create(cntl, StreamOptions(max_buf_size=1 << 20))
        c = stch.call_method("S.Start", b"", cntl=cntl)
        assert not c.failed, c.error_text
        for _ in range(8):
            if s.write(b"x" * 4096) != 0:
                break
        s.close()

    dch = Channel(); dch.init(addr)
    x = jnp.arange(2048, dtype=jnp.float32)
    def device():
        cntl = Controller(); cntl.timeout_ms = 30_000
        cntl.request_device_attachment = x
        c = dch.call_method("PS.EchoTensor", b"", cntl=cntl)
        assert not c.failed, c.error_text
        c.response_device_attachment.tensor()

    import brpc_tpu.rpcz                     # defines the rpcz flags
    def flag_flipper():
        cur = int(get_flag("rpcz_max_samples_per_second", 1000))
        assert set_flag("rpcz_max_samples_per_second",
                        500 if cur == 1000 else 1000)
        mb = int(get_flag("max_body_size", 64 << 20))
        assert set_flag("max_body_size",
                        (32 << 20) if mb == (64 << 20) else (64 << 20))
        time.sleep(0.2)

    pch = Channel(co); pch.init(proxy.address)
    partition_recovered = [0]
    def through_proxy():
        cntl = Controller(); cntl.timeout_ms = 3_000
        c = pch.call_method("E.Echo", b"via-proxy", cntl=cntl)
        assert not c.failed, c.error_text
        if partition_done[0]:
            partition_recovered[0] += 1

    partition_done = [False]
    def partitioner():
        # one partition event mid-run, then heal
        time.sleep(max(1.0, soak_s * 0.3))
        proxy.partition = True
        proxy.kill_connections()
        time.sleep(min(3.0, soak_s * 0.2))
        proxy.heal()
        partition_done[0] = True

    threads = [lane("unary_pooled", unary_pooled),
               lane("unary_short", unary_short),
               lane("raw", raw_lane),
               lane("pyraw", pyraw_lane),
               lane("http", native_http),
               lane("batch", batch),
               lane("stream", stream),
               lane("device", device),
               lane("flags", flag_flipper),
               lane("proxy", through_proxy, tolerate=True),
               threading.Thread(target=partitioner, name="partitioner")]
    for t in threads:
        t.start()
    for t in threads:
        t.join(soak_s + 60)
    try:
        assert not errors, errors[:4]
        for name in ("unary_pooled", "unary_short", "raw", "pyraw",
                     "http", "batch", "stream", "device"):
            assert counts.get(name, 0) > 5, counts
        # the partitioned lane recovered after heal
        assert partition_recovered[0] > 0, counts
        # zero leaked ICI window credit (descriptors all settled)
        deadline = time.time() + 10
        def drained():
            return all(e.outstanding_bytes <= baseline.get(e, 0)
                       for e in live_endpoints())
        while not drained() and time.time() < deadline:
            time.sleep(0.05)
        assert drained(), [
            (e.socket_id, e.outstanding_bytes) for e in live_endpoints()
            if e.outstanding_bytes > baseline.get(e, 0)]
        # zero stuck fibers
        assert check_stalls() == 0
        # p99 stability: second half no worse than 5x first half
        if len(lat) >= 200:
            mid = (lat[0][0] + lat[-1][0]) / 2
            h1 = sorted(us for t, us in lat if t <= mid)
            h2 = sorted(us for t, us in lat if t > mid)
            if h1 and h2:
                p99_1 = h1[int(len(h1) * 0.99)]
                p99_2 = h2[int(len(h2) * 0.99)]
                assert p99_2 < max(5 * p99_1, 5_000.0), (p99_1, p99_2)
    finally:
        set_flag("stall_watchdog_s", 0.0)
        set_flag("max_body_size", 64 << 20)
        set_flag("rpcz_max_samples_per_second", 1000)
        proxy.close()
        srv.stop()
        psrv.stop()
