"""Short mixed-load soak: four client lanes (sync unary, pipelined
batch, streaming, device attachments) hammer one process concurrently
for a few seconds.  Catches cross-lane interference — shared reader
stalls, fabric window leaks, correlation-id mixups — that single-lane
tests cannot."""

import threading
import time

import jax.numpy as jnp

from brpc_tpu.butil.iobuf import IOBuf
from brpc_tpu.client import Channel, ChannelOptions, Controller
from brpc_tpu.models.ps_service import PSService
from brpc_tpu.server import Server, ServerOptions, Service
from brpc_tpu.streaming import StreamOptions, stream_accept, stream_create

SOAK_S = 5.0


class _Echo(Service):
    def Echo(self, cntl, request):
        cntl.response_attachment.append_iobuf(cntl.request_attachment)
        return request


class _Sink(Service):
    def Start(self, cntl, request):
        stream_accept(cntl, StreamOptions(on_received=lambda s, m: None,
                                          max_buf_size=1 << 20))
        return b"ok"


def test_mixed_load_soak():
    opts = ServerOptions()
    opts.native = True
    opts.usercode_inline = True
    srv = Server(opts)
    srv.add_service(_Echo(), name="E")
    srv.add_service(PSService(), name="PS")
    psrv = Server()                      # python transport for streams
    psrv.add_service(_Sink(), name="S")
    assert srv.start("127.0.0.1:0") == 0
    assert psrv.start("127.0.0.1:0") == 0
    addr, paddr = str(srv.listen_endpoint), str(psrv.listen_endpoint)

    stop = time.time() + SOAK_S
    errors = []
    counts = {}

    def lane(name, fn):
        def run():
            n = 0
            try:
                while time.time() < stop:
                    fn()
                    n += 1
            except Exception as e:       # noqa: BLE001 - recorded
                errors.append((name, repr(e)))
            counts[name] = n
        return threading.Thread(target=run, name=f"soak_{name}")

    co = ChannelOptions(); co.connection_type = "pooled"
    uch = Channel(co); uch.init(addr)
    def unary():
        cntl = Controller()
        cntl.request_attachment = IOBuf(b"u" * 512)
        c = uch.call_method("E.Echo", b"ping", cntl=cntl)
        assert not c.failed, c.error_text
        assert len(c.response_attachment) == 512

    bo = ChannelOptions(); bo.connection_type = "pooled"
    bch = Channel(bo); bch.init(addr)
    reqs = [b"b" * 64] * 32
    def batch():
        out = bch.call_batch("E.Echo", reqs)
        assert len(out) == 32 and all(o == b"b" * 64 for o in out)

    sch = Channel(); sch.init(paddr)
    def stream():
        cntl = Controller(); cntl.timeout_ms = 10_000
        s = stream_create(cntl, StreamOptions(max_buf_size=1 << 20))
        c = sch.call_method("S.Start", b"", cntl=cntl)
        assert not c.failed, c.error_text
        for _ in range(8):
            if s.write(b"x" * 4096) != 0:
                break
        s.close()

    dch = Channel(); dch.init(addr)
    x = jnp.arange(2048, dtype=jnp.float32)
    def device():
        cntl = Controller(); cntl.timeout_ms = 30_000
        cntl.request_device_attachment = x
        c = dch.call_method("PS.EchoTensor", b"", cntl=cntl)
        assert not c.failed, c.error_text
        c.response_device_attachment.tensor()

    threads = [lane("unary", unary), lane("batch", batch),
               lane("stream", stream), lane("device", device)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(SOAK_S + 30)
    srv.stop()
    psrv.stop()

    assert not errors, errors[:3]
    # every lane made real progress under contention
    for name in ("unary", "batch", "stream", "device"):
        assert counts.get(name, 0) > 5, counts
