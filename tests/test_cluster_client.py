"""Cluster-client tests: naming services, LB policies, circuit breaker,
and channel-over-cluster e2e with server death + recovery —
the reference's naming/LB test shapes
(/root/reference/test/brpc_naming_service_unittest.cpp,
brpc_load_balancer_unittest.cpp) on loopback."""

import collections
import os
import time

import pytest

from brpc_tpu.butil.endpoint import EndPoint, parse_endpoint
from brpc_tpu.client import Channel, Controller
from brpc_tpu.client.circuit_breaker import (CircuitBreakerMap,
                                             global_circuit_breaker_map)
from brpc_tpu.client.load_balancer import create_load_balancer
from brpc_tpu.client.naming_service import (ServerNode,
                                            create_naming_service,
                                            parse_server_line)
from brpc_tpu.policy import load_balancers  # noqa: F401 (registers)
from brpc_tpu.policy import naming          # noqa: F401 (registers)
from brpc_tpu.server import Server, Service


class _Cntl:
    """Minimal selection context."""
    request_code = 0
    excluded_servers = ()
    remote_side = None
    error_code = 0
    latency_us = 1000


def _nodes(*specs):
    return [parse_server_line(s) for s in specs]


def test_parse_server_line():
    n = parse_server_line("10.0.0.1:80 1/4 w=3")
    assert n.endpoint == EndPoint(host="10.0.0.1", port=80)
    assert n.tag == "1/4 w=3"
    assert parse_server_line("# comment") is None
    assert parse_server_line("") is None


def test_list_naming_service():
    ns = create_naming_service("list://1.1.1.1:10,2.2.2.2:20 tagx")
    assert ns is not None
    eps = ns.current
    assert len(eps) == 2
    assert eps[1].tag == "tagx"
    ns.stop()


def test_file_naming_service_reload(tmp_path):
    p = tmp_path / "servers"
    p.write_text("1.1.1.1:10\n# comment\n2.2.2.2:20\n")
    ns = create_naming_service(f"file://{p}")
    assert ns is not None
    ns.refresh_interval_s = 0.05
    assert len(ns.current) == 2
    p.write_text("1.1.1.1:10\n")
    deadline = time.time() + 3.0
    while time.time() < deadline and len(ns.current) != 1:
        ns.run_once()
        time.sleep(0.02)
    assert len(ns.current) == 1
    ns.stop()


def test_mesh_naming_service():
    pytest.importorskip("jax")
    ns = create_naming_service("mesh://testmesh")
    assert ns is not None
    nodes = ns.current
    assert len(nodes) == 8                      # virtual cpu mesh
    assert nodes[3].endpoint.is_device
    assert nodes[3].tag == "3/8"
    ns.stop()


def test_rr_cycles():
    lb = create_load_balancer("rr")
    lb.reset_servers(_nodes("1.1.1.1:1", "1.1.1.1:2", "1.1.1.1:3"))
    picks = [str(lb.select_server(_Cntl())) for _ in range(6)]
    assert picks[:3] == picks[3:]
    assert len(set(picks)) == 3


def test_wrr_respects_weights():
    lb = create_load_balancer("wrr")
    lb.reset_servers(_nodes("1.1.1.1:1 w=3", "1.1.1.1:2 w=1"))
    counts = collections.Counter(
        lb.select_server(_Cntl()).port for _ in range(40))
    assert counts[1] == 30 and counts[2] == 10


def test_consistent_hash_stability():
    lb = create_load_balancer("c_murmurhash")
    lb.reset_servers(_nodes("1.1.1.1:1", "1.1.1.1:2", "1.1.1.1:3",
                            "1.1.1.1:4"))
    class C(_Cntl):
        pass
    mapping = {}
    for code in range(200):
        c = C(); c.request_code = code
        mapping[code] = lb.select_server(c).port
    # same code → same server, and load spreads over all servers
    for code in range(200):
        c = C(); c.request_code = code
        assert lb.select_server(c).port == mapping[code]
    assert len(set(mapping.values())) == 4
    # removing one server only remaps its keys
    lb.reset_servers(_nodes("1.1.1.1:1", "1.1.1.1:2", "1.1.1.1:3"))
    moved = 0
    for code in range(200):
        c = C(); c.request_code = code
        new = lb.select_server(c).port
        if mapping[code] != 4:
            if new != mapping[code]:
                moved += 1
    assert moved < 40       # most keys stay put (consistent property)


def test_locality_aware_prefers_fast():
    lb = create_load_balancer("la")
    fast = parse_server_line("1.1.1.1:1")
    slow = parse_server_line("1.1.1.1:2")
    lb.reset_servers([fast, slow])
    # feed latencies
    for _ in range(50):
        node = lb.select_server(_Cntl())
        class C(_Cntl):
            pass
        c = C()
        c.remote_side = node
        c.latency_us = 1_000 if node.port == 1 else 100_000
        lb.feedback(c)
    picks = collections.Counter()
    for _ in range(100):
        node = lb.select_server(_Cntl())
        picks[node.port] += 1
        class C(_Cntl):
            pass
        c = C()
        c.remote_side = node
        c.latency_us = 1_000 if node.port == 1 else 100_000
        lb.feedback(c)
    assert picks[1] > 80


def test_circuit_breaker_trips_and_recovers():
    m = CircuitBreakerMap()
    ep = parse_endpoint("9.9.9.9:99")
    for _ in range(20):
        m.on_call(ep, 1009, 1000)
    assert m.isolated(ep)
    time.sleep(0.15)     # base isolation window passes
    assert not m.isolated(ep)


class EchoWho(Service):
    def __init__(self, who):
        self.who = who

    def Who(self, cntl, request):
        return self.who.encode()


def _start_server(who):
    srv = Server()
    srv.add_service(EchoWho(who), name="W")
    assert srv.start("127.0.0.1:0") == 0
    return srv


def test_cluster_channel_rr_spread_and_failover():
    global_circuit_breaker_map().reset()
    s1 = _start_server("a")
    s2 = _start_server("b")
    try:
        ch = Channel()
        url = f"list://{s1.listen_endpoint},{s2.listen_endpoint}"
        assert ch.init(url, "rr") == 0
        seen = set()
        for _ in range(8):
            c = ch.call_method("W.Who", b"")
            assert not c.failed, c.error_text
            seen.add(c.response)
        assert seen == {b"a", b"b"}

        # kill one server: calls keep succeeding via retry+exclusion
        s2.stop()
        ok = 0
        for _ in range(12):
            cntl = Controller()
            cntl.timeout_ms = 2000
            c = ch.call_method("W.Who", b"", cntl=cntl)
            if not c.failed:
                ok += 1
                assert c.response == b"a"
        assert ok >= 10
    finally:
        s1.stop()
        s2.stop()
        global_circuit_breaker_map().reset()
