"""SLO-tiered batch scheduler (ISSUE 17): chunked prefill, priority
preemption, speculative decoding.

Three planes:

- IDENTITY: every scheduler mode must emit the exact tokens of the
  monolithic greedy path — chunked prefill (contiguous + paged),
  partial prefix-hit catch-up, spec decode on BOTH the rejection and
  the acceptance path, and a batch-tier session across park/resume;
- POLICY: interactive sessions get chunk budget first, and under pool
  pressure the spill victim is tier-then-footprint — an interactive
  session is NEVER parked while a batch-tier victim exists;
- TELEMETRY: the closed ``SLO_SCHED_EVENTS`` / ``SPEC_DECODE_EVENTS``
  enums are pinned member-by-member (the static enum checker requires
  every name anchored here) and an unregistered event asserts loudly
  at the first count.
"""

import struct
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from brpc_tpu.models.lm_service import (ContinuousBatcher, TierRegistry,
                                        _Session, _reset_sched_for_tests,
                                        count_sched, count_spec,
                                        sched_counters, spec_counters)
from brpc_tpu.models.transformer_lm import (LMConfig, generate,
                                            init_params)
from brpc_tpu.streaming import StreamOptions

# ---------------------------------------------------------------------------
# Closed-event pins (tools/check/enums.py requires every member of the
# scheduler enums anchored under tests/ — this is the anchor)
# ---------------------------------------------------------------------------

SLO_SCHED_PINS = ("sched_chunk_slice", "sched_catchup_slice",
                  "sched_interactive_first", "sched_preempt_batch")
SPEC_DECODE_PINS = ("spec_round", "spec_accept", "spec_reject",
                    "spec_fallback_plain")


def test_sched_enums_match_pins():
    from brpc_tpu.models.lm_service import (SLO_SCHED_EVENTS,
                                            SPEC_DECODE_EVENTS)
    assert SLO_SCHED_EVENTS == SLO_SCHED_PINS
    assert SPEC_DECODE_EVENTS == SPEC_DECODE_PINS
    assert set(sched_counters()) == set(SLO_SCHED_PINS)
    assert set(spec_counters()) == set(SPEC_DECODE_PINS)
    with pytest.raises(AssertionError):
        count_sched("sched_some_new_event")
    with pytest.raises(AssertionError):
        count_spec("spec_some_new_event")


def test_tier_registry():
    reg = TierRegistry()
    assert reg.tier_of(b"nobody") == "standard"      # default tier
    reg.set_tier(b"alice", "interactive")
    reg.set_tier("bob", "batch")
    # keyed on the NORMALIZED TLV-22 identity: bytes and str agree
    assert reg.tier_of("alice") == "interactive"
    assert reg.tier_of(b"bob") == "batch"
    assert reg.rank_of(b"alice") < reg.rank_of(b"nobody") \
        < reg.rank_of("bob")
    with pytest.raises(ValueError, match="unknown SLO tier"):
        reg.set_tier(b"x", "platinum")
    with pytest.raises(ValueError, match="unknown SLO tier"):
        TierRegistry(default="gold")
    # bounded at the admission plane's tenant cardinality cap
    from brpc_tpu.server.admission import _MAX_TENANTS
    full = TierRegistry()
    for i in range(_MAX_TENANTS):
        full.set_tier(f"t{i}", "batch")
    with pytest.raises(ValueError, match="registry full"):
        full.set_tier("one-too-many", "batch")
    full.set_tier("t0", "interactive")               # updates still land


def test_join_resolves_tier_from_registry():
    reg = TierRegistry()
    reg.set_tier(b"alice", "interactive")
    cfg = LMConfig(vocab=64, dim=32, heads=4, depth=2, max_seq=32,
                   remat=False)
    bat = ContinuousBatcher(cfg, params=None, tiers=reg)
    sess = _Session(None, np.zeros((3,), np.int32), 4)
    assert sess.tier == "standard"                   # registry-less default
    bat._assign_tier(sess, b"alice")
    assert sess.tier == "interactive" and sess.tier_rank == 0
    bat._assign_tier(sess, b"unknown-tenant")
    assert sess.tier == "standard"


# ---------------------------------------------------------------------------
# harness (mirrors test_kv_disagg's direct-batcher idiom)
# ---------------------------------------------------------------------------

def _setup(seed=0, **kw):
    cfg = LMConfig(vocab=64, dim=32, heads=4, depth=2, max_seq=32,
                   remat=False, **kw)
    return cfg, init_params(jax.random.PRNGKey(seed), cfg)


def _reset():
    from brpc_tpu.kv import pages as kv_pages
    from brpc_tpu.kv import transport as kv_transport
    kv_pages._reset_for_tests()
    kv_transport._reset_for_tests()
    _reset_sched_for_tests()


class _FakeStream:
    """Batcher-facing stream stub on the Python write lane (the
    batcher only touches closed/options/write/close/id/_native_tx)."""

    def __init__(self):
        self.closed = False
        self.close_reason = None
        self.tokens = []
        self.id = 0
        self._native_tx = None
        self.options = StreamOptions()

    def write(self, data):
        self.tokens.append(struct.unpack("<i", bytes(data))[0])
        return 0

    def close(self, reason=None):
        self.closed = True
        self.close_reason = reason


def _join(bat, prompt, max_new, tenant=None):
    st = _FakeStream()
    bat.join(st, prompt, max_new, tenant=tenant)
    return st


def _finish(*streams, timeout=120.0):
    deadline = time.monotonic() + timeout
    while not all(s.closed for s in streams) \
            and time.monotonic() < deadline:
        time.sleep(0.002)
    assert all(s.closed for s in streams), "decode session never closed"


def _prompt(seed, n, vocab=64):
    return np.asarray(jax.random.randint(jax.random.PRNGKey(seed),
                                         (n,), 0, vocab, jnp.int32))


# ---------------------------------------------------------------------------
# chunked prefill: identity + budget priority
# ---------------------------------------------------------------------------

def test_chunked_prefill_identity_contiguous():
    """A chunk-filled session (ctx 16 in slices of 4) emits the exact
    tokens of whole-prompt prefill — the garbage-beyond-mask argument
    made checkable."""
    _reset()
    cfg, params = _setup()
    prompt = _prompt(3, 17)
    want = np.asarray(generate(params, cfg, prompt[None, :], 6))[0]
    bat = ContinuousBatcher(cfg, params, slots=2,
                            prefill_chunk_tokens=4)
    st = _join(bat, prompt, 6)
    _finish(st)
    assert st.tokens == want.tolist()
    assert st.close_reason == "finished"
    assert sched_counters()["sched_chunk_slice"] >= 4   # ceil(16/4)


def test_chunked_prefill_identity_paged():
    """Same pin on the paged engine: chunk slices scatter through the
    block table and the stream is bit-identical with the monolithic
    path; the chunk-filled context enters the prefix cache exactly
    like a prefilled one (second session full-hits it)."""
    from brpc_tpu.kv import pages as kv_pages
    _reset()
    cfg, params = _setup()
    prompt = _prompt(3, 17)
    want = np.asarray(generate(params, cfg, prompt[None, :], 6))[0]
    bat = ContinuousBatcher(cfg, params, slots=4, paged=True, page=16,
                            prefill_chunk_tokens=4)
    st = _join(bat, prompt, 6)
    _finish(st)
    assert st.tokens == want.tolist()
    assert st.close_reason == "finished"
    assert bat.prefills_run == 1
    assert sched_counters()["sched_chunk_slice"] >= 4
    st2 = _join(bat, prompt, 6)
    _finish(st2)
    assert st2.tokens == want.tolist()
    assert bat.prefills_run == 1                 # full prefix hit
    assert kv_pages.prefix_event_counters()["prefix_hit"] == 1


def test_interactive_gets_chunk_budget_first():
    """Two long prompts filling concurrently: the interactive join's
    slices outrank the standard one's for the per-round budget (the
    named decision is counted), and both streams stay exact."""
    _reset()
    cfg, params = _setup()
    reg = TierRegistry()
    reg.set_tier(b"alice", "interactive")
    pa, pb = _prompt(11, 29), _prompt(12, 29)
    want_a = np.asarray(generate(params, cfg, pa[None, :], 3))[0]
    want_b = np.asarray(generate(params, cfg, pb[None, :], 3))[0]
    bat = ContinuousBatcher(cfg, params, slots=2,
                            prefill_chunk_tokens=2, tiers=reg)
    # both joins land before the batcher's first admit round (the
    # engine compile on the batcher thread gates it), so both sessions
    # chunk-fill in the same rounds
    st_b = _join(bat, pb, 3, tenant=b"bob")
    st_a = _join(bat, pa, 3, tenant=b"alice")
    _finish(st_a, st_b)
    assert st_a.tokens == want_a.tolist()
    assert st_b.tokens == want_b.tolist()
    assert sched_counters()["sched_interactive_first"] >= 1


def test_partial_prefix_hit_catches_up_via_chunks():
    """Round-19 REMAINING thread closed: a context sharing only its
    first full page with the cache aliases that page and the remainder
    catches up through chunk slices (counted as catch-up, NOT as a
    prefill) — stream identical with the uncached path."""
    from brpc_tpu.kv import pages as kv_pages
    _reset()
    cfg = LMConfig(vocab=64, dim=32, heads=4, depth=2, max_seq=48,
                   remat=False)
    params = init_params(jax.random.PRNGKey(0), cfg)
    base = _prompt(6, 16)
    pa = np.concatenate([base, _prompt(7, 17)])   # two full pages cached
    pb = np.concatenate([base, _prompt(8, 17)])   # only page 1 matches
    want_b = np.asarray(generate(params, cfg, pb[None, :], 4))[0]
    bat = ContinuousBatcher(cfg, params, slots=2, paged=True, page=16)
    st_a = _join(bat, pa, 4)
    _finish(st_a)
    pf = bat.prefills_run
    st_b = _join(bat, pb, 4)
    _finish(st_b)
    assert st_b.tokens == want_b.tolist()
    assert st_b.close_reason == "finished"
    assert bat.prefills_run == pf                # the hit avoided one
    assert kv_pages.prefix_event_counters()["prefix_partial_hit"] == 1
    assert sched_counters()["sched_catchup_slice"] >= 1


# ---------------------------------------------------------------------------
# speculative decoding: bit-identity on both paths
# ---------------------------------------------------------------------------

def test_spec_decode_identity_rejection_path():
    """A DIFFERENT draft model (wrong by construction): rejections
    roll the page-table positions back and the emitted stream is
    bit-identical with plain greedy decode — the verify step is the
    ground truth regardless of draft quality."""
    _reset()
    cfg, params = _setup()
    draft = init_params(jax.random.PRNGKey(1), cfg)
    prompt = _prompt(4, 8)
    want = np.asarray(generate(params, cfg, prompt[None, :], 6))[0]
    bat = ContinuousBatcher(cfg, params, slots=2, paged=True, page=16,
                            spec_decode_k=3, draft_params=draft)
    st = _join(bat, prompt, 6)
    _finish(st)
    assert st.tokens == want.tolist()
    assert st.close_reason == "finished"
    sp = spec_counters()
    assert sp["spec_round"] >= 1
    assert sp["spec_reject"] >= 1


def test_spec_decode_acceptance_and_fallback():
    """The SAME weights as draft: some drafts verify (accepts > 0 —
    acceptance is not total even self-speculatively, the draft and
    verify programs are different einsum layouts and argmax ties
    split), the stream stays bit-identical, and once the k+1-row
    headroom runs out near max_seq the round falls back to a plain
    step under its named reason."""
    _reset()
    cfg, params = _setup()
    prompt = _prompt(4, 8)
    want = np.asarray(generate(params, cfg, prompt[None, :], 24))[0]
    bat = ContinuousBatcher(cfg, params, slots=2, paged=True, page=16,
                            spec_decode_k=3, draft_params=params)
    st = _join(bat, prompt, 24)
    _finish(st)
    assert st.tokens == want.tolist()
    assert st.close_reason == "finished"
    sp = spec_counters()
    assert sp["spec_round"] >= 1
    assert sp["spec_accept"] >= 1
    # a session with NO k+1-row headroom (ctx 29 + k + 1 > max_seq
    # from its first round): every round falls back to a plain step
    # under the named reason, stream still exact
    long = _prompt(5, 30)
    want2 = np.asarray(generate(params, cfg, long[None, :], 2))[0]
    st2 = _join(bat, long, 2)
    _finish(st2)
    assert st2.tokens == want2.tolist()
    assert st2.close_reason == "finished"
    assert spec_counters()["spec_fallback_plain"] >= 1


def test_spec_decode_constructor_contract():
    cfg, params = _setup()
    with pytest.raises(ValueError, match="paged"):
        ContinuousBatcher(cfg, params, spec_decode_k=3,
                          draft_params=params)
    with pytest.raises(ValueError, match="draft_params"):
        ContinuousBatcher(cfg, params, paged=True, spec_decode_k=3)


# ---------------------------------------------------------------------------
# tier-aware preemption: batch spills first, interactive never does
# ---------------------------------------------------------------------------

def test_interactive_never_spilled_while_batch_victim_exists(monkeypatch):
    """Pool pressure from an interactive join: the spill victim is the
    BATCH session (tier-then-footprint), never the interactive one —
    every _park call in the run is spied on — and the preempted batch
    session resumes bit-exact."""
    _reset()
    cfg, params = _setup()
    reg = TierRegistry()
    reg.set_tier(b"alice", "interactive")
    reg.set_tier(b"bob", "batch")
    parked_tiers = []
    orig_park = ContinuousBatcher._park

    def spy(self, sess):
        parked_tiers.append(sess.tier)
        return orig_park(self, sess)

    monkeypatch.setattr(ContinuousBatcher, "_park", spy)
    prompt = _prompt(9, 14)
    want_bob = np.asarray(generate(params, cfg, prompt[None, :], 16))[0]
    want_alice = np.asarray(generate(params, cfg, prompt[None, :], 8))[0]
    # 10 usable pages of 4: bob (ctx 13 + 16 new -> 8 pages) fits
    # alone; alice (6 pages) only if bob spills
    bat = ContinuousBatcher(cfg, params, slots=3, paged=True, page=4,
                            pages=11, host_slots=32, prefix=False,
                            tiers=reg)
    st_bob = _join(bat, prompt, 16, tenant=b"bob")
    deadline = time.monotonic() + 120
    while not st_bob.tokens and time.monotonic() < deadline:
        time.sleep(0.002)                # bob live before alice asks
    assert st_bob.tokens, "batch session never started"
    st_alice = _join(bat, prompt, 8, tenant=b"alice")
    _finish(st_alice, st_bob)
    assert st_alice.tokens == want_alice.tolist()
    assert st_bob.tokens == want_bob.tolist()    # park/resume bit-exact
    assert st_alice.close_reason == st_bob.close_reason == "finished"
    assert bat.spills >= 1 and bat.resumes >= 1
    assert parked_tiers and set(parked_tiers) == {"batch"}, parked_tiers
    assert sched_counters()["sched_preempt_batch"] >= 1
