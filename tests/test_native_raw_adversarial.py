"""Adversarial wire tests for the native single raw call (raw_call).

Same discipline as test_native_batch_adversarial: a scripted peer over
a socketpair drives engine.cpp raw_call/read_one_response through its
framing, TICI-drain, fallback, and failure paths byte by byte."""

import socket
import struct
import threading

import pytest

from conftest import (WIRE_TAIL, load_native_or_skip, wire_resp_frame,
                      wire_tlv)


def _native():
    return load_native_or_skip("raw_call")


_tlv = wire_tlv


_resp = wire_resp_frame
TAIL = WIRE_TAIL
CID = 42


def _run(nat, responder, payload=b"pay", attachment=None,
         timeout_ms=5000, lead=None):
    a, b = socket.socketpair()
    a.setblocking(False)
    seen = {}

    def peer():
        b.settimeout(10)
        buf = b""
        try:
            # one whole request frame (and any lead bytes before it)
            while True:
                off = buf.find(b"TRPC")
                if off >= 0 and len(buf) >= off + 12:
                    (body,) = struct.unpack_from("<I", buf, off + 4)
                    if len(buf) >= off + 12 + body:
                        break
                c = b.recv(65536)
                if not c:
                    break
                buf += c
        except socket.timeout:
            pass
        seen["req"] = buf
        reply = responder(buf)
        if reply:
            b.sendall(reply)

    t = threading.Thread(target=peer)
    t.start()
    try:
        return nat.raw_call(a.fileno(), TAIL, payload, attachment,
                            timeout_ms, CID, lead), seen
    finally:
        t.join(15)
        a.close()
        b.close()


def test_plain_success_payload_only():
    nat = _native()
    (ok, buf, n, dom, acks), _ = _run(nat, lambda req: _resp(CID, b"hi"))
    assert ok is True and bytes(buf) == b"hi" and n == 0
    assert dom is None and acks is None


def test_attachment_request_and_response():
    nat = _native()
    att_meta = _tlv(3, struct.pack("<I", 3))
    (ok, buf, n, dom, acks), seen = _run(
        nat, lambda req: _resp(CID, b"bodyXYZ", extra_meta=att_meta),
        attachment=b"reqatt")
    assert ok is True and n == 3
    assert bytes(buf) == b"bodyXYZ"          # payload+att fused; n splits
    # the REQUEST carried an attachment TLV of the right size
    req = seen["req"]
    off = req.find(b"TRPC")
    (body, msize) = struct.unpack_from("<II", req, off + 4)
    meta = req[off + 12:off + 12 + msize]
    assert meta[13] == 3                     # att TLV follows the cid TLV
    (asz,) = struct.unpack_from("<I", meta, 18)
    assert asz == 6
    assert req.endswith(b"reqatt")


def test_peer_domain_learned():
    nat = _native()
    dom_meta = _tlv(15, b"domtoken@addr:1")
    (ok, buf, n, dom, acks), _ = _run(
        nat, lambda req: _resp(CID, b"p", extra_meta=dom_meta))
    assert ok is True and bytes(dom) == b"domtoken@addr:1"
    assert bytes(buf) == b"p"


def test_error_response_falls_back_whole():
    nat = _native()
    err = _tlv(6, struct.pack("<i", 1003)) + _tlv(7, b"bad")
    (ok, buf, msize, dom, acks), _ = _run(
        nat, lambda req: _resp(CID, b"", extra_meta=err))
    assert ok is False
    from brpc_tpu.protocol.meta import RpcMeta
    meta = RpcMeta.decode(bytes(memoryview(buf)[:msize]))
    assert meta.error_code == 1003 and meta.error_text == "bad"


def test_cid_mismatch_falls_back_whole():
    nat = _native()
    (ok, buf, msize, dom, acks), _ = _run(nat, lambda req: _resp(CID + 9))
    assert ok is False        # Python's RpcMeta path decides what to do


def test_tici_around_response_collected():
    nat = _native()
    tici = b"TICI" + struct.pack("<I", 1) + struct.pack("<Q", 77)
    (ok, buf, n, dom, acks), _ = _run(
        nat, lambda req: tici + _resp(CID, b"x")
        + b"TICI" + struct.pack("<I", 1) + struct.pack("<Q", 88))
    assert ok is True and bytes(buf) == b"x"
    assert sorted(acks) == [77, 88]


def test_lead_bytes_written_first():
    nat = _native()
    lead = b"TICI" + struct.pack("<I", 1) + struct.pack("<Q", 5)
    (ok, _, _, _, _), seen = _run(nat, lambda req: _resp(CID),
                                  lead=lead)
    assert ok is True
    assert seen["req"].startswith(lead)


def test_silent_peer_times_out():
    nat = _native()
    with pytest.raises(TimeoutError):
        _run(nat, lambda req: b"", timeout_ms=300)


def test_garbage_reply_rejected():
    nat = _native()
    with pytest.raises(ValueError):
        _run(nat, lambda req: b"NOTAFRAMEATALL!!" * 8)


def test_request_frame_layout():
    """The frame raw_call writes must carry cid TLV first, then the
    tail, then the deadline TLV, with header sizes consistent."""
    nat = _native()
    (ok, *_), seen = _run(nat, lambda req: _resp(CID),
                          payload=b"PP", timeout_ms=1234)
    req = seen["req"]
    off = req.find(b"TRPC")
    assert off == 0
    body, msize = struct.unpack_from("<II", req, 4)
    assert len(req) == 12 + body
    meta = req[12:12 + msize]
    assert meta[0] == 1
    (cid,) = struct.unpack_from("<Q", meta, 5)
    assert cid == CID
    assert meta[13:13 + len(TAIL)] == TAIL       # tail right after cid
    tmo = meta[13 + len(TAIL):]
    assert tmo[0] == 13
    (ms,) = struct.unpack_from("<I", tmo, 5)
    assert ms == 1234
    assert req[12 + msize:12 + body] == b"PP"
