"""Direct unit tests for the concurrency limiters
(policy/concurrency_limiter.py) — until now only exercised incidentally
through test_cluster_hardening's end-to-end paths: AutoLimiter
convergence on a synthetic latency curve, shrink on latency blow-up,
recovery after load drops, and make_limiter spec parsing (including
errors)."""

import time

import pytest

from brpc_tpu.policy.concurrency_limiter import (AutoLimiter,
                                                 ConstantLimiter,
                                                 TimeoutLimiter,
                                                 make_limiter)


def _feed(lim, n, latency_us, error=0, window_sleep=0.012, batches=1):
    """Feed ``batches`` full sampling windows of n samples each at a
    fixed latency — real wall-clock windows (the limiter reads
    time.monotonic), kept short via a tightened sample_window_s."""
    for _ in range(batches):
        for _ in range(n):
            lim.on_responded(error, latency_us)
        time.sleep(window_sleep)
        # one closing sample tips the window evaluation past window_s
        lim.on_responded(error, latency_us)


def _auto(**kw):
    kw.setdefault("sample_window_s", 0.01)
    kw.setdefault("min_sample_count", 10)
    return AutoLimiter(**kw)


def test_auto_limiter_converges_on_synthetic_curve():
    """Steady 5ms latency at ~2K qps: limit converges near
    peak_qps x no-load-latency x (1 + alpha) = ~13, far below
    max_limit — and never collapses to min_limit."""
    lim = _auto(min_limit=2, max_limit=4096)
    _feed(lim, 25, 5_000, batches=12)
    limit = lim.max_concurrency()
    assert 2 <= limit <= 64, limit        # converged, not railed
    assert lim._nolat_ema is not None
    assert 4_000 <= lim._nolat_ema <= 6_500


def test_auto_limiter_shrinks_on_latency_blowup():
    """Latency blows up 20x: overloaded windows must not launder
    queueing delay into the no-load estimate, so the limit ratchets
    DOWN (shrink branch + peak-qps decay) instead of tracking
    qps x inflated-latency upward."""
    lim = _auto(min_limit=2, max_limit=4096)
    _feed(lim, 50, 2_000, batches=10)
    before = lim.max_concurrency()
    nolat_before = lim._nolat_ema
    # overload: latency 20x AND throughput halved (the closed-loop
    # shape a limited server actually produces)
    _feed(lim, 25, 40_000, batches=12)
    after = lim.max_concurrency()
    assert after < before, (before, after)
    # the no-load estimate held its ground through the overload (only
    # the 20x-slower re-measurement path may move it, not the 2% drift)
    assert lim._nolat_ema == pytest.approx(nolat_before, rel=0.5)


def test_auto_limiter_recovers_after_load_drops():
    """Overload ends (latency back to baseline, throughput restored):
    the limit grows back above its depressed value."""
    lim = _auto(min_limit=2, max_limit=4096)
    _feed(lim, 25, 2_000, batches=8)
    _feed(lim, 25, 40_000, batches=8)
    depressed = lim.max_concurrency()
    # recovery: baseline latency at HIGHER throughput (the drained
    # server serves what overload was queueing)
    _feed(lim, 60, 2_000, batches=10)
    assert lim.max_concurrency() > depressed


def test_auto_limiter_errors_not_counted_as_latency():
    """Errored responses count toward window size but never toward the
    latency average (a burst of instant failures must not drag the
    no-load estimate to ~0)."""
    lim = _auto()
    _feed(lim, 25, 5_000, batches=4)
    ema_before = lim._nolat_ema
    _feed(lim, 25, 0, error=2001, batches=4)
    assert lim._nolat_ema == ema_before


def test_timeout_limiter_respects_bounds():
    lim = TimeoutLimiter(timeout_ms=100, min_limit=3, max_limit=7)
    for _ in range(50):
        lim.on_responded(0, 1_000)       # 1ms -> budget fits 100
    assert lim.max_concurrency() == 7    # clamped to max
    for _ in range(100):
        lim.on_responded(0, 500_000)     # 500ms >> budget
    assert lim.max_concurrency() == 3    # clamped to min


def test_make_limiter_specs():
    assert make_limiter(None) is None
    assert make_limiter("unlimited") is None
    assert make_limiter("") is None
    assert make_limiter(0) is None
    assert make_limiter("0") is None
    c = make_limiter(10)
    assert isinstance(c, ConstantLimiter) and c.max_concurrency() == 10
    c = make_limiter("constant:25")
    assert isinstance(c, ConstantLimiter) and c.max_concurrency() == 25
    c = make_limiter("25")
    assert isinstance(c, ConstantLimiter) and c.max_concurrency() == 25
    assert isinstance(make_limiter("auto"), AutoLimiter)
    assert isinstance(make_limiter("AUTO"), AutoLimiter)   # case-folded
    t = make_limiter("timeout:250")
    assert isinstance(t, TimeoutLimiter) and t._timeout_us == 250_000


def test_make_limiter_kind_labels():
    assert make_limiter("auto").kind == "auto"
    assert make_limiter("timeout").kind == "timeout"
    assert make_limiter("constant:5").kind == "constant"


def test_make_limiter_spec_errors():
    with pytest.raises(ValueError):
        make_limiter("bogus")
    with pytest.raises(ValueError):
        make_limiter("timeout:abc")
    with pytest.raises(ValueError):
        make_limiter("constant:xyz")
    with pytest.raises(ValueError):
        make_limiter("auto:3")           # auto takes no argument
