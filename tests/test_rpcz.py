"""rpcz span tests: collection on the server path, trace propagation from
client meta, annotations, the /rpcz page, and the enable flag."""

import http.client
import json

import pytest

from brpc_tpu.butil import flags as flags_mod
from brpc_tpu.client import Channel, Controller
from brpc_tpu.rpcz import global_span_store
from brpc_tpu.server import Server, Service


class Traced(Service):
    def Work(self, cntl, request):
        cntl.annotate("step-one")
        cntl.annotate("step-two")
        return b"done"


@pytest.fixture()
def server():
    global_span_store().clear()
    srv = Server()
    srv.add_service(Traced())
    assert srv.start("127.0.0.1:0") == 0
    yield srv
    srv.stop()
    global_span_store().clear()


def test_span_collected_with_annotations(server):
    ch = Channel()
    ch.init(str(server.listen_endpoint))
    cntl = Controller()
    cntl.trace_id = 0xABCDEF
    c = ch.call_method("Traced.Work", b"payload", cntl=cntl)
    assert not c.failed
    spans = global_span_store().by_trace(0xABCDEF)
    assert len(spans) == 1
    s = spans[0]
    assert s.full_method == "Traced.Work"
    assert s.request_size == len(b"payload")
    assert s.latency_us > 0
    assert [t for _, t in s.annotations] == ["step-one", "step-two"]


def test_rpcz_page(server):
    ch = Channel()
    ch.init(str(server.listen_endpoint))
    ch.call("Traced.Work", b"x")
    ep = server.listen_endpoint
    conn = http.client.HTTPConnection(ep.host, ep.port, timeout=5)
    conn.request("GET", "/rpcz")
    r = conn.getresponse()
    assert r.status == 200
    data = json.loads(r.read())
    assert data["enabled"] is True
    assert any(s["method"] == "Traced.Work" for s in data["spans"])
    conn.close()


def test_rpcz_disable_flag(server):
    assert flags_mod.set_flag("enable_rpcz", "false")
    try:
        global_span_store().clear()
        ch = Channel()
        ch.init(str(server.listen_endpoint))
        ch.call("Traced.Work", b"x")
        assert global_span_store().recent() == []
    finally:
        flags_mod.set_flag("enable_rpcz", "true")
