"""rpcz span tests: collection on the server path, trace propagation from
client meta, annotations, the /rpcz page, and the enable flag."""

import http.client
import json

import pytest

from brpc_tpu.butil import flags as flags_mod
from brpc_tpu.client import Channel, Controller
from brpc_tpu.rpcz import global_span_store
from brpc_tpu.server import Server, Service


class Traced(Service):
    def Work(self, cntl, request):
        cntl.annotate("step-one")
        cntl.annotate("step-two")
        return b"done"


@pytest.fixture()
def server():
    global_span_store().clear()
    srv = Server()
    srv.add_service(Traced())
    assert srv.start("127.0.0.1:0") == 0
    yield srv
    srv.stop()
    global_span_store().clear()


def test_span_collected_with_annotations(server):
    ch = Channel()
    ch.init(str(server.listen_endpoint))
    cntl = Controller()
    cntl.trace_id = 0xABCDEF
    c = ch.call_method("Traced.Work", b"payload", cntl=cntl)
    assert not c.failed
    spans = global_span_store().by_trace(0xABCDEF)
    # an explicitly traced call records BOTH halves: the client span
    # (this process is the caller) and the server span it parents
    assert len(spans) == 2
    server_spans = [s for s in spans if s.is_server]
    client_spans = [s for s in spans if not s.is_server]
    assert len(server_spans) == 1 and len(client_spans) == 1
    s = server_spans[0]
    assert s.full_method == "Traced.Work"
    assert s.request_size == len(b"payload")
    assert s.latency_us > 0
    assert [t for _, t in s.annotations] == ["step-one", "step-two"]
    # linkage: the server span's parent is the client span's id
    cs = client_spans[0]
    assert cs.full_method == "Traced.Work"
    assert s.parent_span_id == cs.span_id
    assert str(server.listen_endpoint) == cs.remote_side


def test_rpcz_page(server):
    ch = Channel()
    ch.init(str(server.listen_endpoint))
    ch.call("Traced.Work", b"x")
    ep = server.listen_endpoint
    conn = http.client.HTTPConnection(ep.host, ep.port, timeout=5)
    conn.request("GET", "/rpcz")
    r = conn.getresponse()
    assert r.status == 200
    data = json.loads(r.read())
    assert data["enabled"] is True
    assert any(s["method"] == "Traced.Work" for s in data["spans"])
    conn.close()


def test_rpcz_disable_flag(server):
    assert flags_mod.set_flag("enable_rpcz", "false")
    try:
        global_span_store().clear()
        ch = Channel()
        ch.init(str(server.listen_endpoint))
        ch.call("Traced.Work", b"x")
        assert global_span_store().recent() == []
    finally:
        flags_mod.set_flag("enable_rpcz", "true")


def test_slim_lane_span_backdated_to_engine_receive():
    """Regression (observability PR): slim-lane spans used to start at
    shim entry, undercounting native read/parse/batch queueing.  The
    engine now passes its CLOCK_MONOTONIC frame-parse timestamp into
    the shim and the span's received_us is backdated to it — so
    received_us <= start_us (shim entry) and the span latency is >= the
    shim-measured (start-based) latency, never under it."""
    import socket as pysock

    from conftest import require_native
    from brpc_tpu.server import ServerOptions

    require_native()
    global_span_store().clear()
    opts = ServerOptions()
    opts.native = True
    opts.usercode_inline = True
    opts.native_loops = 1
    srv = Server(opts)
    srv.add_service(Traced())
    assert srv.start("127.0.0.1:0") == 0
    try:
        ep = srv.listen_endpoint
        # a pipelined burst in ONE write: later items of the batch wait
        # behind earlier handlers, so real engine-side queueing exists
        burst = b"".join(
            b"POST /Traced/Work HTTP/1.1\r\nHost: x\r\n"
            b"Content-Length: 2\r\n\r\nhi" for _ in range(16))
        with pysock.create_connection((str(ep.host), ep.port),
                                      timeout=10) as c:
            c.sendall(burst)
            c.settimeout(10)
            buf = b""
            while buf.count(b"done") < 16:
                part = c.recv(65536)
                assert part, buf[:200]
                buf += part
        spans = [s for s in global_span_store().recent(2048)
                 if s.full_method == "Traced.Work" and s.is_server]
        assert spans, "no slim-lane server spans recorded"
        for s in spans:
            assert s.received_us <= s.start_us
            shim_measured = s.end_us - s.start_us
            assert s.latency_us >= shim_measured
        # across a 16-deep pipelined burst at least one span saw
        # non-zero native queueing before shim entry
        assert any(s.start_us - s.received_us > 0 for s in spans), \
            [(s.start_us - s.received_us) for s in spans]
    finally:
        srv.stop()
        global_span_store().clear()
