"""Sanitizer stress driver — runs inside a subprocess with the ASAN/
UBSAN-instrumented engine (``BRPC_TPU_NATIVE_ASAN=1`` + libasan
LD_PRELOADed; see tests/test_sanitized_native.py, which owns the build
and the report scraping).

Drives every native memory-discipline surface the sanitizers can see:

1. **burst dispatch** — pipelined tpu_std frames blasted down one raw
   socket so the engine batches them into multi-item flush_py_batch
   bursts (kind-3 slim shims, native response coalescing, writev);
2. **HTTP slim bursts** — pipelined keep-alive HTTP/1.1 on the same
   port (kind-4 parse + native serialization), plus ineligible shapes
   (bad header framing) for the fallback paths;
3. **client demux** — a lane-attached "single" connection completing
   plain successes natively, interleaved with error responses and
   attachments that fall back byte-identically;
4. **scatter** — ParallelChannel fan-out over native sub-servers
   (thread-pinned scatter_call path);
5. **shm slot lifecycle** — ≥256KB same-host attachments cycling ring
   slots (describe → echo re-describe → finalizer settle → sweep),
   skipped where the sandbox has no mmap-able shm;
6. **multi-core engine** — a 4-loop server (SO_REUSEPORT sharded
   accept where available) driven CONCURRENTLY by pipelined slim
   bursts on per-loop connections, ParallelChannel scatter fan-out and
   shm slot cycles, so the lock-free cross-loop handoff, the sharded
   slot allocator and the per-loop telemetry all run under ASan/UBSan
   with real thread interleaving;
7. **drain under load** (ISSUE 12) — a fresh native server drained
   MID-BURST: set_lame_duck flips while pipelined slim frames are in
   flight (listener epoll disarm, lame-duck TLV append on natively
   built responses, kind-4 declines), then stop() tears the engine
   down with the late rejections still settling.

Prints ``ASAN_DRIVER_OK`` and exits 0 on success; any sanitizer report
goes to stderr and (for UBSAN, built no-recover) aborts the process.
"""

import struct
import sys
import threading
import time


def wire_tlv(tag, data):
    return bytes([tag]) + struct.pack("<I", len(data)) + data


def frame(cid, payload, svc=b"A", mth=b"Echo"):
    meta = (wire_tlv(1, struct.pack("<Q", cid)) + wire_tlv(4, svc)
            + wire_tlv(5, mth))
    return (b"TRPC" + struct.pack("<II", len(meta) + len(payload),
                                  len(meta)) + meta + payload)


def main():
    from brpc_tpu.butil.iobuf import IOBuf
    from brpc_tpu.client import (Channel, ChannelOptions, Controller,
                                 ParallelChannel)
    from brpc_tpu.server import Server, ServerOptions, Service
    from brpc_tpu.native import available

    assert available(), "sanitized native engine failed to build/load"

    class Svc(Service):
        def Echo(self, cntl, request):
            cntl.response_attachment.append_iobuf(
                cntl.request_attachment)
            return request

        def Err(self, cntl, request):
            cntl.set_failed(1234, "boom")
            return b""

    def mk_server():
        opts = ServerOptions()
        opts.native = True
        opts.usercode_inline = True
        srv = Server(opts)
        srv.add_service(Svc(), name="A")
        assert srv.start("127.0.0.1:0") == 0
        return srv

    servers = [mk_server() for _ in range(3)]
    srv = servers[0]
    port = srv.listen_endpoint.port

    # ---- 1. pipelined burst dispatch (kind-3 slim lane) ----
    import socket as pysock
    for _round in range(4):
        s = pysock.create_connection(("127.0.0.1", port), timeout=10)
        blast = b"".join(frame(i + 1, b"x" * (17 * (i % 53)))
                         for i in range(200))
        s.sendall(blast)
        got = bytearray()
        want = 200
        seen = 0
        while seen < want:
            chunk = s.recv(65536)
            if not chunk:
                break
            got += chunk
            # count complete response frames
            seen = 0
            off = 0
            while off + 12 <= len(got):
                if got[off:off + 4] != b"TRPC":
                    raise AssertionError("bad magic in response burst")
                (blen,) = struct.unpack_from("<I", got, off + 4)
                if off + 12 + blen > len(got):
                    break
                off += 12 + blen
                seen += 1
        assert seen == want, f"burst round: {seen}/{want} responses"
        s.close()

    # ---- 2. pipelined HTTP slim bursts + ineligible shapes ----
    s = pysock.create_connection(("127.0.0.1", port), timeout=10)
    req = (b"POST /A/Echo HTTP/1.1\r\nHost: x\r\nContent-Length: 3\r\n"
           b"Connection: keep-alive\r\n\r\nabc")
    s.sendall(req * 64)
    deadline = time.time() + 10
    body = bytearray()
    while body.count(b"HTTP/1.1 200") < 64 and time.time() < deadline:
        chunk = s.recv(65536)
        if not chunk:
            break
        body += chunk
    assert body.count(b"HTTP/1.1 200") == 64, "http slim burst"
    s.close()
    # ineligible: LF-only header endings fall back to the classic lane
    # (the answer — or a parse-reject close, or silence — is the
    # classic path's business; the probe only drives the fallback scan)
    s = pysock.create_connection(("127.0.0.1", port), timeout=2)
    s.sendall(b"POST /A/Echo HTTP/1.1\nHost: x\nContent-Length: 1\n\nz")
    try:
        s.recv(65536)
    except OSError:
        pass
    s.close()

    # ---- 3. client demux lane: plain successes + fallback shapes ----
    co = ChannelOptions()
    co.connection_type = "single"
    co.timeout_ms = 10_000
    ch = Channel(co)
    ch.init(f"127.0.0.1:{port}")
    done_evt = threading.Event()
    pending = [0]
    lock = threading.Lock()

    def done(cntl):
        with lock:
            pending[0] -= 1
            if pending[0] == 0:
                done_evt.set()

    for i in range(300):
        cntl = Controller()
        cntl.timeout_ms = 10_000
        if i % 7 == 0:
            cntl.request_attachment = IOBuf(b"a" * 1000)
        with lock:
            pending[0] += 1
        ch.call_method("A.Err" if i % 11 == 0 else "A.Echo",
                       b"p" * (i % 97), cntl=cntl, done=done)
    assert done_evt.wait(30), "async demux burst did not drain"

    # ---- 4. ParallelChannel scatter over native sub-servers ----
    pc = ParallelChannel()
    for sub in servers:
        c2 = ChannelOptions()
        c2.timeout_ms = 10_000
        sch = Channel(c2)
        sch.init(f"127.0.0.1:{sub.listen_endpoint.port}")
        pc.add_channel(sch)
    for i in range(50):
        cntl = Controller()
        cntl.timeout_ms = 10_000
        r = pc.call_method("A.Echo", b"scatter", cntl=cntl)
        assert not r.failed, (r.error_code, r.error_text)

    # ---- 5. shm slot lifecycle (≥256KB same-host attachments) ----
    from brpc_tpu.transport import shm_ring
    if shm_ring.shm_supported():
        big = bytes(300 * 1024)
        co2 = ChannelOptions()
        co2.connection_type = "pooled"
        co2.timeout_ms = 10_000
        ch2 = Channel(co2)
        ch2.init(f"127.0.0.1:{port}")
        for i in range(40):
            cntl = Controller()
            cntl.timeout_ms = 10_000
            cntl.request_attachment = IOBuf(big)
            r = ch2.call_method("A.Echo", b"shm", cntl=cntl)
            assert not r.failed, (r.error_code, r.error_text)
            att = r.response_attachment.to_bytes()
            assert att == big, "shm echo corrupted"
            del r, att, cntl       # drop views: slot credits settle
    else:
        print("shm unsupported in sandbox; lane skipped",
              file=sys.stderr)

    # ---- 6. 4-loop engine: slim bursts + scatter + shm, concurrently ----
    opts4 = ServerOptions()
    opts4.native = True
    opts4.usercode_inline = True
    opts4.native_loops = 4
    srv4 = Server(opts4)
    srv4.add_service(Svc(), name="A")
    assert srv4.start("127.0.0.1:0") == 0
    port4 = srv4.listen_endpoint.port
    errors = []

    def _pipelined_conn(rounds):
        try:
            for _ in range(rounds):
                s = pysock.create_connection(("127.0.0.1", port4),
                                             timeout=10)
                blast = b"".join(frame(i + 1, b"q" * (13 * (i % 31)))
                                 for i in range(120))
                s.sendall(blast)
                got = bytearray()
                seen = 0
                while seen < 120:
                    chunk = s.recv(65536)
                    if not chunk:
                        raise AssertionError("peer closed mid-burst")
                    got += chunk
                    seen = 0
                    off = 0
                    while off + 12 <= len(got):
                        (blen,) = struct.unpack_from("<I", got, off + 4)
                        if off + 12 + blen > len(got):
                            break
                        off += 12 + blen
                        seen += 1
                s.close()
        except Exception as e:          # surfaced after join
            errors.append(f"pipelined: {type(e).__name__}: {e}")

    def _scatter4(rounds):
        try:
            pc4 = ParallelChannel()
            for sub in servers:
                c3 = ChannelOptions()
                c3.timeout_ms = 10_000
                sch = Channel(c3)
                sch.init(f"127.0.0.1:{sub.listen_endpoint.port}")
                pc4.add_channel(sch)
            for _ in range(rounds):
                cntl = Controller()
                cntl.timeout_ms = 10_000
                r = pc4.call_method("A.Echo", b"mc-scatter", cntl=cntl)
                assert not r.failed, (r.error_code, r.error_text)
        except Exception as e:
            errors.append(f"scatter: {type(e).__name__}: {e}")

    def _shm4(rounds):
        try:
            from brpc_tpu.transport import shm_ring as _shm
            if not _shm.shm_supported():
                return
            data = bytes(280 * 1024)
            c5 = ChannelOptions()
            c5.connection_type = "pooled"
            c5.timeout_ms = 10_000
            ch5 = Channel(c5)
            ch5.init(f"127.0.0.1:{port4}")
            for _ in range(rounds):
                cntl = Controller()
                cntl.timeout_ms = 10_000
                cntl.request_attachment = IOBuf(data)
                r = ch5.call_method("A.Echo", b"shm4", cntl=cntl)
                assert not r.failed, (r.error_code, r.error_text)
                assert r.response_attachment.to_bytes() == data
                del r, cntl
        except Exception as e:
            errors.append(f"shm4: {type(e).__name__}: {e}")

    workers = ([threading.Thread(target=_pipelined_conn, args=(3,))
                for _ in range(4)]
               + [threading.Thread(target=_scatter4, args=(15,)),
                  threading.Thread(target=_shm4, args=(12,))])
    for t in workers:
        t.start()
    for t in workers:
        t.join(timeout=120)
    assert not errors, errors
    tel = srv4._native_bridge.engine.telemetry()
    assert sum(lo["frames"] for lo in tel["loops"]) > 0
    srv4.stop()

    # ---- 7. drain under load (graceful lame-duck mid-burst) ----
    optsd = ServerOptions()
    optsd.native = True
    optsd.usercode_inline = True
    optsd.native_loops = 2
    srvd = Server(optsd)
    srvd.add_service(Svc(), name="A")
    assert srvd.start("127.0.0.1:0") == 0
    portd = srvd.listen_endpoint.port
    conns = [pysock.create_connection(("127.0.0.1", portd), timeout=10)
             for _ in range(3)]
    stop_blast = threading.Event()
    derrors = []

    def _blaster(s):
        # keep pipelined frames flowing while the drain flips the
        # engine into lame-duck: pre-drain frames answer 0, post-drain
        # ones answer ELAMEDUCK with the native duck TLV appended —
        # both shapes must be sanitizer-clean
        i = 0
        try:
            s.settimeout(5)
            while not stop_blast.is_set():
                i += 1
                s.sendall(frame(i, b"d" * (11 * (i % 23))))
                try:
                    s.recv(65536)
                except OSError:
                    return
        except OSError:
            pass
        except Exception as e:
            derrors.append(f"drain blaster: {type(e).__name__}: {e}")

    blasters = [threading.Thread(target=_blaster, args=(c,))
                for c in conns]
    for t in blasters:
        t.start()
    time.sleep(0.3)
    rc = srvd.drain(grace_ms=2000)
    assert rc == 0, f"drain under load rc={rc}"
    stop_blast.set()
    for t in blasters:
        t.join(timeout=10)
    for c in conns:
        c.close()
    assert not derrors, derrors
    srvd.stop()
    srvd.join(timeout=5)

    # ---- 8. kind-5 streaming lane: pipelined streams + session churn ----
    # A 2-loop engine serving streaming echo: concurrent sessions open
    # (kind-5 stream-open shim + native registration), pump chunks both
    # ways (burst-batched delivery, C++ credit accounting, coalesced
    # writes), then close and CHURN — the register/unregister/
    # conn-destroy sweep paths all run under ASan/UBSan with real
    # thread interleaving.
    from brpc_tpu.streaming import StreamOptions, stream_accept, \
        stream_create

    class StreamSvc(Service):
        def Start(self, cntl, request):
            def on_received(stream, msgs):
                for m in msgs:
                    stream.write(bytes(m)[::-1])
            s = stream_accept(cntl,
                              StreamOptions(on_received=on_received))
            assert s is not None
            return b"ok"

    optss = ServerOptions()
    optss.native = True
    optss.usercode_inline = True
    optss.native_loops = 2
    srvs = Server(optss)
    srvs.add_service(StreamSvc(), name="ST")
    assert srvs.start("127.0.0.1:0") == 0
    serrors = []

    def _stream_churn(rounds):
        try:
            chs = Channel()
            chs.init(f"127.0.0.1:{srvs.listen_endpoint.port}")
            for r in range(rounds):
                got = []
                cntl = Controller()
                cntl.timeout_ms = 10_000
                stream = stream_create(cntl, StreamOptions(
                    on_received=lambda st, msgs: got.extend(msgs)))
                c = chs.call_method("ST.Start", b"", cntl=cntl)
                assert not c.failed, (c.error_code, c.error_text)
                assert stream.wait_established(10)
                n = 24
                for i in range(n):
                    assert stream.write(b"chunk-%03d" % i) == 0
                deadline = time.time() + 20
                while len(got) < n and time.time() < deadline:
                    time.sleep(0.005)
                assert len(got) == n, f"stream churn {len(got)}/{n}"
                stream.close()
        except Exception as e:
            serrors.append(f"stream churn: {type(e).__name__}: {e}")

    churners = [threading.Thread(target=_stream_churn, args=(4,))
                for _ in range(3)]
    for t in churners:
        t.start()
    for t in churners:
        t.join(timeout=120)
    assert not serrors, serrors
    tels = srvs._native_bridge.engine.telemetry()
    assert tels["streams"]["chunks_in"] > 0
    assert tels["streams"]["chunks_out"] > 0
    # close delivery is async (F_CLOSE rides the deliver queue):
    # bounded wait for the last unregister before asserting clean
    deadline = time.time() + 10
    while time.time() < deadline:
        tels = srvs._native_bridge.engine.telemetry()
        if tels["streams"]["open"] == 0:
            break
        time.sleep(0.05)
    assert tels["streams"]["open"] == 0      # churned clean
    srvs.stop()

    for sub in servers:
        sub.stop()
    print("ASAN_DRIVER_OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
