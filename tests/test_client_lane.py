"""Client completion lane (ISSUE 8) — adversarial wire/state comparison.

Pins three contracts:

1. **Native demux vs Python demux are observably identical**: the same
   call matrix (success, errors, attachments, deadlines, traces,
   tenants, retries/backups) runs with the lane force-enabled and
   force-disabled (``rpc_native_client_lane``), and every Controller
   observable — error codes/texts, responses, attachments, span pairs,
   breaker feed — must match.
2. **The eligible matrix stays native**: trace-on, deadline-on and
   tenant-stamped traffic completes through the lane with ZERO new
   fallbacks; every ineligible shape lands in exactly its NAMED
   fallback reason (closed enum — no "unknown" bucket).
3. **Pooled reuse leaks nothing**: client Controllers and the slim
   lane's pooled ServerControllers come back from their free lists with
   every observable field reset.
"""

import socket as pysock
import struct
import threading
import time

import pytest

from conftest import require_native  # noqa: E402

from brpc_tpu.butil.flags import set_flag
from brpc_tpu.butil.iobuf import IOBuf
from brpc_tpu.butil.status import Errno
from brpc_tpu.client import Channel, ChannelOptions, Controller
from brpc_tpu.transport.client_lane import (REASONS,
                                            client_lane_telemetry,
                                            global_client_lane)


def _lane_counts():
    t = client_lane_telemetry()
    fb = t.get("fallbacks", {}) or {r: 0 for r in REASONS}
    return t.get("completions", 0), dict(fb)


def _fb_delta(before, after):
    return {r: after.get(r, 0) - before.get(r, 0) for r in REASONS
            if after.get(r, 0) != before.get(r, 0)}


class _Svc:
    """Service under test (built as a plain Service subclass inside the
    fixture to keep brpc_tpu imports lazy for the skip path)."""


def _mk_server(**opt):
    from brpc_tpu.server import Server, ServerOptions, Service

    class Probe(Service):
        def __init__(self):
            super().__init__()
            self.seen = []           # per-call state snapshots
            self.park = threading.Event()

        def Echo(self, cntl, request):
            cntl.response_attachment.append_iobuf(
                cntl.request_attachment)
            return request

        def Err(self, cntl, request):
            cntl.set_failed(1234, "boom")
            return b""

        def Slow(self, cntl, request):
            time.sleep(float(request or b"0.05"))
            return b"slow"

        def Snap(self, cntl, request):
            # observable server-controller state: pooled reuse must
            # reset every one of these between calls
            self.seen.append({
                "att": cntl.request_attachment.to_bytes(),
                "deadline": cntl.deadline_remaining_ms(),
                "tenant": bytes(cntl.request_meta.tenant or b""),
                "trace": cntl.trace_id,
                "failed": cntl.failed,
                "resp_att": len(cntl.response_attachment),
            })
            return b"snap"

    opts = ServerOptions()
    opts.native = True
    opts.usercode_inline = True
    opts.native_loops = 1
    for k, v in opt.items():
        setattr(opts, k, v)
    svc = Probe()
    srv = Server(opts)
    srv.add_service(svc, name="CL")
    assert srv.start("127.0.0.1:0") == 0
    return srv, svc


def _single_channel(srv, **copt):
    o = ChannelOptions()
    o.connection_type = "single"      # the lane's home: multiplexed demux
    for k, v in copt.items():
        setattr(o, k, v)
    ch = Channel(o)
    ch.init(str(srv.listen_endpoint))
    return ch


@pytest.fixture()
def lane_server():
    require_native()
    srv, svc = _mk_server()
    yield srv, svc
    srv.stop()


# ---------------------------------------------------------------------------
# 1. the eligible matrix stays native (zero new fallbacks)
# ---------------------------------------------------------------------------

def test_eligible_matrix_stays_native(lane_server):
    srv, _svc = lane_server
    ch = _single_channel(srv, tenant="acme")
    comp0, fb0 = _lane_counts()

    # plain
    c = ch.call_method("CL.Echo", b"plain")
    assert not c.failed and c.response == b"plain"
    # deadline-on
    cntl = Controller()
    cntl.timeout_ms = 5000
    c = ch.call_method("CL.Echo", b"deadline", cntl=cntl)
    assert not c.failed and c.response == b"deadline"
    # trace-on (explicitly traced: client+server span pair must record)
    cntl = Controller()
    cntl.trace_id = 0xBEEF01
    c = ch.call_method("CL.Echo", b"traced", cntl=cntl)
    assert not c.failed and c.response == b"traced"
    # attachment response
    cntl = Controller()
    cntl.request_attachment = IOBuf(b"A" * 512)
    c = ch.call_method("CL.Echo", b"att", cntl=cntl)
    assert not c.failed
    assert c.response_attachment.to_bytes() == b"A" * 512
    # async done
    ev = threading.Event()
    out = {}

    def done(cc):
        out["resp"] = cc.response
        ev.set()

    ch.call_method("CL.Echo", b"async", done=done)
    assert ev.wait(5) and out["resp"] == b"async"

    comp1, fb1 = _lane_counts()
    assert comp1 - comp0 == 5, "eligible traffic must demux natively"
    assert _fb_delta(fb0, fb1) == {}, "zero new fallbacks on the matrix"

    # the traced call recorded the client/server span pair
    from brpc_tpu.rpcz import global_span_store
    spans = global_span_store().by_trace(0xBEEF01)
    kinds = {s.is_server for s in spans}
    assert kinds == {True, False}, \
        f"traced lane call must record both span halves, got {spans}"


def test_error_response_falls_back_named(lane_server):
    srv, _svc = lane_server
    ch = _single_channel(srv)
    ch.call_method("CL.Echo", b"warm")        # socket + lane attach
    comp0, fb0 = _lane_counts()
    c = ch.call_method("CL.Err", b"x")
    assert c.error_code == 1234 and c.error_text == "boom"
    _comp1, fb1 = _lane_counts()
    assert _fb_delta(fb0, fb1) == {"cli_meta_tags": 1}


def test_stream_frames_fall_back_named(lane_server):
    srv, _svc = lane_server
    from brpc_tpu.server import Server, ServerOptions, Service
    from brpc_tpu.streaming import (StreamOptions, stream_accept,
                                    stream_create)

    got = []
    done = threading.Event()

    class Sink(Service):
        def Start(self, cntl, request):
            def on_received(stream, msgs):
                got.extend(bytes(m) for m in msgs)
                done.set()
            stream_accept(cntl, StreamOptions(on_received=on_received))
            return b"ok"

    o = ServerOptions()
    o.native = True
    o.usercode_inline = True
    srv2 = Server(o)
    srv2.add_service(Sink(), name="SK")
    assert srv2.start("127.0.0.1:0") == 0
    try:
        ch = _single_channel(srv2)
        # a PLAIN call first pins the shared single socket to the lane;
        # the stream then rides the same lane-attached connection
        with pytest.raises(Exception):
            ch.call("SK.Nope", b"")           # warms the conn (error)
        comp0, fb0 = _lane_counts()
        cntl = Controller()
        cntl.timeout_ms = 5000
        stream = stream_create(cntl, StreamOptions())
        c = ch.call_method("SK.Start", b"", cntl=cntl)
        assert not c.failed, c.error_text
        # server->client stream traffic arrives as TSTR frames on the
        # lane socket: each must fall back under its NAMED reason; the
        # stream itself works end-to-end (byte-identical demux)
        assert stream.write(b"chunk-1") == 0
        assert stream.write(b"chunk-2") == 0
        assert done.wait(5)
        assert got and got[0] == b"chunk-1"
        _comp, fb1 = _lane_counts()
        d = _fb_delta(fb0, fb1)
        assert set(d) <= {"cli_meta_tags", "cli_stream_frame"}, d
        assert d.get("cli_meta_tags", 0) >= 1   # the stream grant
        stream.close()
    finally:
        srv2.stop()


def test_backup_request_stale_response_handled(lane_server):
    """A backup request's losing response must be consumed without
    corrupting anything: same-burst arrivals demux natively and drop at
    the versioned-id rendezvous (the classic stale discipline);
    later-burst arrivals fall back under cli_unknown_cid (the entry was
    cancelled at call end).  Either way the call succeeds exactly once
    and the connection keeps working."""
    srv, _svc = lane_server
    ch = _single_channel(srv)
    ch.call_method("CL.Echo", b"warm")
    comp0, fb0 = _lane_counts()
    cntl = Controller()
    cntl.timeout_ms = 5000
    cntl.backup_request_ms = 20           # fires during the 100ms sleep
    cntl.max_retry = 1
    c = ch.call_method("CL.Slow", b"0.1", cntl=cntl)
    assert not c.failed and c.response == b"slow"
    assert c.has_backup_request
    # both attempts' responses drain (winner + loser), one way or the
    # other — and the stale one never lands on a later call
    deadline = time.time() + 5
    while time.time() < deadline:
        comp1, fb1 = _lane_counts()
        consumed = (comp1 - comp0) + (fb1.get("cli_unknown_cid", 0)
                                      - fb0.get("cli_unknown_cid", 0))
        if consumed >= 2:
            break
        time.sleep(0.01)
    assert consumed >= 2, "loser's response must be consumed"
    c2 = ch.call_method("CL.Echo", b"after")
    assert not c2.failed and c2.response == b"after"


# ---------------------------------------------------------------------------
# 2. force-disabled vs enabled: identical Controller observables
# ---------------------------------------------------------------------------

def _run_matrix(srv):
    """One pass of the comparison matrix against ``srv``; returns the
    list of observable outcomes."""
    out = []
    ch = _single_channel(srv, tenant="cmp")
    # success
    c = ch.call_method("CL.Echo", b"ok")
    out.append(("ok", c.error_code, c.response,
                c.response_attachment.to_bytes()))
    # error
    c = ch.call_method("CL.Err", b"x")
    out.append(("err", c.error_code, c.error_text))
    # attachment + deadline
    cntl = Controller()
    cntl.timeout_ms = 5000
    cntl.request_attachment = IOBuf(b"B" * 300)
    c = ch.call_method("CL.Echo", b"a", cntl=cntl)
    out.append(("att", c.error_code, c.response,
                c.response_attachment.to_bytes()))
    # client-side timeout (doomed work)
    cntl = Controller()
    cntl.timeout_ms = 30
    cntl.max_retry = 0
    c = ch.call_method("CL.Slow", b"0.5", cntl=cntl)
    out.append(("timeout", c.error_code))
    # traced
    cntl = Controller()
    cntl.trace_id = 0xCAFE
    c = ch.call_method("CL.Echo", b"t", cntl=cntl)
    out.append(("traced", c.error_code, c.response))
    return out


def test_lane_on_off_state_comparison():
    """The whole matrix, lane force-disabled vs enabled, on separate
    servers (a 'single' socket keeps its demux mode for life): every
    Controller observable must match."""
    require_native()
    results = {}
    for lane_on in (True, False):
        set_flag("rpc_native_client_lane", lane_on)
        try:
            srv, _svc = _mk_server()
            try:
                results[lane_on] = _run_matrix(srv)
            finally:
                srv.stop()
        finally:
            set_flag("rpc_native_client_lane", True)
    assert results[True] == results[False]


def test_breaker_feed_identical_on_lane():
    """Single-server channels route completion health into the GLOBAL
    breaker map from _finish_locked — lane completions must feed it
    exactly like dispatcher completions."""
    require_native()
    from brpc_tpu.client.circuit_breaker import global_circuit_breaker_map

    def feed_count(lane_on):
        set_flag("rpc_native_client_lane", lane_on)
        try:
            srv, _svc = _mk_server()
            try:
                ch = _single_channel(srv, enable_circuit_breaker=True)
                for _ in range(4):
                    assert ch.call("CL.Echo", b"x") == b"x"
                node = global_circuit_breaker_map()._node(
                    srv.listen_endpoint)
                return node is not None
            finally:
                srv.stop()
        finally:
            set_flag("rpc_native_client_lane", True)

    assert feed_count(True) == feed_count(False)


# ---------------------------------------------------------------------------
# 3. demux unit surface: crafted wire bytes -> named reasons
# ---------------------------------------------------------------------------

def _tlv(tag, data):
    return bytes([tag]) + struct.pack("<I", len(data)) + data


def _resp_frame(cid, payload=b"", extra_meta=b""):
    meta = _tlv(1, struct.pack("<Q", cid)) + extra_meta
    return (b"TRPC" + struct.pack("<II", len(meta) + len(payload),
                                  len(meta)) + meta + payload)


class _DemuxHarness:
    def __init__(self):
        from brpc_tpu.native import load
        self.m = load()
        self.events = []
        self.cv = threading.Condition()
        self.demux = self.m.ClientDemux(self._cb)
        self.thread = threading.Thread(target=self.demux.run_loop,
                                       daemon=True)
        self.thread.start()
        self.a, self.b = pysock.socketpair()
        self.a.setblocking(False)
        self.token = self.demux.attach(self.a.fileno())
        assert self.demux.arm(self.token)

    def _cb(self, *args):
        with self.cv:
            self.events.append(args)
            self.cv.notify_all()

    def wait_events(self, n, timeout=5.0):
        with self.cv:
            self.cv.wait_for(lambda: len(self.events) >= n, timeout)
            return list(self.events)

    def close(self):
        self.demux.stop()
        self.thread.join(timeout=5)
        self.a.close()
        self.b.close()


def test_demux_unit_reasons_and_completions():
    require_native()
    h = _DemuxHarness()
    try:
        m = h.m
        assert h.demux.expect(h.token, 7)
        # burst: one plain completion + one unknown cid + one TICI ack
        h.b.sendall(_resp_frame(7, b"PAY")
                    + _resp_frame(99, b"zz")
                    + b"TICI" + struct.pack("<I", 1)
                    + struct.pack("<Q", 4242))
        evs = h.wait_events(1)
        token, status, comps, fbs, acks = evs[0]
        assert status == 0
        assert [(c[0], bytes(c[1]), c[2]) for c in comps] \
            == [(7, b"PAY", 0)]
        assert [f[0] for f in fbs] == [m.CFB_UNKNOWN_CID]
        assert bytes(fbs[0][1]) == _resp_frame(99, b"zz")
        assert list(acks) == [4242]
        # error-meta response on a registered cid: falls back WHOLE,
        # entry kept (classic demux owns completion)
        assert h.demux.expect(h.token, 8)
        h.b.sendall(_resp_frame(8, b"", _tlv(6, struct.pack("<i", 1003))))
        evs = h.wait_events(2)
        _t, _s, comps, fbs, _a = evs[1]
        assert comps is None and [f[0] for f in fbs] == [m.CFB_META_TAGS]
        assert h.demux.cancel(h.token, 8)      # entry survived
        # malformed meta: no cid tag at all
        h.b.sendall(b"TRPC" + struct.pack("<II", 4, 4) + b"\x00" * 4)
        evs = h.wait_events(3)
        assert [f[0] for f in evs[2][3]] == [m.CFB_META_UNPARSED]
        # unknown magic: sticky passthrough forwards everything
        h.b.sendall(b"*1\r\nPING\r\n")
        evs = h.wait_events(4)
        assert [f[0] for f in evs[3][3]] == [m.CFB_UNKNOWN_MAGIC]
        h.b.sendall(b"more-bytes")
        evs = h.wait_events(5)
        assert [f[0] for f in evs[4][3]] == [m.CFB_UNKNOWN_MAGIC]
        # telemetry reasons form the closed enum exactly
        tel = h.demux.telemetry()
        assert set(tel["fallbacks"]) == set(REASONS)
        assert "unknown" not in tel["fallbacks"]
    finally:
        h.close()


def test_demux_unit_stream_frame_and_eof():
    require_native()
    h = _DemuxHarness()
    try:
        m = h.m
        payload = b"S" * 10
        tstr = (b"TSTR" + bytes([0]) + struct.pack("<Q", 5)
                + struct.pack("<I", len(payload)) + payload)
        h.b.sendall(tstr)
        evs = h.wait_events(1)
        assert [f[0] for f in evs[0][3]] == [m.CFB_STREAM_FRAME]
        assert bytes(evs[0][3][0][1]) == tstr
        # EOF after a final completion: the response wins, status=1 rides
        assert h.demux.expect(h.token, 11)
        h.b.sendall(_resp_frame(11, b"last"))
        h.b.close()
        evs = h.wait_events(2)
        flat_comps = [c for e in evs[1:] if e[2] for c in e[2]]
        assert [(c[0], bytes(c[1])) for c in flat_comps] == [(11, b"last")]
        assert any(e[1] == 1 for e in evs[1:])
    finally:
        h.demux.stop()
        h.thread.join(timeout=5)
        h.a.close()


# ---------------------------------------------------------------------------
# 4. pooled reuse leaks nothing
# ---------------------------------------------------------------------------

def test_pooled_client_controller_resets():
    c = Controller.obtain()
    c.timeout_ms = 123
    c.trace_id = 0xDEAD
    c.span_id = 7
    c.max_retry = 9
    c.request_attachment = IOBuf(b"leak?")
    c.excluded_servers.add(("1.2.3.4", 5))
    c.response = b"old-response"
    c.set_failed(42, "old")
    c.remote_side = ("9.9.9.9", 1)
    c.retried_count = 3
    c.recycle()
    c2 = Controller.obtain()
    assert c2 is c, "free list must hand the instance back"
    assert c2.timeout_ms is None and c2.max_retry is None
    assert c2.trace_id == 0 and c2.span_id == 0
    assert c2._req_att is None and len(c2.request_attachment) == 0
    assert not c2.excluded_servers
    assert c2.response is None and not c2.failed
    assert c2.error_code == 0 and c2.error_text == ""
    assert c2.remote_side is None and c2.retried_count == 0
    assert c2._done is None and c2._inflight_marks == []


def test_pooled_server_controller_no_cross_call_leak(lane_server):
    """Request 1 stamps tenant + deadline + attachment + trace; request
    2 is bare.  The slim lane's pooled ServerController must show the
    handler pristine state on request 2."""
    srv, svc = lane_server
    ch_rich = _single_channel(srv, tenant="leaky")
    cntl = Controller()
    cntl.timeout_ms = 5000
    cntl.trace_id = 0xF00D
    cntl.request_attachment = IOBuf(b"STICKY")
    assert not ch_rich.call_method("CL.Snap", b"", cntl=cntl).failed
    ch_bare = _single_channel(srv)
    bare_cntl = Controller()
    bare_cntl.timeout_ms = -1            # no TLV 13 on the wire at all
    assert not ch_bare.call_method("CL.Snap", b"", cntl=bare_cntl).failed
    rich, bare = svc.seen[-2], svc.seen[-1]
    assert rich["att"] == b"STICKY" and rich["tenant"] == b"leaky"
    assert rich["deadline"] is not None and rich["trace"] == 0xF00D
    assert bare["att"] == b""
    assert bare["tenant"] == b""
    assert bare["deadline"] is None
    assert bare["trace"] == 0
    assert not bare["failed"] and bare["resp_att"] == 0


def test_parallel_legs_recycled_without_leak():
    """Fan-out legs come from the pool; a traced fan-out followed by an
    untraced one must not leak trace context into the second's legs
    (observable: the second fan-out's sub-servers record no spans)."""
    require_native()
    from brpc_tpu.client.parallel_channel import ParallelChannel
    srvs = []
    pc = ParallelChannel()
    for _ in range(2):
        srv, _svc = _mk_server()
        srvs.append(srv)
        o = ChannelOptions()
        sub = Channel(o)
        sub.init(str(srv.listen_endpoint))
        pc.add_channel(sub)
    try:
        cntl = Controller()
        cntl.trace_id = 0xFA90
        c = pc.call_method("CL.Echo", b"one", cntl=cntl)
        assert not c.failed
        c = pc.call_method("CL.Echo", b"two")
        assert not c.failed and c.response == [b"two", b"two"]
        from brpc_tpu.rpcz import global_span_store
        traced = global_span_store().by_trace(0xFA90)
        assert traced, "traced fan-out must record spans"
        # the untraced fan-out inherited nothing: no span carries a
        # zero/foreign trace id from the recycled legs
        for s in traced:
            assert s.trace_id == 0xFA90
    finally:
        for srv in srvs:
            srv.stop()


def test_lane_flag_off_uses_dispatcher():
    """Force-disabled lane: a fresh single connection must route through
    the classic dispatcher (no completions counted) and still work."""
    require_native()
    set_flag("rpc_native_client_lane", False)
    try:
        srv, _svc = _mk_server()
        try:
            comp0, _ = _lane_counts()
            ch = _single_channel(srv)
            assert ch.call("CL.Echo", b"classic") == b"classic"
            comp1, _ = _lane_counts()
            assert comp1 == comp0
        finally:
            srv.stop()
    finally:
        set_flag("rpc_native_client_lane", True)
