"""Inference-plane observability (ISSUE 18): batcher step profiler,
per-session token timelines, SLO attainment, the /lm portal, and the
stitched decode-session rpcz trace.

Five planes:

- CLOSED ENUMS: ``LM_STEP_PHASES`` / ``LM_SLO_VERDICTS`` pinned
  member-by-member (the static enum checker requires every name
  anchored here); an unregistered verdict asserts loudly at the first
  count;
- PROFILER INVARIANTS: per-phase histogram mass equals the phase
  count, counts are monotonic across sessions, and the decode-round
  count equals the batcher's step counter exactly — the profiler is
  wired to the loop, not near it;
- SLO ATTAINMENT: per-tier verdict deltas against
  ``TierRegistry.set_slo`` targets (ok / ttft-miss / itl-miss /
  untargeted), judged at session close;
- STITCHED TRACE: one traced ``LM.Decode`` through the disaggregated
  prefill→decode handoff produces ONE trace id carrying both tiers'
  session spans — chunk-slice on the prefill side, first-token on the
  decode side — with no new wire format (the handoff RPC's ordinary
  trace TLVs);
- SURFACES: /lm + Prometheus exposition smoke, the
  one-snapshot-per-interval cache pin, windowed-vs-lifetime ratio
  semantics, bounded-ring eviction.
"""

import http.client
import json
import struct
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from brpc_tpu.client import Channel, Controller
from brpc_tpu.models import lm_telemetry as lmt
from brpc_tpu.models.lm_service import (ContinuousBatcher, LMService,
                                        TierRegistry,
                                        _reset_sched_for_tests,
                                        pack_generate_request,
                                        unpack_token)
from brpc_tpu.models.transformer_lm import LMConfig, init_params
from brpc_tpu.rpcz import global_span_store
from brpc_tpu.server import Server
from brpc_tpu.streaming import StreamOptions, stream_create

# ---------------------------------------------------------------------------
# Closed-enum pins (tools/check/enums.py requires every member of the
# observability enums anchored under tests/ — this is the anchor)
# ---------------------------------------------------------------------------

LM_STEP_PHASE_PINS = (
    "decode_round", "chunk_slice", "catchup_slice", "spec_draft",
    "spec_verify", "prefix_lookup", "page_alloc", "host_spill",
    "host_resume", "stream_emit",
)
LM_SLO_VERDICT_PINS = ("slo_ok", "slo_ttft_miss", "slo_itl_miss",
                       "slo_untargeted")


def test_lm_obs_enums_match_pins():
    assert lmt.LM_STEP_PHASES == LM_STEP_PHASE_PINS
    assert lmt.LM_SLO_VERDICTS == LM_SLO_VERDICT_PINS
    assert set(lmt.phase_counters()) == set(LM_STEP_PHASE_PINS)
    # the index constants ARE the write-side API: drift fails here
    for i, name in enumerate(LM_STEP_PHASE_PINS):
        assert getattr(lmt, "PH_" + name.upper()) == i
        assert lmt.phase_index(name) == i
    with pytest.raises(AssertionError):
        lmt.phase_index("some_new_phase")
    with pytest.raises(AssertionError):
        lmt.count_slo("standard", "slo_some_new_verdict")
    with pytest.raises(AssertionError):
        lmt.count_slo("platinum", "slo_ok")


def test_tier_registry_slo_targets():
    reg = TierRegistry()
    assert reg.slo_of("interactive") == (None, None)
    reg.set_slo("interactive", ttft_ms=250.0, itl_ms=50.0)
    reg.set_slo("batch", itl_ms=1000.0)
    assert reg.slo_of("interactive") == (250.0, 50.0)
    assert reg.slo_of("batch") == (None, 1000.0)
    with pytest.raises(ValueError, match="unknown SLO tier"):
        reg.set_slo("platinum", ttft_ms=1.0)


# ---------------------------------------------------------------------------
# Harness (the direct-batcher idiom from test_slo_sched)
# ---------------------------------------------------------------------------

def _setup(seed=0, **kw):
    cfg = LMConfig(vocab=64, dim=32, heads=4, depth=2, max_seq=32,
                   remat=False, **kw)
    return cfg, init_params(jax.random.PRNGKey(seed), cfg)


def _reset():
    from brpc_tpu.kv import pages as kv_pages
    from brpc_tpu.kv import transport as kv_transport
    kv_pages._reset_for_tests()
    kv_transport._reset_for_tests()
    _reset_sched_for_tests()
    lmt._reset_for_tests()


class _FakeStream:
    def __init__(self):
        self.closed = False
        self.close_reason = None
        self.tokens = []
        self.id = 0
        self._native_tx = None
        self.options = StreamOptions()

    def write(self, data):
        self.tokens.append(struct.unpack("<i", bytes(data))[0])
        return 0

    def close(self, reason=None):
        self.closed = True
        self.close_reason = reason


def _join(bat, prompt, max_new, tenant=None):
    st = _FakeStream()
    bat.join(st, prompt, max_new, tenant=tenant)
    return st


def _finish(*streams, timeout=120.0):
    deadline = time.monotonic() + timeout
    while not all(s.closed for s in streams) \
            and time.monotonic() < deadline:
        time.sleep(0.002)
    assert all(s.closed for s in streams), "decode session never closed"


def _prompt(seed, n, vocab=64):
    return np.asarray(jax.random.randint(jax.random.PRNGKey(seed),
                                         (n,), 0, vocab, jnp.int32))


# ---------------------------------------------------------------------------
# Step profiler: histogram/count invariants, count == steps
# ---------------------------------------------------------------------------

def test_phase_profiler_invariants():
    """Histogram mass == phase count for every phase; the decode-round
    count equals the batcher's own step counter EXACTLY (the profiler
    brackets the loop, one sample per round); counts are monotonic
    across sessions; total_ns is consistent with the counts."""
    _reset()
    cfg, params = _setup()
    bat = ContinuousBatcher(cfg, params, slots=2, paged=True, page=16,
                            prefill_chunk_tokens=4)
    st = _join(bat, _prompt(3, 17), 6)
    _finish(st)
    c1 = lmt.phase_counters()
    assert c1["decode_round"] == bat.steps_run()
    assert c1["chunk_slice"] >= 4                # ceil(16/4) slices
    assert c1["prefix_lookup"] >= 1
    assert c1["page_alloc"] >= 1
    assert c1["stream_emit"] >= 1
    for name in lmt.LM_STEP_PHASES:
        hist = lmt.phase_histogram(name)
        assert len(hist) == lmt.NBUCKETS
        assert sum(hist) == c1[name], name
        assert all(v >= 0 for v in hist)
    totals = lmt.phase_total_ns()
    assert totals["decode_round"] > 0
    assert totals["host_spill"] == 0             # nothing spilled here
    # monotonic across a second session, and still step-exact
    st2 = _join(bat, _prompt(4, 9), 4)
    _finish(st2)
    c2 = lmt.phase_counters()
    assert all(c2[p] >= c1[p] for p in lmt.LM_STEP_PHASES)
    assert c2["decode_round"] == bat.steps_run()
    assert sum(lmt.phase_histogram("decode_round")) \
        == c2["decode_round"]


def test_spec_round_phases_recorded():
    _reset()
    cfg, params = _setup()
    bat = ContinuousBatcher(cfg, params, slots=2, paged=True, page=16,
                            spec_decode_k=3, draft_params=params)
    st = _join(bat, _prompt(4, 8), 6)
    _finish(st)
    c = lmt.phase_counters()
    assert c["spec_draft"] >= 1
    assert c["spec_verify"] >= 1
    assert c["decode_round"] == bat.steps_run()


def test_profiler_disable_flag_stops_sampling():
    from brpc_tpu.butil.flags import set_flag
    _reset()
    cfg, params = _setup()
    bat = ContinuousBatcher(cfg, params, slots=2)
    assert set_flag("lm_telemetry", "false")
    try:
        assert not lmt.telemetry_enabled()
        st = _join(bat, _prompt(5, 6), 3)
        _finish(st)
        assert lmt.phase_counters()["decode_round"] == 0
        assert lmt.live_sessions() == [] and lmt.ring_len() == 0
    finally:
        assert set_flag("lm_telemetry", "true")
    assert lmt.telemetry_enabled()


# ---------------------------------------------------------------------------
# SLO attainment: per-tier verdict deltas at session close
# ---------------------------------------------------------------------------

def test_slo_verdicts_per_tier():
    _reset()
    cfg, params = _setup()
    reg = TierRegistry()
    reg.set_tier(b"alice", "interactive")
    reg.set_tier(b"bob", "batch")
    # generous targets: a toy decode on CPU finishes well inside 10 min
    reg.set_slo("interactive", ttft_ms=600_000.0, itl_ms=600_000.0)
    # impossible targets: a negative bound no real session can meet
    reg.set_slo("batch", ttft_ms=-1.0)
    # the default tier ("standard") configures no targets
    bat = ContinuousBatcher(cfg, params, slots=3, tiers=reg)
    st_a = _join(bat, _prompt(6, 6), 3, tenant=b"alice")
    st_b = _join(bat, _prompt(7, 6), 3, tenant=b"bob")
    st_c = _join(bat, _prompt(8, 6), 3, tenant=b"carol")
    _finish(st_a, st_b, st_c)
    slo = lmt.slo_counters()
    assert slo[("interactive", "slo_ok")] == 1
    assert slo[("batch", "slo_ttft_miss")] == 1
    assert slo[("standard", "slo_untargeted")] == 1
    # itl-miss: ttft untargeted, itl target impossible — a session
    # with a second token always exceeds it
    reg.set_slo("batch", itl_ms=-1.0)
    st_d = _join(bat, _prompt(9, 6), 3, tenant=b"bob")
    _finish(st_d)
    assert lmt.slo_counters()[("batch", "slo_itl_miss")] == 1
    # the finished sessions moved into the ring with their verdicts
    recs = lmt.timeline_records()
    assert len(recs) == 4 and lmt.live_sessions() == []
    by_tier = {r["tier"]: r for r in recs}
    assert by_tier["interactive"]["verdict"] == "slo_ok"
    assert by_tier["standard"]["verdict"] == "slo_untargeted"
    assert all(r["close_reason"] == "finished" for r in recs)
    assert all(r["tokens"] == 3 for r in recs)
    assert by_tier["interactive"]["ttft_ms"] is not None


def test_timeline_ring_bounded():
    _reset()
    lmt._reset_for_tests(ring=4)
    try:
        seqs = []
        for i in range(6):
            tl = lmt.open_timeline("standard", f"t{i}", 8, 2, "fresh")
            seqs.append(tl.seq)
            lmt.close_timeline(tl, "finished")
        assert lmt.ring_len() == 4 and lmt.ring_maxlen() == 4
        kept = [r["seq"] for r in lmt.timeline_records()]
        assert kept == seqs[-4:]             # oldest two evicted
        assert lmt.live_sessions() == []
    finally:
        lmt._reset_for_tests()


# ---------------------------------------------------------------------------
# Snapshot cache: one build per interval; windowed vs lifetime ratios
# ---------------------------------------------------------------------------

def test_one_snapshot_per_interval():
    _reset()
    cache = lmt.LmTelemetryCache(ttl_s=60.0)
    for _ in range(25):
        cache.get()
        cache.window()
    assert cache.builds == 1


def test_windowed_ratios_reflect_current_window():
    """Lifetime counters carry history; the windowed ratios are deltas
    between consecutive snapshots — stale history cannot dilute them."""
    from brpc_tpu.models.lm_service import count_spec
    _reset()
    # seed old history: 9 accepts, 1 reject (lifetime rate 0.9)
    for _ in range(9):
        count_spec("spec_accept")
    count_spec("spec_reject")
    assert lmt.lifetime_spec_accept_rate() == pytest.approx(0.9)
    cache = lmt.LmTelemetryCache(ttl_s=0.0)      # every call refreshes
    cache.get()                                  # baseline snapshot
    # the current window: 1 accept, 3 rejects
    count_spec("spec_accept")
    for _ in range(3):
        count_spec("spec_reject")
    assert lmt.windowed_spec_accept_rate(cache) == pytest.approx(0.25)
    # lifetime is untouched by the windowing
    assert lmt.lifetime_spec_accept_rate() == pytest.approx(10 / 14)


def test_windowed_prefix_ratio():
    from brpc_tpu.kv.pages import count_prefix
    _reset()
    count_prefix("prefix_miss")                  # history
    cache = lmt.LmTelemetryCache(ttl_s=0.0)
    cache.get()
    count_prefix("prefix_hit")
    count_prefix("prefix_partial_hit")
    count_prefix("prefix_miss")
    count_prefix("prefix_hit")
    assert lmt.windowed_prefix_hit_ratio(cache) == pytest.approx(0.75)


# ---------------------------------------------------------------------------
# Stitched disagg trace: ONE trace id across prefill + decode tiers
# ---------------------------------------------------------------------------

def _stream_decode_traced(srv, prompt, max_new, trace_id,
                          timeout=120.0):
    toks, closed = [], []

    def on_received(st, msgs):
        toks.extend(unpack_token(m) for m in msgs)

    ch = Channel()
    ch.init(str(srv.listen_endpoint))
    cntl = Controller()
    cntl.timeout_ms = int(timeout * 1000)
    cntl.trace_id = trace_id
    stream_create(cntl, StreamOptions(
        on_received=on_received,
        on_closed=lambda st: closed.append(st.close_reason)))
    c = ch.call_method("LM.Decode",
                       pack_generate_request(prompt, max_new),
                       cntl=cntl)
    assert not c.failed, (c.error_code, c.error_text)
    deadline = time.monotonic() + timeout
    while not closed and time.monotonic() < deadline:
        time.sleep(0.005)
    assert closed, "decode stream never closed"
    return toks, closed[0]


def _spans_by_method(trace_id, want, timeout=10.0):
    """The decode-tier session span finishes on the batcher thread at
    evict — poll briefly so the assert races nothing."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        spans = global_span_store().by_trace(trace_id)
        have = {s.full_method for s in spans}
        if want <= have:
            return {m: [s for s in spans if s.full_method == m]
                    for m in have}
        time.sleep(0.01)
    raise AssertionError(
        f"trace {trace_id:x} never collected {want - have}; "
        f"has {sorted(have)}")


def test_disagg_decode_session_trace_stitched():
    """The acceptance pin: a single traced LM.Decode through the
    disaggregated prefill→decode handoff yields ONE trace id holding
    both tiers' session spans — the prefill side's chunk-slice and
    handoff events, the decode side's first-token and evict events —
    parented to their tiers' server spans.  The trace context crossed
    tiers on the handoff RPC's EXISTING trace TLVs (no new wire
    format)."""
    from test_kv_disagg import _setup as _kv_setup
    from test_kv_disagg import _two_tier
    _reset()
    global_span_store().clear()
    cfg, params, prompt = _kv_setup()
    trace_id = 0x1517_0018
    pre_srv, dec_srv, dec_lm, _pre, _dch = _two_tier(cfg, params)
    try:
        toks, reason = _stream_decode_traced(pre_srv, prompt, 6,
                                             trace_id)
        assert reason == "finished" and len(toks) == 6
        by = _spans_by_method(trace_id, {
            "LMService.DecodeSession", "KV.DecodeTierSession",
            "LM.Decode", "KV.ImportSession"})
        # prefill tier: the session span parents to the Decode server
        # span and carries the join/chunk-slice/handoff events
        (pre_sess,) = by["LMService.DecodeSession"]
        dec_server = [s for s in by["LM.Decode"] if s.is_server]
        assert pre_sess.parent_span_id in {s.span_id
                                           for s in dec_server}
        pre_notes = [t for _, t in pre_sess.annotations]
        assert pre_notes[0] == "lm_join"
        assert "lm_chunk_slice" in pre_notes
        assert pre_notes[-1] == "lm_handoff"
        # decode tier: the session span parents to the ImportSession
        # server span (which is forced under the SAME trace id because
        # the handoff controller carried it) and sees the first token
        (dec_sess,) = by["KV.DecodeTierSession"]
        imp_server = [s for s in by["KV.ImportSession"] if s.is_server]
        assert dec_sess.parent_span_id in {s.span_id
                                           for s in imp_server}
        dec_notes = [t for _, t in dec_sess.annotations]
        assert "lm_first_token" in dec_notes
        assert dec_notes[-1] == "lm_evict:finished"
        assert dec_sess.trace_id == pre_sess.trace_id == trace_id
    finally:
        pre_srv.stop()
        dec_srv.stop()
        global_span_store().clear()


def test_monolithic_decode_session_span():
    """Single-tier shape: a traced Decode gets one session span with
    join → first-token → evict, child of the Decode server span."""
    _reset()
    global_span_store().clear()
    cfg, params = _setup()
    lm = LMService(cfg=cfg, params=params, decode_slots=2)
    srv = Server()
    srv.add_service(lm, name="LM")
    assert srv.start("127.0.0.1:0") == 0
    try:
        trace_id = 0xA11CE
        toks, reason = _stream_decode_traced(
            srv, _prompt(2, 8)[None, :], 4, trace_id)
        assert reason == "finished" and len(toks) == 4
        by = _spans_by_method(trace_id, {"LMService.DecodeSession",
                                         "LM.Decode"})
        (sess,) = by["LMService.DecodeSession"]
        notes = [t for _, t in sess.annotations]
        assert notes[0] == "lm_join"
        assert "lm_first_token" in notes
        assert notes[-1] == "lm_evict:finished"
        server_ids = {s.span_id for s in by["LM.Decode"]
                      if s.is_server}
        assert sess.parent_span_id in server_ids
    finally:
        srv.stop()
        global_span_store().clear()


# ---------------------------------------------------------------------------
# Surfaces: /lm portal page + Prometheus exposition
# ---------------------------------------------------------------------------

def _http_get(ep, path):
    conn = http.client.HTTPConnection(ep.host, ep.port, timeout=10)
    conn.request("GET", path)
    r = conn.getresponse()
    body = r.read()
    conn.close()
    return r.status, body


def test_lm_portal_and_metrics_exposition():
    _reset()
    cfg, params = _setup()
    lm = LMService(cfg=cfg, params=params, decode_slots=2)
    srv = Server()
    srv.add_service(lm, name="LM")
    assert srv.start("127.0.0.1:0") == 0
    try:
        st = _FakeStream()
        lm.batcher().join(st, _prompt(2, 8), 4)
        _finish(st)
        ep = srv.listen_endpoint
        status, body = _http_get(ep, "/lm")
        assert status == 200
        page = json.loads(body)
        assert page["enabled"] is True
        assert page["phases"]["decode_round"]["count"] \
            == lm.batcher().steps_run()
        assert page["phases"]["decode_round"]["buckets_ns"]
        recent = page["recent_sessions"]
        assert len(recent) == 1 and recent[0]["tokens"] == 4
        assert recent[0]["verdict"] == "slo_untargeted"
        assert page["live_sessions"] == []
        assert "spec_accept_rate" in page["windowed"]
        assert "prefix_cache_hit_ratio" in page["windowed"]
        assert page["lifetime"]["spec_accept_rate"] == 0.0
        assert page["timeline_ring"]["len"] == 1
        assert page["kv"]["phases"]["decode_round"] \
            == lm.batcher().steps_run()
        # the same counters ride the Prometheus exposition
        status, body = _http_get(ep, "/metrics")
        assert status == 200
        text = body.decode()
        assert 'lm_step_phase_total{phase="decode_round"}' in text
        assert 'lm_slo_attained_total{tier="standard",' \
            'verdict="slo_untargeted"}' in text
        assert 'lm_ttft_ms{tier="standard",quantile="p50"}' in text
        assert 'lm_windowed{ratio="spec_accept_rate"}' in text
        assert 'lm_step_phase_ns{phase="decode_round",bin=' in text
    finally:
        srv.stop()
