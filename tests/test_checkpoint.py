"""Checkpoint/resume: sharded save + sharding-preserving restore,
latest-step resume, retention pruning, and a mid-training resume that
continues bit-identically."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from brpc_tpu.utils.checkpoint import TrainCheckpointer, abstract_like


def _sharded_state(mesh):
    return {
        "params": {
            "w": jax.device_put(
                jnp.arange(64, dtype=jnp.float32).reshape(8, 8),
                NamedSharding(mesh, P("d", None))),
            "b": jax.device_put(jnp.ones((8,), jnp.float32),
                                NamedSharding(mesh, P(None))),
        },
        "step": jnp.int32(0),
    }


@pytest.fixture
def mesh():
    if len(jax.devices()) < 4:
        pytest.skip("needs the virtual multi-device mesh")
    return Mesh(np.array(jax.devices()), ("d",))


def test_save_restore_preserves_values_and_sharding(tmp_path, mesh):
    ckpt = TrainCheckpointer(str(tmp_path), max_to_keep=2)
    state = _sharded_state(mesh)
    ckpt.save(1, state)
    got = ckpt.restore(like=abstract_like(state))
    np.testing.assert_array_equal(np.asarray(got["params"]["w"]),
                                  np.asarray(state["params"]["w"]))
    assert got["params"]["w"].sharding == state["params"]["w"].sharding
    assert len(got["params"]["w"].sharding.device_set) == len(jax.devices())
    ckpt.close()


def test_latest_step_and_retention(tmp_path, mesh):
    ckpt = TrainCheckpointer(str(tmp_path), max_to_keep=2)
    state = _sharded_state(mesh)
    for s in (1, 2, 3, 4):
        state["step"] = jnp.int32(s)
        ckpt.save(s, state)
    assert ckpt.latest_step() == 4
    assert ckpt.all_steps() == [3, 4]           # max_to_keep pruned 1, 2
    got = ckpt.restore(like=abstract_like(state))
    assert int(got["step"]) == 4
    ckpt.close()


def test_restore_without_checkpoint_raises(tmp_path):
    ckpt = TrainCheckpointer(str(tmp_path))
    with pytest.raises(FileNotFoundError):
        ckpt.restore()
    ckpt.close()


def test_mid_training_resume_is_bit_identical(tmp_path, mesh):
    """Train 4 steps; checkpoint at 2; resume from the checkpoint and
    re-run steps 3-4: the final params must match the uninterrupted
    run exactly (determinism of the resumed trajectory)."""
    from brpc_tpu.models.transformer_lm import (LMConfig, init_params,
                                                make_train_step)

    cfg = LMConfig(vocab=32, dim=16, heads=2, depth=1, lr=0.3)
    params = init_params(jax.random.PRNGKey(0), cfg)
    ids = jnp.tile(jnp.arange(8, dtype=jnp.int32), (2, 2))
    labels = jnp.roll(ids, -1, axis=-1)
    step = jax.jit(make_train_step(cfg))

    ckpt = TrainCheckpointer(str(tmp_path))
    for i in range(1, 5):
        params, _ = step(params, ids, labels)
        if i == 2:
            ckpt.save(i, params)
    want = params

    resumed = ckpt.restore(like=abstract_like(want))
    for _ in range(3, 5):
        resumed, _ = step(resumed, ids, labels)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                   np.asarray(b)),
        resumed, want)
    ckpt.close()
