"""Streaming RPC tests: establishment over an RPC, ordered bidi data,
credit-window flow control, graceful close
(≈ /root/reference/test/brpc_streaming_rpc_unittest.cpp shapes +
example/streaming_echo_c++)."""

import threading
import time

import pytest

from brpc_tpu.client import Channel, Controller
from brpc_tpu.server import Server, Service
from brpc_tpu.streaming import (Stream, StreamOptions, stream_accept,
                                stream_create)


class StreamEcho(Service):
    """Accepts a stream and echoes every message back upper-cased."""

    def __init__(self):
        self.server_streams = []

    def Start(self, cntl, request):
        def on_received(stream, msgs):
            for m in msgs:
                stream.write(m.upper())

        s = stream_accept(cntl, StreamOptions(on_received=on_received))
        assert s is not None
        self.server_streams.append(s)
        return b"stream accepted"

    def StartTinyWindow(self, cntl, request):
        """Accepts with a 4KB receive buffer: the CLIENT's writes must
        obey this negotiated window."""
        received = []

        def on_received(stream, msgs):
            time.sleep(0.002)            # slow-ish consumer
            received.extend(msgs)

        s = stream_accept(cntl, StreamOptions(on_received=on_received,
                                              max_buf_size=4096))
        s.test_received = received       # type: ignore[attr-defined]
        self.server_streams.append(s)
        return b"ok"

    def NoStream(self, cntl, request):
        return b"plain"


@pytest.fixture()
def server(server_options):
    srv = Server(server_options)
    srv.add_service(StreamEcho(), name="SE")
    assert srv.start("127.0.0.1:0") == 0
    yield srv
    srv.stop()


def _collect(received, closed=None):
    def on_received(stream, msgs):
        received.extend(msgs)
    return StreamOptions(on_received=on_received,
                         on_closed=closed)


def test_stream_echo_roundtrip(server):
    ch = Channel()
    ch.init(str(server.listen_endpoint))
    received = []
    cntl = Controller()
    stream = stream_create(cntl, _collect(received))
    c = ch.call_method("SE.Start", b"hi", cntl=cntl)
    assert not c.failed, c.error_text
    assert c.response == b"stream accepted"
    assert stream.wait_established(5.0)

    for i in range(20):
        assert stream.write(f"msg{i}".encode()) == 0
    deadline = time.time() + 5.0
    while len(received) < 20 and time.time() < deadline:
        time.sleep(0.01)
    assert received == [f"MSG{i}".encode() for i in range(20)]
    stream.close()


def test_stream_flow_control_blocks_and_resumes(server):
    svc = server.services["SE"]
    ch = Channel()
    ch.init(str(server.listen_endpoint))
    cntl = Controller()
    opts = StreamOptions(write_timeout_s=10.0)
    stream = stream_create(cntl, opts)
    c = ch.call_method("SE.StartTinyWindow", b"", cntl=cntl)
    assert not c.failed, c.error_text
    assert stream.wait_established(5.0)
    # the SERVER advertised 4096: negotiation must have set our window
    assert stream._write_window == 4096
    payload = b"x" * 1024
    max_outstanding = 0
    for _ in range(32):                 # 32KB >> 4KB window
        assert stream.write(payload) == 0
        max_outstanding = max(max_outstanding,
                              stream._produced - stream._remote_consumed)
    # credit accounting really constrained the writer
    assert max_outstanding <= 4096 + len(payload)
    peer = svc.server_streams[-1]
    deadline = time.time() + 10.0
    while len(peer.test_received) < 32 and time.time() < deadline:
        time.sleep(0.01)
    assert len(peer.test_received) == 32
    stream.close()


def test_stream_close_notifies_peer(server):
    svc = server.services["SE"]
    ch = Channel()
    ch.init(str(server.listen_endpoint))
    closed_evt = threading.Event()
    cntl = Controller()
    stream = stream_create(cntl, _collect([], lambda s: closed_evt.set()))
    c = ch.call_method("SE.Start", b"", cntl=cntl)
    assert not c.failed
    assert stream.wait_established(5.0)
    peer = svc.server_streams[-1]
    peer.close()                        # server closes → client notified
    assert closed_evt.wait(5.0)
    assert stream.closed


def test_no_stream_method_unaffected(server):
    ch = Channel()
    ch.init(str(server.listen_endpoint))
    assert ch.call("SE.NoStream", b"") == b"plain"


def test_failed_establishment_closes_stream():
    ch = Channel()
    ch.init("127.0.0.1:1")          # nothing listens
    cntl = Controller()
    cntl.timeout_ms = 1500
    stream = stream_create(cntl, StreamOptions())
    c = ch.call_method("SE.Start", b"", cntl=cntl)
    assert c.failed
    assert stream.closed


def test_forged_frames_from_other_connections_dropped():
    """Frames carrying a valid stream id but arriving on a DIFFERENT
    connection than the stream is bound to must be dropped (spoof guard;
    the reference's versioned-SocketId stream ids give this implicitly)."""
    from brpc_tpu.protocol.streaming import F_DATA, _dispatch

    got = []
    s = Stream(StreamOptions(on_received=lambda st, msgs: got.extend(msgs)))
    try:
        s.socket_id = 424242          # bound connection (no real socket)

        class FakeSock:
            def __init__(self, sid):
                self.id = sid

        _dispatch((F_DATA, s.id, b"forged"), FakeSock(999999))
        time.sleep(0.1)
        assert got == []              # dropped
        _dispatch((F_DATA, s.id, b"legit"), FakeSock(424242))
        deadline = time.time() + 2
        while not got and time.time() < deadline:
            time.sleep(0.01)
        assert got == [b"legit"]
    finally:
        s._close_local(notify_peer=False)


def test_stream_ids_not_enumerable():
    """Ids start at a random offset, not 1 — a fresh peer can't guess
    live stream ids by counting."""
    s = Stream()
    try:
        assert s.id > 1000
    finally:
        s._close_local(notify_peer=False)
