"""Transport-layer tests: socket write/drain, dispatcher wakeups,
acceptor + input messenger with a toy length-prefixed protocol —
the fake-protocol + loopback pattern from the reference's test suite
(/root/reference/test/brpc_channel_unittest.cpp:166-230)."""

import socket
import struct
import threading
import time

import pytest

from brpc_tpu.butil.endpoint import EndPoint, parse_endpoint
from brpc_tpu.butil.iobuf import IOBuf
from brpc_tpu.butil.status import Errno
from brpc_tpu.protocol.base import ParseResult, Protocol, ProtocolType
from brpc_tpu.transport.acceptor import Acceptor
from brpc_tpu.transport.event_dispatcher import EventDispatcher, global_dispatcher
from brpc_tpu.transport.input_messenger import InputMessenger
from brpc_tpu.transport.socket import Socket, SocketOptions
from brpc_tpu.transport.socket_map import SocketMap, pooled_socket, return_pooled_socket


def _wait_until(pred, timeout=5.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(0.005)
    return False


def test_socket_versioned_addressing():
    sid = Socket.create(SocketOptions())
    assert Socket.address(sid) is not None
    Socket.address(sid).release()
    assert Socket.address(sid) is None


def test_socket_write_over_socketpair():
    a, b = socket.socketpair()
    sid = Socket.create(SocketOptions(fd=a))
    s = Socket.address(sid)
    buf = IOBuf(b"hello world")
    assert s.write(buf) == 0
    b.settimeout(2.0)
    assert b.recv(1024) == b"hello world"
    s.release()
    b.close()


def test_socket_large_write_drains_via_keepwrite():
    a, b = socket.socketpair()
    sid = Socket.create(SocketOptions(fd=a))
    s = Socket.address(sid)
    payload = b"x" * (4 * 1024 * 1024)   # beyond socket buffers => EAGAIN
    assert s.write(IOBuf(payload)) == 0
    received = bytearray()
    b.settimeout(5.0)
    while len(received) < len(payload):
        chunk = b.recv(65536)
        assert chunk
        received.extend(chunk)
    assert bytes(received) == payload
    s.release()
    b.close()


def test_socket_write_order_preserved_under_concurrency():
    a, b = socket.socketpair()
    sid = Socket.create(SocketOptions(fd=a))
    s = Socket.address(sid)
    n_threads, per_thread = 8, 50
    counter = threading.Lock()
    seq = [0]

    def writer():
        for _ in range(per_thread):
            with counter:
                i = seq[0]
                seq[0] += 1
                # sequence number assigned and enqueued atomically ⇒ the
                # wire must carry strictly increasing sequence numbers
                assert s.write(IOBuf(struct.pack("<I", i))) == 0

    threads = [threading.Thread(target=writer) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    total = n_threads * per_thread * 4
    data = bytearray()
    b.settimeout(5.0)
    while len(data) < total:
        data.extend(b.recv(65536))
    values = [struct.unpack_from("<I", data, off)[0]
              for off in range(0, total, 4)]
    assert values == sorted(values)
    s.release()
    b.close()


def test_set_failed_notifies_id_wait():
    from brpc_tpu.fiber.versioned_id import global_id_pool
    got = {}

    def on_error(call_id, data, code, text):
        got["code"] = code
        global_id_pool().unlock_and_destroy(call_id)

    cid = global_id_pool().create(data=None, on_error=on_error)
    a, b = socket.socketpair()
    sid = Socket.create(SocketOptions(fd=a))
    s = Socket.address(sid)
    s.write(IOBuf(b"zzz"), id_wait=0)
    s.set_failed(Errno.EFAILEDSOCKET, "test")
    # queued writes after failure must report immediately
    rc = s.write(IOBuf(b"after"), id_wait=cid)
    assert rc != 0
    assert got.get("code") == int(Errno.EFAILEDSOCKET)
    b.close()


# -- toy framed protocol (4-byte magic + u32 len + body) ------------------

MAGIC = b"TOY0"


def _toy_parse(source, sock, read_eof, arg):
    if len(source) < 8:
        got = source.fetch(min(4, len(source)))
        if MAGIC.startswith(got[:len(MAGIC)]) or got == MAGIC:
            return ParseResult.not_enough_data()
        return ParseResult.try_others()
    head = source.fetch(8)
    if head[:4] != MAGIC:
        return ParseResult.try_others()
    (ln,) = struct.unpack_from("<I", head, 4)
    if len(source) < 8 + ln:
        return ParseResult.not_enough_data()
    source.pop_front(8)
    body = source.cutn(ln)
    return ParseResult.make_message(body)


def _toy_frame(payload: bytes) -> bytes:
    return MAGIC + struct.pack("<I", len(payload)) + payload


class _EchoServerState:
    def __init__(self):
        self.seen = []

    def process_request(self, msg, sock, arg):
        data = msg.to_bytes()
        self.seen.append(data)
        sock.write(IOBuf(_toy_frame(data.upper())))


def test_acceptor_echo_roundtrip():
    state = _EchoServerState()
    proto = Protocol(ProtocolType.UNKNOWN, "toy", _toy_parse,
                     process_request=state.process_request)
    messenger = InputMessenger([proto], arg="server")
    acceptor = Acceptor(messenger)
    listener = socket.socket()
    listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    listener.bind(("127.0.0.1", 0))
    listener.listen(16)
    port = listener.getsockname()[1]
    acceptor.start_accept(listener)

    c = socket.create_connection(("127.0.0.1", port), timeout=2.0)
    c.sendall(_toy_frame(b"hello") + _toy_frame(b"there"))
    c.settimeout(5.0)
    got = bytearray()
    while got.count(MAGIC) < 2 or len(got) < 8 + 5 + 8 + 5:
        got.extend(c.recv(4096))
    assert b"HELLO" in got and b"THERE" in got
    assert _wait_until(lambda: acceptor.connection_count() == 1)
    c.close()
    assert _wait_until(lambda: acceptor.connection_count() == 0)
    acceptor.stop_accept()


def test_socket_map_dedup_and_pooled():
    listener = socket.socket()
    listener.bind(("127.0.0.1", 0))
    listener.listen(16)
    port = listener.getsockname()[1]
    ep = parse_endpoint(f"127.0.0.1:{port}")
    m = SocketMap(health_check_interval_s=0.0)
    sid1, rc1 = m.get_socket(ep)
    sid2, rc2 = m.get_socket(ep)
    assert rc1 == 0 and rc2 == 0 and sid1 == sid2

    psid1, _ = pooled_socket(ep)
    return_pooled_socket(psid1)
    psid2, _ = pooled_socket(ep)
    assert psid1 == psid2          # reused from the free list
    m.clear()
    listener.close()


def test_health_check_revives():
    listener = socket.socket()
    listener.bind(("127.0.0.1", 0))
    listener.listen(16)
    port = listener.getsockname()[1]
    ep = parse_endpoint(f"127.0.0.1:{port}")
    sid = Socket.create(SocketOptions(
        remote_side=ep, health_check_interval_s=0.05))
    s = Socket.address(sid)
    assert s.connect_if_not() == 0
    s.set_failed(Errno.EFAILEDSOCKET, "injected")
    assert s.failed
    assert _wait_until(lambda: not Socket.address(sid).failed, timeout=5.0)
    Socket.address(sid).release()
    listener.close()


def test_inflight_call_errors_promptly_when_connection_dies():
    """A request already flushed on a 'single' (multiplexed) connection
    must be errored by the socket's death immediately — not discover it
    at its own deadline (the reference's Socket id wait list shape)."""
    import threading
    import time as _time

    from brpc_tpu.client import Channel, ChannelOptions, Controller
    from brpc_tpu.server import Server, Service

    class Slow(Service):
        def Nap(self, cntl, request):
            _time.sleep(3.0)       # longer than the kill below
            return b"late"

    srv = Server()
    srv.add_service(Slow(), name="S")
    assert srv.start("127.0.0.1:0") == 0
    co = ChannelOptions()
    co.timeout_ms = 10_000
    co.max_retry = 0
    co.connection_type = "single"
    ch = Channel(co)
    assert ch.init(str(srv.listen_endpoint)) == 0

    cntl = Controller()
    cntl.timeout_ms = 10_000
    done = threading.Event()
    ch.call_method("S.Nap", b"", cntl=cntl, done=lambda c: done.set())
    _time.sleep(0.3)               # request is in flight server-side
    t0 = _time.monotonic()
    srv.stop()                     # connection dies under the call
    assert done.wait(5.0), "in-flight call never completed"
    took = _time.monotonic() - t0
    assert cntl.failed
    assert took < 4.0, f"failure took {took:.1f}s — deadline-driven, " \
        "not socket-death-driven"


def test_retry_exhaustion_on_dead_single_connection_finishes():
    """Retries against a dead server on a 'single' connection must end
    in a terminal failure, not spin: queued id errors are delivered
    with the ATTEMPT's call id (a re-delivery that substituted the base
    id re-errored version 0 forever and the call never completed)."""
    import threading
    import time as _time

    from brpc_tpu.client import Channel, ChannelOptions, Controller
    from brpc_tpu.server import Server, Service

    class Slow(Service):
        def Nap(self, cntl, request):
            _time.sleep(2.0)
            return b"late"

    srv = Server()
    srv.add_service(Slow(), name="S")
    assert srv.start("127.0.0.1:0") == 0
    co = ChannelOptions()
    co.timeout_ms = 8_000
    co.max_retry = 2
    co.connection_type = "single"
    ch = Channel(co)
    assert ch.init(str(srv.listen_endpoint)) == 0
    cntl = Controller()
    cntl.timeout_ms = 8_000
    done = threading.Event()
    ch.call_method("S.Nap", b"", cntl=cntl, done=lambda c: done.set())
    _time.sleep(0.2)
    t0 = _time.monotonic()
    srv.stop()
    assert done.wait(5.0), "retry chain never terminated"
    assert cntl.failed
    assert cntl.retried_count == 2          # budget spent, then finished
    assert _time.monotonic() - t0 < 4.0


def test_single_connection_survives_server_bounce_on_same_port():
    """A bounced server on the same address (ephemeral port reuse, a
    production restart): the shared 'single' connection EOFs on first
    use, and the call's RETRY must reconnect inline (fail-fast revival)
    instead of failing until the health checker's 3s tick."""
    import time as _time

    from brpc_tpu.client import Channel, ChannelOptions, Controller
    from brpc_tpu.server import Server, Service

    class E(Service):
        def Echo(self, cntl, request):
            return request

    srv = Server()
    srv.add_service(E(), name="E")
    assert srv.start("127.0.0.1:0") == 0
    port = int(srv.listen_endpoint.port)
    co = ChannelOptions()
    co.timeout_ms = 3000
    co.max_retry = 3
    co.connection_type = "single"
    ch = Channel(co)
    assert ch.init(f"127.0.0.1:{port}") == 0
    assert ch.call("E.Echo", b"warm") == b"warm"
    srv.stop()
    srv2 = Server()
    srv2.add_service(E(), name="E")
    rebound = srv2.start(f"127.0.0.1:{port}") == 0
    if not rebound:
        import pytest
        pytest.skip("port not immediately rebindable on this kernel")
    try:
        t0 = _time.monotonic()
        cntl = Controller()
        # generous deadline: the PROPERTY under test is that revival is
        # retry-driven (took < 2.5s, under the 3s health tick), asserted
        # separately below — a deadline near the health tick would
        # misreport a slow-but-working revival as an opaque call
        # failure (seen rarely under full-suite load)
        cntl.timeout_ms = 8000
        c = ch.call_method("E.Echo", b"back", cntl=cntl)
        took = _time.monotonic() - t0
        if c.failed or took >= 2.5:
            # full diagnostics on the record — this spot produced an
            # order-dependent failure ~1/6 full-suite runs in r5
            from brpc_tpu.transport.socket import Socket
            from brpc_tpu.transport.socket_map import global_socket_map
            sid = global_socket_map()._map.get(
                (ch.single_server, False))
            s = Socket.address(sid) if sid is not None else None
            diag = (f"failed={c.failed} code={c.error_code} "
                    f"text={c.error_text!r} took={took:.2f}s "
                    f"retried={c.retried_count} sid={sid} "
                    f"sock_failed={getattr(s, 'failed', None)} "
                    f"sock_err={getattr(s, '_error_text', None)!r} "
                    f"direct_read={getattr(s, 'direct_read', None)}")
            assert not c.failed, diag
            assert took < 2.5, f"revival health-tick-bound: {diag}"
        assert c.response == b"back"
    finally:
        srv2.stop()
