"""Periodic bvar dump-to-file: snapshot contents, prefix filter,
atomic swap, and live flag gating."""

import os

import pytest

from brpc_tpu.butil.flags import set_flag
from brpc_tpu.bvar import Adder
from brpc_tpu.bvar.dump import dump_once
from brpc_tpu.bvar.variable import clear_registry_for_tests


@pytest.fixture(autouse=True)
def _clean():
    clear_registry_for_tests()
    yield
    set_flag("bvar_dump", False)
    set_flag("bvar_dump_prefix", "")
    clear_registry_for_tests()


def test_dump_once_writes_snapshot(tmp_path):
    a = Adder("dump_test_requests")
    a << 41
    a << 1
    path = str(tmp_path / "monitor" / "bvar.data")
    got = dump_once(path)
    assert got == path
    text = open(path).read()
    assert "dump_test_requests : 42" in text
    # atomic swap leaves no temp file behind
    assert not [f for f in os.listdir(tmp_path / "monitor")
                if f.startswith("bvar.data.tmp")]


def test_dump_prefix_filters(tmp_path):
    Adder("svc_a_count") << 1
    Adder("other_count") << 2
    set_flag("bvar_dump_prefix", "svc_a")
    path = str(tmp_path / "bvar.data")
    dump_once(path)
    text = open(path).read()
    assert "svc_a_count" in text
    assert "other_count" not in text


def test_dump_overwrites_previous_snapshot(tmp_path):
    a = Adder("dump_test_counter")
    path = str(tmp_path / "bvar.data")
    a << 1
    dump_once(path)
    a << 1
    dump_once(path)
    text = open(path).read()
    assert "dump_test_counter : 2" in text
    assert text.count("dump_test_counter") == 1
