"""Pallas flash attention vs the dense oracle: causal/full, padded
shapes (seq/head-dim not block multiples), gradients, and use inside
the TransformerLM forward."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from brpc_tpu.ops.flash_attention import flash_attention
from brpc_tpu.parallel.ring_attention import reference_attention


def _qkv(b=2, s=64, h=2, d=16, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    return tuple(jax.random.normal(k, (b, s, h, d), jnp.float32) * 0.5
                 for k in ks)


@pytest.mark.parametrize("causal", [False, True])
def test_matches_dense(causal):
    q, k, v = _qkv()
    got = flash_attention(q, k, v, causal)
    want = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("s,d", [(40, 16), (100, 24), (129, 8)])
def test_padded_shapes(s, d):
    """Sequence/head-dim far from block multiples: pad keys masked,
    pad rows sliced."""
    q, k, v = _qkv(b=1, s=s, h=2, d=d, seed=3)
    got = flash_attention(q, k, v, True, 32, 32)
    want = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_mismatched_block_sizes_cover_all_keys():
    """block_q != block_k with neither dividing the other: the padded
    seq must be a common multiple or trailing keys are silently
    dropped (regression: s_pad was padded only to max(bq, bk))."""
    q, k, v = _qkv(b=1, s=64, h=2, d=16, seed=7)
    got = flash_attention(q, k, v, False, 64, 48)
    want = reference_attention(q, k, v, causal=False)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-3, atol=1e-3)


def test_multiple_k_blocks_online_softmax():
    """seq spanning several k blocks exercises the running max/denom
    accumulation across the innermost grid dimension."""
    q, k, v = _qkv(b=1, s=256, h=1, d=16, seed=4)
    got = flash_attention(q, k, v, False, 64, 64)
    want = reference_attention(q, k, v, causal=False)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_gradients_match_dense():
    q, k, v = _qkv(b=1, s=48, h=2, d=16, seed=5)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, True) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(reference_attention(q, k, v, causal=True) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("s,d,causal", [(40, 16, True), (100, 24, False),
                                        (256, 16, True)])
def test_fused_backward_padded_and_multiblock(s, d, causal):
    """The fused dq/dk/dv kernels across padded shapes and several
    blocks per sweep match dense autodiff exactly."""
    q, k, v = _qkv(b=2, s=s, h=2, d=d, seed=9)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal, 32, 64) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(reference_attention(q, k, v, causal=causal) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5)


def test_fused_backward_in_train_loop():
    """Training through the flash kernel descends (end-to-end grads)."""
    from brpc_tpu.models.transformer_lm import (LMConfig, init_params,
                                                make_train_step)

    cfg = LMConfig(vocab=32, dim=32, heads=4, depth=2, lr=0.1,
                   use_flash=True)
    params = init_params(jax.random.PRNGKey(0), cfg)
    ids = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, 32,
                             jnp.int32)
    labels = jnp.roll(ids, -1, axis=-1)
    step = jax.jit(make_train_step(cfg))
    first = None
    for _ in range(25):
        params, loss = step(params, ids, labels)
        first = first if first is not None else float(loss)
    assert float(loss) < first * 0.9, (first, float(loss))


def test_lm_forward_with_flash():
    """The LM wired to flash attention matches its XLA-attention self."""
    from brpc_tpu.models.transformer_lm import (LMConfig, init_params,
                                                make_forward)

    cfg_x = LMConfig(vocab=32, dim=32, heads=4, depth=2, max_seq=64)
    cfg_f = LMConfig(vocab=32, dim=32, heads=4, depth=2, max_seq=64,
                     use_flash=True)
    params = init_params(jax.random.PRNGKey(0), cfg_x)
    ids = jax.random.randint(jax.random.PRNGKey(1), (2, 40), 0, 32,
                             jnp.int32)
    want = jax.jit(make_forward(cfg_x))(params, ids)
    got = jax.jit(make_forward(cfg_f))(params, ids)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=3e-2, atol=3e-3)


def test_adaptive_attention_dispatch():
    """attention(impl="auto") picks dense below the crossover and flash
    at/above it, and both agree with the oracle."""
    import numpy as np
    import jax

    from brpc_tpu.ops import flash_attention as fa

    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(k1, (2, 64, 2, 16), jnp.float32)
    k = jax.random.normal(k2, (2, 64, 2, 16), jnp.float32)
    v = jax.random.normal(k3, (2, 64, 2, 16), jnp.float32)
    want = fa.dense_attention(q, k, v, causal=True)
    for impl in ("auto", "dense", "flash"):
        got = fa.attention(q, k, v, causal=True, impl=impl)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-3, atol=2e-3)
    # trace-time selection: short seq -> dense einsum in the jaxpr; on
    # this CPU test backend auto NEVER picks the kernel (interpret mode
    # would be the slow choice) even past the crossover
    short = jax.make_jaxpr(
        lambda a, b, c: fa.attention(a, b, c, impl="auto"))(q, k, v)
    assert "pallas" not in str(short)
    s = min(fa.DENSE_FLASH_CROSSOVER, 4096)
    ql = jax.numpy.zeros((1, s, 1, 16), jnp.float32)
    long = jax.make_jaxpr(
        lambda a, b, c: fa.attention(a, b, c, impl="auto"))(ql, ql, ql)
    assert "pallas" not in str(long)       # off-TPU: dense
    forced = jax.make_jaxpr(
        lambda a, b, c: fa.attention(a, b, c, impl="flash"))(q, k, v)
    assert "pallas" in str(forced) or "custom" in str(forced)
