"""Disaggregated prefill/decode serving + the KV-cache transfer
subsystem (ISSUE 15, brpc_tpu/kv/).

Four planes, mirroring test_data_plane's discipline:

- END-TO-END: a prefill tier exports a session's KV pages, the decode
  tier imports them MID-REQUEST into the continuous batch, tokens
  stream to the original client — and the decoded tokens are
  bit-identical with the monolithic path on every lane (ici/shm/copy);
- ZERO-COPY: the same-host (ici-lane) handoff moves zero payload bytes
  through the message path — BOTH copy ledgers (engine
  ``data_plane_copies`` + Python ``copy_audit``) pinned at exactly 0,
  while the forced shm lane admits exactly its per-page staging memcpy
  (the ledger is proven live, not merely quiet);
- LIFECYCLE: generation-checked double-free/stale-import rejected
  loudly (client ERESPONSE, never "success with an empty cache"), leak
  pin after 1k handoffs, owner-sweep on socket death, drain settles
  outstanding exported pages;
- FALLBACKS: every ineligible shape falls back under a NAMED reason
  from the closed enum (no "unknown" bucket), each pinned here.
"""

import struct
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from brpc_tpu.butil.flags import get_flag, set_flag
from brpc_tpu.butil.status import Errno
from brpc_tpu.client import Channel, Controller
from brpc_tpu.models.lm_service import LMService, pack_generate_request, \
    unpack_token
from brpc_tpu.models.transformer_lm import LMConfig, generate, init_params
from brpc_tpu.server import Server, ServerOptions
from brpc_tpu.streaming import Stream, StreamOptions, stream_create

from conftest import require_native  # noqa: E402

# ---------------------------------------------------------------------------
# Closed-reason pins (the static enum checker requires every member to
# be anchored under tests/ — this is the anchor; renaming/adding a
# reason fails here until acknowledged on both sides)
# ---------------------------------------------------------------------------

KV_FALLBACK_PINS = (
    "kv_disabled", "kv_probe_failed", "kv_model_mismatch",
    "kv_shm_unavailable", "kv_page_over_slot", "kv_ring_exhausted",
    "kv_pages_exhausted", "kv_peer_remote", "kv_stream_not_local",
    "kv_import_rejected", "kv_no_decode_tier",
)
KV_CLOSE_PINS = ("kv_handoff_failed",)


def test_kv_reason_enums_match_pins():
    from brpc_tpu.kv import KV_CLOSE_REASONS, KV_FALLBACK_REASONS
    assert KV_FALLBACK_REASONS == KV_FALLBACK_PINS
    assert KV_CLOSE_REASONS == KV_CLOSE_PINS


def test_no_unknown_kv_bucket():
    from brpc_tpu.kv import count_fallback, kv_fallback_counters
    assert set(kv_fallback_counters()) == set(KV_FALLBACK_PINS)
    with pytest.raises(AssertionError):
        count_fallback("kv_some_new_reason")


# ---------------------------------------------------------------------------
# Harness
# ---------------------------------------------------------------------------

def _setup(seed=0, **kw):
    cfg = LMConfig(vocab=64, dim=32, heads=4, depth=2, max_seq=32,
                   remat=False, **kw)
    params = init_params(jax.random.PRNGKey(seed), cfg)
    prompt = np.asarray(jax.random.randint(jax.random.PRNGKey(1),
                                           (1, 8), 0, cfg.vocab,
                                           jnp.int32))
    return cfg, params, prompt


def _reset_kv():
    from brpc_tpu.kv import pages as kv_pages
    from brpc_tpu.kv import transport as kv_transport
    kv_pages._reset_for_tests()
    kv_transport._reset_for_tests()


def _two_tier(cfg, params, force_lane=None, decode_slots=4,
              native=False, decode_cfg=None, decode_params=None,
              decode_lm_kw=None, **prefill_kw):
    """Build a decode tier (LM + KV services) and a prefill tier
    pointed at it; returns (pre_srv, dec_srv, dec_lm, pre_svc, dch)."""
    from brpc_tpu.kv import DecodeTierService, KvTransport, \
        PrefillService

    def opts():
        o = ServerOptions()
        if native:
            o.native = True
            o.usercode_inline = False    # handlers run nested RPCs
        return o

    dec_lm = LMService(cfg=decode_cfg or cfg,
                       params=params if decode_params is None
                       else decode_params,
                       decode_slots=decode_slots,
                       **(decode_lm_kw or {}))
    dec_srv = Server(opts())
    dec_srv.add_service(dec_lm, name="LM")
    dec_srv.add_service(DecodeTierService(dec_lm), name="KV")
    assert dec_srv.start("127.0.0.1:0") == 0
    dch = Channel()
    dch.init(str(dec_srv.listen_endpoint))
    pre_svc = PrefillService(
        cfg=cfg, params=params, decode_channel=dch,
        transport=KvTransport(force_lane=force_lane),
        decode_slots=decode_slots, **prefill_kw)
    pre_srv = Server(opts())
    pre_srv.add_service(pre_svc, name="LM")
    assert pre_srv.start("127.0.0.1:0") == 0
    return pre_srv, dec_srv, dec_lm, pre_svc, dch


def _stream_decode(srv, prompt, max_new, timeout=120.0):
    """One streamed decode session -> (tokens, close_reason, ttft_s)."""
    toks, closed, first = [], [], []

    def on_received(st, msgs):
        if not first:
            first.append(time.monotonic())
        toks.extend(unpack_token(m) for m in msgs)

    ch = Channel()
    ch.init(str(srv.listen_endpoint))
    cntl = Controller()
    cntl.timeout_ms = int(timeout * 1000)
    stream_create(cntl, StreamOptions(
        on_received=on_received,
        on_closed=lambda st: closed.append(st.close_reason)))
    t0 = time.monotonic()
    c = ch.call_method("LM.Decode",
                       pack_generate_request(prompt, max_new),
                       cntl=cntl)
    assert not c.failed, (c.error_code, c.error_text)
    deadline = time.monotonic() + timeout
    while not closed and time.monotonic() < deadline:
        time.sleep(0.005)
    assert closed, "decode stream never closed"
    return toks, closed[0], (first[0] - t0 if first else None)


# ---------------------------------------------------------------------------
# End-to-end: two-tier == monolithic, on every lane
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("lane", [None, "shm", "copy"],
                         ids=["auto-ici", "shm", "copy"])
def test_two_tier_tokens_identical_to_monolithic(lane):
    """The acceptance demo: prefill worker exports the session's KV
    pages, the decode worker imports them mid-request and joins the
    continuous batch, tokens stream to the ORIGINAL client — and the
    token stream is identical with the monolithic path (greedy
    ``generate``) on the auto-picked ici lane AND the forced shm/copy
    lanes."""
    from brpc_tpu.kv import kv_stats, outstanding_pages
    if lane == "shm":
        from brpc_tpu.transport import shm_ring
        if not shm_ring.shm_supported():
            pytest.skip("no shm support in sandbox")
    _reset_kv()
    cfg, params, prompt = _setup()
    pre_srv, dec_srv, dec_lm, _pre, _dch = _two_tier(
        cfg, params, force_lane=lane)
    try:
        toks, reason, ttft = _stream_decode(pre_srv, prompt, 6)
        want = np.asarray(generate(params, cfg, prompt, 6))[0]
        assert toks == want.tolist()
        assert reason == "finished"
        assert ttft is not None
        st = kv_stats()
        assert st["sessions"] == 1
        assert st[f"{lane or 'ici'}_sessions"] == 1
        assert st["local_fallbacks"] == 0
        # the decode ran on the DECODE tier's batcher, not locally
        assert dec_lm.batcher().steps_run() >= 6
        # every exported page settled once the handoff completed
        assert outstanding_pages() == 0
    finally:
        pre_srv.stop()
        dec_srv.stop()


def test_handed_off_session_joins_live_batch():
    """Continuous batching across tiers: a session decoding DIRECTLY
    on the decode tier and a handed-off session share one live batch;
    both finish with their solo-greedy tokens."""
    _reset_kv()
    cfg, params, prompt = _setup()
    p2 = np.asarray(jax.random.randint(jax.random.PRNGKey(7), (1, 5),
                                       0, cfg.vocab, jnp.int32))
    pre_srv, dec_srv, dec_lm, _pre, _dch = _two_tier(cfg, params)
    try:
        res = {}
        t1 = threading.Thread(target=lambda: res.__setitem__(
            "direct", _stream_decode(dec_srv, prompt, 10)))
        t1.start()
        time.sleep(0.3)          # direct session is mid-generation
        res["handoff"] = _stream_decode(pre_srv, p2, 4)
        t1.join(120)
        wa = np.asarray(generate(params, cfg, prompt, 10))[0]
        wb = np.asarray(generate(params, cfg, p2, 4))[0]
        assert res["direct"][0] == wa.tolist()
        assert res["handoff"][0] == wb.tolist()
        assert res["direct"][1] == res["handoff"][1] == "finished"
    finally:
        pre_srv.stop()
        dec_srv.stop()


def test_same_host_handoff_zero_copies_both_ledgers():
    """THE zero-copy pin: a same-host (shared-runtime) handoff of a
    512KB session cache moves ZERO payload bytes through the message
    path — the engine ``data_plane_copies`` ledger of BOTH tiers and
    the Python ``copy_audit`` both read exactly 0 across the whole
    session.  The forced-shm control run then admits exactly its
    per-page ``stage_shm`` memcpy, proving the ledger is live."""
    require_native()
    from brpc_tpu.butil import copy_audit
    from brpc_tpu.kv import kv_stats
    from brpc_tpu.transport import shm_ring
    _reset_kv()
    # page size 256KB > AUDIT_FLOOR: a staged/serialized page would
    # be visible to the audit — silence means zero-copy, not smallness
    cfg = LMConfig(vocab=128, dim=128, heads=4, depth=2, max_seq=512,
                   remat=False)
    params = init_params(jax.random.PRNGKey(0), cfg)
    prompt = np.arange(8, dtype=np.int32)[None, :] % cfg.vocab
    pre_srv, dec_srv, _dec_lm, _pre, _dch = _two_tier(
        cfg, params, native=True)
    engines = [s._native_bridge.engine for s in (pre_srv, dec_srv)]
    try:
        want = np.asarray(generate(params, cfg, prompt, 4))[0]
        _stream_decode(pre_srv, prompt, 4)       # warm compiles first

        def ledgers():
            total = 0
            for eng in engines:
                total += sum(eng.telemetry()["data_plane_copies"]
                             .values())
            return total

        base = ledgers()
        with copy_audit.audit() as snap:
            toks, reason, _ = _stream_decode(pre_srv, prompt, 4)
            counts, _nb = snap()
        assert toks == want.tolist()
        assert reason == "finished"
        assert kv_stats()["ici_sessions"] >= 1
        assert sum(counts.values()) == 0, counts       # Python ledger
        assert ledgers() - base == 0                   # engine ledgers
    finally:
        pre_srv.stop()
        dec_srv.stop()

    # control arm: the forced shm lane admits exactly ONE staging
    # memcpy per page (2 layers x k/v = 4 pages) and nothing else
    if not shm_ring.shm_supported():
        return
    _reset_kv()
    shm_ring._reset_for_tests()
    pre_srv, dec_srv, _dec_lm, _pre, _dch = _two_tier(
        cfg, params, force_lane="shm", native=True)
    try:
        _stream_decode(pre_srv, prompt, 4)       # handshake + compiles
        with copy_audit.audit() as snap:
            toks, _reason, _ = _stream_decode(pre_srv, prompt, 4)
            counts, _nb = snap()
        assert toks == want.tolist()
        assert counts["stage_shm"] == 2 * cfg.depth, counts
        assert counts["ingest"] == counts["materialize"] == 0, counts
    finally:
        pre_srv.stop()
        dec_srv.stop()
        shm_ring._reset_for_tests()


def test_two_tier_over_native_stream_lane():
    """Handed-off sessions stream their tokens over the engine's
    kind-5 lane: the client's stream on the PREFILL tier is adopted
    natively, and the decode tier's batcher writes ride it."""
    require_native()
    _reset_kv()
    cfg, params, prompt = _setup()
    from brpc_tpu.kv import DecodeTierService, KvTransport, \
        PrefillService

    def native_opts(inline):
        o = ServerOptions()
        o.native = True
        o.usercode_inline = inline
        return o

    dec_lm = LMService(cfg=cfg, params=params, decode_slots=4)
    dec_srv = Server(native_opts(False))
    dec_srv.add_service(dec_lm, name="LM")
    dec_srv.add_service(DecodeTierService(dec_lm), name="KV")
    assert dec_srv.start("127.0.0.1:0") == 0
    dch = Channel()
    dch.init(str(dec_srv.listen_endpoint))
    # the prefill tier runs inline (kind-5 adoption requires the slim
    # lane) — its Decode handler's nested handoff RPC targets the
    # OTHER server's loops, so the nested wait cannot deadlock
    pre_svc = PrefillService(cfg=cfg, params=params, decode_channel=dch,
                             transport=KvTransport())
    pre_srv = Server(native_opts(True))
    pre_srv.add_service(pre_svc, name="LM")
    assert pre_srv.start("127.0.0.1:0") == 0
    try:
        _stream_decode(pre_srv, prompt, 4)          # compile warmup
        toks, reason, _ = _stream_decode(pre_srv, prompt, 6)
        want = np.asarray(generate(params, cfg, prompt, 6))[0]
        assert toks == want.tolist()
        assert reason == "finished"
        tele = pre_srv._native_bridge.engine.telemetry()
        # the handed-off session's tokens left through the PREFILL
        # engine's kind-5 chunk path (the decode tier's batcher writes
        # ride the adopted stream)
        assert tele["streams"]["chunks_out"] >= 6, tele["streams"]
    finally:
        pre_srv.stop()
        dec_srv.stop()


# ---------------------------------------------------------------------------
# Page lifecycle: leaks, generations, sweeps, drain
# ---------------------------------------------------------------------------

def test_page_leak_pin_after_1k_handoffs():
    """1000 export→describe→import→release cycles leave the page table
    exactly as found: zero outstanding pages, zero live fabric
    descriptors — the leak pin (bounded table = leaks surface fast)."""
    from brpc_tpu.ici.fabric import in_process_fabric
    from brpc_tpu.kv import process_kv_store
    from brpc_tpu.kv.pages import decode_desc
    _reset_kv()
    store = process_kv_store()
    fabric = in_process_fabric()
    base_desc = fabric.live_descriptors
    page = jnp.arange(1024, dtype=jnp.float32)
    for i in range(1000):
        handles = [store.export_array(page, 4096, owner=("kv", i))
                   for _ in range(4)]
        assert all(h is not None for h in handles)
        for h in handles[:2]:
            # imported half: the importer consumed the registration
            pid, gen, n = decode_desc(h.describe())
            got = store.import_page(pid, gen, n)
            assert got is page
        store.settle_handles(handles)
    assert store.outstanding() == 0
    assert fabric.live_descriptors == base_desc
    st = store.stats()
    assert st["exported"] == 4000 and st["imported"] == 2000


def test_generation_checked_double_free_and_stale_import():
    """The loud-failure matrix: double release raises; import after
    release raises; a RECYCLED page id under a new generation rejects
    the old descriptor (the shm_ring generation discipline)."""
    from brpc_tpu.kv import KvPageError, process_kv_store
    _reset_kv()
    store = process_kv_store()
    page = jnp.ones((8,), jnp.float32)
    h = store.export_array(page, 32)
    store.release(h.page_id, h.gen)
    with pytest.raises(KvPageError, match="double/stale"):
        store.release(h.page_id, h.gen)              # double free
    with pytest.raises(KvPageError, match="stale"):
        store.import_page(h.page_id, h.gen, 32)      # stale import
    # recycle the id: the OLD generation's descriptor must not resolve
    h2 = store.export_array(page, 32)
    assert h2.page_id == h.page_id and h2.gen != h.gen
    with pytest.raises(KvPageError, match="stale"):
        store.import_page(h.page_id, h.gen, 32)
    # double import of a live page is loud too
    assert store.import_page(h2.page_id, h2.gen, 32) is page
    with pytest.raises(KvPageError, match="already imported"):
        store.import_page(h2.page_id, h2.gen, 32)
    store.release(h2.page_id, h2.gen)
    assert store.outstanding() == 0


def test_stale_import_over_rpc_is_eresponse_never_empty_cache():
    """A handoff manifest naming already-settled pages must FAIL the
    RPC with ERESPONSE — the decode tier never seats a session on an
    empty cache and the batcher never sees it."""
    from brpc_tpu.kv import process_kv_store
    from brpc_tpu.kv.transport import (LANE_ICI, SessionManifest,
                                       encode_manifest, stream_auth)
    from brpc_tpu.models.transformer_lm import export_decode_cache
    _reset_kv()
    cfg, params, prompt = _setup()
    pre_srv, dec_srv, dec_lm, pre_svc, dch = _two_tier(cfg, params)
    try:
        # export a real session cache, then settle it (stale descs)
        from brpc_tpu.models.lm_service import bucketed_prefill
        cache1, ctx_len = bucketed_prefill(pre_svc._ensure_prefill(),
                                           cfg, prompt[0])
        pages = export_decode_cache(cfg, cache1)
        store = process_kv_store()
        handles = [store.export_array(a, n) for a, n in pages]
        descs = [h.describe() for h in handles]
        store.settle_handles(handles)
        steps_before = dec_lm.batcher().steps_run()
        client_stream = Stream()         # adoptable, never written
        try:
            man = SessionManifest(LANE_ICI, client_stream.id,
                                  stream_auth(client_stream.id),
                                  ctx_len, int(prompt[0][-1]), 4,
                                  dec_lm.model_fingerprint(), descs)
            cntl = Controller()
            cntl.timeout_ms = 30_000
            c = dch.call_method("KV.ImportSession",
                                encode_manifest(man), cntl=cntl)
            assert c.failed
            assert c.error_code == int(Errno.ERESPONSE), \
                (c.error_code, c.error_text)
            assert "kv_import_rejected" in c.error_text
            assert dec_lm.batcher().live_slots() == 0
            assert dec_lm.batcher().steps_run() == steps_before
        finally:
            client_stream.close()
    finally:
        pre_srv.stop()
        dec_srv.stop()


def test_forged_stream_adoption_rejected():
    """Stream ids are enumerable — a manifest naming another client's
    LIVE stream without the process-keyed adoption tag must be refused
    before any page resolves (no token injection into someone else's
    session)."""
    from brpc_tpu.kv import process_kv_store
    from brpc_tpu.kv.transport import (LANE_ICI, SessionManifest,
                                       encode_manifest)
    from brpc_tpu.models.lm_service import bucketed_prefill
    from brpc_tpu.models.transformer_lm import export_decode_cache
    _reset_kv()
    cfg, params, prompt = _setup()
    pre_srv, dec_srv, dec_lm, pre_svc, dch = _two_tier(cfg, params)
    try:
        cache1, ctx_len = bucketed_prefill(pre_svc._ensure_prefill(),
                                           cfg, prompt[0])
        store = process_kv_store()
        handles = [store.export_array(a, n)
                   for a, n in export_decode_cache(cfg, cache1)]
        victim = Stream()                # a live, adoptable stream
        try:
            man = SessionManifest(LANE_ICI, victim.id, b"\0" * 8,
                                  ctx_len, int(prompt[0][-1]), 4,
                                  dec_lm.model_fingerprint(),
                                  [h.describe() for h in handles])
            cntl = Controller()
            cntl.timeout_ms = 30_000
            c = dch.call_method("KV.ImportSession",
                                encode_manifest(man), cntl=cntl)
            assert c.failed
            assert "kv_stream_not_local" in c.error_text
            # the refusal ran BEFORE any page import: all still live
            assert store.outstanding() == len(handles)
            assert dec_lm.batcher().live_slots() == 0
        finally:
            victim.close()
            store.settle_handles(handles)
    finally:
        pre_srv.stop()
        dec_srv.stop()


def test_ambiguous_handoff_never_double_decodes():
    """A handoff failure that does NOT prove the decode tier never
    seated the session (timeout / transport death) must not fall back
    to local decode — two batchers on one stream is the at-most-once
    violation.  The session is refused with the named close reason
    even under fallback_local=True."""
    from brpc_tpu.kv import PrefillService
    from brpc_tpu.kv.transport import HandoffResult
    _reset_kv()
    cfg, params, prompt = _setup()
    pre_svc = PrefillService(cfg=cfg, params=params,
                             decode_channel=None, decode_slots=4)

    class _AmbiguousTransport:
        def handoff(self, *a, **kw):
            return HandoffResult(False, None, "kv_import_rejected",
                                 ambiguous=True)

    pre_svc.transport = _AmbiguousTransport()
    srv = Server()
    srv.add_service(pre_svc, name="LM")
    assert srv.start("127.0.0.1:0") == 0
    try:
        closed = []
        ch = Channel()
        ch.init(str(srv.listen_endpoint))
        cntl = Controller()
        cntl.timeout_ms = 60_000
        stream_create(cntl, StreamOptions(
            on_closed=lambda st: closed.append(st.close_reason)))
        c = ch.call_method("LM.Decode",
                           pack_generate_request(prompt, 4), cntl=cntl)
        assert c.failed
        assert c.error_code == int(Errno.EINTERNAL)
        deadline = time.time() + 10
        while not closed and time.time() < deadline:
            time.sleep(0.01)
        assert closed == ["kv_handoff_failed"], closed
        # the local batcher never saw the session
        assert pre_svc.batcher().live_slots() == 0
        assert pre_svc.batcher().steps_run() == 0
    finally:
        srv.stop()


def test_owner_sweep_on_socket_death():
    """Pages exported for a connection's session are swept when the
    socket dies before the handoff settles — and the swept pages'
    descriptors reject imports loudly afterwards."""
    from brpc_tpu.kv import (KvPageError, on_socket_closed,
                             outstanding_pages, process_kv_store)
    _reset_kv()
    store = process_kv_store()
    page = jnp.ones((16,), jnp.float32)
    owner = ("kv", 424242)
    handles = [store.export_array(page, 64, owner=owner)
               for _ in range(3)]
    other = store.export_array(page, 64, owner=("kv", 7))
    assert outstanding_pages() == 4
    on_socket_closed(owner)              # the Socket.release hook
    assert outstanding_pages() == 1      # the other conn's page stays
    for h in handles:
        with pytest.raises(KvPageError):
            store.import_page(h.page_id, h.gen, 64)
    store.release(other.page_id, other.gen)
    assert outstanding_pages() == 0


def test_drain_settles_outstanding_exported_pages():
    """The drain plane waits (deadline-bounded) for exported pages to
    settle: a late settle is seen inside the grace; an expired grace
    reports the residue instead of hanging."""
    from brpc_tpu.kv import drain_settle, process_kv_store
    _reset_kv()
    store = process_kv_store()
    page = jnp.ones((16,), jnp.float32)
    h = store.export_array(page, 64)
    # grace too short, nothing settles: residue reported, no hang
    t0 = time.monotonic()
    left = drain_settle(time.monotonic() + 0.15)
    assert left == 1
    assert time.monotonic() - t0 < 5.0
    # a settle landing inside the grace is observed
    threading.Timer(0.1, lambda: store.release(h.page_id, h.gen)).start()
    assert drain_settle(time.monotonic() + 5.0) == 0


# ---------------------------------------------------------------------------
# Named fallbacks — every ineligible shape, pinned
# ---------------------------------------------------------------------------

def _fallback_session(pre_srv, prompt, cfg, params, reason):
    """Run one session expecting a LOCAL fallback under ``reason``:
    tokens still monolithic-identical (the client never notices)."""
    from brpc_tpu.kv import kv_fallback_counters
    before = kv_fallback_counters()[reason]
    toks, close_reason, _ = _stream_decode(pre_srv, prompt, 5)
    want = np.asarray(generate(params, cfg, prompt, 5))[0]
    assert toks == want.tolist()
    assert close_reason == "finished"
    assert kv_fallback_counters()[reason] == before + 1


def test_fallback_no_decode_tier():
    """No decode channel configured: named local fallback, client
    unaffected."""
    from brpc_tpu.kv import PrefillService
    _reset_kv()
    cfg, params, prompt = _setup()
    pre_svc = PrefillService(cfg=cfg, params=params,
                             decode_channel=None, decode_slots=4)
    srv = Server()
    srv.add_service(pre_svc, name="LM")
    assert srv.start("127.0.0.1:0") == 0
    try:
        _fallback_session(srv, prompt, cfg, params,
                          "kv_no_decode_tier")
        assert pre_svc.batcher().steps_run() >= 5   # decoded LOCALLY
    finally:
        srv.stop()


def test_fallback_probe_failed_against_kv_less_peer():
    """A decode channel pointing at a server with no KV service: the
    probe fails once, the session decodes locally under the named
    reason."""
    from brpc_tpu.kv import PrefillService
    _reset_kv()
    cfg, params, prompt = _setup()
    plain = Server()
    plain.add_service(LMService(cfg=cfg, params=params), name="LM")
    assert plain.start("127.0.0.1:0") == 0
    ch = Channel()
    ch.init(str(plain.listen_endpoint))
    pre_svc = PrefillService(cfg=cfg, params=params, decode_channel=ch,
                             decode_slots=4)
    srv = Server()
    srv.add_service(pre_svc, name="LM")
    assert srv.start("127.0.0.1:0") == 0
    try:
        _fallback_session(srv, prompt, cfg, params,
                          "kv_probe_failed")
    finally:
        srv.stop()
        plain.stop()


def test_fallback_model_mismatch():
    """The decode tier serves a DIFFERENT model: the handoff is refused
    at the fingerprint check and the session decodes locally — pages
    never move under a wrong layout."""
    _reset_kv()
    cfg, params, prompt = _setup()
    cfg2 = LMConfig(vocab=64, dim=32, heads=4, depth=3, max_seq=32,
                    remat=False)
    params2 = init_params(jax.random.PRNGKey(9), cfg2)
    pre_srv, dec_srv, _dec_lm, _pre, _dch = _two_tier(
        cfg, params, decode_cfg=cfg2, decode_params=params2)
    try:
        _fallback_session(pre_srv, prompt, cfg, params,
                          "kv_model_mismatch")
    finally:
        pre_srv.stop()
        dec_srv.stop()


def test_fallback_stream_not_local():
    """A handoff naming a stream the decode tier cannot resolve falls
    back under kv_stream_not_local (the cross-process topology's named
    decline — never a silent empty session)."""
    from brpc_tpu.kv import KvTransport, kv_fallback_counters, \
        process_kv_store
    _reset_kv()
    cfg, params, prompt = _setup()
    pre_srv, dec_srv, _dec_lm, pre_svc, dch = _two_tier(cfg, params)
    try:
        from brpc_tpu.models.lm_service import bucketed_prefill
        from brpc_tpu.models.transformer_lm import export_decode_cache
        cache1, ctx_len = bucketed_prefill(pre_svc._ensure_prefill(),
                                           cfg, prompt[0])
        pages = export_decode_cache(cfg, cache1)
        tr = pre_svc.transport
        res = tr.handoff(dch, 999_999_999_999, ctx_len,
                         int(prompt[0][-1]), 4,
                         pre_svc.model_fingerprint(), pages)
        assert not res.ok
        assert res.reason == "kv_stream_not_local"
        assert kv_fallback_counters()["kv_stream_not_local"] == 1
        # the failed handoff settled its leases
        assert process_kv_store().outstanding() == 0
    finally:
        pre_srv.stop()
        dec_srv.stop()


def test_fallback_shm_unavailable_and_peer_remote():
    """Synthetic peer capabilities (the probe cache is the injection
    point): a same-host peer without shm demotes to the copy lane
    under kv_shm_unavailable; a remote-host peer without a transfer
    fabric demotes under kv_peer_remote — the handoff still completes
    (copy lane), the reason is named."""
    from brpc_tpu.kv import kv_fallback_counters
    from brpc_tpu.transport import shm_ring
    _reset_kv()
    cfg, params, prompt = _setup()
    want = np.asarray(generate(params, cfg, prompt, 5))[0]
    for peer, reason in (
            ((b"\0" * 16, shm_ring._host_token(), False),
             "kv_shm_unavailable"),
            ((b"\0" * 16, b"some-other-host", True),
             "kv_peer_remote")):
        pre_srv, dec_srv, _dec_lm, pre_svc, dch = _two_tier(cfg, params)
        try:
            # seed the probe cache with the synthetic peer capability
            pre_svc.transport._peers[dch] = (peer,
                                             time.monotonic() + 60.0)
            toks, close_reason, _ = _stream_decode(pre_srv, prompt, 5)
            assert toks == want.tolist()
            assert close_reason == "finished"
            assert kv_fallback_counters()[reason] >= 1
            from brpc_tpu.kv import kv_stats
            assert kv_stats()["copy_sessions"] >= 1
        finally:
            pre_srv.stop()
            dec_srv.stop()


def test_fallback_page_over_slot_and_ring_exhausted():
    """shm-lane sizing fallbacks: pages over the ring slot size (or a
    ring with too few slots) demote the handoff to the copy lane under
    their named reasons — tokens identical throughout."""
    from brpc_tpu.kv import kv_fallback_counters, kv_stats
    from brpc_tpu.transport import shm_ring
    if not shm_ring.shm_supported():
        pytest.skip("no shm support in sandbox")
    _reset_kv()
    cfg = LMConfig(vocab=64, dim=32, heads=4, depth=2, max_seq=64,
                   remat=False)                # 8KB pages
    params = init_params(jax.random.PRNGKey(0), cfg)
    prompt = np.asarray(jax.random.randint(jax.random.PRNGKey(1),
                                           (1, 8), 0, cfg.vocab,
                                           jnp.int32))
    want = np.asarray(generate(params, cfg, prompt, 4))[0]
    slot0 = get_flag("rpc_shm_slot_bytes")
    nslots0 = get_flag("rpc_shm_slots")
    for flag_kv, reason in ((("rpc_shm_slot_bytes", 4096),
                             "kv_page_over_slot"),
                            (("rpc_shm_slots", 1),
                             "kv_ring_exhausted")):
        shm_ring._reset_for_tests()
        set_flag(*flag_kv)
        pre_srv, dec_srv, _dec_lm, _pre, _dch = _two_tier(
            cfg, params, force_lane="shm")
        try:
            toks, close_reason, _ = _stream_decode(pre_srv, prompt, 4)
            assert toks == want.tolist()
            assert close_reason == "finished"
            assert kv_fallback_counters()[reason] >= 1, reason
            assert kv_stats()["copy_sessions"] >= 1
            assert shm_ring.outstanding_tx_slots() == 0
        finally:
            pre_srv.stop()
            dec_srv.stop()
            set_flag("rpc_shm_slot_bytes", slot0)
            set_flag("rpc_shm_slots", nslots0)
            shm_ring._reset_for_tests()


def test_fallback_pages_exhausted():
    """A full export table demotes to the copy lane under
    kv_pages_exhausted (backpressure, not an error)."""
    from brpc_tpu.kv import kv_fallback_counters, kv_stats
    from brpc_tpu.kv import pages as kv_pages
    _reset_kv()
    flag0 = get_flag("kv_pages")
    set_flag("kv_pages", 2)              # table smaller than one session
    try:
        cfg, params, prompt = _setup()
        want = np.asarray(generate(params, cfg, prompt, 4))[0]
        pre_srv, dec_srv, _dec_lm, _pre, _dch = _two_tier(cfg, params)
        try:
            toks, close_reason, _ = _stream_decode(pre_srv, prompt, 4)
            assert toks == want.tolist()
            assert close_reason == "finished"
            assert kv_fallback_counters()["kv_pages_exhausted"] == 1
            assert kv_stats()["copy_sessions"] == 1
            assert kv_pages.outstanding_pages() == 0   # demotion settled
        finally:
            pre_srv.stop()
            dec_srv.stop()
    finally:
        set_flag("kv_pages", flag0)
        kv_pages._reset_for_tests()


def test_fallback_disabled_flag():
    """kv_transfer_enabled=False: every handoff rides the copy lane
    under kv_disabled — correct, counted, reversible."""
    from brpc_tpu.kv import kv_fallback_counters, kv_stats
    _reset_kv()
    cfg, params, prompt = _setup()
    set_flag("kv_transfer_enabled", False)
    try:
        pre_srv, dec_srv, _dec_lm, _pre, _dch = _two_tier(cfg, params)
        try:
            toks, reason, _ = _stream_decode(pre_srv, prompt, 5)
            want = np.asarray(generate(params, cfg, prompt, 5))[0]
            assert toks == want.tolist()
            assert reason == "finished"
            assert kv_fallback_counters()["kv_disabled"] == 1
            assert kv_stats()["copy_sessions"] == 1
        finally:
            pre_srv.stop()
            dec_srv.stop()
    finally:
        set_flag("kv_transfer_enabled", True)


# ---------------------------------------------------------------------------
# Paged KV allocator (ISSUE 16): block-paged attention, cross-session
# prefix cache, host-tier eviction
# ---------------------------------------------------------------------------

KV_EVICT_PINS = ("kv_pool_exhausted", "kv_host_tier_full",
                 "kv_spill_drain_aborted")
PREFIX_EVENT_PINS = ("prefix_hit", "prefix_partial_hit", "prefix_miss",
                     "prefix_insert", "prefix_evict")


def test_paged_enums_match_pins():
    from brpc_tpu.kv.pages import (KV_EVICT_REASONS, PREFIX_CACHE_EVENTS,
                                   count_evict, count_prefix,
                                   kv_evict_counters,
                                   prefix_event_counters)
    assert KV_EVICT_REASONS == KV_EVICT_PINS
    assert PREFIX_CACHE_EVENTS == PREFIX_EVENT_PINS
    assert set(kv_evict_counters()) == set(KV_EVICT_PINS)
    assert set(prefix_event_counters()) == set(PREFIX_EVENT_PINS)
    with pytest.raises(AssertionError):
        count_evict("kv_some_new_evict_reason")
    with pytest.raises(AssertionError):
        count_prefix("prefix_some_new_event")


class _FakeStream:
    """Batcher-facing stream stub on the Python write lane (the
    batcher only touches closed/options/write/close/id/_native_tx)."""

    def __init__(self):
        self.closed = False
        self.close_reason = None
        self.tokens = []
        self.id = 0
        self._native_tx = None
        self.options = StreamOptions()

    def write(self, data):
        self.tokens.append(struct.unpack("<i", bytes(data))[0])
        return 0

    def close(self, reason=None):
        self.closed = True
        self.close_reason = reason


def _paged_run(bat, prompt, max_new, timeout=90.0):
    """One session through a paged batcher via a fake stream."""
    st = _FakeStream()
    bat.join(st, prompt, max_new)
    deadline = time.monotonic() + timeout
    while not st.closed and time.monotonic() < deadline:
        time.sleep(0.002)
    assert st.closed, "paged decode session never closed"
    return st


def _wait(pred, timeout=30.0, msg="condition never held"):
    deadline = time.monotonic() + timeout
    while not pred() and time.monotonic() < deadline:
        time.sleep(0.002)
    assert pred(), msg


def test_paged_decode_identity_and_prefix_hit_skips_prefill():
    """Block-paged attention is token-identical with the monolithic
    path, and a re-sent context ALIASES the cached pages: the second
    session runs NO prefill, copies ZERO bytes, and streams the same
    tokens."""
    from brpc_tpu.butil import copy_audit
    from brpc_tpu.kv import pages as kv_pages
    from brpc_tpu.models.lm_service import ContinuousBatcher
    _reset_kv()
    cfg, params, _ = _setup()
    prompt = np.asarray(jax.random.randint(jax.random.PRNGKey(3), (17,),
                                           0, cfg.vocab, jnp.int32))
    want = np.asarray(generate(params, cfg, prompt[None, :], 6))[0]
    bat = ContinuousBatcher(cfg, params, slots=4, paged=True, page=16)
    st1 = _paged_run(bat, prompt, 6)
    assert st1.tokens == want.tolist()
    assert st1.close_reason == "finished"
    assert bat.prefills_run == 1
    ev = kv_pages.prefix_event_counters()
    assert ev["prefix_miss"] == 1 and ev["prefix_insert"] == 1
    # the SAME context again: full-page prefix hit — prefill skipped,
    # the aliased pages move zero audited bytes
    with copy_audit.audit() as snap:
        st2 = _paged_run(bat, prompt, 6)
        counts, _nb = snap()
    assert st2.tokens == want.tolist()
    assert st2.close_reason == "finished"
    assert bat.prefills_run == 1                  # no new prefill
    assert kv_pages.prefix_event_counters()["prefix_hit"] == 1
    assert sum(counts.values()) == 0, counts      # aliasing copies nothing
    # sessions gone: only the prefix cache still holds pages
    st = bat.kv_stats()
    assert st["alloc"]["in_use"] == st["prefix"]["nodes"] == 1


def test_prefix_hit_partial_page_teacher_forced_identity():
    """A context whose FULL pages are all cached but whose tail spills
    past them: the hit aliases the covered page and the remainder
    catches up with teacher-forced steps — the emitted stream is
    identical with the uncached path (the big numerics risk of
    partial-page coverage)."""
    from brpc_tpu.kv import pages as kv_pages
    from brpc_tpu.models.lm_service import ContinuousBatcher
    _reset_kv()
    cfg, params, _ = _setup()
    base = np.asarray(jax.random.randint(jax.random.PRNGKey(5), (16,),
                                         0, cfg.vocab, jnp.int32))
    pa = np.concatenate([base, np.asarray([3, 9], np.int32)])
    pb = np.concatenate([base, np.asarray([7, 1, 4, 2, 8], np.int32)])
    want_b = np.asarray(generate(params, cfg, pb[None, :], 6))[0]
    bat = ContinuousBatcher(cfg, params, slots=4, paged=True, page=16)
    _paged_run(bat, pa, 4)            # seeds the shared prefix's page
    pf = bat.prefills_run
    st = _paged_run(bat, pb, 6)       # ctx 20: page cached, 4 forced
    assert st.tokens == want_b.tolist()
    assert st.close_reason == "finished"
    assert bat.prefills_run == pf     # covered prefix: no prefill
    # every FULL page matched -> classified a hit (the tail is never
    # shareable); the true partial classification is the test below
    assert kv_pages.prefix_event_counters()["prefix_hit"] == 1


def test_prefix_partial_hit_teacher_forced_identity():
    """A context sharing only its FIRST of two full pages with the
    cached prefix: partial hit — one page aliased, a full page plus
    tail caught up with teacher-forced steps, stream identical with
    the uncached path."""
    from brpc_tpu.kv import pages as kv_pages
    from brpc_tpu.models.lm_service import ContinuousBatcher
    _reset_kv()
    cfg = LMConfig(vocab=64, dim=32, heads=4, depth=2, max_seq=48,
                   remat=False)
    params = init_params(jax.random.PRNGKey(0), cfg)
    base = np.asarray(jax.random.randint(jax.random.PRNGKey(6), (16,),
                                         0, cfg.vocab, jnp.int32))
    ta = np.asarray(jax.random.randint(jax.random.PRNGKey(7), (17,),
                                       0, cfg.vocab, jnp.int32))
    tb = np.asarray(jax.random.randint(jax.random.PRNGKey(8), (17,),
                                       0, cfg.vocab, jnp.int32))
    pa = np.concatenate([base, ta])   # ctx 32: two full pages cached
    pb = np.concatenate([base, tb])   # ctx 32: only page 1 matches
    want_b = np.asarray(generate(params, cfg, pb[None, :], 4))[0]
    bat = ContinuousBatcher(cfg, params, slots=2, paged=True, page=16)
    _paged_run(bat, pa, 4)
    pf = bat.prefills_run
    st = _paged_run(bat, pb, 4)
    assert st.tokens == want_b.tolist()
    assert st.close_reason == "finished"
    assert bat.prefills_run == pf     # aliased page: no prefill
    assert kv_pages.prefix_event_counters()["prefix_partial_hit"] == 1


@pytest.mark.parametrize("lane", [None, "shm", "copy"],
                         ids=["auto-ici", "shm", "copy"])
def test_two_tier_into_paged_decode_tier_identical(lane):
    """The disagg handoff lands in a PAGED decode tier: the imported
    contiguous cache blockifies into allocator pages and the token
    stream stays monolithic-identical on every lane."""
    from brpc_tpu.kv import outstanding_pages
    if lane == "shm":
        from brpc_tpu.transport import shm_ring
        if not shm_ring.shm_supported():
            pytest.skip("no shm support in sandbox")
    _reset_kv()
    cfg, params, prompt = _setup()
    pre_srv, dec_srv, dec_lm, _pre, _dch = _two_tier(
        cfg, params, force_lane=lane,
        decode_lm_kw={"paged": True, "page": 16})
    try:
        toks, reason, _ = _stream_decode(pre_srv, prompt, 6)
        want = np.asarray(generate(params, cfg, prompt, 6))[0]
        assert toks == want.tolist()
        assert reason == "finished"
        bst = dec_lm.batcher().kv_stats()
        assert bst["paged"] and bst["steps"] >= 6
        assert bst["alloc"]["in_use"] == 0   # imported pages settled
        assert outstanding_pages() == 0
    finally:
        pre_srv.stop()
        dec_srv.stop()


def test_evict_resume_roundtrip_token_identity():
    """Host-tier eviction roundtrip: admitting B under a dry pool
    SPILLS A's private pages to host RAM and parks it; A resumes
    bit-exact once B's pages free — both streams monolithic-identical,
    nothing leaks."""
    from brpc_tpu.kv.pages import host_inflight_spills
    from brpc_tpu.models.lm_service import ContinuousBatcher
    _reset_kv()
    cfg, params, prompt = _setup()
    pa = prompt[0]
    pb = np.asarray(jax.random.randint(jax.random.PRNGKey(11), (8,),
                                       0, cfg.vocab, jnp.int32))
    want_a = np.asarray(generate(params, cfg, pa[None, :], 12))[0]
    want_b = np.asarray(generate(params, cfg, pb[None, :], 6))[0]
    # 2 usable pages (page 0 reserved): A's 2-page session fills the
    # pool; B's 1-page admit must spill A
    bat = ContinuousBatcher(cfg, params, slots=2, paged=True, page=16,
                            pages=3, host_slots=8, prefix=False)
    sta = _FakeStream()
    bat.join(sta, pa, 12)                 # pages_for(7, 12) = 2
    _wait(lambda: bat.live_slots() >= 1, msg="A never admitted")
    stb = _FakeStream()
    bat.join(stb, pb, 6)                  # pages_for(7, 6) = 1
    _wait(lambda: sta.closed and stb.closed, timeout=90.0,
          msg="spill/resume sessions never finished")
    assert sta.tokens == want_a.tolist()
    assert stb.tokens == want_b.tolist()
    assert sta.close_reason == stb.close_reason == "finished"
    assert bat.spills >= 1 and bat.resumes >= 1
    st = bat.kv_stats()
    assert st["alloc"]["in_use"] == 0
    assert st["host"]["free"] == 8        # every host slot returned
    assert host_inflight_spills() == 0


def test_pool_exhausted_closes_with_named_reason():
    """An unsatisfiable admit (no host tier to spill to) closes the
    stream under kv_pool_exhausted — backpressure with a name, never a
    partial grant."""
    from brpc_tpu.kv.pages import kv_evict_counters
    from brpc_tpu.models.lm_service import ContinuousBatcher
    _reset_kv()
    cfg, params, prompt = _setup()
    bat = ContinuousBatcher(cfg, params, slots=2, paged=True, page=16,
                            pages=2, host_slots=0, prefix=False)
    st = _paged_run(bat, prompt[0], 12)   # needs 2 pages, pool has 1
    assert st.close_reason == "kv_pool_exhausted"
    assert st.tokens == []
    assert kv_evict_counters()["kv_pool_exhausted"] == 1


def test_host_tier_full_closes_with_named_reason():
    """A spill that cannot fit in the host tier closes the ADMITTING
    stream under kv_host_tier_full; the would-be victim keeps decoding
    and stays token-identical."""
    from brpc_tpu.kv.pages import kv_evict_counters
    from brpc_tpu.models.lm_service import ContinuousBatcher
    _reset_kv()
    cfg, params, prompt = _setup()
    pa = prompt[0]
    pb = np.asarray(jax.random.randint(jax.random.PRNGKey(13), (8,),
                                       0, cfg.vocab, jnp.int32))
    want_a = np.asarray(generate(params, cfg, pa[None, :], 12))[0]
    # host tier holds ONE page; spilling A needs two
    bat = ContinuousBatcher(cfg, params, slots=2, paged=True, page=16,
                            pages=3, host_slots=1, prefix=False)
    sta = _FakeStream()
    bat.join(sta, pa, 12)
    _wait(lambda: bat.live_slots() >= 1, msg="A never admitted")
    stb = _paged_run(bat, pb, 12)         # 2 pages: must spill A, can't
    assert stb.close_reason == "kv_host_tier_full"
    assert kv_evict_counters()["kv_host_tier_full"] == 1
    _wait(lambda: sta.closed, timeout=90.0, msg="A never finished")
    assert sta.tokens == want_a.tolist()
    assert sta.close_reason == "finished"
    assert bat.kv_stats()["host"]["free"] == 1   # staged slot rolled back


def test_drain_counts_inflight_spills_and_aborts_at_expiry():
    """Server.drain's settle gauge: a host-tier spill in flight holds
    the drain open; grace expiry marks the pool aborted (named reason)
    instead of hanging or leaking the mid-evict pages."""
    from brpc_tpu.kv.pages import (HostPagePool, drain_settle,
                                   host_inflight_spills)
    _reset_kv()
    pool = HostPagePool(2, 64)
    assert pool.begin_spill()
    assert host_inflight_spills() == 1
    t0 = time.monotonic()
    left = drain_settle(time.monotonic() + 0.15)
    assert left == 1
    assert time.monotonic() - t0 < 5.0
    assert pool.abort_reason() == "kv_spill_drain_aborted"
    assert not pool.begin_spill()         # aborted pool refuses spills
    pool.end_spill()
    assert drain_settle(time.monotonic() + 1.0) == 0
    # a spill landing INSIDE the grace is observed
    pool2 = HostPagePool(2, 64)
    assert pool2.begin_spill()
    threading.Timer(0.1, pool2.end_spill).start()
    assert drain_settle(time.monotonic() + 5.0) == 0


def test_drain_abort_closes_parked_under_named_reason():
    """A parked (spilled) session at drain-abort time force-closes
    under kv_spill_drain_aborted and frees its host slots; the live
    session is untouched."""
    from brpc_tpu.kv.pages import kv_evict_counters
    from brpc_tpu.models.lm_service import ContinuousBatcher
    _reset_kv()
    cfg, params, prompt = _setup()
    pa = prompt[0]
    pb = np.asarray(jax.random.randint(jax.random.PRNGKey(17), (8,),
                                       0, cfg.vocab, jnp.int32))
    want_b = np.asarray(generate(params, cfg, pb[None, :], 20))[0]
    bat = ContinuousBatcher(cfg, params, slots=2, paged=True, page=16,
                            pages=3, host_slots=4, prefix=False)
    sta = _FakeStream()
    bat.join(sta, pa, 24)                 # 2 pages
    _wait(lambda: bat.live_slots() >= 1, msg="A never admitted")
    stb = _FakeStream()
    bat.join(stb, pb, 20)                 # 2 pages: spills A
    _wait(lambda: bat.spills >= 1, msg="A never spilled")
    # drain-grace expiry while A sits parked: the pool aborts, the
    # batcher closes A under the named reason between steps
    bat._host.drain_abort("kv_spill_drain_aborted")
    _wait(lambda: sta.closed, msg="parked session never closed")
    assert sta.close_reason == "kv_spill_drain_aborted"
    assert kv_evict_counters()["kv_spill_drain_aborted"] >= 1
    _wait(lambda: stb.closed, timeout=90.0, msg="B never finished")
    assert stb.tokens == want_b.tolist()
    assert stb.close_reason == "finished"
    st = bat.kv_stats()
    assert st["alloc"]["in_use"] == 0
    assert st["host"]["free"] == 4        # parked slots reclaimed


def test_allocator_and_host_pool_loud_double_free():
    """The loud-failure matrix for the allocator planes: double page
    release raises, aliasing a dead page raises, host-slot double free
    and stale fetch raise, an oversized spill raises."""
    from brpc_tpu.kv import KvPageError
    from brpc_tpu.kv.pages import HostPagePool, PageAllocator
    _reset_kv()
    a = PageAllocator(4, 16)
    pages = a.alloc(2)
    assert pages is not None and 0 not in pages   # page 0 reserved
    a.release(pages[0])
    with pytest.raises(KvPageError, match="double/stale"):
        a.release(pages[0])
    with pytest.raises(KvPageError, match="dead"):
        a.ref(pages[0])                   # aliasing a freed page
    # an aliased page survives the first release, frees on the last
    a.ref(pages[1])
    a.release(pages[1])
    assert a.refcount(pages[1]) == 1
    a.release(pages[1])
    assert a.in_use() == 0
    with pytest.raises(ValueError):
        PageAllocator(1, 16)              # garbage page + >= 1 real

    pool = HostPagePool(2, 64)
    h = pool.stage(np.arange(64, dtype=np.uint8))
    assert bytes(pool.fetch(h)) == bytes(range(64))
    pool.free(h)
    with pytest.raises(KvPageError, match="double/stale"):
        pool.free(h)
    with pytest.raises(KvPageError, match="stale"):
        pool.fetch(h)
    with pytest.raises(KvPageError, match="exceeds"):
        pool.stage(np.zeros(65, np.uint8))


def test_prefix_cache_refcounts_aliased_pages():
    """An aliased page never returns to the free list while any holder
    (session or cache) remains, and the last release frees it — the
    invariant the generation check turns into an assertion."""
    from brpc_tpu.kv.pages import PageAllocator, PrefixCache
    _reset_kv()
    a = PageAllocator(4, 4)
    cache = PrefixCache(a)
    toks = list(range(4))
    (pg,) = a.alloc(1)
    cache.insert(toks, [pg])              # the cache takes its own hold
    assert a.refcount(pg) == 2
    a.release(pg)                         # the prefilling session leaves
    assert a.refcount(pg) == 1            # cached page stays live
    pages, covered = cache.lookup(toks)
    assert pages == [pg] and covered == 4
    assert a.refcount(pg) == 2            # the hit session's hold
    a.release(pg)
    assert cache.evict_all() == 1         # last holder: page frees
    assert a.in_use() == 0
    pages, covered = cache.lookup(toks)   # cold again
    assert pages == [] and covered == 0


def test_paged_leak_pin_1k_sessions_alias_and_evict():
    """1000 sessions over two alternating contexts on a paged batcher:
    every stream is monolithic-identical (aliased pages included), and
    afterwards the allocator holds exactly the prefix cache's pages —
    evict_all returns the pool to empty.  The alias/evict leak pin."""
    from brpc_tpu.kv import pages as kv_pages
    from brpc_tpu.models.lm_service import ContinuousBatcher
    _reset_kv()
    cfg, params, _ = _setup()
    pa = np.asarray(jax.random.randint(jax.random.PRNGKey(21), (17,),
                                       0, cfg.vocab, jnp.int32))
    pb = np.asarray(jax.random.randint(jax.random.PRNGKey(22), (17,),
                                       0, cfg.vocab, jnp.int32))
    want = {0: np.asarray(generate(params, cfg, pa[None, :], 2))[0],
            1: np.asarray(generate(params, cfg, pb[None, :], 2))[0]}
    bat = ContinuousBatcher(cfg, params, slots=4, paged=True, page=16)
    streams = []
    for i in range(1000):
        st = _FakeStream()
        streams.append((i % 2, st))
        bat.join(st, pa if i % 2 == 0 else pb, 2)
    _wait(lambda: all(st.closed for _k, st in streams), timeout=300.0,
          msg="1k paged sessions never drained")
    for k, st in streams:
        assert st.close_reason == "finished"
        assert st.tokens == want[k].tolist()
    ev = kv_pages.prefix_event_counters()
    assert ev["prefix_hit"] + ev["prefix_partial_hit"] >= 990
    st = bat.kv_stats()
    held = st["prefix"]["nodes"]
    assert st["alloc"]["in_use"] == held     # only the cache holds pages
    bat._prefix.evict_all()
    assert bat.kv_stats()["alloc"]["in_use"] == 0
    assert kv_pages.prefix_event_counters()["prefix_evict"] >= held


def test_strict_tier_closes_with_named_reason():
    """fallback_local=False: a failed handoff REFUSES the session —
    stream closed with the named kv_handoff_failed reason, EINTERNAL
    on the RPC (capacity-planned tiers fail loudly, never absorb)."""
    from brpc_tpu.kv import PrefillService
    _reset_kv()
    cfg, params, prompt = _setup()
    pre_svc = PrefillService(cfg=cfg, params=params,
                             decode_channel=None,
                             fallback_local=False, decode_slots=4)
    srv = Server()
    srv.add_service(pre_svc, name="LM")
    assert srv.start("127.0.0.1:0") == 0
    try:
        closed = []
        ch = Channel()
        ch.init(str(srv.listen_endpoint))
        cntl = Controller()
        cntl.timeout_ms = 60_000
        stream_create(cntl, StreamOptions(
            on_closed=lambda st: closed.append(st.close_reason)))
        c = ch.call_method("LM.Decode",
                           pack_generate_request(prompt, 4), cntl=cntl)
        assert c.failed
        assert c.error_code == int(Errno.EINTERNAL)
        assert "kv_no_decode_tier" in c.error_text
        deadline = time.time() + 10
        while not closed and time.time() < deadline:
            time.sleep(0.01)
        assert closed == ["kv_handoff_failed"], closed
        assert pre_svc.batcher().live_slots() == 0
    finally:
        srv.stop()
