"""HPACK + h2 session unit tests (RFC 7541/7540 vectors + loopback).
The heavyweight conformance check is tests/test_grpc_interop.py (real
grpcio as the oracle); these pin the primitives."""

import pytest

from brpc_tpu.protocol.hpack import (Decoder, Encoder, HpackError,
                                     decode_int, encode_int,
                                     huffman_decode, huffman_encode)
from brpc_tpu.protocol.h2_session import PREFACE, H2Session


def test_hpack_integer_rfc_examples():
    # RFC 7541 C.1: 10 in 5-bit prefix; 1337 in 5-bit prefix
    assert encode_int(10, 5) == b"\x0a"
    assert encode_int(1337, 5) == b"\x1f\x9a\x0a"
    assert decode_int(b"\x0a", 0, 5) == (10, 1)
    assert decode_int(b"\x1f\x9a\x0a", 0, 5) == (1337, 3)


def test_huffman_rfc_vectors():
    # RFC 7541 C.4.1-C.4.3
    assert huffman_encode(b"www.example.com").hex() == \
        "f1e3c2e5f23a6ba0ab90f4ff"
    assert huffman_encode(b"no-cache").hex() == "a8eb10649cbf"
    assert huffman_decode(bytes.fromhex("25a849e95ba97d7f")) == \
        b"custom-key"
    assert huffman_decode(bytes.fromhex("25a849e95bb8e8b4bf")) == \
        b"custom-value"


def test_huffman_roundtrip_all_bytes():
    data = bytes(range(256)) * 3
    assert huffman_decode(huffman_encode(data)) == data


def test_huffman_bad_padding_rejected():
    with pytest.raises(HpackError):
        huffman_decode(b"\x00")      # '0' bits of padding are invalid


def test_hpack_dynamic_table_shrinks_repeat_headers():
    e, d = Encoder(), Decoder()
    hs = [(":status", "200"), ("x-long-header-name", "v" * 64)]
    w1 = e.encode(hs)
    w2 = e.encode(hs)
    assert d.decode(w1) == hs
    assert d.decode(w2) == hs
    assert len(w2) < len(w1) // 4        # fully indexed second time


def test_hpack_sensitive_headers_never_indexed():
    e, d = Encoder(), Decoder()
    hs = [("authorization", "Bearer tok")]
    w1 = e.encode(hs)
    w2 = e.encode(hs)
    assert len(w2) >= len(w1) - 1        # no dynamic-table win
    assert d.decode(w1) == hs and d.decode(w2) == hs


def test_h2_session_loopback_request_response():
    client = H2Session(is_server=False)
    server = H2Session(is_server=True)
    client.start()

    sid = client.next_stream_id()
    client.send_headers(sid, [(":method", "POST"), (":path", "/x")])
    client.send_data(sid, b"hello", end_stream=True)

    events = server.feed(client.take_output())
    kinds = [e[0] for e in events]
    assert "headers" in kinds and "data" in kinds
    hev = next(e for e in events if e[0] == "headers")
    assert (":path", "/x") in hev[2]
    dev = next(e for e in events if e[0] == "data")
    assert dev[2] == b"hello" and dev[3] is True

    server.send_headers(sid, [(":status", "200")])
    server.send_data(sid, b"world", end_stream=True)
    revents = client.feed(server.take_output())
    assert any(e[0] == "data" and e[2] == b"world" for e in revents)


def test_h2_flow_control_blocks_and_resumes():
    import struct

    from brpc_tpu.protocol import h2_session as h2

    client = H2Session(is_server=False)
    client.start()
    # pretend the peer acked settings and left the default 64KB windows
    sid = client.next_stream_id()
    client.send_headers(sid, [(":method", "POST"), (":path", "/big")])
    client.take_output()
    big = bytes(200_000)                 # > 65535 default window
    client.send_data(sid, big, end_stream=True)
    sent1 = client.take_output()
    assert 0 < len(sent1) < len(big) + 1000   # clipped at the window
    # grant more connection+stream window: the rest flushes
    upd = struct.pack(">I", 150_000)
    client.feed(b"")                     # no-op
    client._on_frame(h2.F_WINDOW_UPDATE, 0, 0, upd, [])
    client._on_frame(h2.F_WINDOW_UPDATE, 0, sid, upd, [])
    sent2 = client.take_output()
    total_payload = sum(len(f) for f in (sent1, sent2))
    assert total_payload > len(big)      # everything (plus frame headers)


def test_h2_ping_is_acked():
    server = H2Session(is_server=True)
    events = server.feed(PREFACE)
    server.take_output()
    import struct
    ping = struct.pack(">I", 8)[1:] + bytes([0x6, 0x0]) + \
        struct.pack(">I", 0) + b"12345678"
    events = server.feed(ping)
    assert ("ping", b"12345678") in events
    out = server.take_output()
    assert b"12345678" in out            # PING ACK echoed
