"""Zero-copy tensor data plane (ISSUE 6).

Three invariant families, asserted rather than claimed:

1. **shm attachment lane** — same-host attachments ≥ the threshold ride
   a ``(ring, slot, offset, len)`` descriptor through a ring negotiated
   at handshake; echo-class responses re-describe the request's slot
   (zero data motion); every ineligible shape falls back to the byte
   lane with a NAMED reason and an unperturbed wire.
2. **copy counts** — ``engine.telemetry()['data_plane_copies']`` plus
   the Python-side ``copy_audit`` read ZERO for eligible 1MB
   attachments on the raw, full-controller and shm lanes (the byte
   lane's one admitted engine copy is the bounded ``ingest_spill``
   buffered-prefix move; the shm lane's is its ONE staging memcpy).
3. **resource discipline** — ring slots return after completion (1k-call
   soak), and file-backed blocks spill via sendfile on a TCP lane.
"""

import os
import socket
import struct
import threading

import pytest

from brpc_tpu.butil import copy_audit
from brpc_tpu.butil.flags import get_flag, set_flag
from brpc_tpu.butil.iobuf import IOBuf
from brpc_tpu.client import Channel, ChannelOptions, Controller
from brpc_tpu.server import Server, ServerOptions, Service
from brpc_tpu.server.service import raw_method
from brpc_tpu.transport import shm_ring

# tier-1 discipline: shm tests skip (not fail) in sandboxes without a
# writable tmpfs/mmap path (gVisor images without /dev/shm)
shm_required = pytest.mark.skipif(
    not shm_ring.shm_supported(),
    reason="no tmpfs/mmap shm support in this sandbox")

_FLAGS = ("rpc_shm_data_plane", "rpc_shm_threshold",
          "rpc_shm_slot_bytes", "rpc_shm_slots")

ATT_1MB = bytes(range(256)) * 4096          # patterned, not zeros
ATT_300K = (b"\x5a" + bytes(range(255))) * 1200


@pytest.fixture(autouse=True)
def _shm_env():
    saved = {k: get_flag(k) for k in _FLAGS}
    shm_ring._reset_for_tests()
    copy_audit.reset()
    yield
    for k, v in saved.items():
        set_flag(k, v)
    shm_ring._reset_for_tests()


class DataSvc(Service):
    @raw_method
    def EchoRaw(self, payload, attachment):
        return bytes(payload) or b"ok", attachment

    def Echo(self, cntl, request):
        cntl.response_attachment.append_iobuf(cntl.request_attachment)
        return b"done"

    def Gen(self, cntl, request):
        # fresh (non-aliasing) response attachment: exercises response
        # STAGING (our ring, after the peer maps it) instead of the
        # echo re-describe path
        cntl.response_attachment.append_user_data(ATT_300K)
        return b"gen"

    def Bad(self, cntl, request):
        # large eligible attachment + unserializable response object:
        # the error downgrade must not leak a staged response slot
        cntl.response_attachment.append_user_data(ATT_300K)
        return 12345


def _server(native=True):
    opts = ServerOptions()
    opts.native = native
    opts.usercode_inline = native
    srv = Server(opts)
    srv.add_service(DataSvc(), name="D")
    assert srv.start("127.0.0.1:0") == 0
    return srv


def _channel(srv):
    co = ChannelOptions()
    co.connection_type = "pooled"
    ch = Channel(co)
    ch.init(str(srv.listen_endpoint))
    return ch


def _cntl_echo(ch, att):
    cntl = Controller()
    cntl.timeout_ms = 10_000
    cntl.request_attachment = IOBuf(att)
    r = ch.call_method("D.Echo", b"x", cntl=cntl)
    assert not r.failed, (r.error_code, r.error_text)
    return r.response_attachment.to_bytes()


# ---------------------------------------------------------------------------
# satellite 1: IOBuf large read-only views append by reference
# ---------------------------------------------------------------------------

def test_iobuf_large_readonly_view_appends_by_reference():
    data = bytes(200_000)
    mv = memoryview(data)
    buf = IOBuf(mv)
    assert buf.backing_block_count == 1
    assert buf._refs[0][0].data is mv          # block identity: no copy

    # the tpu_std response-serialization path takes the same fast path
    from brpc_tpu.protocol.tpu_std import serialize_payload
    out = serialize_payload(mv)
    assert out._refs[0][0].data is mv

    # a WRITABLE view must still copy (storage could mutate under us)
    w = memoryview(bytearray(200_000))
    b2 = IOBuf(w)
    assert b2.backing_block_count > 1 or b2._refs[0][0].data is not w
    assert b2.to_bytes() == bytes(200_000)

    # a READ-ONLY view over MUTABLE storage copies too: readonly blocks
    # writes through the view, not through the owner — aliasing it
    # would put corrupted bytes on a backlogged wire if the owner
    # mutates after append (append keeps copy semantics; owners of a
    # no-mutate contract attach explicitly via append_user_data)
    src = bytearray(200_000)
    ro = memoryview(src).toreadonly()
    b3 = IOBuf(ro)
    assert b3.backing_block_count > 1 or b3._refs[0][0].data is not ro
    src[0] = 0xFF                               # owner mutates...
    assert b3.to_bytes()[0] == 0                # ...the IOBuf is immune

    # sub-block sizes still pack into pool blocks (no behavior change)
    small = IOBuf(memoryview(b"x" * 100))
    assert small.to_bytes() == b"x" * 100


def test_copy_audit_counts_ingest():
    with copy_audit.audit() as snap:
        IOBuf(bytearray(100_000))              # bytearray: must copy
        counts, nbytes = snap()
    assert counts["ingest"] >= 1
    assert nbytes["ingest"] >= 100_000


# ---------------------------------------------------------------------------
# shm lane: negotiation, echo-by-reference, response staging
# ---------------------------------------------------------------------------

@shm_required
@pytest.mark.parametrize("native", [True, False],
                         ids=["native-server", "py-server"])
def test_shm_lane_engages_after_handshake(native):
    if native:
        from conftest import require_native
        require_native()
    srv = _server(native)
    try:
        ch = _channel(srv)
        for i in range(4):
            body, ratt = ch.call_raw("D.EchoRaw", b"p", ATT_1MB,
                                     timeout_ms=10_000)
            assert bytes(body) == b"p"
            assert bytes(ratt) == ATT_1MB, f"call {i}"
        st = shm_ring.shm_stats()
        # call 1 = handshake (bytes); calls 2-4 stage + echo by reference
        assert st["staged"] == 3
        assert st["desc_reused"] == 3
        assert st["resolved"] >= 6             # server + client resolves
        fb = {k: v for k, v in shm_ring.shm_fallback_counters().items()
              if v}
        assert set(fb) <= {"shm_handshake", "shm_peer_no_cap"}, fb
    finally:
        srv.stop()


@shm_required
def test_shm_controller_lane_and_response_staging():
    srv = _server(native=False)
    try:
        ch = _channel(srv)
        for _ in range(3):
            assert _cntl_echo(ch, ATT_1MB) == ATT_1MB
        assert shm_ring.shm_stats()["desc_reused"] >= 2

        # non-aliasing response attachment: server stages into ITS ring
        # once the client has acked the mapping
        for _ in range(3):
            cntl = Controller()
            cntl.timeout_ms = 10_000
            r = ch.call_method("D.Gen", b"x", cntl=cntl)
            assert not r.failed, (r.error_code, r.error_text)
            assert r.response_attachment.to_bytes() == ATT_300K
        st = shm_ring.shm_stats()
        assert st["staged"] >= 4     # request stagings + response stagings

        # response slots recycle when the RESPONSE BUFFER is dropped
        # (finalizer-bound settle), NOT at the next request on the
        # connection — a concurrent caller issuing the next request
        # must not recycle a slot whose view another thread still
        # holds.  While the last Gen response is alive its slot stays
        # allocated even across another call:
        ring = shm_ring.process_tx_ring()
        held_before = ring.nslots - ring.free_count()
        assert held_before >= 1                # the live Gen response
        cntl2 = Controller()
        cntl2.timeout_ms = 10_000
        r2 = ch.call_method("D.Echo", b"drain", cntl=cntl2)
        assert not r2.failed
        assert ring.nslots - ring.free_count() >= 1   # still held
        del r, cntl, r2, cntl2                 # drop every response
        import gc
        gc.collect()
        assert ring.free_count() == ring.nslots
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# named fallbacks: every ineligible shape stays on the byte lane,
# byte-identically, under exactly one named reason (no "unknown")
# ---------------------------------------------------------------------------

def _fb(reason):
    return shm_ring.shm_fallback_counters()[reason]


@shm_required
def test_fallback_under_threshold():
    srv = _server(native=False)
    try:
        ch = _channel(srv)
        small = b"s" * 1024
        before = _fb("shm_under_threshold")
        r0 = shm_ring.shm_stats()["resolved"]
        body, ratt = ch.call_raw("D.EchoRaw", b"p", small,
                                 timeout_ms=10_000)
        assert bytes(ratt) == small
        assert _fb("shm_under_threshold") == before + 1
        assert shm_ring.shm_stats()["resolved"] == r0   # pure byte lane
    finally:
        srv.stop()


@shm_required
def test_fallback_over_slot():
    set_flag("rpc_shm_slot_bytes", 256 * 1024)   # 1MB att > 256KB slot
    srv = _server(native=False)
    try:
        ch = _channel(srv)
        before = _fb("shm_over_slot")
        _, ratt = ch.call_raw("D.EchoRaw", b"p", ATT_1MB,
                              timeout_ms=10_000)
        assert bytes(ratt) == ATT_1MB
        assert _fb("shm_over_slot") >= before + 1
    finally:
        srv.stop()


@shm_required
def test_fallback_ring_exhausted():
    srv = _server(native=False)
    try:
        ch = _channel(srv)
        # complete the handshake first (two calls), then drain the
        # deferred echo-slot free with an attachment-less call so the
        # hold-all-slots step below really empties the ring
        for _ in range(2):
            _, ratt = ch.call_raw("D.EchoRaw", b"p", ATT_1MB,
                                  timeout_ms=10_000)
        ch.call_raw("D.EchoRaw", b"drain", b"", timeout_ms=10_000)
        ring = shm_ring.process_tx_ring()
        held = []
        while True:                            # drain every free slot
            s = ring.alloc(owner="test")
            if s is None:
                break
            held.append(s)
        before = _fb("shm_ring_exhausted")
        _, ratt = ch.call_raw("D.EchoRaw", b"p", ATT_1MB,
                              timeout_ms=10_000)
        assert bytes(ratt) == ATT_1MB          # byte lane, correct
        # client request half AND server response half (same-process
        # shared ring) each count once
        assert _fb("shm_ring_exhausted") == before + 2
        for s in held:
            ring.free(s)
    finally:
        srv.stop()


@shm_required
def test_fallback_peer_without_capability(monkeypatch):
    # the peer never maps our ring (capability-less): the offer is
    # answered plain, the client stops offering, and every later
    # eligible attachment counts shm_peer_no_cap — still byte-correct
    monkeypatch.setattr(shm_ring, "attach_spec",
                        lambda spec: shm_ring.count_fallback(
                            "shm_attach_failed") or None)
    srv = _server(native=False)
    try:
        ch = _channel(srv)
        for _ in range(2):
            _, ratt = ch.call_raw("D.EchoRaw", b"p", ATT_1MB,
                                  timeout_ms=10_000)
            assert bytes(ratt) == ATT_1MB
        before = _fb("shm_peer_no_cap")
        _, ratt = ch.call_raw("D.EchoRaw", b"p", ATT_1MB,
                              timeout_ms=10_000)
        assert bytes(ratt) == ATT_1MB
        # counted at least on the client request half (the server's
        # response half counts its own peer_no_cap per echo response)
        assert _fb("shm_peer_no_cap") >= before + 1
        assert shm_ring.shm_stats()["staged"] == 0   # never left bytes
    finally:
        srv.stop()


def test_fallback_disabled_flag():
    set_flag("rpc_shm_data_plane", False)
    srv = _server(native=False)
    try:
        ch = _channel(srv)
        before = _fb("shm_disabled")
        _, ratt = ch.call_raw("D.EchoRaw", b"p", ATT_1MB,
                              timeout_ms=10_000)
        assert bytes(ratt) == ATT_1MB
        assert _fb("shm_disabled") == before + 1
        assert shm_ring.shm_stats()["staged"] == 0
    finally:
        srv.stop()


@shm_required
def test_fallback_multi_attempt():
    """A backup/retry attempt (an earlier attempt's descriptor may
    still be live on the wire) declines the shm lane under its named
    reason — an early slot settle could recycle a slot an unread
    descriptor still points at."""
    srv = _server(native=False)
    try:
        ch = _channel(srv)
        for _ in range(2):                     # complete the handshake
            ch.call_raw("D.EchoRaw", b"p", ATT_1MB, timeout_ms=10_000)

        class _Sock:                           # negotiated socket stub
            id = 999
        sock = _Sock()
        st = shm_ring.sock_state(sock)
        st.offered = st.tx_ok = True
        before = _fb("shm_multi_attempt")
        extra, wire_att, slot, offered = shm_ring.client_prepare(
            sock, ATT_1MB, multi_attempt=True)
        assert wire_att is not None            # stays on the byte lane
        assert slot is None and not offered
        assert _fb("shm_multi_attempt") == before + 1
    finally:
        srv.stop()


@shm_required
def test_reoffer_after_lost_offer():
    """A lost offer response (transport death of the offer-carrying
    call) must not disable the lane for the connection's life: after
    _REOFFER_AFTER unanswered eligible calls the offer is re-sent."""
    ring = shm_ring.process_tx_ring()
    assert ring is not None

    class _Sock:
        id = 1001
    sock = _Sock()
    # first eligible call carries the offer
    _, _, slot, offered = shm_ring.client_prepare(sock, ATT_1MB)
    assert offered and slot is None
    # the response never arrives (no accept, no refusal): eligible
    # calls keep falling back under shm_handshake...
    for _ in range(shm_ring._REOFFER_AFTER - 1):
        _, _, slot, offered = shm_ring.client_prepare(sock, ATT_1MB)
        assert not offered and slot is None
    # ...then the counter trips and the NEXT call re-offers
    _, _, slot, offered = shm_ring.client_prepare(sock, ATT_1MB)
    assert not offered                         # the tripping call itself
    _, _, slot, offered = shm_ring.client_prepare(sock, ATT_1MB)
    assert offered, "offer was never re-sent after loss"
    # a peer that REFUSED stays refused: no re-offer churn
    st = shm_ring.sock_state(sock)
    st.peer_refused = True
    for _ in range(shm_ring._REOFFER_AFTER + 2):
        _, _, slot, offered = shm_ring.client_prepare(sock, ATT_1MB)
        assert not offered


@shm_required
def test_generation_checked_free():
    """A stale settle (timed-out call whose slot was swept by the dead
    connection's free_owner and re-allocated) must not free the new
    tenant's slot."""
    ring = shm_ring.ShmRing(64 * 1024, 2)
    try:
        s1 = ring.alloc(owner=("req", 1))
        g1 = ring.gen_of(s1)
        # the connection dies: owner sweep reclaims the slot
        assert ring.free_owner(("req", 1)) == 1
        # a live call re-allocates the same slot index
        s2 = ring.alloc(owner=("req", 2))
        while s2 != s1:                        # force the same index
            other = s2
            s2 = ring.alloc(owner=("req", 2))
            ring.free(other)
        free_before = ring.free_count()
        ring.free(s1, g1)                      # the stale settle fires
        assert ring.free_count() == free_before, \
            "stale generation freed a live slot"
        ring.free(s2, ring.gen_of(s2))         # the real settle works
        assert ring.free_count() == free_before + 1
    finally:
        ring.close()


@shm_required
def test_serialize_failure_does_not_leak_response_slot():
    """Response staging is deferred past serialization: a handler whose
    response object fails serialize_payload must not strand a staged
    tx-ring slot behind its error frame."""
    import gc
    srv = _server(native=False)
    try:
        ch = _channel(srv)
        for _ in range(2):                     # handshake + mapping ack
            _cntl_echo(ch, ATT_1MB)
        ring = shm_ring.process_tx_ring()
        for _ in range(ring.nslots + 2):       # > nslots: a leak would
            cntl = Controller()                # exhaust the ring
            cntl.timeout_ms = 10_000
            r = ch.call_method("D.Bad", b"x", cntl=cntl)
            assert r.failed and "serialization" in r.error_text
        del r, cntl
        gc.collect()
        assert ring.free_count() == ring.nslots
    finally:
        srv.stop()


@shm_required
def test_unresolvable_response_descriptor_fails_loudly():
    """A response descriptor naming an unknown ring must surface as an
    error (never 'success' with a silently empty attachment), and the
    staged request lease still settles."""
    from brpc_tpu.protocol.meta import RpcMeta

    ring = shm_ring.process_tx_ring()
    assert ring is not None

    class _Sock:
        id = 1002
    sock = _Sock()
    slot = ring.alloc(owner=("req", sock.id))
    lease = (slot, ring.gen_of(slot))
    free_before = ring.free_count()
    meta = RpcMeta()
    meta.shm_desc = shm_ring.encode_desc(b"\xde\xad\xbe\xef\xde\xad"
                                         b"\xbe\xef", 0, 0, 1024)
    with pytest.raises(shm_ring.ShmDescriptorError):
        shm_ring.client_on_response_meta(sock, meta, staged_slot=lease)
    assert ring.free_count() == free_before + 1   # lease settled


def test_no_unknown_fallback_bucket():
    assert "unknown" not in shm_ring.FALLBACK_REASONS
    assert set(shm_ring.shm_fallback_counters()) \
        == set(shm_ring.FALLBACK_REASONS)
    with pytest.raises(AssertionError):
        shm_ring.count_fallback("something_unnamed")


@shm_required
def test_wire_bytes_identical_for_ineligible_shape():
    """Adversarial wire comparison (test_slim_dispatch style): the raw
    response bytes for an under-threshold attachment are identical
    whether the shm plane is on or off — ineligibility must not perturb
    the wire."""
    from brpc_tpu.protocol.meta import (TAG_METHOD, TAG_SERVICE,
                                        TLV_ATTACHMENT, TLV_CORRELATION,
                                        encode_tlv)

    def exchange(port):
        att = b"A" * 4096
        payload = b"pp"
        mb = (TLV_CORRELATION + struct.pack("<Q", 7)
              + TLV_ATTACHMENT + struct.pack("<I", len(att))
              + encode_tlv(TAG_SERVICE, b"D")
              + encode_tlv(TAG_METHOD, b"EchoRaw"))
        frame = (b"TRPC"
                 + struct.pack("<II",
                               len(mb) + len(payload) + len(att), len(mb))
                 + mb + payload + att)
        s = socket.create_connection(("127.0.0.1", port), timeout=10)
        try:
            s.sendall(frame)
            buf = b""
            while len(buf) < 12:
                buf += s.recv(65536)
            body, _meta = struct.unpack_from("<II", buf, 4)
            while len(buf) < 12 + body:
                buf += s.recv(65536)
            return buf[:12 + body]
        finally:
            s.close()

    srv = _server(native=False)
    try:
        port = srv.listen_endpoint.port
        set_flag("rpc_shm_data_plane", True)
        with_shm = exchange(port)
        set_flag("rpc_shm_data_plane", False)
        without = exchange(port)
        assert with_shm == without
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# the zero-copy invariant, pinned by counters (raw / cntl / shm matrix)
# ---------------------------------------------------------------------------

def _dp(eng):
    return dict(eng.telemetry()["data_plane_copies"])


@pytest.mark.parametrize("lane", ["raw", "cntl", "shm"])
def test_data_plane_copies_zero_for_eligible_1mb(lane):
    from conftest import require_native
    require_native()
    if lane == "shm" and not shm_ring.shm_supported():
        pytest.skip("no shm support in this sandbox")
    if lane != "shm":
        set_flag("rpc_shm_data_plane", False)
    srv = _server(native=True)
    try:
        eng = srv._native_bridge.engine
        ch = _channel(srv)

        def one():
            if lane == "cntl":
                cntl = Controller()
                cntl.timeout_ms = 10_000
                cntl.request_attachment = IOBuf(ATT_1MB)
                r = ch.call_method("D.Echo", b"x", cntl=cntl)
                assert not r.failed, (r.error_code, r.error_text)
                # length only inside the audited window — to_bytes IS a
                # materialization and would charge the test to the lane
                assert len(r.response_attachment) == len(ATT_1MB)
            else:
                _, ratt = ch.call_raw("D.EchoRaw", b"p", ATT_1MB,
                                      timeout_ms=10_000)
                assert len(ratt) == len(ATT_1MB)

        if lane == "cntl":
            assert _cntl_echo(ch, ATT_1MB) == ATT_1MB  # full correctness
        for _ in range(3):
            one()                       # warmup + shm handshake
        base = _dp(eng)
        with copy_audit.audit() as snap:
            for _ in range(5):
                one()
            counts, _nb = snap()
        delta = {k: v - base[k] for k, v in _dp(eng).items()}
        # the engine must copy payload bytes NOWHERE on these paths:
        # not at ingest, not for a shim call, not at serialization.
        # (ingest_spill — the bounded ≤inbuf buffered-prefix move at
        # the direct-read rendezvous — is the byte lane's one admitted
        # engine-side move and is absent on the shm lane.)
        assert delta["ingest"] == 0, delta
        assert delta["shim"] == 0, delta
        assert delta["serialize"] == 0, delta
        if lane == "shm":
            assert delta["ingest_spill"] == 0, delta
        # Python side: zero ingest/materialize/gather at tensor scale;
        # the shm lane admits exactly its one staging memcpy per call
        assert counts["ingest"] == 0, counts
        assert counts["materialize"] == 0, counts
        assert counts["gather"] == 0, counts
        if lane == "shm":
            assert counts["stage_shm"] == 5, counts
        else:
            assert counts["stage_shm"] == 0, counts
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# resource discipline
# ---------------------------------------------------------------------------

@shm_required
def test_shm_ring_slots_returned_after_soak():
    srv = _server(native=False)
    try:
        ch = _channel(srv)
        for i in range(1000):
            _, ratt = ch.call_raw("D.EchoRaw", b"p", ATT_300K,
                                  timeout_ms=10_000)
            assert len(ratt) == len(ATT_300K), i
        # one more small call drains the last deferred echo-slot free
        ch.call_raw("D.EchoRaw", b"tail", b"", timeout_ms=10_000)
        ring = shm_ring.process_tx_ring()
        assert ring is not None
        assert ring.free_count() == ring.nslots    # no leak
    finally:
        srv.stop()


@shm_required
def test_sendfile_spill_of_file_backed_block():
    """A shm-slot block forwarded onto a TCP byte lane ships via
    os.sendfile (cut_into_socket's file_ref path) byte-correctly."""
    ring = shm_ring.ShmRing(512 * 1024, 2)
    try:
        data = bytes(range(256)) * 512          # 128KB ≥ SENDFILE_MIN
        slot = ring.alloc(owner="t")
        off, n = ring.write(slot, data)
        view = ring.view(off, n)
        buf = IOBuf()
        # file_ref = (fd, file-absolute offset of the block's byte 0)
        buf.append_user_data(view, file_ref=(ring.fd, off))
        a, b = socket.socketpair()
        got = bytearray()

        def reader():
            while len(got) < n:
                chunk = b.recv(65536)
                if not chunk:
                    break
                got.extend(chunk)

        t = threading.Thread(target=reader)
        t.start()
        try:
            a.setblocking(True)
            while len(buf):
                buf.cut_into_socket(a)
        finally:
            a.close()
            t.join(10)
            b.close()
        assert bytes(got) == data
        ring.free(slot)
    finally:
        ring.close()
