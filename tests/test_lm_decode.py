"""KV-cache decoding: teacher-forced equivalence with the full forward,
greedy generate shapes/determinism, MoE decode, and the LMService
serving generation over a real RPC server."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from brpc_tpu.models.transformer_lm import (LMConfig, generate,
                                            init_params, make_decode,
                                            make_forward)


def _setup(seed=0, **kw):
    cfg = LMConfig(vocab=64, dim=32, heads=4, depth=2, max_seq=32,
                   remat=False, **kw)
    params = init_params(jax.random.PRNGKey(seed), cfg)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 8), 0,
                                cfg.vocab, jnp.int32)
    return cfg, params, prompt


def test_decode_matches_forward_teacher_forced():
    """decode_step logits at each position == full-forward last-position
    logits for the identical prefix (bf16 matmul tolerance)."""
    cfg, params, prompt = _setup()
    fwd = jax.jit(make_forward(cfg))
    prefill, decode_step = make_decode(cfg)
    cache, logits = jax.jit(prefill)(params, prompt)
    np.testing.assert_allclose(
        np.asarray(logits), np.asarray(fwd(params, prompt)[:, -1]),
        rtol=2e-2, atol=2e-2)
    seq = prompt
    for i in range(5):
        tok = jax.random.randint(jax.random.PRNGKey(10 + i), (2,), 0,
                                 cfg.vocab, jnp.int32)
        cache, dl = decode_step(params, cache, tok)
        seq = jnp.concatenate([seq, tok[:, None]], axis=1)
        np.testing.assert_allclose(
            np.asarray(dl), np.asarray(fwd(params, seq)[:, -1]),
            rtol=2e-2, atol=2e-2)
    assert int(cache["len"]) == prompt.shape[1] + 5


def test_generate_shape_and_determinism():
    cfg, params, prompt = _setup()
    a = generate(params, cfg, prompt, 6)
    b = generate(params, cfg, prompt, 6)
    assert a.shape == (2, 6)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_temperature_sampling():
    """temperature>0 samples (reproducible under a fixed rng, generally
    different across rngs); temperature=0 stays greedy-deterministic."""
    from brpc_tpu.models.transformer_lm import make_generator

    cfg, params, prompt = _setup()
    gen = make_generator(cfg, params)
    a = gen(prompt, 8, temperature=1.0, rng=jax.random.PRNGKey(3))
    b = gen(prompt, 8, temperature=1.0, rng=jax.random.PRNGKey(3))
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    diff_any = any(
        not np.array_equal(
            np.asarray(gen(prompt, 8, temperature=1.0,
                           rng=jax.random.PRNGKey(100 + i))),
            np.asarray(a))
        for i in range(3))
    assert diff_any, "three different rngs all sampled identically"


def test_moe_decode_generates():
    cfg, params, prompt = _setup(seed=2, moe_experts=2)
    out = generate(params, cfg, prompt, 4)
    assert out.shape == (2, 4)
    assert ((np.asarray(out) >= 0) & (np.asarray(out) < cfg.vocab)).all()


def test_lm_service_generates_over_rpc():
    from brpc_tpu.client import Channel, Controller
    from brpc_tpu.models.lm_service import (LMService,
                                            pack_generate_request,
                                            unpack_generated)
    from brpc_tpu.server import Server

    cfg, params, prompt = _setup()
    srv = Server()
    srv.add_service(LMService(cfg=cfg, params=params), name="LM")
    assert srv.start("127.0.0.1:0") == 0
    try:
        ch = Channel()
        ch.init(str(srv.listen_endpoint))
        cntl = Controller()
        cntl.timeout_ms = 120_000
        c = ch.call_method(
            "LM.Generate",
            pack_generate_request(np.asarray(prompt), 6), cntl=cntl)
        assert not c.failed, c.error_text
        got = unpack_generated(c.response)
        want = np.asarray(generate(params, cfg, prompt, 6))
        np.testing.assert_array_equal(got, want)

        # admission errors, not crashes
        bad = Controller(); bad.timeout_ms = 30_000
        c = ch.call_method("LM.Generate",
                           pack_generate_request(np.asarray(prompt), 999),
                           cntl=bad)
        assert c.failed and "max_new" in c.error_text
    finally:
        srv.stop()


def test_decode_rejects_scan_layers():
    cfg, params, prompt = _setup(scan_layers=True)
    with pytest.raises(AssertionError):
        make_decode(cfg)


def test_scan_generator_matches_stepwise_greedy():
    """The whole-completion scan program must produce the same greedy
    tokens as the per-step generator (same model, same prompt)."""
    import numpy as np
    import jax

    from brpc_tpu.models.transformer_lm import (LMConfig, init_params,
                                                make_generator,
                                                make_scan_generator)
    cfg = LMConfig(vocab=64, dim=32, heads=4, depth=2, max_seq=48,
                   remat=False)
    params = init_params(jax.random.PRNGKey(3), cfg)
    prompt = np.arange(6, dtype=np.int32)[None, :] % cfg.vocab
    step_out = np.asarray(make_generator(cfg, params)(prompt, 10))
    scan_out = np.asarray(make_scan_generator(cfg, params)(prompt, 10))
    np.testing.assert_array_equal(step_out, scan_out)


def test_scan_generator_sampling_contract():
    import numpy as np
    import jax
    import pytest

    from brpc_tpu.models.transformer_lm import (LMConfig, init_params,
                                                make_scan_generator)
    cfg = LMConfig(vocab=64, dim=32, heads=4, depth=1, max_seq=32,
                   remat=False)
    params = init_params(jax.random.PRNGKey(0), cfg)
    gen = make_scan_generator(cfg, params)
    prompt = np.array([[1, 2, 3]], dtype=np.int32)
    with pytest.raises(ValueError, match="rng"):
        gen(prompt, 4, temperature=0.8)
    a = np.asarray(gen(prompt, 6, temperature=0.8,
                       rng=jax.random.PRNGKey(1)))
    b = np.asarray(gen(prompt, 6, temperature=0.8,
                       rng=jax.random.PRNGKey(1)))
    np.testing.assert_array_equal(a, b)      # same key -> same sample
    assert a.shape == (1, 6)
    with pytest.raises(ValueError, match="max_seq"):
        gen(prompt, 64)
