"""KV-cache decoding: teacher-forced equivalence with the full forward,
greedy generate shapes/determinism, MoE decode, and the LMService
serving generation over a real RPC server."""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from brpc_tpu.models.transformer_lm import (LMConfig, generate,
                                            init_params, make_decode,
                                            make_forward)


def _setup(seed=0, **kw):
    cfg = LMConfig(vocab=64, dim=32, heads=4, depth=2, max_seq=32,
                   remat=False, **kw)
    params = init_params(jax.random.PRNGKey(seed), cfg)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 8), 0,
                                cfg.vocab, jnp.int32)
    return cfg, params, prompt


def test_decode_matches_forward_teacher_forced():
    """decode_step logits at each position == full-forward last-position
    logits for the identical prefix (bf16 matmul tolerance)."""
    cfg, params, prompt = _setup()
    fwd = jax.jit(make_forward(cfg))
    prefill, decode_step = make_decode(cfg)
    cache, logits = jax.jit(prefill)(params, prompt)
    np.testing.assert_allclose(
        np.asarray(logits), np.asarray(fwd(params, prompt)[:, -1]),
        rtol=2e-2, atol=2e-2)
    seq = prompt
    for i in range(5):
        tok = jax.random.randint(jax.random.PRNGKey(10 + i), (2,), 0,
                                 cfg.vocab, jnp.int32)
        cache, dl = decode_step(params, cache, tok)
        seq = jnp.concatenate([seq, tok[:, None]], axis=1)
        np.testing.assert_allclose(
            np.asarray(dl), np.asarray(fwd(params, seq)[:, -1]),
            rtol=2e-2, atol=2e-2)
    assert int(cache["len"]) == prompt.shape[1] + 5


def test_generate_shape_and_determinism():
    cfg, params, prompt = _setup()
    a = generate(params, cfg, prompt, 6)
    b = generate(params, cfg, prompt, 6)
    assert a.shape == (2, 6)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_temperature_sampling():
    """temperature>0 samples (reproducible under a fixed rng, generally
    different across rngs); temperature=0 stays greedy-deterministic."""
    from brpc_tpu.models.transformer_lm import make_generator

    cfg, params, prompt = _setup()
    gen = make_generator(cfg, params)
    a = gen(prompt, 8, temperature=1.0, rng=jax.random.PRNGKey(3))
    b = gen(prompt, 8, temperature=1.0, rng=jax.random.PRNGKey(3))
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    diff_any = any(
        not np.array_equal(
            np.asarray(gen(prompt, 8, temperature=1.0,
                           rng=jax.random.PRNGKey(100 + i))),
            np.asarray(a))
        for i in range(3))
    assert diff_any, "three different rngs all sampled identically"


def test_moe_decode_generates():
    cfg, params, prompt = _setup(seed=2, moe_experts=2)
    out = generate(params, cfg, prompt, 4)
    assert out.shape == (2, 4)
    assert ((np.asarray(out) >= 0) & (np.asarray(out) < cfg.vocab)).all()


def test_lm_service_generates_over_rpc():
    from brpc_tpu.client import Channel, Controller
    from brpc_tpu.models.lm_service import (LMService,
                                            pack_generate_request,
                                            unpack_generated)
    from brpc_tpu.server import Server

    cfg, params, prompt = _setup()
    srv = Server()
    srv.add_service(LMService(cfg=cfg, params=params), name="LM")
    assert srv.start("127.0.0.1:0") == 0
    try:
        ch = Channel()
        ch.init(str(srv.listen_endpoint))
        cntl = Controller()
        cntl.timeout_ms = 120_000
        c = ch.call_method(
            "LM.Generate",
            pack_generate_request(np.asarray(prompt), 6), cntl=cntl)
        assert not c.failed, c.error_text
        got = unpack_generated(c.response)
        want = np.asarray(generate(params, cfg, prompt, 6))
        np.testing.assert_array_equal(got, want)

        # admission errors, not crashes
        bad = Controller(); bad.timeout_ms = 30_000
        c = ch.call_method("LM.Generate",
                           pack_generate_request(np.asarray(prompt), 999),
                           cntl=bad)
        assert c.failed and "max_new" in c.error_text
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# continuous batching (ISSUE 13): per-slot batch decode + the streaming
# Decode service — join-mid-batch, evict, TTFT under load
# ---------------------------------------------------------------------------

def test_batch_decode_matches_solo_decode():
    """A slot inside the continuous batch produces the same tokens as
    a solo make_decode run (per-element math is independent)."""
    import functools as ft

    from brpc_tpu.models.transformer_lm import (empty_batch_cache,
                                                make_batch_decode)

    cfg, params, prompt = _setup()
    prefill, step = make_batch_decode(cfg)
    cache = empty_batch_cache(cfg, 4)
    # insert session 0 (prompt row 0) into slot 2, nothing else active
    c1, logits = jax.jit(ft.partial(prefill, params))(prompt[:1])
    for i in range(cfg.depth):
        cache[f"k{i}"] = cache[f"k{i}"].at[2].set(c1[f"k{i}"][0])
        cache[f"v{i}"] = cache[f"v{i}"].at[2].set(c1[f"v{i}"][0])
    cache["len"] = cache["len"].at[2].set(prompt.shape[1])
    active = jnp.zeros((4,), bool).at[2].set(True)
    toks = [int(jnp.argmax(logits[0]))]
    tokens = jnp.zeros((4,), jnp.int32).at[2].set(toks[0])
    step_j = jax.jit(ft.partial(step, params))
    for _ in range(5):
        cache, lg = step_j(cache, tokens, active)
        t = int(jnp.argmax(lg[2]))
        toks.append(t)
        tokens = tokens.at[2].set(t)
    want = np.asarray(generate(params, cfg, prompt[:1], 6))[0].tolist()
    assert toks == want


def test_batch_decode_scan_layers_rejected():
    from brpc_tpu.models.transformer_lm import make_batch_decode
    cfg = LMConfig(vocab=64, dim=32, heads=2, depth=2, max_seq=16,
                   scan_layers=True)
    with pytest.raises(NotImplementedError, match="unrolled"):
        make_batch_decode(cfg)


def _decode_server(cfg, params, slots=4):
    from brpc_tpu.models.lm_service import LMService
    from brpc_tpu.server import Server

    srv = Server()
    svc = LMService(cfg=cfg, params=params, decode_slots=slots)
    srv.add_service(svc, name="LM")
    assert srv.start("127.0.0.1:0") == 0
    return srv, svc


def _stream_decode(srv, prompt, max_new, timeout=120.0):
    """One streamed decode session: returns (tokens, close_reason,
    ttft_seconds)."""
    import time

    from brpc_tpu.client import Channel, Controller
    from brpc_tpu.models.lm_service import (pack_generate_request,
                                            unpack_token)
    from brpc_tpu.streaming import StreamOptions, stream_create

    toks, closed, first = [], [], []

    def on_received(st, msgs):
        if not first:
            first.append(time.monotonic())
        toks.extend(unpack_token(m) for m in msgs)

    ch = Channel()
    ch.init(str(srv.listen_endpoint))
    cntl = Controller()
    cntl.timeout_ms = int(timeout * 1000)
    stream = stream_create(cntl, StreamOptions(
        on_received=on_received,
        on_closed=lambda st: closed.append(st.close_reason)))
    t0 = time.monotonic()
    c = ch.call_method("LM.Decode",
                       pack_generate_request(prompt, max_new),
                       cntl=cntl)
    assert not c.failed, (c.error_code, c.error_text)
    deadline = time.monotonic() + timeout
    while not closed and time.monotonic() < deadline:
        time.sleep(0.005)
    assert closed, "decode stream never closed"
    return toks, closed[0], (first[0] - t0 if first else None)


def test_decode_streams_tokens_and_finishes():
    """Server-streaming decode: one token chunk per step, greedy-
    identical with Generate, stream closed with reason 'finished'."""
    cfg, params, prompt = _setup()
    srv, svc = _decode_server(cfg, params)
    try:
        toks, reason, ttft = _stream_decode(srv, np.asarray(prompt[:1]),
                                            6)
        want = np.asarray(generate(params, cfg, prompt[:1], 6))[0]
        assert toks == want.tolist()
        assert reason == "finished"
        assert ttft is not None
    finally:
        srv.stop()


def test_decode_join_mid_batch_and_evict():
    """Continuous batching: a second session joins while the first is
    mid-generation; both produce their solo-greedy tokens; finished
    sessions evict and free their slot for reuse."""
    import threading

    cfg, params, prompt = _setup()
    p2 = np.asarray(jax.random.randint(jax.random.PRNGKey(7), (1, 5),
                                       0, cfg.vocab, jnp.int32))
    srv, svc = _decode_server(cfg, params, slots=2)
    try:
        res = {}
        t1 = threading.Thread(target=lambda: res.__setitem__(
            "a", _stream_decode(srv, np.asarray(prompt[:1]), 10)))
        t1.start()
        time.sleep(0.3)          # a is mid-generation; b joins the batch
        res["b"] = _stream_decode(srv, p2, 4)
        t1.join(120)
        wa = np.asarray(generate(params, cfg, prompt[:1], 10))[0]
        wb = np.asarray(generate(params, cfg, p2, 4))[0]
        assert res["a"][0] == wa.tolist()
        assert res["b"][0] == wb.tolist()
        assert res["a"][1] == res["b"][1] == "finished"
        # both evicted: slots free again, and a THIRD session reuses one
        deadline = time.time() + 10
        while svc.batcher().live_slots() and time.time() < deadline:
            time.sleep(0.01)
        assert svc.batcher().live_slots() == 0
        toks, reason, _ = _stream_decode(srv, p2, 3)
        assert toks == wb.tolist()[:3]
        assert reason == "finished"
    finally:
        srv.stop()


def test_decode_ttft_under_load():
    """TTFT: with more sessions than slots, queued sessions still get
    their first token as soon as a slot frees (prefill-on-join emits
    immediately), and every session completes correctly."""
    import threading

    cfg, params, prompt = _setup()
    srv, svc = _decode_server(cfg, params, slots=2)
    try:
        results = {}

        def one(i):
            results[i] = _stream_decode(srv, np.asarray(prompt[:1]), 5)

        threads = [threading.Thread(target=one, args=(i,))
                   for i in range(5)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(180)
        want = np.asarray(generate(params, cfg, prompt[:1], 5))[0]
        for i, (toks, reason, ttft) in results.items():
            assert toks == want.tolist(), i
            assert reason == "finished"
            assert ttft is not None and ttft < 120
    finally:
        srv.stop()


def test_decode_stalled_client_evicted_not_hol_blocking():
    """A client that stops consuming (tiny window, handler wedged) is
    evicted with reason 'backpressure' after ONE bounded stall — it
    must not head-of-line-block the other live sessions' tokens."""
    import threading

    from brpc_tpu.client import Channel, Controller
    from brpc_tpu.models.lm_service import pack_generate_request
    from brpc_tpu.streaming import StreamOptions, stream_create

    cfg, params, prompt = _setup()
    srv, svc = _decode_server(cfg, params, slots=4)
    try:
        ch = Channel()
        ch.init(str(srv.listen_endpoint))
        stall_closed = []
        wedge = threading.Event()
        cntl = Controller()
        cntl.timeout_ms = 120_000
        stalled = stream_create(cntl, StreamOptions(
            on_received=lambda s, m: wedge.wait(60),
            on_closed=lambda s: stall_closed.append(s.close_reason),
            max_buf_size=16))           # 4 tokens of credit, no acks
        c = ch.call_method("LM.Decode",
                           pack_generate_request(
                               np.asarray(prompt[:1]), 20), cntl=cntl)
        assert not c.failed, c.error_text
        # a healthy session joins the same batch and must complete
        toks, reason, _ = _stream_decode(srv, np.asarray(prompt[:1]), 8)
        want = np.asarray(generate(params, cfg, prompt[:1], 8))[0]
        assert toks == want.tolist()
        assert reason == "finished"
        # server side evicts the stalled session (slot freed)...
        deadline = time.time() + 60
        while svc.batcher().live_slots() and time.time() < deadline:
            time.sleep(0.02)
        assert svc.batcher().live_slots() == 0
        # ...and once the wedged client handler releases, the queued
        # FIN delivers the NAMED reason
        wedge.set()
        deadline = time.time() + 10
        while not stall_closed and time.time() < deadline:
            time.sleep(0.02)
        assert stall_closed == ["backpressure"], stall_closed
    finally:
        wedge.set()
        srv.stop()


def test_decode_rejects_bad_shapes():
    cfg, params, prompt = _setup()
    srv, _ = _decode_server(cfg, params)
    try:
        from brpc_tpu.client import Channel, Controller
        from brpc_tpu.models.lm_service import pack_generate_request
        from brpc_tpu.streaming import StreamOptions, stream_create

        ch = Channel()
        ch.init(str(srv.listen_endpoint))
        # no stream attached
        c = ch.call_method("LM.Decode",
                           pack_generate_request(
                               np.asarray(prompt[:1]), 4),
                           cntl=Controller())
        assert c.failed and "stream" in c.error_text
        # batch != 1
        cntl = Controller()
        stream_create(cntl, StreamOptions())
        c = ch.call_method("LM.Decode",
                           pack_generate_request(np.asarray(prompt), 4),
                           cntl=cntl)
        assert c.failed and "one session" in c.error_text
        # over max_new cap
        cntl = Controller()
        stream_create(cntl, StreamOptions())
        c = ch.call_method("LM.Decode",
                           pack_generate_request(
                               np.asarray(prompt[:1]), 999),
                           cntl=cntl)
        assert c.failed and "max_new" in c.error_text
    finally:
        srv.stop()


def test_decode_scan_layers_moe_rejected():
    """Scanned decode supports dense blocks (see
    test_scanned_decode_matches_unrolled); the MoE combination is the
    one explicitly unsupported shape and must say so loudly."""
    from brpc_tpu.models.transformer_lm import LMConfig
    cfg = LMConfig(vocab=64, dim=32, heads=2, depth=2, max_seq=16,
                   scan_layers=True, moe_experts=2)
    with pytest.raises(NotImplementedError, match="MoE"):
        make_decode(cfg)


def test_scan_generator_matches_stepwise_greedy():
    """The whole-completion scan program must produce the same greedy
    tokens as the per-step generator (same model, same prompt)."""
    import numpy as np
    import jax

    from brpc_tpu.models.transformer_lm import (LMConfig, init_params,
                                                make_generator,
                                                make_scan_generator)
    cfg = LMConfig(vocab=64, dim=32, heads=4, depth=2, max_seq=48,
                   remat=False)
    params = init_params(jax.random.PRNGKey(3), cfg)
    prompt = np.arange(6, dtype=np.int32)[None, :] % cfg.vocab
    step_out = np.asarray(make_generator(cfg, params)(prompt, 10))
    scan_out = np.asarray(make_scan_generator(cfg, params)(prompt, 10))
    np.testing.assert_array_equal(step_out, scan_out)


def test_scan_generator_sampling_contract():
    import numpy as np
    import jax
    import pytest

    from brpc_tpu.models.transformer_lm import (LMConfig, init_params,
                                                make_scan_generator)
    cfg = LMConfig(vocab=64, dim=32, heads=4, depth=1, max_seq=32,
                   remat=False)
    params = init_params(jax.random.PRNGKey(0), cfg)
    gen = make_scan_generator(cfg, params)
    prompt = np.array([[1, 2, 3]], dtype=np.int32)
    with pytest.raises(ValueError, match="rng"):
        gen(prompt, 4, temperature=0.8)
    a = np.asarray(gen(prompt, 6, temperature=0.8,
                       rng=jax.random.PRNGKey(1)))
    b = np.asarray(gen(prompt, 6, temperature=0.8,
                       rng=jax.random.PRNGKey(1)))
    np.testing.assert_array_equal(a, b)      # same key -> same sample
    assert a.shape == (1, 6)
    with pytest.raises(ValueError, match="max_seq"):
        gen(prompt, 64)


def test_scanned_decode_matches_unrolled():
    """cfg.scan_layers decode (one compiled layer body, stacked caches)
    must produce the same logits/tokens as the unrolled path given the
    same weights — the compile-time answer for deep serving models."""
    import functools as ft

    import jax
    import jax.numpy as jnp
    import numpy as np

    from brpc_tpu.models.transformer_lm import (LMConfig, empty_cache,
                                                init_params, make_decode)

    kw = dict(vocab=64, dim=32, heads=2, depth=3, max_seq=16, mlp_mult=2,
              remat=False, attn_impl="dense")
    cfg_u = LMConfig(**kw)
    cfg_s = LMConfig(**kw, scan_layers=True)
    pu = init_params(jax.random.PRNGKey(0), cfg_u)
    # same weights, stacked layout
    ps = {k: v for k, v in pu.items() if not k.startswith("blk")}
    blks = [pu[f"blk{i}"] for i in range(cfg_u.depth)]
    ps["blocks"] = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs),
                                          *blks)

    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 5), 0,
                                cfg_u.vocab, jnp.int32)
    pre_u, step_u = make_decode(cfg_u)
    pre_s, step_s = make_decode(cfg_s)
    cu, lu = jax.jit(ft.partial(pre_u, pu))(prompt)
    cs, ls = jax.jit(ft.partial(pre_s, ps))(prompt)
    np.testing.assert_allclose(np.asarray(lu), np.asarray(ls),
                               atol=2e-2, rtol=2e-2)
    tok = jnp.argmax(lu, axis=-1).astype(jnp.int32)
    su = jax.jit(ft.partial(step_u, pu))
    ss = jax.jit(ft.partial(step_s, ps))
    for _ in range(4):
        cu, lu = su(cu, tok)
        cs, ls = ss(cs, tok)
        np.testing.assert_allclose(np.asarray(lu), np.asarray(ls),
                                   atol=2e-2, rtol=2e-2)
        tok = jnp.argmax(lu, axis=-1).astype(jnp.int32)
    # stacked empty_cache matches the scanned layout
    ec = empty_cache(cfg_s, 2)
    assert ec["k"].shape == (3, 2, 16, 2, 16)


def test_scanned_decode_int8():
    """Stacked scan_layers trees quantize (per-layer,out-channel
    scales) and the scanned decode streams them."""
    import functools as ft

    import jax
    import jax.numpy as jnp
    import numpy as np

    from brpc_tpu.models.transformer_lm import (LMConfig, init_params,
                                                make_decode)
    from brpc_tpu.ops.quant import QuantTensor, quantize_lm_params

    cfg = LMConfig(vocab=64, dim=32, heads=2, depth=2, max_seq=16,
                   mlp_mult=2, remat=False, attn_impl="dense",
                   scan_layers=True)
    params = init_params(jax.random.PRNGKey(0), cfg)
    qp = quantize_lm_params(params)
    assert isinstance(qp["blocks"]["wqkv"], QuantTensor)
    assert qp["blocks"]["wqkv"].s.shape == (2, 3 * 32)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 4), 0,
                                cfg.vocab, jnp.int32)
    pre, step = make_decode(cfg)
    cf, lf = jax.jit(ft.partial(pre, params))(prompt)
    cq, lq = jax.jit(ft.partial(pre, qp))(prompt)
    # int8 is an approximation: same argmax is the serving contract
    tok = jnp.argmax(lf, axis=-1).astype(jnp.int32)
    cq, lq2 = jax.jit(ft.partial(step, qp))(cq, tok)
    cf, lf2 = jax.jit(ft.partial(step, params))(cf, tok)
    corr = np.corrcoef(np.asarray(lf2).ravel(),
                       np.asarray(lq2).ravel())[0, 1]
    assert corr > 0.99, corr


def test_lm_service_scan_layers_quantized():
    """LMService over RPC with a scan_layers + int8 config: the serving
    stack (scan generator, quantized stacked tree) composes end-to-end."""
    import numpy as np

    from brpc_tpu.client import Channel, Controller
    from brpc_tpu.models.lm_service import (LMService,
                                            pack_generate_request,
                                            unpack_generated)
    from brpc_tpu.models.transformer_lm import LMConfig
    from brpc_tpu.server import Server

    cfg = LMConfig(vocab=128, dim=32, heads=2, depth=2, max_seq=64,
                   remat=False, scan_layers=True, attn_impl="dense")
    srv = Server()
    srv.add_service(LMService(cfg=cfg, quantize=True), name="LM")
    assert srv.start("127.0.0.1:0") == 0
    try:
        ch = Channel()
        ch.init(str(srv.listen_endpoint))
        cntl = Controller()
        cntl.timeout_ms = 120_000        # first compile pays its way
        prompt = np.array([[1, 2, 3]], dtype=np.int32)
        c = ch.call_method("LM.Generate",
                           pack_generate_request(prompt, 4), cntl=cntl)
        assert not c.failed, c.error_text
        out = unpack_generated(bytes(c.response))
        assert out.shape == (1, 4)
        assert (out >= 0).all() and (out < cfg.vocab).all()
    finally:
        srv.stop()
