"""Operability plane — graceful drain, lame-duck, hot restart
(ISSUE 12 acceptance).

The rolling-restart story end to end:

- ``Server.join()`` waits for in-flight settle, not just the stop
  event (the headline semantics fix, pinned first);
- ``Server.drain()`` finishes in-flight work on every lane while NEW
  requests bounce ELAMEDUCK / 503 + x-lame-duck / grpc-status 8
  through the ONE shared admission stage — matrix-tested over classic
  tpu_std, the slim kind-3 native lane, classic HTTP/1.1, the kind-4
  slim HTTP lane, gRPC unary over h2 and the gRPC streaming fiber
  body;
- the lame-duck signal (meta TLV 23 / x-lame-duck / GOAWAY) removes
  the node from LB selection immediately with NO breaker penalty, and
  ELAMEDUCK fail-fast-retries on LB channels like ELIMIT;
- a 3-replica rolling restart under sustained Controller load
  completes with ``rolling_restart_failed_rpcs == 0``;
- drain-grace expiry force-closes stragglers with the named reason
  ``drain_grace_expired``; staged shm-ring slots settle before exit;
- hot restart hands listener fds (kernel listen queue included) to a
  successor over a unix socket — established connections finish on
  the predecessor, everything else lands on the successor.
"""

import os
import socket as pysock
import struct
import threading
import time

import pytest

from brpc_tpu.butil.flags import get_flag, set_flag
from brpc_tpu.butil.status import Errno
from brpc_tpu.client import Channel, ChannelOptions
from brpc_tpu.client.naming_service import global_lame_ducks
from brpc_tpu.client.circuit_breaker import global_circuit_breaker_map
from brpc_tpu.protocol.meta import RpcMeta, TLV_CORRELATION, encode_tlv
from brpc_tpu.server import Server, ServerOptions, Service
from brpc_tpu.server.admission import LAME_DUCK, admission_counters
from brpc_tpu.server.service import grpc_streaming
from brpc_tpu.butil.endpoint import EndPoint

from conftest import require_native  # noqa: E402

ELAMEDUCK = int(Errno.ELAMEDUCK)

# the closed-enum literals this plane exports (the static enums pass
# requires every exportable reason name pinned by a test):
assert LAME_DUCK == "lame_duck"
HTTP_LAME_DUCK_REASON = "http_lame_duck"
FORCE_CLOSE_REASON = "drain_grace_expired"


class OpSvc(Service):
    def __init__(self):
        self.calls = []
        self.parked = []
        self._plock = threading.Lock()
        self.stream_release = threading.Event()

    def Echo(self, cntl, request):
        self.calls.append(bytes(request))
        return b"ok:" + bytes(request)

    def Park(self, cntl, request):
        """Async in-flight occupancy (works on inline native servers
        where a blocking handler would stall the loop serving the
        probe itself)."""
        cntl.begin_async()
        with self._plock:
            self.parked.append(cntl)
        return None

    @grpc_streaming
    def Stream(self, cntl, msgs):
        for m in msgs:
            pass
        self.stream_release.wait(10)
        return b"stream-done"

    def release_parked(self):
        with self._plock:
            parked, self.parked = self.parked, []
        for c in parked:
            c.finish(b"released")


def _server(native: bool, **opt_kv):
    opts = ServerOptions()
    if native:
        opts.native = True
        opts.usercode_inline = True
        opts.native_loops = 1
    for k, v in opt_kv.items():
        setattr(opts, k, v)
    svc = OpSvc()
    srv = Server(opts)
    srv.add_service(svc, name="OP")
    assert srv.start("127.0.0.1:0") == 0
    return srv, svc


def _frame(cid: int, mth: bytes, payload: bytes = b"") -> bytes:
    mb = TLV_CORRELATION + struct.pack("<Q", cid)
    mb += encode_tlv(4, b"OP") + encode_tlv(5, mth)
    body = mb + payload
    return b"TRPC" + struct.pack("<II", len(body), len(mb)) + body


def _read_frames(c: pysock.socket, n: int, timeout=10.0):
    c.settimeout(timeout)
    buf = b""
    out = {}
    while len(out) < n:
        while True:
            if len(buf) >= 12:
                (blen,) = struct.unpack_from("<I", buf, 4)
                if len(buf) >= 12 + blen:
                    break
            chunk = c.recv(65536)
            if not chunk:
                raise ConnectionError("peer closed")
            buf += chunk
        (blen,) = struct.unpack_from("<I", buf, 4)
        (mlen,) = struct.unpack_from("<I", buf, 8)
        meta = RpcMeta.decode(buf[12:12 + mlen])
        assert meta is not None
        out[meta.correlation_id] = meta
        buf = buf[12 + blen:]
    return out


def _connect(ep) -> pysock.socket:
    return pysock.create_connection((str(ep.host), ep.port), timeout=10)


def _park(srv, conn, cid=900, svc=None):
    base = srv.inflight
    nparked = len(svc.parked) if svc is not None else 0
    conn.sendall(_frame(cid, b"Park"))
    deadline = time.time() + 5
    while srv.inflight < base + 1 and time.time() < deadline:
        time.sleep(0.005)
    assert srv.inflight >= base + 1, "Park not admitted in time"
    if svc is not None:
        # wait for the HANDLER too (admission precedes it by a fiber
        # hop): releasing before the cntl is parked would release
        # nothing
        while len(svc.parked) <= nparked and time.time() < deadline:
            time.sleep(0.005)
        assert len(svc.parked) > nparked, "Park handler not reached"


def _drain_on_thread(srv, grace_ms=5000):
    out = {}

    def run():
        out["rc"] = srv.drain(grace_ms=grace_ms)

    t = threading.Thread(target=run, daemon=True)
    t.start()
    deadline = time.time() + 5
    while not srv.draining and time.time() < deadline:
        time.sleep(0.005)
    assert srv.draining
    return t, out


def _http_exchange_on(c: pysock.socket, request: bytes):
    c.sendall(request)
    c.settimeout(10)
    buf = b""
    while b"\r\n\r\n" not in buf:
        chunk = c.recv(65536)
        if not chunk:
            raise ConnectionError("peer closed before headers")
        buf += chunk
    head, _, rest = buf.partition(b"\r\n\r\n")
    lines = head.decode("latin1").split("\r\n")
    status = int(lines[0].split()[1])
    headers = {}
    for ln in lines[1:]:
        k, _, v = ln.partition(":")
        headers[k.strip().lower()] = v.strip()
    clen = int(headers.get("content-length", "0"))
    while len(rest) < clen:
        rest += c.recv(65536)
    return status, headers, rest[:clen]


def _http_req(path: bytes, body: bytes = b"") -> bytes:
    return (b"POST " + path + b" HTTP/1.1\r\nHost: x\r\n"
            b"Content-Length: " + str(len(body)).encode()
            + b"\r\n\r\n" + body)


def _teardown(*servers):
    for s in servers:
        try:
            s.stop()
        except Exception:
            pass
    global_lame_ducks().reset()
    global_circuit_breaker_map().reset()


# ---------------------------------------------------------------------------
# join() semantics (satellite 1)
# ---------------------------------------------------------------------------

def test_join_waits_for_inflight_settle():
    """join() must block until in-flight work settles — the old
    behavior returned the instant stop() fired, handlers still
    running."""
    srv, svc = _server(native=False)
    conn = _connect(srv.listen_endpoint)
    try:
        _park(srv, conn, svc=svc)
        release_at = [0.0]

        def releaser():
            time.sleep(0.4)
            release_at[0] = time.monotonic()
            svc.release_parked()

        threading.Thread(target=releaser, daemon=True).start()
        srv.stop()
        t0 = time.monotonic()
        srv.join(timeout=5)
        t1 = time.monotonic()
        # join returned only AFTER the handler finished (not at stop)
        assert release_at[0] > 0 and t1 >= release_at[0] - 0.01, \
            (t0, release_at[0], t1)
        assert srv.inflight == 0
    finally:
        conn.close()
        _teardown(srv)


def test_join_bounded_by_drain_grace():
    """A handler that never finishes cannot pin join() forever: the
    wait is bounded by drain_grace_ms."""
    srv, svc = _server(native=False)
    conn = _connect(srv.listen_endpoint)
    old = get_flag("drain_grace_ms")
    try:
        set_flag("drain_grace_ms", 300)
        _park(srv, conn, svc=svc)     # never released
        srv.stop()
        t0 = time.monotonic()
        srv.join(timeout=5)
        assert time.monotonic() - t0 < 2.0
    finally:
        set_flag("drain_grace_ms", old)
        svc.release_parked()
        conn.close()
        _teardown(srv)


# ---------------------------------------------------------------------------
# drain matrix: in-flight finishes + new work bounces, on every lane
# ---------------------------------------------------------------------------

def _probe_tpu_std_lame(srv, ep, conn, cid=51):
    before = admission_counters()
    conn.sendall(_frame(cid, b"Echo", b"probe"))
    metas = _read_frames(conn, 1)
    assert metas[cid].error_code == ELAMEDUCK, metas[cid].error_code
    assert metas[cid].lame_duck == 1      # rejection carries the TLV
    after = admission_counters()
    assert after.get(("-", "lame_duck"), 0) \
        - before.get(("-", "lame_duck"), 0) == 1


@pytest.mark.parametrize("native", [False, True],
                         ids=["classic", "slim_native"])
def test_drain_finishes_inflight_tpu_std(native):
    """tpu_std lanes (classic + kind-3 slim): an in-flight request
    admitted before drain COMPLETES during it (response stamped with
    the lame-duck TLV), a new request bounces ELAMEDUCK, drain
    returns 0 once released."""
    if native:
        require_native()
    srv, svc = _server(native=native)
    ep = srv.listen_endpoint
    inflight_conn = _connect(ep)
    probe_conn = _connect(ep)
    try:
        _park(srv, inflight_conn, svc=svc)
        t, out = _drain_on_thread(srv)
        _probe_tpu_std_lame(srv, ep, probe_conn)
        assert t.is_alive()               # still waiting on the park
        svc.release_parked()
        t.join(timeout=5)
        assert out.get("rc") == 0, out
        metas = _read_frames(inflight_conn, 1)
        assert metas[900].error_code == 0
        assert metas[900].lame_duck == 1  # in-flight response signals
    finally:
        svc.release_parked()
        inflight_conn.close()
        probe_conn.close()
        _teardown(srv)


@pytest.mark.parametrize("native", [False, True],
                         ids=["classic", "slim_http"])
def test_drain_finishes_inflight_http(native):
    """HTTP lanes (classic + kind-4 slim): in-flight async request
    completes during drain; a new request gets 503 + x-lame-duck +
    Connection: close; on the native server the kind-4 lane declines
    under the NAMED reason http_lame_duck."""
    if native:
        require_native()
    srv, svc = _server(native=native)
    ep = srv.listen_endpoint
    inflight_conn = _connect(ep)
    probe_conn = _connect(ep)
    try:
        # async park over HTTP (held by the handler until release)
        inflight_conn.sendall(_http_req(b"/OP/Park"))
        deadline = time.time() + 5
        while srv.inflight < 1 and time.time() < deadline:
            time.sleep(0.005)
        assert srv.inflight >= 1
        while not svc.parked and time.time() < deadline:
            time.sleep(0.005)
        assert svc.parked, "Park handler not reached"
        fb_before = 0
        if native and srv._native_bridge is not None:
            fb_before = srv._native_bridge.engine.telemetry()["fallbacks"].get(
                HTTP_LAME_DUCK_REASON, 0)
        t, out = _drain_on_thread(srv)
        status, headers, body = _http_exchange_on(
            probe_conn, _http_req(b"/OP/Echo", b"probe"))
        assert status == 503
        assert headers.get("x-lame-duck") == "1"
        assert headers.get("x-rpc-error-code") == str(ELAMEDUCK)
        assert headers.get("connection") == "close"
        if native and srv._native_bridge is not None:
            fb_after = srv._native_bridge.engine.telemetry()["fallbacks"].get(
                HTTP_LAME_DUCK_REASON, 0)
            assert fb_after > fb_before   # kind-4 declined, by name
        svc.release_parked()
        t.join(timeout=5)
        assert out.get("rc") == 0, out
        status, headers, body = _http_exchange_on(inflight_conn, b"")
        assert status == 200 and body == b"released"
        assert headers.get("x-lame-duck") == "1"
    finally:
        svc.release_parked()
        inflight_conn.close()
        probe_conn.close()
        _teardown(srv)


def test_drain_finishes_inflight_grpc_unary_and_goaway():
    """gRPC over h2: in-flight unary completes during drain, the
    connection receives a NO_ERROR GOAWAY with the response, and a
    new request on the same connection bounces grpc-status 8."""
    from brpc_tpu.protocol.h2_rpc import pack_grpc_message
    from brpc_tpu.protocol.h2_session import H2Session

    srv, svc = _server(native=False)
    ep = srv.listen_endpoint
    sess = H2Session(is_server=False)
    sess.start()
    c = _connect(ep)
    try:
        sid = sess.next_stream_id()
        sess.send_headers(sid, [
            (":method", "POST"), (":path", "/OP/Park"),
            (":scheme", "http"), (":authority", "t"),
            ("content-type", "application/grpc"), ("te", "trailers")])
        sess.send_data(sid, pack_grpc_message(b"x"), end_stream=True)
        c.sendall(sess.take_output())
        deadline = time.time() + 5
        while srv.inflight < 1 and time.time() < deadline:
            time.sleep(0.005)
        assert srv.inflight >= 1
        t, out = _drain_on_thread(srv)
        svc.release_parked()
        t.join(timeout=5)
        assert out.get("rc") == 0, out
        # collect the in-flight response + the GOAWAY
        statuses = {}
        saw_goaway = False
        c.settimeout(10)
        end = time.time() + 10
        while sid not in statuses and time.time() < end:
            data = c.recv(65536)
            if not data:
                break
            for ev in sess.feed(data):
                if ev[0] == "headers":
                    for k, v in ev[2]:
                        if k == "grpc-status":
                            statuses[ev[1]] = v
                elif ev[0] == "goaway":
                    saw_goaway = True
            pend = sess.take_output()
            if pend:
                c.sendall(pend)
        assert statuses.get(sid) == "0", statuses
        assert saw_goaway
        # new request while still lame-duck (pre-stop): grpc-status 8
        sid2 = sess.next_stream_id()
        sess.send_headers(sid2, [
            (":method", "POST"), (":path", "/OP/Echo"),
            (":scheme", "http"), (":authority", "t"),
            ("content-type", "application/grpc"), ("te", "trailers")])
        sess.send_data(sid2, pack_grpc_message(b"y"), end_stream=True)
        c.sendall(sess.take_output())
        end = time.time() + 10
        while sid2 not in statuses and time.time() < end:
            data = c.recv(65536)
            if not data:
                break
            for ev in sess.feed(data):
                if ev[0] == "headers":
                    for k, v in ev[2]:
                        if k == "grpc-status":
                            statuses[ev[1]] = v
            pend = sess.take_output()
            if pend:
                c.sendall(pend)
        assert statuses.get(sid2) == "8", statuses
    finally:
        svc.release_parked()
        c.close()
        _teardown(srv)


def test_drain_finishes_inflight_grpc_streaming():
    """The gRPC streaming fiber body (sixth lane): a live stream
    admitted before drain runs to completion during it."""
    from brpc_tpu.protocol.h2_rpc import pack_grpc_message
    from brpc_tpu.protocol.h2_session import H2Session

    srv, svc = _server(native=False)
    ep = srv.listen_endpoint
    sess = H2Session(is_server=False)
    sess.start()
    c = _connect(ep)
    try:
        sid = sess.next_stream_id()
        sess.send_headers(sid, [
            (":method", "POST"), (":path", "/OP/Stream"),
            (":scheme", "http"), (":authority", "t"),
            ("content-type", "application/grpc"), ("te", "trailers")])
        sess.send_data(sid, pack_grpc_message(b"m1"), end_stream=True)
        c.sendall(sess.take_output())
        deadline = time.time() + 5
        while srv.inflight < 1 and time.time() < deadline:
            time.sleep(0.005)
        assert srv.inflight >= 1
        t, out = _drain_on_thread(srv)
        svc.stream_release.set()
        t.join(timeout=5)
        assert out.get("rc") == 0, out
        status = None
        c.settimeout(10)
        end = time.time() + 10
        while status is None and time.time() < end:
            data = c.recv(65536)
            if not data:
                break
            for ev in sess.feed(data):
                if ev[0] == "headers":
                    for k, v in ev[2]:
                        if k == "grpc-status":
                            status = v
            pend = sess.take_output()
            if pend:
                c.sendall(pend)
        assert status == "0"
    finally:
        svc.stream_release.set()
        c.close()
        _teardown(srv)


# ---------------------------------------------------------------------------
# client half: lame-duck removes the node from LB, breaker untouched
# ---------------------------------------------------------------------------

def test_lame_duck_removes_node_from_lb_without_breaker_trip():
    srv_a, svc_a = _server(native=False)
    srv_b, svc_b = _server(native=False)
    ep_a, ep_b = srv_a.listen_endpoint, srv_b.listen_endpoint
    park_conn = _connect(ep_a)
    try:
        opts = ChannelOptions()
        opts.enable_circuit_breaker = True
        opts.retry_backoff_ms = 2000      # fail-fast must SKIP this
        ch = Channel(opts)
        assert ch.init(f"list://{ep_a.host}:{ep_a.port},"
                       f"{ep_b.host}:{ep_b.port}", "rr") == 0
        # warm both replicas
        for i in range(4):
            assert ch.call("OP.Echo", b"warm%d" % i) == b"ok:warm%d" % i
        # hold one in-flight on A so drain stays in the draining phase
        _park(srv_a, park_conn, svc=svc_a)
        t, out = _drain_on_thread(srv_a)
        svc_a.calls.clear()
        svc_b.calls.clear()
        t0 = time.monotonic()
        for i in range(12):
            assert ch.call("OP.Echo", b"d%d" % i) == b"ok:d%d" % i
        elapsed = time.monotonic() - t0
        # ELAMEDUCK bounces fail-fast-retried on the LB channel: with a
        # 2s backoff configured, sub-second completion proves the
        # backoff was skipped
        assert elapsed < 1.5, elapsed
        # every call landed on B (the bounced first one retried there);
        # once marked, A was never selected again
        assert svc_a.calls == []
        assert len(svc_b.calls) == 12
        assert global_lame_ducks().is_lame(ep_a)
        # planned restart ≠ failure: the breaker did NOT isolate A
        assert not global_circuit_breaker_map().isolated(ep_a)
        svc_a.release_parked()
        t.join(timeout=5)
        assert out.get("rc") == 0
    finally:
        svc_a.release_parked()
        park_conn.close()
        _teardown(srv_a, srv_b)


# ---------------------------------------------------------------------------
# the acceptance centerpiece: 3-replica rolling restart, zero failures
# ---------------------------------------------------------------------------

def test_rolling_restart_zero_failed_rpcs(tmp_path, monkeypatch):
    import brpc_tpu.client.naming_service as ns_mod
    monkeypatch.setattr(ns_mod, "DEFAULT_REFRESH_S", 0.2)

    nsfile = str(tmp_path / "fleet")
    open(nsfile, "w").close()
    replicas = []
    for _ in range(3):
        srv, _svc = _server(native=False)
        assert srv.publish(f"file://{nsfile}") == 0
        replicas.append(srv)

    opts = ChannelOptions()
    opts.timeout_ms = 3000
    ch = Channel(opts)
    assert ch.init(f"file://{nsfile}", "rr") == 0

    stop_load = threading.Event()
    failed = [0]
    sent = [0]

    def load():
        i = 0
        while not stop_load.is_set():
            i += 1
            sent[0] += 1
            try:
                r = ch.call("OP.Echo", b"r%d" % i)
                if r != b"ok:r%d" % i:
                    failed[0] += 1
            except Exception:
                failed[0] += 1

    threads = [threading.Thread(target=load, daemon=True)
               for _ in range(3)]
    for t in threads:
        t.start()
    try:
        time.sleep(0.3)
        for idx in range(3):
            old = replicas[idx]
            # successor first (a fresh address), then drain the old —
            # the kubernetes-rolling-update order
            new, _svc = _server(native=False)
            assert new.publish(f"file://{nsfile}") == 0
            time.sleep(0.45)          # one naming refresh period
            assert old.drain(grace_ms=3000) == 0
            old.stop()
            old.join(timeout=3)
            replicas[idx] = new
            time.sleep(0.25)
    finally:
        stop_load.set()
        for t in threads:
            t.join(timeout=5)
        _teardown(*replicas)
    assert sent[0] > 50, sent[0]
    # THE acceptance key: a full fleet roll under sustained load
    # completed without one client-visible failure
    assert failed[0] == 0, f"{failed[0]}/{sent[0]} rpcs failed"


# ---------------------------------------------------------------------------
# grace expiry + shm settle + observability + hot restart
# ---------------------------------------------------------------------------

def test_drain_grace_expiry_force_closes_with_named_reason():
    srv, svc = _server(native=False)
    conn = _connect(srv.listen_endpoint)
    try:
        _park(srv, conn, svc=svc)     # never released within the grace
        t0 = time.monotonic()
        rc = srv.drain(grace_ms=250)
        assert rc == -1
        assert 0.2 <= time.monotonic() - t0 < 2.0
        assert srv.drain_force_closed >= 1
        # the straggler's socket was force-closed: reads see EOF/RST
        conn.settimeout(2)
        try:
            got = conn.recv(4096)
        except OSError:
            got = b""
        assert got == b""
        assert FORCE_CLOSE_REASON == "drain_grace_expired"
    finally:
        svc.release_parked()
        conn.close()
        _teardown(srv)


def test_drain_settles_shm_slots():
    """Staged tx-ring slots settle before drain returns 0 (the slot
    frees when the consumer drops the response view)."""
    from brpc_tpu.transport import shm_ring

    if not shm_ring.shm_supported():
        pytest.skip("no shm support here")
    srv, svc = _server(native=False)
    try:
        ch = Channel()
        assert ch.init(f"127.0.0.1:{srv.listen_endpoint.port}") == 0
        from brpc_tpu.butil.iobuf import IOBuf
        from brpc_tpu.client.controller import Controller
        big = os.urandom(int(get_flag("rpc_shm_threshold")) + 1024)
        for _ in range(3):            # later calls ride the shm lane
            cntl = Controller()
            cntl.timeout_ms = 10_000
            cntl.request_attachment = IOBuf(big)
            r = ch.call_method("OP.Echo", b"shm", cntl=cntl)
            assert not r.failed, (r.error_code, r.error_text)
            del cntl, r               # drop response views -> settle
        deadline = time.monotonic() + 2
        rc = srv.drain(grace_ms=2000)
        assert rc == 0
        assert shm_ring.outstanding_tx_slots() == 0
        assert deadline > time.monotonic()  # settled, did not expire
    finally:
        _teardown(srv)


def test_health_status_and_bvars_during_drain():
    from brpc_tpu.bvar.variable import find_exposed
    import json as _json

    srv, svc = _server(native=False)
    ep = srv.listen_endpoint
    park_conn = _connect(ep)
    page_conn = _connect(ep)
    try:
        status, headers, body = _http_exchange_on(
            page_conn, _http_req(b"/health"))
        assert status == 200 and body == b"OK\n"
        _park(srv, park_conn, svc=svc)
        t, out = _drain_on_thread(srv)
        # /status shows the drain phase + remaining in-flight
        status, headers, body = _http_exchange_on(
            page_conn, _http_req(b"/status"))
        st = _json.loads(body)
        assert st["drain_phase"] == "draining"
        assert st["drain_inflight_remaining"] >= 1
        # bvars on /vars + /metrics families
        assert find_exposed("server_drain_state").get_value() == 1
        assert find_exposed("drain_inflight_remaining").get_value() >= 1
        # /health flips 503 + x-lame-duck (LB-pollable) — last request
        # on this conn: the drain response closes it
        status, headers, body = _http_exchange_on(
            page_conn, _http_req(b"/health"))
        assert status == 503 and body == b"draining\n"
        assert headers.get("x-lame-duck") == "1"
        svc.release_parked()
        t.join(timeout=5)
        assert out.get("rc") == 0
        srv.stop()
        assert find_exposed("server_drain_state").get_value() == 0
    finally:
        svc.release_parked()
        park_conn.close()
        page_conn.close()
        _teardown(srv)


def test_hot_restart_fd_passing_preserves_service(tmp_path):
    """The binary-swap story: the successor inherits the listener fd
    (kernel listen queue included) while the predecessor finishes its
    established connections — no refused connects, no dropped
    in-flight work."""
    handoff = str(tmp_path / "handoff.sock")
    old_srv, old_svc = _server(native=False)
    ep = old_srv.listen_endpoint
    inflight_conn = _connect(ep)
    try:
        _park(old_srv, inflight_conn, svc=old_svc)
        t = threading.Thread(target=old_srv.export_listeners,
                             args=(handoff, 10.0), daemon=True)
        t.start()
        time.sleep(0.05)
        # build the successor explicitly (same port, inherited fd)
        new_srv = None
        opts = ServerOptions()
        new_svc = OpSvc()
        new_srv = Server(opts)
        new_srv.add_service(new_svc, name="OP")
        assert new_srv.start(f"127.0.0.1:{ep.port}",
                             inherit_from=handoff) == 0
        t.join(timeout=5)
        assert new_srv.listen_endpoint.port == ep.port
        # predecessor drains: its established conn finishes HERE
        t2, out = _drain_on_thread(old_srv)
        old_svc.release_parked()
        t2.join(timeout=5)
        assert out.get("rc") == 0
        metas = _read_frames(inflight_conn, 1)
        assert metas[900].error_code == 0
        old_srv.stop()
        old_srv.join(timeout=3)
        # a brand-new connection lands on the successor via the SAME fd
        with _connect(ep) as c:
            c.sendall(_frame(7, b"Echo", b"post-swap"))
            metas = _read_frames(c, 1)
            assert metas[7].error_code == 0
        assert new_svc.calls == [b"post-swap"]
        assert old_svc.calls == []
    finally:
        old_svc.release_parked()
        inflight_conn.close()
        _teardown(old_srv)
        if new_srv is not None:
            _teardown(new_srv)


def test_hot_restart_native_sharded_listeners(tmp_path):
    """Native engine flavor: the predecessor exports its primary +
    SO_REUSEPORT shard listeners; the successor's engine adopts them
    (listener_fds non-empty, same port served)."""
    require_native()
    handoff = str(tmp_path / "handoff-native.sock")
    old_srv, old_svc = _server(native=True)
    ep = old_srv.listen_endpoint
    try:
        assert old_srv._native_bridge is not None
        fds = old_srv._native_bridge.engine.listener_fds()
        assert fds, "engine exports no listener fds"
        t = threading.Thread(target=old_srv.export_listeners,
                             args=(handoff, 10.0), daemon=True)
        t.start()
        time.sleep(0.05)
        opts = ServerOptions()
        opts.native = True
        opts.usercode_inline = True
        opts.native_loops = 1
        new_svc = OpSvc()
        new_srv = Server(opts)
        new_srv.add_service(new_svc, name="OP")
        assert new_srv.start(f"127.0.0.1:{ep.port}",
                             inherit_from=handoff) == 0
        t.join(timeout=5)
        assert old_srv.drain(grace_ms=2000) == 0
        old_srv.stop()
        with _connect(ep) as c:
            c.sendall(_frame(9, b"Echo", b"native-swap"))
            metas = _read_frames(c, 1)
            assert metas[9].error_code == 0
        assert new_svc.calls == [b"native-swap"]
    finally:
        _teardown(old_srv)
        try:
            _teardown(new_srv)
        except NameError:
            pass
