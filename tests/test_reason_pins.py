"""Closed-reason-enum pins.

Every reason name the process can export — engine fallback reasons,
client-lane demux reasons, the client scatter screening literals — is
pinned HERE as a literal: renaming, removing or adding a reason fails
this file until the change is acknowledged on both sides.  The static
suite (tools/check) enforces that every such name has a pin under
tests/; this module is where the names that have no behavioral test of
their own get their literal anchor (the behavioral suites pin the rest:
test_native_telemetry, test_client_lane, test_trace_propagation).
"""

import ast
import os

# engine server-lane fallback reasons — must equal engine.cpp kFbNames
# and the bridge's FB_REASON_NAMES mirror, in order
ENGINE_FB_REASONS = (
    "rpc_dispatch_off", "rpc_meta_tag", "rpc_no_method",
    "rpc_att_over_cap", "rpc_large_frame", "rpc_trace_raw_lane",
    "rpc_shm_lane",
    "http_slim_off", "http_malformed_line", "http_version",
    "http_no_route", "http_expect", "http_upgrade", "http_connection",
    "http_transfer_encoding", "http_bad_header", "http_large_body",
    "http_chunk_stream", "http_lame_duck",
)

# client demux lane reasons — must equal engine.cpp kCliFbNames
CLIENT_LANE_REASONS = (
    "cli_unknown_cid", "cli_meta_unparsed", "cli_meta_tags",
    "cli_stream_frame", "cli_unknown_magic",
)

# kind-5 streaming-lane fallback reasons — must equal engine.cpp
# kStreamFbNames and stream_slim's STREAM_FB_NAMES mirror, in order
STREAM_FB_REASONS = (
    "stream_no_shim", "stream_non_inline", "stream_compressed",
    "stream_chunk_oversize", "stream_drain", "stream_unregistered",
)

# scatter_call screening reasons — the closed set of
# _scatter_fallback("...") literals in client/fast_call.py
SCATTER_REASONS = {
    "ineligible_cntl", "load_balancer", "device_attachment",
    "nonbytes_request", "auth_on_first", "oversized_request",
    "mixed_deadlines", "no_single_server", "connect_failed",
    "socket_busy", "repeated_remote",
}


def test_bridge_mirror_matches_pins():
    from brpc_tpu.transport.native_bridge import FB_REASON_NAMES
    assert FB_REASON_NAMES == ENGINE_FB_REASONS


def test_client_lane_reasons_match_pins():
    from brpc_tpu.transport.client_lane import REASONS
    assert REASONS == CLIENT_LANE_REASONS


def test_stream_lane_reasons_match_pins():
    from brpc_tpu.server.stream_slim import STREAM_FB_NAMES
    assert STREAM_FB_NAMES == STREAM_FB_REASONS


def test_engine_tables_match_pins():
    """The C++ source's name tables equal the pinned literals (source
    scan — no toolchain needed, so the pin holds even where the engine
    cannot build)."""
    from brpc_tpu.tools.check import cppscan
    src = os.path.join(os.path.dirname(__file__), "..", "brpc_tpu",
                       "native", "src", "engine.cpp")
    with open(src, "r", encoding="utf-8", errors="replace") as f:
        text = f.read()
    assert tuple(cppscan.parse_string_array(text, "kFbNames")) \
        == ENGINE_FB_REASONS
    assert tuple(cppscan.parse_string_array(text, "kCliFbNames")) \
        == CLIENT_LANE_REASONS
    assert tuple(cppscan.parse_string_array(text, "kStreamFbNames")) \
        == STREAM_FB_REASONS


def test_scatter_screening_set_matches_pins():
    """The set of screening literals in fast_call.py is exactly the
    pinned closed set — a new screening site must register its reason
    here (and thereby in the telemetry family's documented values)."""
    src = os.path.join(os.path.dirname(__file__), "..", "brpc_tpu",
                       "client", "fast_call.py")
    with open(src, "r", encoding="utf-8") as f:
        mod = ast.parse(f.read())
    found = set()
    for node in ast.walk(mod):
        if isinstance(node, ast.Call):
            fn = node.func
            name = fn.id if isinstance(fn, ast.Name) else (
                fn.attr if isinstance(fn, ast.Attribute) else None)
            if name == "_scatter_fallback" and node.args \
                    and isinstance(node.args[0], ast.Constant):
                found.add(node.args[0].value)
    assert found == SCATTER_REASONS
