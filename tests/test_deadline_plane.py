"""Deadline plane — end-to-end enforcement, shedding, inheritance and
retry budgets (ISSUE 5 acceptance matrix).

The shed matrix mirrors test_trace_propagation's shape: a request that
arrives with an already-expired propagated deadline is answered
``ERPCTIMEDOUT`` WITHOUT the handler running, on all five server
dispatch paths — classic tpu_std full dispatch, the slim kind-3 native
lane, classic HTTP/1.1, the kind-4 slim HTTP lane, and gRPC over h2 —
with per-(lane, method) ``deadline_shed_total`` counters recording each
shed.  Untraced no-deadline traffic (and deadline'd traffic whose
budget is alive) must keep riding the slim lanes with zero new
fallbacks.
"""

import socket as pysock
import struct
import threading
import time

import pytest

from brpc_tpu.butil.flags import get_flag, set_flag
from brpc_tpu.butil.status import Errno
from brpc_tpu.client import Channel, ChannelOptions, Controller
from brpc_tpu.deadline import (RetryBudget, backoff_ms, shed_counters)
from brpc_tpu.protocol.meta import (RpcMeta, TLV_CORRELATION, TLV_TIMEOUT,
                                    encode_tlv)
from brpc_tpu.server import Server, ServerOptions, Service

from conftest import require_native  # noqa: E402

TIMEDOUT = int(Errno.ERPCTIMEDOUT)


class DeadlineSvc(Service):
    def __init__(self):
        self.echo_calls = []          # payloads the handler actually saw
        self.seen_remaining = []      # cntl.deadline_remaining_ms() values

    def Echo(self, cntl, request):
        self.echo_calls.append(bytes(request))
        self.seen_remaining.append(cntl.deadline_remaining_ms())
        return b"ok:" + bytes(request)

    def Sleep(self, cntl, request):
        time.sleep(0.2)
        return b"slept"


def _server(native: bool, inline: bool = True):
    opts = ServerOptions()
    if native:
        opts.native = True
        opts.usercode_inline = inline
        opts.native_loops = 1
    svc = DeadlineSvc()
    srv = Server(opts)
    srv.add_service(svc, name="D")
    assert srv.start("127.0.0.1:0") == 0
    return srv, svc


def _frame(cid: int, mth: bytes, payload: bytes,
           timeout_ms=None) -> bytes:
    mb = TLV_CORRELATION + struct.pack("<Q", cid)
    mb += encode_tlv(4, b"D") + encode_tlv(5, mth)
    if timeout_ms is not None:
        mb += TLV_TIMEOUT + struct.pack("<I", timeout_ms)
    body = mb + payload
    return b"TRPC" + struct.pack("<II", len(body), len(mb)) + body


def _read_frames(c: pysock.socket, n: int, timeout=10.0):
    """Read n complete TRPC frames; returns {cid: RpcMeta}."""
    c.settimeout(timeout)
    buf = b""
    out = {}
    while len(out) < n:
        while True:
            if len(buf) >= 12:
                (blen,) = struct.unpack_from("<I", buf, 4)
                if len(buf) >= 12 + blen:
                    break
            buf += c.recv(65536)
        (blen,) = struct.unpack_from("<I", buf, 4)
        (mlen,) = struct.unpack_from("<I", buf, 8)
        meta = RpcMeta.decode(buf[12:12 + mlen])
        assert meta is not None
        out[meta.correlation_id] = meta
        buf = buf[12 + blen:]
    return out


def _shed_delta(before, lane, method):
    after = shed_counters()
    return after.get((lane, method), 0) - before.get((lane, method), 0)


# ---------------------------------------------------------------------------
# the five-lane shed matrix
# ---------------------------------------------------------------------------

def test_shed_classic_tpu_std():
    """rpc_dispatch: an explicit on-wire remaining-deadline of 0
    (expired at arrival; real clients stamp >= 1) is answered
    ERPCTIMEDOUT before auth/parse/handler."""
    srv, svc = _server(native=False)
    try:
        before = shed_counters()
        with pysock.create_connection(
                (str(srv.listen_endpoint.host), srv.listen_endpoint.port),
                timeout=10) as c:
            c.sendall(_frame(11, b"Echo", b"doomed", timeout_ms=0))
            metas = _read_frames(c, 1)
        assert metas[11].error_code == TIMEDOUT
        assert svc.echo_calls == []
        assert _shed_delta(before, "tpu_std", "D.Echo") == 1
    finally:
        srv.stop()


def test_shed_slim_kind3_native_queueing():
    """Slim kind-3: a pipelined burst whose first request chews the
    whole batch (inline Sleep) makes the second one's budget expire IN
    THE NATIVE BATCH — the shim sheds against the engine's
    CLOCK_MONOTONIC parse timestamp, handler never runs."""
    require_native()
    srv, svc = _server(native=True, inline=True)
    try:
        before = shed_counters()
        ep = srv.listen_endpoint
        with pysock.create_connection((str(ep.host), ep.port),
                                      timeout=10) as c:
            # ONE write → one read burst → one batched GIL entry:
            # Sleep(200ms) runs first, Echo's 50ms budget dies in queue
            c.sendall(_frame(21, b"Sleep", b"")
                      + _frame(22, b"Echo", b"doomed", timeout_ms=50))
            metas = _read_frames(c, 2)
        assert metas[21].error_code == 0
        assert metas[22].error_code == TIMEDOUT
        assert svc.echo_calls == []
        assert _shed_delta(before, "slim", "D.Echo") == 1
    finally:
        srv.stop()


def test_shed_slim_kind3_explicit_zero():
    """Slim kind-3, the crafted expired-at-arrival case: an explicit
    on-wire TLV 13 of 0 (real clients stamp >= 1) must shed on the
    slim lane too — the engine's timeout_present bit tells a present 0
    apart from an absent deadline (None reaches the shim)."""
    require_native()
    srv, svc = _server(native=True, inline=True)
    try:
        before = shed_counters()
        ep = srv.listen_endpoint
        with pysock.create_connection((str(ep.host), ep.port),
                                      timeout=10) as c:
            c.sendall(_frame(25, b"Echo", b"doomed", timeout_ms=0))
            metas = _read_frames(c, 1)
        assert metas[25].error_code == TIMEDOUT
        assert svc.echo_calls == []
        assert _shed_delta(before, "slim", "D.Echo") == 1
    finally:
        srv.stop()


def test_shed_bridge_slim_meta_fallback():
    """An over-cap attachment (> kSlimAttCap, 16KB) makes the engine
    decline the kind-3 lane (rpc_att_over_cap) and hand the frame to
    the Python bridge, whose slim-meta path rebuilds RpcMeta from the
    raw-lane TLV scan — an explicit on-wire TLV 13 of 0 must still
    shed there (the scan forwards timeout_present)."""
    require_native()
    srv, svc = _server(native=True, inline=True)
    try:
        before = shed_counters()
        att = b"A" * (17 * 1024)
        mb = TLV_CORRELATION + struct.pack("<Q", 27)
        mb += encode_tlv(4, b"D") + encode_tlv(5, b"Echo")
        mb += encode_tlv(3, struct.pack("<I", len(att)))
        mb += TLV_TIMEOUT + struct.pack("<I", 0)
        body = mb + b"doomed" + att
        ep = srv.listen_endpoint
        with pysock.create_connection((str(ep.host), ep.port),
                                      timeout=10) as c:
            c.sendall(b"TRPC" + struct.pack("<II", len(body), len(mb))
                      + body)
            metas = _read_frames(c, 1)
        assert metas[27].error_code == TIMEDOUT
        assert svc.echo_calls == []
        assert _shed_delta(before, "tpu_std", "D.Echo") == 1
    finally:
        srv.stop()


def _http_exchange(ep, request: bytes) -> tuple:
    """One HTTP/1.1 exchange; returns (status, headers dict, body)."""
    with pysock.create_connection((str(ep.host), ep.port), timeout=10) as c:
        c.sendall(request)
        c.settimeout(10)
        buf = b""
        while b"\r\n\r\n" not in buf:
            buf += c.recv(65536)
        head, _, rest = buf.partition(b"\r\n\r\n")
        lines = head.decode("latin1").split("\r\n")
        status = int(lines[0].split()[1])
        headers = {}
        for ln in lines[1:]:
            k, _, v = ln.partition(":")
            headers[k.strip().lower()] = v.strip()
        clen = int(headers.get("content-length", "0"))
        while len(rest) < clen:
            rest += c.recv(65536)
        return status, headers, rest[:clen]


def _http_req(mth: bytes, path: bytes, body: bytes, deadline_ms,
              close=False) -> bytes:
    h = [mth + b" " + path + b" HTTP/1.1",
         b"Host: x",
         b"Content-Length: " + str(len(body)).encode()]
    if deadline_ms is not None:
        h.append(b"x-deadline-ms: " + str(deadline_ms).encode())
    if close:
        h.append(b"Connection: close")
    return b"\r\n".join(h) + b"\r\n\r\n" + body


def test_shed_http_classic():
    """Classic HTTP/1.1 bridge: x-deadline-ms: 0 → 500 with
    x-rpc-error-code ERPCTIMEDOUT, handler never runs."""
    srv, svc = _server(native=False)
    try:
        before = shed_counters()
        status, headers, body = _http_exchange(
            srv.listen_endpoint,
            _http_req(b"POST", b"/D/Echo", b"doomed", 0, close=True))
        assert status == 500
        assert headers.get("x-rpc-error-code") == str(TIMEDOUT)
        assert svc.echo_calls == []
        assert _shed_delta(before, "http", "D.Echo") == 1
    finally:
        srv.stop()


def test_shed_http_slim_kind4():
    """Kind-4 slim HTTP lane: the engine captures x-deadline-ms, the
    shim sheds against the engine parse timestamp, and the 500 is
    serialized natively with the burst."""
    require_native()
    srv, svc = _server(native=True, inline=True)
    try:
        before = shed_counters()
        status, headers, body = _http_exchange(
            srv.listen_endpoint,
            _http_req(b"POST", b"/D/Echo", b"doomed", 0))
        assert status == 500
        assert headers.get("x-rpc-error-code") == str(TIMEDOUT)
        assert svc.echo_calls == []
        assert _shed_delta(before, "http_slim", "D.Echo") == 1
    finally:
        srv.stop()


def test_shed_grpc_h2():
    """gRPC/h2: grpc-timeout: 0m → DEADLINE_EXCEEDED (grpc-status 4)
    trailers, handler never runs."""
    from brpc_tpu.protocol.h2_rpc import pack_grpc_message
    from brpc_tpu.protocol.h2_session import H2Session

    srv, svc = _server(native=False)
    try:
        before = shed_counters()
        sess = H2Session(is_server=False)
        sess.start()
        sid = sess.next_stream_id()
        sess.send_headers(sid, [
            (":method", "POST"), (":path", "/D/Echo"),
            (":scheme", "http"), (":authority", "t"),
            ("content-type", "application/grpc"), ("te", "trailers"),
            ("grpc-timeout", "0m")])
        sess.send_data(sid, pack_grpc_message(b"doomed"),
                       end_stream=True)
        ep = srv.listen_endpoint
        grpc_status = None
        with pysock.create_connection((str(ep.host), ep.port),
                                      timeout=10) as c:
            c.sendall(sess.take_output())
            c.settimeout(10)
            deadline = time.time() + 10
            while grpc_status is None and time.time() < deadline:
                data = c.recv(65536)
                if not data:
                    break
                for ev in sess.feed(data):
                    if ev[0] == "headers":
                        for k, v in ev[2]:
                            if k == "grpc-status":
                                grpc_status = v
                out = sess.take_output()
                if out:
                    c.sendall(out)      # settings acks etc.
        assert grpc_status == "4"       # DEADLINE_EXCEEDED
        assert svc.echo_calls == []
        assert _shed_delta(before, "grpc", "D.Echo") == 1
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# pinned: deadline'd / no-deadline requests still ride the slim lanes
# ---------------------------------------------------------------------------

def test_no_new_fallbacks_on_slim_lanes():
    require_native()
    srv, svc = _server(native=True, inline=True)
    try:
        eng = srv._native_bridge.engine
        t0 = eng.telemetry()
        ep = srv.listen_endpoint
        with pysock.create_connection((str(ep.host), ep.port),
                                      timeout=10) as c:
            # no deadline, then a live 5s deadline — both must ride slim
            c.sendall(_frame(31, b"Echo", b"plain"))
            _read_frames(c, 1)
            c.sendall(_frame(32, b"Echo", b"budgeted", timeout_ms=5000))
            metas = _read_frames(c, 1)
        assert metas[32].error_code == 0
        # the deadline'd handler saw its remaining budget
        assert svc.seen_remaining[-1] is not None
        assert 0 < svc.seen_remaining[-1] <= 5000
        # kind-4 with a live budget stays slim too
        status, headers, body = _http_exchange(
            ep, _http_req(b"POST", b"/D/Echo", b"h", 5000))
        assert status == 200 and body == b"ok:h"
        t1 = eng.telemetry()
        assert sum(t1["fallbacks"].values()) == \
            sum(t0["fallbacks"].values()), t1["fallbacks"]
        assert t1["lanes"]["slim"]["handled"] \
            >= t0["lanes"]["slim"]["handled"] + 2
        assert t1["lanes"]["http"]["handled"] \
            >= t0["lanes"]["http"]["handled"] + 1
    finally:
        srv.stop()


def test_shed_togglable_via_flag():
    """enable_deadline_shed=False lets an expired request through to
    the handler (the bench's goodput A/B switch)."""
    srv, svc = _server(native=False)
    try:
        prev = get_flag("enable_deadline_shed", True)
        set_flag("enable_deadline_shed", False)
        try:
            with pysock.create_connection(
                    (str(srv.listen_endpoint.host),
                     srv.listen_endpoint.port), timeout=10) as c:
                c.sendall(_frame(41, b"Echo", b"letin", timeout_ms=0))
                metas = _read_frames(c, 1)
            assert metas[41].error_code == 0
            assert svc.echo_calls == [b"letin"]
        finally:
            set_flag("enable_deadline_shed", prev)
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# controller API + ambient inheritance
# ---------------------------------------------------------------------------

def test_server_controller_deadline_api():
    srv, svc = _server(native=False)
    try:
        co = ChannelOptions()
        co.connection_type = "pooled"
        ch = Channel(co)
        ch.init(str(srv.listen_endpoint))
        cntl = Controller()
        cntl.timeout_ms = 3000
        ch.call_method("D.Echo", b"x", cntl=cntl)
        assert not cntl.failed, cntl.error_text
        rem = svc.seen_remaining[-1]
        assert rem is not None and 0 < rem <= 3000
    finally:
        srv.stop()


def test_downstream_call_inherits_remaining_budget():
    """A handler's downstream RPC defaults its timeout to the inherited
    remaining budget; the downstream server sees a propagated deadline
    strictly under the upstream timeout."""
    down_srv, down_svc = _server(native=False)

    class Front(Service):
        def Relay(self, cntl, request):
            time.sleep(0.05)         # burn some budget first
            co = ChannelOptions()
            co.connection_type = "pooled"
            # NOTE: no timeout set anywhere — inheritance must supply it
            co.timeout_ms = 0
            ch = Channel(co)
            ch.init(str(down_srv.listen_endpoint))
            sub = Controller()
            ch.call_method("D.Echo", b"inner", cntl=sub)
            assert not sub.failed, sub.error_text
            return b"relayed"

    front = Server()
    front.add_service(Front(), name="F")
    assert front.start("127.0.0.1:0") == 0
    try:
        ch = Channel()
        cntl = Controller()
        cntl.timeout_ms = 2000
        ch.init(str(front.listen_endpoint))
        ch.call_method("F.Relay", b"", cntl=cntl)
        assert not cntl.failed, cntl.error_text
        rem = down_svc.seen_remaining[-1]
        assert rem is not None
        # inherited minus elapsed: visibly less than the original 2000
        assert 0 < rem <= 1980
    finally:
        front.stop()
        down_srv.stop()


def test_downstream_call_fails_fast_after_budget_gone():
    """Once the handler outlives its budget, downstream calls fail
    ERPCTIMEDOUT WITHOUT dispatching (the downstream handler never
    runs)."""
    down_srv, down_svc = _server(native=False)
    observed = {}

    class Front(Service):
        def Relay(self, cntl, request):
            time.sleep(0.3)          # overshoot the 150ms budget
            ch = Channel()
            ch.init(str(down_srv.listen_endpoint))
            sub = Controller()
            ch.call_method("D.Echo", b"doomed-inner", cntl=sub)
            observed["code"] = sub.error_code
            return b"late"

    front = Server()
    front.add_service(Front(), name="F")
    assert front.start("127.0.0.1:0") == 0
    try:
        ch = Channel()
        cntl = Controller()
        cntl.timeout_ms = 150
        ch.init(str(front.listen_endpoint))
        ch.call_method("F.Relay", b"", cntl=cntl)
        assert cntl.failed          # the upstream call itself timed out
        deadline = time.time() + 5
        while "code" not in observed and time.time() < deadline:
            time.sleep(0.01)
        assert observed.get("code") == TIMEDOUT
        assert b"doomed-inner" not in down_svc.echo_calls
    finally:
        front.stop()
        down_srv.stop()


# ---------------------------------------------------------------------------
# fan-out budget sharing (satellite: parallel_channel regression)
# ---------------------------------------------------------------------------

def test_selective_channel_legs_share_one_budget():
    """SelectiveChannel: a slow failing first leg leaves the second leg
    only the REMAINING budget, not a fresh copy of the timeout."""
    from brpc_tpu.client.parallel_channel import SelectiveChannel

    class SlowFail(Service):
        def Echo(self, cntl, request):
            time.sleep(0.15)
            cntl.set_failed(Errno.EINTERNAL, "leg down")
            return None

    s1 = Server()
    s1.add_service(SlowFail(), name="D")
    assert s1.start("127.0.0.1:0") == 0
    s2, svc2 = _server(native=False)
    try:
        ch1, ch2 = Channel(), Channel()
        ch1.init(str(s1.listen_endpoint))
        ch2.init(str(s2.listen_endpoint))
        sel = SelectiveChannel()
        sel.add_channel(ch1)
        sel.add_channel(ch2)
        cntl = Controller()
        cntl.timeout_ms = 600
        sel.call_method("D.Echo", b"x", cntl=cntl)
        assert not cntl.failed, cntl.error_text
        rem = svc2.seen_remaining[-1]
        assert rem is not None
        # leg 2's budget must reflect the ~150ms leg 1 burned
        assert rem <= 470, rem
    finally:
        s1.stop()
        s2.stop()


def test_leg_budget_math():
    from brpc_tpu.butil.time_utils import monotonic_us
    from brpc_tpu.client.parallel_channel import _leg_budget_ms
    now = monotonic_us()
    assert _leg_budget_ms(now, None) is None
    assert _leg_budget_ms(now, 0) == 0
    left = _leg_budget_ms(now - 100_000, 500)    # 100ms elapsed
    assert 390 <= left <= 401
    assert _leg_budget_ms(now - 700_000, 500) <= 0


def test_parallel_channel_scatter_legs_capped():
    """ParallelChannel sync fan-out: every leg's propagated budget is
    the fan-out's remaining budget (observed by the sub-servers)."""
    from brpc_tpu.client.parallel_channel import ParallelChannel

    s1, svc1 = _server(native=False)
    s2, svc2 = _server(native=False)
    try:
        pc = ParallelChannel()
        for s in (s1, s2):
            co = ChannelOptions()
            co.connection_type = "pooled"
            ch = Channel(co)
            ch.init(str(s.listen_endpoint))
            pc.add_channel(ch)
        cntl = Controller()
        cntl.timeout_ms = 800
        pc.call_method("D.Echo", b"fan", cntl=cntl)
        assert not cntl.failed, cntl.error_text
        for svc in (svc1, svc2):
            rem = svc.seen_remaining[-1]
            assert rem is not None and 0 < rem <= 800
    finally:
        s1.stop()
        s2.stop()


# ---------------------------------------------------------------------------
# retry hardening
# ---------------------------------------------------------------------------

def test_retry_budget_token_bucket():
    b = RetryBudget(max_tokens=4, token_ratio=0.5)
    assert b.acquire() and b.acquire()       # 4 → 3 → 2
    assert not b.acquire()                   # 2 > 2 is false: denied
    assert b.denied_count == 1
    b.on_success()                           # 2 → 2.5
    assert b.acquire()
    assert not b.acquire()
    # refills cap at max_tokens
    for _ in range(100):
        b.on_success()
    assert b.tokens == 4.0


def test_backoff_exponential_with_jitter():
    assert backoff_ms(0, 3) == 0.0
    d1 = [backoff_ms(50, 1) for _ in range(50)]
    d3 = [backoff_ms(50, 3) for _ in range(50)]
    assert all(40.0 <= d <= 60.0 for d in d1)        # 50 ± 20%
    assert all(160.0 <= d <= 240.0 for d in d3)      # 200 ± 20%
    # the cap is a hard bound — jitter never pierces it
    assert all(backoff_ms(1000, 10, max_ms=3000) <= 3000
               for _ in range(50))
    assert len(set(d1)) > 1                          # jitter present


def test_channel_retry_budget_caps_attempts():
    """Against a dead backend, retries across calls are capped by the
    channel budget (and further calls don't retry at all)."""
    co = ChannelOptions()
    co.timeout_ms = 2000
    co.max_retry = 3
    co.retry_budget_max = 4
    ch = Channel(co)
    assert ch.init("127.0.0.1:1") == 0      # nothing listens here
    total_retries = 0
    for _ in range(6):
        cntl = Controller()
        cntl.timeout_ms = 2000
        c = ch.call_method("D.Echo", b"x", cntl=cntl)
        assert c.failed
        total_retries += c.retried_count
    # 4 tokens → exactly 2 retries ever granted, then the budget gates
    assert total_retries == 2, total_retries
    assert ch.retry_budget().denied_count > 0


def test_backup_request_draws_from_budget():
    """Backup (hedged) requests spend the same tokens as retries: with
    the budget exhausted, no backup goes out."""

    class Slow(Service):
        def __init__(self):
            self.calls = 0

        def Nap(self, cntl, request):
            self.calls += 1
            time.sleep(0.3)
            return b"ok"

    svc = Slow()
    srv = Server()
    srv.add_service(svc, name="SL")
    assert srv.start("127.0.0.1:0") == 0
    try:
        co = ChannelOptions()
        co.timeout_ms = 2000
        co.backup_request_ms = 50
        co.connection_type = "single"
        co.retry_budget_max = 4
        ch = Channel(co)
        ch.init(str(srv.listen_endpoint))
        # drain the budget to the deny line
        budget = ch.retry_budget()
        while budget.acquire():
            pass
        cntl = Controller()
        cntl.timeout_ms = 2000
        ch.call_method("SL.Nap", b"", cntl=cntl)
        assert not cntl.failed, cntl.error_text
        assert not cntl.has_backup_request       # budget said no
        time.sleep(0.1)
        assert svc.calls == 1
    finally:
        srv.stop()


def test_backoff_spaces_retries():
    """retry_backoff_ms spreads the retry chain out in time (timer-
    thread scheduled, exponential)."""
    co = ChannelOptions()
    co.timeout_ms = 5000
    co.max_retry = 2
    co.retry_backoff_ms = 80
    co.connection_type = "single"
    ch = Channel(co)
    assert ch.init("127.0.0.1:1") == 0
    cntl = Controller()
    cntl.timeout_ms = 5000
    t0 = time.monotonic()
    c = ch.call_method("D.Echo", b"x", cntl=cntl)
    elapsed = time.monotonic() - t0
    assert c.failed
    assert c.retried_count == 2
    # backoff 80ms + 160ms (±20% jitter) must be visible in wall time
    assert elapsed >= 0.18, elapsed
