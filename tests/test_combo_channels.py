"""Combo channel tests: Parallel fan-out with partial failure,
Selective failover, Partition sharding by naming tags
(≈ /root/reference/example/parallel_echo_c++, partition_echo_c++ as
integration shapes)."""

import threading
import time

import pytest

from brpc_tpu.client import (SKIP, Channel, Controller, ParallelChannel,
                             PartitionChannel, SelectiveChannel)
from brpc_tpu.client.circuit_breaker import global_circuit_breaker_map
from brpc_tpu.butil.status import Errno
from brpc_tpu.server import Server, Service


class Tagged(Service):
    def __init__(self, who):
        self.who = who

    def Who(self, cntl, request):
        return f"{self.who}:{request.decode()}".encode()


def _server(who):
    srv = Server()
    srv.add_service(Tagged(who), name="T")
    assert srv.start("127.0.0.1:0") == 0
    return srv


@pytest.fixture(autouse=True)
def _clean_breakers():
    global_circuit_breaker_map().reset()
    yield
    global_circuit_breaker_map().reset()


def test_parallel_channel_fanout_and_merge():
    servers = [_server(w) for w in "abc"]
    try:
        pc = ParallelChannel()
        for s in servers:
            ch = Channel()
            ch.init(str(s.listen_endpoint))
            pc.add_channel(ch)
        c = pc.call_method("T.Who", b"x",
                           merger=lambda rs: b",".join(rs))
        assert not c.failed, c.error_text
        assert c.response == b"a:x,b:x,c:x"
    finally:
        for s in servers:
            s.stop()


def test_parallel_channel_call_mapper_skip():
    servers = [_server(w) for w in "ab"]
    try:
        pc = ParallelChannel()
        for i, s in enumerate(servers):
            ch = Channel()
            ch.init(str(s.listen_endpoint))
            pc.add_channel(ch, call_mapper=lambda i, sub, req, _i=i:
                           SKIP if _i == 1 else req + b"!")
        c = pc.call_method("T.Who", b"q")
        assert not c.failed
        assert c.response == [b"a:q!"]
    finally:
        for s in servers:
            s.stop()


def test_parallel_channel_fail_limit():
    s1 = _server("a")
    try:
        pc = ParallelChannel(fail_limit=1)
        ok = Channel()
        ok.init(str(s1.listen_endpoint))
        dead = Channel()
        dead.init("127.0.0.1:1")        # nothing listens
        pc.add_channel(ok)
        pc.add_channel(dead)
        cntl = Controller()
        cntl.timeout_ms = 2000
        c = pc.call_method("T.Who", b"x", cntl=cntl)
        assert c.failed
        assert c.error_code == int(Errno.ETOOMANYFAILS)
    finally:
        s1.stop()


def test_parallel_channel_tolerates_failures_under_limit():
    s1 = _server("a")
    try:
        pc = ParallelChannel(fail_limit=2)
        ok = Channel()
        ok.init(str(s1.listen_endpoint))
        dead = Channel()
        dead.init("127.0.0.1:1")
        pc.add_channel(ok)
        pc.add_channel(dead)
        cntl = Controller()
        cntl.timeout_ms = 2000
        c = pc.call_method("T.Who", b"x", cntl=cntl)
        assert not c.failed, c.error_text
        assert c.response == [b"a:x", None]
    finally:
        s1.stop()


def test_selective_channel_failover():
    s1 = _server("alive")
    try:
        sc = SelectiveChannel()
        dead = Channel()
        dead.init("127.0.0.1:1")
        ok = Channel()
        ok.init(str(s1.listen_endpoint))
        sc.add_channel(dead)
        sc.add_channel(ok)
        for _ in range(4):
            cntl = Controller()
            cntl.timeout_ms = 2000
            c = sc.call_method("T.Who", b"z", cntl=cntl)
            assert not c.failed, c.error_text
            assert c.response == b"alive:z"
    finally:
        s1.stop()


def test_partition_channel_shards_by_tag():
    # 2 partitions × 2 replicas
    servers = {w: _server(w) for w in ("p0a", "p0b", "p1a", "p1b")}
    try:
        url = ("list://"
               f"{servers['p0a'].listen_endpoint} 0/2,"
               f"{servers['p0b'].listen_endpoint} 0/2,"
               f"{servers['p1a'].listen_endpoint} 1/2,"
               f"{servers['p1b'].listen_endpoint} 1/2")
        pch = PartitionChannel()
        assert pch.init(url, "rr") == 0
        assert pch.partitions == [0, 1]

        # per-partition request shaping: partition k gets its own slice
        c = pch.call_method(
            "T.Who", b"k0|k1",
            call_mapper=lambda i, sub, req: req.split(b"|")[i])
        assert not c.failed, c.error_text
        assert len(c.response) == 2
        assert c.response[0].endswith(b":k0")
        assert c.response[0][:2] == b"p0"
        assert c.response[1].endswith(b":k1")
        assert c.response[1][:2] == b"p1"
        pch.stop()
    finally:
        for s in servers.values():
            s.stop()
