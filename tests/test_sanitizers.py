"""Runtime sanitizers: stall watchdog catches a stuck butex wait; the
lock-order detector flags an ABBA inversion without needing the actual
deadlock timing."""

import threading
import time

import pytest

from brpc_tpu.butil.flags import set_flag
from brpc_tpu.butil.sanitizers import (DebugLock, check_stalls,
                                       lock_order_warnings,
                                       reset_for_tests)
from brpc_tpu.fiber.butex import Butex


@pytest.fixture(autouse=True)
def _clean():
    reset_for_tests()
    yield
    set_flag("stall_watchdog_s", 0.0)
    set_flag("debug_lock_order", False)
    reset_for_tests()


def test_stall_watchdog_reports_stuck_wait_once():
    set_flag("stall_watchdog_s", 0.05)
    bx = Butex(0)
    t = threading.Thread(target=lambda: bx.wait(0, timeout=5.0),
                         daemon=True)
    t.start()
    time.sleep(0.15)                      # wait is now past the limit
    assert check_stalls() == 1            # reported
    assert check_stalls() == 0            # only once per wait
    bx.wake_all()
    t.join(2)
    assert not t.is_alive()


def test_no_report_under_threshold():
    set_flag("stall_watchdog_s", 5.0)
    bx = Butex(0)
    t = threading.Thread(target=lambda: bx.wait(0, timeout=2.0),
                         daemon=True)
    t.start()
    time.sleep(0.05)
    assert check_stalls() == 0
    bx.wake_all()
    t.join(2)


def test_lock_order_cycle_detected():
    set_flag("debug_lock_order", True)
    a, b = DebugLock("A"), DebugLock("B")

    with a:
        with b:                           # records A -> B
            pass
    assert lock_order_warnings() == 0

    def inverted():
        with b:
            with a:                       # B -> A closes the cycle
                pass

    t = threading.Thread(target=inverted)
    t.start()
    t.join(2)
    assert lock_order_warnings() == 1

    # the same cycle does not re-warn — in either direction
    t = threading.Thread(target=inverted)
    t.start()
    t.join(2)
    assert lock_order_warnings() == 1
    with a:
        with b:                       # original order re-trips the path
            pass
    assert lock_order_warnings() == 1


def test_consistent_order_never_warns():
    set_flag("debug_lock_order", True)
    a, b = DebugLock("A2"), DebugLock("B2")
    for _ in range(5):
        with a:
            with b:
                pass
    assert lock_order_warnings() == 0


def test_execution_queue_lock_in_order_graph():
    """The fiber ExecutionQueue's lock is a DebugLock with a ROLE name
    (instance digits stripped — bounded graph), so queue↔app lock
    inversions show up in the order graph like any other ABBA."""
    set_flag("debug_lock_order", True)
    from brpc_tpu.fiber.execution_queue import ExecutionQueue

    q = ExecutionQueue(lambda it: list(it), name="sanit_probe_7")
    assert isinstance(q._lock, DebugLock)
    assert q._lock.name == "execq:sanit_probe"      # digits stripped

    app = DebugLock("APP_SAN")
    done = threading.Event()

    def executor(it):
        for _ in it:
            with app:                 # execq held -> APP_SAN acquired
                pass
        done.set()

    q2 = ExecutionQueue(executor, name="sanit_probe_8")
    # NOTE: execute() itself acquires the queue lock, and the consumer
    # acquires it around batch pops — the executor callback runs with
    # the queue lock RELEASED, so the edge recorded here is the benign
    # producer-side one; the inversion below closes the cycle
    q2.execute("x")
    assert done.wait(2)

    with app:
        q2._lock.acquire()            # APP_SAN held -> execq acquired
        q2._lock.release()
    # whether this warns depends on which thread interleaving recorded
    # the first edge; the assertion is that BOTH edges exist (the graph
    # saw the queue role), not the warn count
    from brpc_tpu.butil import sanitizers as _san
    with _san._order_lock:
        edges = {k: set(v) for k, v in _san._edges.items()}
    assert "execq:sanit_probe" in edges.get("APP_SAN", set()) \
        or "APP_SAN" in edges.get("execq:sanit_probe", set())


def test_lock_order_warning_count_exported_as_bvar():
    """sanitizer_lock_order_warnings rides /vars once any DebugLock
    exists (satellite: the count was test-only before)."""
    DebugLock("EXPORT_PROBE")          # triggers lazy registration
    from brpc_tpu.bvar import find_exposed
    v = find_exposed("sanitizer_lock_order_warnings")
    assert v is not None
    assert int(v.get_value()) == lock_order_warnings()
