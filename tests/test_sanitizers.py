"""Runtime sanitizers: stall watchdog catches a stuck butex wait; the
lock-order detector flags an ABBA inversion without needing the actual
deadlock timing."""

import threading
import time

import pytest

from brpc_tpu.butil.flags import set_flag
from brpc_tpu.butil.sanitizers import (DebugLock, check_stalls,
                                       lock_order_warnings,
                                       reset_for_tests)
from brpc_tpu.fiber.butex import Butex


@pytest.fixture(autouse=True)
def _clean():
    reset_for_tests()
    yield
    set_flag("stall_watchdog_s", 0.0)
    set_flag("debug_lock_order", False)
    reset_for_tests()


def test_stall_watchdog_reports_stuck_wait_once():
    set_flag("stall_watchdog_s", 0.05)
    bx = Butex(0)
    t = threading.Thread(target=lambda: bx.wait(0, timeout=5.0),
                         daemon=True)
    t.start()
    time.sleep(0.15)                      # wait is now past the limit
    assert check_stalls() == 1            # reported
    assert check_stalls() == 0            # only once per wait
    bx.wake_all()
    t.join(2)
    assert not t.is_alive()


def test_no_report_under_threshold():
    set_flag("stall_watchdog_s", 5.0)
    bx = Butex(0)
    t = threading.Thread(target=lambda: bx.wait(0, timeout=2.0),
                         daemon=True)
    t.start()
    time.sleep(0.05)
    assert check_stalls() == 0
    bx.wake_all()
    t.join(2)


def test_lock_order_cycle_detected():
    set_flag("debug_lock_order", True)
    a, b = DebugLock("A"), DebugLock("B")

    with a:
        with b:                           # records A -> B
            pass
    assert lock_order_warnings() == 0

    def inverted():
        with b:
            with a:                       # B -> A closes the cycle
                pass

    t = threading.Thread(target=inverted)
    t.start()
    t.join(2)
    assert lock_order_warnings() == 1

    # the same cycle does not re-warn — in either direction
    t = threading.Thread(target=inverted)
    t.start()
    t.join(2)
    assert lock_order_warnings() == 1
    with a:
        with b:                       # original order re-trips the path
            pass
    assert lock_order_warnings() == 1


def test_consistent_order_never_warns():
    set_flag("debug_lock_order", True)
    a, b = DebugLock("A2"), DebugLock("B2")
    for _ in range(5):
        with a:
            with b:
                pass
    assert lock_order_warnings() == 0
