"""Cluster hardening tests: timeout concurrency limiter, cluster recover
policy, LA-LB weight tree, DynamicPartitionChannel
(≈ /root/reference/src/brpc/policy/timeout_concurrency_limiter.h,
cluster_recover_policy.h, policy/locality_aware_load_balancer.h:41-80,
partition_channel.h:136)."""

import pytest

from brpc_tpu.butil.endpoint import EndPoint
from brpc_tpu.client.naming_service import ServerNode
from brpc_tpu.policy.concurrency_limiter import (TimeoutLimiter,
                                                 make_limiter)
from brpc_tpu.policy.load_balancers import LocalityAwareLB, WeightTree


def _node(port, tag=""):
    return ServerNode(endpoint=EndPoint(host="10.0.0.1", port=port), tag=tag)


# -- timeout concurrency limiter -------------------------------------------

def test_timeout_limiter_tracks_latency_budget():
    lim = TimeoutLimiter(timeout_ms=100, min_limit=2, max_limit=1000)
    # 10ms avg latency -> ~10 requests fit in a 100ms budget
    for _ in range(50):
        lim.on_responded(0, 10_000)
    assert 8 <= lim.max_concurrency() <= 12
    # latency inflates to 50ms -> limit shrinks toward 2
    for _ in range(80):
        lim.on_responded(0, 50_000)
    assert lim.max_concurrency() <= 3


def test_timeout_limiter_counts_failures_at_full_timeout():
    lim = TimeoutLimiter(timeout_ms=100, min_limit=1)
    for _ in range(60):
        lim.on_responded(1008, 0)        # timeouts
    assert lim.max_concurrency() <= 2


def test_make_limiter_timeout_specs():
    assert isinstance(make_limiter("timeout"), TimeoutLimiter)
    lim = make_limiter("timeout:250")
    assert isinstance(lim, TimeoutLimiter)
    assert lim._timeout_us == 250_000


def test_timeout_limiter_enforced_end_to_end():
    import time

    from brpc_tpu.client import Channel, ChannelOptions, Controller
    from brpc_tpu.server import Server, ServerOptions, Service

    class Slow(Service):
        def Hit(self, cntl, request):
            time.sleep(0.08)
            return b"ok"

    opts = ServerOptions()
    opts.method_max_concurrency = {"S.Hit": "timeout:20"}
    srv = Server(opts)
    srv.add_service(Slow(), name="S")
    assert srv.start("127.0.0.1:0") == 0
    try:
        co = ChannelOptions()
        co.timeout_ms = 2000
        co.max_retry = 0
        ch = Channel(co)
        ch.init(str(srv.listen_endpoint))
        codes = []
        for _ in range(6):
            cntl = Controller()
            ch.call_method("S.Hit", b"", cntl=cntl)
            codes.append(cntl.error_code)
        # after the first 80ms responses the 20ms budget admits ~1
        # concurrent request; the serial loop still succeeds, proving
        # the limiter converged without rejecting a healthy pipeline
        entry = srv.find_method("S", "Hit")
        assert entry.status.limiter.max_concurrency() <= 2
        assert codes[-1] == 0
    finally:
        srv.stop()


# -- cluster recover policy -------------------------------------------------

def test_cluster_recover_probes_isolated_servers():
    from brpc_tpu.client.circuit_breaker import global_circuit_breaker_map
    from brpc_tpu.policy.load_balancers import RoundRobinLB

    lb = RoundRobinLB()
    lb.use_circuit_breaker = True
    lb.min_working_instances = 2
    nodes = [_node(9001), _node(9002), _node(9003)]
    lb.reset_servers(nodes)

    breakers = global_circuit_breaker_map()
    # break two of three servers
    for n in nodes[:2]:
        for _ in range(200):
            breakers.on_call(n.endpoint, 1014, 100_000)
    broken = [n for n in nodes if breakers.isolated(n.endpoint)]
    if len(broken) < 2:
        pytest.skip("breaker did not isolate under this config")

    class C:
        excluded_servers = set()
        remote_side = None

    picked = {lb.select_server(C()) for _ in range(60)}
    # recovering mode must include isolated servers in the rotation
    assert lb.recovering
    assert any(n.endpoint in picked for n in broken)
    # heal them: expire the isolation windows (isolation is time-based);
    # the next selection sees enough working instances and drops the flag
    for n in nodes:
        nb = breakers._nodes.get(n.endpoint)
        if nb is not None:
            nb.isolated_until = 0.0
    lb.select_server(C())
    assert not lb.recovering


# -- LA-LB weight tree ------------------------------------------------------

def test_weight_tree_pick_distribution():
    t = WeightTree(4)
    for i, w in enumerate([1.0, 0.0, 3.0, 6.0]):
        t.update(i, w)
    assert t.total() == pytest.approx(10.0)
    counts = [0] * 4
    steps = 1000
    for k in range(steps):
        r = (k + 0.5) / steps * 10.0
        counts[t.pick(r)] += 1
    assert counts[1] == 0
    assert counts[0] == pytest.approx(100, abs=5)
    assert counts[2] == pytest.approx(300, abs=5)
    assert counts[3] == pytest.approx(600, abs=5)
    # dynamic update shifts mass
    t.update(3, 0.0)
    assert t.total() == pytest.approx(4.0)
    assert t.pick(3.9) == 2


def test_la_lb_prefers_fast_server():
    lb = LocalityAwareLB()
    nodes = [_node(9101), _node(9102)]
    lb.reset_servers(nodes)

    class C:
        excluded_servers = set()
        remote_side = None
        error_code = 0
        latency_us = 0
        attempt_remotes = {}

    # teach it: 9101 is 10x faster
    for _ in range(60):
        for n, lat in ((nodes[0], 1_000), (nodes[1], 10_000)):
            ep = lb.select(nodes, C())          # bump inflight
            c = C()
            c.remote_side = n.endpoint
            c.latency_us = lat
            c.attempt_remotes = {0: n.endpoint}
            lb.feedback(c)
    picks = [lb.select(nodes, C()).endpoint.port for _ in range(300)]
    # drain inflight so the punish term doesn't accumulate
    fast = picks.count(9101)
    assert fast > 200, f"fast server got only {fast}/300"


def test_la_lb_respects_exclusions():
    lb = LocalityAwareLB()
    nodes = [_node(9201), _node(9202)]
    lb.reset_servers(nodes)

    class C:
        excluded_servers = {nodes[0].endpoint}
        remote_side = None

    for _ in range(10):
        ep = lb.select_server(C())
        assert ep == nodes[1].endpoint


# -- DynamicPartitionChannel ------------------------------------------------

def test_dynamic_partition_scheme_weighting():
    from brpc_tpu.client.partition_channel import DynamicPartitionChannel

    dpc = DynamicPartitionChannel()
    dpc._lb_name = "rr"
    # 2-scheme complete with 2 replicas each; 3-scheme complete with 1 each
    nodes = ([_node(9300 + i, tag=f"{i % 2}/2") for i in range(4)]
             + [_node(9400 + i, tag=f"{i}/3") for i in range(3)])
    dpc._on_servers(nodes)
    w = dpc.scheme_weights
    assert w == {2: 4, 3: 3}
    # incomplete scheme is dropped
    nodes2 = [_node(9500, tag="0/2")] + [_node(9600 + i, tag=f"{i}/3")
                                         for i in range(3)]
    dpc._on_servers(nodes2)
    assert dpc.scheme_weights == {3: 3}


def test_dynamic_partition_live_migration():
    """Real servers: start with a 2-partition scheme, migrate to 3."""
    from brpc_tpu.client import ChannelOptions
    from brpc_tpu.client.partition_channel import DynamicPartitionChannel
    from brpc_tpu.server import Server, Service

    class Part(Service):
        def __init__(self, label):
            self.label = label

        def Get(self, cntl, request):
            return self.label

    servers = []

    def spawn(label):
        s = Server()
        s.add_service(Part(label), name="P")
        assert s.start("127.0.0.1:0") == 0
        servers.append(s)
        return s

    try:
        two = [spawn(b"2p-%d" % i) for i in range(2)]
        co = ChannelOptions()
        co.timeout_ms = 3000
        dpc = DynamicPartitionChannel(options=co)
        url = "list://" + ",".join(
            f"{s.listen_endpoint} {i}/2" for i, s in enumerate(two))
        assert dpc.init(url, "rr") == 0
        c = dpc.call_method("P.Get", b"")
        assert not c.failed, c.error_text
        assert dpc.scheme_weights == {2: 2}

        # migration: the 3-partition generation appears in naming
        three = [spawn(b"3p-%d" % i) for i in range(3)]
        nodes = ([ServerNode(endpoint=s.listen_endpoint, tag=f"{i}/2")
                  for i, s in enumerate(two)]
                 + [ServerNode(endpoint=s.listen_endpoint, tag=f"{i}/3")
                    for i, s in enumerate(three)])
        dpc._on_servers(nodes)
        assert dpc.scheme_weights == {2: 2, 3: 3}
        seen_counts = set()
        for _ in range(20):
            c = dpc.call_method("P.Get", b"")
            assert not c.failed, c.error_text
            seen_counts.add(len(c.response) and c.response.count(b"|"))
        # old scheme drains away
        dpc._on_servers([ServerNode(endpoint=s.listen_endpoint,
                                    tag=f"{i}/3")
                         for i, s in enumerate(three)])
        assert dpc.scheme_weights == {3: 3}
        c = dpc.call_method("P.Get", b"")
        assert not c.failed
        dpc.stop()
    finally:
        for s in servers:
            s.stop()
