"""Adversarial fault-injection suite — SURVEY §4's closing lesson:
drive the full client/server stack through a fault-injecting transport
(drops, delays, partitions, corruption, reordering), churn naming
during in-flight calls, race stream close against writes, and recycle
correlation-id versions (fixture shape
≈ /root/reference/test/brpc_channel_unittest.cpp:166-230)."""

import threading
import time

import pytest

from brpc_tpu.butil.status import Errno
from brpc_tpu.client import Channel, ChannelOptions, Controller
from brpc_tpu.server import Server, Service
from fault_proxy import FaultyTransport


class Echo(Service):
    def Echo(self, cntl, request):
        return request

    def Slow(self, cntl, request):
        time.sleep(0.2)
        return b"slow"


@pytest.fixture(scope="module")
def backend():
    srv = Server()
    srv.add_service(Echo(), name="E")
    assert srv.start("127.0.0.1:0") == 0
    yield srv
    srv.stop()


@pytest.fixture()
def proxy(backend):
    ep = backend.listen_endpoint
    p = FaultyTransport(ep.host, ep.port)
    yield p
    p.close()


def _channel(proxy, timeout_ms=2000, max_retry=3, ctype="pooled"):
    co = ChannelOptions()
    co.timeout_ms = timeout_ms
    co.max_retry = max_retry
    co.connection_type = ctype
    ch = Channel(co)
    assert ch.init(proxy.address) == 0
    return ch


def test_clean_proxy_baseline(proxy):
    ch = _channel(proxy)
    for i in range(10):
        assert ch.call("E.Echo", b"m%d" % i) == b"m%d" % i


def test_injected_delay_adds_latency_then_heals(proxy):
    ch = _channel(proxy, timeout_ms=5000)
    assert ch.call("E.Echo", b"warm") == b"warm"
    proxy.delay_s = 0.15
    cntl = Controller()
    cntl.timeout_ms = 5000
    ch.call_method("E.Echo", b"delayed", cntl=cntl)
    assert not cntl.failed
    assert cntl.latency_us >= 140_000          # both directions delayed
    proxy.heal()
    cntl = Controller()
    cntl.timeout_ms = 5000
    ch.call_method("E.Echo", b"fast-again", cntl=cntl)
    assert not cntl.failed and cntl.latency_us < 140_000


def test_delay_beyond_deadline_times_out_and_recovers(proxy):
    ch = _channel(proxy, timeout_ms=300, max_retry=0)
    assert ch.call("E.Echo", b"warm") == b"warm"
    proxy.delay_s = 1.0
    cntl = Controller()
    cntl.timeout_ms = 300
    ch.call_method("E.Echo", b"too-slow", cntl=cntl)
    assert cntl.failed and cntl.error_code == int(Errno.ERPCTIMEDOUT)
    proxy.heal()
    # the timed-out pooled connection was failed, a fresh one works
    assert ch.call("E.Echo", b"recovered") == b"recovered"


def test_connection_cut_mid_response_fails_cleanly(proxy):
    ch = _channel(proxy, timeout_ms=2000, max_retry=0)
    assert ch.call("E.Echo", b"warm") == b"warm"
    proxy.drop_after_bytes = proxy.forwarded_bytes + 10   # cut mid-frame
    cntl = Controller()
    cntl.timeout_ms = 2000
    ch.call_method("E.Echo", b"x" * 4096, cntl=cntl)
    assert cntl.failed
    proxy.heal()
    assert ch.call("E.Echo", b"back") == b"back"


def test_connection_cut_with_retries_succeeds(proxy):
    ch = _channel(proxy, timeout_ms=5000, max_retry=3)
    assert ch.call("E.Echo", b"warm") == b"warm"
    proxy.drop_after_bytes = proxy.forwarded_bytes + 5
    # first attempt dies on the cut; the retry reconnects (cut cleared
    # once tripped by the break) and must succeed
    cntl = Controller()
    cntl.timeout_ms = 5000
    proxy.drop_after_bytes = proxy.forwarded_bytes + 5
    ch.call_method("E.Echo", b"retry-me", cntl=cntl)
    proxy.heal()
    if cntl.failed:
        # retried attempts may race the cut marker; the channel must
        # still converge once healed
        assert ch.call("E.Echo", b"converged") == b"converged"
    else:
        assert cntl.response == b"retry-me"


def test_partition_then_heal(proxy):
    ch = _channel(proxy, timeout_ms=400, max_retry=1)
    assert ch.call("E.Echo", b"warm") == b"warm"
    proxy.partition = True
    cntl = Controller()
    cntl.timeout_ms = 400
    t0 = time.monotonic()
    ch.call_method("E.Echo", b"void", cntl=cntl)
    assert cntl.failed
    assert time.monotonic() - t0 < 5.0
    proxy.partition = False
    proxy.kill_connections()          # stale blackholed conns die
    assert ch.call("E.Echo", b"healed") == b"healed"


def test_corrupted_byte_detected(proxy):
    ch = _channel(proxy, timeout_ms=2000, max_retry=0)
    assert ch.call("E.Echo", b"warm") == b"warm"
    # the pumps count forwarded bytes AFTER sendall, so the client can
    # see the warm response before the counter includes it — wait for
    # the counter to go quiet or the +2 offset can land in the past
    # (never matching) and the poisoned call sails through clean
    stable, deadline = -1, time.time() + 2.0
    while time.time() < deadline:
        cur = proxy.forwarded_bytes
        if cur == stable:
            break
        stable = cur
        time.sleep(0.05)
    proxy.corrupt_byte_at = proxy.forwarded_bytes + 2   # clobber a header
    cntl = Controller()
    cntl.timeout_ms = 2000
    ch.call_method("E.Echo", b"poisoned", cntl=cntl)
    # corruption may hit the request (server kills conn) or the
    # response (client parse fails): either way the call must FAIL,
    # never deliver corrupt payload silently
    assert cntl.failed
    proxy.heal()
    assert ch.call("E.Echo", b"clean") == b"clean"


def test_reordered_segments_still_parse_or_fail(proxy):
    """TCP-level reordering through the proxy (bytes swap across
    segments): the framed parser must either reassemble correctly (if
    offsets happen to align) or fail the connection — never deliver
    wrong bytes as a valid response."""
    ch = _channel(proxy, timeout_ms=2000, max_retry=3)
    assert ch.call("E.Echo", b"warm") == b"warm"
    proxy.reorder_window = 2
    payload = bytes(range(256)) * 64          # multi-segment
    for _ in range(3):
        cntl = Controller()
        cntl.timeout_ms = 2000
        ch.call_method("E.Echo", payload, cntl=cntl)
        if not cntl.failed:
            assert cntl.response == payload
    proxy.heal()
    assert ch.call("E.Echo", b"after") == b"after"


# -- naming churn during in-flight traffic ----------------------------------

def test_naming_churn_under_load(backend):
    """Cluster channel whose server list flips every few ms while calls
    are in flight: no crashes, and calls keep succeeding (retries may
    fire, wrong-server attempts excluded)."""
    srv2 = Server()
    srv2.add_service(Echo(), name="E")
    assert srv2.start("127.0.0.1:0") == 0
    try:
        ep1, ep2 = backend.listen_endpoint, srv2.listen_endpoint
        co = ChannelOptions()
        co.timeout_ms = 2000
        ch = Channel(co)
        assert ch.init(f"list://{ep1},{ep2}", "rr") == 0
        lb = ch.load_balancer

        stop = threading.Event()

        def churn():
            from brpc_tpu.client.naming_service import ServerNode
            flip = False
            while not stop.is_set():
                flip = not flip
                nodes = [ServerNode(endpoint=ep1)] if flip else \
                    [ServerNode(endpoint=ep1), ServerNode(endpoint=ep2)]
                lb._lb.reset_servers(nodes) if hasattr(lb, "_lb") \
                    else lb.reset_servers(nodes)
                time.sleep(0.002)

        t = threading.Thread(target=churn)
        t.start()
        try:
            ok = 0
            for i in range(300):
                cntl = Controller()
                cntl.timeout_ms = 2000
                ch.call_method("E.Echo", b"c%d" % i, cntl=cntl)
                if not cntl.failed:
                    ok += 1
            assert ok >= 295, f"only {ok}/300 under naming churn"
        finally:
            stop.set()
            t.join()
    finally:
        srv2.stop()


# -- stream close/write races -----------------------------------------------

def test_stream_close_write_race(backend):
    from brpc_tpu.streaming import StreamOptions, stream_accept, stream_create

    class Sink(Service):
        def Start(self, cntl, request):
            stream_accept(cntl, StreamOptions(on_received=lambda s, m: None))
            return b"ok"

    srv = Server()
    srv.add_service(Sink(), name="SK")
    assert srv.start("127.0.0.1:0") == 0
    try:
        for round_ in range(10):
            ch = Channel()
            ch.init(str(srv.listen_endpoint))
            cntl = Controller()
            cntl.timeout_ms = 3000
            stream = stream_create(cntl, StreamOptions())
            c = ch.call_method("SK.Start", b"", cntl=cntl)
            assert not c.failed, c.error_text
            errs = []

            def writer():
                for _ in range(100):
                    rc = stream.write(b"data")
                    if rc != 0:
                        errs.append(rc)
                        return

            w = threading.Thread(target=writer)
            w.start()
            time.sleep(0.001 * (round_ % 4))
            stream.close()
            w.join(5)
            assert not w.is_alive(), "writer deadlocked against close"
            # post-close writes must fail, not hang or crash
            assert stream.write(b"late") != 0
    finally:
        srv.stop()


# -- correlation id version recycling ---------------------------------------

def test_id_version_recycling_rejects_stale():
    from brpc_tpu.fiber.versioned_id import global_id_pool

    idp = global_id_pool()
    seen = set()
    stale = []
    for i in range(2000):
        holder = object()
        cid = idp.create_ranged(holder, lambda *a: None, 4)
        assert cid not in seen          # versions never collide while live
        seen.add(cid)
        ok, data = idp.lock(cid)
        assert ok and data is holder
        idp.unlock_and_destroy(cid)
        stale.append(cid)
    # every destroyed id must refuse to lock (stale version)
    for cid in stale[-50:]:
        ok, _ = idp.lock(cid)
        assert not ok


class RawAndTensor(Service):
    from brpc_tpu.server.service import raw_method

    @raw_method
    def REcho(self, payload, attachment):
        return bytes(payload), attachment

    def TEcho(self, cntl, request):
        att = cntl.request_device_attachment
        if att is not None:
            cntl.response_device_attachment = att.tensor()
        return b"t"


@pytest.fixture(scope="module")
def raw_backend():
    srv = Server()
    srv.add_service(RawAndTensor(), name="RT")
    assert srv.start("127.0.0.1:0") == 0
    yield srv
    srv.stop()


@pytest.fixture()
def raw_proxy(raw_backend):
    ep = raw_backend.listen_endpoint
    p = FaultyTransport(ep.host, ep.port)
    yield p
    p.close()


def test_raw_lane_through_faulty_proxy_baseline(raw_proxy):
    ch = _channel(raw_proxy, timeout_ms=3000)
    for i in range(8):
        r, a = ch.call_raw("RT.REcho", b"p%d" % i, b"a%d" % i,
                           timeout_ms=3000)
        assert bytes(r) == b"p%d" % i and bytes(a) == b"a%d" % i


def test_raw_lane_survives_connection_cut(raw_proxy):
    """Cut the connection mid-traffic: the raw lane reports the failure
    (no retries by contract) and the NEXT call transparently pins a
    fresh connection."""
    from brpc_tpu.client.channel import RpcError
    ch = _channel(raw_proxy, timeout_ms=3000)
    r, _ = ch.call_raw("RT.REcho", b"warm", timeout_ms=3000)
    assert bytes(r) == b"warm"
    raw_proxy.drop_after_bytes = raw_proxy.forwarded_bytes  # cut NOW
    try:
        ch.call_raw("RT.REcho", b"dead", timeout_ms=1000)
    except RpcError:
        pass          # expected: cut or timeout
    raw_proxy.heal()
    deadline = time.time() + 5.0
    ok = False
    while time.time() < deadline and not ok:
        try:
            r, _ = ch.call_raw("RT.REcho", b"back", timeout_ms=2000)
            ok = bytes(r) == b"back"
        except RpcError:
            time.sleep(0.05)
    assert ok, "raw lane never recovered after heal"


def test_raw_lane_with_delay(raw_proxy):
    """An injected 50ms delay must surface as latency, not corruption."""
    ch = _channel(raw_proxy, timeout_ms=5000)
    r, _ = ch.call_raw("RT.REcho", b"warm", timeout_ms=5000)
    raw_proxy.delay_s = 0.05
    t0 = time.time()
    r, _ = ch.call_raw("RT.REcho", b"slowpath", timeout_ms=5000)
    assert bytes(r) == b"slowpath"
    assert time.time() - t0 >= 0.05
    raw_proxy.heal()


def test_device_attachment_calls_through_faulty_proxy(raw_proxy):
    """Device-descriptor RPCs (with piggybacked TICI acks on the wire)
    parse correctly through a proxy that re-segments the byte stream,
    and the window drains."""
    import jax.numpy as jnp
    import numpy as np
    from brpc_tpu.ici.endpoint import live_endpoints

    ch = _channel(raw_proxy, timeout_ms=10_000)
    x = jnp.arange(4096, dtype=jnp.float32)
    for i in range(6):
        cntl = Controller()
        cntl.timeout_ms = 10_000
        cntl.request_device_attachment = x
        c = ch.call_method("RT.TEcho", b"", cntl=cntl)
        assert not c.failed, (i, c.error_text)
        att = c.response_device_attachment
        assert att is not None
        np.testing.assert_array_equal(np.asarray(att.tensor()),
                                      np.asarray(x))
    deadline = time.time() + 5.0
    while time.time() < deadline:
        if all(ep.outstanding_bytes == 0 for ep in live_endpoints()):
            break
        time.sleep(0.01)
    assert all(ep.outstanding_bytes == 0 for ep in live_endpoints())


def test_corrupted_tici_ack_fails_or_recovers_never_corrupts(raw_proxy):
    """A corrupted byte inside the credit-return path must never make a
    call deliver wrong payload bytes: either the call fails (connection
    killed on parse error) or the payload round-trips intact."""
    import jax.numpy as jnp
    import numpy as np

    ch = _channel(raw_proxy, timeout_ms=5000)
    x = jnp.arange(1024, dtype=jnp.float32)
    cntl = Controller()
    cntl.timeout_ms = 5000
    cntl.request_device_attachment = x
    c = ch.call_method("RT.TEcho", b"", cntl=cntl)
    assert not c.failed, c.error_text
    c.response_device_attachment.tensor()
    # corrupt a byte a little into the upcoming exchange (lands in the
    # next request frame or its piggybacked ack, depending on timing)
    stable, deadline = -1, time.time() + 2.0
    while time.time() < deadline:
        cur = raw_proxy.forwarded_bytes
        if cur == stable:
            break
        stable = cur
        time.sleep(0.05)
    raw_proxy.corrupt_byte_at = raw_proxy.forwarded_bytes + 5
    cntl = Controller()
    cntl.timeout_ms = 5000
    cntl.request_device_attachment = x
    c = ch.call_method("RT.TEcho", b"", cntl=cntl)
    if not c.failed and c.response_device_attachment is not None:
        np.testing.assert_array_equal(
            np.asarray(c.response_device_attachment.tensor()),
            np.asarray(x))
    raw_proxy.heal()


# -- deadline plane under injected faults -----------------------------------

def test_deadline_expiry_sheds_server_side_under_delay():
    """Through a delay-injecting proxy, a pipelined burst whose first
    request chews the native batch makes the second one's propagated
    budget expire IN QUEUE: the server answers ERPCTIMEDOUT without
    running the handler (deadline plane; ≈ brpc -server_fail_fast)."""
    import socket as pysock
    import struct

    from brpc_tpu.deadline import shed_counters
    from brpc_tpu.protocol.meta import RpcMeta, TLV_CORRELATION, \
        TLV_TIMEOUT, encode_tlv
    from brpc_tpu.server import ServerOptions
    from conftest import require_native
    require_native()

    class SlowEcho(Service):
        def __init__(self):
            self.echo_calls = 0

        def Echo(self, cntl, request):
            self.echo_calls += 1
            return bytes(request)

        def Slow(self, cntl, request):
            time.sleep(0.25)
            return b"slow"

    opts = ServerOptions()
    opts.native = True
    opts.usercode_inline = True
    opts.native_loops = 1
    svc = SlowEcho()
    srv = Server(opts)
    srv.add_service(svc, name="DL")
    assert srv.start("127.0.0.1:0") == 0
    ep = srv.listen_endpoint
    p = FaultyTransport(ep.host, ep.port)
    try:
        p.delay_s = 0.02

        def frame(cid, mth, payload, tmo=None):
            mb = TLV_CORRELATION + struct.pack("<Q", cid)
            mb += encode_tlv(4, b"DL") + encode_tlv(5, mth)
            if tmo is not None:
                mb += TLV_TIMEOUT + struct.pack("<I", tmo)
            body = mb + payload
            return b"TRPC" + struct.pack("<II", len(body), len(mb)) + body

        before = shed_counters().get(("slim", "DL.Echo"), 0)
        with pysock.create_connection(("127.0.0.1", p.port),
                                      timeout=10) as c:
            c.sendall(frame(1, b"Slow", b"") +
                      frame(2, b"Echo", b"doomed", tmo=60))
            c.settimeout(10)
            buf = b""
            metas = {}
            while len(metas) < 2:
                while True:
                    if len(buf) >= 12:
                        (blen,) = struct.unpack_from("<I", buf, 4)
                        if len(buf) >= 12 + blen:
                            break
                    buf += c.recv(65536)
                (blen,) = struct.unpack_from("<I", buf, 4)
                (mlen,) = struct.unpack_from("<I", buf, 8)
                m = RpcMeta.decode(buf[12:12 + mlen])
                metas[m.correlation_id] = m
                buf = buf[12 + blen:]
        assert metas[1].error_code == 0
        assert metas[2].error_code == int(Errno.ERPCTIMEDOUT)
        assert svc.echo_calls == 0          # the handler never ran
        assert shed_counters().get(("slim", "DL.Echo"), 0) == before + 1
    finally:
        p.close()
        srv.stop()


def test_retry_storm_capped_by_budget():
    """A dead backend behind the proxy: the channel retry budget bounds
    proxy-observed attempts; an unbudgeted channel storms.  Attempts
    are counted AT THE PROXY (connections — every failed attempt costs
    a fresh connect)."""
    # a port with no listener: the proxy accepts, fails upstream, and
    # closes — every client attempt is one accepted connection
    import socket as pysock
    probe = pysock.socket()
    probe.bind(("127.0.0.1", 0))
    dead_port = probe.getsockname()[1]
    probe.close()

    p = FaultyTransport("127.0.0.1", dead_port)
    try:
        def storm(budget_max):
            co = ChannelOptions()
            co.timeout_ms = 2000
            co.max_retry = 3
            co.connection_type = "pooled"
            co.retry_budget_max = budget_max
            ch = Channel(co)
            assert ch.init(p.address) == 0
            start = p.connections
            for _ in range(8):
                cntl = Controller()
                cntl.timeout_ms = 2000
                c = ch.call_method("E.Echo", b"x", cntl=cntl)
                assert c.failed
            # the proxy accept loop is async: settle
            deadline = time.time() + 2.0
            last = -1
            while time.time() < deadline:
                cur = p.connections
                if cur == last:
                    break
                last = cur
                time.sleep(0.05)
            return p.connections - start, ch

        capped_attempts, capped_ch = storm(budget_max=4)
        uncapped_attempts, _ = storm(budget_max=0)
        # budget 4 → exactly 2 granted retries: 8 originals + 2
        assert capped_attempts <= 12, capped_attempts
        assert capped_ch.retry_budget().denied_count > 0
        # no budget → full 1 + max_retry amplification
        assert uncapped_attempts >= 24, uncapped_attempts
        assert uncapped_attempts > capped_attempts * 2
    finally:
        p.close()


def test_flapping_backend_trips_breaker_from_raw_lane():
    """The pinned raw lane (call_raw) has no LB in the path, yet its
    outcomes must feed the GLOBAL circuit breaker when the channel opts
    in — a flapping backend observed only through raw calls still gets
    isolated for every cluster channel sharing it."""
    from brpc_tpu.client.channel import RpcError
    from brpc_tpu.client.circuit_breaker import global_circuit_breaker_map
    from brpc_tpu.server.service import raw_method

    class RawSvc(Service):
        @raw_method
        def REcho(self, payload, attachment):
            return bytes(payload), attachment

    m = global_circuit_breaker_map()
    m.reset()
    srv = Server()
    srv.add_service(RawSvc(), name="RW")
    assert srv.start("127.0.0.1:0") == 0
    ep = srv.listen_endpoint
    try:
        co = ChannelOptions()
        co.timeout_ms = 1000
        co.enable_circuit_breaker = True
        ch = Channel(co)
        ch.init(str(ep))
        for _ in range(2):
            r, _a = ch.call_raw("RW.REcho", b"warm", timeout_ms=1000)
            assert bytes(r) == b"warm"
        srv.stop()
        fails = 0
        deadline = time.time() + 10
        while fails < 12 and time.time() < deadline:
            try:
                ch.call_raw("RW.REcho", b"down", timeout_ms=300)
            except RpcError:
                fails += 1
        assert fails >= 12
        assert m.isolated(ep), "raw-lane failures never tripped the breaker"
        # and an LB consulting the shared map skips the dead node: only
        # the live server survives candidate filtering
        from brpc_tpu.client.load_balancer import LoadBalancer
        from brpc_tpu.client.naming_service import ServerNode

        class _RR(LoadBalancer):
            def select(self, nodes, cntl):
                return nodes[0]

        live = Server()
        live.add_service(RawSvc(), name="RW")
        assert live.start("127.0.0.1:0") == 0
        try:
            lb = _RR()
            lb.use_circuit_breaker = True
            lb.reset_servers([ServerNode(endpoint=ep),
                              ServerNode(endpoint=live.listen_endpoint)])
            cand = lb.candidates(Controller())
            assert [n.endpoint for n in cand] == [live.listen_endpoint]
        finally:
            live.stop()
    finally:
        m.reset()
        srv.stop()
