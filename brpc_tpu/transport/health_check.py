"""Health checking — revive failed connections by periodic re-connect.

Capability parity with /root/reference/src/brpc/details/health_check.cpp:
70,146,161,237: when a Socket with a health-check interval fails, a
periodic task re-connects; on success the socket is revived (and the
channel's load balancer sees it usable again). An optional app-level
check RPC (``health_check_path``) can gate revival — wired in by the
client layer once HTTP is available.
"""

from __future__ import annotations

from ..butil.logging_util import LOG
from ..bvar.reducer import Adder
from ..fiber.timer_thread import global_timer_thread
from .socket import Socket

_revived = Adder("socket_revive_count")


def start_health_check(sid: int, interval_s: float,
                       max_attempts: int = 0) -> None:
    """Schedule periodic reconnect attempts for the failed socket ``sid``
    every ``interval_s`` (reference default 3s, socket_map.cpp:33)."""
    attempt = {"n": 0}

    def check() -> None:
        s = Socket.address(sid)
        if s is None or not s.failed or s.remote_side is None:
            return                       # destroyed or already revived
        attempt["n"] += 1
        # one shared revival recipe (TLS wrap, dispatcher re-register,
        # serialized against fail-fast revivers) — Socket.reconnect_now
        if s.reconnect_now():
            _revived << 1
            return
        if max_attempts and attempt["n"] >= max_attempts:
            LOG.warning("health check giving up on socket %d (%s)",
                        sid, s.remote_side)
            return
        global_timer_thread().schedule(check, delay_s=interval_s)

    global_timer_thread().schedule(check, delay_s=interval_s)
