"""EventDispatcher — completion notification for the transport.

Capability parity with the reference's epoll dispatcher
(/root/reference/src/brpc/event_dispatcher_epoll.cpp:59,157,190-218): a
dedicated thread blocks in the OS poller; on readiness it wakes the
socket's consumer *task* (never runs user code on the dispatcher thread).

Fresh design notes:

- Built on :mod:`selectors` (epoll on Linux). Read interest is persistent
  (``add_consumer``); write interest is one-shot (``add_epollout``) used
  by Socket's keep-write parking, mirroring WaitEpollOut.
- Control-plane changes (register/unregister from other threads) go
  through a self-pipe so the poller never races its own fd set.
- The same poller is the template for the device-side completion-queue
  poller (ICI transport): poll CQs with spin-then-park, then wake fiber
  tasks — the dispatcher interface is identical, only the "fd" differs.
"""

from __future__ import annotations

import errno
import os
import selectors
import socket as _socket
import threading
from collections import deque
from typing import Callable, Deque, Dict, Optional, Tuple

from ..butil.logging_util import LOG


class EventDispatcher:
    def __init__(self, name: str = "event_dispatcher"):
        self._sel = selectors.DefaultSelector()
        self._name = name
        self._lock = threading.Lock()
        self._pending: Deque[Tuple] = deque()
        self._wakeup_r, self._wakeup_w = os.pipe()
        os.set_blocking(self._wakeup_r, False)
        self._sel.register(self._wakeup_r, selectors.EVENT_READ, ("wakeup",))
        self._thread: Optional[threading.Thread] = None
        self._stopped = False
        # fd -> [read_cb or None, one-shot write_cb or None, read_armed]
        self._interest: Dict[int, list] = {}

    # -- public API --------------------------------------------------------

    def start(self) -> None:
        with self._lock:
            if self._thread is not None:
                return
            self._thread = threading.Thread(
                target=self._run, name=self._name, daemon=True)
            self._thread.start()

    def stop(self) -> None:
        self._stopped = True
        self._wake()
        t = self._thread
        if t is not None:
            t.join(timeout=2.0)

    def add_consumer(self, sock: _socket.socket,
                     on_readable: Callable) -> None:
        """≈ EventDispatcher::AddConsumer (event_dispatcher_epoll.cpp:157):
        one-shot-armed read interest; ``on_readable()`` must not block
        the dispatcher (it only wakes a task). Read interest is
        suspended when an event fires and re-armed by ``rearm_read``
        once the consumer drains to EAGAIN — otherwise the level-
        triggered poller spins while the consumer task works."""
        self._submit(("add_read", sock.fileno(), on_readable))

    def rearm_read(self, fd: int) -> None:
        """Consumer finished (hit EAGAIN): re-enable read interest.
        Pending kernel data re-fires immediately (level-triggered)."""
        self._submit(("rearm_read", fd))

    def remove_consumer(self, sock: _socket.socket) -> None:
        self._submit(("remove", sock.fileno()))

    def add_epollout(self, sock: _socket.socket,
                     on_writable: Callable) -> None:
        """One-shot write-readiness callback (≈ RegisterEvent w/ EPOLLOUT
        for WaitEpollOut)."""
        self._submit(("add_write", sock.fileno(), on_writable))

    # -- internals ---------------------------------------------------------

    def _submit(self, op: Tuple) -> None:
        with self._lock:
            self._pending.append(op)
        self._wake()
        self.start()

    def _wake(self) -> None:
        try:
            os.write(self._wakeup_w, b"\0")
        except OSError:
            pass

    def _apply_pending(self) -> None:
        while True:
            with self._lock:
                if not self._pending:
                    return
                op = self._pending.popleft()
            kind = op[0]
            try:
                if kind == "add_read":
                    _, fd, cb = op
                    ent = self._interest.setdefault(fd, [None, None, True])
                    ent[0] = cb
                    ent[2] = True
                    self._reregister(fd)
                elif kind == "rearm_read":
                    fd = op[1]
                    ent = self._interest.get(fd)
                    if ent is not None and ent[0] is not None:
                        ent[2] = True
                        self._reregister(fd)
                elif kind == "add_write":
                    _, fd, cb = op
                    ent = self._interest.setdefault(fd, [None, None, True])
                    ent[1] = cb
                    self._reregister(fd)
                elif kind == "remove":
                    fd = op[1]
                    self._interest.pop(fd, None)
                    try:
                        self._sel.unregister(fd)
                    except (KeyError, ValueError, OSError):
                        pass
            except (ValueError, OSError) as e:
                if isinstance(e, OSError) and e.errno == errno.EBADF:
                    # fd closed under a queued op (socket torn down
                    # between enqueue and apply): drop the stale
                    # interest quietly — set_failed owns the cleanup
                    self._interest.pop(op[1], None)
                    continue
                LOG.warning("dispatcher op %s failed: %s", kind, e)

    def _reregister(self, fd: int) -> None:
        read_cb, write_cb, armed = self._interest.get(
            fd, (None, None, False))
        events = 0
        if read_cb is not None and armed:
            events |= selectors.EVENT_READ
        if write_cb is not None:
            events |= selectors.EVENT_WRITE
        if events == 0:
            if read_cb is None and write_cb is None:
                self._interest.pop(fd, None)
            try:
                self._sel.unregister(fd)
            except (KeyError, ValueError, OSError):
                pass
            return
        try:
            self._sel.modify(fd, events, ("fd",))
        except KeyError:
            self._sel.register(fd, events, ("fd",))
        except OSError:
            # fd number was closed+reused behind a stale registration:
            # drop the stale entry and register fresh
            try:
                self._sel.unregister(fd)
            except (KeyError, ValueError, OSError):
                pass
            self._sel.register(fd, events, ("fd",))

    def _run(self) -> None:
        while not self._stopped:
            self._apply_pending()
            try:
                events = self._sel.select(timeout=1.0)
            except OSError:
                continue
            for key, mask in events:
                if key.data and key.data[0] == "wakeup":
                    try:
                        while os.read(self._wakeup_r, 4096):
                            pass
                    except (BlockingIOError, OSError):
                        pass
                    continue
                fd = key.fd
                ent = self._interest.get(fd)
                if ent is None:
                    continue
                read_cb, write_cb = ent[0], ent[1]
                if mask & selectors.EVENT_WRITE and write_cb is not None:
                    # one-shot: clear write interest before firing
                    ent[1] = None
                    try:
                        self._reregister(fd)
                    except (KeyError, ValueError, OSError):
                        pass
                    try:
                        write_cb()
                    except Exception:
                        LOG.exception("epollout callback failed")
                if mask & selectors.EVENT_READ and read_cb is not None:
                    # suspend read interest until the consumer drains to
                    # EAGAIN and rearms (one-shot semantics)
                    ent[2] = False
                    try:
                        self._reregister(fd)
                    except (KeyError, ValueError, OSError):
                        pass
                    try:
                        read_cb()
                    except Exception:
                        LOG.exception("readable callback failed")
        try:
            self._sel.close()
            os.close(self._wakeup_r)
            os.close(self._wakeup_w)
        except OSError:
            pass


_global: Optional[EventDispatcher] = None
_global_lock = threading.Lock()


def global_dispatcher() -> EventDispatcher:
    global _global
    with _global_lock:
        if _global is None:
            _global = EventDispatcher()
            _global.start()
        return _global
