"""SocketMap — client-side connection management.

Capability parity with /root/reference/src/brpc/socket_map.cpp and the
connection-type matrix (protocol.h:174-181):

- **single**: one shared connection per peer, responses matched by
  correlation id (the default; cheapest, what multiplexing protocols use);
- **pooled**: a free-list of connections per peer; a connection carries
  one in-flight call then returns to the pool (for protocols without
  multiplexing — HTTP/1 without pipelining);
- **short**: connect per call, close after.

All connections are wired to the process-wide client InputMessenger so
responses flow back through the protocol's process_response.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Deque, Dict, Optional, Tuple

from ..butil.endpoint import EndPoint
from ..butil.status import Errno
from .event_dispatcher import global_dispatcher
from .input_messenger import client_messenger
from .socket import Socket, SocketOptions

DEFAULT_HEALTH_CHECK_INTERVAL_S = 3.0   # reference socket_map.cpp:33


def _new_connection(remote: EndPoint,
                    health_check_interval_s: float = 0.0,
                    direct_read: bool = False,
                    ssl_context=None,
                    prefer_lane: bool = False) -> Tuple[int, int]:
    """Create+connect a client Socket wired for responses.
    Returns (socket_id, error_code).

    ``direct_read`` skips dispatcher registration: the synchronous
    caller reads responses itself (pooled/short fast path); an async
    user later converts via ``ensure_dispatched()``.

    ``prefer_lane`` routes the read side through the NATIVE client
    completion lane (tpu_std multiplexed connections — the "single"
    connection type's demux); the classic dispatcher is the fallback
    whenever the lane declines (TLS, flag off, no native module)."""
    sid = Socket.create(SocketOptions(
        remote_side=remote,
        on_edge_triggered_events=client_messenger().on_new_messages,
        health_check_interval_s=health_check_interval_s,
        ssl_context=ssl_context))
    s = Socket.address(sid)
    rc = s.connect_if_not()
    if rc != 0:
        return sid, rc
    if direct_read:
        s.direct_read = True
        return sid, 0
    if prefer_lane and ssl_context is None:
        from .client_lane import global_client_lane
        lane = global_client_lane()
        if lane is not None and lane.attach(s):
            return sid, 0
    disp = global_dispatcher()
    s.attach_dispatcher(disp)
    disp.add_consumer(s.fd, s.start_input_event)
    return sid, 0


class SocketMap:
    """Peer → shared "single" connection dedup map (socket_map.cpp)."""

    def __init__(self, health_check_interval_s: Optional[float] = None):
        self._lock = threading.Lock()
        self._map: Dict[EndPoint, int] = {}
        # None = follow the live flag at connection time
        self._hc = health_check_interval_s

    def _hc_interval(self) -> float:
        if self._hc is not None:
            return self._hc
        from ..butil.flags import get_flag
        return get_flag("health_check_interval_s",
                        DEFAULT_HEALTH_CHECK_INTERVAL_S)

    def get_socket(self, remote: EndPoint,
                   ssl_context=None,
                   prefer_lane: bool = False) -> Tuple[int, int]:
        """Return (socket_id, 0) for the shared connection to ``remote``,
        creating it on first use. A failed socket stays in the map —
        health check revives it in place, exactly the reference behavior
        (callers see EFAILEDSOCKET meanwhile and may retry elsewhere).
        ``prefer_lane`` applies only when THIS call creates the
        connection (first caller wins the demux mode)."""
        key = (remote, ssl_context is not None)
        with self._lock:
            sid = self._map.get(key)
            s = Socket.address(sid) if sid is not None else None
            if s is None:
                sid, rc = _new_connection(remote, self._hc_interval(),
                                          ssl_context=ssl_context,
                                          prefer_lane=prefer_lane)
                if rc == 0 or Socket.address(sid) is not None:
                    self._map[key] = sid
                return sid, rc
        if s.failed:
            # fail-fast revival OUTSIDE the map lock (the connect can
            # block up to connect_timeout_s; one dead peer must not
            # stall get_socket for every other peer): reconnect now,
            # rate-limited, instead of failing calls until the health
            # checker's next tick — the case is a bounced server on the
            # same address
            s.try_reconnect_now()
        return sid, 0

    def remove(self, remote: EndPoint) -> None:
        with self._lock:
            sid = self._map.pop((remote, False), None) \
                or self._map.pop((remote, True), None)
        if sid is not None:
            s = Socket.address(sid)
            if s is not None:
                s.release()

    def clear(self) -> None:
        with self._lock:
            sids = list(self._map.values())
            self._map.clear()
        for sid in sids:
            s = Socket.address(sid)
            if s is not None:
                s.release()


class SocketPool:
    """Per-peer pooled connections (≈ Socket::GetPooledSocket,
    socket.cpp:2650)."""

    def __init__(self, remote: EndPoint, max_pooled: int = 32,
                 ssl_context=None):
        self._remote = remote
        self._lock = threading.Lock()
        self._free: Deque[int] = deque()
        self._max = max_pooled
        self._ssl_context = ssl_context

    def get(self) -> Tuple[int, int]:
        while True:
            with self._lock:
                sid = self._free.popleft() if self._free else None
            if sid is None:
                break
            s = Socket.address(sid)
            if s is not None and not s.failed:
                return sid, 0
            if s is not None:
                s.release()      # failed pooled conn: free the slot
        # pooled connections are born direct-read (sync fast path);
        # async callers convert them via ensure_dispatched()
        sid, rc = _new_connection(self._remote, direct_read=True,
                                  ssl_context=self._ssl_context)
        s = Socket.address(sid)
        if s is not None:
            s._pooled_home = self
        return sid, rc

    def put(self, sid: int) -> None:
        s = Socket.address(sid)
        if s is None:
            return
        if s.failed:
            s.release()      # free the slot; do not pool dead conns
            return
        if s._pending_acks:
            # flush ICI credit-returns while we still own the connection
            # exclusively — queued writes are safe here; once pooled, a
            # new owner's raw-fd fast-lane write could be in flight
            s.flush_pending_acks()
        with self._lock:
            if len(self._free) < self._max:
                self._free.append(sid)
                return
        s.release()

    def try_take(self, sid: int) -> bool:
        """Remove ``sid`` from the free list if (and only if) it is
        idle there.  True ⇒ the caller owns the connection exclusively
        (nobody else can check it out) and must ``put`` it back."""
        with self._lock:
            try:
                self._free.remove(sid)
                return True
            except ValueError:
                return False


_global_map: Optional[SocketMap] = None
_global_map_lock = threading.Lock()
_pools_lock = threading.Lock()
_pools: Dict[EndPoint, SocketPool] = {}


def global_socket_map() -> SocketMap:
    global _global_map
    with _global_map_lock:
        if _global_map is None:
            _global_map = SocketMap()
        return _global_map


def pooled_socket(remote: EndPoint, ssl_context=None) -> Tuple[int, int]:
    key = (remote, ssl_context is not None)
    with _pools_lock:
        pool = _pools.get(key)
        if pool is None:
            pool = _pools[key] = SocketPool(remote,
                                            ssl_context=ssl_context)
    return pool.get()


def return_pooled_socket(sid: int) -> None:
    s = Socket.address(sid)
    if s is not None and s._pooled_home is not None:
        s._pooled_home.put(sid)


def short_socket(remote: EndPoint, ssl_context=None) -> Tuple[int, int]:
    return _new_connection(remote, direct_read=True,
                           ssl_context=ssl_context)
