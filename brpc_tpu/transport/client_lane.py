"""Client completion lane — the Python half of the engine's ClientDemux.

The full-Controller async/multiplexed response path used to cost, per
response: one dispatcher wakeup, a fiber spawn, a Python frame cut, a
full ``RpcMeta`` decode and an id-pool dict lookup.  With the lane, an
attached client socket's reads belong to ONE native epoll loop
(``native.ClientDemux``): the engine parses response frames off the
read burst in C++, correlates them by cid against a native in-flight
table (registered at send time from ``controller._issue_rpc``), and
delivers the whole burst in ONE batched callback — the client-side twin
of the server's one-GIL-entry-per-burst slim lanes.

Division of labor per burst item:

* **plain success** (cid/attachment/ici-domain meta only) — completed
  here natively: no ``RpcMeta`` object, no frame cut, one id-pool lock.
  Sync completions run inline on the demux thread (they end in an event
  set); calls carrying a ``done`` callback finish on a fiber worker —
  user code must never block the demux loop (the dispatcher path ran
  done on a fiber too).
* **anything else** — error responses, compressed/shm/descriptor
  shapes, stream grants, stream frames, unknown cids — falls back to
  the classic Python demux BYTE-IDENTICALLY: the engine hands the exact
  wire bytes over under a NAMED reason (closed enum, no "unknown"
  bucket), and they flow through ``sock.read_portal`` +
  ``client_messenger()`` exactly like dispatcher-read bytes, serialized
  per connection on an ExecutionQueue.
* **unknown magic** (h2/redis/HTTP response on a lane socket) — sticky
  conversion: the lane detaches and the classic dispatcher takes over,
  with every buffered byte re-played through the portal first.

The lane is process-global (client side), guarded by the
``rpc_native_client_lane`` flag; with the flag off — or the native
module absent — every socket takes the classic dispatcher path and
behavior is unchanged by construction.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional

from ..butil.flags import define_flag, get_flag
from ..butil.logging_util import LOG
from ..butil.status import Errno
from ..bvar.multi_dimension import PassiveDimension
from ..bvar.passive_status import PassiveStatus
from ..fiber import runtime as fiber_runtime

define_flag("rpc_native_client_lane", True,
            "route eligible client sockets' response demux through the "
            "native engine's ClientDemux (batched completion delivery); "
            "off = classic Python dispatcher demux for every socket",
            validator=lambda v: isinstance(v, bool))
define_flag("rpc_client_lane_loops", 0,
            "ClientDemux loops in the process-wide client lane (each "
            "owns an epoll loop + thread; sockets spread round-robin "
            "so completion demux scales with cores instead of "
            "contending on one loop).  0 = auto: cores//2 capped at 4, "
            "min 1.  Read once at lane creation",
            validator=lambda v: isinstance(v, int) and 0 <= v <= 16)


def _auto_lane_loops() -> int:
    import os
    return max(1, min(4, (os.cpu_count() or 1) // 2))

# closed fallback reason enum — MUST mirror engine.cpp's CliFb order
REASONS = ("cli_unknown_cid", "cli_meta_unparsed", "cli_meta_tags",
           "cli_stream_frame", "cli_unknown_magic")

_lane: Optional["ClientLane"] = None
_lane_lock = threading.Lock()
_lane_failed = False


def global_client_lane(create: bool = True) -> Optional["ClientLane"]:
    """The process-wide client lane, created on first eligible attach
    (``create=False`` returns the existing one only — failure paths
    must not boot a demux loop)."""
    global _lane, _lane_failed
    if _lane is not None or not create or _lane_failed:
        return _lane
    with _lane_lock:
        if _lane is None and not _lane_failed:
            try:
                from ..native import load
                mod = load()
                if not hasattr(mod, "ClientDemux"):
                    raise RuntimeError("native module has no ClientDemux")
                _lane = ClientLane(mod)
            except Exception:
                _lane_failed = True
                return None
    return _lane


def lane_expect(sock, cid: int) -> None:
    """Register an in-flight cid for a lane-attached socket (no-op
    otherwise).  Call BEFORE the request write — a response racing the
    registration would demux as ``cli_unknown_cid``."""
    if sock.lane_token:
        lane = _lane
        if lane is not None:
            lane.expect(sock, cid)


def lane_cancel(sock, cid: int) -> None:
    """Drop an in-flight registration at call teardown (no-op when the
    socket is not lane-attached)."""
    if sock.lane_token:
        lane = _lane
        if lane is not None:
            lane.cancel(sock, cid)


def pending_inflight() -> int:
    """ClientDemux in-flight entries still registered across the demux
    pool (0 when the lane was never created).  The drain plane waits
    for this to reach zero before process exit — an entry left behind
    is a response the native table would deliver into a torn-down
    Python world."""
    lane = _lane
    if lane is None:
        return 0
    n = 0
    for d in lane._demuxes:
        try:
            n += int(d.pending())
        except AttributeError:     # stale prebuilt engine: best effort
            return 0
    return n


def drain_settle(deadline_mono_s: float) -> int:
    """Wait (bounded by the drain-grace deadline, monotonic seconds)
    for the demux pool's in-flight tables to empty.  Returns entries
    still pending at the deadline."""
    import time as _time
    ev = threading.Event()
    while True:
        n = pending_inflight()
        if n == 0:
            return 0
        if _time.monotonic() >= deadline_mono_s:
            return n
        ev.wait(0.005)     # timed: the drain path stays deadline-bound


def client_lane_telemetry() -> dict:
    """Snapshot of the lane's native counters MERGED across the demux
    pool (empty dict when the lane was never created) — the /native
    portal's client section and the ``native_client_*`` bvars read
    this.  Scalars sum; the fallbacks dict sums per reason; the
    completions-per-burst histogram merges bucket-wise; a ``loops``
    list carries the per-demux-loop burst counts (the lane's own
    imbalance view)."""
    lane = _lane
    if lane is None:
        return {}
    try:
        snaps = [d.telemetry() for d in lane._demuxes]
    except Exception:
        return {}
    if not snaps:
        return {}
    out = dict(snaps[0])
    for s in snaps[1:]:
        for k, v in s.items():
            if isinstance(v, dict):
                base = dict(out.get(k, {}))
                for rk, rv in v.items():
                    base[rk] = base.get(rk, 0) + rv
                out[k] = base
            elif isinstance(v, list):
                prev = out.get(k) or []
                out[k] = [a + b for a, b in zip(prev, v)]
            else:
                out[k] = out.get(k, 0) + v
    out["demux_loops"] = len(snaps)
    out["loops"] = [{"bursts": s.get("bursts", 0),
                     "completions": s.get("completions", 0),
                     "attached": s.get("attached", 0),
                     # Python-side delivery count for this loop (the
                     # engine's `bursts` counts parsed bursts; this one
                     # counts callbacks that actually entered Python)
                     "py_bursts": lane._loop_bursts[i]}
                    for i, s in enumerate(snaps)]
    return out


# eager bvar registration (the families must exist in /vars//metrics
# from the first scrape, fallback or not — mirrors fast_call's scatter
# counters)
_fallback_var = PassiveDimension(
    ("reason",),
    lambda: client_lane_telemetry().get(
        "fallbacks", {r: 0 for r in REASONS}),
    name="native_client_fallback_total")
_completions_var = PassiveStatus(
    lambda: client_lane_telemetry().get("completions", 0),
    name="native_client_completions")
_bursts_var = PassiveStatus(
    lambda: client_lane_telemetry().get("bursts", 0),
    name="native_client_bursts")


class ClientLane:
    """Owns a POOL of ClientDemux loops (one per core-ish — see
    ``rpc_client_lane_loops``), their loop threads, and the token →
    socket routing state.  Tokens are process-unique (the engine hands
    them out from one counter), so one routing table serves every
    demux; each socket's reads belong to exactly ONE demux loop for
    its whole life — the client-side mirror of the server's
    connection-pinned-to-loop discipline."""

    def __init__(self, mod):
        self._m = mod
        nloops = int(get_flag("rpc_client_lane_loops", 0)) \
            or _auto_lane_loops()
        self._demuxes = [mod.ClientDemux(self._bind_burst(i))
                         for i in range(nloops)]
        self._socks: Dict[int, int] = {}     # token -> socket id
        self._demux_of: Dict[int, int] = {}  # token -> demux index
        self._queues: Dict[int, Any] = {}    # token -> ExecutionQueue
        self._lock = threading.Lock()
        self._rr = 0                         # attach spread counter
        # per-demux-loop burst delivery counters (each slot written
        # only by its own demux thread; GIL-snapshotted reads)
        self._loop_bursts = [0] * nloops
        # the loops run on Python threads: resident frames pin the
        # datastack chunk, so per-burst callbacks skip cold-eval mmap
        # churn (same rationale as the server bridge's external loops)
        self._threads = []
        for i, d in enumerate(self._demuxes):
            t = threading.Thread(target=d.run_loop,
                                 name=f"client-lane-{i}", daemon=True)
            t.start()
            self._threads.append(t)

    def _bind_burst(self, idx: int):
        return lambda token, status, comps, fbs, acks, _i=idx: \
            self._on_loop_burst(token, status, comps, fbs, acks,
                                _idx=_i)

    # -- attach / detach ---------------------------------------------------

    def attach(self, sock) -> bool:
        """Take over the read side of ``sock``.  False = ineligible
        (no fd, TLS, flag off, attach failure) — the caller falls back
        to the classic dispatcher.  The socket is spread round-robin
        over the demux pool and stays on its loop for life."""
        if sock.fd is None or sock.ssl_context is not None \
                or sock.failed:
            return False
        if not get_flag("rpc_native_client_lane", True):
            return False
        with self._lock:
            idx = self._rr % len(self._demuxes)
            self._rr += 1
        demux = self._demuxes[idx]
        try:
            token = demux.attach(sock.fd.fileno())
        except (OSError, ValueError):
            return False
        # routing state BEFORE arming: the very first burst (or an
        # immediate EOF on an already-closed peer) must find the socket
        with self._lock:
            self._socks[token] = sock.id
            self._demux_of[token] = idx
        sock.lane_token = token
        sock._lane_pref = True
        if not demux.arm(token):
            self.detach(sock)
            return False
        return True

    def _demux_for(self, token: int):
        idx = self._demux_of.get(token)
        return self._demuxes[idx] if idx is not None else None

    def detach(self, sock, _stop_queue: bool = True) -> None:
        token = sock.lane_token
        if not token:
            return
        sock.lane_token = 0
        demux = self._demux_for(token)
        with self._lock:
            self._socks.pop(token, None)
            self._demux_of.pop(token, None)
            q = self._queues.pop(token, None)
        if demux is not None:
            demux.detach(token)
        if q is not None and _stop_queue:
            q.stop()

    def expect(self, sock, cid: int) -> None:
        demux = self._demux_for(sock.lane_token)
        if demux is not None:
            demux.expect(sock.lane_token, cid)

    def cancel(self, sock, cid: int) -> None:
        demux = self._demux_for(sock.lane_token)
        if demux is not None:
            demux.cancel(sock.lane_token, cid)

    # -- burst delivery (runs on the demux loop threads, GIL held) ---------

    def _on_loop_burst(self, token: int, status: int, comps, fbs, acks,
                       _idx: int = 0) -> None:
        """Per-demux-loop burst entry — the cross-loop completion
        handoff delivery callback: completions parsed on demux loop
        ``_idx`` are handed to callers living on ANY other thread or
        loop (event sets for sync calls, fiber hops for done-bearing
        ones).  Runs ON the loop: everything reachable from here is
        loop-thread code (the blocking-call linter pins this entry)."""
        self._loop_bursts[_idx] += 1
        self._on_burst(token, status, comps, fbs, acks)

    def _on_burst(self, token: int, status: int, comps, fbs, acks
                  ) -> None:
        from .socket import Socket
        with self._lock:
            sid = self._socks.get(token)
        sock = Socket.address(sid) if sid is not None else None
        if sock is None or sock.lane_token != token:
            return                    # detached under us: nothing to own
        try:
            if acks:
                from ..ici.endpoint import _process_ack
                _process_ack(acks, sock)
            if comps:
                self._complete_burst(sock, comps)
            if fbs or status:
                self._enqueue_classic(token, sock, fbs, status)
        except Exception:
            LOG.exception("client lane burst delivery failed")

    def _complete_burst(self, sock, comps) -> None:
        """Finish a burst of PLAIN successes in arrival order.  Sync
        calls complete inline (their tail is an event set + cheap
        feedback); ``done``-bearing calls — and any call whose id is
        momentarily HELD (a timer/backup handler may be mid-connect
        under it) — hop to a fiber worker, so neither user code nor a
        contended id can ever stall the one demux loop."""
        from ..fiber.versioned_id import global_id_pool
        idp = global_id_pool()
        for cid, buf, att, dom in comps:
            sock.remove_inflight(cid)
            st, cntl = idp.try_lock(cid)
            if st < 0:
                continue              # already finished (timeout/cancel)
            if st == 0:
                # id busy: the fiber blocks in lock(), not this thread
                fiber_runtime.spawn(self._complete_on_fiber, cid, buf,
                                    att, dom, sock.id, name="lane_busy")
                continue
            if cntl is None:
                idp.unlock(cid)
                continue
            if cntl._done is not None:
                idp.unlock(cid)
                fiber_runtime.spawn(self._complete_on_fiber, cid, buf,
                                    att, dom, sock.id, name="lane_done")
                continue
            cntl._on_plain_response(cid, buf, att, dom, sock)

    @staticmethod
    def _complete_on_fiber(cid, buf, att, dom, sid) -> None:
        from ..fiber.versioned_id import global_id_pool
        from .socket import Socket
        sock = Socket.address(sid)
        if sock is None:
            return
        idp = global_id_pool()
        ok, cntl = idp.lock(cid)
        if not ok:
            return
        if cntl is None:
            idp.unlock(cid)
            return
        cntl._on_plain_response(cid, buf, att, dom, sock)

    # -- classic fallback (byte-identical demux) ---------------------------

    def _queue_for(self, token: int, sock):
        with self._lock:
            q = self._queues.get(token)
            if q is not None:
                return q
        from ..fiber.execution_queue import ExecutionQueue

        def executor(it, _sock=sock, _self=self):
            for kind, payload in it:
                try:
                    if kind == 0:          # raw frame bytes
                        _sock.read_portal.append_user_data(
                            memoryview(payload))
                        _self._messenger()._cut_and_process(_sock)
                    elif kind == 1:        # convert to dispatcher reads
                        _self._convert_to_dispatcher(_sock)
                    else:                  # terminal socket failure
                        code, text = payload
                        _sock.set_failed(code, text)
                except Exception:
                    LOG.exception("client lane fallback dispatch failed")

        q = ExecutionQueue(executor, name=f"client_lane_{token}")
        with self._lock:
            # racing creators: first one in wins, extras are dropped
            q = self._queues.setdefault(token, q)
        return q

    @staticmethod
    def _messenger():
        from .input_messenger import client_messenger
        return client_messenger()

    def _enqueue_classic(self, token: int, sock, fbs, status: int
                         ) -> None:
        """Route fallback frames (exact wire bytes) through the classic
        demux, serialized per connection; terminal status rides the SAME
        queue so a response already on the wire wins against the EOF
        that followed it (classic gulp ordering)."""
        q = self._queue_for(token, sock)
        convert = False
        if fbs:
            for reason, raw in fbs:
                if reason == self._m.CFB_UNKNOWN_MAGIC:
                    convert = True
                q.execute((0, raw))
        if convert:
            # sticky passthrough: the protocol registry owns this conn.
            # Detach FIRST (we are ON the demux thread — no further lane
            # reads can race this), then hand reads to the dispatcher
            # strictly after the queued bytes are processed.  The queue
            # must keep accepting the tail items below, so it is not
            # stopped here (it auto-quits once drained).
            self.detach(sock, _stop_queue=False)
            q.execute((1, None))
        if status:
            code = int(Errno.EEOF) if status == 1 \
                else int(Errno.EFAILEDSOCKET)
            text = "remote closed connection" if status == 1 \
                else "client lane transport error"
            q.execute((2, (code, text)))
            if not convert:
                self.detach(sock)

    @staticmethod
    def _convert_to_dispatcher(sock) -> None:
        if sock.failed or sock.fd is None:
            return
        from .event_dispatcher import global_dispatcher
        disp = global_dispatcher()
        sock.attach_dispatcher(disp)
        disp.add_consumer(sock.fd, sock.start_input_event)
