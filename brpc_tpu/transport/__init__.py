"""Transport layer — sockets, event dispatch, message ingestion.

TPU-native re-design of the reference's L3 core runtime
(/root/reference/src/brpc/socket.h, event_dispatcher_epoll.cpp,
acceptor.cpp, input_messenger.cpp): versioned-id addressed Socket objects
with an ordered write queue drained by a keep-write task, an epoll-backed
event dispatcher that wakes fiber tasks, an acceptor, and a
protocol-agnostic input messenger with adaptive read sizing and
multi-protocol detection.
"""

from .socket import Socket, SocketOptions, socket_pool
from .event_dispatcher import EventDispatcher, global_dispatcher
from .acceptor import Acceptor
from .input_messenger import InputMessenger

__all__ = [
    "Socket",
    "SocketOptions",
    "socket_pool",
    "EventDispatcher",
    "global_dispatcher",
    "Acceptor",
    "InputMessenger",
]
