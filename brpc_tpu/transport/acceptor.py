"""Acceptor — turns a listening fd into per-connection Sockets.

Capability parity with /root/reference/src/brpc/acceptor.cpp:50,243,327:
the listener is itself a Socket whose edge-triggered callback accepts in
a loop and creates a connection Socket wired to the server's
InputMessenger; connections are tracked so Join can drain them.
"""

from __future__ import annotations

import socket as _socket
import threading
from typing import Dict, Optional

from ..butil.endpoint import EndPoint
from ..butil.logging_util import LOG
from ..butil.status import Errno
from .event_dispatcher import EventDispatcher, global_dispatcher
from .input_messenger import InputMessenger
from .socket import Socket, SocketOptions


class Acceptor:
    def __init__(self, messenger: InputMessenger,
                 dispatcher: Optional[EventDispatcher] = None,
                 tag: Optional[str] = None,
                 ssl_context=None):
        self._messenger = messenger
        self._dispatcher = dispatcher or global_dispatcher()
        self._tag = tag                  # stamped on accepted sockets
        self._ssl_context = ssl_context  # TLS: wrap accepted connections
        self._listen_sid = 0
        self._conn_lock = threading.Lock()
        self._connections: Dict[int, int] = {}   # sid -> sid (set)
        self._stopped = False

    def start_accept(self, listen_fd: _socket.socket) -> int:
        """≈ Acceptor::StartAccept (acceptor.cpp:50)."""
        listen_fd.setblocking(False)
        sid = Socket.create(SocketOptions(
            fd=listen_fd,
            on_edge_triggered_events=self._on_new_connections))
        self._listen_sid = sid
        s = Socket.address(sid)
        s.attach_dispatcher(self._dispatcher)
        self._dispatcher.add_consumer(listen_fd, s.start_input_event)
        return 0

    def _on_new_connections(self, listen_sock: Socket) -> None:
        """≈ OnNewConnections (acceptor.cpp:243): accept until EAGAIN."""
        while not self._stopped:
            try:
                conn, addr = listen_sock.fd.accept()
            except (BlockingIOError, OSError):
                return
            try:
                conn.setsockopt(_socket.IPPROTO_TCP, _socket.TCP_NODELAY, 1)
            except OSError:
                pass
            remote = EndPoint(host=addr[0], port=addr[1]) \
                if isinstance(addr, tuple) else EndPoint(host=str(addr), port=0)
            if self._ssl_context is not None:
                # bounded blocking handshake on its own fiber so the
                # accept loop never stalls behind a slow TLS peer
                from ..fiber import runtime as fiber_runtime
                fiber_runtime.spawn(self._tls_accept, conn, remote,
                                    name="tls_accept")
                continue
            conn.setblocking(False)
            self._register(conn, remote)

    def _tls_accept(self, conn: _socket.socket, remote: EndPoint) -> None:
        try:
            conn.settimeout(5.0)
            tls = self._ssl_context.wrap_socket(conn, server_side=True)
            tls.setblocking(False)
        except (OSError, ValueError) as e:
            LOG.warning("TLS handshake with %s failed: %s", remote, e)
            try:
                conn.close()
            except OSError:
                pass
            return
        self._register(tls, remote)

    def _register(self, conn: _socket.socket, remote: EndPoint) -> None:
        sid = Socket.create(SocketOptions(
            fd=conn, remote_side=remote,
            on_edge_triggered_events=self._messenger.on_new_messages))
        s = Socket.address(sid)
        s.pin_local_side()
        s.tag = self._tag
        s.attach_dispatcher(self._dispatcher)
        with self._conn_lock:
            self._connections[sid] = sid
        self._dispatcher.add_consumer(conn, s.start_input_event)

    def connection_count(self) -> int:
        self._gc()
        with self._conn_lock:
            return len(self._connections)

    def _gc(self) -> None:
        with self._conn_lock:
            dead = []
            for sid in self._connections:
                s = Socket.address(sid)
                if s is None or s.failed:
                    dead.append((sid, s))
            for sid, _ in dead:
                del self._connections[sid]
        for sid, s in dead:
            if s is not None:
                s.release()      # return the pool slot (no revival for
                                 # server-side connections)

    def pause_accept(self) -> None:
        """Drain mode (operability plane): stop accepting NEW
        connections — the listener leaves the dispatcher but its fd
        stays OPEN and bound (hot restart may pass it to a successor,
        and the kernel keeps the listen queue for whoever owns it
        next).  Live connections keep serving; ``stop_accept`` still
        runs at stop() for the final teardown."""
        self._stopped = True
        ls = Socket.address(self._listen_sid)
        if ls is not None and ls.fd is not None:
            self._dispatcher.remove_consumer(ls.fd)

    def live_sockets(self):
        """Snapshot of the live accepted connection Sockets (the drain
        force-close sweep walks it at grace expiry)."""
        self._gc()
        with self._conn_lock:
            sids = list(self._connections)
        return [s for s in (Socket.address(sid) for sid in sids)
                if s is not None]

    def stop_accept(self) -> None:
        """≈ Acceptor::StopAccept: close listener, fail connections."""
        self._stopped = True
        ls = Socket.address(self._listen_sid)
        if ls is not None:
            ls.set_failed(Errno.ELOGOFF, "server stopping")
        with self._conn_lock:
            sids = list(self._connections)
            self._connections.clear()
        for sid in sids:
            s = Socket.address(sid)
            if s is not None:
                s.release()      # set_failed + free the pool slot
